"""Central SRJT_* knob registry + typed environment accessors (ISSUE 7).

Before this module every subsystem read ``os.environ`` directly with
its own ad-hoc parser (``env_float`` in retry, ``_env_int`` in the
pool, ``_env_seconds`` in the sidecar, bare ``int(raw)`` in memgov),
and the README/PACKAGING knob tables drifted from the code — 40 knobs
in code, 34 documented. This registry is the single source of truth:

- every knob is DECLARED here once — name, type, default, validation,
  one-line doc — and read through the typed ``get_*`` accessors,
- ``srjt-lint`` (analysis/lint.py) fails the build on any SRJT_* string
  literal that is not declared here, on any direct ``os.environ`` read
  of an SRJT key outside this file, and on any drift between this
  registry and the README/PACKAGING knob tables,
- ``python -m spark_rapids_jni_tpu.analysis.lint --knob-table`` renders
  the registry as the markdown table the docs embed.

Parsing posture (inherited from the original ``env_float``): malformed
values WARN and fall back to the declared default — a bad knob degrades
the feature, never crashes an import or a query. ``positive=True``
knobs additionally reject values <= 0 (a zero socket deadline would
make sockets non-blocking, not timeout-free — the C++ client applies
the same v > 0 rule).

This module is deliberately dependency-free (stdlib only, no locks, no
package imports): it must be importable by the package ``__init__``
BEFORE the lockdep shim (analysis/lockdep.py) decides whether to
instrument ``threading``, and by every utils module without cycles.

Accessors read the environment LIVE on every call (the test hook and
operator-override contract); modules that latch a value at import time
(metrics/retry arming) do so explicitly at their own import site.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, Optional

__all__ = [
    "Knob",
    "declare",
    "knob",
    "all_knobs",
    "names",
    "is_declared",
    "is_set",
    "get_raw",
    "get_str",
    "get_bool",
    "get_int",
    "get_float",
    "env_float",
    "markdown_table",
    "SENTINELS",
]

_TRUE = ("1", "true", "yes")
_FALSE = ("0", "false", "no")

# NOT env knobs: stdout/wire handshake sentinel lines that share the
# SRJT_ prefix (spawn harnesses poll for them). Declared so srjt-lint
# can tell a sentinel literal from an undeclared knob.
SENTINELS = frozenset({"SRJT_SIDECAR_READY", "SRJT_EXCHANGE_READY"})


class Knob:
    """One declared knob: the registry row and its validation spec."""

    __slots__ = ("name", "type", "default", "doc", "positive", "minimum",
                 "choices", "scope")

    def __init__(self, name, type, default, doc, positive=False,
                 minimum=None, choices=None, scope="python"):
        self.name = name
        self.type = type  # "bool" | "int" | "float" | "str"
        self.default = default
        self.doc = doc
        self.positive = positive  # floats/ints: value must be > 0
        self.minimum = minimum  # ints: clamp floor (pool sizes etc.)
        self.choices = choices  # strs: allowed values (warn + default)
        # "python" | "native" | "harness": where the knob is consumed —
        # native knobs are read by the C++ client, harness knobs by
        # bench/test drivers; all are documented from this one registry
        self.scope = scope


_REGISTRY: Dict[str, Knob] = {}


def declare(name: str, type: str, default, doc: str, **kw) -> Knob:
    if name in _REGISTRY:
        raise ValueError(f"knob {name} declared twice")
    if not name.startswith("SRJT_"):
        raise ValueError(f"knob {name} must carry the SRJT_ prefix")
    k = Knob(name, type, default, doc, **kw)
    _REGISTRY[name] = k
    return k


def knob(name: str) -> Knob:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"undeclared knob {name!r}: declare it in utils/knobs.py "
            "(srjt-lint enforces this)"
        ) from None


def all_knobs() -> Iterable[Knob]:
    return [_REGISTRY[n] for n in sorted(_REGISTRY)]


def names() -> frozenset:
    return frozenset(_REGISTRY)


def is_declared(name: str) -> bool:
    return name in _REGISTRY


def _warn(msg: str) -> None:
    import warnings

    warnings.warn(f"knobs: {msg}", stacklevel=3)


def get_raw(name: str, env=None) -> Optional[str]:
    """The raw environment string for a declared knob, or None when
    unset. The untyped escape hatch — prefer the typed accessors."""
    knob(name)  # undeclared reads fail loudly, even through the API
    return (os.environ if env is None else env).get(name)


def is_set(name: str, env=None) -> bool:
    """True when the knob is present AND non-empty in the environment."""
    return bool(get_raw(name, env))


def get_str(name: str, env=None, default=...) -> Optional[str]:
    k = knob(name)
    if default is ...:
        default = k.default
    raw = get_raw(name, env)
    if raw is None or raw == "":
        return default
    if k.choices and raw.lower() not in k.choices:
        _warn(f"unknown {name}={raw!r}; using {default!r}")
        return default
    return raw.lower() if k.choices else raw


def get_bool(name: str, env=None, default=...) -> bool:
    """Tri-state text -> bool: explicit true/false spellings win, any
    other spelling WARNS and keeps the default (same degradation
    contract as the numeric accessors), unset/empty keeps it silently —
    so a default-on knob (SRJT_INTEGRITY_CHECKS) only disarms on an
    explicit "0", and a default-off one (SRJT_METRICS_ENABLED) only
    arms on an explicit "1"."""
    k = knob(name)
    if default is ...:
        default = k.default
    raw = get_raw(name, env)
    if raw is None or raw == "":
        return bool(default)
    low = raw.lower()
    if low in _TRUE:
        return True
    if low in _FALSE:
        return False
    _warn(f"ignoring malformed {name}={raw!r}; using {bool(default)!r}")
    return bool(default)


def get_int(name: str, env=None, default=...) -> Optional[int]:
    k = knob(name)
    if default is ...:
        default = k.default
    raw = get_raw(name, env)
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
    except ValueError:
        _warn(f"ignoring malformed {name}={raw!r}; using {default!r}")
        return default
    if k.positive and v <= 0:
        _warn(f"{name}={raw!r} must be > 0; keeping default {default!r}")
        return default
    if k.minimum is not None:
        v = max(v, k.minimum)
    return v


def get_float(name: str, env=None, default=...) -> Optional[float]:
    k = knob(name)
    if default is ...:
        default = k.default
    return env_float(
        os.environ if env is None else env, name, default,
        positive=k.positive,
    )


def env_float(env, key: str, default, positive: bool = False):
    """Parse a float env knob, warning and falling back to ``default``
    on malformed input — and, with ``positive=True``, on values <= 0.
    The historical shared parser (born in utils/retry.py); the typed
    ``get_float`` accessor above is the declared-knob front door, this
    remains for callers carrying an injected env mapping."""
    raw = env.get(key)
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        _warn(f"ignoring malformed {key}={raw!r}")
        return default
    if positive and v <= 0:
        _warn(f"{key}={raw!r} must be > 0; keeping default {default}")
        return default
    return v


def markdown_table(scope: Optional[str] = None) -> str:
    """Render the registry as the markdown knob table the docs embed
    (``python -m spark_rapids_jni_tpu.analysis.lint --knob-table``)."""
    rows = ["| knob | type | default | description |",
            "|---|---|---|---|"]
    for k in all_knobs():
        if scope is not None and k.scope != scope:
            continue
        d = "—" if k.default is None else repr(k.default).strip("'\"")
        rows.append(f"| `{k.name}` | {k.type} | `{d}` | {k.doc} |")
    return "\n".join(rows)


# ---------------------------------------------------------------------------
# THE registry: every SRJT_* knob in the tree, grouped by subsystem.
# srjt-lint fails on any SRJT literal in code that is missing here and
# on any entry here missing from the README/PACKAGING knob tables.
# ---------------------------------------------------------------------------

# retry orchestrator (utils/retry.py, PR 1)
declare("SRJT_RETRY_ENABLED", "bool", False,
        "arm op-boundary retry (bounded backoff + retry-with-split)")
declare("SRJT_RETRY_MAX_ATTEMPTS", "int", 4,
        "total attempts incl. the first", positive=True)
declare("SRJT_RETRY_BASE_DELAY_MS", "float", 25.0, "first backoff delay")
declare("SRJT_RETRY_MAX_DELAY_MS", "float", 1000.0, "backoff ceiling")
declare("SRJT_RETRY_JITTER", "float", 0.25,
        "multiplicative jitter fraction in [0,1)")
declare("SRJT_RETRY_SPLIT_DEPTH", "int", 3,
        "max halvings in retry_with_split")
declare("SRJT_RETRY_SEED", "int", None,
        "jitter RNG seed (deterministic chaos runs)")

# deadlines + circuit breaker (utils/deadline.py, PR 3)
declare("SRJT_DEADLINE_SEC", "float", None,
        "ambient per-query wall-clock budget in seconds (unset: "
        "unbounded, the seed contract)", positive=True)
declare("SRJT_BREAKER_THRESHOLD", "int", 5,
        "consecutive sidecar supervision failures before the breaker "
        "opens", positive=True)
declare("SRJT_BREAKER_COOLDOWN_SEC", "float", 30.0,
        "breaker open -> half-open probe delay", positive=True)

# metrics + tracing (utils/metrics.py / utils/tracing.py, PR 2)
declare("SRJT_METRICS_ENABLED", "bool", False,
        "arm hot-path instrumentation (per-op wall time, shuffle "
        "bytes, retry/backoff counters per error class)")
declare("SRJT_METRICS_LOG", "str", None,
        "append one JSON object per runtime event to this path "
        "(line-atomic, shareable across worker + client)")
declare("SRJT_TRACE_ENABLED", "bool", False,
        "arm distributed per-query tracing (srjt-trace spans with "
        "cross-process propagation) plus the jax named-scope/"
        "TraceAnnotation ranges on every op boundary (the NVTX-range "
        "analog; visible in XProf)")

# distributed tracing + flight recorder (utils/tracing.py /
# utils/trace_sink.py, ISSUE 12)
declare("SRJT_TRACE_LOG", "str", None,
        "span-log base path: each process appends its finished spans "
        "(and flushed trace trees) to <base>.<pid>.jsonl — the "
        "analysis.tracemerge join input")
declare("SRJT_TRACE_SAMPLE", "float", 1.0,
        "fraction of root traces sampled (0 disables roots entirely; "
        "unsampled queries cost one RNG draw)")
declare("SRJT_SLOW_QUERY_SEC", "float", None,
        "flight recorder: a completed query slower than this flushes "
        "its full span tree + metrics delta to SRJT_TRACE_LOG "
        "(shed/failed queries always flush)", positive=True)
declare("SRJT_TRACE_RING", "int", 64,
        "flight recorder ring capacity: completed query traces kept "
        "in memory for runtime.explain_last()", minimum=1)
declare("SRJT_TRACE_MAX_SPANS", "int", 4096,
        "per-trace in-memory span cap (overflow counted; the span LOG "
        "is never capped)", minimum=16)

# integrity + fault injection (utils/integrity.py / utils/faultinj.py)
declare("SRJT_INTEGRITY_CHECKS", "bool", True,
        "0 disables every CRC check (frames ship legacy framing, "
        "spills skip verify, exchanges skip the checksum)")
declare("SRJT_FAULTINJ_CONFIG", "str", None,
        "JSON chaos profile path (hot-reloaded on mtime change); a "
        "malformed config degrades the injector, never the process")
declare("SRJT_CHAOS_EXIT_ON_OP", "int", None,
        "sidecar worker chaos: die (exit 42) after consuming a request "
        "for this op code, before any response")
declare("SRJT_FAULTINJ_WORKER", "str", None,
        "this process's worker tag (w0, w1, ...) for per-worker fault "
        "rule keys like sidecar.worker.<OP>@w1; the pool sets it on "
        "every spawned worker")
declare("SRJT_FAULTINJ_RANK", "str", None,
        "this process's exchange-rank tag (r0, r1, ...) for per-rank "
        "fault rule keys like exchange.connect@r2; the exchange-worker "
        "harness sets it on every spawned rank")

# sidecar supervision (sidecar.py, PRs 1/3/5)
declare("SRJT_SIDECAR_TIMEOUT_SEC", "float", 600.0,
        "per-request sidecar socket deadline (both clients; truncated "
        "to the remaining budget under a deadline scope)",
        positive=True)
declare("SRJT_SIDECAR_DEADLINE_S", "float", None,
        "float override of SRJT_SIDECAR_TIMEOUT_SEC for the Python "
        "client (wins when both are set)", positive=True)
declare("SRJT_SIDECAR_HEARTBEAT_S", "float", 30.0,
        "idle-connection PING probe interval", positive=True)
declare("SRJT_SIDECAR_STATS_TIMEOUT_SEC", "float", 5.0,
        "STATS-verb probe deadline (throwaway connection, never the "
        "heavy-op budget)", positive=True)
declare("SRJT_SIDECAR_HEARTBEAT_TIMEOUT_SEC", "float", 5.0,
        "native C++ client: heartbeat() PING deadline (NOT the "
        "heavy-op SRJT_SIDECAR_TIMEOUT_SEC)", scope="native",
        positive=True)
declare("SRJT_PYTHON", "str", None,
        "native C++ client: python executable used to fork the sidecar "
        "worker", scope="native")

# worker pool + slab arena (sidecar_pool.py, PRs 5/6)
declare("SRJT_SIDECAR_POOL_SIZE", "int", 1,
        "workers in the supervised pool (1 = single-worker footprint)",
        minimum=1)
declare("SRJT_POOL_RESPAWN_MAX", "int", 3,
        "spawn attempts per worker death before the slot stays dead",
        minimum=1)
declare("SRJT_POOL_RESPAWN_DELAY_S", "float", 0.5,
        "pause between failed respawn attempts")
declare("SRJT_ARENA_SLAB_BYTES", "int", 64 << 20,
        "slab arena size, rounded up to a power of two (memfd-backed, "
        "virtual until touched)", minimum=4096)

# tail tolerance: gray-failure quarantine + hedged dispatch
# (sidecar_pool.py, ISSUE 9)
declare("SRJT_QUARANTINE_ENABLED", "bool", True,
        "arm the gray-failure detector: persistently-slow pool workers "
        "are quarantined out of routing and background-probed")
declare("SRJT_QUARANTINE_SLOW_FACTOR", "float", 3.0,
        "a sample slower than this multiple of the pool-wide op-class "
        "p50 is a strike", positive=True)
declare("SRJT_QUARANTINE_STRIKES", "int", 5,
        "net strikes (slow samples minus clean ones) before a worker "
        "is quarantined", minimum=1)
declare("SRJT_QUARANTINE_MIN_SAMPLES", "int", 20,
        "op-class samples required before the detector issues "
        "verdicts (cold starts are never strikes)", minimum=1)
declare("SRJT_QUARANTINE_PROBES", "int", 3,
        "consecutive clean probes before a quarantined worker is "
        "reinstated", minimum=1)
declare("SRJT_QUARANTINE_PROBE_INTERVAL_S", "float", 0.25,
        "pause between background probes of a quarantined worker",
        positive=True)
declare("SRJT_QUARANTINE_PROBE_SLOW_S", "float", 0.25,
        "a probe round-trip slower than this is dirty (resets the "
        "clean-probe run)", positive=True)
declare("SRJT_HEDGE_ENABLED", "bool", True,
        "arm hedged dispatch: a pool request outliving the op-class "
        "p95 launches one duplicate on a different healthy worker, "
        "first valid response wins")
declare("SRJT_HEDGE_BUDGET_PCT", "float", 10.0,
        "global hedge budget: duplicates stay under this percent of "
        "total pool calls", positive=True)
declare("SRJT_HEDGE_MIN_SAMPLES", "int", 20,
        "op-class samples required before hedging arms (cold ops "
        "never hedge)", minimum=1)
declare("SRJT_HEDGE_MIN_DELAY_S", "float", 0.05,
        "floor on the hedge trigger delay: ops faster than this "
        "never hedge", positive=True)
declare("SRJT_HEDGE_SHED_WINDOW_S", "float", 5.0,
        "hedging auto-disarms for this long after a serve-layer shed "
        "(an overloaded pool must not carry duplicate load)",
        positive=True)

# adaptive timeouts (sidecar.py / parallel/shuffle.py, ISSUE 9)
declare("SRJT_ADAPTIVE_TIMEOUT_ENABLED", "bool", True,
        "derive per-op socket deadlines from observed latency "
        "quantiles (q99 x multiplier) instead of the static knob "
        "once enough samples exist")
declare("SRJT_ADAPTIVE_TIMEOUT_MULT", "float", 4.0,
        "adaptive deadline = observed op q99 x this multiplier",
        positive=True)
declare("SRJT_ADAPTIVE_TIMEOUT_FLOOR_S", "float", 10.0,
        "adaptive deadlines never shrink below this floor",
        positive=True)
declare("SRJT_ADAPTIVE_TIMEOUT_MIN_SAMPLES", "int", 40,
        "per-op samples required before the adaptive deadline "
        "replaces the static knob (cold-start ops keep the knob)",
        minimum=1)

# cross-process exchange (parallel/shuffle.py, PR 6)
declare("SRJT_EXCHANGE_MODE", "str", "mesh",
        "mesh (in-process collective) or tcp (cross-process frames); "
        "the --exchange-worker harness defaults to tcp and refuses "
        "mesh", choices=("mesh", "tcp"))
declare("SRJT_EXCHANGE_TIMEOUT_SEC", "float", 30.0,
        "per-fetch deadline on the TCP exchange (always clamped by an "
        "active query deadline)", positive=True)
declare("SRJT_EXCHANGE_RETAIN_EPOCHS", "int", 4,
        "published exchange rounds kept servable; older epochs are "
        "evicted on publish", minimum=1)

# cluster membership + liveness (parallel/cluster.py, ISSUE 16)
declare("SRJT_CLUSTER_HEARTBEAT_SEC", "float", 0.5,
        "heartbeat cadence: each rank PINGs every peer this often; "
        "misses drive the alive -> suspect -> dead transitions",
        positive=True)
declare("SRJT_CLUSTER_HEARTBEAT_TIMEOUT_SEC", "float", 2.0,
        "per-PING deadline budget (utils/deadline scope); a PING "
        "slower than this counts as a miss", positive=True)
declare("SRJT_CLUSTER_SUSPECT_MISSES", "int", 2,
        "consecutive heartbeat misses before an ALIVE peer is marked "
        "SUSPECT (still routable, health-degraded)", minimum=1)
declare("SRJT_CLUSTER_DEAD_MISSES", "int", 4,
        "consecutive heartbeat misses before a SUSPECT peer is marked "
        "DEAD: the generation bumps and recovery engages", minimum=1)
declare("SRJT_CLUSTER_QUORUM_FRACTION", "float", 0.5,
        "alive fraction (self included) at or below which the cluster "
        "is degraded: serving sheds Overloaded(cluster_degraded)",
        positive=True)
declare("SRJT_CLUSTER_TOPOLOGY", "str", "auto",
        "exchange plan over the ClusterView: all_to_all (direct pulls "
        "from every peer), tree (hypercube rounds, power-of-two "
        "worlds), or auto (tree iff world is a power of two >= 4)",
        choices=("auto", "all_to_all", "tree"))

# memory governor (memgov/, PR 4)
declare("SRJT_DEVICE_MEMORY_BUDGET", "int", None,
        "device byte budget (read LIVE; unset: memoized backend probe "
        "minus live bytes_in_use)")
declare("SRJT_HOST_MEMORY_BUDGET", "int", 0,
        "host-tier bytes before host->disk demotion (0 = unlimited)")
declare("SRJT_SPILL_ENABLED", "bool", None,
        "1/0 arms/disarms the governor explicitly; unset: armed iff a "
        "device budget is declared")
declare("SRJT_SPILL_DIR", "str", None,
        "disk-tier directory (unset: per-process dir under the system "
        "tempdir)")
declare("SRJT_ADMISSION_MAX_CONCURRENT", "int", 0,
        "cap on concurrently admitted ops (0 = bytes only)")
declare("SRJT_ADMISSION_MAX_WAIT_SEC", "float", 30.0,
        "admission queue wait before the retryable "
        "MemoryBudgetExceeded", positive=True)
declare("SRJT_MEMGOV_HEADROOM", "float", 2.0,
        "input-bytes -> footprint multiplier for the default estimate",
        positive=True)
declare("SRJT_MEMGOV_DROP_SMCACHE", "bool", False,
        "1 lets pressure drop compiled shard_map executables as a "
        "last resort")

# out-of-core partitioned execution (plan/ooc.py, ISSUE 18)
declare("SRJT_OOC_ENABLED", "bool", False,
        "arm out-of-core degradation: a plan whose estimated peak "
        "exceeds the armed SRJT_DEVICE_MEMORY_BUDGET is rewritten "
        "(partition_for_ooc, verifier-discharged) into K hash "
        "partitions streamed through the compiled pipeline and merged")
declare("SRJT_OOC_PARTITIONS", "int", 0,
        "partition count K for out-of-core plans; 0 = auto (smallest "
        "K <= 64 whose per-partition estimate fits half the device "
        "budget)")
declare("SRJT_OOC_PREFETCH", "bool", True,
        "overlap the next partition's spill-in (catalog "
        "re-materialization + a sidecar-pool ping) with the current "
        "partition's compute")
declare("SRJT_OOC_METRICS", "str", None,
        "JSONL path appended one line per out-of-core run (partitions, "
        "resumes, lineage recomputes, spill count, wall) — the "
        "premerge ooc tier's artifact gate")

# concurrent serving runtime (serve/, ISSUE 8)
declare("SRJT_SERVE_MAX_CONCURRENT", "int", 4,
        "scheduler dispatch slots: queries executing concurrently "
        "across the op_boundary -> memgov -> sidecar-pool path",
        minimum=1)
declare("SRJT_SERVE_QUEUE_DEPTH", "int", 64,
        "per-tenant bounded FIFO queue depth; a full queue sheds "
        "lowest-priority-first with retryable Overloaded", minimum=1)
declare("SRJT_SERVE_MAX_QUEUED", "int", 0,
        "global queued-query cap across all tenants (0 = per-tenant "
        "bounds only); past it the overload controller sheds at "
        "admission")
declare("SRJT_SERVE_MAX_QUEUE_AGE_SEC", "float", 30.0,
        "overload controller: oldest-queued-query age past which "
        "admission sheds lowest-priority-first", positive=True)
declare("SRJT_SERVE_RETRY_AFTER_SEC", "float", 0.25,
        "default retry_after_s backoff hint carried by a shed's "
        "Overloaded error", positive=True)

# serving-tier caches (cache/, ISSUE 17)
declare("SRJT_PLAN_CACHE", "bool", False,
        "arm the compiled-plan cache: serve.submit(plan) keys on the "
        "parameterized structural fingerprint, a hit skips "
        "rewrite->verify->compile and rebinds the fresh literals into "
        "the cached optimized plan (re-verified once per structure at "
        "insert, not per submission)")
declare("SRJT_SUBRESULT_CACHE", "bool", False,
        "arm the subresult cache: scan/aggregate stage outputs are "
        "registered as memgov catalog entries (kind=cache) keyed by "
        "(parameterized subtree fingerprint, literal bindings, table "
        "generations), so eviction/spill tiering/byte accounting ride "
        "the governor")
declare("SRJT_CACHE_SHARING", "bool", True,
        "in-flight single-flight sharing of identical submissions "
        "(multi-query optimization): concurrent queries with one plan "
        "key attach to ONE computation and fan the result out — only "
        "consulted when SRJT_PLAN_CACHE is armed")
declare("SRJT_CACHE_PLAN_ENTRIES", "int", 64,
        "parameterized-structure entries the compiled-plan cache "
        "retains (LRU past it)", minimum=1)
declare("SRJT_CACHE_PLAN_VARIANTS", "int", 8,
        "fully-bound CompiledPlan variants retained per structure "
        "entry (exact-literal resubmission reuses the artifact "
        "outright; LRU past it)", minimum=1)
declare("SRJT_CACHE_SUBRESULT_BYTES", "int", 256 * 1024 * 1024,
        "byte cap on subresult-cache catalog entries; past it the "
        "cache LRU-unregisters its own entries (on top of — never "
        "instead of — memgov's spill/eviction pressure)", minimum=1)
declare("SRJT_SERVE_FORECAST_BUDGET_SEC", "float", 0.0,
        "admission-cost forecasting: predicted seconds of queued plan "
        "runtime (observed-cost EWMA carried by cached plans) the "
        "scheduler accepts before shedding with "
        "Overloaded(cause=\"forecast\"); 0 disables the forecaster")

# crash-recoverable serving: durable query journal + spill/checkpoint
# re-attach (serve/journal.py, memgov/persist.py, ISSUE 20)
declare("SRJT_JOURNAL_DIR", "str", None,
        "arm the durable query journal: serve.submit appends an "
        "fsync'd CRC-framed record per admitted query (and its state "
        "transitions) to segmented logs under this directory; a "
        "restarted coordinator replays it to answer DONE work by "
        "digest and resubmit incomplete work (unset: today's "
        "volatile posture — zero new files, no fsync on submit)")
declare("SRJT_JOURNAL_SEGMENT_BYTES", "int", 4 * 1024 * 1024,
        "journal segment roll threshold: an append that would push "
        "the active segment past this many bytes opens a new one",
        minimum=4096)
declare("SRJT_JOURNAL_FSYNC", "bool", True,
        "0 skips the per-append fsync (crash window widens to the OS "
        "page cache; replay still truncates any torn tail)")
declare("SRJT_SPILL_MANIFESTS", "bool", False,
        "arm durable spill metadata: every disk-tier spill/checkpoint "
        "frame gains a CRC-framed sidecar manifest, a fresh process "
        "re-attaches surviving entries into its catalog "
        "(memgov.reattached) and a startup sweep reclaims frames "
        "owned by a provably-dead PID (memgov.orphans_reclaimed)")
declare("SRJT_OOC_DURABLE_CHECKPOINTS", "bool", False,
        "force every completed out-of-core partition checkpoint to "
        "the disk tier at registration (with SRJT_SPILL_MANIFESTS "
        "this is what a restarted coordinator resumes past; off, "
        "checkpoints demote to host and die with the process)")

# Pallas kernel tier (ops/pallas_kernels.py, ISSUE 13)
declare("SRJT_PALLAS_JOIN", "bool", True,
        "arm the paged-hash-table Pallas join tier for single int-key "
        "inner/left joins (0 forces the XLA sort-probe formulation; "
        "unsupported shapes/dtypes fall back automatically either way)")
declare("SRJT_PALLAS_DECODE", "bool", True,
        "arm the fused ragged-decode Pallas kernel for string-column "
        "row decode (0 forces the XLA scatter/funnel formulation; "
        "over-cap windows fall back automatically either way)")
declare("SRJT_PALLAS_INTERPRET", "bool", False,
        "run kernel-tier Pallas paths through the Pallas interpreter "
        "off-TPU (hermetic CI parity of the exact kernel bodies; "
        "production CPU keeps the XLA formulations)")

# runtime / harness
declare("SRJT_NATIVE_LIB", "str", None,
        "explicit libsrjt.so path (before the packaged / dev-build "
        "candidates)")
declare("SRJT_TEST_TPU", "bool", False,
        "run the hermetic test suite against real TPU devices instead "
        "of the virtual 8-device CPU mesh", scope="harness")
declare("SRJT_RESULTS", "str", None,
        "bench drivers append BENCH/JSONL result rows to this path",
        scope="harness")

# plan compiler (plan/, ISSUE 14)
declare("SRJT_PLAN_REPORT", "str", None,
        "append one JSON line per compiled-plan execution (node counts, "
        "rewrites fired, per-stage estimate-vs-actual bytes) to this "
        "path — the ci/premerge.sh compiler tier's artifact source",
        scope="harness")

# plan verification + differential fuzzing (plan/verifier.py,
# analysis/plancheck.py, analysis/planfuzz.py, ISSUE 15)
declare("SRJT_PLANCHECK_ROWS", "int", 256,
        "rows bound per generator when the plancheck CLI compiles the "
        "checked-in plans (compile-only — no execution)",
        scope="harness", positive=True)
declare("SRJT_PLANCHECK_FUZZ_SEEDS", "str", "1234",
        "comma-separated base seeds for the planfuzz differential "
        "smoke; every generated plan is a pure function of "
        "(seed, index)", scope="harness")
declare("SRJT_PLANCHECK_FUZZ_PLANS", "int", 50,
        "plans generated per base seed by the planfuzz CLI",
        scope="harness", minimum=1)

# statistics + cost-based optimizer (plan/stats/, plan/optimizer.py,
# ISSUE 19)
declare("SRJT_STATS_ENABLED", "bool", True,
        "collect per-column sketches (row count, min/max, HLL distinct "
        "count, equi-depth histogram, null fraction) lazily at Scan and "
        "cache them against table generation stamps; 0 falls the "
        "compiler back to its hand-tuned selectivity/width heuristics")
declare("SRJT_STATS_HISTOGRAM_BINS", "int", 16,
        "equi-depth histogram bins per sketched column (more bins = "
        "tighter range-predicate selectivity, more stats memory)",
        minimum=2)
declare("SRJT_STATS_HLL_BITS", "int", 9,
        "HyperLogLog register-index bits per sketched column (2^bits "
        "registers; 9 = 512 registers ~= 3.6% standard error; read "
        "sites clamp to at most 14)", minimum=4)
declare("SRJT_STATS_MAX_ROWS", "int", 262144,
        "head-sample cap per column when collecting sketches; counts "
        "above the cap are scaled back up by the sampling ratio",
        positive=True)
declare("SRJT_CBO_ENABLED", "bool", True,
        "run the cost-based optimizer pass after the default rewrite: "
        "join-order enumeration, build-side commutes, and physical join "
        "strategy resolution, each fired as a verified rewrite with its "
        "own PLAN006 obligation (requires SRJT_STATS_ENABLED)")
declare("SRJT_CBO_DP_TABLES", "int", 6,
        "join-chain length up to which the exact subset-DP order search "
        "runs; longer chains use the greedy fanout-sorted fallback",
        minimum=2)
declare("SRJT_CBO_CALIBRATION", "str", "artifacts/plan_compile.jsonl",
        "plan-report JSONL the byte-estimate calibration is learned "
        "from (per-stage-kind median actual/est, clamped to [0.5, 2x]); "
        "missing file = neutral factors")

# correctness tooling (analysis/, ISSUE 7)
declare("SRJT_LOCKDEP", "bool", False,
        "arm the runtime lock-order instrumentation "
        "(analysis/lockdep.py): records per-thread acquisition stacks, "
        "reports lock-order cycles and blocking-while-locked events at "
        "process exit")
declare("SRJT_LOCKDEP_DIR", "str", "artifacts/lockdep",
        "directory lockdep writes its per-process JSON reports into "
        "(merged/gated by python -m "
        "spark_rapids_jni_tpu.analysis.lockdep)")
declare("SRJT_RACE", "bool", False,
        "arm the dynamic race detector (srjt-race layer 2, rides the "
        "lockdep shim): per-thread vector clocks over lock/Event/"
        "Thread/Semaphore/Barrier edges; unordered accesses to tracked "
        "state land as race_pairs in the lockdep report and fail the "
        "merge gate")

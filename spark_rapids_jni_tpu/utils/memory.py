"""Device-memory budget + retryable-OOM semantics (SURVEY §2.8 RMM row).

The reference threads RMM memory resources through every op signature
(row_conversion.hpp:27-49) and relies on the plugin's retry-on-OOM
discipline; its 2 GiB batching (row_conversion.cu:100-105) is the
splitting mechanism. Here device memory is XLA-owned, so the analog is
*predictive*: ops that grow buffers data-dependently (the exchange
capacity escalation in parallel/table_ops.py) estimate their device
footprint BEFORE dispatch and, over budget, either raise
``MemoryBudgetExceeded`` (a ``RetryableError``: Spark task retry
semantics apply) or split the batch and re-run — never drive XLA into
an allocator OOM that may poison the client.

ENFORCEMENT lives in ``spark_rapids_jni_tpu/memgov`` (ISSUE 4): the
byte-weighted admission controller gates every outermost op_boundary
dispatch on this module's budget, and the spillable buffer catalog
demotes cold buffers device->host->disk under pressure. This module
keeps the shared pieces both tiers consume: the budget resolution
(memoized backend probe, live env override, live ``bytes_in_use``
subtraction) and the footprint estimators.
"""

from __future__ import annotations

from . import knobs
from .errors import RetryableError

__all__ = [
    "MemoryBudgetExceeded",
    "device_memory_budget",
    "exchange_bytes_estimate",
    "split_retry_count",
]


class MemoryBudgetExceeded(RetryableError):
    """A requested device buffer footprint exceeds the memory budget.
    Retryable: the caller may split the batch (ops with split-retry do
    so automatically) or the task may re-run elsewhere."""


# observability: how many batch splits the memory tier has forced.
# The count lives in the metrics registry (utils/metrics.py,
# ``memory.split_retries``) — registry-direct, so it keeps counting
# whether or not SRJT_METRICS_ENABLED arms the hot-path tier (a split
# is a rare recovery event, not a hot path).
_SPLIT_COUNTER = "memory.split_retries"


def split_retry_count() -> int:
    """DEPRECATED: thin alias over the metrics registry counter
    ``memory.split_retries``; read it via
    ``utils.metrics.registry().counter("memory.split_retries").value``
    (or a ``runtime.stats_report()`` snapshot) in new code."""
    from . import metrics

    return metrics.registry().counter(_SPLIT_COUNTER).value


def _note_split() -> None:
    from . import metrics

    metrics.registry().counter(_SPLIT_COUNTER).inc()
    metrics.event("memory.split_retry")


# memoized backend probe: resolving the budget used to re-import jax
# and re-read memory_stats() on EVERY call, which the memgov admission
# controller now makes per-dispatch. The resolved limit is cached; the
# env override stays live (the test hook), and live bytes_in_use is
# subtracted when the backend reports it.
_RESOLVED: "int | None" = None
_STATS_DEV = None  # device whose memory_stats() reports live bytes_in_use
_MIN_BUDGET = 64 << 20  # floor after bytes_in_use subtraction


def _resolve_backend_budget() -> int:
    """One-time probe of the backend's reported limit (or the platform
    default); remembers the device handle when it can report live
    ``bytes_in_use``."""
    global _STATS_DEV
    try:
        import jax

        dev = jax.local_devices()[0]
        stats = dev.memory_stats() if hasattr(dev, "memory_stats") else None
        if stats and stats.get("bytes_limit"):
            if stats.get("bytes_in_use") is not None:
                _STATS_DEV = dev
            return int(stats["bytes_limit"] * 0.5)
        if dev.platform == "tpu":
            return 8 << 30  # half of v5e's 16 GB HBM
    except Exception:  # srjt-lint: allow-broad-except(backend probe is best-effort; any failure falls to the conservative platform default)
        pass
    return 4 << 30  # conservative CPU-tier default


def device_memory_budget() -> int:
    """Usable device bytes for a single op's working buffers.

    Resolution order: ``SRJT_DEVICE_MEMORY_BUDGET`` (bytes; read LIVE —
    the test hook and the operator override), else the memoized backend
    probe — the reported limit when the backend exposes one, else a
    platform default (v5e HBM less runtime reserve; host RAM share on
    CPU) — minus the backend's live ``bytes_in_use`` when it reports
    one (floored at 64 MiB so transient allocator spikes degrade to
    splitting, never to a zero budget). The budget is per-op headroom,
    not the raw chip size: XLA temps routinely need a small multiple of
    the declared buffers."""
    # `is not None`, not truthiness: an explicit 0 is a real operator
    # contract (arm the governor, force everything over-budget), never
    # "unset" (the declared default is None)
    env = knobs.get_int("SRJT_DEVICE_MEMORY_BUDGET")
    if env is not None:
        return env
    global _RESOLVED
    if _RESOLVED is None:
        _RESOLVED = _resolve_backend_budget()
    budget = _RESOLVED
    if _STATS_DEV is not None:
        try:
            in_use = int(_STATS_DEV.memory_stats().get("bytes_in_use") or 0)
        except Exception:  # srjt-lint: allow-broad-except(live bytes_in_use probe is advisory; a failed stats call must not sink the budget query)
            in_use = 0
        if in_use:
            budget = max(budget - in_use, _MIN_BUDGET)
    return budget


def exchange_bytes_estimate(row_bytes: int, n_parts: int, capacity: int) -> int:
    """PER-DEVICE bytes an all_to_all exchange program needs at a given
    per-destination ``capacity``: each shard holds its own [n_parts,
    capacity] bucket matrix per lane, doubled for the send/receive pair
    the collective keeps live. Compared against the per-device
    budget — a fleet-total estimate would over-reject by n_parts."""
    return 2 * n_parts * capacity * max(row_bytes, 1)

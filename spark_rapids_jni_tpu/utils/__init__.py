"""Aux subsystems (SURVEY §5): op-boundary dispatch instrumentation,
fault injection, tracing/profiling hooks, error classification, the
retry orchestrator (backoff / split / capacity re-try), the runtime
metrics registry + structured event log (utils/metrics.py), and the
deadline/cancellation/circuit-breaker tier (utils/deadline.py)."""

from . import deadline, dispatch, errors, faultinj, metrics, retry, tracing  # noqa: F401

"""Aux subsystems (SURVEY §5): op-boundary dispatch instrumentation,
fault injection, tracing/profiling hooks, error classification."""

from . import dispatch, errors, faultinj, tracing  # noqa: F401

"""Aux subsystems (SURVEY §5): op-boundary dispatch instrumentation,
fault injection, tracing/profiling hooks, error classification, and
the retry orchestrator (backoff / split / capacity re-try)."""

from . import dispatch, errors, faultinj, retry, tracing  # noqa: F401

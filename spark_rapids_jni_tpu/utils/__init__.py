"""Aux subsystems (SURVEY §5): op-boundary dispatch instrumentation,
fault injection, tracing/profiling hooks, error classification, the
retry orchestrator (backoff / split / capacity re-try), and the runtime
metrics registry + structured event log (utils/metrics.py)."""

from . import dispatch, errors, faultinj, metrics, retry, tracing  # noqa: F401

"""Per-query deadline budgets, cooperative cancellation, and the
circuit breaker (SURVEY §5 bounded-latency posture; ISSUE 3).

PR 1 closed the recovery loop (retry/backoff/split) and PR 2 made it
observable, but nothing bounded *total* wall-clock: a query under chaos
could retry indefinitely, a hung sidecar worker blocked callers for the
full per-request socket deadline on every attempt, and the runtime kept
redialing a persistently failing device path forever. Production query
engines treat bounded latency and fail-fast degradation as first-class
(Theseus builds distributed execution around deadline-bounded data
movement; PAPERS.md); this module is that subsystem:

- **Deadline**: a wall-clock budget carried in a context-local
  (``contextvars``) object every blocking layer consults —
  ``remaining()`` / ``expired()`` / ``check()``. One budget spans the
  whole dynamic extent of a query: nested scopes can only SHRINK the
  remaining time, never extend it.
- **CancelToken**: cooperative cancellation any layer can trip
  (``cancel(reason)``) or poll (``cancelled()``). A Deadline carries a
  token, and nested scopes share the enclosing scope's token, so
  tripping the query's token cancels every layer beneath it.
- **DeadlineExceeded** (utils/errors.py): the error an exhausted budget
  raises. It is a DeviceError so dispatch classification passes it
  through unchanged, but deliberately NOT a RetryableError — retrying
  cannot manufacture time — and not Fatal: the device is fine, the
  query is out of budget.
- **CircuitBreaker**: the fail-fast degradation state machine for the
  sidecar path (sidecar.py holds the process-global instance). After
  ``threshold`` consecutive supervision failures the breaker OPENS and
  requests degrade to the host engine immediately — no dial, no socket
  timeout wait; after ``cooldown_s`` one HALF-OPEN probe rides the
  device path — success CLOSES the breaker (device mode restored),
  failure re-opens it. States, transitions, and trip causes write
  registry-direct into utils/metrics (durable product counters, the
  PR 2 always-on contract) and surface in ``runtime.stats_report()``.

Activation: ``SRJT_DEADLINE_SEC`` installs an ambient per-query budget
— the OUTERMOST op_boundary dispatch (utils/dispatch.py) opens the
scope, so one env knob bounds every op including all its retries and
backoff sleeps — or per call: ``some_op(..., deadline_s=2.5)`` on any
op_boundary-wrapped op / ``runtime.device_groupby_sum``, or
``deadline.scope(2.5)`` for an explicit region.

Environment:

    SRJT_DEADLINE_SEC          ambient per-query budget in seconds
                               (default: none — unbounded, the seed
                               contract)
    SRJT_BREAKER_THRESHOLD     consecutive sidecar supervision failures
                               before the breaker opens (default 5)
    SRJT_BREAKER_COOLDOWN_SEC  open -> half-open probe delay (default 30)
"""

from __future__ import annotations

import contextlib
import contextvars
import math
import threading
import time
from typing import Optional

from . import knobs
from .errors import DeadlineExceeded

__all__ = [
    "CancelToken",
    "Deadline",
    "CircuitBreaker",
    "scope",
    "op_scope",
    "current",
    "remaining",
    "check",
    "cancel",
    "default_budget",
    "set_default_budget",
    "BREAKER_STATE_CODES",
]


class CancelToken:
    """Cooperative cancellation flag: any layer trips it, every layer
    polls it. Idempotent — the FIRST cancel's reason wins (it names the
    root cause; later trips are echoes)."""

    __slots__ = ("_lock", "_flag", "_reason")

    def __init__(self):
        self._lock = threading.Lock()
        self._flag = False
        self._reason: Optional[str] = None

    def cancel(self, reason: str = "cancelled") -> None:
        with self._lock:
            if not self._flag:
                self._flag = True
                self._reason = str(reason)

    def cancelled(self) -> bool:
        # this poll sits on every cancel point of every hot path: a
        # bool read is GIL-atomic, monotonic False->True, and a racing
        # reader that misses the flip just polls again one layer down
        return self._flag  # srjt-race: allow-unguarded(lock-free cancel-point poll; GIL-atomic monotonic flag, next poll sees the flip)

    @property
    def reason(self) -> Optional[str]:
        return self._reason  # srjt-race: allow-unguarded(written once under _lock before _flag flips; only read after cancelled() observed True)


class Deadline:
    """A wall-clock budget plus a cancel token.

    ``budget_s=None`` is the unbounded deadline (token-only): it never
    expires, but its token still cancels — the shape an interactive
    "stop this query" control wants without forcing a time limit.
    """

    __slots__ = ("budget_s", "token", "_t_end", "_clock")

    def __init__(
        self,
        budget_s: Optional[float] = None,
        token: Optional[CancelToken] = None,
        clock=time.monotonic,
    ):
        if budget_s is not None and budget_s <= 0:
            raise ValueError(f"deadline budget must be > 0, got {budget_s}")
        self.budget_s = None if budget_s is None else float(budget_s)
        self.token = token if token is not None else CancelToken()
        self._clock = clock
        self._t_end = math.inf if budget_s is None else clock() + float(budget_s)

    def remaining(self) -> float:
        """Seconds left (may be negative once expired; +inf unbounded)."""
        return self._t_end - self._clock()

    def expired(self) -> bool:
        return self._clock() >= self._t_end

    def cancelled(self) -> bool:
        return self.token.cancelled()

    def done(self) -> bool:
        """True when no further work should START under this deadline."""
        return self.token.cancelled() or self.expired()

    def cancel(self, reason: str = "cancelled") -> None:
        self.token.cancel(reason)

    def exceeded(self, what: str = "op") -> DeadlineExceeded:
        """Build (don't raise) the error describing why this deadline is
        done — cancel reason when the token tripped first, the budget
        otherwise."""
        if self.token.cancelled() and not self.expired():
            return DeadlineExceeded(f"{what}: cancelled ({self.token.reason})")
        b = "unbounded" if self.budget_s is None else f"{self.budget_s:g}s"
        return DeadlineExceeded(
            f"{what}: deadline budget exhausted (budget={b})"
        )

    def check(self, what: str = "op") -> None:
        """Cancel point: raise DeadlineExceeded when done, else return."""
        if self.done():
            raise self.exceeded(what)


# ---------------------------------------------------------------------------
# context-local propagation
# ---------------------------------------------------------------------------

_current: contextvars.ContextVar = contextvars.ContextVar(
    "srjt_deadline", default=None
)


def current() -> Optional[Deadline]:
    """The active Deadline for this context, or None."""
    return _current.get()


def remaining() -> float:
    """Seconds left in the active scope; +inf with no active deadline."""
    d = _current.get()
    return math.inf if d is None else d.remaining()


def check(what: str = "op") -> None:
    """Module-level cancel point: no-op without an active deadline."""
    d = _current.get()
    if d is not None:
        d.check(what)


def cancel(reason: str = "cancelled") -> bool:
    """Trip the active scope's token; False when no scope is active."""
    d = _current.get()
    if d is None:
        return False
    d.cancel(reason)
    return True


@contextlib.contextmanager
def scope(
    budget_s: Optional[float] = None,
    token: Optional[CancelToken] = None,
    clock=time.monotonic,
):
    """Install a Deadline for the dynamic extent of the with-block.

    Nesting discipline: the effective budget is
    ``min(budget_s, enclosing remaining)`` — an inner scope can shrink
    the time left but never extend past the query's budget — and, with
    no explicit ``token``, the enclosing scope's token is SHARED, so
    cancelling the query cancels every nested layer.
    """
    outer = _current.get()
    eff = None if budget_s is None else float(budget_s)
    tok = token
    if outer is not None:
        rem = outer.remaining()
        if not math.isinf(rem):
            # an already-expired outer still yields a valid (instantly
            # done) inner deadline rather than a constructor error
            rem = max(rem, 1e-9)
            eff = rem if eff is None else min(eff, rem)
        if tok is None:
            tok = outer.token
    d = Deadline(eff, token=tok, clock=clock)
    if outer is not None:
        # clamp the absolute edge too: remaining() and the constructor
        # read the clock at different instants, and even that epsilon
        # must not let an inner scope outlive the query's deadline
        d._t_end = min(d._t_end, outer._t_end)
    handle = _current.set(d)
    try:
        yield d
    finally:
        _current.reset(handle)


# ---------------------------------------------------------------------------
# ambient per-query budget (SRJT_DEADLINE_SEC)
# ---------------------------------------------------------------------------


def _parse_env_budget() -> Optional[float]:
    # typed registry accessor (utils/knobs.py): malformed / <= 0 warns
    # and keeps the default — None, "no ambient budget", the seed posture
    return knobs.get_float("SRJT_DEADLINE_SEC")


_default_budget: Optional[float] = _parse_env_budget()


def default_budget() -> Optional[float]:
    """The ambient per-query budget (SRJT_DEADLINE_SEC), or None."""
    return _default_budget


def set_default_budget(budget_s: Optional[float]) -> None:
    """Programmatic override of the ambient budget (tests, embedders)."""
    global _default_budget
    if budget_s is not None and float(budget_s) <= 0:
        raise ValueError(f"deadline budget must be > 0, got {budget_s}")
    _default_budget = None if budget_s is None else float(budget_s)


@contextlib.contextmanager
def op_scope(budget_s: Optional[float] = None):
    """Dispatch-entry helper (runtime.py entry points): an explicit
    per-call budget opens a nested scope; with none, the OUTERMOST
    dispatch under an ambient SRJT_DEADLINE_SEC opens the per-query
    scope; otherwise the enclosing scope (or no deadline at all) rides
    through unchanged. Yields the active Deadline or None.

    utils/dispatch.py's op_boundary INLINES this same policy on its hot
    path (so the fully-disarmed case pays no context manager) — a
    semantic change here must land there in lockstep.
    """
    if budget_s is None:
        if _current.get() is not None or _default_budget is None:
            yield _current.get()
            return
        budget_s = _default_budget
    with scope(budget_s) as d:
        yield d


# ---------------------------------------------------------------------------
# circuit breaker (the sidecar path's fail-fast degradation machine)
# ---------------------------------------------------------------------------

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"

# gauge encoding for the metrics registry (JSON-clean, orderable)
BREAKER_STATE_CODES = {STATE_CLOSED: 0, STATE_OPEN: 1, STATE_HALF_OPEN: 2}


class CircuitBreaker:
    """Consecutive-failure circuit breaker with a half-open probe.

    State machine::

        CLOSED --(threshold consecutive failures)--> OPEN
        OPEN   --(cooldown_s elapsed, next request)--> HALF_OPEN
        HALF_OPEN --(probe success)--> CLOSED
        HALF_OPEN --(probe failure)--> OPEN  (cooldown restarts)

    While OPEN, ``allow()`` returns False and counts a fast-fail — the
    caller degrades immediately (the sidecar client runs the op on the
    host engine without dialing). While HALF_OPEN exactly ONE in-flight
    probe is allowed; concurrent requests keep fast-failing until the
    probe settles.

    Observability is registry-direct (utils/metrics; the always-on
    durable-counter contract): ``<name>.state`` gauge
    (0 closed / 1 open / 2 half_open), ``<name>.opened_total`` /
    ``.half_opened_total`` / ``.closed_total`` / ``.fast_fails_total``
    counters, and a ``<name>.transition`` event (gated, like all
    events) carrying the trip cause.
    """

    def __init__(
        self,
        name: str = "sidecar.breaker",
        threshold: Optional[int] = None,
        cooldown_s: Optional[float] = None,
        clock=time.monotonic,
    ):
        self.name = name
        self._lock = threading.Lock()
        self._clock = clock
        # env values ride the knobs warn-and-default posture; a
        # fractional threshold (0 < v < 1) additionally clamps to 1 so
        # int() truncation can never produce a lazily-crashing 0
        self._threshold = (
            max(1, int(knobs.get_float("SRJT_BREAKER_THRESHOLD")))
            if threshold is None
            else int(threshold)
        )
        self._cooldown_s = (
            knobs.get_float("SRJT_BREAKER_COOLDOWN_SEC")
            if cooldown_s is None
            else float(cooldown_s)
        )
        if self._threshold < 1:
            raise ValueError(
                f"breaker threshold must be >= 1, got {self._threshold}"
            )
        if self._cooldown_s <= 0:
            raise ValueError(
                f"breaker cooldown must be > 0, got {self._cooldown_s}"
            )
        self._state = STATE_CLOSED
        self._failures = 0
        self._open_until = 0.0
        self._probe_in_flight = False
        self._last_trip_cause: Optional[str] = None
        self._transitions = {STATE_CLOSED: 0, STATE_OPEN: 0, STATE_HALF_OPEN: 0}
        self._fast_fails = 0
        self._gauge().set(BREAKER_STATE_CODES[STATE_CLOSED])

    # -- metrics plumbing ----------------------------------------------------

    def _gauge(self):
        from . import metrics

        return metrics.registry().gauge(f"{self.name}.state")

    def _transition_locked(self, new_state: str, cause: str) -> None:
        from . import metrics

        self._state = new_state
        self._transitions[new_state] += 1
        suffix = {
            STATE_OPEN: "opened_total",
            STATE_HALF_OPEN: "half_opened_total",
            STATE_CLOSED: "closed_total",
        }[new_state]
        metrics.registry().counter(f"{self.name}.{suffix}").inc()
        self._gauge().set(BREAKER_STATE_CODES[new_state])
        metrics.event(
            f"{self.name}.transition", state=new_state, cause=cause,
            consecutive_failures=self._failures,
        )

    # -- configuration -------------------------------------------------------

    def configure(
        self, threshold: Optional[int] = None, cooldown_s: Optional[float] = None
    ) -> None:
        """Replace the knobs and reset the state machine (tests, and
        operators re-tuning a live process)."""
        with self._lock:
            if threshold is not None:
                if int(threshold) < 1:
                    raise ValueError(f"breaker threshold must be >= 1, got {threshold}")
                self._threshold = int(threshold)
            if cooldown_s is not None:
                if float(cooldown_s) <= 0:
                    raise ValueError(f"breaker cooldown must be > 0, got {cooldown_s}")
                self._cooldown_s = float(cooldown_s)
            self._reset_locked()

    def reset(self) -> None:
        """Back to CLOSED with zeroed local history (registry counters
        are cumulative and keep their totals)."""
        with self._lock:
            self._reset_locked()

    def _reset_locked(self) -> None:
        self._state = STATE_CLOSED
        self._failures = 0
        self._open_until = 0.0
        self._probe_in_flight = False
        self._last_trip_cause = None
        self._transitions = {STATE_CLOSED: 0, STATE_OPEN: 0, STATE_HALF_OPEN: 0}
        self._fast_fails = 0
        self._gauge().set(BREAKER_STATE_CODES[STATE_CLOSED])

    # -- the state machine ---------------------------------------------------

    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May this request ride the device path? False == fast-fail
        (degrade immediately, no dial). Entering half-open happens here,
        lazily, on the first request after the cooldown."""
        from . import metrics

        with self._lock:
            if self._state == STATE_CLOSED:
                return True
            if self._state == STATE_OPEN and self._clock() >= self._open_until:
                self._transition_locked(STATE_HALF_OPEN, cause="cooldown_elapsed")
                self._probe_in_flight = True
                return True
            if self._state == STATE_HALF_OPEN and not self._probe_in_flight:
                self._probe_in_flight = True
                return True
            self._fast_fails += 1
            metrics.registry().counter(f"{self.name}.fast_fails_total").inc()
            return False

    def record_success(self) -> None:
        """A device-path request round-tripped: reset the consecutive-
        failure run; a successful half-open probe closes the breaker."""
        with self._lock:
            self._failures = 0
            self._probe_in_flight = False
            if self._state != STATE_CLOSED:
                self._transition_locked(STATE_CLOSED, cause="probe_success")

    def abort_probe(self) -> None:
        """Release the half-open probe slot with NO health verdict (the
        probe was interrupted, not answered) so the breaker cannot wedge
        in half-open fast-failing forever."""
        with self._lock:
            self._probe_in_flight = False

    def record_failure(self, cause: str = "failure") -> None:
        """One supervision failure. Trips OPEN at the threshold (or
        instantly from HALF_OPEN: the probe failed, the path is still
        bad) and (re)starts the cooldown."""
        with self._lock:
            self._failures += 1
            self._probe_in_flight = False
            if self._state == STATE_HALF_OPEN or (
                self._state == STATE_CLOSED and self._failures >= self._threshold
            ):
                self._last_trip_cause = cause
                self._open_until = self._clock() + self._cooldown_s
                self._transition_locked(STATE_OPEN, cause=cause)
            elif self._state == STATE_OPEN:
                # stragglers failing while open keep the cooldown fresh
                self._open_until = self._clock() + self._cooldown_s

    # -- observability -------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-clean state for runtime.stats_report()."""
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "opened_total": self._transitions[STATE_OPEN],
                "half_opened_total": self._transitions[STATE_HALF_OPEN],
                "closed_total": self._transitions[STATE_CLOSED],
                "fast_fails_total": self._fast_fails,
                "last_trip_cause": self._last_trip_cause,
                "threshold": self._threshold,
                "cooldown_s": self._cooldown_s,
            }

"""srjt-trace: distributed per-query tracing (ISSUE 12 tentpole).

The seed's trace tool was a 57-line local ``jax.named_scope`` wrapper —
the NVTX-range analog (SURVEY §5, ``CUDF_FUNC_RANGE``): per-operation
ranges, one process, no causality. A query now crosses the serve
scheduler's tenant queue, memgov admission, retry/split recursion, pool
routing with hedged duplicate legs, a spawned sidecar worker process,
and possibly a TCP exchange peer — and "why was THIS query slow" needs
a trace that follows causality ACROSS those process boundaries, which
NVTX never had to (Theseus, arxiv 2508.05029: distributed query engines
live or die by visibility into data movement). This module is that
subsystem:

- **TraceContext**: trace_id / span_id / parent_id plus a sampled flag,
  carried context-locally (``contextvars``) alongside the existing
  ``deadline.scope`` discipline — one context spans a query's whole
  dynamic extent, including threads entered via
  ``contextvars.copy_context()`` (hedge legs, exchange pulls).
- **Span**: one timed region with annotations. ``span(name, **ann)``
  opens a child of the active span; ``op_span`` (utils/dispatch.py's
  entry) additionally AUTO-ROOTS a one-op trace at the outermost
  boundary when no context is active, so a standalone runtime call is
  traceable without a serving layer.
- **Gated no-op stubs** (the metrics/SRJT005 pattern): with
  ``SRJT_TRACE_ENABLED=0`` every entry point is one boolean read and a
  shared null object — no ids minted, no clock read, no allocation.
- **Cross-process propagation**: ``wire_context()`` packs the active
  context into a fixed 17-byte blob (trace_id, parent span id, flags);
  the sidecar client sends it under a new TRACE flag bit negotiated
  per request exactly like CRC_FLAG (sidecar.py — the C++ legacy
  walker stays byte-for-byte), and the TCP exchange carries it on a
  traced fetch verb (parallel/shuffle.py). The receiving process
  installs it with ``remote_scope`` so its spans parent to the
  caller's span — in its OWN per-process span log, joined later by
  ``python -m spark_rapids_jni_tpu.analysis.tracemerge``.
- **Flight recorder** (utils/trace_sink.py): every finished root trace
  lands in a bounded ring; slow (``SRJT_SLOW_QUERY_SEC``), shed, and
  failed queries auto-flush to ``SRJT_TRACE_LOG`` with their full span
  tree plus a metrics-delta snapshot. ``runtime.explain_last()``
  renders the worst recent query.

The original XProf hooks survive unchanged: ``func_range`` emits a
``jax.named_scope`` + ``TraceAnnotation`` under the same gate, and
``profile_to`` wraps jax.profiler start/stop (now gate-aware and
exception-safe — ISSUE 12 satellite).

Environment (declared in utils/knobs.py; srjt-lint SRJT001/007):

    SRJT_TRACE_ENABLED    arm tracing (spans + jax named scopes)
    SRJT_TRACE_LOG        span-log base path; each process appends to
                          ``<base>.<pid>.jsonl`` (per-process logs —
                          the tracemerge join input)
    SRJT_TRACE_SAMPLE     fraction of root traces sampled (default 1.0)
    SRJT_SLOW_QUERY_SEC   root traces slower than this auto-flush
    SRJT_TRACE_RING       flight-recorder ring capacity
    SRJT_TRACE_MAX_SPANS  per-trace in-memory span cap (the log is
                          never capped; overflow is counted)
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import struct
import threading
import time
from typing import Optional

import jax

from . import knobs

__all__ = [
    "set_enabled",
    "is_enabled",
    "enabled",
    "func_range",
    "profile_to",
    "TraceContext",
    "Span",
    "QueryTrace",
    "span",
    "op_span",
    "closed_span",
    "event_span",
    "annotate",
    "start_trace",
    "current_context",
    "current_span",
    "wire_context",
    "decode_wire_context",
    "remote_scope",
    "TRACE_CTX_LEN",
]

# one module bool, rebound plainly — the SAME discipline as
# metrics._enabled (ISSUE 12 satellite: the old set_enabled wrote under
# a lock while func_range read bare, a guarded/unguarded mix for a
# GIL-atomic monotonic flag; now both sides are the plain word)
_enabled = knobs.get_bool("SRJT_TRACE_ENABLED")


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def enabled():
    """Scoped arming for tests/benches (mirrors metrics.enabled)."""
    global _enabled
    prev = _enabled
    _enabled = True
    try:
        yield
    finally:
        _enabled = prev


@contextlib.contextmanager
def func_range(name: str):
    """Named scope over an op: no-op when tracing is off (same contract
    as NVTX ranges — safe to leave in hot paths)."""
    if not _enabled:
        yield
        return
    with jax.named_scope(name):
        with jax.profiler.TraceAnnotation(name):
            yield


@contextlib.contextmanager
def profile_to(log_dir: str):
    """Capture a device+host profile into ``log_dir`` (XProf/TensorBoard
    format; the nsys-profile analog for a region). Gate-aware: with
    tracing disabled the body runs unprofiled (the region stays a
    no-op, like every other entry point here). Exception-safe: a
    ``start_trace`` that raises AFTER partially arming the profiler is
    torn down before the error surfaces — the old version leaked the
    half-started session, and the NEXT profile_to then failed on a
    "trace already started" it did not cause."""
    if not _enabled:
        yield
        return
    try:
        jax.profiler.start_trace(log_dir)
    except BaseException:
        try:
            jax.profiler.stop_trace()
        except Exception:  # srjt-lint: allow-broad-except(best-effort teardown of a partially-armed profiler session; the original start_trace error is what surfaces)
            pass
        raise
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# ---------------------------------------------------------------------------
# distributed spans: ids, context, and the context-local carrier
# ---------------------------------------------------------------------------

# wire blob (cross-process propagation): trace_id, parent span id,
# flags (bit 0 = sampled). Fixed size so the sidecar worker and the
# exchange peer read exactly TRACE_CTX_LEN bytes after the header.
_TRACE_BLOB = struct.Struct("<QQB")
TRACE_CTX_LEN = _TRACE_BLOB.size  # 17


def _new_id() -> int:
    """64-bit random span/trace id (armed paths only — never minted
    when the gate is off)."""
    return int.from_bytes(os.urandom(8), "little") or 1


class _NullSpan:
    """Shared no-op handed out when tracing is disabled or the trace is
    unsampled: annotate() is a pass, so instrumented sites stay
    branch-free."""

    __slots__ = ()
    span_id = 0
    depth = 0

    def annotate(self, **kw) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Span:
    """One timed region of a trace. Created only through the module
    entry points; finished (duration computed, record emitted) by the
    ``span()`` context manager. ``annotate()`` is owner-thread writes
    (or race-settle-lock writes, the hedge winner mark) — the record is
    built only at finish, after all writers are done."""

    __slots__ = ("ctx", "name", "span_id", "parent_id", "depth",
                 "t_wall", "_t0", "annotations", "status")

    def __init__(self, ctx: "TraceContext", name: str,
                 parent_id: Optional[int], depth: int, annotations):
        self.ctx = ctx
        self.name = name
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.depth = depth
        self.t_wall = time.time()
        self._t0 = time.perf_counter()
        self.annotations = dict(annotations) if annotations else {}
        self.status = "ok"

    def annotate(self, **kw) -> None:
        self.annotations.update(kw)

    def _record(self, dur_s: float) -> dict:
        rec = {
            "kind": "span",
            "trace": f"{self.ctx.trace_id:016x}",
            "span": f"{self.span_id:016x}",
            "parent": (None if self.parent_id is None
                       else f"{self.parent_id:016x}"),
            "name": self.name,
            "ts": round(self.t_wall, 6),
            "dur_us": round(dur_s * 1e6, 1),
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "status": self.status,
        }
        if self.annotations:
            rec["annotations"] = self.annotations
        return rec


class _Anchor:
    """Parent-only carrier for a REMOTE context (the caller's span id
    decoded off the wire): spans created under it parent to the remote
    span, but there is no local Span object to finish."""

    __slots__ = ("span_id", "depth")

    def __init__(self, span_id: int):
        self.span_id = span_id
        self.depth = 0


class TraceContext:
    """One query's trace identity plus its per-process span buffer.
    The buffer is BOUNDED (``SRJT_TRACE_MAX_SPANS``; overflow counted,
    the span LOG is never capped) and SEALED when the root finishes —
    a straggling hedge loser that completes after the query settled
    still reaches the log, it just misses the in-memory record."""

    __slots__ = ("trace_id", "sampled", "remote", "_lock", "_spans",
                 "_dropped", "_sealed", "_counters0", "_max_spans")

    def __init__(self, trace_id: Optional[int] = None, sampled: bool = True,
                 remote: bool = False):
        self.trace_id = _new_id() if trace_id is None else int(trace_id)
        self.sampled = bool(sampled)
        self.remote = bool(remote)
        self._lock = threading.Lock()
        self._spans: list = []
        self._dropped = 0
        self._sealed = False
        self._counters0: Optional[dict] = None
        self._max_spans = knobs.get_int("SRJT_TRACE_MAX_SPANS")

    def add(self, rec: dict) -> None:
        with self._lock:
            if self._sealed:
                return  # straggler past the root finish: log-only
            if len(self._spans) < self._max_spans:
                self._spans.append(rec)
            else:
                self._dropped += 1

    def seal(self):
        """Freeze the buffer; returns (spans, dropped)."""
        with self._lock:
            self._sealed = True
            return list(self._spans), self._dropped


# the active (context, span-like) pair; span-like is the innermost OPEN
# Span (or a remote _Anchor) new spans parent to
_current: contextvars.ContextVar = contextvars.ContextVar(
    "srjt_trace_ctx", default=None
)


def current_context() -> Optional[TraceContext]:
    a = _current.get()
    return None if a is None else a[0]


def current_span():
    """The innermost open Span (or remote anchor), or None."""
    a = _current.get()
    return None if a is None else a[1]


def _sink():
    from . import trace_sink

    return trace_sink


def _record_and_emit(ctx: TraceContext, rec: dict, depth: int) -> None:
    """The one record pipeline every finished span goes through:
    in-memory buffer, span log, stage-summary counters."""
    ctx.add(rec)
    sink = _sink()
    sink.emit_span(rec)
    sink.note_span(rec["dur_us"], depth)


def _finish_span(sp: Span) -> None:
    dur_s = time.perf_counter() - sp._t0
    _record_and_emit(sp.ctx, sp._record(dur_s), sp.depth)


@contextlib.contextmanager
def span(name: str, **annotations):
    """A child span of the active trace. No-op (shared null span) when
    tracing is disabled or no sampled context is active — random
    instrumented layers never mint stray traces; roots come only from
    ``start_trace`` (the serve scheduler) and ``op_span`` (the
    outermost op boundary). An escaping exception marks the span
    status ``error`` (and propagates)."""
    if not _enabled:
        yield _NULL_SPAN
        return
    a = _current.get()
    if a is None or not a[0].sampled:
        yield _NULL_SPAN
        return
    ctx, parent = a
    sp = Span(ctx, name, parent.span_id, parent.depth + 1, annotations)
    tok = _current.set((ctx, sp))
    try:
        yield sp
    except BaseException as e:
        sp.status = "error"
        sp.annotations.setdefault("error", type(e).__name__)
        raise
    finally:
        _current.reset(tok)
        _finish_span(sp)


def event_span(name: str, **annotations) -> None:
    """An instantaneous event recorded as a zero-duration closed child
    span — how the cache tier (srjt-cache, ISSUE 17) stamps hit/miss/
    attach decisions into the query's span tree without opening a
    region. Same no-op contract as ``closed_span``: nothing happens
    without an active sampled context."""
    closed_span(name, 0.0, **annotations)


def closed_span(name: str, dur_s: float, t_wall: Optional[float] = None,
                **annotations) -> None:
    """Record an already-elapsed region (e.g. the serve queue wait,
    measured between submit and dispatch) as a finished child span of
    the active trace. No-op without an active sampled context."""
    if not _enabled:
        return
    a = _current.get()
    if a is None or not a[0].sampled:
        return
    ctx, parent = a
    sp = Span(ctx, name, parent.span_id, parent.depth + 1, annotations)
    sp.t_wall = time.time() - dur_s if t_wall is None else t_wall
    _record_and_emit(ctx, sp._record(max(float(dur_s), 0.0)), sp.depth)


def annotate(**kw) -> None:
    """Annotate the innermost open span (no-op when none is active) —
    the retry orchestrator stamps attempt counts through this without
    knowing which layer's span it lands on."""
    if not _enabled:
        return
    a = _current.get()
    if a is None or not a[0].sampled:
        return
    sp = a[1]
    if isinstance(sp, Span):
        sp.annotations.update(kw)


# ---------------------------------------------------------------------------
# roots: per-query traces (serve scheduler, outermost op boundary)
# ---------------------------------------------------------------------------


class QueryTrace:
    """One root span + its context: the handle the query's OWNER holds
    across threads (the serve scheduler stores it on the QueryHandle;
    ``op_span`` holds it for one dispatch). ``activate()`` installs it
    on the executing thread; ``finish(status)`` is idempotent — it
    seals the context, computes the metrics delta, and hands the
    completed trace to the flight recorder (which flushes slow / shed /
    failed queries to the span log automatically)."""

    __slots__ = ("ctx", "root", "_lock", "_finished")

    def __init__(self, ctx: TraceContext, root: Span):
        self.ctx = ctx
        self.root = root
        self._lock = threading.Lock()
        self._finished = False

    @contextlib.contextmanager
    def activate(self):
        tok = _current.set((self.ctx, self.root))
        try:
            yield self
        finally:
            _current.reset(tok)

    def annotate(self, **kw) -> None:
        self.root.annotations.update(kw)

    def finish(self, status: str = "ok") -> None:
        with self._lock:
            if self._finished:
                return
            self._finished = True
        if not self.ctx.sampled:
            # an UNSAMPLED query trace: the context existed only so
            # inner layers saw "a trace is active (and declined)" —
            # nothing was buffered, nothing is recorded
            return
        dur_s = time.perf_counter() - self.root._t0
        self.root.status = status
        _record_and_emit(self.ctx, self.root._record(dur_s),
                         self.root.depth)
        sink = _sink()
        spans, dropped = self.ctx.seal()
        delta = None
        if self.ctx._counters0 is not None:
            from . import metrics

            delta = {
                k: v - self.ctx._counters0.get(k, 0)
                for k, v in metrics.counters_snapshot().items()
                if v != self.ctx._counters0.get(k, 0)
            }
        sink.record_trace({
            "kind": "trace",
            "trace": f"{self.ctx.trace_id:016x}",
            "name": self.root.name,
            "status": status,
            "ts": round(self.root.t_wall, 6),
            "duration_s": round(dur_s, 6),
            "pid": os.getpid(),
            "annotations": self.root.annotations,
            "spans": spans,
            "dropped_spans": dropped,
            "metrics_delta": delta or {},
        })


def _sampled() -> bool:
    frac = knobs.get_float("SRJT_TRACE_SAMPLE")
    if frac is None or frac >= 1.0:
        return True
    if frac <= 0.0:
        return False
    return random.random() < frac


def start_trace(name: str, **annotations) -> Optional[QueryTrace]:
    """Open a ROOT span + context for one query. Returns None only
    when tracing is DISABLED (callers keep a None-guard, the
    one-boolean-read contract). When the SAMPLER declines, an
    UNSAMPLED QueryTrace is returned instead: activating it installs
    a not-sampled context, so every layer inside the query — span(),
    wire_context(), and crucially op_span's auto-root — sees "a trace
    decision was made" and stays silent, rather than each outermost op
    boundary re-rolling the sampler and minting one-op fragment
    traces. The start-of-query counters snapshot (sampled roots only)
    feeds the flight recorder's metrics-delta."""
    if not _enabled:
        return None
    if not _sampled():
        _sink().note_unsampled()
        ctx = TraceContext(sampled=False)
        return QueryTrace(ctx, Span(ctx, name, None, 0, None))
    from . import metrics

    ctx = TraceContext()
    ctx._counters0 = metrics.counters_snapshot()
    root = Span(ctx, name, None, 0, annotations)
    _sink().note_trace()
    return QueryTrace(ctx, root)


@contextlib.contextmanager
def op_span(name: str):
    """utils/dispatch.py's entry: a child span when a trace is active,
    else a fresh auto-rooted one-op trace (mirroring the deadline
    ``op_scope`` outermost-only policy) — a standalone runtime call is
    a one-op query, traceable without the serving layer."""
    if not _enabled:
        yield _NULL_SPAN
        return
    a = _current.get()
    if a is not None:
        with span(f"op.{name}") as sp:
            yield sp
        return
    qt = start_trace(f"op.{name}")
    if qt is None:
        yield _NULL_SPAN
        return
    status = "ok"
    try:
        with qt.activate():
            yield qt.root
    except BaseException:
        status = "failed"
        raise
    finally:
        qt.finish(status)


# ---------------------------------------------------------------------------
# cross-process propagation (the TRACE wire bit / traced fetch verb)
# ---------------------------------------------------------------------------


def wire_context() -> Optional[bytes]:
    """The active sampled context packed for the wire (17 bytes:
    trace_id, the CURRENT span id as the remote parent, flags), or None
    when tracing is off / no sampled context is active — the caller
    only sets its TRACE flag bit when this returns bytes, so legacy
    peers never see the blob."""
    if not _enabled:
        return None
    a = _current.get()
    if a is None or not a[0].sampled:
        return None
    return _TRACE_BLOB.pack(a[0].trace_id, a[1].span_id, 1)


def decode_wire_context(blob: bytes):
    """(trace_id, parent_span_id, sampled) off a wire blob."""
    tid, parent, flags = _TRACE_BLOB.unpack(blob)
    return tid, parent, bool(flags & 1)


@contextlib.contextmanager
def remote_scope(trace_id: int, parent_span_id: int, sampled: bool = True):
    """Install a REMOTE context (decoded off the wire) for one
    request's dynamic extent: spans created inside parent to the
    caller's span and stream to THIS process's span log — the root
    lives in the submitting process; tracemerge joins the logs by
    trace_id."""
    if not _enabled or not sampled:
        yield
        return
    ctx = TraceContext(trace_id=trace_id, remote=True)
    tok = _current.set((ctx, _Anchor(parent_span_id)))
    try:
        yield
    finally:
        _current.reset(tok)

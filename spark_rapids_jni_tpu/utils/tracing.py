"""Tracing/profiling hooks: the NVTX-range analog (SURVEY §5).

The reference wraps CPU-side hot functions in ``CUDF_FUNC_RANGE()``
(NativeParquetJni.cpp:136 et al) and toggles NVTX via a system property.
Here: ``func_range`` emits a ``jax.named_scope`` (visible in XLA HLO and
XProf timelines) plus an optional ``jax.profiler.TraceAnnotation`` for
host-side spans, toggled by ``SRJT_TRACE_ENABLED`` or ``set_enabled``.
``profile_to`` wraps jax.profiler start/stop for Perfetto/XProf dumps —
the nsight-systems replacement.
"""

from __future__ import annotations

import contextlib
import threading

import jax

from . import knobs

__all__ = ["set_enabled", "is_enabled", "func_range", "profile_to"]

_enabled = knobs.get_bool("SRJT_TRACE_ENABLED")
_lock = threading.Lock()


def set_enabled(on: bool) -> None:
    global _enabled
    with _lock:
        _enabled = bool(on)


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def func_range(name: str):
    """Named scope over an op: no-op when tracing is off (same contract
    as NVTX ranges — safe to leave in hot paths)."""
    if not _enabled:
        yield
        return
    with jax.named_scope(name):
        with jax.profiler.TraceAnnotation(name):
            yield


@contextlib.contextmanager
def profile_to(log_dir: str):
    """Capture a device+host profile into ``log_dir`` (XProf/TensorBoard
    format; the nsys-profile analog for a region)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()

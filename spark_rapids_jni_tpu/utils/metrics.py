"""Runtime metrics subsystem: counters, gauges, log2 histograms, and a
structured JSON-lines event log (SURVEY §5 observability; ISSUE 2).

PR 1 closed the recovery loop but left it blind: the retry orchestrator
kept private counters, the memory tier a single module global, and the
sidecar client two instance attributes — nothing shared a namespace,
nothing could be snapshotted together, and nothing recorded *time*.
This module is the one registry every layer reports into, modeled on
the reference plugin's metrics posture (per-op NVTX ranges + the
RapidsShuffleManager's shuffle byte/latency counters) and on Theseus /
Thallus (PAPERS.md), which both treat data-movement visibility as a
first-class subsystem of a distributed columnar engine.

Design contract:

- **Always-on registry, gated instrumentation.** The registry itself
  (``registry()``) is always live and cheap — durable product counters
  (memory split-retries, sidecar worker op counts) write through it
  unconditionally. The *hot-path* instrumentation (per-op wall-clock
  timing in ``op_boundary``, per-exchange shuffle timings, the event
  log) is gated by ``SRJT_METRICS_ENABLED`` / ``enable()``: disabled,
  the module-level ``counter()``/``histogram()``/``timer()`` helpers
  hand back no-op stubs and never touch a clock, so an instrumented
  hot path costs one boolean read (the NVTX-disabled contract,
  utils/tracing.py has the same stance).
- **Fixed log2 bucketing.** ``Histogram`` keeps 64 power-of-two
  buckets in a preallocated list — recording is index arithmetic plus
  one locked increment, never a dict resize or sort on the hot path.
- **Structured event log.** ``SRJT_METRICS_LOG=<path>`` (or
  ``set_log_path()``) appends one JSON object per line:
  ``{"ts": ..., "event": ..., **fields}``. Events are emitted only
  when metrics are enabled AND a path is set; writes are line-atomic
  (single ``write()`` of one line under a lock, O_APPEND semantics)
  so the sidecar worker process and the client can share a file.

Environment:

    SRJT_METRICS_ENABLED  "1"/"true"/"yes" arms instrumentation
    SRJT_METRICS_LOG      JSON-lines event log path (optional)

The cross-layer snapshot — this registry plus the retry orchestrator's
stats plus native sidecar stats — is assembled by
``runtime.stats_report()``; ``render_report()`` here is its pretty
printer.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Dict, Optional

from . import knobs

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "KeyedEwma",
    "adaptive_timeout_s",
    "Registry",
    "registry",
    "counter",
    "gauge",
    "histogram",
    "timer",
    "event",
    "record_op",
    "snapshot",
    "counters_snapshot",
    "fold_worker_counters",
    "reset",
    "enable",
    "disable",
    "is_enabled",
    "enabled",
    "disabled",
    "set_log_path",
    "log_path",
    "close_log",
    "render_report",
    "stage_report",
]

_N_BUCKETS = 64  # log2 buckets cover [1, 2^63); values clamp at the ends


class Counter:
    """Monotonic counter (thread-safe; a GIL-era ``+=`` is not atomic
    across the read/add/store bytecodes, so increments lock)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value  # srjt-race: allow-unguarded(single machine-word stats read; GIL-atomic — a reader sees a valid pre- or post-increment value, never a tear)

    def _reset(self) -> None:
        with self._lock:
            self._value = 0

    def _snapshot(self):
        return self._value  # same GIL-atomic word read as .value (annotated there)


class Gauge:
    """Last-write-wins scalar (remote snapshots, pool sizes, ...)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    def inc(self, n=1) -> None:
        with self._lock:
            self._value += n

    def set_max(self, v) -> None:
        """Monotonic high-water update: compare-and-set under the
        gauge's own lock, so two concurrent observers can never let a
        smaller value overwrite a larger one (the trace.max_depth
        contract — an unlocked read-then-set is exactly the
        check-then-act the race tier polices)."""
        with self._lock:
            if v > self._value:
                self._value = v

    @property
    def value(self):
        return self._value  # srjt-race: allow-unguarded(last-write-wins scalar; a reference read is GIL-atomic and any concurrent set is a valid value)

    def _reset(self) -> None:
        with self._lock:
            self._value = 0

    def _snapshot(self):
        return self._value  # same GIL-atomic reference read as .value (annotated there)


class Histogram:
    """Fixed log2-bucket histogram: bucket k counts values in
    [2^(k-1), 2^k) (bucket 0 holds values < 1, i.e. zero/negative
    after int truncation). Preallocated — recording is allocation-free
    modulo interpreter internals, safe on hot paths."""

    __slots__ = ("_lock", "_buckets", "_count", "_sum", "_min", "_max")

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets = [0] * _N_BUCKETS
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None

    @staticmethod
    def bucket_index(value) -> int:
        iv = int(value)
        if iv <= 0:
            return 0
        b = iv.bit_length()  # 1 -> bucket 1 ([1,2)), 2..3 -> 2, 4..7 -> 3
        return b if b < _N_BUCKETS else _N_BUCKETS - 1

    def record(self, value) -> None:
        idx = self.bucket_index(value)
        with self._lock:
            self._buckets[idx] += 1
            self._count += 1
            self._sum += value
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value

    @property
    def count(self) -> int:
        return self._count  # srjt-race: allow-unguarded(single machine-word warm-up check; GIL-atomic, and quantile() re-reads under _lock)

    def quantile(self, q: float):
        """Approximate quantile read off the log2 buckets (ISSUE 9):
        the rank's bucket is found by cumulative count, then linearly
        interpolated across the bucket's [2^(k-1), 2^k) span and
        tightened by the recorded min/max. None when empty. Good to a
        factor of 2 by construction — exactly the precision an
        adaptive timeout or a hedge trigger needs, at zero extra
        hot-path cost (the recording side is unchanged)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            total = self._count
            if total == 0:
                return None
            lo, hi = self._min, self._max
            rank = q * total
            if rank <= 1:
                return lo
            cum = 0
            for k, n in enumerate(self._buckets):
                if not n:
                    continue
                if cum + n >= rank:
                    if k == 0:
                        return min(max(0.0, lo), hi)
                    lower, upper = float(1 << (k - 1)), float(1 << k)
                    frac = (rank - cum) / n
                    est = lower + frac * (upper - lower)
                    return min(max(est, lo), hi)
                cum += n
            return hi

    def _reset(self) -> None:
        with self._lock:
            self._buckets = [0] * _N_BUCKETS
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None

    def _snapshot(self) -> dict:
        with self._lock:
            buckets = {
                # bucket k spans [2^(k-1), 2^k); label by the inclusive
                # lower edge so readers can reconstruct the range
                ("0" if k == 0 else str(1 << (k - 1))): n
                for k, n in enumerate(self._buckets)
                if n
            }
            return {
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
                "buckets": buckets,
            }


class _NullMetric:
    """Shared no-op stub handed out when metrics are disabled: every
    mutator is a pass, so instrumented call sites stay branch-free."""

    __slots__ = ()

    def inc(self, n: int = 1) -> None:
        pass

    def set(self, v) -> None:
        pass

    def set_max(self, v) -> None:
        pass

    def record(self, value) -> None:
        pass

    @property
    def value(self):
        return 0

    @property
    def count(self):
        return 0

    def quantile(self, q: float):
        return None


_NULL = _NullMetric()


class KeyedEwma:
    """Bounded-memory per-key EWMA + jitter tracker (ISSUE 9): the
    health scorer's streaming state. Each key carries an exponentially
    weighted moving average of its samples plus an EWMA of the absolute
    deviation (the jitter — a worker whose heartbeat round-trips wander
    is as suspect as one whose mean drifts). The map is BOUNDED:
    at ``max_keys`` the least-recently-updated key is evicted, so a
    per-(worker, op) keying can never grow with workload cardinality."""

    __slots__ = ("_lock", "_alpha", "_max_keys", "_entries", "_seq")

    def __init__(self, alpha: float = 0.3, max_keys: int = 512):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if max_keys < 1:
            raise ValueError(f"max_keys must be >= 1, got {max_keys}")
        self._lock = threading.Lock()
        self._alpha = float(alpha)
        self._max_keys = int(max_keys)
        self._entries: Dict[str, list] = {}  # key -> [ewma, jitter, count, seq]
        self._seq = 0

    def update(self, key: str, value: float) -> float:
        """Fold one sample into ``key``'s EWMA; returns the new mean."""
        v = float(value)
        with self._lock:
            self._seq += 1
            e = self._entries.get(key)
            if e is None:
                if len(self._entries) >= self._max_keys:
                    oldest = min(self._entries, key=lambda k: self._entries[k][3])
                    del self._entries[oldest]
                self._entries[key] = [v, 0.0, 1, self._seq]
                return v
            dev = abs(v - e[0])
            e[0] += self._alpha * (v - e[0])
            e[1] += self._alpha * (dev - e[1])
            e[2] += 1
            e[3] = self._seq
            return e[0]

    def get(self, key: str, default=None):
        with self._lock:
            e = self._entries.get(key)
            return default if e is None else e[0]

    def jitter(self, key: str, default=None):
        with self._lock:
            e = self._entries.get(key)
            return default if e is None else e[1]

    def count(self, key: str) -> int:
        with self._lock:
            e = self._entries.get(key)
            return 0 if e is None else e[2]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                k: {"ewma": e[0], "jitter": e[1], "count": e[2]}
                for k, e in self._entries.items()
            }


class Registry:
    """Name -> metric map. get-or-create under one lock; the returned
    metric objects are internally locked, so holders increment without
    re-entering the registry."""

    def __init__(self):
        self._lock = threading.Lock()
        # srjt-race layer 2: the registry map is tracked when
        # SRJT_RACE=1 — every metric lookup/registration is a checked
        # access (a plain dict otherwise; analysis/lockdep is
        # import-light stdlib, safe this early in the import order)
        from ..analysis.lockdep import track as _race_track

        self._metrics: Dict[str, object] = _race_track(
            {}, "metrics.registry"
        )

    def _get(self, name: str, cls):
        # the whole get-or-create runs under the lock (srjt-race
        # SRJT008): the old lock-free first probe was the textbook
        # benign-until-it-isn't double-checked read — the dynamic
        # detector flags it, and hot call sites cache their metric
        # handles anyway (record_op), so the lock costs one uncontended
        # acquire per registry lookup
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls()
                self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {type(m).__name__}, "
                f"not {cls.__name__}"
            )
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def peek(self, name: str):
        """The live metric object for ``name``, or None — WITHOUT
        creating it (stats assembly and the adaptive-timeout reader
        must never mint histograms as a side effect). The map read is
        locked; the returned object carries its own lock."""
        with self._lock:
            return self._metrics.get(name)

    def value(self, name: str, default=0):
        """Scalar read with a default — snapshot assembly for counters
        that may never have been touched."""
        m = self.peek(name)
        if m is None:
            return default
        if isinstance(m, Histogram):
            return m._snapshot()
        return m.value

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> dict:
        """{"counters": {...}, "gauges": {...}, "histograms": {...}} —
        plain JSON-serializable values only."""
        with self._lock:
            items = list(self._metrics.items())
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in sorted(items):
            if isinstance(m, Counter):
                out["counters"][name] = m._snapshot()
            elif isinstance(m, Gauge):
                out["gauges"][name] = m._snapshot()
            else:
                out["histograms"][name] = m._snapshot()
        return out

    def reset(self) -> None:
        with self._lock:
            items = list(self._metrics.values())
        for m in items:
            m._reset()


_REGISTRY = Registry()


def registry() -> Registry:
    """The process-wide registry. ALWAYS live: durable product counters
    (memory split-retries, worker-side op counts) go through here
    directly, independent of the SRJT_METRICS_ENABLED gate — the gate
    governs hot-path instrumentation, not bookkeeping."""
    return _REGISTRY


# ---------------------------------------------------------------------------
# enable gate + gated convenience accessors
# ---------------------------------------------------------------------------

_enabled = knobs.get_bool("SRJT_METRICS_ENABLED")


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def enabled(log_path: Optional[str] = None):
    """Scoped arming for tests/benches; optionally installs a scoped
    event-log path."""
    global _enabled
    prev = _enabled
    prev_path = log_path_holder = None
    if log_path is not None:
        prev_path = _log_path
        set_log_path(log_path)
        log_path_holder = log_path
    _enabled = True
    try:
        yield _REGISTRY
    finally:
        _enabled = prev
        if log_path_holder is not None:
            set_log_path(prev_path)


@contextlib.contextmanager
def disabled():
    """Scoped disarming (the overhead-guard test's tool)."""
    global _enabled
    prev = _enabled
    _enabled = False
    try:
        yield
    finally:
        _enabled = prev


def counter(name: str):
    """Gated accessor: the real counter when armed, a no-op stub when
    not — instrumented hot paths pay one boolean read disabled."""
    return _REGISTRY.counter(name) if _enabled else _NULL


def gauge(name: str):
    return _REGISTRY.gauge(name) if _enabled else _NULL


def histogram(name: str):
    return _REGISTRY.histogram(name) if _enabled else _NULL


# per-op handle cache: op_boundary resolves (calls counter, wall-us
# histogram) once per op name instead of two dict lookups per dispatch
_op_handles: Dict[str, tuple] = {}
_op_handles_lock = threading.Lock()


def record_op(name: str, seconds: float) -> None:
    """One op dispatch: count + wall-clock histogram (microseconds).
    Callers gate on is_enabled() BEFORE reading the clock."""
    h = _op_handles.get(name)
    if h is None:
        with _op_handles_lock:
            h = _op_handles.get(name)
            if h is None:
                h = (
                    _REGISTRY.counter(f"op.{name}.calls"),
                    _REGISTRY.histogram(f"op.{name}.wall_us"),
                )
                _op_handles[name] = h
    h[0].inc()
    h[1].record(seconds * 1e6)


@contextlib.contextmanager
def timer(name: str):
    """Time a region into the op metrics namespace (``op.<name>.calls``
    + ``op.<name>.wall_us``). No clock read when disabled."""
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_op(name, time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# structured JSON-lines event log
# ---------------------------------------------------------------------------

_log_lock = threading.Lock()
_log_path: Optional[str] = knobs.get_str("SRJT_METRICS_LOG") or None
_log_file = None


def log_path() -> Optional[str]:
    return _log_path


def set_log_path(path: Optional[str]) -> None:
    """Install (or clear, with None) the event-log destination. The
    file opens lazily on first event and appends — multiple processes
    (sidecar worker + client) may share one path."""
    global _log_path, _log_file
    with _log_lock:
        if _log_file is not None:
            try:
                _log_file.close()
            finally:
                _log_file = None
        _log_path = path


def close_log() -> None:
    set_log_path(_log_path)  # closes the handle, keeps the path


def event(name: str, **fields) -> None:
    """Append one structured event line. Cheap no-op unless metrics are
    enabled AND a log path is configured. One write() per line keeps
    lines atomic under O_APPEND across processes."""
    global _log_file
    if not _enabled or _log_path is None:
        return
    rec = {"ts": round(time.time(), 6), "event": name}
    rec.update(fields)
    line = json.dumps(rec, default=str) + "\n"
    with _log_lock:
        # re-check under the lock: a concurrent set_log_path(None)
        # between the fast-path guard above and here must not turn
        # into open(None) — a bad/ripped-out path degrades the log,
        # never the op being instrumented
        if _log_path is None:
            return
        if _log_file is None:
            try:
                _log_file = open(_log_path, "a")
            except OSError:
                return
        try:
            _log_file.write(line)
            _log_file.flush()
        except (OSError, ValueError):
            pass


# ---------------------------------------------------------------------------
# snapshots + reporting
# ---------------------------------------------------------------------------


def snapshot() -> dict:
    return _REGISTRY.snapshot()


def counters_snapshot() -> Dict[str, int]:
    """COUNTERS only, as one flat name -> value dict — the cheap
    before/after pair the flight recorder diffs into a per-query
    metrics delta (ISSUE 12). Skips gauges and histograms: a delta of
    last-write-wins or bucketed state is not meaningful, and walking
    just the counters keeps the per-root-trace cost to one locked list
    copy plus word reads."""
    with _REGISTRY._lock:
        items = list(_REGISTRY._metrics.items())
    return {name: m.value for name, m in items if isinstance(m, Counter)}


def adaptive_timeout_s(hist_name: str, static_s: float):
    """Derive an ADAPTIVE socket deadline from an observed latency
    histogram recorded in MICROSECONDS (ISSUE 9): returns
    ``(budget_s, clamped)`` where ``budget_s`` is
    ``clamp(q99 × SRJT_ADAPTIVE_TIMEOUT_MULT,
    [SRJT_ADAPTIVE_TIMEOUT_FLOOR_S, static_s])`` once the histogram
    holds at least ``SRJT_ADAPTIVE_TIMEOUT_MIN_SAMPLES`` samples, and
    the static knob unchanged before that (cold-start ops — first
    compile, first dial — keep the conservative deadline). ``clamped``
    is True only when observation actually SHRANK the deadline, so
    callers can count clamps without re-deriving. Reads the registry
    directly (never creates the histogram): adaptive deadlines are
    product behavior and must work with SRJT_METRICS_ENABLED off."""
    if not knobs.get_bool("SRJT_ADAPTIVE_TIMEOUT_ENABLED"):
        return static_s, False
    h = _REGISTRY.peek(hist_name)
    if not isinstance(h, Histogram):
        return static_s, False
    if h.count < knobs.get_int("SRJT_ADAPTIVE_TIMEOUT_MIN_SAMPLES"):
        return static_s, False
    q99_us = h.quantile(0.99)
    if q99_us is None:
        return static_s, False
    budget = q99_us / 1e6 * knobs.get_float("SRJT_ADAPTIVE_TIMEOUT_MULT")
    budget = max(budget, knobs.get_float("SRJT_ADAPTIVE_TIMEOUT_FLOOR_S"))
    budget = min(budget, float(static_s))
    return budget, budget < float(static_s)


def fold_worker_counters(counters: Optional[dict], prefix: str = "sidecar.worker.") -> None:
    """Fold a sidecar WORKER's counter snapshot (the STATS verb's
    ``snapshot.counters`` map) into this process's registry under
    ``prefix`` — as GAUGES, because a remote snapshot is
    last-write-wins and folding increments would double-count on every
    poll. Shared by SupervisedClient.worker_stats (Python client),
    runtime.device_stats (native client), and the worker pool
    (sidecar_pool.py, which keys PER WORKER: ``sidecar.worker.w<id>.*``)
    so the fold policy cannot diverge between the paths."""
    for name, value in (counters or {}).items():
        _REGISTRY.gauge(
            name if name.startswith(prefix) else f"{prefix}{name}"
        ).set(value)


def reset() -> None:
    """Zero every metric (registered names survive; tests and bench
    stage boundaries use this)."""
    _REGISTRY.reset()


def stage_report(stage: str) -> dict:
    """Per-stage snapshot shape for bench emission: op timings, shuffle
    movement, and retry counts — the three sections VERDICT items 5/7/8
    audit — with zero defaults so the schema is stable even when a
    stage never touched a section."""
    from . import memory, retry

    snap = _REGISTRY.snapshot()
    ops = {}
    for name, h in snap["histograms"].items():
        if name.startswith("op.") and name.endswith(".wall_us") and h["count"]:
            op = name[len("op."):-len(".wall_us")]
            ops[op] = {
                "calls": h["count"],
                "wall_us_sum": round(h["sum"], 1),
                "wall_us_max": round(h["max"], 1) if h["max"] is not None else None,
            }
    rs = retry.stats()
    return {
        "stage": stage,
        "ops": ops,
        "shuffle": {
            "exchanges": _REGISTRY.value("shuffle.exchanges"),
            "bytes_exchanged": _REGISTRY.value("shuffle.bytes_exchanged"),
            "capacity_retries": _REGISTRY.value("shuffle.capacity_retries"),
        },
        "retry": rs,
        "memory": {"split_retries": memory.split_retry_count()},
        # ISSUE 4 memory-governor counters: admissions vs queue/reject
        # pressure, and the spill volume the squeeze artifacts audit
        "memgov": {
            "admitted": _REGISTRY.value("memgov.admitted"),
            "queued": _REGISTRY.value("memgov.queued"),
            "rejected": _REGISTRY.value("memgov.rejected"),
            "spilled_bytes": _REGISTRY.value("memgov.spilled_bytes"),
            "respilled": _REGISTRY.value("memgov.respilled"),
        },
        # ISSUE 3 robustness counters: budget give-ups vs truncated
        # backoffs, and the sidecar breaker's registry-direct gauges
        "deadline": {
            "deadline_exceeded": rs["deadline_exceeded"],
            "backoff_truncated": rs["backoff_truncated"],
        },
        "breaker": {
            "state": _REGISTRY.value("sidecar.breaker.state"),
            "opened": _REGISTRY.value("sidecar.breaker.opened_total"),
            "fast_fails": _REGISTRY.value("sidecar.breaker.fast_fails_total"),
        },
        # ISSUE 5 crash-tolerance counters: pool failovers/respawns and
        # the integrity layer's caught-corruption tally — the crash-storm
        # artifacts assert on exactly these
        "pool": {
            "live": _REGISTRY.value("sidecar.pool.live"),
            "failovers": _REGISTRY.value("sidecar.pool.failovers"),
            "respawns": _REGISTRY.value("sidecar.pool.respawns"),
            "rehydrations": _REGISTRY.value("sidecar.pool.rehydrations"),
        },
        "integrity": {
            "crc_mismatch": _REGISTRY.value("sidecar.integrity.crc_mismatch"),
            "frames_checked": _REGISTRY.value("sidecar.integrity.frames_checked"),
        },
        # ISSUE 9 tail-tolerance counters: gray-failure quarantine
        # verdicts and hedged-dispatch accounting — the gray-storm
        # artifacts assert quarantines/hedges_won > 0 from exactly these
        "health": {
            "quarantines": _REGISTRY.value("sidecar.pool.quarantines"),
            "reinstatements": _REGISTRY.value("sidecar.pool.reinstatements"),
            "probes": _REGISTRY.value("sidecar.pool.quarantine_probes"),
            "quarantined_now": _REGISTRY.value("sidecar.pool.quarantined"),
        },
        "hedge": {
            "launched": _REGISTRY.value("sidecar.pool.hedges_launched"),
            "won": _REGISTRY.value("sidecar.pool.hedges_won"),
            "cancelled": _REGISTRY.value("sidecar.pool.hedges_cancelled"),
            "suppressed": _REGISTRY.value("sidecar.pool.hedges_suppressed"),
            "adaptive_timeout_clamps": (
                _REGISTRY.value("sidecar.adaptive_timeout_clamps")
                + _REGISTRY.value("shuffle.tcp.adaptive_timeout_clamps")
            ),
        },
        # ISSUE 12 tracing counters: per-stage span volume — bench
        # drivers pair this with the dedicated {"trace": ...} summary
        # line (trace_sink.stage_summary) so a BENCH latency regression
        # can be correlated with the span that grew
        "trace": {
            "spans": _REGISTRY.value("trace.spans"),
            "traces": _REGISTRY.value("trace.traces"),
            "flushed": _REGISTRY.value("trace.flushed"),
        },
        # ISSUE 8 serving counters: admission outcomes under load — the
        # chaos-under-load artifacts assert sheds surfaced as Overloaded
        # (serve.shed_total) and never as silent buffering or timeouts
        "serve": {
            "submitted": _REGISTRY.value("serve.submitted"),
            "completed": _REGISTRY.value("serve.completed"),
            "shed_total": _REGISTRY.value("serve.shed_total"),
            "expired_in_queue": _REGISTRY.value("serve.expired_in_queue"),
        },
        # ISSUE 17 caching counters: plan-cache hit economics, stage
        # (subresult) reuse, and in-flight sharing — the cache-tier
        # artifacts gate warm hit rate and share>0 from exactly these
        "cache": {
            "hits": _REGISTRY.value("cache.hits"),
            "misses": _REGISTRY.value("cache.misses"),
            "rebinds": _REGISTRY.value("cache.rebinds"),
            "share": _REGISTRY.value("cache.share"),
            "sub_hits": _REGISTRY.value("cache.sub_hits"),
            "sub_misses": _REGISTRY.value("cache.sub_misses"),
            "evictions": (_REGISTRY.value("cache.evictions")
                          + _REGISTRY.value("cache.sub_evictions")),
            "evict_injected": _REGISTRY.value("cache.evict_injected"),
        },
        # ISSUE 20 durability counters: journal append/replay volume,
        # manifest re-attach, and orphan reclamation — the restart-tier
        # artifacts assert replays/reattached/resumes > 0 from exactly
        # these
        "durability": {
            "journal_appends": _REGISTRY.value("journal.appends"),
            "journal_append_failures": _REGISTRY.value(
                "journal.append_failures"),
            "journal_replays": _REGISTRY.value("journal.replays"),
            "journal_replayed_records": _REGISTRY.value(
                "journal.replayed_records"),
            "journal_truncated_records": _REGISTRY.value(
                "journal.truncated_records"),
            "idempotent_hits": _REGISTRY.value("journal.idempotent_hits"),
            "recovered_resubmits": _REGISTRY.value(
                "journal.recovered_resubmits"),
            "manifests_written": _REGISTRY.value("memgov.manifests_written"),
            "reattached": _REGISTRY.value("memgov.reattached"),
            "orphans_reclaimed": _REGISTRY.value("memgov.orphans_reclaimed"),
            "partition_resumes": _REGISTRY.value("ooc.partition_resumes"),
        },
    }


def render_report(report: dict) -> str:
    """Human renderer for runtime.stats_report(): one aligned line per
    scalar, histograms as count/sum/max."""
    lines = []

    def emit(prefix: str, obj):
        if isinstance(obj, dict):
            if set(obj) >= {"count", "sum", "buckets"}:  # histogram leaf
                mx = obj.get("max")
                lines.append(
                    f"{prefix:<52} n={obj['count']} sum={obj['sum']:.1f}"
                    + (f" max={mx:.1f}" if isinstance(mx, (int, float)) else "")
                )
                return
            for k in sorted(obj):
                emit(f"{prefix}.{k}" if prefix else str(k), obj[k])
        else:
            lines.append(f"{prefix:<52} {obj}")

    emit("", report)
    return "\n".join(lines)

"""Retry orchestrator: bounded backoff, retry-with-split, and the
capacity re-try loop (SURVEY §5 recovery; the RmmRapidsRetryIterator
analog for the TPU tier).

The error taxonomy (utils/errors.py) splits device failures into
``FatalDeviceError`` (executor must be replaced — NEVER retried here)
and ``RetryableError`` (transient — Spark task-retry semantics re-run
the batch). ``DataCorruption`` (ISSUE 5, utils/integrity.py) is a
RetryableError subclass with re-FETCH semantics: a CRC-rejected wire
frame, spill file, or shuffle exchange re-runs here like any transient
fault — the re-execution reads fresh bytes, which is exactly the
productive recovery (and its retries are visible as their own class:
``retry.retries.DataCorruption``). Splitting never engages for
corruption — halving a batch cannot fix a rotten copy — only for the
RESOURCE_EXHAUSTED class below. The seed classified but never
recovered: a RetryableError propagated straight to the caller and
killed the query. This module closes that loop with three strategies:

1. **Bounded retry + exponential backoff + jitter**
   (``call_with_retry``): re-run the failed operation up to
   ``max_attempts`` times, sleeping ``base * 2^attempt`` ms (capped at
   ``max_delay_ms``) with multiplicative jitter between attempts —
   the reference plugin's retry framework posture, and what UCX
   shuffle does for transient transport failures.
2. **Retry-with-split** (``retry_with_split``): on
   RESOURCE_EXHAUSTED-class failures the orchestrator halves the input
   batch, runs the halves independently (each again under bounded
   retry, splitting recursively up to ``split_depth``), and reassembles
   the results — the RmmRapidsRetryIterator ``withRetry``/
   ``splitAndRetry`` discipline: a batch too big for device memory is
   not a fatal condition, it is two smaller batches.
3. **Capacity re-try** lives where the capacity does:
   ``parallel/shuffle.py`` ``on_overflow="retry"`` doubles the bucket
   capacity (geometric, bounded by the cannot-overflow per-shard
   ceiling) and re-executes the all-to-all; this module only counts it
   (``stats().capacity_retries``).

Configuration: environment (read once at import) or programmatic.

    SRJT_RETRY_ENABLED       "1"/"true" arms op-boundary retry (default off)
    SRJT_RETRY_MAX_ATTEMPTS  total attempts incl. the first (default 4)
    SRJT_RETRY_BASE_DELAY_MS first backoff (default 25)
    SRJT_RETRY_MAX_DELAY_MS  backoff ceiling (default 1000)
    SRJT_RETRY_JITTER        multiplicative jitter fraction in [0,1)
                             (default 0.25: sleep in [0.75x, 1.25x])
    SRJT_RETRY_SPLIT_DEPTH   max halvings in retry_with_split (default 3)
    SRJT_RETRY_SEED          jitter RNG seed (deterministic chaos runs)

Deadline interplay (utils/deadline.py, ISSUE 3): under an active
deadline scope no backoff sleep ever extends past the remaining budget
(a backoff that would cross the deadline raises immediately, returning
the residual budget to the caller) and the loop raises
``DeadlineExceeded`` instead of starting an attempt (or a split) once
the budget is gone or the cancel token tripped —
``retry.deadline_exceeded`` / ``retry.backoff_truncated_total`` count
the two outcomes so stats_report tells "gave up on budget" apart from
"exhausted attempts".

Op-boundary wiring (utils/dispatch.py): when the orchestrator is
enabled, every ``op_boundary`` op retries injected/classified
RetryableErrors transparently; disabled (the default) the seed's
propagate-to-caller contract is unchanged, so capacity-managing callers
and the existing test surface keep their semantics.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

from . import knobs
from .errors import DeadlineExceeded, FatalDeviceError, RetryableError
from .knobs import env_float  # noqa: F401  historical home; re-exported

__all__ = [
    "env_float",
    "RetryPolicy",
    "call_with_retry",
    "retry_with_split",
    "is_resource_exhausted",
    "configure",
    "policy",
    "enable",
    "disable",
    "is_enabled",
    "enabled",
    "stats",
    "reset_stats",
]


class RetryPolicy:
    """Immutable-ish bundle of retry knobs; see module docstring for
    the matching SRJT_RETRY_* environment schema."""

    __slots__ = (
        "max_attempts",
        "base_delay_ms",
        "max_delay_ms",
        "jitter",
        "split_depth",
        "sleep",
        "_rng",
    )

    def __init__(
        self,
        max_attempts: int = 4,
        base_delay_ms: float = 25.0,
        max_delay_ms: float = 1000.0,
        jitter: float = 0.25,
        split_depth: int = 3,
        seed: Optional[int] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        if base_delay_ms < 0 or max_delay_ms < 0:
            raise ValueError("backoff delays must be non-negative")
        if not (0 <= jitter < 1):
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        if split_depth < 0:
            raise ValueError(f"split_depth must be >= 0, got {split_depth}")
        self.max_attempts = int(max_attempts)
        self.base_delay_ms = float(base_delay_ms)
        self.max_delay_ms = float(max_delay_ms)
        self.jitter = float(jitter)
        self.split_depth = int(split_depth)
        self.sleep = sleep
        self._rng = random.Random(seed)

    @classmethod
    def from_env(cls, env=None) -> "RetryPolicy":
        seed = knobs.get_int("SRJT_RETRY_SEED", env=env)
        return cls(
            max_attempts=int(knobs.get_float("SRJT_RETRY_MAX_ATTEMPTS", env=env)),
            base_delay_ms=knobs.get_float("SRJT_RETRY_BASE_DELAY_MS", env=env),
            max_delay_ms=knobs.get_float("SRJT_RETRY_MAX_DELAY_MS", env=env),
            jitter=knobs.get_float("SRJT_RETRY_JITTER", env=env),
            split_depth=int(knobs.get_float("SRJT_RETRY_SPLIT_DEPTH", env=env)),
            seed=seed,
        )

    def backoff_ms(self, attempt: int) -> float:
        """Delay before re-running attempt ``attempt + 1`` (0-based):
        exponential with multiplicative jitter (so a fleet of executors
        retrying the same stall does not re-stampede in lockstep),
        clamped LAST — ``max_delay_ms`` is a hard ceiling, never
        exceeded by jitter."""
        raw = self.base_delay_ms * (2.0**attempt)
        if self.jitter:
            raw *= self._rng.uniform(1.0 - self.jitter, 1.0 + self.jitter)
        return min(raw, self.max_delay_ms)


class _Stats:
    """Cross-thread counters for observability and chaos assertions."""

    __slots__ = ("lock", "attempts", "retries", "splits", "capacity_retries",
                 "fatal", "exhausted", "backoff_ms_total",
                 "deadline_exceeded", "backoff_truncated")

    def __init__(self):
        self.lock = threading.Lock()
        self.reset()

    def reset(self):
        self.attempts = 0
        self.retries = 0
        self.splits = 0
        self.capacity_retries = 0
        self.fatal = 0
        self.exhausted = 0
        self.backoff_ms_total = 0.0
        self.deadline_exceeded = 0
        self.backoff_truncated = 0

    def snapshot(self) -> dict:
        with self.lock:
            return {
                "attempts": self.attempts,
                "retries": self.retries,
                "splits": self.splits,
                "capacity_retries": self.capacity_retries,
                "fatal": self.fatal,
                "exhausted": self.exhausted,
                "backoff_ms_total": self.backoff_ms_total,
                "deadline_exceeded": self.deadline_exceeded,
                "backoff_truncated": self.backoff_truncated,
            }


_stats = _Stats()


def stats() -> dict:
    return _stats.snapshot()


def reset_stats() -> None:
    with _stats.lock:
        _stats.reset()


def record_capacity_retry(n: int = 1) -> None:
    """Called by the shuffle capacity re-try loop (parallel/shuffle.py)."""
    with _stats.lock:
        _stats.capacity_retries += n
    from . import metrics

    metrics.counter("shuffle.capacity_retries").inc(n)


# ---------------------------------------------------------------------------
# module-level policy + arming (env once, programmatic any time)
# ---------------------------------------------------------------------------

try:
    _policy = RetryPolicy.from_env()
except ValueError as _e:  # out-of-range knobs degrade, never crash import
    import warnings

    warnings.warn(f"retry: invalid SRJT_RETRY_* configuration ({_e}); using defaults")
    _policy = RetryPolicy()
_enabled = knobs.get_bool("SRJT_RETRY_ENABLED")
_lock = threading.Lock()

# per-thread nesting guard: only the OUTERMOST armed op_boundary owns
# the retry loop. Without it, layered boundaries (exchange_by_key ->
# all_to_all_exchange) would multiply attempts (max_attempts^depth)
# and stack backoff sleeps before a persistent failure surfaces.
_tls = threading.local()


def in_attempt() -> bool:
    """True while a call_with_retry attempt is executing on this
    thread (utils/dispatch.py consults this to keep nested boundaries
    from opening their own retry loops)."""
    return getattr(_tls, "depth", 0) > 0


def policy() -> RetryPolicy:
    return _policy


def configure(**kwargs) -> RetryPolicy:
    """Replace the module policy (same keywords as RetryPolicy)."""
    global _policy
    with _lock:
        _policy = RetryPolicy(**kwargs)
        return _policy


def enable() -> None:
    """Arm op-boundary retry (utils/dispatch.py consults this)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def enabled(**kwargs):
    """Scoped arming for tests / chaos runs; keyword overrides install a
    temporary policy (e.g. ``with retry.enabled(base_delay_ms=1): ...``)."""
    global _policy, _enabled
    prev_policy, prev_enabled = _policy, _enabled
    if kwargs:
        configure(**kwargs)
    _enabled = True
    try:
        yield _policy
    finally:
        _policy, _enabled = prev_policy, prev_enabled


# ---------------------------------------------------------------------------
# strategies
# ---------------------------------------------------------------------------


def _raise_deadline_exceeded(d, op_name: str, cause):
    """The deadline budget died mid-orchestration: count it — the
    ``retry.deadline_exceeded`` counter is how stats_report tells "gave
    up on budget" from "exhausted attempts" — and raise DeadlineExceeded
    chained to the last transient failure (the root cause the budget ran
    out retrying)."""
    from . import metrics

    with _stats.lock:
        _stats.deadline_exceeded += 1
    metrics.counter("retry.deadline_exceeded").inc()
    metrics.event(
        "retry.deadline_exceeded", op=op_name,
        cls=None if cause is None else type(cause).__name__,
    )
    raise d.exceeded(op_name) from cause


def is_resource_exhausted(exc: BaseException) -> bool:
    """RESOURCE_EXHAUSTED-class: the failure scales with input size, so
    splitting the batch (not just waiting) is the productive retry."""
    from .memory import MemoryBudgetExceeded

    return isinstance(exc, MemoryBudgetExceeded) or "RESOURCE_EXHAUSTED" in str(exc)


def call_with_retry(
    fn: Callable[..., Any],
    *args,
    op_name: str = "op",
    policy: Optional[RetryPolicy] = None,
    **kwargs,
):
    """Run ``fn(*args, **kwargs)`` under bounded retry + backoff.

    RetryableError retries up to ``policy.max_attempts`` total attempts;
    the final failure re-raises the LAST error. FatalDeviceError never
    retries — re-running batches on a dead device strands the executor
    (the reference's CudaFatalTest contract).

    Deadline discipline (utils/deadline.py): under an active deadline
    scope the orchestrator never STARTS an attempt once the budget is
    gone or the cancel token tripped — it raises DeadlineExceeded
    (chained to the last transient failure) instead — and a backoff
    that would cross the deadline raises immediately rather than
    sleeping out budget no attempt can use, so the worst case is
    bounded by the budget, not by max_attempts x max_delay, and the
    residual budget goes back to the caller.
    """
    from . import deadline as deadline_mod
    from . import metrics

    pol = policy if policy is not None else _policy
    last: Optional[RetryableError] = None
    for attempt in range(pol.max_attempts):
        d = deadline_mod.current()
        if d is not None and d.done():
            _raise_deadline_exceeded(d, op_name, last)
        with _stats.lock:
            _stats.attempts += 1
        metrics.counter("retry.attempts").inc()
        _tls.depth = getattr(_tls, "depth", 0) + 1
        try:
            return fn(*args, **kwargs)
        except FatalDeviceError as e:
            with _stats.lock:
                _stats.fatal += 1
            metrics.counter("retry.fatal").inc()
            metrics.event("retry.fatal", op=op_name, cls=type(e).__name__)
            raise
        except DeadlineExceeded:
            # the budget died INSIDE the attempt (an interrupted hang, a
            # sidecar request whose socket deadline was the remaining
            # budget): same "gave up on budget" outcome as the loop-top
            # guard, counted the same way
            with _stats.lock:
                _stats.deadline_exceeded += 1
            metrics.counter("retry.deadline_exceeded").inc()
            metrics.event("retry.deadline_exceeded", op=op_name, attempt=attempt)
            raise
        except RetryableError as e:
            last = e
            if attempt == pol.max_attempts - 1:
                break
            delay_ms = pol.backoff_ms(attempt)
            if d is not None:
                if d.done():
                    _raise_deadline_exceeded(d, op_name, last)
                rem_ms = d.remaining() * 1000.0
                if delay_ms >= rem_ms:
                    # the backoff would cross the deadline, so the
                    # post-sleep outcome is already determined (the
                    # loop-top guard would refuse the next attempt):
                    # count the truncation, RETURN the residual budget
                    # to the caller, and raise now instead of sleeping
                    # out wall-clock nothing can use
                    with _stats.lock:
                        _stats.backoff_truncated += 1
                    metrics.counter("retry.backoff_truncated_total").inc()
                    metrics.event(
                        "retry.backoff_truncated", op=op_name, attempt=attempt,
                        delay_ms=round(delay_ms, 3),
                        remaining_ms=round(rem_ms, 3),
                    )
                    _raise_deadline_exceeded(d, op_name, last)
            with _stats.lock:
                _stats.retries += 1
                _stats.backoff_ms_total += delay_ms
            # per-error-class counters (the chaos assertions read these:
            # one injected fault == one retry of its class)
            cls = type(e).__name__
            metrics.counter("retry.retries").inc()
            metrics.counter(f"retry.retries.{cls}").inc()
            metrics.histogram("retry.backoff_ms").record(delay_ms)
            metrics.event(
                "retry.backoff", op=op_name, attempt=attempt, cls=cls,
                delay_ms=round(delay_ms, 3),
            )
            # srjt-trace (ISSUE 12): the retry history lands ON the
            # enclosing op span (utils/dispatch.py opens it around the
            # whole boundary) — attempts-so-far overwrites each round,
            # so a finished span reads "how many re-runs this op cost"
            from . import tracing

            tracing.annotate(retry_attempts=attempt + 1, retry_error=cls)
            if delay_ms > 0:
                pol.sleep(delay_ms / 1000.0)
        finally:
            _tls.depth -= 1
    with _stats.lock:
        _stats.exhausted += 1
    metrics.counter("retry.exhausted").inc()
    metrics.counter(f"retry.exhausted.{type(last).__name__}").inc()
    metrics.event("retry.exhausted", op=op_name, cls=type(last).__name__)
    raise last


def _default_split(batch):
    from ..ops.copying import slice_table

    n = batch.num_rows
    mid = n // 2
    return slice_table(batch, 0, mid), slice_table(batch, mid, n)


def _default_combine(parts: Sequence[Any]):
    from ..ops.copying import concatenate

    return concatenate(list(parts))


def _batch_rows(batch) -> int:
    n = getattr(batch, "num_rows", None)
    return int(n) if n is not None else len(batch)


def retry_with_split(
    fn: Callable[[Any], Any],
    batch,
    *,
    split: Optional[Callable[[Any], tuple]] = None,
    combine: Optional[Callable[[List[Any]], Any]] = None,
    op_name: str = "op",
    policy: Optional[RetryPolicy] = None,
):
    """Run ``fn(batch)`` under bounded retry; on RESOURCE_EXHAUSTED-class
    exhaustion halve the batch and recurse (up to ``policy.split_depth``
    levels), reassembling with ``combine`` — the RmmRapidsRetryIterator
    splitAndRetry analog.

    Defaults treat ``batch`` as a ``columnar.Table``: ``split`` is a
    row-range halving (ops.copying.slice_table) and ``combine`` is
    row-wise ``concatenate``. Pass both for any other batch shape.

    Non-exhaustion RetryableErrors never split (halving does not fix a
    flaky transport); they surface after bounded retry. FatalDeviceError
    propagates immediately.
    """
    pol = policy if policy is not None else _policy
    split = split if split is not None else _default_split
    combine = combine if combine is not None else _default_combine

    def run(b, depth: int):
        try:
            return call_with_retry(fn, b, op_name=op_name, policy=pol)
        except RetryableError as e:
            # the reassembly loop consults the deadline/cancel token
            # BETWEEN attempts: never start a split whose halves cannot
            # finish inside the budget (call_with_retry guards each
            # attempt, but the split decision itself is a cancel point)
            from . import deadline as deadline_mod

            d = deadline_mod.current()
            if d is not None and d.done():
                _raise_deadline_exceeded(d, op_name, e)
            if (
                not is_resource_exhausted(e)
                or depth >= pol.split_depth
                or _batch_rows(b) < 2
            ):
                raise
            with _stats.lock:
                _stats.splits += 1
            from . import metrics

            cls = type(e).__name__
            metrics.counter("retry.splits").inc()
            metrics.counter(f"retry.splits.{cls}").inc()
            metrics.event(
                "retry.split", op=op_name, depth=depth, cls=cls,
                rows=_batch_rows(b),
            )
            lo, hi = split(b)

            # srjt-trace (ISSUE 12): each half is a CHILD span of the
            # op span (or the parent half's span on deeper recursion),
            # so a split cascade reads as a tree of shrinking batches
            def _half(x):
                from . import tracing

                with tracing.span(
                    "retry.split", depth=depth + 1, rows=_batch_rows(x)
                ):
                    return run(x, depth + 1)

            return combine([_half(lo), _half(hi)])

    return run(batch, 0)

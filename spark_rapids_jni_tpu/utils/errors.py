"""Error classification: fatal vs retryable (SURVEY §5 failure
detection).

The reference's fault-injection tool exists to verify that the upper
framework classifies CUDA errors as fatal-context-poisoning vs
retryable (faultinj/README.md:5-16), with `CudaFatalTest` isolated in
its own JVM fork (pom.xml:523-532). The TPU analog: a wedged chip /
poisoned PJRT client is `FatalDeviceError` (executor must be replaced),
anything transient is `RetryableError` (Spark task retry semantics
re-run the batch).
"""

from __future__ import annotations

import re

__all__ = [
    "DeviceError",
    "FatalDeviceError",
    "RetryableError",
    "DataCorruption",
    "DeadlineExceeded",
    "Overloaded",
    "classify",
]


class DeviceError(RuntimeError):
    """Base for device-side failures crossing the runtime boundary."""


class FatalDeviceError(DeviceError):
    """The device/client is unusable; the executor must be torn down."""


class RetryableError(DeviceError):
    """Transient failure; the same batch may be retried on this device."""


class DataCorruption(RetryableError):
    """A CRC-checked payload failed verification (utils/integrity.py):
    a wire frame, a disk-spill file, or a shuffle exchange whose bytes
    changed between producer and consumer. RETRYABLE by design — the
    device and the data source are healthy; the COPY is bad, so the
    retry/split machinery re-fetches or re-computes instead of
    returning wrong rows (Thallus's checksummed-transport posture:
    corruption must surface as an error, never as an answer)."""


class Overloaded(RetryableError):
    """The serving runtime (serve/) refused to ADMIT work: a tenant's
    bounded queue is full, the overload controller is shedding under
    queue-age/memgov pressure, the submission's deadline was dead on
    arrival, the pool is dark and the query cannot run on the host
    engine, or the scheduler is shutting down. RETRYABLE by design —
    the system is healthy, just saturated, and backing off IS the
    productive recovery — and always raised at admission, never
    mid-flight, so a shed query costs the client nothing but the
    submit call. ``retry_after_s`` is the scheduler's backoff hint
    (never a promise); ``cause`` names the shed reason
    (``queue_full`` / ``pressure`` / ``doa_deadline`` / ``breaker`` /
    ``quarantine`` / ``cluster_degraded`` / ``shutting_down`` /
    ``injected``). Distinct from DeadlineExceeded
    (the QUERY ran out of time) and MemoryBudgetExceeded (one op's
    footprint cannot fit): Overloaded is about aggregate offered load,
    and a shed must never masquerade as a timeout."""

    def __init__(self, message: str = "overloaded",
                 retry_after_s=None, cause: str = "overload"):
        super().__init__(message)
        self.retry_after_s = (
            None if retry_after_s is None else float(retry_after_s)
        )
        self.cause = str(cause)


class DeadlineExceeded(DeviceError):
    """The query's deadline budget is exhausted (or its cancel token
    tripped; utils/deadline.py). Deliberately NOT a RetryableError —
    retrying cannot manufacture time, so the orchestrator must never
    re-run under it — and not Fatal: the device is healthy, the query
    is out of budget. Distinct from the sidecar's per-request
    DEADLINE_EXCEEDED socket timeout, which IS retryable (the next
    attempt may have budget left)."""


# Patterns in backend error text that indicate a dead device/client.
# "DEAD" is word-bounded so it cannot swallow DEADLINE_EXCEEDED (a
# retryable timeout), since fatal patterns are checked first.
_FATAL_MARKERS = (
    r"\bDEAD\b",
    "device is in an invalid state",
    "client has been shut down",
    "deadlock",
    "halted",
    "INTERNAL: Accelerator",
)

_RETRYABLE_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "Socket closed",
    "transient",
    # sidecar transport faults (utils/retry.py supervision): a refused/
    # reset connection means the worker died or is restarting — the
    # task retries (reconnect or host fallback), the executor survives.
    # Deliberately NOT "timed out": that substring appears in wedged-
    # mesh/collective backend errors where the conservative fatal
    # classification (executor replacement) must win; sidecar deadline
    # errors carry their own DEADLINE_EXCEEDED marker.
    "Connection refused",
    "Connection reset",
    "Broken pipe",
    # integrity layer (utils/integrity.py): a stringified DataCorruption
    # crossing a process boundary (sidecar wire taxonomy) must stay
    # retryable — re-fetching is exactly the productive recovery
    "CRC mismatch",
    # serving runtime (serve/): a stringified Overloaded crossing a
    # process boundary stays retryable — the client backs off and
    # resubmits (the retry_after_s field does not survive stringification;
    # the sidecar wire prefix path preserves the class itself)
    "Overloaded",
)


def classify(exc: BaseException) -> DeviceError:
    """Map an arbitrary backend exception onto the fatal/retryable
    taxonomy (conservative: unknown errors are fatal, like the
    reference's CudaFatalTest treats unknown CUDA states)."""
    if isinstance(exc, DeviceError):
        return exc
    text = str(exc)
    # Fatal markers are checked FIRST: a message carrying both (e.g.
    # "INTERNAL: Accelerator ... channel UNAVAILABLE") means the device
    # is gone, and retrying batches on a dead device would strand the
    # executor — fatal must win on mixed-marker messages.
    for m in _FATAL_MARKERS:
        if re.search(m, text):
            return FatalDeviceError(text)
    for m in _RETRYABLE_MARKERS:
        if m in text:
            return RetryableError(text)
    if isinstance(exc, (ValueError, TypeError, KeyError, IndexError)):
        # host-side programming/input errors are not device failures;
        # re-raise unchanged by convention (caller checks type)
        raise exc
    return FatalDeviceError(text)

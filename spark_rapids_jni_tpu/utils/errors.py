"""Error classification: fatal vs retryable (SURVEY §5 failure
detection).

The reference's fault-injection tool exists to verify that the upper
framework classifies CUDA errors as fatal-context-poisoning vs
retryable (faultinj/README.md:5-16), with `CudaFatalTest` isolated in
its own JVM fork (pom.xml:523-532). The TPU analog: a wedged chip /
poisoned PJRT client is `FatalDeviceError` (executor must be replaced),
anything transient is `RetryableError` (Spark task retry semantics
re-run the batch).
"""

from __future__ import annotations

__all__ = ["DeviceError", "FatalDeviceError", "RetryableError", "classify"]


class DeviceError(RuntimeError):
    """Base for device-side failures crossing the runtime boundary."""


class FatalDeviceError(DeviceError):
    """The device/client is unusable; the executor must be torn down."""


class RetryableError(DeviceError):
    """Transient failure; the same batch may be retried on this device."""


# Substrings in backend error text that indicate a dead device/client.
_FATAL_MARKERS = (
    "DEAD",
    "device is in an invalid state",
    "client has been shut down",
    "deadlock",
    "halted",
    "INTERNAL: Accelerator",
)

_RETRYABLE_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "Socket closed",
    "transient",
)


def classify(exc: BaseException) -> DeviceError:
    """Map an arbitrary backend exception onto the fatal/retryable
    taxonomy (conservative: unknown errors are fatal, like the
    reference's CudaFatalTest treats unknown CUDA states)."""
    if isinstance(exc, DeviceError):
        return exc
    text = str(exc)
    for m in _RETRYABLE_MARKERS:
        if m in text:
            return RetryableError(text)
    for m in _FATAL_MARKERS:
        if m in text:
            return FatalDeviceError(text)
    if isinstance(exc, (ValueError, TypeError, KeyError, IndexError)):
        # host-side programming/input errors are not device failures;
        # re-raise unchanged by convention (caller checks type)
        raise exc
    return FatalDeviceError(text)

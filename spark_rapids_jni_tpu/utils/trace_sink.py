"""srjt-trace span emitter + slow-query flight recorder (ISSUE 12).

The sink half of the tracing subsystem (utils/tracing.py owns the
context/span front door): this module writes the per-process JSON-lines
span log and keeps the bounded ring of recently completed query traces.

- **Span log**: ``SRJT_TRACE_LOG=<base>`` makes every process append
  its finished spans to ``<base>.<pid>.jsonl`` — one JSON object per
  line, one file per process (client, each sidecar worker, each
  exchange peer), which is exactly the join input
  ``python -m spark_rapids_jni_tpu.analysis.tracemerge`` turns into
  per-trace trees and Chrome/Perfetto JSON. Writes are one ``write()``
  per line (the utils/metrics event-log discipline).
- **Flight recorder**: every finished ROOT trace lands in a ring of
  the last ``SRJT_TRACE_RING`` traces; queries that were shed, failed,
  cancelled, expired, or slower than ``SRJT_SLOW_QUERY_SEC`` are
  FLUSHED automatically — the full span tree plus a metrics-delta
  snapshot goes to the span log as a ``{"kind": "trace", ...}`` line,
  so the evidence for "why was THIS query slow" survives the process.
  ``runtime.explain_last()`` renders the worst recent query from the
  ring as an annotated span tree.

Stage summary counters (``trace.spans`` / ``trace.traces`` /
``trace.flushed`` / ``trace.max_depth`` gauges + the ``trace.span_us``
histogram) are registry-direct so bench drivers can emit a per-stage
trace summary next to their ``{"metrics": ...}`` lines and
``metrics.reset()`` scopes them per stage.

Disabled posture: nothing here runs unless utils/tracing's gate armed a
span in the first place — the module's own fast-outs are one attribute
read (no path configured == no I/O).
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import List, Optional

from . import knobs

__all__ = [
    "emit_span",
    "note_span",
    "note_trace",
    "note_unsampled",
    "record_trace",
    "recorder",
    "FlightRecorder",
    "set_log_path",
    "log_path",
    "resolved_log_path",
    "close_log",
    "explain_last",
    "render_trace",
    "stage_summary",
    "stats_section",
    "reset_for_tests",
]

_log_lock = threading.Lock()
_log_base: Optional[str] = knobs.get_str("SRJT_TRACE_LOG") or None
_log_file = None
_log_file_path: Optional[str] = None


def log_path() -> Optional[str]:
    """The configured span-log BASE path (the per-process file adds a
    ``.<pid>`` suffix; see ``resolved_log_path``)."""
    return _log_base


def resolved_log_path() -> Optional[str]:
    """The per-process span-log file this process appends to, or None:
    ``<base>.<pid>.jsonl`` — per-process files keep worker and client
    logs separate for the tracemerge join, with no cross-process write
    interleaving to reason about."""
    if _log_base is None:
        return None
    root, ext = os.path.splitext(_log_base)
    return f"{root}.{os.getpid()}{ext or '.jsonl'}"


def set_log_path(base: Optional[str]) -> None:
    """Install (or clear) the span-log base path. The per-process file
    opens lazily on the first span."""
    global _log_base, _log_file, _log_file_path
    with _log_lock:
        if _log_file is not None:
            try:
                _log_file.close()
            finally:
                _log_file = None
                _log_file_path = None
        _log_base = base


def close_log() -> None:
    set_log_path(_log_base)


def _write_line(rec: dict) -> None:
    """One JSON line to the per-process span log; a bad path degrades
    the log, never the op being traced."""
    global _log_file, _log_file_path
    if _log_base is None:
        return
    line = json.dumps(rec, default=str) + "\n"
    with _log_lock:
        path = resolved_log_path()
        if path is None:
            return
        if _log_file is None or _log_file_path != path:
            if _log_file is not None:
                try:
                    _log_file.close()
                except OSError:
                    pass
                _log_file = None
            d = os.path.dirname(path)
            try:
                if d:
                    os.makedirs(d, exist_ok=True)
                _log_file = open(path, "a")
                _log_file_path = path
            except OSError:
                return
        try:
            _log_file.write(line)
            _log_file.flush()
        except (OSError, ValueError):
            pass


def emit_span(rec: dict) -> None:
    """Stream one finished span record to the per-process log."""
    _write_line(rec)


def _registry():
    from . import metrics

    return metrics.registry()


def note_span(dur_us: float, depth: int) -> None:
    """Stage-summary accounting for one finished span (registry-direct;
    metrics.reset() scopes it per bench stage)."""
    reg = _registry()
    reg.counter("trace.spans").inc()
    reg.histogram("trace.span_us").record(dur_us)
    reg.gauge("trace.max_depth").set_max(depth)


def note_trace() -> None:
    _registry().counter("trace.traces").inc()


def note_unsampled() -> None:
    _registry().counter("trace.unsampled").inc()


# ---------------------------------------------------------------------------
# the flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded ring of the last N completed query traces. ``record``
    decides the auto-flush: non-ok status (shed / failed / cancelled /
    expired / error) always flushes; an ok trace flushes when it ran
    longer than ``SRJT_SLOW_QUERY_SEC`` (unset: never). Flushing
    appends the FULL trace record — span tree + metrics delta — to the
    span log, so a storm's evidence is on disk even if the process
    dies before anyone calls explain_last()."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = knobs.get_int("SRJT_TRACE_RING")
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(capacity))
        self._recorded = 0
        self._flushed = 0

    def record(self, rec: dict) -> None:
        slow_s = knobs.get_float("SRJT_SLOW_QUERY_SEC")
        flush = rec.get("status") != "ok" or (
            slow_s is not None and rec.get("duration_s", 0.0) > slow_s
        )
        if flush:
            rec = dict(rec)
            rec["flushed"] = True
        with self._lock:
            self._ring.append(rec)
            self._recorded += 1
            if flush:
                self._flushed += 1
        reg = _registry()
        if flush:
            reg.counter("trace.flushed").inc()
            _write_line(rec)

    def last(self, n: int = 1) -> List[dict]:
        with self._lock:
            items = list(self._ring)
        return items[-n:]

    def worst(self) -> Optional[dict]:
        """The worst recent query: failures outrank successes, then
        duration decides — the trace explain_last() renders."""
        with self._lock:
            items = list(self._ring)
        if not items:
            return None
        return max(
            items,
            key=lambda r: (
                0 if r.get("status") == "ok" else 1,
                r.get("duration_s", 0.0),
            ),
        )

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ring": len(self._ring),
                "capacity": self._ring.maxlen,
                "recorded": self._recorded,
                "flushed": self._flushed,
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def record_trace(rec: dict) -> None:
    recorder().record(rec)


def reset_for_tests() -> None:
    """Fresh recorder + closed log handle (tests only)."""
    global _recorder
    with _recorder_lock:
        _recorder = None
    close_log()


# ---------------------------------------------------------------------------
# rendering (runtime.explain_last)
# ---------------------------------------------------------------------------


def _fmt_span(s: dict) -> str:
    dur = s.get("dur_us", 0.0)
    dur_txt = f"{dur / 1e3:.2f}ms" if dur < 1e6 else f"{dur / 1e6:.3f}s"
    ann = s.get("annotations") or {}
    ann_txt = "".join(f" {k}={v}" for k, v in sorted(ann.items()))
    status = s.get("status", "ok")
    status_txt = "" if status == "ok" else f" [{status}]"
    return f"{s.get('name')} {dur_txt}{status_txt} (pid {s.get('pid')}){ann_txt}"


def render_trace(rec: dict) -> str:
    """An annotated span tree for one recorded trace: the
    ``explain_last`` rendering. Spans are nested by parent id and
    ordered by start time; spans whose parent is missing from the
    record (in-memory cap overflow, cross-process children) are listed
    under an ``(unparented)`` marker rather than dropped."""
    spans = list(rec.get("spans") or [])
    by_id = {s["span"]: s for s in spans}
    children: dict = {}
    roots: List[dict] = []
    orphans: List[dict] = []
    for s in spans:
        p = s.get("parent")
        if p is None:
            roots.append(s)
        elif p in by_id:
            children.setdefault(p, []).append(s)
        else:
            orphans.append(s)
    lines = [
        f"trace {rec.get('trace')} {rec.get('name')} "
        f"status={rec.get('status')} {rec.get('duration_s', 0.0):.3f}s"
        + ("  [flushed]" if rec.get("flushed") else "")
    ]
    delta = rec.get("metrics_delta") or {}
    if delta:
        top = sorted(delta.items(), key=lambda kv: -abs(kv[1]))[:8]
        lines.append(
            "  metrics-delta: "
            + ", ".join(f"{k}+{v}" for k, v in top)
        )
    if rec.get("dropped_spans"):
        lines.append(f"  ({rec['dropped_spans']} spans dropped at the "
                     "in-memory cap; the span log has them all)")

    def walk(s: dict, indent: int) -> None:
        lines.append("  " * indent + "- " + _fmt_span(s))
        for c in sorted(children.get(s["span"], ()),
                        key=lambda x: x.get("ts", 0.0)):
            walk(c, indent + 1)

    for r in sorted(roots, key=lambda x: x.get("ts", 0.0)):
        walk(r, 1)
    if orphans:
        lines.append("  (unparented)")
        for s in sorted(orphans, key=lambda x: x.get("ts", 0.0)):
            walk(s, 2)
    return "\n".join(lines)


def explain_last() -> Optional[str]:
    """Render the WORST recent query (failures first, then duration)
    from the flight-recorder ring, or None when nothing was traced.
    This is the local-process view; the cross-process tree lives in the
    span logs (``analysis.tracemerge`` joins them)."""
    rec = recorder().worst()
    return None if rec is None else render_trace(rec)


# ---------------------------------------------------------------------------
# stage summary / stats sections
# ---------------------------------------------------------------------------


def stage_summary() -> dict:
    """The per-stage trace summary bench drivers emit next to their
    ``{"metrics": ...}`` lines: span count, trace count, max tree
    depth, and the p99 span duration — enough to correlate a latency
    regression with the span that grew."""
    from . import metrics

    reg = _registry()
    h = reg.peek("trace.span_us")
    p99 = h.quantile(0.99) if isinstance(h, metrics.Histogram) else None
    return {
        "spans": reg.value("trace.spans"),
        "traces": reg.value("trace.traces"),
        "flushed": reg.value("trace.flushed"),
        "max_depth": reg.value("trace.max_depth"),
        "p99_span_us": None if p99 is None else round(p99, 1),
    }


def stats_section() -> dict:
    """The ``trace`` section of runtime.stats_report(): registry
    counters plus the flight recorder's ring state (None-safe before
    anything was traced — a stats poll never mints the recorder)."""
    out = dict(stage_summary())
    out["unsampled"] = _registry().value("trace.unsampled")
    out["log"] = resolved_log_path()
    with _recorder_lock:
        rec = _recorder
    out["recorder"] = None if rec is None else rec.snapshot()
    return out

"""Rewrite-pass framework over the logical-plan IR (srjt-plan).

The standard executor expansions QUERIES.md documents (and
tests/test_ledger_rewrites.py proves in isolation), applied MECHANICALLY
by an optimizer instead of by hand per query:

- ``decorrelate_scalar_agg``   correlated scalar subquery -> aggregate +
                               join + filter (q1/q6/q30/q32/q92 family)
- ``expand_grouping_sets``     ROLLUP / GROUPING SETS -> UnionAll of
                               plain group-bys, rolled keys null-filled
                               (q5/q18/q22/q27/q77 family)
- ``setop_to_joins``           INTERSECT/EXCEPT -> semi/anti join on
                               deduplicated keys (q8/q14/q38/q87)
- ``exists_to_semijoin``       EXISTS / NOT EXISTS -> semi / anti join
                               (q10/q16/q35/q69)
- ``having_to_filter``         HAVING -> post-aggregate Filter (q34/q73)
- ``merge_filters``            stacked Filters -> one conjunction
- ``push_filter_through_project`` / ``push_filter_into_join`` /
  ``push_filter_through_union``  predicate pushdown, conjunct-at-a-time
- ``prune_columns``            projection pushdown: scans narrowed to
                               the columns the plan actually reads

Engine contract: ``rewrite()`` runs bottom-up passes to a FIXPOINT
(a pass that fires nothing is the last), preserving node sharing (a CTE
node referenced twice stays one object, so the compiler still evaluates
it once). Every rule is idempotent at the fixpoint by construction —
sugar rules eliminate their node class, merges reduce filter count, and
pushes only fire when a conjunct actually moves — which is what the
applied-twice-equals-applied-once test pins.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Callable, Dict, List, Optional, Tuple

from . import exprs as ex
from .exprs import PlanError, pcol, plit
from .nodes import (
    Aggregate,
    AggSpec,
    CorrelatedAggFilter,
    Exchange,
    Exists,
    Filter,
    Having,
    Join,
    Limit,
    Node,
    Project,
    Scan,
    SetOp,
    Sort,
    UnionAll,
    Window,
    infer_schema,
)

__all__ = ["rewrite", "prune_columns", "RewriteResult", "RULES",
           "Obligation", "fingerprint", "ParamFingerprint",
           "parameterized_fingerprint", "rebind_literals"]


def fingerprint(node: Node) -> str:
    """Stable structural fingerprint of a subtree (over
    ``nodes.structure``) — the obligation records and the fuzzer's
    bisection reports identify subtrees by it."""
    from .nodes import structure

    return hashlib.sha1(repr(structure(node)).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# parameterized fingerprint (srjt-cache, ISSUE 17)
# ---------------------------------------------------------------------------
#
# The plan cache keys on structure with literal VALUES slotted out, so
# "same dashboard query, different date" maps to one cache entry. Each
# slot keeps a type tag (and the literal's explicit dtype when one was
# given): ``plit(1998)`` and ``plit(19.98)`` infer different dtypes, so
# they must never share a key — a hit must be schema-identical to the
# cached structure, not merely tree-shaped like it.


def _lit_tag(value) -> str:
    """Type-class tag of a literal value. Two literals are slot-
    compatible (one may be rebound to the other) iff tags match — the
    tag pins exactly what ``_PLit.dtype`` infers, so a rebind can never
    change the plan's schema."""
    import numpy as np

    if value is None:
        return "null"
    if isinstance(value, (bool, np.bool_)):
        return "bool"
    if isinstance(value, np.int32):
        return "i32"
    if isinstance(value, (int, np.integer)):
        return "int"
    if isinstance(value, (float, np.floating)):
        return "float"
    return f"other:{type(value).__name__}"


@dataclasses.dataclass(frozen=True)
class ParamFingerprint:
    """``key`` hashes the plan structure with literal values replaced by
    typed slot markers; ``bindings`` are the slotted-out
    ``(tag, value, dtype_key)`` triples in deterministic traversal
    order. Two submissions with equal ``key`` are the same query modulo
    literal values — the compiled-plan cache's identity."""

    key: str
    bindings: Tuple[Tuple, ...]

    @property
    def values(self) -> Tuple:
        return tuple(b[1] for b in self.bindings)


def _slot_literals(s, bindings: list):
    """Recursively replace ``("lit", value, d)`` leaves of a
    ``nodes.structure`` rendering with positional typed slots,
    collecting the displaced values. Literal tuples are the only
    3-tuples whose head is "lit" and whose tail is a dtype key (tuple or
    None) — agg/window triples carry a string there, so the shape test
    cannot misfire on them."""
    if isinstance(s, tuple):
        if (len(s) == 3 and s[0] == "lit"
                and (s[2] is None or isinstance(s[2], tuple))):
            tag = _lit_tag(s[1])
            if tag.startswith("other"):
                return s  # untypable literal: keep inline, never slot
            bindings.append((tag, s[1], s[2]))
            return ("lit", ("?", len(bindings) - 1, tag), s[2])
        return tuple(_slot_literals(x, bindings) for x in s)
    return s


def parameterized_fingerprint(node: Node) -> ParamFingerprint:
    """Structural fingerprint with literals slotted out (srjt-cache):
    plans differing only in literal values share a ``key``; plans
    differing in structure, literal type class, or explicit literal
    dtype never do."""
    from .nodes import structure

    bindings: list = []
    slotted = _slot_literals(structure(node), bindings)
    key = hashlib.sha1(repr(slotted).encode()).hexdigest()[:16]
    return ParamFingerprint(key, tuple(bindings))


def _map_node_exprs(node: Node, f) -> Node:
    """Rebuild ``node`` (inputs untouched) with its expressions mapped
    through ``f`` — only Filter/Project/Having/CorrelatedAggFilter
    carry expressions."""
    if isinstance(node, Filter):
        return Filter(node.input, f(node.predicate))
    if isinstance(node, Project):
        return Project(node.input, tuple((n, f(e)) for n, e in node.exprs))
    if isinstance(node, Having):
        return Having(node.input, f(node.predicate))
    if isinstance(node, CorrelatedAggFilter):
        return CorrelatedAggFilter(node.input, node.sub, node.on,
                                   node.agg, f(node.predicate))
    return node


def rebind_literals(plan: Node, mapping: Dict) -> Node:
    """Rebuild ``plan`` with literal values substituted through
    ``mapping`` (``(tag, value, dtype_key) -> new_value``). Literals
    without a mapping entry — e.g. the null fills grouping-set
    expansion synthesizes — are kept. Shared subtrees stay shared (the
    memo is by object identity), so a rebound CTE still lowers to one
    stage. The caller is responsible for mapping only tag-compatible
    values; rewrite rules copy and reorder literals but never fold
    them, which is what makes by-value rebinding sound."""
    from .exprs import map_literals, plit

    def map_expr(e):
        def one(lit):
            d = None if lit.d is None else (int(lit.d.id), lit.d.scale)
            key = (_lit_tag(lit.value), lit.value, d)
            if key in mapping:
                new = mapping[key]
                if new is not lit.value and not _same_value(new, lit.value):
                    return plit(new, lit.d)
            return lit
        return map_literals(e, one)

    memo: Dict[int, Node] = {}

    def visit(n: Node) -> Node:
        got = memo.get(id(n))
        if got is not None:
            return got
        new_inputs = tuple(visit(i) for i in n.inputs())
        out = n if new_inputs == n.inputs() else _with_inputs(n, new_inputs)
        out = _map_node_exprs(out, map_expr)
        memo[id(n)] = out
        return out

    return visit(plan)


def _same_value(a, b) -> bool:
    try:
        return bool(a == b) and type(a) is type(b)
    except Exception:  # srjt-lint: allow-broad-except(exotic literal __eq__ = not rebindable, never an error)
        return False


@dataclasses.dataclass
class Obligation:
    """Translation-validation record for ONE rule firing (srjt-plancheck,
    ISSUE 15): the subtree before, the rule's one-step output (captured
    BEFORE the engine recursed into the fresh children), structure
    fingerprints of both, and the preserved-schema witness inferred from
    the before-subtree. ``plan.verifier.verify_obligations`` discharges
    these structurally; an undischargeable obligation is a hard PLAN006
    violation. Records are collected on EVERY rewrite (and retained by
    ``CompiledPlan``) by design: a plan tree is dozens of nodes and real
    queries fire a handful of rules, so the witness inference and the
    pinned subtrees are noise next to the lowering itself — and a
    production-compiled plan stays verifiable after the fact."""

    rule: str
    before: Node
    after: Node
    before_fp: str
    after_fp: str
    schema: Optional[Dict] = None  # name -> DType witness (before-subtree)


def _make_obligation(rule: str, before: Node, after: Node,
                     catalog) -> Obligation:
    try:
        schema = infer_schema(before, catalog)
    except PlanError:
        # a malformed before-subtree cannot witness a schema; the
        # discharge still runs its structural checks
        schema = None
    return Obligation(rule, before, after, fingerprint(before),
                      fingerprint(after), schema)


@dataclasses.dataclass
class RewriteResult:
    plan: Node
    fired: Dict[str, int]
    obligations: List[Obligation] = dataclasses.field(default_factory=list)


# each rule: (name, fn(node, catalog, memo) -> Optional[Node]) — a
# one-step rewrite of THIS node, or None when it does not apply
Rule = Tuple[str, Callable]


def _schema(node: Node, catalog, memo):
    # a fresh inference memo per query: rules run on freshly-built
    # subtrees whose lifetimes are shorter than a shared id()-keyed
    # memo could safely cache
    return infer_schema(node, catalog)


def _decorrelate_scalar_agg(node, catalog, memo) -> Optional[Node]:
    if not isinstance(node, CorrelatedAggFilter):
        return None
    pk, bk = node.on
    agg = Aggregate(node.sub, keys=(bk,), aggs=(node.agg,))
    joined = Join(node.input, agg, on=((pk, bk),), how="inner")
    return Filter(joined, node.predicate)


def _expand_grouping_sets(node, catalog, memo) -> Optional[Node]:
    if not isinstance(node, Aggregate) or node.grouping_sets is None:
        return None
    in_schema = _schema(node.input, catalog, memo)
    branches: List[Node] = []
    for gs in node.grouping_sets:
        branch = Aggregate(node.input, keys=gs, aggs=node.aggs)
        outs = []
        for k in node.keys:
            if k in gs:
                outs.append((k, pcol(k)))
            else:
                outs.append((k, plit(None, in_schema[k])))
        for a in node.aggs:
            outs.append((a.name, pcol(a.name)))
        branches.append(Project(branch, tuple(outs)))
    if len(branches) == 1:
        return branches[0]
    return UnionAll(tuple(branches))


def _setop_to_joins(node, catalog, memo) -> Optional[Node]:
    if not isinstance(node, SetOp):
        return None
    cols = tuple(_schema(node.left, catalog, memo).keys())
    dl = Aggregate(node.left, keys=cols, aggs=())
    dr = Aggregate(node.right, keys=cols, aggs=())
    how = "semi" if node.kind == "intersect" else "anti"
    return Join(dl, dr, on=tuple((c, c) for c in cols), how=how)


def _exists_to_semijoin(node, catalog, memo) -> Optional[Node]:
    if not isinstance(node, Exists):
        return None
    keys = Project(node.sub, tuple((r, pcol(r)) for _, r in node.on))
    return Join(node.input, keys, on=node.on,
                how="anti" if node.negated else "semi")


def _having_to_filter(node, catalog, memo) -> Optional[Node]:
    if not isinstance(node, Having):
        return None
    return Filter(node.input, node.predicate)


def _merge_filters(node, catalog, memo) -> Optional[Node]:
    if not (isinstance(node, Filter) and isinstance(node.input, Filter)):
        return None
    inner = node.input
    pred = ex.conjoin(ex.conjuncts(inner.predicate) + ex.conjuncts(node.predicate))
    return Filter(inner.input, pred)


def _push_filter_through_project(node, catalog, memo) -> Optional[Node]:
    if not (isinstance(node, Filter) and isinstance(node.input, Project)):
        return None
    proj = node.input
    mapping = {}
    for name, e in proj.exprs:
        src = ex.is_col(e)
        if src is not None:
            mapping[name] = src
    refs = node.predicate.refs()
    if not refs <= set(mapping):
        return None  # predicate reads a computed column — stays above
    pushed = ex.substitute(node.predicate, mapping)
    return Project(Filter(proj.input, pushed), proj.exprs)


def _push_filter_through_union(node, catalog, memo) -> Optional[Node]:
    if not (isinstance(node, Filter) and isinstance(node.input, UnionAll)):
        return None
    u = node.input
    return UnionAll(tuple(Filter(b, node.predicate) for b in u.branches))


def _push_filter_into_join(node, catalog, memo) -> Optional[Node]:
    """Move conjuncts below the join where row-subsetting commutes:
    probe-side conjuncts for inner/semi/anti/left joins, build-side
    conjuncts for inner joins (the build side of a semi/anti defines
    membership — filtering it changes semantics; a full join
    null-extends both sides, so nothing commutes)."""
    if not (isinstance(node, Filter) and isinstance(node.input, Join)):
        return None
    j = node.input
    if j.how == "full":
        return None
    left_schema = set(_schema(j.left, catalog, memo))
    right_schema = set(_schema(j.right, catalog, memo))
    to_left, to_right, stay = [], [], []
    for c in ex.conjuncts(node.predicate):
        refs = c.refs()
        if refs <= left_schema:
            to_left.append(c)
        elif j.how == "inner" and refs <= right_schema:
            to_right.append(c)
        else:
            stay.append(c)
    if not to_left and not to_right:
        return None
    left = Filter(j.left, ex.conjoin(to_left)) if to_left else j.left
    right = Filter(j.right, ex.conjoin(to_right)) if to_right else j.right
    out: Node = Join(left, right, on=j.on, how=j.how, bounded=j.bounded)
    if stay:
        out = Filter(out, ex.conjoin(stay))
    return out


RULES: Tuple[Rule, ...] = (
    ("decorrelate_scalar_agg", _decorrelate_scalar_agg),
    ("expand_grouping_sets", _expand_grouping_sets),
    ("setop_to_joins", _setop_to_joins),
    ("exists_to_semijoin", _exists_to_semijoin),
    ("having_to_filter", _having_to_filter),
    ("merge_filters", _merge_filters),
    ("push_filter_through_project", _push_filter_through_project),
    ("push_filter_through_union", _push_filter_through_union),
    ("push_filter_into_join", _push_filter_into_join),
)

_MAX_PASSES = 64  # defensive bound; real plans converge in a handful


def _one_pass(node: Node, catalog, fired: Dict[str, int],
              rebuilt: Dict[int, Node], keepalive: List[Node],
              rules: Tuple[Rule, ...],
              obligations: Optional[List[Obligation]],
              budget: Optional[List[int]]) -> Node:
    """One bottom-up pass: rewrite children (sharing-preserving via the
    ``rebuilt`` memo), then apply rules at this node until none fires.
    ``keepalive`` pins every memo key's node for the pass so an id()
    can never be recycled into a stale hit. Each fire appends one
    ``Obligation`` (the before-subtree and the rule's ONE-STEP output,
    captured before recursing into the fresh children) and spends one
    unit of ``budget`` when set — the fuzzer's bisection replays the
    deterministic fire sequence with ``max_fires=k``."""
    key = id(node)
    if key in rebuilt:
        return rebuilt[key]
    new_inputs = tuple(_one_pass(i, catalog, fired, rebuilt, keepalive,
                                 rules, obligations, budget)
                       for i in node.inputs())
    cur = node if all(a is b for a, b in zip(new_inputs, node.inputs())) \
        else _with_inputs(node, new_inputs)
    changed = True
    while changed:
        changed = False
        if budget is not None and budget[0] <= 0:
            break
        for name, fn in rules:
            nxt = fn(cur, catalog, None)
            if nxt is not None:
                fired[name] = fired.get(name, 0) + 1
                if budget is not None:
                    budget[0] -= 1
                if obligations is not None:
                    obligations.append(
                        _make_obligation(name, cur, nxt, catalog))
                # a rule's output may contain unrewritten children —
                # recurse over the fresh subtree before retrying rules
                sub_inputs = tuple(
                    _one_pass(i, catalog, fired, rebuilt, keepalive,
                              rules, obligations, budget)
                    for i in nxt.inputs()
                )
                cur = nxt if all(a is b for a, b in zip(sub_inputs, nxt.inputs())) \
                    else _with_inputs(nxt, sub_inputs)
                changed = True
                break
    keepalive.append(node)
    rebuilt[key] = cur
    return cur


def _with_inputs(node: Node, inputs: Tuple[Node, ...]) -> Node:
    if isinstance(node, Filter):
        return Filter(inputs[0], node.predicate)
    if isinstance(node, Project):
        return Project(inputs[0], node.exprs)
    if isinstance(node, Join):
        return Join(inputs[0], inputs[1], on=node.on, how=node.how,
                    bounded=node.bounded)
    if isinstance(node, Aggregate):
        return Aggregate(inputs[0], keys=node.keys, aggs=node.aggs,
                         grouping_sets=node.grouping_sets)
    if isinstance(node, Window):
        return Window(inputs[0], node.partition_by, node.order_by, node.aggs)
    if isinstance(node, Exchange):
        return Exchange(inputs[0], node.keys, node.world)
    if isinstance(node, Sort):
        return Sort(inputs[0], node.keys)
    if isinstance(node, Limit):
        return Limit(inputs[0], node.n)
    if isinstance(node, UnionAll):
        return UnionAll(inputs)
    if isinstance(node, SetOp):
        return SetOp(inputs[0], inputs[1], node.kind)
    if isinstance(node, Exists):
        return Exists(inputs[0], inputs[1], node.on, node.negated)
    if isinstance(node, Having):
        return Having(inputs[0], node.predicate)
    if isinstance(node, CorrelatedAggFilter):
        return CorrelatedAggFilter(inputs[0], inputs[1], node.on, node.agg,
                                   node.predicate)
    if isinstance(node, Scan):
        return node
    raise PlanError(f"unknown plan node {type(node).__name__}")


def rewrite(plan: Node, catalog: Dict[str, Dict], *,
            rules: Optional[Tuple[Rule, ...]] = None,
            max_fires: Optional[int] = None,
            prune: bool = True) -> RewriteResult:
    """Run the rule set bottom-up to a fixpoint, then prune columns.
    ``catalog`` maps table name -> {column: DType} (rules that split
    predicates or null-fill rolled keys need schemas). Every rule
    firing emits a translation-validation ``Obligation`` (discharged by
    ``plan.verifier``); ``rules``/``max_fires``/``prune`` exist for the
    fuzzer's bisection (replay the first k fires of the deterministic
    chain) and for seeded broken-rewrite fixtures."""
    rules = RULES if rules is None else tuple(rules)
    infer_schema(plan, catalog)  # validate before touching anything
    fired: Dict[str, int] = {}
    obligations: List[Obligation] = []
    budget = None if max_fires is None else [max_fires]
    from .nodes import structure

    cur = plan
    for _ in range(_MAX_PASSES):
        before = structure(cur)
        cur = _one_pass(cur, catalog, fired, {}, [], rules, obligations,
                        budget)
        if structure(cur) == before:
            break
    else:
        raise PlanError("rewrite did not converge (rule oscillation?)")
    if prune:
        pre_prune = cur
        cur = prune_columns(cur, catalog)
        obligations.append(
            _make_obligation("prune_columns", pre_prune, cur, catalog))
    infer_schema(cur, catalog)  # the rewritten plan must still validate
    return RewriteResult(cur, fired, obligations)


# ---------------------------------------------------------------------------
# projection pushdown (column pruning)
# ---------------------------------------------------------------------------


def prune_columns(plan: Node, catalog: Dict[str, Dict]) -> Node:
    """Narrow every Scan to the columns the plan actually consumes and
    drop unused Project outputs / Aggregate aggregates. Runs after the
    rule fixpoint (sugar nodes must be gone). Shared nodes accumulate
    requirements across ALL their consumers and stay shared."""
    schema_memo: dict = {}
    required: Dict[int, set] = {}

    def need(node: Node, cols: set) -> None:
        required.setdefault(id(node), set()).update(cols)

    order: List[Node] = []  # reverse-topological collection
    seen: Dict[int, Node] = {}

    def topo(node: Node) -> None:
        if id(node) in seen:
            return
        seen[id(node)] = node
        for i in node.inputs():
            topo(i)
        order.append(node)

    topo(plan)
    need(plan, set(infer_schema(plan, catalog, schema_memo)))

    # propagate requirements top-down (reverse of the topo order)
    for node in reversed(order):
        req = required.get(id(node), set())
        if isinstance(node, Filter):
            need(node.input, req | node.predicate.refs())
        elif isinstance(node, Project):
            kept = [(n, e) for n, e in node.exprs if n in req]
            refs: set = set()
            for _, e in kept:
                refs |= e.refs()
            need(node.input, refs)
        elif isinstance(node, Join):
            ls = infer_schema(node.left, catalog, schema_memo)
            rs = infer_schema(node.right, catalog, schema_memo)
            need(node.left, (req & set(ls)) | {l for l, _ in node.on})
            need(node.right, (req & set(rs)) | {r for _, r in node.on})
        elif isinstance(node, Aggregate):
            srcs = {a.source for a in node.aggs if a.source is not None}
            cols = set(node.keys) | srcs
            if not cols:
                # pure COUNT(*): keep one column so the scan still
                # carries the row count
                cols = {next(iter(infer_schema(node.input, catalog,
                                               schema_memo)))}
            need(node.input, cols)
        elif isinstance(node, Window):
            ins = infer_schema(node.input, catalog, schema_memo)
            req_in = (req & set(ins)) | set(node.partition_by)
            req_in |= {c for c, _ in node.order_by}
            req_in |= {s for s, _, _ in node.aggs}
            need(node.input, req_in)
        elif isinstance(node, Exchange):
            # partition keys must survive pruning — the repartition
            # hashes them even when no consumer reads them back
            need(node.input, req | set(node.keys))
        elif isinstance(node, Sort):
            need(node.input, req | {c for c, _ in node.keys})
        elif isinstance(node, Limit):
            need(node.input, req)
        elif isinstance(node, UnionAll):
            for b in node.branches:
                need(b, set(req))
        elif isinstance(node, Scan):
            pass
        else:
            raise PlanError(
                f"prune_columns before desugaring: {type(node).__name__} "
                "must be rewritten away first")

    rebuilt: Dict[int, Node] = {}

    def narrow(child_old: Node, child_new: Node, cols: set) -> Node:
        """Insert a passthrough Project when the rebuilt child still
        carries columns its consumer does not need (a filter-only dim
        column must not ride into a join payload)."""
        s = list(infer_schema(child_old, catalog, schema_memo))
        keep = [c for c in s if c in cols]
        if set(s) == set(keep):
            return child_new
        return Project(child_new, tuple((c, pcol(c)) for c in keep))

    def build(node: Node) -> Node:
        if id(node) in rebuilt:
            return rebuilt[id(node)]
        req = required.get(id(node), set())
        if isinstance(node, Scan):
            base = catalog[node.table]
            cols = tuple(c for c in base if c in req)
            out: Node = Scan(node.table, columns=cols, alias=node.alias)
        elif isinstance(node, Project):
            kept = tuple((n, e) for n, e in node.exprs if n in req)
            if not kept:  # a branch whose output is entirely unused
                kept = node.exprs[:1]
            out = Project(build(node.input), kept)
        elif isinstance(node, Aggregate):
            aggs = tuple(a for a in node.aggs if a.name in req)
            if not aggs and not node.keys:
                aggs = node.aggs[:1]
            out = Aggregate(build(node.input), keys=node.keys, aggs=aggs)
        elif isinstance(node, Join):
            ls = infer_schema(node.left, catalog, schema_memo)
            rs = infer_schema(node.right, catalog, schema_memo)
            lneed = (req & set(ls)) | {l for l, _ in node.on}
            rneed = (req & set(rs)) | {r for _, r in node.on}
            left = narrow(node.left, build(node.left), lneed)
            right = narrow(node.right, build(node.right), rneed)
            out = Join(left, right, on=node.on, how=node.how,
                       bounded=node.bounded)
        else:
            out = _with_inputs(node, tuple(build(i) for i in node.inputs()))
        rebuilt[id(node)] = out
        return out

    return build(plan)

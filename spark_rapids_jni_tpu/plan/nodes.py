"""Logical-plan IR nodes + schema inference (srjt-plan, ISSUE 14).

A small relational algebra over named Tables — ``Scan / Filter /
Project / Join / Aggregate / Window / Sort / Limit / UnionAll`` — plus
the SUGAR nodes the rewrite framework (rewrites.py) eliminates before
lowering: ``SetOp`` (INTERSECT/EXCEPT), ``Exists`` (EXISTS/NOT EXISTS),
``Having``, ``CorrelatedAggFilter`` (the correlated-scalar-subquery
family), and grouping sets on ``Aggregate`` (ROLLUP). These are exactly
the constructs QUERIES.md documents as "standard executor rewrites":
the IR keeps them first-class so a query transliterates from its SQL,
and the optimizer — not the query author — performs the expansion Spark
itself would.

Every node infers its output schema (ordered ``{name: DType}``) under a
catalog of table schemas, validating references as it goes; inference
follows the ENGINE's materialization contract, not textbook SQL:

- aggregate outputs: ``count``/``count_all`` -> INT64, the variance
  family -> FLOAT64, and ``sum``/``mean``/``min``/``max`` over numerics
  -> FLOAT64 (the fused pipeline materializes every non-count aggregate
  into FLOAT64 bit-lanes — ``pipeline._wrap_result`` — and the
  operator-tier lowering normalizes to the same contract so a plan's
  dtype never depends on which tier it landed on);
- window outputs mirror ``ops/window.py`` exactly (rank family INT32,
  count INT64, int cumsum INT64, lag/lead/min/max source-typed);
- join outputs: probe/left schema + the build/right non-key columns.

Plans are TREES by construction but DAGs by sharing: reusing a node
object (a CTE referenced twice, q1's customer_total_return) is the
sharing mechanism — the compiler memoizes execution per node identity.

``structure(node)`` renders a plan as canonical nested tuples; the
rewrite-idempotence and bit-identity tests compare those, since node
``__eq__`` is left as identity (expressions overload ``==`` to build
comparison nodes, so structural ``__eq__`` on dataclasses would lie).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

from ..columnar import dtype as dt
from ..columnar.dtype import DType, TypeId
from .exprs import PExpr, PlanError

__all__ = [
    "Node", "Scan", "Filter", "Project", "Join", "AggSpec", "Aggregate",
    "Window", "Sort", "Limit", "UnionAll", "SetOp", "Exists", "Having",
    "CorrelatedAggFilter", "Exchange", "rollup", "infer_schema",
    "structure", "PlanError",
]

Schema = Dict[str, DType]

_JOIN_HOWS = ("inner", "left", "full", "semi", "anti")
_AGG_HOWS = ("sum", "count", "count_all", "min", "max", "mean",
             "var", "std", "var_pop", "stddev_pop", "nunique")
_WINDOW_HOWS = ("row_number", "rank", "dense_rank", "lag", "lead", "sum",
                "mean", "min", "max", "count", "cumsum", "var", "std",
                "var_pop", "stddev_pop")
_SETOP_KINDS = ("intersect", "except")


class Node:
    """Base logical-plan node."""

    def inputs(self) -> Tuple["Node", ...]:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True, eq=False)
class Scan(Node):
    """Read a named table from the bound catalog. ``columns`` is the
    pruned projection (None = all); ``alias`` disambiguates two scans of
    one table (self-joins) in the fused tier's build map."""

    table: str
    columns: Optional[Tuple[str, ...]] = None
    alias: Optional[str] = None

    def inputs(self):
        return ()

    @property
    def key(self) -> str:
        return self.alias or self.table


@dataclasses.dataclass(frozen=True, eq=False)
class Filter(Node):
    input: Node
    predicate: PExpr

    def inputs(self):
        return (self.input,)


@dataclasses.dataclass(frozen=True, eq=False)
class Project(Node):
    """Output schema IS ``exprs`` (name, expression), in order — a
    rename/narrow/compute node, like Spark's Project."""

    input: Node
    exprs: Tuple[Tuple[str, PExpr], ...]

    def inputs(self):
        return (self.input,)


@dataclasses.dataclass(frozen=True, eq=False)
class Join(Node):
    """Equi-join on ``on = ((left_col, right_col), ...)`` pairs.
    ``bounded=True`` hints the fused tier to lower a single-int-key
    inner/semi/anti join through the dense bounded-domain map (domain
    scanned from the build table at bind time); ``False`` (the
    default) lowers sort-merge; ``None`` means "author abstains" and
    lets the cost-based optimizer resolve the strategy from the build
    key's sketch (``cbo_join_strategy`` — falsy, so an unresolved
    ``None`` still lowers sort-merge). The hint never changes
    semantics, only the kernel."""

    left: Node
    right: Node
    on: Tuple[Tuple[str, str], ...]
    how: str = "inner"
    bounded: Optional[bool] = False

    def __post_init__(self):
        if self.how not in _JOIN_HOWS:
            raise PlanError(f"unknown join how {self.how!r}")
        if not self.on:
            raise PlanError("join needs at least one key pair")

    def inputs(self):
        return (self.left, self.right)


@dataclasses.dataclass(frozen=True, eq=False)
class AggSpec:
    """One aggregate: ``source`` column (None only for count_all),
    ``how``, output ``name``."""

    source: Optional[str]
    how: str
    name: str

    def __post_init__(self):
        if self.how not in _AGG_HOWS:
            raise PlanError(f"unknown aggregate {self.how!r}")
        if self.source is None and self.how != "count_all":
            raise PlanError(f"aggregate {self.how!r} needs a source column")


@dataclasses.dataclass(frozen=True, eq=False)
class Aggregate(Node):
    """GROUP BY ``keys`` computing ``aggs``; empty keys = one global
    row; empty aggs = DISTINCT over the keys. ``grouping_sets`` (e.g.
    from ``rollup()``) is sugar the optimizer expands into a UnionAll
    of plain group-bys with null-filled rolled columns."""

    input: Node
    keys: Tuple[str, ...] = ()
    aggs: Tuple[AggSpec, ...] = ()
    grouping_sets: Optional[Tuple[Tuple[str, ...], ...]] = None

    def __post_init__(self):
        if not self.keys and not self.aggs:
            raise PlanError("aggregate needs keys or aggregates")
        if self.grouping_sets is not None:
            if not self.aggs:
                raise PlanError("grouping sets need at least one aggregate")
            for gs in self.grouping_sets:
                extra = set(gs) - set(self.keys)
                if extra:
                    raise PlanError(f"grouping set {gs} not a subset of keys: {extra}")
        names = list(self.keys) + [a.name for a in self.aggs]
        if len(set(names)) != len(names):
            raise PlanError(f"duplicate output names in aggregate: {names}")

    def inputs(self):
        return (self.input,)


def rollup(*keys: str) -> Tuple[Tuple[str, ...], ...]:
    """ROLLUP(k1, .., kn) -> the n+1 grouping sets (k1..kn), (k1..kn-1),
    ..., () — pass as ``Aggregate(grouping_sets=rollup(...))``."""
    return tuple(tuple(keys[:i]) for i in range(len(keys), -1, -1))


@dataclasses.dataclass(frozen=True, eq=False)
class Exchange(Node):
    """Hash-repartition the input across ``world`` ranks on ``keys``
    (ISSUE 16): after this stage, all rows of one key value live on
    hash(key) % world, whatever rank produced them — the distribution
    guarantee a downstream keyed Aggregate/Join needs to compute its
    partition of the answer locally. Schema- and (globally)
    row-preserving: an Exchange moves rows, it never creates, drops,
    or rewrites one. On a single rank (``world == 1`` or no exchange
    binding at run time) it lowers to the identity, so a distributed
    plan compiles and runs unchanged on one host."""

    input: Node
    keys: Tuple[str, ...]
    world: int

    def __post_init__(self):
        if not self.keys:
            raise PlanError("exchange needs at least one key column")
        if self.world < 1:
            raise PlanError(f"exchange world must be >= 1, got {self.world}")

    def inputs(self):
        return (self.input,)


@dataclasses.dataclass(frozen=True, eq=False)
class Window(Node):
    """Append window columns (``ops/window.window_aggregate``), original
    row order preserved. ``aggs``: ((source, how, out_name), ...)."""

    input: Node
    partition_by: Tuple[str, ...]
    order_by: Tuple[Tuple[str, bool], ...]
    aggs: Tuple[Tuple[str, str, str], ...]

    def __post_init__(self):
        for _, how, _ in self.aggs:
            if how not in _WINDOW_HOWS:
                raise PlanError(f"unknown window function {how!r}")

    def inputs(self):
        return (self.input,)


@dataclasses.dataclass(frozen=True, eq=False)
class Sort(Node):
    """Total-order sort by ``keys = ((column, ascending), ...)``."""

    input: Node
    keys: Tuple[Tuple[str, bool], ...]

    def inputs(self):
        return (self.input,)


@dataclasses.dataclass(frozen=True, eq=False)
class Limit(Node):
    input: Node
    n: int

    def inputs(self):
        return (self.input,)


@dataclasses.dataclass(frozen=True, eq=False)
class UnionAll(Node):
    branches: Tuple[Node, ...]

    def __post_init__(self):
        if len(self.branches) < 2:
            raise PlanError("UnionAll needs at least two branches")

    def inputs(self):
        return self.branches


@dataclasses.dataclass(frozen=True, eq=False)
class SetOp(Node):
    """INTERSECT / EXCEPT (set semantics — deduplicated), rewritten to
    semi/anti joins over deduped keys (the q8/q14/q38/q87 expansion)."""

    left: Node
    right: Node
    kind: str

    def __post_init__(self):
        if self.kind not in _SETOP_KINDS:
            raise PlanError(f"unknown set op {self.kind!r}")

    def inputs(self):
        return (self.left, self.right)


@dataclasses.dataclass(frozen=True, eq=False)
class Exists(Node):
    """EXISTS / NOT EXISTS correlated on equi-pairs — rewritten to a
    semi/anti join (Spark's own EXISTS plan; q10/q16/q35/q69 class)."""

    input: Node
    sub: Node
    on: Tuple[Tuple[str, str], ...]
    negated: bool = False

    def inputs(self):
        return (self.input, self.sub)


@dataclasses.dataclass(frozen=True, eq=False)
class Having(Node):
    """Post-aggregate predicate — rewritten to a plain Filter over the
    aggregate's output schema (q34/q73 class)."""

    input: Node
    predicate: PExpr

    def inputs(self):
        return (self.input,)


@dataclasses.dataclass(frozen=True, eq=False)
class CorrelatedAggFilter(Node):
    """The correlated scalar-subquery comparison (q1/q6/q30/q32/q92
    family): for each input row, compare against ``agg`` computed over
    the ``sub`` rows whose ``on[1]`` equals the row's ``on[0]``.
    Decorrelated (rewrites.py) into ``Filter(Join(input,
    Aggregate(sub, keys=(on[1],), aggs=(agg,))), predicate)`` — an
    aggregate + join, which is how Spark decorrelates it. The inner
    join implements SQL's NULL-comparison semantics: rows with an empty
    subquery group drop. The aggregate's output column joins the
    schema, so ``predicate`` may reference ``agg.name``."""

    input: Node
    sub: Node
    on: Tuple[str, str]
    agg: AggSpec
    predicate: PExpr

    def inputs(self):
        return (self.input, self.sub)


# ---------------------------------------------------------------------------
# schema inference
# ---------------------------------------------------------------------------


def _numeric_agg_dtype(d: DType, how: str, where: str) -> DType:
    if how in ("count", "count_all", "nunique"):
        return dt.INT64
    if not (d.is_integral or d.is_floating):
        raise PlanError(f"{where}: {how} needs a numeric column, got {d!r}")
    return dt.FLOAT64


def _window_dtype(d: DType, how: str) -> DType:
    if how in ("row_number", "rank", "dense_rank"):
        return dt.INT32
    if how == "count":
        return dt.INT64
    if how in ("mean", "var", "std", "var_pop", "stddev_pop"):
        return dt.FLOAT64
    if how == "cumsum":
        return dt.INT64 if d.is_integral else d
    if how == "sum":
        if d.id == TypeId.FLOAT32:
            return dt.FLOAT32
        return dt.INT64 if d.is_integral else dt.FLOAT64
    return d  # lag/lead/min/max keep the source type


def _check_key_pair(ls: Schema, rs: Schema, pair, where: str) -> None:
    lname, rname = pair
    if lname not in ls:
        raise PlanError(f"{where}: left key {lname!r} not in {sorted(ls)}")
    if rname not in rs:
        raise PlanError(f"{where}: right key {rname!r} not in {sorted(rs)}")
    ld, rd = ls[lname], rs[rname]
    compat = (ld.id == rd.id) or (ld.is_integral and rd.is_integral)
    if not compat:
        raise PlanError(f"{where}: key dtypes incompatible: "
                        f"{lname}:{ld!r} vs {rname}:{rd!r}")


def infer_schema(node: Node, catalog: Dict[str, Schema],
                 _memo: Optional[dict] = None) -> Schema:
    """Infer (and validate) ``node``'s output schema under ``catalog``
    (table name -> {column: DType}). Raises PlanError on unknown
    columns/tables, name collisions, or dtype mismatches."""
    memo = {} if _memo is None else _memo
    key = id(node)
    if key in memo:
        return memo[key]
    s = _infer(node, catalog, memo)
    memo[key] = s
    return s


def _infer(node: Node, catalog, memo) -> Schema:
    if isinstance(node, Scan):
        if node.table not in catalog:
            raise PlanError(f"unknown table {node.table!r}; catalog has "
                            f"{sorted(catalog)}")
        base = catalog[node.table]
        if node.columns is None:
            return dict(base)
        out: Schema = {}
        for c in node.columns:
            if c not in base:
                raise PlanError(f"scan {node.key}: no column {c!r}")
            out[c] = base[c]
        return out

    if isinstance(node, Filter):
        s = infer_schema(node.input, catalog, memo)
        d = node.predicate.dtype(s)
        if d.id != TypeId.BOOL8:
            raise PlanError(f"filter predicate must be BOOL8, got {d!r}")
        return dict(s)

    if isinstance(node, Project):
        s = infer_schema(node.input, catalog, memo)
        out = {}
        for name, e in node.exprs:
            if name in out:
                raise PlanError(f"project: duplicate output name {name!r}")
            out[name] = e.dtype(s)
        return out

    if isinstance(node, Join):
        ls = infer_schema(node.left, catalog, memo)
        rs = infer_schema(node.right, catalog, memo)
        for pair in node.on:
            _check_key_pair(ls, rs, pair, f"{node.how} join")
        if node.how in ("semi", "anti"):
            return dict(ls)
        rkeys = {r for _, r in node.on}
        out = dict(ls)
        for name, d in rs.items():
            if name in rkeys:
                continue
            if name in out:
                raise PlanError(
                    f"join: build column {name!r} collides with the probe "
                    "schema; Project-rename one side first")
            out[name] = d
        return out

    if isinstance(node, Aggregate):
        s = infer_schema(node.input, catalog, memo)
        out: Schema = {}
        for k in node.keys:
            if k not in s:
                raise PlanError(f"aggregate key {k!r} not in {sorted(s)}")
            out[k] = s[k]
        for a in node.aggs:
            if a.how == "count_all":
                out[a.name] = dt.INT64
                continue
            if a.source not in s:
                raise PlanError(f"aggregate source {a.source!r} not in {sorted(s)}")
            out[a.name] = _numeric_agg_dtype(s[a.source], a.how, "aggregate")
        return out

    if isinstance(node, Exchange):
        s = infer_schema(node.input, catalog, memo)
        for c in node.keys:
            if c not in s:
                raise PlanError(f"exchange key {c!r} not in {sorted(s)}")
        return dict(s)

    if isinstance(node, Window):
        s = infer_schema(node.input, catalog, memo)
        for c in node.partition_by:
            if c not in s:
                raise PlanError(f"window partition key {c!r} not in {sorted(s)}")
        for c, _ in node.order_by:
            if c not in s:
                raise PlanError(f"window order key {c!r} not in {sorted(s)}")
        out = dict(s)
        for src, how, name in node.aggs:
            if src not in s:
                raise PlanError(f"window source {src!r} not in {sorted(s)}")
            if name in out:
                raise PlanError(f"window output {name!r} collides")
            out[name] = _window_dtype(s[src], how)
        return out

    if isinstance(node, (Sort,)):
        s = infer_schema(node.input, catalog, memo)
        for c, _ in node.keys:
            if c not in s:
                raise PlanError(f"sort key {c!r} not in {sorted(s)}")
        return dict(s)

    if isinstance(node, Limit):
        return dict(infer_schema(node.input, catalog, memo))

    if isinstance(node, UnionAll):
        first = infer_schema(node.branches[0], catalog, memo)
        for b in node.branches[1:]:
            s = infer_schema(b, catalog, memo)
            if list(s.keys()) != list(first.keys()) or any(
                s[k].id != first[k].id or s[k].scale != first[k].scale
                for k in first
            ):
                raise PlanError(
                    f"UNION ALL branch schemas differ: {first} vs {s}")
        return dict(first)

    if isinstance(node, SetOp):
        ls = infer_schema(node.left, catalog, memo)
        rs = infer_schema(node.right, catalog, memo)
        if list(ls.keys()) != list(rs.keys()) or any(
            ls[k].id != rs[k].id for k in ls
        ):
            raise PlanError(f"{node.kind} sides disagree: {ls} vs {rs}")
        return dict(ls)

    if isinstance(node, Exists):
        s = infer_schema(node.input, catalog, memo)
        sub = infer_schema(node.sub, catalog, memo)
        for pair in node.on:
            _check_key_pair(s, sub, pair, "exists")
        return dict(s)

    if isinstance(node, Having):
        s = infer_schema(node.input, catalog, memo)
        d = node.predicate.dtype(s)
        if d.id != TypeId.BOOL8:
            raise PlanError(f"having predicate must be BOOL8, got {d!r}")
        return dict(s)

    if isinstance(node, CorrelatedAggFilter):
        s = infer_schema(node.input, catalog, memo)
        sub = infer_schema(node.sub, catalog, memo)
        _check_key_pair(s, sub, node.on, "correlated filter")
        a = node.agg
        if a.source is not None and a.source not in sub:
            raise PlanError(f"correlated agg source {a.source!r} not in "
                            f"{sorted(sub)}")
        out = dict(s)
        if a.name in out:
            raise PlanError(f"correlated agg output {a.name!r} collides")
        out[a.name] = (dt.INT64 if a.how in ("count", "count_all", "nunique")
                       else _numeric_agg_dtype(sub[a.source], a.how,
                                               "correlated filter"))
        d = node.predicate.dtype(out)
        if d.id != TypeId.BOOL8:
            raise PlanError(f"correlated predicate must be BOOL8, got {d!r}")
        return out

    raise PlanError(f"unknown plan node {type(node).__name__}")


# ---------------------------------------------------------------------------
# canonical structure (structural equality for tests / idempotence)
# ---------------------------------------------------------------------------


def structure(node: Node) -> tuple:
    """Canonical nested-tuple rendering of a plan (expressions included
    via ``PExpr.structure``); two plans are structurally equal iff their
    structures compare equal."""
    if isinstance(node, Scan):
        return ("scan", node.table, node.columns, node.alias)
    if isinstance(node, Filter):
        return ("filter", node.predicate.structure(), structure(node.input))
    if isinstance(node, Project):
        return ("project",
                tuple((n, e.structure()) for n, e in node.exprs),
                structure(node.input))
    if isinstance(node, Join):
        return ("join", node.how, node.on, node.bounded,
                structure(node.left), structure(node.right))
    if isinstance(node, Aggregate):
        return ("aggregate", node.keys,
                tuple((a.source, a.how, a.name) for a in node.aggs),
                node.grouping_sets, structure(node.input))
    if isinstance(node, Exchange):
        return ("exchange", node.keys, node.world, structure(node.input))
    if isinstance(node, Window):
        return ("window", node.partition_by, node.order_by, node.aggs,
                structure(node.input))
    if isinstance(node, Sort):
        return ("sort", node.keys, structure(node.input))
    if isinstance(node, Limit):
        return ("limit", node.n, structure(node.input))
    if isinstance(node, UnionAll):
        return ("union_all", tuple(structure(b) for b in node.branches))
    if isinstance(node, SetOp):
        return ("set_op", node.kind, structure(node.left), structure(node.right))
    if isinstance(node, Exists):
        return ("exists", node.on, node.negated,
                structure(node.input), structure(node.sub))
    if isinstance(node, Having):
        return ("having", node.predicate.structure(), structure(node.input))
    if isinstance(node, CorrelatedAggFilter):
        return ("corr_agg_filter", node.on,
                (node.agg.source, node.agg.how, node.agg.name),
                node.predicate.structure(),
                structure(node.input), structure(node.sub))
    raise PlanError(f"unknown plan node {type(node).__name__}")

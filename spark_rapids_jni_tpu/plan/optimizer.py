"""Cost-based optimizer: join enumeration as verified rewrites
(srjt-cbo, ISSUE 19).

The search half of the plan tier. Three rules, each firing through the
SAME rewrite machinery as the standard executor rewrites — every fire
emits a PLAN006-style translation-validation obligation that
``plan/verifier.py`` discharges (schema witness + join-predicate
multiset preservation + outer-join legality), so a buggy search can
never silently change answers:

- ``cbo_reorder_joins`` — collects the maximal left-deep spine of
  stacked INNER joins over one base (a star: every probe key resolves
  in the base's schema; snowflake spines, whose probe keys come from
  an earlier dim's payload, are left in author order — reordering
  across the dependency is where the legality proofs stop today).
  Dim order is chosen by bounded DP over the join-output cardinality
  model (exact subset DP up to ``SRJT_CBO_DP_TABLES`` dims, greedy
  sort past the bound; under the position-independent fanout
  multipliers the two provably coincide, which also makes the
  canonical order PREFIX-STABLE — a sub-chain of an optimal chain is
  itself optimal, so the bottom-up rewrite fixpoint converges instead
  of oscillating). A fire rebuilds the chain in canonical order and
  wraps it in a passthrough Project restoring the original column
  order, so the obligation's order-sensitive schema witness holds.

- ``cbo_build_side`` — commutes one inner join when the modeled build
  side (right) is strictly larger than the probe side; the wrapper
  Project renames the surviving right key back to the dropped left
  key's name (legal: equi-join output has them equal, and the rule
  only fires when the key dtypes match exactly).

- ``cbo_join_strategy`` — resolves a ``bounded=None`` ("CBO decides")
  join to the dense bounded-domain kernel or sort-merge from the build
  key's sketch (INT32, null-free, non-negative, domain under
  ``_MAX_BOUNDED_DOMAIN``). Author-written ``True``/``False`` are
  binding and never touched; the Pallas paged-hash tier keeps riding
  the op-level ``SRJT_PALLAS_*`` gates underneath either choice.

The CBO pass runs inside ``compile_ir`` AFTER the standard rewrite
fixpoint (so sugar is gone and the idempotence contract of the default
RULES set is untouched), as two ``rewrite(..., rules=..., prune=False)``
invocations: reorder first, then build-side + strategy — physical
decisions must not disturb the canonical order mid-fixpoint.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

from ..columnar.dtype import TypeId
from ..utils import knobs
from . import stats as plan_stats
from .exprs import pcol
from .nodes import Filter, Join, Node, PlanError, Project, Scan, infer_schema
from .rewrites import Obligation, Rule, fingerprint, rewrite

__all__ = [
    "enabled", "optimize", "CboResult", "collect_chain",
    "is_passthrough_project", "reorder_rules", "physical_rules",
]

# dense bounded-domain joins stop paying off (and start bailing at
# bind time) past this build-key domain size
_MAX_BOUNDED_DOMAIN = 1 << 20


def enabled() -> bool:
    return (knobs.get_bool("SRJT_CBO_ENABLED")
            and knobs.get_bool("SRJT_STATS_ENABLED"))


# ---------------------------------------------------------------------------
# chain shape helpers (shared with the verifier's dischargers)
# ---------------------------------------------------------------------------


def is_passthrough_project(node: Node) -> bool:
    """True for a Project whose every output is a bare same-name column
    reference (a pure column permutation / narrowing)."""
    from . import exprs as ex
    return (isinstance(node, Project)
            and all(ex.is_col(e) == name for name, e in node.exprs))


def collect_chain(node: Node, catalog) -> Tuple[Node, List[Join]]:
    """Walk the left spine of stacked inner joins, seeing through any
    passthrough Project — column-pruning's narrowing wrappers and
    earlier fires' own restore Projects both land on the spine — and
    return ``(base, joins)`` with ``joins`` ordered OUTERMOST first. A
    non-inner join, a computing Project, or any other node terminates
    the spine and becomes the base. The rebuild drops the interleaved
    spine Projects (re-widening the intermediates); the head fire's
    restore Project re-narrows to the witnessed schema, and a rebuild
    that resurrects a projected-away name collision fails schema
    inference and aborts the fire."""
    joins: List[Join] = []
    cur = node
    while True:
        if isinstance(cur, Join) and cur.how == "inner":
            joins.append(cur)
            cur = cur.left
            continue
        if is_passthrough_project(cur):
            cur = cur.input
            continue
        break
    return cur, joins


# ---------------------------------------------------------------------------
# the enumeration core
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _Dim:
    """One chain member: the join's key pairs + its build subtree."""

    on: Tuple[Tuple[str, str], ...]
    right: Node
    bounded: Optional[bool]
    factor: float          # modeled fanout multiplier (position-free)
    build_rows: int
    fp: str                # deterministic tie-break

    @property
    def order_key(self):
        return (self.factor, self.build_rows, self.fp)


def _dims_of(chain: Sequence[Join], est, catalog) -> List[_Dim]:
    out = []
    for j in chain:
        rrows = plan_stats.model.estimate_rows(j.right, est, catalog)
        denom = 1.0
        for l, r in j.on:
            d = max(est.ndv(l), est.ndv(r))
            if d > 0:
                denom *= d
        # unclamped fanout: rows multiply by build_rows / key-ndv —
        # position-independent, which the prefix-stability (and hence
        # fixpoint convergence) argument relies on
        factor = rrows / denom if denom > 1.0 else float(rrows)
        out.append(_Dim(on=j.on, right=j.right, bounded=j.bounded,
                        factor=factor, build_rows=rrows,
                        fp=fingerprint(j.right)))
    return out


def _order_cost(base_rows: float, dims: Sequence[_Dim]) -> float:
    """Sum of modeled intermediate cardinalities — the DP objective."""
    card = float(base_rows)
    total = 0.0
    for d in dims:
        card *= d.factor
        total += card
    return total


def _dp_order(base_rows: float, dims: List[_Dim]) -> List[_Dim]:
    """Exact left-deep subset DP minimizing the sum of intermediate
    cardinalities, ties broken toward the greedy (sorted) order — so
    the result is deterministic and equals the greedy order under the
    position-independent multiplier model."""
    n = len(dims)
    order = sorted(range(n), key=lambda i: dims[i].order_key)
    best: Dict[int, Tuple[float, Tuple[int, ...]]] = {0: (0.0, ())}
    rank = {i: pos for pos, i in enumerate(order)}
    for mask in range(1, 1 << n):
        card = base_rows
        for i in range(n):
            if mask & (1 << i):
                card *= dims[i].factor
        choices = []
        for i in range(n):
            if not (mask & (1 << i)):
                continue
            prev_cost, prev_seq = best[mask & ~(1 << i)]
            choices.append((prev_cost + card,
                            tuple(rank[j] for j in prev_seq + (i,)),
                            prev_seq + (i,)))
        choices.sort(key=lambda c: (c[0], c[1]))
        best[mask] = (choices[0][0], choices[0][2])
    seq = best[(1 << n) - 1][1]
    return [dims[i] for i in seq]


def _canonical_order(base_rows: float, dims: List[_Dim]) -> List[_Dim]:
    bound = max(2, knobs.get_int("SRJT_CBO_DP_TABLES"))
    if len(dims) <= bound:
        return _dp_order(base_rows, dims)
    return sorted(dims, key=lambda d: d.order_key)  # greedy fallback


def _rebuild_chain(base: Node, dims: Sequence[_Dim]) -> Node:
    cur = base
    for d in dims:
        cur = Join(cur, d.right, on=d.on, how="inner", bounded=d.bounded)
    return cur


def _restore_order(inner: Node, original_schema) -> Project:
    return Project(inner, tuple((n, pcol(n)) for n in original_schema))


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------


def _r_reorder(est):
    def fn(node, catalog, memo) -> Optional[Node]:
        if not (isinstance(node, Join) and node.how == "inner"):
            return None
        base, chain = collect_chain(node, catalog)
        if len(chain) < 2:
            return None
        base_schema = infer_schema(base, catalog)
        # star only: every probe key resolves in the base schema —
        # snowflake dependencies pin the author order
        for j in chain:
            if any(l not in base_schema for l, _ in j.on):
                return None
        # a spine node reused INSIDE a dim subtree is a CTE (q32/q92:
        # the decorrelated scalar agg aggregates the same dated fact
        # join the spine probes) — the plan computes it once by object
        # identity, and a rebuilt spine would break that sharing and
        # pay for the subtree twice, so the author order is pinned
        spine_ids = set()
        walk = node
        while walk is not base:
            spine_ids.add(id(walk))
            walk = walk.left if isinstance(walk, Join) else walk.input
        for j in chain:
            stack = [j.right]
            while stack:
                n = stack.pop()
                if id(n) in spine_ids:
                    return None
                stack.extend(n.inputs())
        dims = list(reversed(_dims_of(chain, est, catalog)))  # innermost 1st
        base_rows = plan_stats.model.estimate_rows(base, est, catalog)
        want = _canonical_order(float(base_rows), dims)
        if [d.fp for d in want] == [d.fp for d in dims]:
            return None
        rebuilt = _rebuild_chain(base, want)
        out = _restore_order(rebuilt, infer_schema(node, catalog))
        try:
            # dropping the spine's narrowing Projects can resurrect a
            # payload-name collision the author projected away — such a
            # rebuild does not validate, so the fire aborts
            infer_schema(out, catalog)
        except PlanError:
            return None
        return out
    return fn


def _key_unique(est, name: str) -> bool:
    """EXACT evidence that a base column is null-free and all-distinct
    — the classic build-on-the-PK-side gate. Sketch ``unique`` is a
    full-scan ``np.unique`` witness (never claimed under sampling): the
    dense payload maps reject duplicate build keys at RUNTIME, so an
    approximate HLL "probably unique" would turn a profitable-looking
    commute into a query failure."""
    sk = est.resolve(name)
    return (sk is not None and sk.nulls == 0 and sk.non_null > 0
            and sk.unique)


def _multiplicity_preserving(node: Node) -> bool:
    """True when ``node`` is a Scan under only Filters / passthrough
    Projects — shapes that can only DROP rows, never duplicate them.
    Base-column uniqueness (``_key_unique``) survives exactly these
    shapes; a join above the scan could fan rows out and re-introduce
    duplicate keys the sketch cannot see."""
    cur = node
    while True:
        if isinstance(cur, Filter):
            cur = cur.input
        elif isinstance(cur, Project) and is_passthrough_project(cur):
            cur = cur.input
        else:
            return isinstance(cur, Scan)


def _r_build_side(est):
    def fn(node, catalog, memo) -> Optional[Node]:
        if not (isinstance(node, Join) and node.how == "inner"):
            return None
        ls = infer_schema(node.left, catalog)
        rs = infer_schema(node.right, catalog)
        # the restore-Project renames the surviving right key to the
        # dropped left key's name: only legal when dtypes match exactly
        if any(ls[l].id != rs[r].id or ls[l].scale != rs[r].scale
               for l, r in node.on):
            return None
        # the commute makes the old probe side the new BUILD side: the
        # fused tier's payload maps need unique build keys, so only
        # commute onto a key-side (a dup-heavy FK stays the probe) that
        # cannot have re-duplicated the key above its scan
        if any(not _key_unique(est, l) for l, _ in node.on) \
                or not _multiplicity_preserving(node.left):
            return None
        lrows = plan_stats.model.estimate_rows(node.left, est, catalog)
        rrows = plan_stats.model.estimate_rows(node.right, est, catalog)
        if rrows <= lrows:
            return None  # build already the smaller side
        swapped = Join(node.right, node.left,
                       on=tuple((r, l) for l, r in node.on),
                       how="inner", bounded=node.bounded)
        rename = {l: r for l, r in node.on if l != r}
        out = tuple((n, pcol(rename.get(n, n)))
                    for n in infer_schema(node, catalog))
        return Project(swapped, out)
    return fn


def _r_join_strategy(est):
    def fn(node, catalog, memo) -> Optional[Node]:
        if not (isinstance(node, Join) and node.bounded is None):
            return None
        decision = False
        if node.how in ("inner", "semi", "anti") and len(node.on) == 1:
            _, r = node.on[0]
            rs = infer_schema(node.right, catalog)
            sk = est.resolve(r)
            if (rs[r].id == TypeId.INT32 and sk is not None
                    and sk.non_null > 0 and sk.nulls == 0
                    and sk.min_val is not None and sk.min_val >= 0
                    and sk.max_val < _MAX_BOUNDED_DOMAIN
                    # dense bounded-domain builds require UNIQUE keys
                    # (the pipeline rejects duplicate payload slots)
                    and _key_unique(est, r)
                    and _multiplicity_preserving(node.right)):
                decision = True
        return Join(node.left, node.right, on=node.on, how=node.how,
                    bounded=decision)
    return fn


def reorder_rules(est) -> Tuple[Rule, ...]:
    return (("cbo_reorder_joins", _r_reorder(est)),)


def physical_rules(est) -> Tuple[Rule, ...]:
    return (("cbo_build_side", _r_build_side(est)),
            ("cbo_join_strategy", _r_join_strategy(est)))


# ---------------------------------------------------------------------------
# the compile_ir entry point
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CboResult:
    plan: Node
    fired: Dict[str, int]
    obligations: List[Obligation]
    author_cost: Optional[float]
    chosen_cost: Optional[float]
    join_count: int
    estimator: object


def _count_joins(node: Node, seen=None) -> int:
    seen = set() if seen is None else seen
    if id(node) in seen:
        return 0
    seen.add(id(node))
    return (1 if isinstance(node, Join) else 0) + sum(
        _count_joins(i, seen) for i in node.inputs())


def optimize(plan: Node, catalog, tables, *, est=None) -> CboResult:
    """Run the CBO search over an already-desugared plan. The author
    plan's modeled cost is recorded BEFORE the search so the premerge
    gate can assert chosen <= author from the compile report."""
    if est is None:
        est = plan_stats.make_estimator(tables)
    if est is None:  # stats knobbed off: CBO has no model to search on
        return CboResult(plan, {}, [], None, None, _count_joins(plan), None)
    author_cost = plan_stats.plan_cost(plan, est, catalog)
    fired: Dict[str, int] = {}
    obligations: List[Obligation] = []
    cur = plan
    # reorder phase: the chain enumeration is local and cannot see DAG
    # sharing across the rest of the plan, so the global model vetoes —
    # a reorder that models worse than the author order is discarded
    res = rewrite(cur, catalog, rules=reorder_rules(est), prune=False)
    if res.fired and plan_stats.plan_cost(
            res.plan, est, catalog) <= author_cost:
        cur = res.plan
        for k, v in res.fired.items():
            fired[k] = fired.get(k, 0) + v
        obligations.extend(res.obligations)
    # physical phase: build-side only commutes the bigger side out of
    # build position and strategy only sets a hint — never cost-raising
    res = rewrite(cur, catalog, rules=physical_rules(est), prune=False)
    cur = res.plan
    for k, v in res.fired.items():
        fired[k] = fired.get(k, 0) + v
    obligations.extend(res.obligations)
    chosen_cost = plan_stats.plan_cost(cur, est, catalog)
    return CboResult(cur, fired, obligations, author_cost, chosen_cost,
                     _count_joins(plan), est)

"""srjt-plan: logical-plan IR + rewrite passes + compiler (ISSUE 14).

The front-end that turns QUERIES.md "lowers" green mechanically: express
a TPC-DS query as a small relational-algebra tree (``nodes``), with a
typed expression layer (``exprs``); the optimizer (``rewrites``) applies
the standard executor expansions (decorrelation, ROLLUP, set ops,
EXISTS, HAVING, predicate/projection pushdown); the compiler
(``compiler``) lowers the optimized plan onto the fused
``CompiledPipeline`` tier where the grammar allows and the tested
``ops/`` operators elsewhere, carrying per-stage ``memory_bytes``
estimates for memgov admission and the serve scheduler.

Quick shape::

    from spark_rapids_jni_tpu import plan as P

    ir = P.Sort(
        P.Aggregate(
            P.Join(P.Scan("fact"), P.Filter(P.Scan("dim"),
                   P.pcol("d_moy") == P.plit(11)),
                   on=(("f_date_sk", "d_date_sk"),)),
            keys=("f_key",),
            aggs=(P.AggSpec("f_price", "sum", "total"),),
        ),
        keys=(("total", False),),
    )
    out = P.compile_ir(ir, {"fact": fact, "dim": dim}, name="demo")()
"""

from .compiler import CompiledPlan, compile_ir, lower_ir  # noqa: F401
from .distribute import (  # noqa: F401
    exchange_context,
    insert_exchanges,
)
from .exprs import (  # noqa: F401
    PExpr,
    PlanError,
    pcol,
    plike,
    plit,
    ppart,
    prlike,
    pwhen,
)
from .ooc import (  # noqa: F401
    OutOfCorePlan,
    maybe_out_of_core,
)
from .nodes import (  # noqa: F401
    Aggregate,
    AggSpec,
    CorrelatedAggFilter,
    Exchange,
    Exists,
    Filter,
    Having,
    Join,
    Limit,
    Node,
    Project,
    Scan,
    SetOp,
    Sort,
    UnionAll,
    Window,
    infer_schema,
    rollup,
    structure,
)
from .rewrites import (  # noqa: F401
    Obligation,
    ParamFingerprint,
    RewriteResult,
    fingerprint,
    parameterized_fingerprint,
    prune_columns,
    rebind_literals,
    rewrite,
)
from .verifier import (  # noqa: F401
    PlanViolation,
    verify_estimates,
    verify_obligations,
    verify_plan,
)

__all__ = [
    "CompiledPlan", "compile_ir", "lower_ir",
    "OutOfCorePlan", "maybe_out_of_core",
    "PExpr", "PlanError", "pcol", "plit", "pwhen", "plike", "prlike", "ppart",
    "Node", "Scan", "Filter", "Project", "Join", "Aggregate", "AggSpec",
    "Window", "Sort", "Limit", "UnionAll", "SetOp", "Exists", "Having",
    "CorrelatedAggFilter", "Exchange", "rollup", "infer_schema",
    "structure", "rewrite", "prune_columns", "RewriteResult", "Obligation",
    "fingerprint", "ParamFingerprint", "parameterized_fingerprint",
    "rebind_literals",
    "PlanViolation", "verify_plan", "verify_obligations",
    "verify_estimates", "insert_exchanges", "exchange_context",
]

"""Distributed plan assembly: Exchange insertion + run-time binding.

The two halves of ISSUE 16's "compiled plans gain exchange stages":

**insert_exchanges(plan, world)** is the *structural* half — a
deterministic tree rebuild that wraps every keyed Aggregate's input in
an ``Exchange`` on the grouping keys, so each rank aggregates only the
key space hashed to it. It is deliberately NOT a registered rewrite
rule: rewrite rules are semantics-preserving *per-process* transforms
with translation-validation obligations, while Exchange changes
where rows live, which is only meaning-preserving under the N-rank
execution contract this module owns. Joins stay local: the shard
binding replicates every non-sharded table on every rank (broadcast
join), so only the aggregate's key space needs movement — the same
shape Spark picks for a fact-table scan joined to small dims.

**exchange_context(...)** is the *runtime* half — a contextvar-scoped
binding from the logical Exchange stages to a concrete
``TcpExchange`` + peer map (+ optional ``ClusterView`` for fenced
recovery). Outside any binding — or at ``world == 1`` — an Exchange
stage is the identity, so the SAME compiled plan runs single-host
(plancheck, tests, the oracle side of the chaos gate) and distributed
without recompilation. Stage epochs are allocated in first-run order,
which the compiled plan makes deterministic and identical on every
rank; each stage gets its own epoch namespace
(``base_epoch + i * _STAGE_EPOCH_STRIDE``) so two exchange stages in
one plan can never collide in the publish store.

Recovery lineage: with a cluster AND ``shard_tables`` bound, each
Exchange stage installs ``lineage(r) = replay my child subtree over
rank r's catalog shard`` just before it moves rows — the Spark
lineage story, but the replay is the already-lowered exec subtree, so
a dead rank's exchange input is recomputed by exactly the code that
produced the original.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Callable, Dict, Optional

from ..columnar import Table
from .exprs import PlanError
from .nodes import Aggregate, Exchange, Node
from .rewrites import _with_inputs

__all__ = ["insert_exchanges", "exchange_context", "current_binding",
           "merge_partials",
           "ExchangeBinding"]

# one epoch namespace per exchange stage; a worker's own result
# publishes ride base_epoch + 1, which stage 0 (base_epoch) and stage
# 1 (base_epoch + 16) both clear
_STAGE_EPOCH_STRIDE = 16


def insert_exchanges(plan: Node, world: int) -> Node:
    """Rebuild ``plan`` with an ``Exchange(keys, world)`` under every
    keyed Aggregate. Shared subtrees stay shared (memo by identity,
    the same discipline as the rewrite pass); non-keyed aggregates are
    left alone — a global aggregate has no partitioning to exploit and
    its distribution is the coordinator's merge problem."""
    if world < 1:
        raise PlanError(f"insert_exchanges: world must be >= 1, got {world}")
    memo: Dict[int, Node] = {}

    def walk(n: Node) -> Node:
        if id(n) in memo:
            return memo[id(n)]
        kids = tuple(walk(i) for i in n.inputs())
        if isinstance(n, Aggregate) and n.keys:
            out: Node = Aggregate(
                Exchange(kids[0], tuple(n.keys), world),
                keys=n.keys, aggs=n.aggs, grouping_sets=n.grouping_sets,
            )
        else:
            out = _with_inputs(n, kids)
        memo[id(n)] = out
        return out

    return walk(plan)


def merge_partials(partials, sort_keys) -> Table:
    """Coordinator-side merge of per-rank results: concatenate and
    re-apply the plan's Sort keys (``((column, ascending), ...)``).
    Bit-identical to the single-host run whenever (a) the exchange
    made every rank's groups complete — true by construction — and (b) the
    sort keys form a total order (the distributed TPC-DS plans end in
    one: the group key breaks ties)."""
    from ..ops.copying import concatenate
    from ..ops.sort import sort_by_key

    merged = concatenate(list(partials))
    if not sort_keys:
        return merged
    keys = Table([merged.column(c) for c, _ in sort_keys],
                 [f"k{i}" for i in range(len(sort_keys))])
    return sort_by_key(merged, keys,
                       ascending=[asc for _, asc in sort_keys])


class ExchangeBinding:
    """The concrete fabric a plan's Exchange stages run against:
    ``exchange`` (a TcpExchange), ``peers`` (rank -> host:port, this
    rank excluded), optional ``cluster`` (ClusterView: fencing +
    failover) and ``shard_tables`` (rank -> catalog shard, the lineage
    reproducer)."""

    def __init__(self, exchange, peers: Dict[int, str], *,
                 cluster=None,
                 shard_tables: Optional[Callable[[int], Dict[str, Table]]] = None,
                 base_epoch: int = 0) -> None:
        self.exchange = exchange
        self.peers = dict(peers)
        self.cluster = cluster
        self.shard_tables = shard_tables
        self.base_epoch = int(base_epoch)
        self._stage_epochs: Dict[int, int] = {}

    @property
    def world(self) -> int:
        return len(self.peers) + 1

    def stage_epoch(self, stage_id: int) -> int:
        """Deterministic per-stage epoch: allocated in first-run
        order, which the compiled plan's data dependencies make
        identical on every rank."""
        if stage_id not in self._stage_epochs:
            self._stage_epochs[stage_id] = (
                self.base_epoch + len(self._stage_epochs) * _STAGE_EPOCH_STRIDE
            )
        return self._stage_epochs[stage_id]


_BINDING: contextvars.ContextVar[Optional[ExchangeBinding]] = \
    contextvars.ContextVar("srjt_exchange_binding", default=None)


def current_binding() -> Optional[ExchangeBinding]:
    return _BINDING.get()


@contextlib.contextmanager
def exchange_context(exchange, peers: Dict[int, str], *,
                     cluster=None,
                     shard_tables: Optional[Callable[[int], Dict[str, Table]]] = None,
                     base_epoch: int = 0):
    """Bind the plan compiler's Exchange stages to a live fabric for
    the dynamic extent of the block (contextvar-scoped: thread- and
    task-local, exactly like the deadline scopes)."""
    token = _BINDING.set(ExchangeBinding(
        exchange, peers, cluster=cluster, shard_tables=shard_tables,
        base_epoch=base_epoch,
    ))
    try:
        yield
    finally:
        _BINDING.reset(token)

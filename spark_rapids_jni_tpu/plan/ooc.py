"""Out-of-core partitioned execution (srjt-ooc, ISSUE 18).

memgov can spill *buffers*, but a query whose working set exceeds
``SRJT_DEVICE_MEMORY_BUDGET`` used to split-retry until it failed — the
one remaining hard failure mode on the memory axis. This module turns
that case into a scheduled data-movement strategy (the Theseus thesis:
out-of-core as a plan-level decision, not an error path): when the
compiler's whole-plan peak exceeds the admitted budget and the plan has
the partitionable shape, the query is executed as K hash-partitioned
slices streamed through the SAME compiled pipeline, with partials
merged by the distributed tier's proven coordinator merge
(``distribute.merge_partials``).

The decision is a *verified rewrite* (the Flare discipline): the
selected Aggregate is rewritten to a ``UnionAll`` of per-partition
aggregates filtered by ``part_hash(keys, K) == i`` and recorded as a
``partition_for_ooc`` obligation the plancheck verifier discharges
structurally (``verifier._d_partition_ooc``). The rewrite is EXACT, not
approximate:

- every row of one group carries the same key tuple, so the murmur3
  partition id puts each group whole into exactly one branch —
  per-group aggregation inputs are untouched;
- the physical partitioner (``parallel.shuffle.hash_partition``) uses a
  STABLE argsort over the very same ``ops.hashing.hash_partition_map``
  the plan predicate lowers to, so within a partition the original row
  order is preserved — each group's accumulation SEQUENCE is identical
  to the in-core run, making the partials bit-identical, not just
  numerically close;
- the plan's root Sort must be a total order over the group keys, so
  the post-merge re-sort reproduces the in-core row order exactly.

Execution streams the partitions under ONE plan-level memgov admission
sized to the PER-PARTITION peak (nested op/sub-plan admissions skip,
the engine's standing outermost-only discipline — so the degraded
query's footprint claim is what it actually streams, not the whole-plan
estimate that could never be admitted). Inputs are registered as
spill-backed ``kind="partition"`` memgov catalog entries (CRC-framed on
disk like every spill), the in-flight partition is PINNED so the
pressure loop can never evict the bytes the current step is computing
over (the self-eviction livelock), and a prefetch thread warms the NEXT
partition's spill-in — and pings the sidecar pool to keep the device
path live — overlapped with compute. Each completed partition's partial
is checkpointed in the catalog under a stable fingerprinted key, so a
retried run (worker crash, corrupt spill) RESUMES from the last
complete partition and lineage-recomputes only the hole (the PR 16
discipline) instead of restarting the query.

Cache safety: ``OutOfCorePlan`` delegates ``optimized`` (and every
other un-overridden attribute) to the inner ``CompiledPlan`` — the plan
cache must key/rebind on the UN-partitioned structure (the partition
branch literals ``0..K-1`` are plan shape, not query parameters); a
cache hit re-enters ``maybe_out_of_core`` through ``lower_ir`` and
re-wraps under the budget then in force.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Dict, Optional, Tuple

from ..utils import knobs, metrics
from . import exprs as ex
from .nodes import Aggregate, Exchange, Filter, Node, Project, Scan, Sort, UnionAll
from .rewrites import Obligation, _make_obligation, fingerprint

__all__ = ["OutOfCorePlan", "maybe_out_of_core", "partition_rewrite"]

_MAX_AUTO_PARTITIONS = 64

# re-entrancy guard: the per-partition lower_ir calls inside
# OutOfCorePlan.__call__ must never select out-of-core again (a
# partition that still overflows the budget falls back to the
# split-retry path rather than recursing)
_tls = threading.local()


def _reg():
    return metrics.registry()


@dataclasses.dataclass(frozen=True)
class _OocTarget:
    """The partitionable shape: ``Sort(Aggregate(spine(Scan)))`` where
    the Sort totally orders the group keys and every group key traces
    through the spine's Projects as a pure column ref down to the
    Scan."""

    sort: Sort
    agg: Aggregate
    table: str
    key_cols: Tuple[str, ...]


def find_target(opt_plan: Node) -> Optional[_OocTarget]:
    """Match the (conservative) partitionable plan shape, or None.

    Requirements, each load-bearing for bit-identity:
    - root ``Sort`` whose key columns cover the aggregate keys (total
      order over the output -> the merged re-sort reproduces the
      in-core row order exactly);
    - keyed ``Aggregate`` (no grouping sets — ROLLUP expands to a
      UnionAll before this runs, and its branches do not share one key
      set);
    - the aggregate input is a unary spine of Filter/Project (and
      world-1 Exchange) over a single Scan, with every group key a pure
      rename through the Projects — those resolved names are the
      physical partition keys ``hash_partition`` uses, guaranteeing the
      executor's slices select exactly the rewrite's branch rows.
    """
    if not isinstance(opt_plan, Sort):
        return None
    agg = opt_plan.input
    if not isinstance(agg, Aggregate) or not agg.keys \
            or agg.grouping_sets is not None:
        return None
    sort_cols = {c for c, _ in opt_plan.keys}
    if not set(agg.keys) <= sort_cols:
        return None
    names = list(agg.keys)
    n = agg.input
    while True:
        if isinstance(n, Filter):
            n = n.input
        elif isinstance(n, Exchange):
            if n.world != 1:
                return None  # a distributed plan partitions via its exchanges
            n = n.input
        elif isinstance(n, Project):
            mapping = {out: ex.is_col(e) for out, e in n.exprs}
            resolved = [mapping.get(name) for name in names]
            if any(r is None for r in resolved):
                return None  # a key is computed, not a rename
            names = resolved
            n = n.input
        elif isinstance(n, Scan):
            if n.columns is not None and not set(names) <= set(n.columns):
                return None
            return _OocTarget(opt_plan, agg, n.table, tuple(names))
        else:
            return None


def partition_rewrite(agg: Aggregate, parts: int) -> UnionAll:
    """The ``partition_for_ooc`` rewrite output: branch ``i`` aggregates
    exactly the rows whose key tuple hashes to partition ``i``. Ordered
    ``i = 0..parts-1`` branches give the verifier disjointness and
    completeness by construction (the partition ids partition rows)."""
    branches = []
    for i in range(parts):
        pred = ex.ppart(agg.keys, parts) == ex.plit(i)
        branches.append(
            Aggregate(Filter(agg.input, pred), keys=agg.keys, aggs=agg.aggs)
        )
    return UnionAll(tuple(branches))


def _auto_partitions(est_bytes: int, budget: int) -> int:
    """Smallest K whose per-partition estimate fits HALF the budget —
    headroom for the checkpointed partial, the prefetched next
    partition, and the merge — capped at ``_MAX_AUTO_PARTITIONS``."""
    target = max(1, budget // 2)
    for k in range(2, _MAX_AUTO_PARTITIONS + 1):
        if -(-est_bytes // k) <= target:
            return k
    return _MAX_AUTO_PARTITIONS


def maybe_out_of_core(cp, tables: Dict):
    """Compiler tail hook (``compile_ir``/``lower_ir``): when the plan's
    estimated peak exceeds the armed device budget and the plan has the
    partitionable shape, wrap it for streamed partitioned execution.
    Everything else returns ``cp`` unchanged — the hook is free unless
    ``SRJT_OOC_ENABLED`` is set."""
    if not knobs.get_bool("SRJT_OOC_ENABLED"):
        return cp
    if getattr(_tls, "active", False):
        return cp
    from .. import memgov

    if not memgov.is_enabled():
        return cp
    budget = knobs.get_int("SRJT_DEVICE_MEMORY_BUDGET") or 0
    if budget <= 0 or cp.estimated_memory_bytes <= budget:
        return cp
    target = find_target(cp.optimized)
    if target is None:
        return cp
    parts = knobs.get_int("SRJT_OOC_PARTITIONS") or 0
    if parts < 2:
        # srjt-cbo (ISSUE 19): K comes from the cost model (calibrated
        # per-partition peak vs half the budget) — the knob is now an
        # explicit OVERRIDE, not the primary source; the uncalibrated
        # ladder remains the fallback when even max_parts cannot fit
        from .stats.model import choose_ooc_partitions

        parts = choose_ooc_partitions(
            cp.estimated_memory_bytes, budget,
            max_parts=_MAX_AUTO_PARTITIONS,
        ) or _auto_partitions(cp.estimated_memory_bytes, budget)
    union = partition_rewrite(target.agg, parts)
    catalog = {t: {n: c.dtype for n, c in zip(tbl.names, tbl.columns)}
               for t, tbl in tables.items()}
    ob = _make_obligation("partition_for_ooc", target.agg, union, catalog)
    partitioned = Sort(union, target.sort.keys)
    _reg().counter("plan.ooc.selected").inc()
    metrics.event(
        "plan.ooc.selected", query=cp.name, partitions=parts,
        est_bytes=cp.estimated_memory_bytes, budget_bytes=budget,
    )
    return OutOfCorePlan(cp, partitioned, ob, target, parts)


class OutOfCorePlan:
    """A ``CompiledPlan`` degraded to streamed partitioned execution.

    Delegates every attribute it does not own to the inner plan
    (``optimized``, ``stages``, ``schema``, ``estimated_memory_bytes``,
    ``exec_for`` — the whole audit/cache surface), and overrides only:

    - ``obligations``: the inner ledger plus the ``partition_for_ooc``
      record (any stale partition obligation from a cached ledger is
      replaced — the budget, and so K, may differ per binding);
    - ``partition_memory_bytes``: the per-partition peak estimate the
      serve scheduler admits INSTEAD of the whole-plan peak;
    - ``__call__``: the streamed pin/prefetch/checkpoint/resume/merge
      loop.
    """

    def __init__(self, inner, partitioned: Sort, obligation: Obligation,
                 target: _OocTarget, partitions: int):
        self._inner = inner
        self.partitioned = partitioned
        self.partition_obligation = obligation
        self.partitions = int(partitions)
        self.obligations = [
            ob for ob in inner.obligations if ob.rule != "partition_for_ooc"
        ] + [obligation]
        self.partition_memory_bytes = max(
            1, -(-inner.estimated_memory_bytes // self.partitions)
        )
        self._target = target
        self._fp = fingerprint(partitioned)
        self.last_report: Optional[dict] = None

    def __getattr__(self, name):
        try:
            inner = object.__getattribute__(self, "_inner")
        except AttributeError:
            raise AttributeError(name)
        return getattr(inner, name)

    @property
    def rewrites_fired(self) -> Dict[str, int]:
        out = self._inner.rewrites_fired
        out["partition_for_ooc"] = out.get("partition_for_ooc", 0) + 1
        return out

    # -- checkpoint keys (stable across retries: resume depends on a
    # -- retried __call__ finding the prior attempt's partials) --------------
    def _in_key(self, i: int) -> str:
        return f"ooc.{self._inner.name}.{self._fp}.in.{i}"

    def _part_key(self, i: int) -> str:
        return f"ooc.{self._inner.name}.{self._fp}.part.{i}"

    def _release(self, cat, inputs: bool = True, partials: bool = True) -> None:
        for i in range(self.partitions):
            if inputs:
                cat.unregister(self._in_key(i))
            if partials:
                cat.unregister(self._part_key(i))

    def __call__(self):
        from .. import memgov
        from ..ops.copying import slice_table
        from ..parallel.shuffle import hash_partition
        from ..utils import deadline, faultinj
        from ..utils.errors import DataCorruption, RetryableError
        from .compiler import lower_ir
        from .distribute import merge_partials

        inner = self._inner
        reg = _reg()
        cat = memgov.catalog()
        parts = self.partitions
        t0 = time.perf_counter()
        spills0 = (reg.counter("memgov.spills").value
                   + reg.counter("memgov.disk_spills").value)
        reg.counter("ooc.runs").inc()
        reg.counter("ooc.partitions").inc(parts)
        resumes = 0
        recomputes = 0

        src_tables = dict(inner._tables)
        src = src_tables[self._target.table]
        key_cols = list(self._target.key_cols)

        built_inputs: set = set()

        def ensure_input(i: int):
            """The partition-i input handle, (re)computed from lineage
            when absent or retired — deterministic: the stable argsort
            over the seeded hash reproduces the identical slice."""
            nonlocal recomputes
            h = cat.lookup(self._in_key(i))
            if h is not None:
                return h
            if i in built_inputs:
                # the entry existed and is gone: retired by the catalog
                # on a corrupt spill frame (possibly discovered by the
                # prefetcher, whose advisory read swallows the error) or
                # evicted under pressure — either way this rebuild IS
                # the lineage recompute for the hole
                recomputes += 1
                reg.counter("ooc.lineage_recomputes").inc()
                metrics.event("plan.ooc.recompute", query=inner.name,
                              partition=i)
            deadline.check(f"plan.ooc.repartition[{i}]")
            reordered, offsets = hash_partition(src, parts, key_cols)
            lo = offsets[i]
            hi = offsets[i + 1] if i + 1 < parts else reordered.num_rows
            h = cat.register(self._in_key(i),
                             slice_table(reordered, lo, hi),
                             kind="partition")
            built_inputs.add(i)
            return h

        def warm(i: int, pool):
            """Prefetch: re-materialize the next partition's spill-in
            (and ping the sidecar pool to keep the device path live)
            overlapped with the current partition's compute. Strictly
            best-effort — a prefetch failure is the compute path's
            problem to rediscover, never the query's."""
            try:
                h = cat.lookup(self._in_key(i))
                if h is not None:
                    h.get()
                if pool is not None:
                    from .. import sidecar

                    pool.call(sidecar.OP_PING, b"")
            except Exception:  # srjt-lint: allow-broad-except(prefetch is advisory; the compute path re-raises anything real)
                pass

        prefetch_on = knobs.get_bool("SRJT_OOC_PREFETCH") and parts > 1
        pool = None
        if prefetch_on:
            from .. import sidecar_pool

            pool = sidecar_pool.current_pool()

        def demote(h) -> None:
            """Best-effort device->host demotion: partitions at rest are
            SPILL-BACKED, not device-resident — the whole point of the
            strategy. A failed spill (injected spill_fail, sick disk)
            leaves the entry resident; the pressure loop and the
            catalog's own counters already account for it."""
            try:
                h.spill()
            except (ValueError, RetryableError, OSError):
                pass

        def compute_partition(i: int) -> None:
            """Run partition ``i`` through the compiled pipeline
            (pinned input — the self-eviction livelock guard), then
            checkpoint the partial in the catalog and demote it; the
            input entry is dropped (recomputable from lineage)."""
            attempt = 0
            while True:
                h = ensure_input(i)
                h.pin()
                try:
                    part_tbl = h.get()
                    sub = lower_ir(
                        inner.optimized,
                        {**src_tables, self._target.table: part_tbl},
                        name=f"{inner.name}.ooc{i}",
                    )
                    out = sub()
                    break
                except DataCorruption:
                    # corrupt partition spill: the catalog already
                    # retired the entry — loop back so ensure_input
                    # lineage-recomputes (and counts) the hole, once; a
                    # second corruption propagates to the caller's
                    # retry machinery
                    attempt += 1
                    if attempt >= 2:
                        raise
                finally:
                    h.unpin()
            # checkpoint the partial BEFORE dropping the input: a crash
            # after this line resumes past partition i. The checkpoint
            # is demoted immediately — only the in-flight partition's
            # working set stays device-resident.
            ckpt = cat.register(self._part_key(i), out, kind="partition")
            cat.unregister(self._in_key(i))
            # deliberate drop: a later rebuild (e.g. for a rotted
            # checkpoint, counted at the merge site) is not a new hole
            built_inputs.discard(i)
            # srjt-durable (ISSUE 20): force the checkpoint all the way
            # to the DISK tier so its manifest survives a coordinator
            # kill -9 — a restarted process re-attaches it and the
            # resume fast path below fires ACROSS processes. Same
            # best-effort posture as the plain demotion.
            from ..utils import knobs
            if knobs.get_bool("SRJT_OOC_DURABLE_CHECKPOINTS"):
                try:
                    ckpt.spill(to_disk=True)
                except (ValueError, RetryableError, OSError):
                    pass
            else:
                demote(ckpt)

        prefetcher: Optional[threading.Thread] = None
        # ONE plan-level admission sized to the per-partition peak for
        # the whole streamed run: nested admissions (hash_partition's op
        # boundary, each partition sub-plan) skip under the outermost-
        # only discipline, so the degraded query claims the footprint it
        # actually streams — the whole-plan estimate could never be
        # admitted (that is why this strategy was selected)
        _durable_admit = memgov.admit(f"plan.{inner.name}.ooc",
                                      nbytes=self.partition_memory_bytes)
        _tls.active = True
        try:
            # partition the source once up front (skipping any partition
            # a prior attempt already checkpointed — the resume fast
            # path)
            deadline.check("plan.ooc.partition_inputs")
            have_ckpt = [cat.lookup(self._part_key(i)) is not None
                         for i in range(parts)]
            if not all(have_ckpt):
                reordered, offsets = hash_partition(src, parts, key_cols)
                n = reordered.num_rows
                first_pending = have_ckpt.index(False)
                for i in range(parts):
                    if have_ckpt[i] or cat.lookup(self._in_key(i)) is not None:
                        continue
                    lo = offsets[i]
                    hi = offsets[i + 1] if i + 1 < parts else n
                    h = cat.register(self._in_key(i),
                                     slice_table(reordered, lo, hi),
                                     kind="partition")
                    built_inputs.add(i)
                    # partitions at rest demote off-device; the first
                    # pending one stays resident — it runs next
                    if i != first_pending:
                        demote(h)
                del reordered

            for i in range(parts):
                deadline.check(f"plan.ooc.partition[{i}]")
                faultinj.maybe_inject("plan.ooc.partition")
                if prefetch_on and i + 1 < parts:
                    prefetcher = threading.Thread(
                        target=warm, args=(i + 1, pool), daemon=True,
                        name=f"srjt-ooc-prefetch-{i + 1}",
                    )
                    prefetcher.start()
                if cat.lookup(self._part_key(i)) is not None:
                    # a prior attempt's checkpoint: resume past it (the
                    # partial is fetched — and integrity-checked — at
                    # merge; a rotted one lineage-recomputes there)
                    resumes += 1
                    reg.counter("ooc.partition_resumes").inc()
                    metrics.event("plan.ooc.resume", query=inner.name,
                                  partition=i)
                else:
                    compute_partition(i)
                if prefetcher is not None:
                    prefetcher.join(timeout=60.0)
                    prefetcher = None
            deadline.check("plan.ooc.merge")
            partials = []
            for i in range(parts):
                h = cat.lookup(self._part_key(i))
                if h is not None:
                    try:
                        partials.append(h.get())
                        continue
                    except DataCorruption:
                        recomputes += 1
                        reg.counter("ooc.lineage_recomputes").inc()
                        metrics.event("plan.ooc.recompute",
                                      query=inner.name, partition=i)
                # checkpoint missing or rotted: recompute the hole
                compute_partition(i)
                partials.append(cat.lookup(self._part_key(i)).get())
            merged = merge_partials(partials,
                                    list(self._target.sort.keys))
        except BaseException as e:
            if isinstance(e, RetryableError):
                # keep the completed-partition checkpoints — a retried
                # call resumes from them; inputs are recomputable from
                # lineage and must not outlive the attempt
                self._release(cat, inputs=True, partials=False)
            else:
                # cancel/deadline/fatal: the query is over — release
                # every partition catalog entry (the conftest leak
                # assertion covers kind="partition")
                self._release(cat, inputs=True, partials=True)
            raise
        finally:
            _tls.active = False
            if prefetcher is not None:
                prefetcher.join(timeout=60.0)
            if _durable_admit is not None:
                _durable_admit.release()
        self._release(cat, inputs=True, partials=True)
        wall = time.perf_counter() - t0
        spills = (reg.counter("memgov.spills").value
                  + reg.counter("memgov.disk_spills").value) - spills0
        self.last_report = {
            "query": inner.name,
            "ooc": True,
            "partitions": parts,
            "resumes": resumes,
            "lineage_recomputes": recomputes,
            "spills": spills,
            "wall_s": wall,
            "est_peak_bytes": inner.estimated_memory_bytes,
            "partition_peak_bytes": self.partition_memory_bytes,
        }
        metrics.event("plan.ooc.run", **self.last_report)
        path = knobs.get_str("SRJT_OOC_METRICS")
        if path:
            with open(path, "a") as f:
                f.write(json.dumps(self.last_report) + "\n")
        return merged

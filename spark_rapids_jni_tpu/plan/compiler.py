"""Plan compiler: optimized logical plan -> executable stages (srjt-plan).

The Flare thesis (arxiv 1703.08219) applied to this engine: the hot
scan->join*->filter->project->aggregate region of a query should run as
ONE compiled program, not operator-at-a-time. The compiler walks the
optimized plan and, at every ``Aggregate``, tries to FUSE its input
chain into the same ``pipeline.CompiledPipeline`` the hand-built greens
use — star joins become ``JoinSpec``s (dense bounded-domain when the
``Join.bounded`` hint is set, sort-merge otherwise; a build side that is
itself a subplan is materialized at call time and joined sort-merge),
filters conjoin into the fused mask, projections become fused
projections, and bounded group-key domains are scanned host-side from
the bound tables exactly as the hand-built queries did. Everything the
fused grammar cannot express — fact-fact set ops, post-aggregate joins,
windows, sorts, unions — lowers to the tested ``ops/`` operators over
the (small) intermediate tables.

Estimates (Theseus, arxiv 2508.05029: the plan is where data-movement /
memory decisions belong): every stage carries ``rows``/``bytes``
estimates derived from schema width x bound-table cardinalities at
compile time. The whole-plan peak feeds ``memgov`` admission when the
governor is armed (``CompiledPlan.estimated_memory_bytes`` — the same
``memory_bytes=`` contract the serve scheduler's pre-admission uses),
and after every run the per-stage estimate-vs-actual pairs are recorded
(``last_report``; appended to the ``SRJT_PLAN_REPORT`` JSONL when set)
so CI can gate estimate blowups.

Engine dtype contract (mirrored by ``nodes.infer_schema``): aggregate
outputs materialize as INT64 (counts) / FLOAT64 (everything else) on
BOTH tiers — the operator tier normalizes to the fused pipeline's
``_wrap_result`` convention so a plan's schema never depends on which
tier a stage landed on.
"""

from __future__ import annotations

import json
import math
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..columnar.dtype import DType, TypeId
from ..utils import knobs, metrics
from .exprs import PExpr, PlanError, conjoin, is_col, is_null_lit
from .nodes import (
    Aggregate,
    Exchange,
    Filter,
    Join,
    Limit,
    Node,
    Project,
    Scan,
    Sort,
    UnionAll,
    Window,
    infer_schema,
)
from .rewrites import rewrite
from .stats.model import calibration_factor


def _durable(name: str):
    """Registry-direct counter (always-on, like serve's shed accounting)
    so the compiler tier can be metrics-asserted without arming the
    event log."""
    return metrics.registry().counter(name)

__all__ = ["CompiledPlan", "compile_ir", "lower_ir"]

Schema = Dict[str, DType]

_FUSED_AGGS = ("sum", "count", "count_all", "min", "max", "mean")
_FILTER_SELECTIVITY = 0.5  # conservative: only UNDERestimates are gated
_MAX_DENSE_GROUPS = 1 << 22


def _width(schema: Schema) -> int:
    total = 0
    for d in schema.values():
        # +1: the per-row validity lane. The archived r6 estimate-vs-
        # actual reports (artifacts/plan_compile.jsonl) showed the
        # value-only width UNDERestimating every nullable narrow table
        # by up to 1.25x (a lone INT32 column is 5 bytes/row with its
        # bool mask, not 4) — the one systematic drift in the gated
        # direction, and what let premerge tighten the blowup gate to 3x
        total += (d.size_bytes if d.is_fixed_width else 16) + 1
    return max(total, 1)


def _table_nbytes(t: Table) -> int:
    import jax

    return sum(int(getattr(leaf, "nbytes", 0))
               for leaf in jax.tree_util.tree_leaves(t))


def _eval_expr(e: PExpr, table: Table, want: DType) -> Column:
    """Evaluate a lowered plan expression, broadcasting a scalar result
    (bare literal projection) to the table's row count and pinning the
    inferred dtype for typed null literals."""
    n_rows = table.num_rows
    if is_null_lit(e):
        # typed SQL NULL: materialize at the DECLARED dtype — the
        # runtime literal tier evaluates NULL as INT32 lanes, which
        # would silently contradict the inferred schema for FLOAT64
        # (or any non-int) rolled keys in a grouping-set union
        if not want.is_fixed_width:
            raise PlanError(f"cannot materialize a NULL literal as {want!r}")
        shape = (n_rows, 4) if want.id == TypeId.DECIMAL128 else (n_rows,)
        return Column(want, data=jnp.zeros(shape, want.jnp_dtype),
                      validity=jnp.zeros((n_rows,), bool))
    c = e.lower().evaluate(table)
    n = table.num_rows
    if c.data.ndim == 0:
        data = jnp.broadcast_to(c.data, (n,))
        v = None if c.validity is None else jnp.broadcast_to(c.validity, (n,))
        c = Column(c.dtype, data=data, validity=v)
    elif len(c) != n:
        raise PlanError(f"projection produced {len(c)} rows for {n}")
    if c.dtype.id != want.id and c.dtype.is_integral and want.is_integral:
        c = Column(want, data=c.data.astype(want.jnp_dtype), validity=c.validity)
    elif c.dtype.id != want.id and want.id == TypeId.BOOL8:
        c = Column(dt.BOOL8, data=c.data.astype(jnp.uint8), validity=c.validity)
    return c


def _normalize_agg_column(col: Column, how: str) -> Column:
    """Bring an operator-tier aggregate column onto the fused tier's
    materialization contract (counts INT64, everything else FLOAT64
    bit-lanes) so schema inference holds regardless of tier."""
    if how in ("count", "count_all", "nunique"):
        return col
    if col.dtype.id == TypeId.FLOAT64:
        return col
    from ..ops import bitutils
    from ..ops.f64acc import i64_to_f64bits

    if col.dtype.is_integral:
        return Column(dt.FLOAT64, data=i64_to_f64bits(col.data.astype(jnp.int64)),
                      validity=col.validity)
    if col.dtype.id == TypeId.FLOAT32:
        x = col.data.astype(jnp.float64) if bitutils.backend_has_f64() else col.data
        return Column(dt.FLOAT64, data=bitutils.float_store(x, dt.FLOAT64),
                      validity=col.validity)
    raise PlanError(f"cannot normalize {how} over {col.dtype!r}")


class _RunContext:
    """One execution of a compiled plan: node-result memoization (shared
    CTE subtrees run once) + per-stage actual byte accounting. Actuals
    live HERE, not on the shared _Exec objects — one CompiledPlan may
    be running on several serve slots at once, and per-run state on the
    stage objects would tear the estimate-vs-actual report."""

    __slots__ = ("tables", "cache", "actuals", "subcache")

    def __init__(self, tables: Dict[str, Table], subcache=None):
        self.tables = tables
        self.cache: Dict[int, Table] = {}
        self.actuals: Dict[int, Tuple[int, int]] = {}  # exec id -> (rows, bytes)
        # srjt-cache (ISSUE 17): the cross-run subresult cache, or None
        # when caching is off — stages annotated with a ``cache_key``
        # consult it before recomputing
        self.subcache = subcache


class _Exec:
    """One lowered stage: knows its schema, estimates, and inputs."""

    kind = "?"

    # srjt-cache (ISSUE 17): set once at annotation time (before any
    # concurrent run) on stages whose subtree result is cacheable; the
    # key pins (parameterized structure, literal bindings, table
    # generations), so a stale entry is unreachable by construction
    cache_key = None

    def __init__(self, schema: Schema, est_rows: int, inputs: List["_Exec"]):
        self.schema = schema
        self.est_rows = max(int(est_rows), 1)
        self.inputs = inputs
        # srjt-cbo (ISSUE 19): byte estimates carry the per-kind factor
        # learned from archived estimate-vs-actual reports (neutral 1.0
        # on a fresh checkout, clamped to [0.5, 2x]); the floor keeps
        # the verifier's est_bytes >= est_rows invariant under any factor
        self.est_bytes = max(self.est_rows,
                             int(self.est_rows * _width(schema)
                                 * calibration_factor(self.kind)))

    def run(self, ctx: _RunContext) -> Table:
        key = id(self)
        if key in ctx.cache:
            return ctx.cache[key]
        if ctx.subcache is not None and self.cache_key is not None:
            out = ctx.subcache.lookup_or_compute(
                self.cache_key, lambda: self._run(ctx))
        else:
            out = self._run(ctx)
        ctx.actuals[key] = (out.num_rows, _table_nbytes(out))
        ctx.cache[key] = out
        return out

    def _run(self, ctx: _RunContext) -> Table:
        raise NotImplementedError

    def working_set_est(self) -> int:
        return self.est_bytes + sum(i.est_bytes for i in self.inputs)

    def working_set_actual(self, actuals: Dict[int, Tuple[int, int]]) -> Optional[int]:
        mine = actuals.get(id(self))
        if mine is None:
            return None
        parts = [mine[1]]
        for i in self.inputs:
            got = actuals.get(id(i))
            if got is not None:
                parts.append(got[1])
        return sum(parts)


class _ScanExec(_Exec):
    kind = "scan"

    def __init__(self, node: Scan, schema: Schema, tables):
        super().__init__(schema, tables[node.table].num_rows, [])
        self.table = node.table
        self.columns = list(schema.keys())

    def _run(self, ctx):
        return ctx.tables[self.table].select(self.columns)


class _FilterExec(_Exec):
    kind = "filter"

    def __init__(self, node: Filter, schema: Schema, child: _Exec,
                 est_rows: Optional[int] = None):
        if est_rows is None:
            est_rows = math.ceil(child.est_rows * _FILTER_SELECTIVITY)
        super().__init__(schema, min(est_rows, child.est_rows), [child])
        self.pred = node.predicate

    def _run(self, ctx):
        from ..ops import copying

        t = self.inputs[0].run(ctx)
        mask = self.pred.lower().evaluate(t)
        return copying.apply_boolean_mask(t, mask)


class _ProjectExec(_Exec):
    kind = "project"

    def __init__(self, node: Project, schema: Schema, child: _Exec):
        super().__init__(schema, child.est_rows, [child])
        self.exprs = node.exprs

    def _run(self, ctx):
        t = self.inputs[0].run(ctx)
        cols = [_eval_expr(e, t, self.schema[name]) for name, e in self.exprs]
        return Table(cols, [name for name, _ in self.exprs])


class _JoinExec(_Exec):
    kind = "join"

    def __init__(self, node: Join, schema: Schema, left: _Exec, right: _Exec,
                 est_rows: Optional[int] = None):
        if est_rows is None:
            est_rows = (left.est_rows + right.est_rows if node.how == "full"
                        else left.est_rows)
        super().__init__(schema, est_rows, [left, right])
        self.on = node.on
        self.how = node.how

    def _run(self, ctx):
        from ..ops import join as join_ops

        left = self.inputs[0].run(ctx)
        right = self.inputs[1].run(ctx)
        lnames = [l for l, _ in self.on]
        rename = {r: l for l, r in self.on}
        right = Table(list(right.columns),
                      [rename.get(n, n) for n in right.names])
        fn = {
            "inner": join_ops.inner_join,
            "left": join_ops.left_join,
            "full": join_ops.full_join,
            "semi": join_ops.left_semi_join,
            "anti": join_ops.left_anti_join,
        }[self.how]
        out = fn(left, right, on=lnames)
        return out.select(list(self.schema.keys()))


class _ExchangeExec(_Exec):
    """Hash-repartition across the bound exchange fabric (ISSUE 16).
    Unbound — no ``plan.distribute.exchange_context`` in scope — or at
    ``world == 1`` this stage is the identity, so one compiled plan
    serves both the single-host oracle and every rank of the
    distributed run. With a cluster + shard catalog bound, the stage
    installs its child subtree as the dead-rank lineage reproducer
    right before moving rows: recovery replays exactly the lowered
    code that produced the lost input."""

    kind = "exchange"

    def __init__(self, node: Exchange, schema: Schema, child: _Exec):
        super().__init__(schema, child.est_rows, [child])
        self.keys = node.keys
        self.world = node.world

    def _run(self, ctx):
        from .distribute import current_binding

        t = self.inputs[0].run(ctx)
        binding = current_binding()
        if binding is None or self.world <= 1:
            return t
        if binding.world != self.world:
            raise PlanError(
                f"exchange stage compiled for world {self.world} bound to "
                f"a {binding.world}-rank fabric")
        if binding.cluster is not None and binding.shard_tables is not None:
            child = self.inputs[0]
            shards = binding.shard_tables
            binding.cluster.set_lineage(
                lambda r: child.run(_RunContext(shards(r))))
        return binding.exchange.exchange_table(
            t, list(self.keys), binding.peers,
            epoch=binding.stage_epoch(id(self)), cluster=binding.cluster,
        )


class _AggExec(_Exec):
    """Operator-tier grouped/global aggregation (the general fallback:
    arbitrary key dtypes, var/std/nunique, DISTINCT)."""

    kind = "aggregate"

    def __init__(self, node: Aggregate, schema: Schema, child: _Exec,
                 est_rows: Optional[int] = None):
        super().__init__(schema, child.est_rows if est_rows is None else est_rows,
                         [child])
        self.keys = node.keys
        self.aggs = node.aggs

    def _run(self, ctx):
        from ..ops.aggregate import groupby_aggregate

        t = self.inputs[0].run(ctx)
        n = t.num_rows
        if not self.keys and n == 0:
            # SQL global aggregates yield ONE row on empty input (the
            # fused tier does; the sort-based kernel yields zero groups)
            cols, names = [], []
            for a in self.aggs:
                if a.how in ("count", "count_all", "nunique"):
                    cols.append(Column(dt.INT64, data=jnp.zeros((1,), jnp.int64)))
                else:
                    cols.append(Column(
                        dt.FLOAT64, data=jnp.zeros((1,), jnp.uint64),
                        validity=jnp.zeros((1,), bool),
                    ))
                names.append(a.name)
            return Table(cols, names)
        if self.keys:
            keys_tbl = t.select(list(self.keys))
        else:
            keys_tbl = Table(
                [Column(dt.INT32, data=jnp.zeros((n,), jnp.int32))], ["__g"]
            )
        spec = []
        for a in self.aggs:
            src = a.source if a.source is not None else (
                self.keys[0] if self.keys else t.names[0]
            )
            spec.append((src, a.how, a.name))
        values = t
        agg = groupby_aggregate(keys_tbl, values, [(s, h) for s, h, _ in spec])
        # groupby_aggregate names outputs {src}_{how} in order after the
        # keys; rebind positionally to the AggSpec names and normalize
        # onto the fused materialization contract
        nk = keys_tbl.num_columns
        out_cols: List[Column] = []
        out_names: List[str] = []
        if self.keys:
            for i, k in enumerate(self.keys):
                out_cols.append(agg.column(i))
                out_names.append(k)
        for j, (_, how, name) in enumerate(spec):
            out_cols.append(_normalize_agg_column(agg.column(nk + j), how))
            out_names.append(name)
        return Table(out_cols, out_names)


class _FusedAggExec(_Exec):
    """The fused tier: one ``CompiledPipeline`` dispatch for the whole
    join*->filter->project->aggregate stage. ``builds`` maps build name
    -> either a compile-time Table (direct dim build) or an _Exec run at
    call time (materialized subplan build)."""

    kind = "fused_aggregate"

    def __init__(self, schema: Schema, pipeline, fact: _Exec,
                 builds: Dict[str, object], est_rows: int,
                 out_names: List[str]):
        build_execs = [b for b in builds.values() if isinstance(b, _Exec)]
        super().__init__(schema, est_rows, [fact] + build_execs)
        self.pipeline = pipeline
        self.builds = builds
        self.out_names = out_names
        self._static_build_bytes = sum(
            _table_nbytes(b) for b in builds.values() if isinstance(b, Table)
        )
        self.est_bytes += self._static_build_bytes

    def _run(self, ctx):
        fact = self.inputs[0].run(ctx)
        builds = {}
        for name, b in self.builds.items():
            builds[name] = b.run(ctx) if isinstance(b, _Exec) else b
        out = self.pipeline(fact, builds)
        _durable("plan.fused_dispatches").inc()
        return Table(list(out.columns), self.out_names)


class _WindowExec(_Exec):
    kind = "window"

    def __init__(self, node: Window, schema: Schema, child: _Exec):
        super().__init__(schema, child.est_rows, [child])
        self.node = node

    def _run(self, ctx):
        from ..ops.window import window_aggregate

        t = self.inputs[0].run(ctx)
        return window_aggregate(
            t, list(self.node.partition_by), list(self.node.order_by),
            list(self.node.aggs),
        )


class _SortExec(_Exec):
    kind = "sort"

    def __init__(self, node: Sort, schema: Schema, child: _Exec):
        super().__init__(schema, child.est_rows, [child])
        self.keys = node.keys

    def _run(self, ctx):
        from ..ops.sort import sort_by_key

        t = self.inputs[0].run(ctx)
        keys = Table([t.column(c) for c, _ in self.keys],
                     [f"k{i}" for i in range(len(self.keys))])
        return sort_by_key(t, keys, ascending=[asc for _, asc in self.keys])


class _LimitExec(_Exec):
    kind = "limit"

    def __init__(self, node: Limit, schema: Schema, child: _Exec):
        super().__init__(schema, min(child.est_rows, node.n), [child])
        self.n = node.n

    def _run(self, ctx):
        from ..ops import copying

        t = self.inputs[0].run(ctx)
        return copying.slice_table(t, 0, min(self.n, t.num_rows))


class _UnionExec(_Exec):
    kind = "union_all"

    def __init__(self, schema: Schema, children: List[_Exec]):
        super().__init__(schema, sum(c.est_rows for c in children), children)

    def _run(self, ctx):
        from ..ops import copying

        names = list(self.schema.keys())
        parts = [c.run(ctx).select(names) for c in self.inputs]
        return copying.concatenate(parts)


# ---------------------------------------------------------------------------
# fused-stage detection
# ---------------------------------------------------------------------------


class _Bail(Exception):
    """Internal: this aggregate does not fit the fused grammar — fall
    back to the operator tier (never an error)."""


def _int_domain(col: Column) -> Optional[int]:
    """[0, num) bounded domain of an integer column (host scan at bind
    time, the same sync the hand-built queries pay), or None when the
    column is empty/negative/non-integral."""
    if not col.dtype.is_integral:
        return None
    if len(col) == 0:
        return 1
    lo = int(jnp.min(col.data))
    if lo < 0:
        return None
    return int(jnp.max(col.data)) + 1


class _Fuser:
    """Pattern-match one Aggregate's input chain onto a PlanSpec."""

    def __init__(self, lowerer: "_Lowerer", agg: Aggregate):
        self.low = lowerer
        self.agg = agg
        self.joins: List[Join] = []
        self.filters: List[PExpr] = []
        self.project: Optional[Project] = None
        self.fact: Optional[Scan] = None

    def _walk(self, n: Node, under_join: bool) -> None:
        if isinstance(n, Project) and all(
            is_col(e) == name for name, e in n.exprs
        ):
            # passthrough-only narrowing (pruning inserts these): a
            # no-op for the fused working schema at any depth
            self._walk(n.input, under_join)
        elif isinstance(n, Project) and not under_join:
            if self.project is not None:
                raise _Bail("stacked projects")
            self.project = n
            self._walk(n.input, under_join)
        elif isinstance(n, Filter):
            self.filters.append(n.predicate)
            self._walk(n.input, True)
        elif isinstance(n, Join):
            if n.how not in ("inner", "semi", "anti") or len(n.on) != 1:
                raise _Bail("join shape")
            self._walk(n.left, True)
            self.joins.append(n)
        elif isinstance(n, Scan):
            if self.fact is not None:
                raise _Bail("two facts")
            self.fact = n
        else:
            raise _Bail(type(n).__name__)

    def try_build(self) -> Optional[_FusedAggExec]:
        from ..pipeline import Agg as PAgg
        from ..pipeline import GroupKey, JoinSpec, PlanSpec, compile_plan

        agg = self.agg
        if agg.grouping_sets is not None or not agg.aggs:
            return None
        if any(a.how not in _FUSED_AGGS for a in agg.aggs):
            return None
        try:
            self._walk(agg.input, False)
        except _Bail:
            return None
        if self.fact is None:
            return None
        fact_schema = self.low.schema_of(self.fact)

        # the working schema the pipeline sees: fact columns + payloads
        work: Dict[str, str] = {c: self.fact.table for c in fact_schema}
        specs: List[JoinSpec] = []
        builds: Dict[str, object] = {}
        try:
            for idx, j in enumerate(self.joins):
                spec, bname, build = self._build_side(j, work, idx)
                if bname in builds:
                    return None  # duplicate build name (self-join w/o alias)
                specs.append(spec)
                builds[bname] = build
                if j.how == "inner":
                    for pname in spec.payload:
                        work[pname] = bname
        except _Bail:
            return None

        # projections: passthrough names stay; computed exprs fuse
        proj_entries: List[Tuple[str, object]] = []
        visible = set(work)
        key_source: Dict[str, str] = {}
        if self.project is not None:
            visible = set()
            for name, e in self.project.exprs:
                src = is_col(e)
                if src is not None and src == name:
                    visible.add(name)
                    key_source[name] = name
                else:
                    proj_entries.append((name, e))
                    visible.add(name)
        else:
            key_source = {c: c for c in work}

        # group keys: un-projected INT32 columns with scannable domains
        gks: List[GroupKey] = []
        domain_product = 1
        for k in agg.keys:
            src = key_source.get(k)
            if src is None or src not in work:
                return None
            owner = work[src]
            src_col = self._owner_column(owner, src, builds)
            if src_col is None or src_col.dtype.id != TypeId.INT32:
                return None
            num = _int_domain(src_col)
            if num is None:
                return None
            domain_product *= num
            if domain_product > _MAX_DENSE_GROUPS:
                return None
            gks.append(GroupKey(k, num))

        # aggregate sources must be visible post-project
        if not fact_schema:
            return None
        paggs = []
        for a in agg.aggs:
            src = a.source
            if a.how == "count_all":
                src = next(iter(fact_schema))
            if src not in visible:
                return None
            paggs.append(PAgg(src, a.how, a.name))

        filt = None
        if self.filters:
            filt = conjoin(self.filters).lower()
        spec = PlanSpec(
            joins=tuple(specs),
            filter=filt,
            project=tuple((n, e.lower()) for n, e in proj_entries),
            group_by=tuple(gks),
            aggregates=tuple(paggs),
        )
        out_schema = self.low.schema_of(agg)
        out_names = list(out_schema.keys())
        est_rows = min(self.low.exec_of(self.fact).est_rows,
                       domain_product if gks else 1)
        if self.low.est is not None and gks:
            # sketch ndv product is usually tighter than the dense
            # key-domain product (domains count holes, ndv does not)
            est_rows = min(est_rows, self.low.est.agg_rows(
                self.low.exec_of(self.fact).est_rows, agg.keys))
        pipeline = compile_plan(spec)
        fact_exec = self.low.exec_of(self.fact)
        _durable("plan.fused_stages").inc()
        return _FusedAggExec(out_schema, pipeline, fact_exec, builds,
                             est_rows, out_names)

    def _owner_column(self, owner: str, name: str, builds) -> Optional[Column]:
        """The bind-time column backing a group key: a fact column or a
        DIRECT build's payload column (materialized builds have no
        bind-time data to scan)."""
        if owner == self.fact.table:
            return self.low.tables[self.fact.table].column(name)
        b = builds.get(owner)
        if isinstance(b, Table) and name in b.names:
            return b.column(name)
        return None

    def _build_side(self, j: Join, work, idx: int) -> Tuple[object, str, object]:
        """Lower one join's right side: a Scan (+Filter) reduces to a
        compile-time build table + fused build_filter; anything else
        materializes its subplan at call time (sort-merge)."""
        from ..pipeline import JoinSpec

        probe, bkey = j.on[0]
        if probe not in work:
            raise _Bail("probe key not in working schema")
        right = j.right
        rschema = self.low.schema_of(right)
        payload = tuple(n for n in rschema if n != bkey) if j.how == "inner" else ()
        for pname in payload:
            d = rschema[pname]
            if not d.is_fixed_width or d.id == TypeId.DECIMAL128:
                raise _Bail("payload dtype")

        pred = None
        cur = right
        if isinstance(cur, Project) and all(
            is_col(e) == name for name, e in cur.exprs
        ):
            cur = cur.input  # pruning's narrowing wrapper
        if isinstance(cur, Filter):
            pred = cur.predicate
            cur = cur.input
        if isinstance(cur, Scan):
            bname = cur.key
            bt = self.low.tables[cur.table]
            needed = [bkey] + [p for p in payload if p != bkey]
            if pred is not None:
                needed += [r for r in pred.refs() if r not in needed]
            for c in needed:
                if c not in bt.names:
                    raise _Bail("build column missing")
            build_tbl = bt.select(needed)
            num_keys = None
            if j.bounded:
                num_keys = _int_domain(build_tbl.column(bkey))
                if num_keys is None:
                    raise _Bail("unbounded build key domain")
            spec = JoinSpec(
                build=bname, probe_key=probe, build_key=bkey,
                num_keys=num_keys, payload=payload, how=j.how,
                build_filter=None if pred is None else pred.lower(),
            )
            return spec, bname, build_tbl
        # materialized build: run the subplan, join sort-merge
        bexec = self.low.lower(right)
        bname = f"__build_{idx}_{bkey}"
        spec = JoinSpec(build=bname, probe_key=probe, build_key=bkey,
                        num_keys=None, payload=payload, how=j.how)
        return spec, bname, bexec


# ---------------------------------------------------------------------------
# the lowerer
# ---------------------------------------------------------------------------


class _Lowerer:
    def __init__(self, tables: Dict[str, Table], catalog: Dict[str, Schema],
                 est=None):
        self.tables = tables
        self.catalog = catalog
        # srjt-cbo (ISSUE 19): sketch-backed stats.Estimator, or None —
        # stages then keep the original hand-tuned row heuristics
        self.est = est
        self._schemas: dict = {}
        self._execs: Dict[int, _Exec] = {}
        self.all_execs: List[_Exec] = []

    def schema_of(self, node: Node) -> Schema:
        return infer_schema(node, self.catalog, self._schemas)

    def exec_of(self, node: Node) -> _Exec:
        return self.lower(node)

    def lower(self, node: Node) -> _Exec:
        key = id(node)
        if key in self._execs:
            return self._execs[key]
        ex = self._lower(node)
        self._execs[key] = ex
        if ex not in self.all_execs:
            self.all_execs.append(ex)
        return ex

    def _lower(self, node: Node) -> _Exec:
        schema = self.schema_of(node)
        if isinstance(node, Scan):
            return _ScanExec(node, schema, self.tables)
        if isinstance(node, Filter):
            child = self.lower(node.input)
            rows = (self.est.filter_rows(child.est_rows, node.predicate)
                    if self.est is not None else None)
            return _FilterExec(node, schema, child, est_rows=rows)
        if isinstance(node, Project):
            return _ProjectExec(node, schema, self.lower(node.input))
        if isinstance(node, Join):
            left = self.lower(node.left)
            right = self.lower(node.right)
            rows = (self.est.join_rows(node.how, left.est_rows,
                                       right.est_rows, node.on)
                    if self.est is not None else None)
            return _JoinExec(node, schema, left, right, est_rows=rows)
        if isinstance(node, Aggregate):
            fused = _Fuser(self, node).try_build()
            if fused is not None:
                self.all_execs.append(fused)
                return fused
            _durable("plan.ops_stages").inc()
            child = self.lower(node.input)
            rows = (self.est.agg_rows(child.est_rows, node.keys)
                    if self.est is not None else None)
            return _AggExec(node, schema, child, est_rows=rows)
        if isinstance(node, Exchange):
            return _ExchangeExec(node, schema, self.lower(node.input))
        if isinstance(node, Window):
            return _WindowExec(node, schema, self.lower(node.input))
        if isinstance(node, Sort):
            return _SortExec(node, schema, self.lower(node.input))
        if isinstance(node, Limit):
            return _LimitExec(node, schema, self.lower(node.input))
        if isinstance(node, UnionAll):
            return _UnionExec(schema, [self.lower(b) for b in node.branches])
        raise PlanError(
            f"cannot lower {type(node).__name__}: sugar nodes must be "
            "rewritten away before compilation")


# ---------------------------------------------------------------------------
# the public compile surface
# ---------------------------------------------------------------------------


def _count_nodes(node: Node) -> int:
    seen = set()

    def visit(n):
        if id(n) in seen:
            return
        seen.add(id(n))
        for i in n.inputs():
            visit(i)

    visit(node)
    return len(seen)


class CompiledPlan:
    """A bound, optimized, lowered plan. Calling it runs the query over
    the bound tables and returns the result Table. Carries the
    plan-derived ``estimated_memory_bytes`` the memory governor and the
    serve scheduler consume, and a ``last_report`` with per-stage
    estimate-vs-actual bytes after each run."""

    def __init__(self, name: str, root: _Exec, tables: Dict[str, Table],
                 stages: List[_Exec], raw_nodes: int, opt_nodes: int,
                 rewrites_fired: Dict[str, int], opt_plan: Node,
                 obligations: Optional[list] = None,
                 node_execs: Optional[Dict[int, _Exec]] = None,
                 modeled: Optional[dict] = None):
        self.name = name
        self.schema = dict(root.schema)
        self.optimized = opt_plan
        # translation-validation records from the rewrite pass, carried
        # for srjt-plancheck (plan.verifier.verify_obligations)
        self.obligations = list(obligations or ())
        self._root = root
        self._tables = tables
        self._stages = stages
        self._raw_nodes = raw_nodes
        self._opt_nodes = opt_nodes
        self._rewrites = dict(rewrites_fired)
        # srjt-cache (ISSUE 17): id(optimized node) -> lowered stage,
        # so the cache layer can annotate cacheable subtrees with their
        # keys; and the cross-run subresult cache the run context
        # consults (None = caching off). Both are set once before the
        # plan is ever run concurrently.
        self._node_execs = dict(node_execs or {})
        self.subcache = None
        # srjt-cbo (ISSUE 19): {"author": cost, "chosen": cost,
        # "joins": n} when the search ran — the premerge modeled-cost
        # gate's source; None on the cache-hit / CBO-off paths
        self.modeled = dict(modeled) if modeled else None
        self.estimated_memory_bytes = max(
            s.working_set_est() for s in stages
        )
        self.last_report: Optional[dict] = None
        _durable("plan.compiles").inc()

    def exec_for(self, node: Node) -> Optional[_Exec]:
        """The lowered stage an optimized-plan node became, when it
        lowered to a stage of its own (fused pipelines consume their
        inner nodes)."""
        return self._node_execs.get(id(node))

    @property
    def stages(self) -> list:
        """The lowered stage DAG (read-only view) — what
        ``plan.verifier.verify_estimates`` walks for the per-stage
        ``memory_bytes`` presence/monotonicity checks."""
        return list(self._stages)

    @property
    def rewrites_fired(self) -> Dict[str, int]:
        return dict(self._rewrites)

    def __call__(self) -> Table:
        from .. import memgov

        _durable("plan.executions").inc()
        admitted = 0
        adm = memgov.admit(f"plan.{self.name}", nbytes=self.estimated_memory_bytes)
        if adm is not None:
            admitted = self.estimated_memory_bytes
            _durable("plan.admit_bytes").inc(admitted)
            metrics.event("plan.admit", query=self.name, nbytes=admitted)
        try:
            ctx = _RunContext(self._tables, subcache=self.subcache)
            out = self._root.run(ctx)
        finally:
            if adm is not None:
                adm.release()
        # the report is built from THIS run's context and published as
        # one fresh dict — concurrent runs each see a coherent report
        # (last writer wins on the attribute)
        self.last_report = self._report(admitted, ctx.actuals)
        path = knobs.get_str("SRJT_PLAN_REPORT")
        if path:
            with open(path, "a") as f:
                f.write(json.dumps(self.last_report) + "\n")
        return out

    def _report(self, admitted: int, actuals: Dict[int, Tuple[int, int]]) -> dict:
        stages = []
        est_peak = self.estimated_memory_bytes
        actual_peak = 0
        for s in self._stages:
            ws = s.working_set_actual(actuals)
            if ws is not None:
                actual_peak = max(actual_peak, ws)
            mine = actuals.get(id(s))
            stages.append({
                "kind": s.kind,
                "est_rows": s.est_rows,
                "est_bytes": s.est_bytes,
                "actual_rows": None if mine is None else mine[0],
                "actual_bytes": None if mine is None else mine[1],
            })
        return {
            "query": self.name,
            "nodes_raw": self._raw_nodes,
            "nodes_optimized": self._opt_nodes,
            "rewrites": self._rewrites,
            "stages": stages,
            "fused_stages": sum(1 for s in self._stages
                                if s.kind == "fused_aggregate"),
            "est_peak_bytes": est_peak,
            "actual_peak_bytes": actual_peak,
            "peak_blowup": (actual_peak / est_peak) if est_peak else None,
            "memgov_admitted_bytes": admitted,
            "modeled_cost_author": (
                None if self.modeled is None else self.modeled["author"]),
            "modeled_cost_chosen": (
                None if self.modeled is None else self.modeled["chosen"]),
            "join_count": (
                None if self.modeled is None else self.modeled["joins"]),
        }


def compile_ir(plan: Node, tables: Dict[str, Table],
               name: str = "plan") -> CompiledPlan:
    """Validate, rewrite, and lower a logical plan against bound tables.
    The returned ``CompiledPlan`` is a zero-argument callable producing
    the result Table; submit it to ``serve`` directly (the scheduler
    derives ``memory_bytes=`` from its stage estimates)."""
    catalog = {t: {n: c.dtype for n, c in zip(tbl.names, tbl.columns)}
               for t, tbl in tables.items()}
    raw_nodes = _count_nodes(plan)
    infer_schema(plan, catalog)
    res = rewrite(plan, catalog)
    # srjt-cbo (ISSUE 19): the cost-based search runs AFTER the default
    # rewrite (so rule-idempotence of the default set is undisturbed);
    # every reorder / build-side / strategy fire lands in the same
    # obligation ledger the verifier discharges
    from . import optimizer as _cbo
    from . import stats as _stats

    opt_plan, fired, obligations = res.plan, dict(res.fired), list(res.obligations)
    modeled = None
    est = _stats.make_estimator(tables)
    if _cbo.enabled() and est is not None:
        cres = _cbo.optimize(opt_plan, catalog, tables, est=est)
        opt_plan = cres.plan
        for rule, n in cres.fired.items():
            fired[rule] = fired.get(rule, 0) + n
        obligations.extend(cres.obligations)
        modeled = {"author": cres.author_cost, "chosen": cres.chosen_cost,
                   "joins": cres.join_count}
    for rule, n in fired.items():
        _durable(f"plan.rewrites.{rule}").inc(n)
    low = _Lowerer(tables, catalog, est=est)
    root = low.lower(opt_plan)
    cp = CompiledPlan(name, root, tables, low.all_execs, raw_nodes,
                      _count_nodes(opt_plan), fired, opt_plan,
                      obligations=obligations, node_execs=low._execs,
                      modeled=modeled)
    # srjt-ooc (ISSUE 18): a plan whose peak exceeds the armed device
    # budget degrades to streamed partitioned execution instead of
    # split-retrying to failure; a no-op unless SRJT_OOC_ENABLED
    from .ooc import maybe_out_of_core

    return maybe_out_of_core(cp, tables)


def lower_ir(opt_plan: Node, tables: Dict[str, Table], name: str = "plan", *,
             raw_nodes: Optional[int] = None,
             rewrites_fired: Optional[Dict[str, int]] = None,
             obligations: Optional[list] = None) -> CompiledPlan:
    """Lower an ALREADY-OPTIMIZED plan, skipping the rewrite pass — the
    plan-cache hit path (srjt-cache, ISSUE 17): the cached entry's
    optimized structure was verifier-green at insert, so binding fresh
    literals only needs schema inference + lowering. The caller passes
    through the cached entry's rewrite tallies and obligation ledger so
    the compiled artifact stays auditable (``verify_obligations`` still
    discharges the ORIGINAL firings — a literal rebind is
    structure-preserving by construction)."""
    catalog = {t: {n: c.dtype for n, c in zip(tbl.names, tbl.columns)}
               for t, tbl in tables.items()}
    infer_schema(opt_plan, catalog)
    opt_nodes = _count_nodes(opt_plan)
    # srjt-cbo (ISSUE 19): the cache-hit path skips the SEARCH (the
    # cached structure already won it) but keeps sketch-driven row
    # estimates — admission numbers must not depend on cache hit/miss
    from . import stats as _stats

    low = _Lowerer(tables, catalog, est=_stats.make_estimator(tables))
    root = low.lower(opt_plan)
    _durable("plan.lower_only").inc()
    cp = CompiledPlan(name, root, tables, low.all_execs,
                      raw_nodes if raw_nodes is not None else opt_nodes,
                      opt_nodes, dict(rewrites_fired or {}), opt_plan,
                      obligations=obligations, node_execs=low._execs)
    # srjt-ooc (ISSUE 18): the cache-hit path re-selects out-of-core
    # per binding — the cached entry stores the UN-partitioned plan
    # (partition count is a budget decision, not plan structure)
    from .ooc import maybe_out_of_core

    return maybe_out_of_core(cp, tables)

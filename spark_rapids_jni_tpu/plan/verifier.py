"""Plan-IR verifier + per-rewrite translation validation (srjt-plancheck,
ISSUE 15).

Until this module, the ONLY evidence a rewrite pass preserved semantics
was the per-query pandas oracle — every new lower greened through the
compiler was one unchecked rewrite chain away from a silently wrong
answer. srjt-plancheck makes plan transformations first-class checked
artifacts (the Flare stance: plan-level compilation earns its speed only
when the transformations themselves are verified), in three layers:

1. **Well-formedness** (``verify_plan``): an INDEPENDENT bottom-up walk
   of the plan — every column reference resolves against its child
   schema, expression dtypes are sound (reusing ``exprs.py`` inference
   per expression, with node-level typing rules re-derived here rather
   than shared with ``nodes.infer_schema``), join/aggregate/window key
   arity and dtype contracts hold, and no sugar node (``SetOp`` /
   ``Exists`` / ``Having`` / ``CorrelatedAggFilter`` / grouping sets)
   survives when the plan claims to be past the rewrite fixpoint. The
   walk's derived schema is then CROSS-CHECKED against the production
   ``infer_schema`` — two implementations must agree, so a bug in either
   surfaces as a violation instead of propagating silently.

2. **Translation validation** (``verify_obligations``): the rewrite
   engine (``rewrites.py``) emits an ``Obligation`` record for every
   fired rule — rule name, before/after subtrees with structure
   fingerprints, and the preserved-schema witness inferred BEFORE the
   rewrite. Each obligation is discharged STRUCTURALLY by the per-rule
   checker registered in ``OBLIGATION_DISCHARGERS``: schema equality for
   every rule, plus rule-specific soundness (conjunct-multiset
   preservation and join-side legality for pushdowns, dedup/keys shape
   for set-op lowering, null-fill discipline for grouping-set expansion,
   scan-narrowing-only for pruning). An obligation that cannot be
   discharged — or that names a rule with no registered discharger — is
   a hard PLAN006 violation; ``srjt-lint`` SRJT011 statically requires
   every registered rule to carry a discharger here or a reasoned
   ``# srjt-plan: allow-unverified(<reason>)``.

3. **Estimate consistency** (``verify_estimates``): every lowered stage
   must carry a positive ``memory_bytes`` estimate that is
   monotone-consistent with its children (a filter/limit/aggregate never
   estimates MORE output rows than its input, a union estimates exactly
   the sum of its branches), and the plan-level
   ``estimated_memory_bytes`` must equal the per-stage working-set peak
   — the number memgov admission and the serve scheduler trust.

Rule catalog (reported through the shared ``analysis/lint.py`` emitters,
so ``--format=json|sarif`` and exit codes behave exactly like the other
static tools):

    PLAN001 unresolved-ref          column/table reference does not
                                    resolve against the child schema
    PLAN002 dtype-contract          expression/node dtype rules violated
                                    (non-BOOL8 predicate, non-numeric
                                    aggregate source, union/join dtype
                                    mismatch, inference cross-check
                                    disagreement)
    PLAN003 shape-contract          arity/name contracts (duplicate
                                    outputs, payload collisions, unknown
                                    how, negative limit)
    PLAN004 sugar-survives          a sugar node survived past the
                                    rewrite fixpoint
    PLAN005 estimate-inconsistency  missing/non-positive/non-monotone
                                    stage estimate, or a plan peak that
                                    disagrees with its stages
    PLAN006 undischarged-obligation a fired rewrite's obligation failed
                                    its structural discharge
    PLAN007 differential-mismatch   compiler-vs-oracle divergence found
                                    by the fuzzer (analysis/planfuzz.py)
"""

from __future__ import annotations

from collections import Counter
from typing import Callable, Dict, List, Optional

from ..columnar import dtype as dt
from ..columnar.dtype import DType, TypeId
from . import exprs as ex
from .exprs import PExpr, PlanError
from .nodes import (
    Aggregate,
    CorrelatedAggFilter,
    Exchange,
    Exists,
    Filter,
    Having,
    Join,
    Limit,
    Node,
    Project,
    Scan,
    SetOp,
    Sort,
    UnionAll,
    Window,
    infer_schema,
)

__all__ = [
    "PlanViolation",
    "verify_plan",
    "verify_obligations",
    "verify_estimates",
    "verify_for_cache",
    "OBLIGATION_DISCHARGERS",
]

Schema = Dict[str, DType]

_JOIN_HOWS = ("inner", "left", "full", "semi", "anti")
_AGG_HOWS = ("sum", "count", "count_all", "min", "max", "mean",
             "var", "std", "var_pop", "stddev_pop", "nunique")
_COUNT_AGGS = ("count", "count_all", "nunique")
_WINDOW_HOWS = ("row_number", "rank", "dense_rank", "lag", "lead", "sum",
                "mean", "min", "max", "count", "cumsum", "var", "std",
                "var_pop", "stddev_pop")


class PlanViolation:
    """One verifier finding. Attribute-compatible with
    ``analysis.lint.Violation`` so the shared text/json/sarif emitters
    render it unchanged; ``path`` carries the plan name (``plan:q1``)
    instead of a file."""

    __slots__ = ("path", "line", "rule", "message")

    def __init__(self, where: str, rule: str, message: str):
        self.path = where
        self.line = 1
        self.rule = rule
        self.message = message

    def __repr__(self):
        return f"{self.path}: {self.rule} {self.message}"


def _schema_eq(a: Schema, b: Schema) -> bool:
    return list(a) == list(b) and all(
        a[k].id == b[k].id and a[k].scale == b[k].scale for k in a
    )


def _fmt_schema(s: Optional[Schema]) -> str:
    if s is None:
        return "<unavailable>"
    return "{" + ", ".join(f"{k}: {d!r}" for k, d in s.items()) + "}"


# ---------------------------------------------------------------------------
# layer 1: well-formedness
# ---------------------------------------------------------------------------


class _Verifier:
    """Independent bottom-up schema derivation. Returns None for a node
    whose subtree already produced a violation, so one seeded defect
    reports exactly ONE finding instead of cascading up the tree (the
    gate-can-fail fixtures pin that discipline)."""

    def __init__(self, catalog: Dict[str, Schema], desugared: bool,
                 where: str):
        self.catalog = catalog
        self.desugared = desugared
        self.where = where
        self.violations: List[PlanViolation] = []
        self._memo: Dict[int, Optional[Schema]] = {}

    def flag(self, rule: str, message: str) -> None:
        self.violations.append(PlanViolation(self.where, rule, message))

    def schema(self, node: Node) -> Optional[Schema]:
        key = id(node)
        if key in self._memo:
            return self._memo[key]
        # pre-insert None so a (malformed) cyclic plan terminates
        self._memo[key] = None
        s = self._node(node)
        self._memo[key] = s
        return s

    # -- expressions --------------------------------------------------------

    def _expr(self, e: PExpr, s: Schema, what: str,
              want_bool: bool = False) -> Optional[DType]:
        missing = sorted(e.refs() - set(s))
        if missing:
            self.flag("PLAN001",
                      f"{what}: column(s) {missing} not in the child "
                      f"schema {sorted(s)}")
            return None
        try:
            d = e.dtype(s)
        except PlanError as exc:
            self.flag("PLAN002", f"{what}: expression dtype unsound: {exc}")
            return None
        if want_bool and d.id != TypeId.BOOL8:
            self.flag("PLAN002", f"{what}: predicate must be BOOL8, got {d!r}")
            return None
        return d

    def _key_pair(self, ls: Schema, rs: Schema, pair, what: str) -> bool:
        lname, rname = pair
        ok = True
        if lname not in ls:
            self.flag("PLAN001", f"{what}: left key {lname!r} not in {sorted(ls)}")
            ok = False
        if rname not in rs:
            self.flag("PLAN001", f"{what}: right key {rname!r} not in {sorted(rs)}")
            ok = False
        if not ok:
            return False
        ld, rd = ls[lname], rs[rname]
        if not ((ld.id == rd.id) or (ld.is_integral and rd.is_integral)):
            self.flag("PLAN002", f"{what}: key dtypes incompatible: "
                      f"{lname}:{ld!r} vs {rname}:{rd!r}")
            return False
        return True

    def _agg_out(self, s: Schema, a, what: str) -> Optional[DType]:
        if a.how not in _AGG_HOWS:
            self.flag("PLAN003", f"{what}: unknown aggregate {a.how!r}")
            return None
        if a.how == "count_all":
            return dt.INT64
        if a.source not in s:
            self.flag("PLAN001",
                      f"{what}: aggregate source {a.source!r} not in {sorted(s)}")
            return None
        d = s[a.source]
        if a.how in _COUNT_AGGS:
            return dt.INT64
        if not (d.is_integral or d.is_floating):
            self.flag("PLAN002",
                      f"{what}: {a.how} needs a numeric source, got {d!r}")
            return None
        return dt.FLOAT64

    # -- nodes --------------------------------------------------------------

    def _node(self, node: Node) -> Optional[Schema]:
        if isinstance(node, Scan):
            if node.table not in self.catalog:
                self.flag("PLAN001", f"scan of unknown table {node.table!r}; "
                          f"catalog has {sorted(self.catalog)}")
                return None
            base = self.catalog[node.table]
            if node.columns is None:
                return dict(base)
            out: Schema = {}
            bad = [c for c in node.columns if c not in base]
            if bad:
                self.flag("PLAN001",
                          f"scan {node.key}: column(s) {bad} not in table "
                          f"{node.table!r}")
                return None
            for c in node.columns:
                out[c] = base[c]
            return out

        if isinstance(node, Filter):
            s = self.schema(node.input)
            if s is None:
                return None
            if self._expr(node.predicate, s, "filter", want_bool=True) is None:
                return None
            return dict(s)

        if isinstance(node, Project):
            s = self.schema(node.input)
            if s is None:
                return None
            out = {}
            for name, e in node.exprs:
                if name in out:
                    self.flag("PLAN003",
                              f"project: duplicate output name {name!r}")
                    return None
                d = self._expr(e, s, f"project output {name!r}")
                if d is None:
                    return None
                out[name] = d
            return out

        if isinstance(node, Join):
            ls = self.schema(node.left)
            rs = self.schema(node.right)
            if ls is None or rs is None:
                return None
            if node.how not in _JOIN_HOWS:
                self.flag("PLAN003", f"join: unknown how {node.how!r}")
                return None
            if not node.on:
                self.flag("PLAN003", "join: no key pairs")
                return None
            for pair in node.on:
                if not self._key_pair(ls, rs, pair, f"{node.how} join"):
                    return None
            if node.how in ("semi", "anti"):
                return dict(ls)
            rkeys = {r for _, r in node.on}
            out = dict(ls)
            for name, d in rs.items():
                if name in rkeys:
                    continue
                if name in out:
                    self.flag("PLAN003",
                              f"join: build column {name!r} collides with "
                              "the probe schema")
                    return None
                out[name] = d
            return out

        if isinstance(node, Aggregate):
            s = self.schema(node.input)
            if s is None:
                return None
            if node.grouping_sets is not None and self.desugared:
                self.flag("PLAN004",
                          "grouping sets survived the rewrite fixpoint "
                          "(expand_grouping_sets never fired?)")
                # fall through: type it as a plain aggregate so the
                # finding stays exactly one
            out: Schema = {}
            for k in node.keys:
                if k not in s:
                    self.flag("PLAN001",
                              f"aggregate key {k!r} not in {sorted(s)}")
                    return None
                out[k] = s[k]
            for a in node.aggs:
                if a.name in out:
                    self.flag("PLAN003",
                              f"aggregate: duplicate output {a.name!r}")
                    return None
                d = self._agg_out(s, a, "aggregate")
                if d is None:
                    return None
                out[a.name] = d
            if node.grouping_sets is not None:
                for gs in node.grouping_sets:
                    extra = set(gs) - set(node.keys)
                    if extra:
                        self.flag("PLAN003",
                                  f"grouping set {gs} not a subset of the "
                                  f"keys: {sorted(extra)}")
                        return None
            return out

        if isinstance(node, Window):
            s = self.schema(node.input)
            if s is None:
                return None
            for c in node.partition_by:
                if c not in s:
                    self.flag("PLAN001",
                              f"window partition key {c!r} not in {sorted(s)}")
                    return None
            for c, _ in node.order_by:
                if c not in s:
                    self.flag("PLAN001",
                              f"window order key {c!r} not in {sorted(s)}")
                    return None
            out = dict(s)
            for src, how, name in node.aggs:
                if how not in _WINDOW_HOWS:
                    self.flag("PLAN003", f"window: unknown function {how!r}")
                    return None
                if src not in s:
                    self.flag("PLAN001",
                              f"window source {src!r} not in {sorted(s)}")
                    return None
                if name in out:
                    self.flag("PLAN003", f"window output {name!r} collides")
                    return None
                out[name] = self._window_dtype(s[src], how)
            return out

        if isinstance(node, Exchange):
            s = self.schema(node.input)
            if s is None:
                return None
            if node.world < 1:
                self.flag("PLAN003",
                          f"exchange: world must be >= 1 ({node.world})")
                return None
            for c in node.keys:
                if c not in s:
                    self.flag("PLAN001",
                              f"exchange key {c!r} not in {sorted(s)}")
                    return None
            return dict(s)

        if isinstance(node, Sort):
            s = self.schema(node.input)
            if s is None:
                return None
            for c, _ in node.keys:
                if c not in s:
                    self.flag("PLAN001", f"sort key {c!r} not in {sorted(s)}")
                    return None
            return dict(s)

        if isinstance(node, Limit):
            s = self.schema(node.input)
            if s is None:
                return None
            if node.n < 0:
                self.flag("PLAN003", f"limit: negative n ({node.n})")
                return None
            return dict(s)

        if isinstance(node, UnionAll):
            schemas = [self.schema(b) for b in node.branches]
            if any(s is None for s in schemas):
                return None
            first = schemas[0]
            for s in schemas[1:]:
                if not _schema_eq(first, s):
                    self.flag("PLAN002",
                              "UNION ALL branch schemas differ: "
                              f"{_fmt_schema(first)} vs {_fmt_schema(s)}")
                    return None
            return dict(first)

        # -- sugar nodes ----------------------------------------------------

        if isinstance(node, SetOp):
            ls = self.schema(node.left)
            rs = self.schema(node.right)
            if ls is None or rs is None:
                return None
            if self.desugared:
                self.flag("PLAN004",
                          f"SetOp({node.kind}) survived the rewrite fixpoint")
                return dict(ls)
            if list(ls) != list(rs) or any(ls[k].id != rs[k].id for k in ls):
                self.flag("PLAN002", f"{node.kind} sides disagree: "
                          f"{_fmt_schema(ls)} vs {_fmt_schema(rs)}")
                return None
            return dict(ls)

        if isinstance(node, Exists):
            s = self.schema(node.input)
            sub = self.schema(node.sub)
            if s is None or sub is None:
                return None
            if self.desugared:
                self.flag("PLAN004", "Exists survived the rewrite fixpoint")
                return dict(s)
            for pair in node.on:
                if not self._key_pair(s, sub, pair, "exists"):
                    return None
            return dict(s)

        if isinstance(node, Having):
            s = self.schema(node.input)
            if s is None:
                return None
            if self.desugared:
                self.flag("PLAN004", "Having survived the rewrite fixpoint")
                return dict(s)
            if self._expr(node.predicate, s, "having", want_bool=True) is None:
                return None
            return dict(s)

        if isinstance(node, CorrelatedAggFilter):
            s = self.schema(node.input)
            sub = self.schema(node.sub)
            if s is None or sub is None:
                return None
            if self.desugared:
                self.flag("PLAN004",
                          "CorrelatedAggFilter survived the rewrite fixpoint")
                return dict(s)
            if not self._key_pair(s, sub, node.on, "correlated filter"):
                return None
            d = self._agg_out(sub, node.agg, "correlated filter")
            if d is None:
                return None
            out = dict(s)
            if node.agg.name in out:
                self.flag("PLAN003",
                          f"correlated agg output {node.agg.name!r} collides")
                return None
            out[node.agg.name] = d
            if self._expr(node.predicate, out, "correlated predicate",
                          want_bool=True) is None:
                return None
            return out

        self.flag("PLAN003", f"unknown plan node {type(node).__name__}")
        return None

    @staticmethod
    def _window_dtype(d: DType, how: str) -> DType:
        # re-derived independently of nodes._window_dtype: the final
        # cross-check against infer_schema is what catches drift
        if how in ("row_number", "rank", "dense_rank"):
            return dt.INT32
        if how == "count":
            return dt.INT64
        if how in ("mean", "var", "std", "var_pop", "stddev_pop"):
            return dt.FLOAT64
        if how == "cumsum":
            return dt.INT64 if d.is_integral else d
        if how == "sum":
            if d.id == TypeId.FLOAT32:
                return dt.FLOAT32
            return dt.INT64 if d.is_integral else dt.FLOAT64
        return d


def verify_plan(plan: Node, catalog: Dict[str, Schema],
                desugared: bool = False,
                where: str = "plan") -> List[PlanViolation]:
    """Check plan well-formedness bottom-up. ``desugared=True``
    additionally bans sugar nodes (the post-fixpoint contract). The
    independent walk's schema is cross-checked against the production
    ``infer_schema`` when the walk itself is clean."""
    v = _Verifier(catalog, desugared, where)
    mine = v.schema(plan)
    if not v.violations:
        try:
            ref = infer_schema(plan, catalog)
        except PlanError as exc:
            v.flag("PLAN002",
                   "inference cross-check: infer_schema rejects a plan the "
                   f"verifier passed: {exc}")
        else:
            if mine is not None and not _schema_eq(mine, ref):
                v.flag("PLAN002",
                       "inference cross-check: verifier derived "
                       f"{_fmt_schema(mine)} but infer_schema says "
                       f"{_fmt_schema(ref)}")
    return v.violations


# ---------------------------------------------------------------------------
# layer 2: translation validation (obligation discharge)
# ---------------------------------------------------------------------------


def _conjunct_counter(e: PExpr) -> Counter:
    return Counter(repr(c.structure()) for c in ex.conjuncts(e))


def _d_decorrelate(ob, catalog) -> List[str]:
    b, a = ob.before, ob.after
    if not isinstance(b, CorrelatedAggFilter):
        return ["before-subtree is not a CorrelatedAggFilter"]
    if not (isinstance(a, Filter) and isinstance(a.input, Join)):
        return ["after-subtree is not Filter(Join(...))"]
    j = a.input
    msgs = []
    pk, bk = b.on
    if not (isinstance(j.right, Aggregate) and j.right.input is b.sub
            and j.right.keys == (bk,) and j.right.aggs == (b.agg,)):
        msgs.append("join build side is not Aggregate(sub, keys=(corr key,), "
                    "aggs=(the correlated agg,))")
    if j.left is not b.input or j.how != "inner" or j.on != ((pk, bk),):
        msgs.append("join probe side / how / keys do not reproduce the "
                    "correlation (inner join on the correlation pair)")
    if a.predicate.structure() != b.predicate.structure():
        msgs.append("comparison predicate changed across decorrelation")
    return msgs


def _d_grouping_sets(ob, catalog) -> List[str]:
    b, a = ob.before, ob.after
    if not (isinstance(b, Aggregate) and b.grouping_sets is not None):
        return ["before-subtree is not an Aggregate with grouping sets"]
    branches = a.branches if isinstance(a, UnionAll) else (a,)
    if len(branches) != len(b.grouping_sets):
        return [f"{len(b.grouping_sets)} grouping sets expanded into "
                f"{len(branches)} branches"]
    msgs = []
    agg_names = {x.name for x in b.aggs}
    want_names = tuple(b.keys) + tuple(x.name for x in b.aggs)
    for gs, br in zip(b.grouping_sets, branches):
        if not (isinstance(br, Project) and isinstance(br.input, Aggregate)):
            msgs.append(f"branch for grouping set {gs} is not "
                        "Project(Aggregate(...))")
            continue
        ag = br.input
        if ag.input is not b.input or ag.keys != gs or ag.aggs != b.aggs:
            msgs.append(f"branch aggregate for {gs} does not group the "
                        "ORIGINAL input by exactly that set with the "
                        "original aggregates")
        if tuple(n for n, _ in br.exprs) != want_names:
            msgs.append(f"branch for {gs} does not project the original "
                        f"output names {want_names}")
            continue
        for n, e in br.exprs:
            rolled = n in b.keys and n not in gs
            if rolled and not ex.is_null_lit(e):
                msgs.append(f"rolled key {n!r} in branch {gs} is not a "
                            "typed NULL literal")
            if not rolled and (n in gs or n in agg_names) \
                    and ex.is_col(e) != n:
                msgs.append(f"kept column {n!r} in branch {gs} is not a "
                            "passthrough reference")
    return msgs


def _d_setop(ob, catalog) -> List[str]:
    b, a = ob.before, ob.after
    if not isinstance(b, SetOp):
        return ["before-subtree is not a SetOp"]
    if not isinstance(a, Join):
        return ["after-subtree is not a Join"]
    want_how = "semi" if b.kind == "intersect" else "anti"
    msgs = []
    if a.how != want_how:
        msgs.append(f"{b.kind} must lower to a {want_how} join, got {a.how}")
    try:
        cols = tuple(infer_schema(b.left, catalog).keys())
    except PlanError as exc:
        return [f"before-subtree no longer infers: {exc}"]
    for side, src in (("left", b.left), ("right", b.right)):
        node = a.left if side == "left" else a.right
        if not (isinstance(node, Aggregate) and node.input is src
                and node.keys == cols and node.aggs == ()):
            msgs.append(f"{side} side is not deduplicated "
                        "(keys-only Aggregate over the original branch) — "
                        "set semantics lost")
    if a.on != tuple((c, c) for c in cols):
        msgs.append("join keys are not the full column set")
    return msgs


def _d_exists(ob, catalog) -> List[str]:
    b, a = ob.before, ob.after
    if not isinstance(b, Exists):
        return ["before-subtree is not an Exists"]
    if not isinstance(a, Join):
        return ["after-subtree is not a Join"]
    msgs = []
    want_how = "anti" if b.negated else "semi"
    if a.how != want_how:
        msgs.append(f"{'NOT ' if b.negated else ''}EXISTS must lower to a "
                    f"{want_how} join, got {a.how}")
    if a.left is not b.input or a.on != b.on:
        msgs.append("probe side / key pairs do not reproduce the "
                    "correlation")
    sub = a.right
    if isinstance(sub, Project):
        if not (sub.input is b.sub and all(
                ex.is_col(e) == n for n, e in sub.exprs)):
            msgs.append("subquery side is not a passthrough key projection "
                        "of the original subquery")
    elif sub is not b.sub:
        msgs.append("subquery side was replaced")
    return msgs


def _d_having(ob, catalog) -> List[str]:
    b, a = ob.before, ob.after
    if not isinstance(b, Having):
        return ["before-subtree is not a Having"]
    if not (isinstance(a, Filter) and a.input is b.input
            and a.predicate.structure() == b.predicate.structure()):
        return ["after-subtree is not Filter(<original aggregate>, "
                "<original predicate>)"]
    return []


def _d_merge_filters(ob, catalog) -> List[str]:
    b, a = ob.before, ob.after
    if not (isinstance(b, Filter) and isinstance(b.input, Filter)):
        return ["before-subtree is not Filter(Filter(...))"]
    if not (isinstance(a, Filter) and a.input is b.input.input):
        return ["after-subtree does not sit directly on the inner "
                "filter's input"]
    want = _conjunct_counter(b.predicate) + _conjunct_counter(b.input.predicate)
    got = _conjunct_counter(a.predicate)
    if want != got:
        return ["conjunct multiset changed across the merge: "
                f"{sorted(want)} -> {sorted(got)}"]
    return []


def _d_push_project(ob, catalog) -> List[str]:
    b, a = ob.before, ob.after
    if not (isinstance(b, Filter) and isinstance(b.input, Project)):
        return ["before-subtree is not Filter(Project(...))"]
    proj = b.input
    if not (isinstance(a, Project) and isinstance(a.input, Filter)
            and a.input.input is proj.input):
        return ["after-subtree is not Project(Filter(<project input>))"]
    msgs = []
    if a.exprs is not proj.exprs and tuple(
        (n, e.structure()) for n, e in a.exprs
    ) != tuple((n, e.structure()) for n, e in proj.exprs):
        msgs.append("projection list changed while pushing the filter")
    mapping = {}
    for name, e in proj.exprs:
        src = ex.is_col(e)
        if src is not None:
            mapping[name] = src
    refs = b.predicate.refs()
    if not refs <= set(mapping):
        msgs.append("predicate reads a COMPUTED projection column "
                    f"({sorted(refs - set(mapping))}) — pushing it below "
                    "the project changes semantics")
    elif ex.substitute(b.predicate, mapping).structure() \
            != a.input.predicate.structure():
        msgs.append("pushed predicate is not the original under the "
                    "project's rename mapping")
    return msgs


def _d_push_union(ob, catalog) -> List[str]:
    b, a = ob.before, ob.after
    if not (isinstance(b, Filter) and isinstance(b.input, UnionAll)):
        return ["before-subtree is not Filter(UnionAll(...))"]
    u = b.input
    if not (isinstance(a, UnionAll) and len(a.branches) == len(u.branches)):
        return ["after-subtree is not a UnionAll of the same arity"]
    msgs = []
    want = b.predicate.structure()
    for i, (orig, got) in enumerate(zip(u.branches, a.branches)):
        if not (isinstance(got, Filter) and got.input is orig
                and got.predicate.structure() == want):
            msgs.append(f"branch {i} is not Filter(<original branch>, "
                        "<original predicate>)")
    return msgs


def _new_conjuncts(after_side: Node, before_side: Node, what: str,
                   msgs: List[str]) -> List[PExpr]:
    if after_side is before_side:
        return []
    if isinstance(after_side, Filter) and after_side.input is before_side:
        return list(ex.conjuncts(after_side.predicate))
    msgs.append(f"{what} side of the join was restructured, not just "
                "filtered")
    return []


def _d_push_join(ob, catalog) -> List[str]:
    b, a = ob.before, ob.after
    if not (isinstance(b, Filter) and isinstance(b.input, Join)):
        return ["before-subtree is not Filter(Join(...))"]
    j = b.input
    stay: List[PExpr] = []
    aj = a
    if isinstance(a, Filter):
        stay = list(ex.conjuncts(a.predicate))
        aj = a.input
    if not isinstance(aj, Join):
        return ["after-subtree is not a Join (or Filter over one)"]
    msgs: List[str] = []
    if aj.how != j.how or aj.on != j.on:
        msgs.append("join how/keys changed while pushing the filter")
    new_left = _new_conjuncts(aj.left, j.left, "probe", msgs)
    new_right = _new_conjuncts(aj.right, j.right, "build", msgs)
    want = _conjunct_counter(b.predicate)
    got = Counter(repr(c.structure()) for c in new_left + new_right + stay)
    if want != got:
        msgs.append("conjunct multiset changed across the push (a conjunct "
                    "was dropped, duplicated, or invented)")
    # legality: row-subsetting must commute with the join
    if j.how == "full" and (new_left or new_right):
        msgs.append("nothing commutes below a FULL join (both sides "
                    "null-extend)")
    if new_right and j.how != "inner":
        msgs.append(f"build-side conjunct pushed below a {j.how} join — the "
                    "build side defines membership/null-extension there, "
                    "so filtering it changes semantics")
    try:
        ls = set(infer_schema(j.left, catalog))
        rs = set(infer_schema(j.right, catalog))
    except PlanError as exc:
        msgs.append(f"join sides no longer infer: {exc}")
        return msgs
    for c in new_left:
        if not c.refs() <= ls:
            msgs.append(f"probe-side conjunct reads {sorted(c.refs() - ls)} "
                        "outside the probe schema")
    for c in new_right:
        if not c.refs() <= rs:
            msgs.append(f"build-side conjunct reads {sorted(c.refs() - rs)} "
                        "outside the build schema")
    return msgs


def _scans(node: Node) -> List[Scan]:
    out, seen = [], set()

    def visit(n):
        if id(n) in seen:
            return
        seen.add(id(n))
        if isinstance(n, Scan):
            out.append(n)
        for i in n.inputs():
            visit(i)

    visit(node)
    return out


def _d_prune(ob, catalog) -> List[str]:
    # schema equality (the common check) already pins column-set
    # preservation at the root; here: scans may only NARROW within their
    # table, never invent columns
    msgs = []
    for s in _scans(ob.after):
        if s.table not in catalog:
            msgs.append(f"pruned scan references unknown table {s.table!r}")
            continue
        if s.columns is None:
            continue
        extra = [c for c in s.columns if c not in catalog[s.table]]
        if extra:
            msgs.append(f"pruned scan {s.key} invented column(s) {extra}")
    return msgs


def _d_partition_ooc(ob, catalog) -> List[str]:
    """srjt-ooc (ISSUE 18): Aggregate -> UnionAll of per-partition
    aggregates. Branch ``i`` must be the ORIGINAL aggregate (same keys,
    same agg specs, no grouping sets) over ``Filter(<original input>,
    part_hash(keys, K) == i)``. With the branches ordered ``i =
    0..K-1``, disjointness and completeness hold by construction — the
    partition ids partition the rows — and every group lands whole in
    exactly one branch because all of its rows share one key tuple."""
    b, a = ob.before, ob.after
    if not (isinstance(b, Aggregate) and b.keys
            and b.grouping_sets is None):
        return ["before-subtree is not a keyed Aggregate (no grouping "
                "sets)"]
    if not (isinstance(a, UnionAll) and len(a.branches) >= 2):
        return ["after-subtree is not a UnionAll of >= 2 partition "
                "branches"]
    msgs: List[str] = []
    parts = len(a.branches)
    want_aggs = [(s.source, s.how, s.name) for s in b.aggs]
    for i, br in enumerate(a.branches):
        if not (isinstance(br, Aggregate) and br.keys == b.keys
                and br.grouping_sets is None
                and [(s.source, s.how, s.name) for s in br.aggs] == want_aggs):
            msgs.append(f"branch {i} is not the original Aggregate "
                        "(keys/aggs changed)")
            continue
        f = br.input
        want = (ex.ppart(b.keys, parts) == ex.plit(i)).structure()
        if not (isinstance(f, Filter) and f.input is b.input
                and f.predicate.structure() == want):
            msgs.append(f"branch {i} input is not Filter(<original "
                        f"input>, part_hash(keys, {parts}) == {i})")
    return msgs


def _chain_sig(joins) -> Counter:
    """Multiset signature of a join chain: one entry per chain member
    carrying its key pairs, build-subtree fingerprint, join kind, and
    strategy hint — exactly what a pure REORDER must preserve."""
    from .rewrites import fingerprint
    return Counter((j.how, j.on, fingerprint(j.right), j.bounded)
                   for j in joins)


def _d_cbo_reorder(ob, catalog) -> List[str]:
    """srjt-cbo (ISSUE 19): a join-order enumeration fire. The after-
    subtree must be a passthrough Project (restoring the witnessed
    column order — checked by the common schema discharge) over a
    rebuilt chain of the SAME inner joins: same base, and the multiset
    of (how, on-pairs, build fingerprint, bounded) chain members
    preserved. Only inner joins may move (outer-join legality): the
    chain walk itself admits nothing else, so a reorder that absorbed
    a left/semi/anti join shows up as a base-fingerprint mismatch."""
    from .optimizer import collect_chain, is_passthrough_project
    from .rewrites import fingerprint
    b, a = ob.before, ob.after
    if not (isinstance(b, Join) and b.how == "inner"):
        return ["before-subtree is not an inner Join chain head"]
    if not is_passthrough_project(a):
        return ["after-subtree is not a passthrough column-restoring "
                "Project"]
    b_base, b_joins = collect_chain(b, catalog)
    a_base, a_joins = collect_chain(a.input, catalog)
    msgs: List[str] = []
    if len(b_joins) < 2:
        msgs.append("reorder fired on a chain of fewer than 2 joins")
    if fingerprint(b_base) != fingerprint(a_base):
        msgs.append("chain base changed across the reorder (a non-inner "
                    "join or the fact subtree was restructured)")
    if _chain_sig(b_joins) != _chain_sig(a_joins):
        msgs.append("join-predicate multiset not preserved: a chain "
                    "member's keys, build side, kind, or strategy hint "
                    "was dropped, duplicated, or invented")
    return msgs


def _d_cbo_build_side(ob, catalog) -> List[str]:
    """srjt-cbo (ISSUE 19): a build/probe commute. after must be
    Project(Join(right, left, on-swapped)) with the Project renaming
    the surviving right key back to the dropped left key's name — legal
    only for INNER joins with exactly-matching key dtypes (equi-join
    output has the pair equal row-for-row, so the rename is the
    identity on every surviving row)."""
    from .optimizer import is_passthrough_project  # noqa: F401 (shape doc)
    from .rewrites import fingerprint
    b, a = ob.before, ob.after
    if not (isinstance(b, Join) and b.how == "inner"):
        return ["before-subtree is not an inner Join"]
    if not (isinstance(a, Project) and isinstance(a.input, Join)):
        return ["after-subtree is not Project(Join(...))"]
    aj = a.input
    msgs: List[str] = []
    if aj.how != "inner":
        msgs.append("commuted join is not inner (outer-join commutes are "
                    "illegal)")
    if aj.on != tuple((r, l) for l, r in b.on):
        msgs.append("key pairs are not the originals swapped")
    if fingerprint(aj.left) != fingerprint(b.right) \
            or fingerprint(aj.right) != fingerprint(b.left):
        msgs.append("commuted join sides are not the original sides "
                    "swapped")
    if aj.bounded != b.bounded:
        msgs.append("strategy hint changed across the commute")
    try:
        ls = infer_schema(b.left, catalog)
        rs = infer_schema(b.right, catalog)
    except PlanError as exc:
        msgs.append(f"join sides no longer infer: {exc}")
        return msgs
    if any(l in ls and r in rs
           and (ls[l].id != rs[r].id or ls[l].scale != rs[r].scale)
           for l, r in b.on):
        msgs.append("key dtypes differ — the restoring rename would "
                    "retype the key column")
    rename = {l: r for l, r in b.on if l != r}
    try:
        want = list(infer_schema(b, catalog))
    except PlanError as exc:
        msgs.append(f"before-subtree no longer infers: {exc}")
        return msgs
    got = [(n, ex.is_col(e)) for n, e in a.exprs]
    if [n for n, _ in got] != want or any(
            src != rename.get(n, n) for n, src in got):
        msgs.append("restoring Project is not the identity-or-key-rename "
                    "mapping over the original schema")
    return msgs


def _d_cbo_join_strategy(ob, catalog) -> List[str]:
    """srjt-cbo (ISSUE 19): a physical-strategy resolution. Everything
    but the ``bounded`` hint must be identical, the before-hint must be
    None (author abstained — author-written hints are binding), and the
    after-hint a concrete bool. The hint never changes semantics (the
    dense path re-validates its domain at bind time and falls back),
    so structure preservation IS the proof."""
    from .rewrites import fingerprint
    b, a = ob.before, ob.after
    if not (isinstance(b, Join) and isinstance(a, Join)):
        return ["strategy fire is not Join -> Join"]
    msgs: List[str] = []
    if b.bounded is not None:
        msgs.append("author-written strategy hint overridden (before-"
                    "bounded was not None)")
    if not isinstance(a.bounded, bool):
        msgs.append("strategy not resolved to a concrete bool")
    if a.how != b.how or a.on != b.on:
        msgs.append("join how/keys changed in a strategy-only rewrite")
    if fingerprint(a.left) != fingerprint(b.left) \
            or fingerprint(a.right) != fingerprint(b.right):
        msgs.append("join inputs changed in a strategy-only rewrite")
    return msgs


# rule name -> discharge fn(obligation, catalog) -> list of failure
# messages. srjt-lint SRJT011 statically requires every rule registered
# in rewrites.RULES (plus prune_columns) to appear here or carry
# # srjt-plan: allow-unverified(<reason>).
OBLIGATION_DISCHARGERS: Dict[str, Callable] = {
    "decorrelate_scalar_agg": _d_decorrelate,
    "expand_grouping_sets": _d_grouping_sets,
    "setop_to_joins": _d_setop,
    "exists_to_semijoin": _d_exists,
    "having_to_filter": _d_having,
    "merge_filters": _d_merge_filters,
    "push_filter_through_project": _d_push_project,
    "push_filter_through_union": _d_push_union,
    "push_filter_into_join": _d_push_join,
    "prune_columns": _d_prune,
    # emitted by plan/ooc.py (compiler tail), not rewrites.RULES
    "partition_for_ooc": _d_partition_ooc,
    # emitted by the cost-based optimizer pass (plan/optimizer.py,
    # srjt-cbo ISSUE 19), not rewrites.RULES
    "cbo_reorder_joins": _d_cbo_reorder,
    "cbo_build_side": _d_cbo_build_side,
    "cbo_join_strategy": _d_cbo_join_strategy,
}


def _discharge_schema(ob, catalog) -> List[str]:
    """The common obligation: the rewritten subtree still validates and
    its schema equals the preserved-schema witness."""
    try:
        after = infer_schema(ob.after, catalog)
    except PlanError as exc:
        return [f"rewritten subtree no longer validates: {exc}"]
    if ob.schema is not None and not _schema_eq(ob.schema, after):
        return ["schema not preserved: "
                f"{_fmt_schema(ob.schema)} -> {_fmt_schema(after)}"]
    return []


def verify_obligations(obligations, catalog: Dict[str, Schema],
                       where: str = "plan") -> List[PlanViolation]:
    """Discharge every rewrite obligation structurally. Each failed
    obligation yields exactly ONE PLAN006 violation carrying all of its
    failure messages (so a fixture firing one broken rule reports one
    finding)."""
    out: List[PlanViolation] = []
    for i, ob in enumerate(obligations):
        fn = OBLIGATION_DISCHARGERS.get(ob.rule)
        if fn is None:
            out.append(PlanViolation(
                where, "PLAN006",
                f"obligation #{i} ({ob.rule}, {ob.before_fp}->{ob.after_fp}):"
                " no discharger registered in plan/verifier.py — the rule's"
                " output is unverifiable"))
            continue
        msgs = _discharge_schema(ob, catalog)
        if not msgs:
            msgs = fn(ob, catalog)
        if msgs:
            out.append(PlanViolation(
                where, "PLAN006",
                f"obligation #{i} ({ob.rule}, {ob.before_fp}->{ob.after_fp})"
                f" undischargeable: " + "; ".join(msgs)))
    return out


# ---------------------------------------------------------------------------
# layer 3: estimate consistency (the memgov/serve contract)
# ---------------------------------------------------------------------------

# stage kinds whose output-row estimate must never exceed the (first)
# child's: subsetting and grouping never grow the row count
_ROW_MONOTONE_KINDS = ("filter", "limit", "aggregate", "fused_aggregate",
                       "exchange")


def verify_estimates(cp, where: str = "plan") -> List[PlanViolation]:
    """Every lowered stage carries a positive ``memory_bytes`` estimate,
    row estimates are monotone-consistent with child estimates, and the
    plan-level peak equals the per-stage working-set maximum (the number
    memgov admission and ``serve.submit`` consume)."""
    out: List[PlanViolation] = []
    stages = cp.stages
    if not stages:
        out.append(PlanViolation(where, "PLAN005",
                                 "compiled plan has no lowered stages"))
        return out
    for i, s in enumerate(stages):
        what = f"stage #{i} ({s.kind})"
        if not isinstance(getattr(s, "est_rows", None), int) \
                or not isinstance(getattr(s, "est_bytes", None), int) \
                or s.est_rows < 1 or s.est_bytes < s.est_rows:
            out.append(PlanViolation(
                where, "PLAN005",
                f"{what}: missing/non-positive estimate "
                f"(est_rows={getattr(s, 'est_rows', None)}, "
                f"est_bytes={getattr(s, 'est_bytes', None)})"))
            continue
        if s.kind in _ROW_MONOTONE_KINDS and s.inputs:
            child = s.inputs[0]
            if s.est_rows > child.est_rows:
                out.append(PlanViolation(
                    where, "PLAN005",
                    f"{what}: estimate inversion — estimates {s.est_rows} "
                    f"output rows over a {child.est_rows}-row input "
                    f"({child.kind}); a {s.kind} never grows the row "
                    "count"))
        if s.kind == "union_all":
            want = sum(c.est_rows for c in s.inputs)
            if s.est_rows != want:
                out.append(PlanViolation(
                    where, "PLAN005",
                    f"{what}: union estimate {s.est_rows} != sum of branch "
                    f"estimates {want}"))
    peak = max(s.working_set_est() for s in stages)
    if cp.estimated_memory_bytes != peak or peak <= 0:
        out.append(PlanViolation(
            where, "PLAN005",
            f"plan-level estimated_memory_bytes "
            f"({cp.estimated_memory_bytes}) disagrees with the per-stage "
            f"working-set peak ({peak}) — memgov admission would trust a "
            "stale number"))
    return out


# ---------------------------------------------------------------------------
# layer 4: cache-insert verification (srjt-cache, ISSUE 17)
# ---------------------------------------------------------------------------


def verify_for_cache(cp, tables, where: str = "cache") -> List[PlanViolation]:
    """The plan cache's insert gate: a compiled plan enters the cache
    only when its rewrite obligations discharge AND its stage estimates
    are consistent — "verifier-green at insert". Hits then reuse the
    cached structure without re-verifying per submission (the ISSUE 17
    once-per-structure contract); this is the once."""
    catalog = {t: {n: c.dtype for n, c in zip(tbl.names, tbl.columns)}
               for t, tbl in tables.items()}
    out = verify_obligations(cp.obligations, catalog, where=where)
    out.extend(verify_estimates(cp, where=where))
    return out

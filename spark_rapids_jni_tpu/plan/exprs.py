"""Typed expression tree for the logical-plan IR (srjt-plan, ISSUE 14).

The run-time expression tier (``ops/expressions.py``) is evaluation-only:
it answers "what are the values" given a Table, but a *plan* needs two
things a closure cannot give — the output DTYPE before any data exists
(schema inference, the contract memgov estimates and UNION validation
hang on) and the REFERENCED column set (predicate/projection pushdown).
This module is that static layer: a small AST mirroring the runtime
surface (arithmetic, comparisons, 3VL and/or/not, is_null, cast, CASE
WHEN, LIKE/RLIKE) where every node can

- ``dtype(schema)``     -> the output DType under a name->DType schema,
- ``refs()``            -> the column names it reads,
- ``lower()``           -> the equivalent ``ops.expressions.Expression``,
- ``structure()``       -> a canonical nested tuple (structural equality
                           for the rewrite-idempotence contract).

Null/3VL semantics are entirely the runtime tier's; this layer only
types and routes. Aggregate-output and division typing follow the fused
pipeline's materialization contract (``pipeline._wrap_result``):
divisions and floating arithmetic land in FLOAT64.
"""

from __future__ import annotations

import re as _re
from typing import Dict, FrozenSet, Optional, Tuple

import numpy as np

from ..columnar import dtype as dt
from ..columnar.dtype import DType, TypeId
from ..ops import expressions as rt

__all__ = ["PExpr", "pcol", "plit", "pwhen", "plike", "prlike", "ppart",
           "PlanError", "map_literals"]


class PlanError(ValueError):
    """A plan failed validation (unknown column, dtype mismatch, an
    unreducible sugar node at lowering time)."""


Schema = Dict[str, DType]

_INT_RANK = {
    TypeId.INT8: 1, TypeId.UINT8: 1, TypeId.INT16: 2, TypeId.UINT16: 2,
    TypeId.INT32: 3, TypeId.UINT32: 3, TypeId.INT64: 4, TypeId.UINT64: 4,
}


def _is_numeric(d: DType) -> bool:
    return d.is_integral or d.is_floating


def _promote(a: DType, b: DType) -> DType:
    """Binary arithmetic result type: floats dominate (FLOAT64 over
    FLOAT32), otherwise the wider integer (signed wins a width tie,
    mirroring jnp's lattice for the lanes this tier uses)."""
    if a.id == b.id:
        return DType(a.id)
    if dt.FLOAT64.id in (a.id, b.id):
        return dt.FLOAT64
    if a.is_floating or b.is_floating:
        if a.is_floating and b.is_floating:
            return dt.FLOAT64
        return dt.FLOAT64 if (a if a.is_floating else b).id == TypeId.FLOAT64 else dt.FLOAT32
    if a.is_integral and b.is_integral:
        ra, rb = _INT_RANK[a.id], _INT_RANK[b.id]
        if ra == rb:
            return a if a.is_signed else b
        return a if ra > rb else b
    raise PlanError(f"no arithmetic promotion between {a!r} and {b!r}")


class PExpr:
    """Base plan expression. Operator sugar mirrors the runtime tier so
    plans read like the hand-built pipelines they replace."""

    def dtype(self, schema: Schema) -> DType:
        raise NotImplementedError

    def refs(self) -> FrozenSet[str]:
        raise NotImplementedError

    def lower(self) -> rt.Expression:
        raise NotImplementedError

    def structure(self) -> tuple:
        raise NotImplementedError

    # -- operator sugar (mirrors ops/expressions.py) -------------------------
    def _bin(self, other, op):
        return _PBin(op, self, _wrap(other))

    def __add__(self, o):
        return self._bin(o, "add")

    def __sub__(self, o):
        return self._bin(o, "sub")

    def __mul__(self, o):
        return self._bin(o, "mul")

    def __truediv__(self, o):
        return self._bin(o, "div")

    def __mod__(self, o):
        return self._bin(o, "mod")

    def __eq__(self, o):  # noqa: A003 - comparison builds a node, like the runtime tier
        return self._bin(o, "eq")

    def __ne__(self, o):
        return self._bin(o, "ne")

    def __lt__(self, o):
        return self._bin(o, "lt")

    def __le__(self, o):
        return self._bin(o, "le")

    def __gt__(self, o):
        return self._bin(o, "gt")

    def __ge__(self, o):
        return self._bin(o, "ge")

    def __and__(self, o):
        return _PBin("and", self, _wrap(o))

    def __or__(self, o):
        return _PBin("or", self, _wrap(o))

    def __invert__(self):
        return _PNot(self)

    def is_null(self):
        return _PIsNull(self, True)

    def is_not_null(self):
        return _PIsNull(self, False)

    def cast(self, d: DType):
        return _PCast(self, d)

    __hash__ = None


_CMP_OPS = ("eq", "ne", "lt", "le", "gt", "ge")
_BOOL_OPS = ("and", "or")
_ARITH_OPS = ("add", "sub", "mul", "mod")


class _PCol(PExpr):
    def __init__(self, name: str):
        self.name = name

    def dtype(self, schema: Schema) -> DType:
        if self.name not in schema:
            raise PlanError(
                f"column {self.name!r} not in schema {sorted(schema)}"
            )
        return schema[self.name]

    def refs(self):
        return frozenset({self.name})

    def lower(self):
        return rt.col(self.name)

    def structure(self):
        return ("col", self.name)


class _PLit(PExpr):
    """Literal. ``value=None`` is the typed SQL NULL — a dtype is
    required so CASE/UNION schemas stay inferable."""

    def __init__(self, value, d: Optional[DType] = None):
        if value is None and d is None:
            raise PlanError("null literal needs an explicit dtype")
        self.value = value
        self.d = d

    def dtype(self, schema: Schema) -> DType:
        if self.d is not None:
            return self.d
        if isinstance(self.value, bool):
            return dt.BOOL8
        if isinstance(self.value, (int, np.integer)):
            return dt.INT64 if not isinstance(self.value, np.int32) else dt.INT32
        if isinstance(self.value, (float, np.floating)):
            return dt.FLOAT64
        raise PlanError(f"untypable literal {self.value!r}")

    def refs(self):
        return frozenset()

    def lower(self):
        return rt.lit(self.value)

    def structure(self):
        d = None if self.d is None else (int(self.d.id), self.d.scale)
        return ("lit", self.value, d)


class _PBin(PExpr):
    def __init__(self, op: str, a: PExpr, b: PExpr):
        self.op, self.a, self.b = op, a, b

    def dtype(self, schema: Schema) -> DType:
        da, db = self.a.dtype(schema), self.b.dtype(schema)
        if self.op in _CMP_OPS:
            return dt.BOOL8
        if self.op in _BOOL_OPS:
            return dt.BOOL8
        if self.op == "div":
            return dt.FLOAT64  # SQL divide is always floating
        if not (_is_numeric(da) and _is_numeric(db)):
            raise PlanError(f"{self.op} needs numeric operands, got {da!r}, {db!r}")
        # a weak (host-scalar) literal adopts the column operand's dtype,
        # matching the runtime tier's promotion
        if isinstance(self.a, _PLit) and self.a.d is None and da.is_integral:
            return db
        if isinstance(self.b, _PLit) and self.b.d is None and db.is_integral:
            return da
        return _promote(da, db)

    def refs(self):
        return self.a.refs() | self.b.refs()

    def lower(self):
        la, lb = self.a.lower(), self.b.lower()
        return {
            "add": lambda: la + lb, "sub": lambda: la - lb,
            "mul": lambda: la * lb, "div": lambda: la / lb,
            "mod": lambda: la % lb,
            "eq": lambda: la == lb, "ne": lambda: la != lb,
            "lt": lambda: la < lb, "le": lambda: la <= lb,
            "gt": lambda: la > lb, "ge": lambda: la >= lb,
            "and": lambda: la & lb, "or": lambda: la | lb,
        }[self.op]()

    def structure(self):
        return ("bin", self.op, self.a.structure(), self.b.structure())


class _PNot(PExpr):
    def __init__(self, a: PExpr):
        self.a = a

    def dtype(self, schema: Schema) -> DType:
        self.a.dtype(schema)  # validates refs
        return dt.BOOL8

    def refs(self):
        return self.a.refs()

    def lower(self):
        return ~self.a.lower()

    def structure(self):
        return ("not", self.a.structure())


class _PIsNull(PExpr):
    def __init__(self, a: PExpr, want_null: bool):
        self.a, self.want_null = a, want_null

    def dtype(self, schema: Schema) -> DType:
        self.a.dtype(schema)
        return dt.BOOL8

    def refs(self):
        return self.a.refs()

    def lower(self):
        la = self.a.lower()
        return la.is_null() if self.want_null else la.is_not_null()

    def structure(self):
        return ("is_null", self.want_null, self.a.structure())


class _PCast(PExpr):
    def __init__(self, a: PExpr, d: DType):
        self.a, self.d = a, d

    def dtype(self, schema: Schema) -> DType:
        self.a.dtype(schema)
        return self.d

    def refs(self):
        return self.a.refs()

    def lower(self):
        return self.a.lower().cast(self.d)

    def structure(self):
        return ("cast", (int(self.d.id), self.d.scale), self.a.structure())


class _PWhen(PExpr):
    """CASE WHEN cond THEN a ELSE b END; the result dtype follows the
    first branch with a known (non-null-literal) dtype, and both
    branches must agree when both are typed."""

    def __init__(self, cond: PExpr, then: PExpr, other: PExpr):
        self.cond, self.then, self.other = cond, then, other

    def dtype(self, schema: Schema) -> DType:
        self.cond.dtype(schema)
        dthen, dother = self.then.dtype(schema), self.other.dtype(schema)
        t_null = isinstance(self.then, _PLit) and self.then.value is None
        o_null = isinstance(self.other, _PLit) and self.other.value is None
        if t_null and not o_null:
            return dother
        if o_null and not t_null:
            return dthen
        if dthen.id != dother.id or dthen.scale != dother.scale:
            raise PlanError(
                f"CASE branches disagree on dtype: {dthen!r} vs {dother!r}"
            )
        return dthen

    def refs(self):
        return self.cond.refs() | self.then.refs() | self.other.refs()

    def lower(self):
        return rt.when(self.cond.lower(), self.then.lower(), self.other.lower())

    def structure(self):
        return ("when", self.cond.structure(), self.then.structure(),
                self.other.structure())


class _RegexEval(rt.Expression):
    """Runtime bridge: read a STRING column and run the DFA matcher
    (ops/regex). ``full=True`` anchors the whole value (SQL LIKE);
    ``full=False`` is substring search (RLIKE). Reads the column
    directly — STRING lanes (offsets/chars) don't flow through the
    fixed-width expression evaluator."""

    def __init__(self, name: str, pattern: str, full: bool):
        self.name, self.pattern, self.full = name, pattern, full

    def _eval(self, table):
        from ..ops import regex

        c = table.column(self.name)
        if c.dtype.id != TypeId.STRING:
            raise PlanError(f"LIKE/RLIKE needs a STRING input, got {c.dtype!r}")
        fn = regex.matches_re if self.full else regex.contains_re
        r = fn(c, self.pattern)
        return rt._Value(r.data.astype(bool), r.validity, None)


def _like_to_regex(pattern: str) -> str:
    """SQL LIKE pattern -> anchored regex: % -> .*, _ -> ., everything
    else literal (regex metacharacters escaped)."""
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(_re.escape(ch))
    return "".join(out) + "$"


class _PLike(PExpr):
    def __init__(self, a: PExpr, pattern: str, kind: str):
        if kind not in ("like", "rlike"):
            raise PlanError(f"unknown pattern-match kind {kind!r}")
        if not isinstance(a, _PCol):
            raise PlanError("LIKE/RLIKE applies to a column reference")
        self.a, self.pattern, self.kind = a, pattern, kind

    def dtype(self, schema: Schema) -> DType:
        d = self.a.dtype(schema)
        if d.id != TypeId.STRING:
            raise PlanError(f"{self.kind.upper()} needs a STRING column, got {d!r}")
        return dt.BOOL8

    def refs(self):
        return self.a.refs()

    def lower(self):
        if self.kind == "like":
            return _RegexEval(self.a.name, _like_to_regex(self.pattern), True)
        return _RegexEval(self.a.name, self.pattern, False)

    def structure(self):
        return ("like", self.kind, self.pattern, self.a.structure())


class _PartHashEval(rt.Expression):
    """Runtime bridge for ``_PPartHash``: the murmur3-pmod partition map
    over the key columns (``ops/hashing.hash_partition_map``) — the same
    partitioner the physical shuffle uses, so a plan-level partition
    predicate selects *exactly* the rows the executor's
    ``hash_partition`` would route to that partition."""

    def __init__(self, names: Tuple[str, ...], parts: int):
        self.names, self.parts = names, parts

    def _eval(self, table):
        from ..ops.hashing import hash_partition_map

        ids = hash_partition_map(
            [table.column(n) for n in self.names], self.parts
        )
        return rt._Value(ids, None, None)


class _PPartHash(PExpr):
    """``part_hash(keys, K)`` — the INT32 partition id (murmur3 pmod K)
    of each row's key tuple. The out-of-core rewrite's partition
    predicate is ``ppart(keys, K) == plit(i)``; because every row of a
    group hashes identically, each group lands whole in one branch."""

    def __init__(self, names: Tuple[str, ...], parts: int):
        names = tuple(names)
        if not names:
            raise PlanError("part_hash needs at least one key column")
        if int(parts) < 2:
            raise PlanError(f"part_hash needs >= 2 partitions, got {parts}")
        self.names, self.parts = names, int(parts)

    def dtype(self, schema: Schema) -> DType:
        for n in self.names:
            if n not in schema:
                raise PlanError(
                    f"column {n!r} not in schema {sorted(schema)}"
                )
        return dt.INT32

    def refs(self):
        return frozenset(self.names)

    def lower(self):
        return _PartHashEval(self.names, self.parts)

    def structure(self):
        return ("part_hash", self.names, self.parts)


def _wrap(v) -> PExpr:
    if isinstance(v, PExpr):
        return v
    return _PLit(v)


def pcol(name: str) -> PExpr:
    """Reference a column of the node's input schema."""
    return _PCol(name)


def plit(value, d: Optional[DType] = None) -> PExpr:
    """A literal; ``plit(None, dt.INT32)`` is the typed SQL NULL."""
    return _PLit(value, d)


def pwhen(cond, then, otherwise) -> PExpr:
    """SQL ``CASE WHEN cond THEN then ELSE otherwise END``."""
    return _PWhen(_wrap(cond), _wrap(then), _wrap(otherwise))


def plike(expr: PExpr, pattern: str) -> PExpr:
    """SQL ``LIKE`` (``%``/``_`` wildcards, whole-value anchored)."""
    return _PLike(expr, pattern, "like")


def prlike(expr: PExpr, pattern: str) -> PExpr:
    """Spark ``RLIKE`` — regex substring search."""
    return _PLike(expr, pattern, "rlike")


def ppart(names, parts: int) -> PExpr:
    """Row partition id: murmur3-pmod of the key tuple into ``parts``
    buckets — bit-matches the physical shuffle partitioner."""
    return _PPartHash(tuple(names), parts)


def conjuncts(e: PExpr) -> Tuple[PExpr, ...]:
    """Split a predicate into its top-level AND conjuncts (pushdown
    works conjunct-at-a-time; splitting an AND across a Filter is sound
    under 3VL — a row passes iff every conjunct is TRUE either way)."""
    if isinstance(e, _PBin) and e.op == "and":
        return conjuncts(e.a) + conjuncts(e.b)
    return (e,)


def conjoin(es) -> PExpr:
    """Re-AND a non-empty conjunct list."""
    es = list(es)
    if not es:
        raise PlanError("conjoin needs at least one conjunct")
    out = es[0]
    for e in es[1:]:
        out = out & e
    return out


def substitute(e: PExpr, mapping: Dict[str, str]) -> PExpr:
    """Rebuild ``e`` with column references renamed through ``mapping``
    (pushdown through a renaming Project). Names not in the mapping are
    kept."""
    if isinstance(e, _PCol):
        return _PCol(mapping.get(e.name, e.name))
    if isinstance(e, _PLit):
        return e
    if isinstance(e, _PBin):
        return _PBin(e.op, substitute(e.a, mapping), substitute(e.b, mapping))
    if isinstance(e, _PNot):
        return _PNot(substitute(e.a, mapping))
    if isinstance(e, _PIsNull):
        return _PIsNull(substitute(e.a, mapping), e.want_null)
    if isinstance(e, _PCast):
        return _PCast(substitute(e.a, mapping), e.d)
    if isinstance(e, _PWhen):
        return _PWhen(substitute(e.cond, mapping), substitute(e.then, mapping),
                      substitute(e.other, mapping))
    if isinstance(e, _PLike):
        return _PLike(substitute(e.a, mapping), e.pattern, e.kind)
    if isinstance(e, _PPartHash):
        return _PPartHash(tuple(mapping.get(n, n) for n in e.names), e.parts)
    raise PlanError(f"unknown expression node {type(e).__name__}")


def map_literals(e: PExpr, fn) -> PExpr:
    """Rebuild ``e`` with every literal leaf mapped through ``fn``
    (``_PLit -> PExpr``) — the literal-rebinding walker the plan cache
    (srjt-cache) uses to bind fresh parameter values into a cached
    optimized plan. Non-literal leaves are kept."""
    if isinstance(e, _PLit):
        return fn(e)
    if isinstance(e, _PCol):
        return e
    if isinstance(e, _PBin):
        return _PBin(e.op, map_literals(e.a, fn), map_literals(e.b, fn))
    if isinstance(e, _PNot):
        return _PNot(map_literals(e.a, fn))
    if isinstance(e, _PIsNull):
        return _PIsNull(map_literals(e.a, fn), e.want_null)
    if isinstance(e, _PCast):
        return _PCast(map_literals(e.a, fn), e.d)
    if isinstance(e, _PWhen):
        return _PWhen(map_literals(e.cond, fn), map_literals(e.then, fn),
                      map_literals(e.other, fn))
    if isinstance(e, _PLike):
        return _PLike(map_literals(e.a, fn), e.pattern, e.kind)
    if isinstance(e, _PPartHash):
        # partition structure is never a cache parameter — K and the key
        # set are part of the plan's shape, not its literals
        return e
    raise PlanError(f"unknown expression node {type(e).__name__}")


def is_col(e: PExpr) -> Optional[str]:
    """The referenced name when ``e`` is a bare column ref, else None."""
    return e.name if isinstance(e, _PCol) else None


def is_null_lit(e: PExpr) -> bool:
    """True when ``e`` is the typed SQL NULL literal (``plit(None, d)``)
    — the compiler materializes those directly at the declared dtype
    (the runtime literal tier always evaluates NULL as INT32 lanes)."""
    return isinstance(e, _PLit) and e.value is None

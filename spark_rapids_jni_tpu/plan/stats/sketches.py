"""Per-column statistics sketches (srjt-cbo, ISSUE 19).

One :class:`ColumnSketch` per fixed-width column: row count, null
fraction, min/max, an HLL-style distinct-count estimate (2**b
registers, splitmix64-mixed hashes), and an equi-depth histogram
(``SRJT_STATS_HISTOGRAM_BINS`` bins over the non-null values). All of
it is computed host-side with numpy in one pass over (at most
``SRJT_STATS_MAX_ROWS``) rows — sketches are compile-time inputs, not
device work.

``selectivity(pred, resolve)`` walks a plan predicate
(:mod:`plan.exprs`) and turns comparisons against literals into
fractions using the sketches ``resolve(column_name)`` hands back:

- ``col == lit``  -> (1 - null_fraction) / ndv  (capped by histogram
  membership: a literal outside [min, max] estimates ~0)
- range ops      -> histogram bin mass, partial bins counted in full
  on the selected side so the estimate upper-bounds the truth within
  one bin of resolution
- ``isnull``     -> null_fraction (or its complement)
- AND/OR/NOT    -> product / inclusion-exclusion / complement under
  the usual independence assumption
- anything else  -> ``DEFAULT_SELECTIVITY`` per unknown conjunct

Estimates are advisory: they feed ``est_rows`` and the CBO search,
never semantics. The verifier only requires they stay internally
consistent (PLAN007 monotonicity), which selectivities in [0, 1]
guarantee.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from ...columnar.dtype import TypeId
from .. import exprs as ex

__all__ = [
    "ColumnSketch", "TableStats", "sketch_column", "collect_table",
    "selectivity", "hll_estimate", "DEFAULT_SELECTIVITY",
]

DEFAULT_SELECTIVITY = 0.5

_M64 = np.uint64(0xFFFFFFFFFFFFFFFF)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer, vectorized over a uint64 array."""
    x = x.astype(np.uint64, copy=True)
    x += np.uint64(0x9E3779B97F4A7C15)
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return x


def _clz_tail(w: np.ndarray, width: int) -> np.ndarray:
    """Leading-zero count of each uint64 in ``w`` restricted to its
    top ``width`` bits, exactly (6-step binary search, no float
    round-trip — float log2 misranks values near powers of two)."""
    w = w.astype(np.uint64, copy=True)
    n = np.zeros(w.shape, dtype=np.int64)
    shift = 32
    top = np.uint64(64)
    while shift >= 1:
        s = np.uint64(shift)
        mask = (w >> (top - s)) == np.uint64(0)
        n = np.where(mask, n + shift, n)
        w = np.where(mask, w << s, w)
        shift //= 2
    return np.minimum(n, width)


def hll_estimate(registers: np.ndarray) -> float:
    """Standard HyperLogLog estimate with the small-range linear-
    counting correction (the only regime our table sizes hit hard)."""
    m = registers.shape[0]
    if m >= 128:
        alpha = 0.7213 / (1.0 + 1.079 / m)
    elif m >= 64:
        alpha = 0.709
    elif m >= 32:
        alpha = 0.697
    else:
        alpha = 0.673
    est = alpha * m * m / float(np.sum(np.power(2.0, -registers.astype(np.float64))))
    zeros = int(np.sum(registers == 0))
    if est <= 2.5 * m and zeros > 0:
        est = m * math.log(m / zeros)
    return max(est, 1.0)


@dataclasses.dataclass(frozen=True)
class ColumnSketch:
    """One column's compile-time statistics (values in the column's
    LOGICAL domain — decimals stay unscaled, FLOAT64 bit-lanes are
    decoded before sketching)."""

    rows: int
    nulls: int
    min_val: Optional[float]
    max_val: Optional[float]
    ndv: float
    #: equi-depth bin edges over the non-null values, len == bins + 1
    #: (empty when there are no non-null values)
    edges: Tuple[float, ...]
    #: EXACT all-values-distinct witness (np.unique over the full scan)
    #: — False whenever the column was sampled, because a sample cannot
    #: prove global uniqueness. The build-side/strategy rules key off
    #: this: dense payload maps reject duplicate build keys at runtime,
    #: so an approximate "probably unique" is not good enough
    unique: bool = False

    @property
    def null_fraction(self) -> float:
        return self.nulls / self.rows if self.rows else 0.0

    @property
    def non_null(self) -> int:
        return self.rows - self.nulls

    # -- selectivity primitives (fractions of ALL rows) -------------------

    def sel_is_null(self, want_null: bool) -> float:
        return self.null_fraction if want_null else 1.0 - self.null_fraction

    def sel_eq(self, v: float) -> float:
        if self.non_null == 0:
            return 0.0
        if self.min_val is not None and (v < self.min_val or v > self.max_val):
            return 0.0
        return (1.0 - self.null_fraction) / max(self.ndv, 1.0)

    def _frac_below(self, v: float, inclusive: bool) -> float:
        """Fraction of NON-NULL values < v (<= v when inclusive),
        estimated from the equi-depth histogram; partial bins count in
        full, so the answer upper-bounds the truth within one bin."""
        if not self.edges or self.non_null == 0:
            return DEFAULT_SELECTIVITY
        edges = np.asarray(self.edges, dtype=np.float64)
        nbins = len(edges) - 1
        if v < edges[0]:
            return 0.0
        if v > edges[-1] or (inclusive and v == edges[-1]):
            return 1.0
        side = "right" if inclusive else "left"
        # bins fully below v plus the partial bin v falls in, counted
        # in full (equi-depth: each bin holds 1/nbins of the mass)
        pos = int(np.searchsorted(edges, v, side=side))
        return min(1.0, pos / nbins)

    def sel_cmp(self, op: str, v: float) -> float:
        """Fraction of ALL rows satisfying ``col <op> v`` (NULLs never
        satisfy a comparison)."""
        nn = 1.0 - self.null_fraction
        if self.non_null == 0:
            return 0.0
        if op == "eq":
            return self.sel_eq(v)
        if op == "ne":
            return max(0.0, nn - self.sel_eq(v))
        if op == "lt":
            f = self._frac_below(v, inclusive=False)
        elif op == "le":
            f = self._frac_below(v, inclusive=True)
        elif op == "ge":
            f = 1.0 - self._frac_below(v, inclusive=False)
        elif op == "gt":
            f = 1.0 - self._frac_below(v, inclusive=True)
        else:
            return DEFAULT_SELECTIVITY
        return min(max(f, 0.0), 1.0) * nn


@dataclasses.dataclass(frozen=True)
class TableStats:
    """Row count + per-column sketches for one bound table."""

    rows: int
    columns: "dict[str, ColumnSketch]"

    def sketch(self, name: str) -> Optional[ColumnSketch]:
        return self.columns.get(name)

    @property
    def memory_bytes(self) -> int:
        """Resident size of the sketch set (the PACKAGING budget row)."""
        per = sum(8 * (len(s.edges) + 6) for s in self.columns.values())
        return per + 64 * max(1, len(self.columns))


_SKETCHABLE = frozenset({
    TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64,
    TypeId.UINT8, TypeId.UINT16, TypeId.UINT32, TypeId.UINT64,
    TypeId.FLOAT32, TypeId.FLOAT64,
    TypeId.DECIMAL32, TypeId.DECIMAL64,
})


def _host_values(col) -> Optional[np.ndarray]:
    """Column data as a host float64 array in the logical domain, or
    None when the column isn't sketchable (strings, DECIMAL128,
    nested)."""
    if col.dtype.id not in _SKETCHABLE or col.data is None:
        return None
    data = np.asarray(col.data)
    if data.ndim != 1:
        return None
    if col.dtype.id == TypeId.FLOAT64:
        data = data.view(np.float64)
    return data.astype(np.float64, copy=False)


def sketch_column(col, *, bins: int = 16, hll_bits: int = 9,
                  max_rows: int = 1 << 18) -> Optional[ColumnSketch]:
    """Sketch one column, or None for unsketchable types. ``max_rows``
    caps the scan (head sample) so stats collection stays O(bounded)
    whatever the table size."""
    vals = _host_values(col)
    if vals is None:
        return None
    rows = int(vals.shape[0])
    valid = np.asarray(col.validity) if col.validity is not None else None
    if rows > max_rows:
        scale = rows / max_rows
        vals = vals[:max_rows]
        valid = valid[:max_rows] if valid is not None else None
    else:
        scale = 1.0
    if valid is not None:
        nn_vals = vals[valid]
    else:
        nn_vals = vals
    sampled = vals.shape[0]
    nulls = int(round((sampled - nn_vals.shape[0]) * scale))
    if nn_vals.shape[0] == 0:
        return ColumnSketch(rows=rows, nulls=rows, min_val=None,
                            max_val=None, ndv=0.0, edges=())
    # distinct count: HLL over mixed value bits
    m = 1 << hll_bits
    h = _mix64(nn_vals.view(np.uint64))
    idx = (h >> np.uint64(64 - hll_bits)).astype(np.int64)
    tail_width = 64 - hll_bits
    rho = _clz_tail(h << np.uint64(hll_bits), tail_width) + 1
    registers = np.zeros(m, dtype=np.int64)
    np.maximum.at(registers, idx, rho)
    ndv = min(hll_estimate(registers), float(nn_vals.shape[0])) * scale
    # equi-depth histogram
    srt = np.sort(nn_vals)
    qs = np.linspace(0.0, 1.0, bins + 1)
    edges = tuple(float(x) for x in np.quantile(srt, qs))
    unique = bool(scale == 1.0 and nulls == 0
                  and (srt.shape[0] < 2 or bool(np.all(srt[1:] != srt[:-1]))))
    return ColumnSketch(
        rows=rows,
        nulls=nulls,
        min_val=float(srt[0]),
        max_val=float(srt[-1]),
        ndv=max(1.0, ndv),
        edges=edges,
        unique=unique,
    )


def collect_table(table, *, bins: int = 16, hll_bits: int = 9,
                  max_rows: int = 1 << 18) -> TableStats:
    """Sketch every sketchable column of ``table``."""
    cols = {}
    for name, col in zip(table.names, table.columns):
        s = sketch_column(col, bins=bins, hll_bits=hll_bits,
                          max_rows=max_rows)
        if s is not None:
            cols[name] = s
    return TableStats(rows=table.num_rows, columns=cols)


# ---------------------------------------------------------------------------
# predicate selectivity
# ---------------------------------------------------------------------------

_CMP_OPS = ("eq", "ne", "lt", "le", "gt", "ge")

Resolver = Callable[[str], Optional[ColumnSketch]]


def _col_lit(e) -> Optional[Tuple[str, object, str]]:
    """Match ``col <op> lit`` either way round -> (col, value,
    normalized op), else None."""
    if not isinstance(e, ex._PBin) or e.op not in _CMP_OPS:
        return None
    flipped = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
               "eq": "eq", "ne": "ne"}
    a, b = e.a, e.b
    ca, cb = ex.is_col(a), ex.is_col(b)
    if ca is not None and isinstance(b, ex._PLit):
        return ca, b.value, e.op
    if cb is not None and isinstance(a, ex._PLit):
        return cb, a.value, flipped[e.op]
    return None


def _lit_float(v) -> Optional[float]:
    if isinstance(v, bool) or v is None:
        return None
    if isinstance(v, (int, float)):
        return float(v)
    return None


def selectivity(pred, resolve: Resolver) -> float:
    """Estimated fraction of rows a predicate keeps, in [0, 1]."""
    s = _selectivity(pred, resolve)
    return min(max(s, 0.0), 1.0)


def _selectivity(e, resolve: Resolver) -> float:
    if isinstance(e, ex._PBin):
        if e.op == "and":
            return _selectivity(e.a, resolve) * _selectivity(e.b, resolve)
        if e.op == "or":
            sa = _selectivity(e.a, resolve)
            sb = _selectivity(e.b, resolve)
            return sa + sb - sa * sb
        m = _col_lit(e)
        if m is not None:
            name, raw, op = m
            sk = resolve(name)
            v = _lit_float(raw)
            if sk is not None and v is not None:
                return sk.sel_cmp(op, v)
            return DEFAULT_SELECTIVITY
        if e.op in _CMP_OPS:
            # col-vs-col comparison: eq via the larger ndv, else default
            ca, cb = ex.is_col(e.a), ex.is_col(e.b)
            if e.op == "eq" and ca is not None and cb is not None:
                sa, sb = resolve(ca), resolve(cb)
                if sa is not None and sb is not None:
                    return 1.0 / max(sa.ndv, sb.ndv, 1.0)
            return DEFAULT_SELECTIVITY
        return DEFAULT_SELECTIVITY
    if isinstance(e, ex._PNot):
        return 1.0 - _selectivity(e.a, resolve)
    if isinstance(e, ex._PIsNull):
        c = ex.is_col(e.a)
        if c is not None:
            sk = resolve(c)
            if sk is not None:
                return sk.sel_is_null(e.want_null)
        return 0.1 if e.want_null else 0.9
    if isinstance(e, ex._PLit):
        if e.value is True:
            return 1.0
        if e.value is False or e.value is None:
            return 0.0
        return DEFAULT_SELECTIVITY
    return DEFAULT_SELECTIVITY

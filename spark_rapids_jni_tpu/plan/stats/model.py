"""Cardinality + cost model (srjt-cbo, ISSUE 19).

Two consumers, one set of numbers:

- the **compiler** (plan/compiler.py) asks :class:`Estimator` for
  per-operator row estimates (filter selectivity from sketches, join
  cardinality from distinct counts, aggregate output from group-key
  ndv products) and for per-kind **byte calibration factors** learned
  from the ``artifacts/plan_compile.jsonl`` estimate-vs-actual reports
  (knob ``SRJT_CBO_CALIBRATION``). Those estimates are what memgov
  admission and OOC partitioning trust, replacing the flat
  ``_FILTER_SELECTIVITY = 0.5`` and uncalibrated ``_width`` numbers.

- the **optimizer** (plan/optimizer.py) asks :func:`plan_cost` for a
  modeled scalar cost of a whole logical plan — rows materialized +
  bytes moved (exchange volume weighted by world size, spill risk
  weighted when a budget is armed) — which is the objective the
  join-order / build-side / strategy search minimizes and the number
  the premerge modeled-cost gate compares (chosen vs author order).

Calibration is loaded once per process under a lock, tolerates a
missing or partial artifact file (all factors default to 1.0 — the
chicken-and-egg posture of a fresh checkout), and clamps every factor
into [0.5, 2.0] so one bad archived run can never swing admission by
more than 2x.
"""

from __future__ import annotations

import json
import math
import os
import threading
from typing import Dict, Optional, Tuple

from ...utils import knobs
from .. import nodes as N
from .sketches import ColumnSketch, DEFAULT_SELECTIVITY, TableStats, selectivity

__all__ = [
    "Estimator", "plan_cost", "estimate_rows", "row_width",
    "calibration_factor", "load_calibration", "reset_calibration",
    "choose_ooc_partitions",
]

# clamp band for learned per-kind byte factors: a single archived run
# must never swing admission estimates by more than 2x either way
_CAL_MIN, _CAL_MAX = 0.5, 2.0

_cal_lock = threading.Lock()
_cal_cache: Optional[Dict[str, float]] = None


def load_calibration(path: str) -> Dict[str, float]:
    """Per-stage-kind byte factor (median actual/est) from a
    plan_compile.jsonl artifact; {} when the file is missing, empty,
    or unparseable — estimates then run uncalibrated."""
    ratios: Dict[str, list] = {}
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                for st in rec.get("stages", ()):
                    est = st.get("est_bytes")
                    act = st.get("actual_bytes")
                    kind = st.get("kind")
                    if not kind or not est or act is None:
                        continue
                    ratios.setdefault(kind, []).append(act / est)
    except OSError:
        return {}
    out = {}
    for kind, rs in ratios.items():
        rs.sort()
        med = rs[len(rs) // 2]
        out[kind] = min(_CAL_MAX, max(_CAL_MIN, med))
    return out


def calibration_factor(kind: str) -> float:
    """The learned byte factor for one stage kind (1.0 when no
    artifact has been archived yet). Loaded once per process so every
    compile in a run sees the same model."""
    global _cal_cache
    with _cal_lock:
        if _cal_cache is None:
            path = knobs.get_str("SRJT_CBO_CALIBRATION")
            _cal_cache = load_calibration(path) if path else {}
        return _cal_cache.get(kind, 1.0)


def reset_calibration() -> None:
    """Drop the memoized calibration (tests re-point the knob)."""
    global _cal_cache
    with _cal_lock:
        _cal_cache = None


def row_width(schema) -> int:
    """Estimated bytes per row — mirrors the compiler's width model
    (fixed widths, 16 bytes per variable-width column, +1 validity
    lane)."""
    total = 0
    for d in schema.values():
        total += d.size_bytes if d.is_fixed_width else 16
        total += 1
    return max(total, 1)


class Estimator:
    """Sketch-backed cardinality estimates over a set of bound tables.

    Column sketches are resolved by NAME across every bound table —
    TPC-DS column names are table-prefixed, so the flat namespace is
    unambiguous in practice, and a miss just falls back to the default
    selectivity.
    """

    def __init__(self, stats: Dict[str, TableStats]):
        self.stats = dict(stats)
        self._by_col: Dict[str, ColumnSketch] = {}
        for ts in stats.values():
            for name, sk in ts.columns.items():
                self._by_col.setdefault(name, sk)

    def resolve(self, name: str) -> Optional[ColumnSketch]:
        return self._by_col.get(name)

    def table_rows(self, table: str) -> Optional[int]:
        ts = self.stats.get(table)
        return ts.rows if ts is not None else None

    def ndv(self, name: str, default: float = 0.0) -> float:
        sk = self.resolve(name)
        return sk.ndv if sk is not None else default

    # -- per-operator cardinality ------------------------------------------

    def filter_sel(self, pred) -> float:
        return selectivity(pred, self.resolve)

    def filter_rows(self, child_rows: int, pred) -> int:
        return max(1, int(math.ceil(child_rows * self.filter_sel(pred))))

    def join_rows(self, how: str, left_rows: int, right_rows: int,
                  on) -> int:
        """Equi-join output cardinality from key distinct counts:
        |L join R| ~= |L|*|R| / max(ndv(l), ndv(r)), the standard
        containment assumption; multi-key pairs multiply denominators."""
        if how == "full":
            return max(1, left_rows + right_rows)
        denom = 1.0
        known = False
        for l, r in on:
            nl, nr = self.ndv(l), self.ndv(r)
            d = max(nl, nr)
            if d > 0:
                denom *= d
                known = True
        if how in ("semi", "anti"):
            if not known:
                return max(1, left_rows)
            # fraction of left key values with a build match
            nl = max(1.0, self.ndv(on[0][0], 1.0))
            nr = max(1.0, self.ndv(on[0][1], 1.0))
            match = min(1.0, nr / nl)
            frac = match if how == "semi" else 1.0 - match
            return max(1, int(math.ceil(left_rows * min(1.0, max(frac, 1.0 / max(left_rows, 1))))))
        if not known:
            inner = left_rows
        else:
            inner = left_rows * right_rows / denom
        inner = max(1, min(int(math.ceil(inner)), max(1, left_rows) * max(1, right_rows)))
        if how == "left":
            return max(left_rows, inner)
        return inner

    def agg_rows(self, child_rows: int, keys) -> int:
        """GROUP BY output: product of key ndvs, capped by the input."""
        if not keys:
            return 1
        prod = 1.0
        known = False
        for k in keys:
            n = self.ndv(k)
            if n > 0:
                prod *= n
                known = True
        if not known:
            return max(1, child_rows)
        return max(1, min(int(math.ceil(prod)), max(1, child_rows)))


# ---------------------------------------------------------------------------
# whole-plan modeled cost (the CBO search objective)
# ---------------------------------------------------------------------------


def _rows_of(node: N.Node, est: Estimator, catalog, memo) -> int:
    key = id(node)
    hit = memo.get(key)
    if hit is not None:
        return hit
    r = _rows_calc(node, est, catalog, memo)
    memo[key] = r
    return r


def _rows_calc(node, est, catalog, memo) -> int:
    if isinstance(node, N.Scan):
        r = est.table_rows(node.table)
        return max(1, r if r is not None else 1024)
    if isinstance(node, N.Filter):
        return est.filter_rows(_rows_of(node.input, est, catalog, memo),
                               node.predicate)
    if isinstance(node, N.Join):
        return est.join_rows(node.how,
                             _rows_of(node.left, est, catalog, memo),
                             _rows_of(node.right, est, catalog, memo),
                             node.on)
    if isinstance(node, N.Aggregate):
        return est.agg_rows(_rows_of(node.input, est, catalog, memo),
                            node.keys)
    if isinstance(node, N.Limit):
        return max(1, min(_rows_of(node.input, est, catalog, memo), node.n))
    if isinstance(node, N.UnionAll):
        return sum(_rows_of(b, est, catalog, memo) for b in node.branches)
    if isinstance(node, (N.Project, N.Exchange, N.Sort, N.Window)):
        return _rows_of(node.inputs()[0], est, catalog, memo)
    # sugar (SetOp/Exists/Having/CorrelatedAggFilter) is gone by the
    # time the CBO runs; estimate defensively if one slips through
    child = node.inputs()[0] if node.inputs() else None
    base = _rows_of(child, est, catalog, memo) if child is not None else 1
    return max(1, int(math.ceil(base * DEFAULT_SELECTIVITY)))


def estimate_rows(node: N.Node, est: Estimator, catalog) -> int:
    """Modeled output cardinality of one logical subtree."""
    return _rows_of(node, est, catalog, {})


def plan_cost(node: N.Node, est: Estimator, catalog,
              *, budget: Optional[int] = None) -> float:
    """Modeled scalar cost of a logical plan: per-operator work
    (rows + bytes materialized), exchange volume, and a spill-risk
    surcharge on stages whose working set exceeds an armed budget.
    Only RELATIVE values matter — the search and the premerge gate
    compare plans under the same model."""
    smemo: dict = {}
    rmemo: dict = {}
    seen: dict = {}

    def schema_of(n):
        return N.infer_schema(n, catalog, smemo)

    def passthrough(n) -> bool:
        from .. import exprs as ex
        return (isinstance(n, N.Project)
                and all(ex.is_col(e) == name for name, e in n.exprs))

    def walk(n) -> float:
        if id(n) in seen:
            return 0.0  # shared subtree (CTE): computed once
        seen[id(n)] = True
        c = sum(walk(i) for i in n.inputs())
        if passthrough(n):
            # a pure column permutation/narrowing materializes nothing
            # — column pruning and the reorder rules' restore Projects
            # both wrap subtrees in these, and charging them would make
            # a cost-improving reorder look like a regression
            return c
        rows = _rows_of(n, est, catalog, rmemo)
        width = row_width(schema_of(n))
        out_bytes = rows * width
        op = float(rows + out_bytes / 64.0)
        if isinstance(n, N.Join):
            rrows = _rows_of(n.right, est, catalog, rmemo)
            build_bytes = rrows * row_width(schema_of(n.right))
            op += 2.0 * build_bytes / 64.0  # build + probe table touch
        elif isinstance(n, N.Exchange):
            vol = out_bytes * (n.world - 1) / max(1, n.world)
            op += vol / 16.0  # moving a byte costs ~4x touching one
        elif isinstance(n, (N.Sort, N.Window)):
            op += rows * math.log2(max(2, rows))
        if budget and out_bytes > budget:
            op *= 1.0 + out_bytes / budget  # spill-risk surcharge
        return c + op

    return walk(node)


def choose_ooc_partitions(est_bytes: int, budget: int,
                          *, max_parts: int = 64) -> int:
    """Cost-model K for out-of-core degradation: per-partition fixed
    overhead (spill round-trip, sub-plan compile) makes cost increase
    with K, so the model picks the SMALLEST K whose calibrated
    per-partition peak fits half the budget — the other half covers
    the merge working set and partition skew. 0 when even ``max_parts``
    ways cannot fit."""
    cal = max(est_bytes, int(est_bytes * calibration_factor("aggregate")))
    for k in range(2, max_parts + 1):
        if (cal + k - 1) // k <= budget // 2:
            return k
    return 0

"""Statistics subsystem entry point (srjt-cbo, ISSUE 19).

``table_stats(name, table)`` is the lazy, cached way in: sketches are
collected on first use per (table identity, generation) and cached
against ``cache/tablegen.py`` generation stamps, so invalidation rides
the exact discipline the plan/subresult caches already trust — bump
the generation (``invalidate_table``) and the stale sketch can never
be served again, because the stamp IS the cache key.

The cache is process-global and lock-guarded; it holds at most
``_MAX_CACHED`` table sketch-sets (FIFO eviction) so stats memory
stays bounded whatever the serving tier churns through — see the
PACKAGING "stats memory" note for the per-table bound.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from ...utils import knobs
from .sketches import (ColumnSketch, TableStats, collect_table,
                       hll_estimate, selectivity, sketch_column,
                       DEFAULT_SELECTIVITY)
from .model import (Estimator, calibration_factor, choose_ooc_partitions,
                    load_calibration, plan_cost, reset_calibration,
                    row_width)

__all__ = [
    "ColumnSketch", "TableStats", "collect_table", "sketch_column",
    "selectivity", "hll_estimate", "DEFAULT_SELECTIVITY",
    "Estimator", "plan_cost", "row_width", "calibration_factor",
    "load_calibration", "reset_calibration", "choose_ooc_partitions",
    "enabled", "table_stats", "stats_for_tables", "make_estimator",
    "invalidate_table", "reset",
]

_MAX_CACHED = 256

_lock = threading.Lock()
# (tablegen serial, generation) -> TableStats; insertion-ordered for
# FIFO eviction — guarded by _lock
_cache: Dict[Tuple[int, int], TableStats] = {}


def enabled() -> bool:
    return knobs.get_bool("SRJT_STATS_ENABLED")


def table_stats(table) -> TableStats:
    """Sketches for one bound table, collected lazily and cached
    against the table's generation stamp."""
    # lazy: cache/__init__ imports plan.compiler, which imports this
    # package — tablegen must load after plan is fully initialized
    from ...cache import tablegen

    key = tablegen.stamp(table)
    with _lock:
        hit = _cache.get(key)
    if hit is not None:
        return hit
    ts = collect_table(
        table,
        bins=max(2, knobs.get_int("SRJT_STATS_HISTOGRAM_BINS")),
        hll_bits=min(14, max(4, knobs.get_int("SRJT_STATS_HLL_BITS"))),
        max_rows=max(1, knobs.get_int("SRJT_STATS_MAX_ROWS")),
    )
    with _lock:
        while len(_cache) >= _MAX_CACHED:
            _cache.pop(next(iter(_cache)))
        _cache[key] = ts
    return ts


def stats_for_tables(tables) -> Dict[str, TableStats]:
    return {name: table_stats(t) for name, t in tables.items()}


def make_estimator(tables) -> Optional[Estimator]:
    """The compiler's one-stop: an Estimator over every bound table,
    or None when stats are knobbed off (the compiler then falls back
    to its hand-tuned heuristics)."""
    if not enabled():
        return None
    return Estimator(stats_for_tables(tables))


def invalidate_table(table) -> None:
    """Bump the table's generation: every cached sketch keyed to the
    old stamp is dropped AND unreachable (the new stamp is a new key),
    so a stale sketch cannot survive by construction."""
    from ...cache import tablegen

    serial, _gen = tablegen.stamp(table)
    tablegen.bump(table)
    with _lock:
        for key in [k for k in _cache if k[0] == serial]:
            _cache.pop(key)


def reset() -> None:
    """Drop every cached sketch and the memoized calibration (tests)."""
    with _lock:
        _cache.clear()
    reset_calibration()

"""Ragged byte movement as REGULAR array ops — the TPU answer to the
reference's warp-per-row memcpy kernels (row_conversion.cu:827-874).

XLA:TPU's per-ELEMENT irregular u8 gather/scatter runs at ~0.005 GB/s
(round-2 memo; re-verified), which made the mixed/string transcode axis
pathological (71.6 s at 155-col x 1M). The same hardware moves
ROW-granular gathers fast: measured on v5e, ``jnp.take(pool2d, idx,
axis=0)`` with monotonic indices reaches ~29 GB/s at 128-byte rows and
~109 GB/s for the windowed two-tile form — ~4 orders of magnitude over
element addressing. So every ragged access here is decomposed into

1. an axis-0 gather of fixed-width OVERLAPPING tiles (stride s, width
   2s: any s-aligned window of length <= s+1 lands in ONE tile), and
2. a per-row byte ROTATE/SHIFT done arithmetically on u32 lanes —
   log2(W) conditional lane rolls plus an elementwise per-row sub-word
   shift — all regular VPU ops XLA fuses.

No Pallas needed: the formulation is pure jnp, so the hermetic CPU test
tier runs the exact code the chip runs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu import fails without the TPU plugin; interpret mode still works
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover  # srjt-lint: allow-broad-except(optional TPU-plugin import guard; interpret mode works without pltpu)
    pltpu = None
    _VMEM = None

__all__ = [
    "overlap_tiles",
    "byte_rotate_left",
    "byte_shift_right",
    "padded_extract",
    "assemble_rows",
    "expand_u32_planes",
    "pack_u8_planes",
    "u32_rows_to_u8_flat",
    "flat_u8_to_u32",
    "build_pool32",
    "ragged_compact",
    "ragged_compact_tiered",
]


from .pallas_kernels import on_tpu as _on_tpu  # noqa: E402  (memoized probes)
from .pallas_kernels import pallas_available as _pallas_available  # noqa: E402


def _use_pallas() -> bool:
    # memoized probes (pallas_kernels): this gate sits on every ragged
    # helper's hot path and jax.default_backend() re-walks the backend
    # registry per call (ISSUE 13 satellite)
    return _pallas_available() and _on_tpu()


def _pow2_ceil(v: int) -> int:
    p = 1
    while p < v:
        p *= 2
    return p


def overlap_tiles(buf: jnp.ndarray, stride: int, width: int) -> jnp.ndarray:
    """[L] u8 -> [ceil(L/stride), width] where row w = buf[w*stride :
    w*stride + width] (zero padded past the end). width must be a
    multiple of stride; rows overlap so that any stride-aligned window
    of width-stride+... <= width bytes is contained in one row."""
    if width % stride != 0:
        raise ValueError("width must be a multiple of stride")
    n = buf.shape[0]
    rows = max((n + stride - 1) // stride, 1)
    padded = jnp.zeros((rows * stride + width,), jnp.uint8).at[:n].set(buf)
    parts = [
        padded[k * stride : (rows + k) * stride].reshape(rows, stride)
        for k in range(width // stride)
    ]
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


def _as_u32(x: jnp.ndarray) -> jnp.ndarray:
    n, w = x.shape
    return lax.bitcast_convert_type(x.reshape(n, w // 4, 4), jnp.uint32)


def _as_u8(x32: jnp.ndarray) -> jnp.ndarray:
    n, lanes = x32.shape
    return lax.bitcast_convert_type(x32, jnp.uint8).reshape(n, lanes * 4)


def _rotl_u32(x32: jnp.ndarray, sl: jnp.ndarray, rb: jnp.ndarray) -> jnp.ndarray:
    """Per-row byte rotate-left of [B, L] u32 lanes. sl [B, 1] i32 lane
    count in [0, L); rb [B, 1] u32 sub-word shift in BITS (0/8/16/24).
    Log2(L) conditional lane rolls + one elementwise sub-word combine —
    runs entirely in registers inside a Pallas kernel. No dtype
    conversions inside: Mosaic's convert-lowering recurses to a Python
    RecursionError on in-kernel i32<->u32 astype (observed), so callers
    precompute both operand dtypes."""
    w = x32.shape[1]
    k = 1
    while k < w:
        rolled = jnp.concatenate([x32[:, k:], x32[:, :k]], axis=1)
        x32 = jnp.where((sl & k) != 0, rolled, x32)
        k *= 2
    nxt = jnp.concatenate([x32[:, 1:], x32[:, :1]], axis=1)
    combined = (x32 >> rb) | (nxt << (jnp.uint32(32) - rb))
    return jnp.where(rb == jnp.uint32(0), x32, combined)


def _shr_u32(x32: jnp.ndarray, sl: jnp.ndarray, rb: jnp.ndarray) -> jnp.ndarray:
    """Per-row byte shift-right (zero fill) of [B, L] u32 lanes. sl
    [B, 1] i32 lane count (>= L clears the row); rb [B, 1] u32 sub-word
    shift in bits. Same no-conversion discipline as _rotl_u32."""
    n, lanes = x32.shape
    ls = jnp.minimum(sl, lanes)
    k = 1
    while k < lanes:
        shifted = jnp.concatenate(
            [jnp.zeros((n, min(k, lanes)), jnp.uint32), x32[:, : max(lanes - k, 0)]], axis=1
        )
        x32 = jnp.where((ls & k) != 0, shifted, x32)
        k *= 2
    x32 = jnp.where(ls >= lanes, jnp.uint32(0), x32)
    prv = jnp.concatenate([jnp.zeros((n, 1), jnp.uint32), x32[:, :-1]], axis=1)
    combined = (x32 << rb) | (prv >> (jnp.uint32(32) - rb))
    return jnp.where(rb == jnp.uint32(0), x32, combined)


def _split_shift(sh_bytes: jnp.ndarray):
    """[N] (or [N, 1]) byte shift -> ([N, 1] i32 lane count, [N, 1] u32
    sub-word bit count): the operand pair _rotl_u32/_shr_u32 take, in
    their final dtypes so no conversion happens inside a kernel."""
    sh = sh_bytes.astype(jnp.int32)[:, None] if sh_bytes.ndim == 1 else sh_bytes.astype(jnp.int32)
    return sh // 4, ((sh % 4) * 8).astype(jnp.uint32)


# ---------------------------------------------------------------------------
# u32 <-> u8 tile relayout (Pallas sublane bitcast)
# ---------------------------------------------------------------------------
#
# A u32 array and its byte stream have IDENTICAL linear content; XLA:TPU
# still charges a full elementwise conversion with a 32x tile-padded
# [..., 4] u8 temp for the dtype change (u32 tiles are (8, 128), u8
# tiles (32, 128)). Mosaic's `tpu.bitcast` reinterprets a vreg across
# SUBLANES — u32 [P, N] -> u8 [4P, N] with byte k of word (p, n) at row
# (4p + k, n) — so the whole relayout is one streaming kernel: one HBM
# read, one write, no padded temp. Composed with the (fast, ~1.5 TB/s)
# u8 transpose this replaces the lax.map chunked converter that ran the
# 212-col encode axis at 34 GB/s (round-3 profile: 48 of 50.8 ms).
#
# NOTE Mosaic fragility (all verified on v5e): block index_maps MUST use
# jnp.int32 constants (a plain Python `0` crashes the compiler), and
# neither strided lane refs (pl.Slice(stride=4)) nor in-kernel
# swapaxes/reshape rearranges compile — the sublane bitcast is the one
# shape this Mosaic lowers reliably.

_XP_LBLK = 512  # lanes per grid step


def _expand_kernel(x_ref, o_ref):
    o_ref[:] = pltpu.bitcast(x_ref[:], jnp.uint8)


def _pack_kernel(x_ref, o_ref):
    o_ref[:] = pltpu.bitcast(x_ref[:], jnp.uint32)


def _plane_lblk(p: int) -> int:
    # bound the (P, lblk) u32 + (4P, lblk) u8 blocks to ~4 MB of VMEM
    lblk = _XP_LBLK
    while lblk > 128 and p * lblk * 8 > (4 << 20):
        lblk //= 2
    return lblk


def expand_u32_planes(x32: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """u32 [P, N] -> u8 [4P, N] where row 4p+k holds byte k (LE) of
    plane p. Pallas on TPU; jnp fallback elsewhere."""
    p, n = x32.shape
    if not (_use_pallas() or interpret):
        by = lax.bitcast_convert_type(x32, jnp.uint8)  # [P, N, 4]
        return by.transpose(0, 2, 1).reshape(4 * p, n)
    lblk = _plane_lblk(p)
    cols = (n + lblk - 1) // lblk * lblk
    xp = jnp.pad(x32, ((0, 0), (0, cols - n))) if cols != n else x32
    out = pl.pallas_call(
        _expand_kernel,
        out_shape=jax.ShapeDtypeStruct((4 * p, cols), jnp.uint8),
        grid=(cols // lblk,),
        in_specs=[pl.BlockSpec((p, lblk), lambda i: (jnp.int32(0), i),
                               memory_space=_VMEM if not interpret else None)],
        out_specs=pl.BlockSpec((4 * p, lblk), lambda i: (jnp.int32(0), i),
                               memory_space=_VMEM if not interpret else None),
        interpret=interpret,
    )(xp)
    return out[:, :n] if cols != n else out


def pack_u8_planes(x8: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """u8 [4P, N] -> u32 [P, N]: inverse of expand_u32_planes."""
    p4, n = x8.shape
    p = p4 // 4
    if not (_use_pallas() or interpret):
        by = x8.reshape(p, 4, n).transpose(0, 2, 1)  # [P, N, 4]
        return lax.bitcast_convert_type(by, jnp.uint32)
    lblk = _plane_lblk(p)
    cols = (n + lblk - 1) // lblk * lblk
    xp = jnp.pad(x8, ((0, 0), (0, cols - n))) if cols != n else x8
    out = pl.pallas_call(
        _pack_kernel,
        out_shape=jax.ShapeDtypeStruct((p, cols), jnp.uint32),
        grid=(cols // lblk,),
        in_specs=[pl.BlockSpec((4 * p, lblk), lambda i: (jnp.int32(0), i),
                               memory_space=_VMEM if not interpret else None)],
        out_specs=pl.BlockSpec((p, lblk), lambda i: (jnp.int32(0), i),
                               memory_space=_VMEM if not interpret else None),
        interpret=interpret,
    )(xp)
    return out[:, :n] if cols != n else out


def u32_rows_to_u8_flat(x32: jnp.ndarray) -> jnp.ndarray:
    """[R, L] u32 -> [R * 4L] u8 little-endian bytes.

    TPU: transpose -> sublane-expand kernel -> transpose back — three
    streaming passes (~7 ms at 1M x 196 vs 48 ms for the chunked
    converter below). Elsewhere: lax.map row blocks — the u32->u8
    bitcast materializes a [..., L, 4] u8 whose tiled layout pads the
    4-lane minor dim 32x, so converting a GB-scale array in one op is a
    40+ GB allocation (observed); per-block the padded temp is bounded
    to ~70 MB."""
    r, lanes = x32.shape
    if _use_pallas() and r >= 8 and lanes >= 1:
        by = expand_u32_planes(x32.T)  # [4L, R]
        return by.T.reshape(-1)
    nbt = max(1, (1 << 19) // max(lanes, 1))
    rows = (r + nbt - 1) // nbt * nbt
    xp = _pad_rows(x32, rows)

    def block(xb):
        return lax.bitcast_convert_type(xb, jnp.uint8).reshape(nbt, lanes * 4)

    out = lax.map(block, xp.reshape(rows // nbt, nbt, lanes))
    return out.reshape(-1)[: r * lanes * 4]


def byte_rotate_left(x: jnp.ndarray, shift_bytes: jnp.ndarray) -> jnp.ndarray:
    """Rotate each row of [N, W] u8 left by a per-row byte count in
    [0, W). W must be a multiple of 4 (u32 lanes; pow2 W keeps the roll
    ladder minimal). Little-endian lane order matches byte order."""
    sl, rb = _split_shift(shift_bytes)
    return _as_u8(_rotl_u32(_as_u32(x), sl, rb))


def byte_shift_right(x: jnp.ndarray, shift_bytes: jnp.ndarray) -> jnp.ndarray:
    """Shift each row of [N, W] u8 right by a per-row byte count >= 0,
    zero-filling on the left (amounts >= W clear the row). W must be a
    multiple of 4."""
    sl, rb = _split_shift(jnp.minimum(shift_bytes.astype(jnp.int64), x.shape[1]))
    return _as_u8(_shr_u32(_as_u32(x), sl, rb))


# ---------------------------------------------------------------------------
# Pallas epilogue kernels
# ---------------------------------------------------------------------------
#
# The u32 shift ladders are correct as plain XLA but each conditional
# roll materializes a full-width HLO temp: 35 GB of temps (OOM) unfused,
# or ~7 HBM passes fused — measured seconds per call at the 1M-row
# mixed axis. Inside a Pallas kernel the whole ladder runs in
# VMEM/registers: one HBM read + one write per tile.

_PK_BLK = 512  # rows per grid step


def _rows_spec(blk: int, lanes: int, interpret: bool):
    return pl.BlockSpec(
        (blk, lanes),
        lambda i: (i, jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )


def _scal_spec(blk: int, interpret: bool):
    """Per-row scalars travel LANE-PACKED as [G, 1, blk]: a [N, 1] i32
    operand's T(8,128) HBM layout pads the single lane to 128 (a 128x
    expansion — 512 MB per scalar at N=1M, observed OOM); lane-packing
    stores them dense and the kernel reshapes one [1, blk] row to
    [blk, 1] (a cheap in-VMEM relayout, verified lowering)."""
    return pl.BlockSpec(
        (1, 1, blk),
        lambda i: (i, jnp.int32(0), jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )


def _pack_scalar(a: jnp.ndarray, blk: int, rows: int) -> jnp.ndarray:
    return _pad_rows(a, rows).reshape(rows // blk, 1, blk)


def _scal(ref) -> jnp.ndarray:
    return ref[0].reshape(-1, 1)  # [1, blk] -> [blk, 1]


def _pad_rows(a: jnp.ndarray, rows: int) -> jnp.ndarray:
    if a.shape[0] == rows:
        return a
    return jnp.pad(a, ((0, rows - a.shape[0]),) + ((0, 0),) * (a.ndim - 1))


def _rotl_take_kernel(sl_ref, rb_ref, x_ref, o_ref, *, out_lanes: int):
    o_ref[:] = _rotl_u32(x_ref[:], _scal(sl_ref), _scal(rb_ref))[:, :out_lanes]


def rotl_take(
    x: jnp.ndarray, shift_bytes: jnp.ndarray, out_w: int, interpret: bool = False
) -> jnp.ndarray:
    """byte_rotate_left(x, sh)[:, :out_w] — Pallas on TPU (ladder in
    VMEM), plain-jnp fallback elsewhere. out_w % 4 == 0. interpret=True
    forces the kernel through the Pallas interpreter (hermetic CPU
    testing of the kernel body)."""
    if not (_use_pallas() or interpret):
        return byte_rotate_left(x, shift_bytes)[:, :out_w]
    n, w = x.shape
    rows = max((n + _PK_BLK - 1) // _PK_BLK * _PK_BLK, _PK_BLK)
    x32 = _as_u32(_pad_rows(x, rows))
    sl, rb = _split_shift(shift_bytes.astype(jnp.int32))
    out32 = pl.pallas_call(
        functools.partial(_rotl_take_kernel, out_lanes=out_w // 4),
        out_shape=jax.ShapeDtypeStruct((rows, out_w // 4), jnp.uint32),
        grid=(rows // _PK_BLK,),
        in_specs=[_scal_spec(_PK_BLK, interpret)] * 2
        + [_rows_spec(_PK_BLK, w // 4, interpret)],
        out_specs=_rows_spec(_PK_BLK, out_w // 4, interpret),
        interpret=interpret,
    )(
        _pack_scalar(sl[:, 0], _PK_BLK, rows),
        _pack_scalar(rb[:, 0], _PK_BLK, rows),
        x32,
    )
    return _as_u8(out32)[:n]


def rotl_take32(
    x32: jnp.ndarray, shift_bytes: jnp.ndarray, out_w: int, interpret: bool = False
) -> jnp.ndarray:
    """rotl_take for a u32-lane input [N, W/4]: byte rotate-left by
    shift_bytes, keep out_w bytes, return [N, out_w] u8. Same kernel as
    rotl_take minus the [N, W]-u8 -> u32 conversion (which pads ~4x at
    GB scale)."""
    n, w4 = x32.shape
    if not (_use_pallas() or interpret):
        return byte_rotate_left(_as_u8(x32), shift_bytes)[:, :out_w]
    rows = max((n + _PK_BLK - 1) // _PK_BLK * _PK_BLK, _PK_BLK)
    sl, rb = _split_shift(shift_bytes.astype(jnp.int32))
    out32 = pl.pallas_call(
        functools.partial(_rotl_take_kernel, out_lanes=out_w // 4),
        out_shape=jax.ShapeDtypeStruct((rows, out_w // 4), jnp.uint32),
        grid=(rows // _PK_BLK,),
        in_specs=[_scal_spec(_PK_BLK, interpret)] * 2
        + [_rows_spec(_PK_BLK, w4, interpret)],
        out_specs=_rows_spec(_PK_BLK, out_w // 4, interpret),
        interpret=interpret,
    )(
        _pack_scalar(sl[:, 0], _PK_BLK, rows),
        _pack_scalar(rb[:, 0], _PK_BLK, rows),
        _pad_rows(x32, rows),
    )
    return _as_u8(out32)[:n]


def _vacc_kernel(*refs, lane_offs: tuple, out_lanes: int):
    """Accumulate the packed string matrices into the variable section:
    refs = (sl_0..sl_{K-1}, rb_0..rb_{K-1}, packed_p, out); column k's
    lanes live at lane_offs[k]:lane_offs[k+1] of packed_p.

    Accumulates THROUGH the output ref, not an SSA chain: with a chained
    `v = v | shr(...)` Mosaic's stack estimate keeps every column's
    ladder live at once (21.9 MB > the 16 MB scoped-vmem limit at 16
    cols); read-modify-write frees each column's temps before the
    next."""
    num_cols = len(lane_offs) - 1
    pp_ref = refs[-2]
    o_ref = refs[-1]
    o_ref[:] = jnp.zeros((o_ref.shape[0], out_lanes), jnp.uint32)
    for k in range(num_cols):
        sl = _scal(refs[k])
        rb = _scal(refs[num_cols + k])
        p32 = pp_ref[:, lane_offs[k] : lane_offs[k + 1]]
        if p32.shape[1] < out_lanes:
            zero = jnp.zeros((p32.shape[0], out_lanes - p32.shape[1]), jnp.uint32)
            p32 = jnp.concatenate([p32, zero], axis=1)
        o_ref[:] |= _shr_u32(p32, sl, rb)  # strings are disjoint per row


def var_accumulate(p_mats, shifts, maxvar: int, interpret: bool = False) -> jnp.ndarray:
    """Sum_k byte_shift_right(pad(p_k, maxvar), s_k), returned as
    [N, maxvar/4] u32 lanes — Pallas on TPU, jnp fallback elsewhere.
    p_k widths % 4 == 0; maxvar % 4 == 0."""
    n = p_mats[0].shape[0]
    if not (_use_pallas() or interpret):
        v = jnp.zeros((n, maxvar), jnp.uint8)
        for p, s in zip(p_mats, shifts):
            if p.shape[1] < maxvar:
                p = jnp.pad(p, ((0, 0), (0, maxvar - p.shape[1])))
            v = v + byte_shift_right(p, s)
        return _as_u32(v)
    # block rows scale inversely with the section width (the ladder's
    # live VMEM intermediates are [blk, >=128-lane] tiles)
    blk = _PK_BLK
    while blk > 32 and blk * maxvar > 64 * 1792:
        blk //= 2
    rows = max((n + blk - 1) // blk * blk, blk)
    k = len(p_mats)
    packed_args = []
    for sarr in shifts:
        sl, rb = _split_shift(sarr.astype(jnp.int32))
        packed_args.append((sl[:, 0], rb[:, 0]))
    # ONE packed u8 matrix, lanes padded to a 128 multiple: sixteen
    # separate [N, 8-lane] u32 operands tile-pad 16x each (480 MB a
    # piece at N=1M, observed OOM)
    lane_offs = [0]
    for p in p_mats:
        lane_offs.append(lane_offs[-1] + p.shape[1] // 4)
    pad_lanes = (lane_offs[-1] + 127) // 128 * 128 - lane_offs[-1]
    pieces = [_pad_rows(p, rows) for p in p_mats]
    if pad_lanes:
        pieces.append(jnp.zeros((rows, pad_lanes * 4), jnp.uint8))
    packed = _as_u32(jnp.concatenate(pieces, axis=1))
    out32 = pl.pallas_call(
        functools.partial(
            _vacc_kernel, lane_offs=tuple(lane_offs), out_lanes=maxvar // 4
        ),
        out_shape=jax.ShapeDtypeStruct((rows, maxvar // 4), jnp.uint32),
        grid=(rows // blk,),
        in_specs=[_scal_spec(blk, interpret)] * (2 * k)
        + [_rows_spec(blk, packed.shape[1], interpret)],
        out_specs=_rows_spec(blk, maxvar // 4, interpret),
        interpret=interpret,
    )(
        *[_pack_scalar(sl, blk, rows) for sl, _ in packed_args],
        *[_pack_scalar(rb, blk, rows) for _, rb in packed_args],
        packed,
    )
    return out32[:n]


def _asm_kernel(psl_ref, prb_ref, dsl_ref, drb_ref, al_ref, a0_ref, a1_ref, c0_ref, o_ref, *, g_lanes: int):
    ga = jnp.concatenate([a0_ref[:], a1_ref[:]], axis=1)  # VMEM concat
    rot_a = _rotl_u32(ga, _scal(psl_ref), _scal(prb_ref))[:, :g_lanes]
    rot_c = _shr_u32(c0_ref[:], _scal(dsl_ref), _scal(drb_ref))
    lane_byte = jax.lax.broadcasted_iota(jnp.int32, (1, g_lanes), 1) * 4
    o_ref[:] = jnp.where(lane_byte < _scal(al_ref), rot_a, rot_c)


def _asm_epilogue(a0, a1, c0, pmod, delta, alen, g_tile: int, interpret: bool = False) -> jnp.ndarray:
    """Combine the gathered u32 sources into final dst tiles: rotate the
    in-row window (two adjacent tiles, concatenated in VMEM), right-
    shift the next-row head, select at the 8-aligned row boundary."""
    t = a0.shape[0]
    g4 = g_tile // 4
    if not (_use_pallas() or interpret):
        ga = _as_u8(jnp.concatenate([a0, a1], axis=1))
        rot_a = byte_rotate_left(ga, pmod)[:, :g_tile]
        rot_c = byte_shift_right(_as_u8(c0), delta)
        take_a = jnp.arange(g_tile, dtype=jnp.int32)[None, :] < alen[:, None]
        return _as_u32(jnp.where(take_a, rot_a, rot_c))
    rows = max((t + _PK_BLK - 1) // _PK_BLK * _PK_BLK, _PK_BLK)
    psl, prb = _split_shift(pmod.astype(jnp.int32))
    dsl, drb = _split_shift(delta.astype(jnp.int32))
    return pl.pallas_call(
        functools.partial(_asm_kernel, g_lanes=g4),
        out_shape=jax.ShapeDtypeStruct((rows, g4), jnp.uint32),
        grid=(rows // _PK_BLK,),
        in_specs=[_scal_spec(_PK_BLK, interpret)] * 5
        + [_rows_spec(_PK_BLK, g4, interpret)] * 3,
        out_specs=_rows_spec(_PK_BLK, g4, interpret),
        interpret=interpret,
    )(
        _pack_scalar(psl[:, 0], _PK_BLK, rows),
        _pack_scalar(prb[:, 0], _PK_BLK, rows),
        _pack_scalar(dsl[:, 0], _PK_BLK, rows),
        _pack_scalar(drb[:, 0], _PK_BLK, rows),
        _pack_scalar(alen.astype(jnp.int32), _PK_BLK, rows),
        _pad_rows(a0, rows),
        _pad_rows(a1, rows),
        _pad_rows(c0, rows),
    )[:t]


def overlap_tiles_u32(buf: jnp.ndarray, stride: int, width: int) -> jnp.ndarray:
    """overlap_tiles emitting u32 LANES: [ceil(L/stride), width/4] u32
    where row w covers buf bytes [w*stride, w*stride + width). stride
    and width must be multiples of 4. The whole relayout happens on the
    FLAT buffer (flat_u8_to_u32) — a [N, width]-u8 tile matrix
    converted to u32 per element pads ~4x at GB scale and OOMed the
    compile at the 1Mx155 mixed-decode axis (two 7.6 GB temps; round-5
    finding)."""
    if width % stride != 0 or stride % 4 != 0:
        raise ValueError("width must be a multiple of stride; stride of 4")
    n = buf.shape[0]
    rows = max((n + stride - 1) // stride, 1)
    padded = jnp.zeros((rows * stride + width,), jnp.uint8).at[:n].set(buf)
    p32 = flat_u8_to_u32(padded)
    s4 = stride // 4
    parts = [
        p32[k * s4 : (rows + k) * s4].reshape(rows, s4) for k in range(width // stride)
    ]
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]


def padded_extract(pool: jnp.ndarray, starts: jnp.ndarray, max_len: int) -> jnp.ndarray:
    """[N] windows of up to ``max_len`` bytes at arbitrary byte offsets
    ``starts`` in ``pool`` -> [N, W] u8 (W = pow2 >= max_len) where row
    r's bytes 0..max_len are pool[starts[r] : starts[r]+max_len].
    Bytes past max_len are tile garbage — callers mask by true length.

    One overlapping-tile gather + one per-row rotate: stride s =
    pow2_ceil(max_len), width 2s, so window [starts % s, starts % s +
    max_len) always lies inside the gathered row (s - 1 + max_len < 2s).
    The tiles live in u32 lanes end to end (overlap_tiles_u32): the row
    gather feeds the rotate kernel directly, with no per-element u8->u32
    conversion at [N, 2s] scale.
    """
    if max_len < 1:
        return jnp.zeros((starts.shape[0], 4), jnp.uint8)
    stride = max(_pow2_ceil(max_len), 4)
    # u32-lane tiles only at wide strides: s/4 >= 128 lanes keeps the
    # tile matrix unpadded. At short strides (string extracts) the u32
    # minor dim would pad up to 16x, while the u8 path's convert temp
    # is proportionally tiny — the OOM it guards against is a
    # wide-stride (row-blob) phenomenon.
    if _use_pallas() and stride >= 512:
        tiles32 = overlap_tiles_u32(pool, stride, 2 * stride)
        idx = (starts // stride).astype(jnp.int32)
        g32 = jnp.take(tiles32, idx, axis=0)  # [N, 2s/4] u32
        return rotl_take32(g32, (starts % stride).astype(jnp.int32), stride)
    tiles = overlap_tiles(pool, stride, 2 * stride)
    idx = (starts // stride).astype(jnp.int32)
    g = jnp.take(tiles, idx, axis=0)  # [N, 2s]
    return rotl_take(g, (starts % stride).astype(jnp.int32), stride)


def flat_u8_to_u32(buf: jnp.ndarray) -> jnp.ndarray:
    """[L] u8 (L % 4 == 0) -> [L/4] u32 little-endian words.

    TPU: the decode twin of u32_rows_to_u8_flat — transpose ->
    sublane-pack kernel -> transpose, three streaming passes over a
    free [R, 512] view. Both the naive [L/4, 4]-view bitcast AND a
    [L/4, 4] transpose charge a 32x tile-padded temp (measured: a
    1.3 GB blob tried to allocate 43 GB and OOMed the compile).
    Elsewhere the view bitcast is free."""
    n4 = buf.shape[0] // 4
    if _use_pallas() and n4 >= 128:
        lanes = 512
        rows = (buf.shape[0] + lanes - 1) // lanes
        padded = (
            jnp.zeros((rows * lanes,), jnp.uint8).at[: buf.shape[0]].set(buf)
            if rows * lanes != buf.shape[0]
            else buf
        )
        m = padded.reshape(rows, lanes).T  # [512, R]: byte b of row r
        packed = pack_u8_planes(m)  # [128, R]: LE word j of row r
        return packed.T.reshape(-1)[:n4]
    return lax.bitcast_convert_type(buf.reshape(n4, 4), jnp.uint32)


def _funnel_u64(pool64: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
    """u64 little-endian word containing pool bytes [s, s+8) for each
    byte address s (pool64 must extend one word past any s): two
    monotone element gathers + a byte funnel shift."""
    q = (s >> 3).astype(jnp.int32)
    g0 = pool64[q]
    g1 = pool64[q + 1]
    rb = ((s & 7) * 8).astype(jnp.uint64)
    hi = jnp.where(rb == 0, jnp.uint64(0), g1 << (jnp.uint64(64) - jnp.maximum(rb, jnp.uint64(1))))
    return (g0 >> rb) | hi


def build_pool32(pool: jnp.ndarray) -> jnp.ndarray:
    """[L] u8 -> flat little-endian u32 word view, padded two words past
    the end (the funnel's q+1 read). Build ONCE per pool and share
    across every ragged_compact over it — the relayout walks the whole
    pool (a GB-scale blob when decoding rows), and 16 string columns
    rebuilding it dominated the first on-chip measurement."""
    plen = int(pool.shape[0])
    pwords = (plen + 4) // 4 + 2
    pool_pad = jnp.zeros((pwords * 4,), jnp.uint8).at[:plen].set(pool)
    return flat_u8_to_u32(pool_pad)


def _funnel_u32(p32: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
    """u32 little-endian word containing pool bytes [s, s+4) for each
    byte address s (p32 must extend one word past any s): two monotone
    element gathers + a byte funnel shift. All flat 1-D — any 2-D view
    with a tiny minor dim tile-pads 32-64x on TPU (measured 43 GB and
    64 GB compile-time OOMs from [N,4]-u8 / [N,2]-u32 views)."""
    q = (s >> 2).astype(jnp.int32)
    g0 = p32[q]
    g1 = p32[q + 1]
    rb = ((s & 3) * 8).astype(jnp.uint32)
    hi = jnp.where(
        rb == 0, jnp.uint32(0), g1 << (jnp.uint32(32) - jnp.maximum(rb, jnp.uint32(1)))
    )
    return (g0 >> rb) | hi


def ragged_compact(
    pool: jnp.ndarray,
    base: jnp.ndarray,
    offs: jnp.ndarray,
    total: int,
    pool32: jnp.ndarray = None,
) -> jnp.ndarray:
    """Dense ragged gather: out[offs[r] + j] = pool[base[r] + j] for
    j < offs[r+1] - offs[r] — the reference's warp-per-row memcpy
    (row_conversion.cu:1141 copy_strings_from_rows) as REGULAR ops.

    ``offs`` [N+1] must be dense (cumsum of lengths); ``base`` [N] must
    be nondecreasing over rows with nonzero length AND source rows must
    not overlap in row order (base[r+1] >= base[r] + len[r]) — true for
    every row-blob layout (a row contains its own strings) and for
    padded matrices (base = r*W, len <= W). Both i64, addresses < 2^31
    (cudf size_type discipline). The no-overlap form makes
    c = base - offs[r] nondecreasing, so ONE packed scatter-max
    ((c << 31) | end_offset) + one cummax resolves the whole
    owner/source mapping.

    Formulation (the decode twin of assemble_rows): per-element u8
    gathers cost ~8 ns/ELEMENT regardless of width (round-3 memo), so
    the unit of movement is the u32 WORD — 2 gathers + a funnel shift
    per 4 output bytes (~4 ns/byte). Because dst is DENSE, each output
    word splits between one OWNER row (the last row whose span covers
    the word's first byte — computed wholesale by the scatter + cummax
    forward-fill trick) and the sub-word HEAD chunks of later rows
    (<= 3 bytes each, disjoint byte lanes, scatter-ADDed). Pure jnp: the
    hermetic CPU tier runs the exact code the chip runs. Everything
    stays FLAT 1-D (see _funnel_u32 on why).
    """
    n = base.shape[0]
    if total == 0 or n == 0:
        return jnp.zeros((0,), jnp.uint8)
    lens = offs[1:] - offs[:-1]
    nw = (total + 3) // 4 + 1

    if pool32 is None:
        pool32 = build_pool32(pool)
    plen = int(pool.shape[0])

    # Owner-row resolution, all in 32-bit lanes (i64 scans on the
    # emulated-64 datapath cost ~2x):
    # - c_w: the owner's src-minus-dst shift, scatter-MAX of the
    #   nondecreasing c = base - offs[r] at each row's first owned word
    #   + cummax forward-fill (s = c_w + 4w addresses the source).
    # - nb_w: valid bytes of word w before the next row takes over =
    #   scatter-MIN of in-word boundary positions (dense dst: the
    #   owner's bytes always end at the FIRST content start inside the
    #   word; word-aligned boundaries need no mask). The final end
    #   (total) joins as a sentinel boundary.
    nonzero = lens > 0
    wfirst = ((offs[:-1] + 3) >> 2).astype(jnp.int32)
    widx = jnp.where(nonzero, wfirst, nw)  # park zero rows off the end
    c_row = (base - offs[:-1]).astype(jnp.int32)  # nondecreasing, >= 0
    c_w = lax.cummax(
        jnp.zeros((nw + 1,), jnp.int32).at[widx].max(c_row, mode="drop")[:nw]
    )

    # every boundary (row starts AND the final total) is an entry of offs
    bpos = (offs & 3).astype(jnp.uint32)
    bword = (offs >> 2).astype(jnp.int32)
    bidx = jnp.where(bpos > 0, bword, nw)  # aligned boundaries: no mask
    nb = (
        jnp.full((nw + 1,), 4, jnp.uint32).at[bidx].min(bpos, mode="drop")[:nw]
    )

    w0 = jnp.arange(nw, dtype=jnp.int64) * 4
    s = jnp.clip(c_w.astype(jnp.int64) + w0, 0, plen)
    cand = _funnel_u32(pool32, s)
    keep = jnp.where(
        nb >= 4, ~jnp.uint32(0), (jnp.uint32(1) << (nb * 8)) - jnp.uint32(1)
    )
    words = cand & keep

    # head chunks: bytes [offs[r], min(offs[r+1], align4up(offs[r])))
    # of each row land in its start word at byte offset offs[r] % 4 —
    # disjoint lanes across rows, so scatter-add composes them
    x = offs[:-1]
    xa = (x + 3) & ~jnp.int64(3)
    chunk = jnp.clip(jnp.minimum(offs[1:], xa) - x, 0, 3).astype(jnp.uint32)
    has = nonzero & (chunk > 0)
    hsrc = _funnel_u32(pool32, jnp.clip(base, 0, plen))
    hmask = (jnp.uint32(1) << (chunk * 8)) - jnp.uint32(1)
    contrib = (hsrc & hmask) << ((x & 3).astype(jnp.uint32) * 8)
    hidx = jnp.where(has, (x >> 2).astype(jnp.int32), nw)
    words = (
        jnp.concatenate([words, jnp.zeros((1,), jnp.uint32)])
        .at[hidx]
        .add(jnp.where(has, contrib, jnp.uint32(0)), mode="drop")[:nw]
    )

    # flat u32 words -> u8 stream via the sublane-expand path (a direct
    # u32 -> u8 bitcast charges the 32x padded temp)
    lanes = 512
    rows = (nw + lanes - 1) // lanes
    w32p = jnp.zeros((rows * lanes,), jnp.uint32).at[:nw].set(words)
    return u32_rows_to_u8_flat(w32p.reshape(rows, lanes))[:total]


def ragged_compact_tiered(
    pool: jnp.ndarray,
    base: jnp.ndarray,
    offs: jnp.ndarray,
    total: int,
    pool32: jnp.ndarray = None,
) -> jnp.ndarray:
    """EAGER kernel-tier dispatcher for ``ragged_compact`` (ISSUE 13):
    the fused Pallas decode kernel when ``SRJT_PALLAS_DECODE`` arms and
    the probed windows fit (pallas_kernels.pallas_ragged_compact), the
    XLA formulation otherwise — bit-identical either way, and ANY
    kernel-tier failure degrades silently. Host-syncs the window probe,
    so inside-jit callers (the fused multi-column decode program) keep
    calling ``ragged_compact`` directly; row_conversion batches its
    per-column probes through the ``hint`` path instead."""
    from ..utils import metrics
    from ..utils.dispatch import note_tier
    from .pallas_kernels import kernel_tier_mode, pallas_ragged_compact

    mode = kernel_tier_mode("SRJT_PALLAS_DECODE")
    if mode and int(total) > 0:
        try:
            out = pallas_ragged_compact(
                pool, base, offs, int(total), pool32=pool32,
                interpret=mode == "interpret",
            )
        except Exception:  # srjt-lint: allow-broad-except(kernel-tier contract: any kernel failure degrades to the XLA formulation, never errors the decode)
            out = None
            metrics.event("dispatch.tier_degrade", op="ragged_compact", tier=mode)
            note_tier("degrade", "ragged_compact")
        if out is not None:
            note_tier("pallas", "ragged_compact")
            return out
    note_tier("xla", "ragged_compact")
    return ragged_compact(pool, base, offs, int(total), pool32=pool32)


_ASSEMBLE_BLOCK_TILES = 1 << 16  # dst tiles per lax.map block when the
# blob is too large for the single-pass form (bounds per-block temps)
_ASSEMBLE_SINGLE_PASS_BYTES = 768 * (1 << 20)  # single-pass gather cap:
# the three [T, G] gather buffers coexist (3x blob bytes, ~2.3 GB at
# the cap) — fine on 16 GB HBM; above it the lax.map path bounds them.
# Round-3 note: the old 256 MB cap pushed the 1M-row mixed axis
# (537 MB blob) onto 33 SEQUENTIAL map blocks for no memory benefit.


def assemble_rows(
    rp_parts,  # [N, *] u32 lane parts concatenated logically (fixed |
    # var | implicit zero pad): rows are byte sequences in little-endian
    # u32 lanes, bytes >= size_r zero
    sizes: jnp.ndarray,  # [N] int64, 8-aligned true row sizes
    offsets: jnp.ndarray,  # [N+1] int64 dst offsets (cumsum of sizes)
    total: int,  # offsets[-1], static
    min_row_size: int,  # static lower bound on sizes (>= 8, 8-aligned)
) -> jnp.ndarray:
    """Compact padded rows into the exact 8-aligned ragged blob (u8).

    Dst-centric at tile granularity G = pow2 <= min_row_size (so a dst
    tile straddles at most 2 rows): tile t takes G bytes at in-row
    offset p from row r (two adjacent-tile u32 gathers from the free
    reshape view — the windowed form measured ~109 GB/s — concatenated
    in VMEM) and bytes past row r's end come from row r+1's head (third
    gather + zero-filling right shift). All gather indices are
    monotonic. Everything stays in u32 lanes: u8<->u32 bitcasts of 2-D
    arrays are real tiled-layout relayouts, paid once at the final 1-D
    blob view."""
    parts = rp_parts if isinstance(rp_parts, (tuple, list)) else (rp_parts,)
    n = parts[0].shape[0]
    s4 = sum(p.shape[1] for p in parts)
    g_tile = min(_pow2_ceil(min_row_size + 1) // 2, 256)
    g_tile = max(g_tile, 8)
    g4 = g_tile // 4
    # pad S so any in-row window [p, p+2G) with p < size_r stays inside
    # the row's padded span, and keep G | S' so the flat reshape view's
    # tiles never mix two rows
    s_pad4 = (s4 + g4 - 1) // g4 * g4 + 2 * g4
    rp = jnp.concatenate(
        list(parts) + [jnp.zeros((n, s_pad4 - s4), jnp.uint32)], axis=1
    )
    tiles = rp.reshape(n * (s_pad4 // g4), g4)  # free view
    s_pad = s_pad4 * 4

    t_total = (total + g_tile - 1) // g_tile
    single = t_total * g_tile <= _ASSEMBLE_SINGLE_PASS_BYTES
    nbt = t_total if single else _ASSEMBLE_BLOCK_TILES
    nblk = (t_total + nbt - 1) // nbt

    # Per-tile source indices via scatter + forward-fill scan, NOT
    # searchsorted + offsets[r]: searchsorted lowers to ~log2(N) rounds
    # of element gathers and each offsets[r]/sizes[r] is an element
    # gather — the ~0.005 GB/s access class, seconds at 5M tiles.
    # Tile t's owner is max r with D_r <= t*G, i.e. r owns tiles
    # ceil(D_r/G) .. ceil(D_{r+1}/G)-1; row sizes >= G make those
    # first-owned tiles strictly increasing, so scattering each row's
    # (r, D_r, D_{r+1}) into tile ceil(D_r/G) and forward-filling
    # (cummax of monotone values) yields r_t and both offsets for ALL
    # tiles in one scatter + one scan.
    tt = nblk * nbt
    start_tile = ((offsets[:-1] + g_tile - 1) // g_tile).astype(jnp.int32)
    r_fill = (
        jnp.full((tt,), -1, jnp.int32)
        .at[start_tile]
        .max(jnp.arange(n, dtype=jnp.int32), mode="drop")
    )
    d_fill = (
        jnp.full((tt,), jnp.int64(0))
        .at[start_tile]
        .max(offsets[:-1], mode="drop")
    )
    dn_fill = (
        jnp.full((tt,), jnp.int64(0))
        .at[start_tile]
        .max(offsets[1:], mode="drop")
    )
    r = jnp.maximum(lax.cummax(r_fill), 0)
    d_r = lax.cummax(d_fill)  # offsets[r] (monotone in r)
    d_next = lax.cummax(dn_fill)  # offsets[r + 1]

    t0 = jnp.arange(tt, dtype=jnp.int64) * g_tile
    p = jnp.clip(t0 - d_r, 0, s_pad - 2 * g_tile)
    src_a = ((r.astype(jnp.int64) * s_pad + p) // g_tile).astype(jnp.int32)
    r_next = jnp.minimum(r + 1, n - 1)
    src_c = (r_next.astype(jnp.int64) * (s_pad // g_tile)).astype(jnp.int32)
    pmod = (p % g_tile).astype(jnp.int32)
    delta = jnp.clip(d_next - t0, 0, g_tile).astype(jnp.int32)
    alen = jnp.clip(d_next - d_r - p, 0, g_tile).astype(jnp.int32)

    def block(args):
        s_a, s_c, pm, dl, al = args
        a0 = jnp.take(tiles, s_a, axis=0)
        a1 = jnp.take(tiles, s_a + 1, axis=0)
        c0 = jnp.take(tiles, s_c, axis=0)
        return _asm_epilogue(a0, a1, c0, pm, dl, al, g_tile)

    if single:
        out = block((src_a, src_c, pmod, delta, alen))
    else:
        xs = tuple(v.reshape(nblk, nbt) for v in (src_a, src_c, pmod, delta, alen))
        out = lax.map(block, xs)  # [nblk, nbt, g4]
    return u32_rows_to_u8_flat(out.reshape(-1, out.shape[-1]))[:total]

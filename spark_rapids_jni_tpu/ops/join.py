"""Equi-join tier (cudf hash join, SURVEY §2.8) — inner / left /
full-outer / left-semi / left-anti joins.

TPU-first: XLA has no device hash table, so the join is the canonical
sort-probe formulation:

1. factorize both sides' key rows into dense ids by sorting the
   concatenated key table once (shared total-order key machinery),
2. sort the right side's ids; probe each left id with two searchsorted
   calls giving its match range [lo, hi),
3. expand match ranges into (left_idx, right_idx) gather-map pairs with
   a cumsum + searchsorted enumeration (the output-size host sync every
   join implementation pays at allocation time).

SQL semantics: null keys never match (inner rows dropped; left rows
survive with null right side).

Returns cudf-style gather maps; ``inner_join``/``left_join`` build the
joined Table via ops.copying.gather with NULLIFY bounds.

KERNEL TIER (ISSUE 13): single int-key inner/left joins dispatch to
the paged hash-table Pallas kernels (pallas_kernels.build_paged_table /
pallas_probe_paged — the RPA page discipline) instead of the sort-probe
formulation: the build side pages ONCE at build-side scale and the
probe emits each row's match range in one fused pass, skipping the
(nl + nr)-row concatenated sort entirely. Gather maps are BIT-IDENTICAL
to the XLA path (both orders tie-break equal keys by original build
row). Gate: ``SRJT_PALLAS_JOIN`` + backend (see kernel_tier_mode);
unsupported dtypes/shapes, over-cap build sides, and ANY kernel-tier
exception fall back to the XLA formulation silently — a kernel-tier
failure must degrade, never error. The serving tier lands on the op
span and the ``dispatch.tier.*`` counters (utils/dispatch.note_tier).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..columnar.dtype import TypeId
from ..utils import metrics
from ..utils.dispatch import note_tier, op_boundary
from .aggregate import _segment_ids
from .copying import concatenate, gather, gather_column
from .sort import sorted_order

__all__ = [
    "join_gather_maps",
    "semi_anti_gather_map",
    "inner_join",
    "left_join",
    "full_join",
    "left_semi_join",
    "left_anti_join",
]


def _factorize(left_keys: Table, right_keys: Table) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense group ids for each row of both sides (equal keys <-> equal id)."""
    nl, nr = left_keys.num_rows, right_keys.num_rows
    both = concatenate([left_keys, right_keys])
    order = sorted_order(both)
    seg, _num = _segment_ids(both, order)
    ids = jnp.zeros((nl + nr,), jnp.int32).at[order].set(seg)
    return ids[:nl], ids[nl:]


def _any_null(keys: Table) -> Optional[jnp.ndarray]:
    m = None
    for c in keys.columns:
        if c.validity is not None:
            bad = ~c.validity
            m = bad if m is None else (m | bad)
    return m


def _expand_rows(counts: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Enumerate counts[i] output slots per row i: returns (row_of_slot,
    slot_within_row, cum) after the one host sync every join pays for
    the output allocation size."""
    cum = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)])
    total = int(cum[-1])  # host sync: output size
    if total == 0:
        z = jnp.zeros((0,), jnp.int32)
        return z, z, cum
    pair = jnp.arange(total, dtype=jnp.int32)
    row = jnp.searchsorted(cum, pair, side="right").astype(jnp.int32) - 1
    return row, pair - cum[row], cum


# key TypeIds the paged kernel understands: plain integers (the
# order-map/limb machinery is integer-width based; decimals, floats,
# strings, and timestamps keep the XLA formulation)
_PALLAS_KEY_IDS = frozenset(
    {
        TypeId.INT8, TypeId.INT16, TypeId.INT32, TypeId.INT64,
        TypeId.UINT8, TypeId.UINT16, TypeId.UINT32, TypeId.UINT64,
    }
)


def _pallas_join_maps(
    left_keys: Table, right_keys: Table, how: str, interpret: bool
) -> Optional[Tuple[jnp.ndarray, jnp.ndarray]]:
    """Paged-kernel gather maps, or None when the build side gates out
    (empty/all-null/over-cap page table). Bit-identity with the XLA
    path: the probe returns each row's contiguous match range over the
    (bucket, key, row)-sorted build order, and equal keys list original
    build rows in order on both paths."""
    from .pallas_kernels import build_paged_table, pallas_probe_paged

    nl, nr = left_keys.num_rows, right_keys.num_rows
    if nl == 0 or nr == 0:
        return None  # degenerate shapes: the XLA path's early returns apply
    rcol = right_keys.columns[0]
    lcol = left_keys.columns[0]
    table = build_paged_table(rcol.data, rcol.validity)
    if table is None:
        return None
    lo, eq = pallas_probe_paged(lcol.data, lcol.validity, table, interpret)

    counts = eq if how == "inner" else jnp.maximum(eq, 1)
    lrow, within, _cum = _expand_rows(counts)
    if lrow.shape[0] == 0:
        return lrow, within
    matched = eq[lrow] > 0
    rpos = jnp.where(matched, lo[lrow] + within, jnp.int32(-1))
    rrow = jnp.where(
        rpos >= 0,
        table.r_order[jnp.clip(rpos, 0, table.nm - 1)],
        jnp.int32(-1),
    )
    return lrow, rrow


def _pallas_join_usable(left_keys: Table, right_keys: Table, how: str) -> str:
    """The kernel-tier mode for this join shape ('' = keep XLA)."""
    if how not in ("inner", "left"):
        return ""
    if left_keys.num_columns != 1 or right_keys.num_columns != 1:
        return ""
    if left_keys.columns[0].dtype.id not in _PALLAS_KEY_IDS:
        return ""
    if right_keys.columns[0].dtype.id != left_keys.columns[0].dtype.id:
        return ""
    from .pallas_kernels import kernel_tier_mode

    return kernel_tier_mode("SRJT_PALLAS_JOIN")


def join_gather_maps(
    left_keys: Table, right_keys: Table, how: str = "inner"
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(left_idx, right_idx) gather maps; an index of -1 marks the
    null-extended rows of a left/full-outer join (cudf's out-of-bounds
    sentinel discipline)."""
    if how not in ("inner", "left", "full"):
        raise ValueError(f"unsupported join type {how!r}")
    mode = _pallas_join_usable(left_keys, right_keys, how)
    if mode:
        try:
            maps = _pallas_join_maps(
                left_keys, right_keys, how, mode == "interpret"
            )
        except Exception:  # srjt-lint: allow-broad-except(kernel-tier contract: any probe/build failure degrades to the XLA formulation, never errors the join)
            maps = None
            metrics.event("dispatch.tier_degrade", op="join", tier=mode)
            note_tier("degrade", "join_gather_maps")
        if maps is not None:
            note_tier("pallas", "join_gather_maps")
            return maps
    note_tier("xla", "join_gather_maps")
    nl, nr = left_keys.num_rows, right_keys.num_rows
    lid, rid = _factorize(left_keys, right_keys)

    lnull = _any_null(left_keys)
    rnull = _any_null(right_keys)
    if rnull is not None:
        # null right keys can never match: pull them out of the probe set
        rid = jnp.where(rnull, jnp.int32(-1), rid)

    r_order = jnp.argsort(rid).astype(jnp.int32)
    rid_sorted = rid[r_order]

    probe_id = lid if lnull is None else jnp.where(lnull, jnp.int32(-2), lid)
    lo = jnp.searchsorted(rid_sorted, probe_id, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(rid_sorted, probe_id, side="right").astype(jnp.int32)
    counts = hi - lo

    if how in ("left", "full"):
        counts = jnp.maximum(counts, 1)

    lrow, within, _cum = _expand_rows(counts)
    if lrow.shape[0] == 0 and how != "full":
        return lrow, within
    matched = (hi - lo)[lrow] > 0 if lrow.shape[0] else jnp.zeros((0,), bool)
    rpos = jnp.where(matched, lo[lrow] + within, jnp.int32(-1))
    if nr == 0:  # empty probe set: nothing can match
        rrow = jnp.full(lrow.shape, -1, jnp.int32)
    else:
        rrow = jnp.where(rpos >= 0, r_order[jnp.clip(rpos, 0, nr - 1)], jnp.int32(-1))

    if how == "full":
        # append right rows that matched NO left row, with -1 left map.
        # Sentinels are distinct on purpose: left null keys sit in the
        # probe universe as -2 and right null keys as -1, so a null can
        # never accidentally pair with a null from the other side.
        l_sorted = jnp.sort(probe_id)
        r_probe = rid if rnull is None else jnp.where(rnull, jnp.int32(-3), rid)
        rlo = jnp.searchsorted(l_sorted, r_probe, side="left")
        rhi = jnp.searchsorted(l_sorted, r_probe, side="right")
        r_unmatched = rhi == rlo
        urow, _, _ = _expand_rows(r_unmatched.astype(jnp.int32))
        lrow = jnp.concatenate([lrow, jnp.full(urow.shape, -1, jnp.int32)])
        rrow = jnp.concatenate([rrow, urow])
    return lrow, rrow


def semi_anti_gather_map(
    left_keys: Table, right_keys: Table, how: str = "semi"
) -> jnp.ndarray:
    """Left-semi / left-anti gather map over the left table (cudf
    left_semi_join/left_anti_join surface): semi keeps left rows with at
    least one right match, anti keeps rows with none. Null left keys
    never match (semi drops them, anti keeps them — Spark IN / NOT
    EXISTS plan semantics; NOT IN's null-aware variant is planned as a
    separate filter by the engine)."""
    if how not in ("semi", "anti"):
        raise ValueError(f"unsupported semi/anti type {how!r}")
    lid, rid = _factorize(left_keys, right_keys)
    lnull = _any_null(left_keys)
    rnull = _any_null(right_keys)
    if rnull is not None:
        rid = jnp.where(rnull, jnp.int32(-1), rid)
    rid_sorted = jnp.sort(rid)
    probe_id = lid if lnull is None else jnp.where(lnull, jnp.int32(-2), lid)
    lo = jnp.searchsorted(rid_sorted, probe_id, side="left")
    hi = jnp.searchsorted(rid_sorted, probe_id, side="right")
    keep = (hi > lo) if how == "semi" else (hi == lo)
    total = int(jnp.sum(keep))  # host sync: output size
    return jnp.nonzero(keep, size=total)[0].astype(jnp.int32)


def _joined_table(
    left: Table, right: Table, lmap, rmap, on: Sequence[str], keep_right_on: bool
) -> Table:
    cols: List[Column] = []
    names: List[str] = []
    for name, col in zip(left.names, left.columns):
        cols.append(gather_column(col, lmap))
        names.append(name)
    for name, col in zip(right.names, right.columns):
        if not keep_right_on and name in on:
            continue
        cols.append(gather_column(col, rmap, check_bounds=True))
        names.append(name)
    return Table(cols, names)


@op_boundary("inner_join")
def inner_join(left: Table, right: Table, on: Sequence[str]) -> Table:
    lmap, rmap = join_gather_maps(left.select(on), right.select(on), "inner")
    return _joined_table(left, right, lmap, rmap, list(on), keep_right_on=False)


@op_boundary("left_join")
def left_join(left: Table, right: Table, on: Sequence[str]) -> Table:
    lmap, rmap = join_gather_maps(left.select(on), right.select(on), "left")
    return _joined_table(left, right, lmap, rmap, list(on), keep_right_on=False)


def _coalesce_fixed(a: Column, b: Column, use_a: jnp.ndarray) -> Column:
    """Row-wise COALESCE of two gathered key columns (full-join key
    merge). STRING keys merge in padded space and re-compact through
    ragged_compact (closes VERDICT r3 missing #4 — cudf's full join has
    no key-type restriction)."""
    n = len(a)
    av = a.validity if a.validity is not None else jnp.ones((n,), bool)
    bv = b.validity if b.validity is not None else jnp.ones((n,), bool)
    merged_valid = jnp.where(use_a, av, bv)
    if a.dtype.id == TypeId.STRING:
        from .strings import from_padded, to_padded

        pa, la = to_padded(a)
        pb, lb = to_padded(b)
        width = max(pa.shape[1], pb.shape[1])
        if pa.shape[1] < width:
            pa = jnp.pad(pa, ((0, 0), (0, width - pa.shape[1])))
        if pb.shape[1] < width:
            pb = jnp.pad(pb, ((0, 0), (0, width - pb.shape[1])))
        out = jnp.where(use_a[:, None], pa, pb)
        lens = jnp.where(use_a, la, lb)
        return from_padded(out, lens, validity=merged_valid)
    sel = use_a
    if a.data.ndim == 2:  # DECIMAL128 limbs
        sel = use_a[:, None]
    data = jnp.where(sel, a.data, b.data)
    return Column(a.dtype, data=data, validity=merged_valid)


@op_boundary("full_join")
def full_join(left: Table, right: Table, on: Sequence[str]) -> Table:
    """Full outer join: every left row (null-extended right) plus every
    unmatched right row (null-extended left, key columns coalesced from
    the right side) — cudf full_join surface."""
    lmap, rmap = join_gather_maps(left.select(on), right.select(on), "full")
    use_left = lmap >= 0
    cols: List[Column] = []
    names: List[str] = []
    for name, col in zip(left.names, left.columns):
        g = gather_column(col, lmap, check_bounds=True)
        if name in on:
            rg = gather_column(right.column(name), rmap, check_bounds=True)
            g = _coalesce_fixed(g, rg, use_left)
        cols.append(g)
        names.append(name)
    for name, col in zip(right.names, right.columns):
        if name in on:
            continue
        cols.append(gather_column(col, rmap, check_bounds=True))
        names.append(name)
    return Table(cols, names)


@op_boundary("left_semi_join")
def left_semi_join(left: Table, right: Table, on: Sequence[str]) -> Table:
    """Left rows with at least one right match (Spark IN-subquery plan)."""
    lmap = semi_anti_gather_map(left.select(on), right.select(on), "semi")
    return gather(left, lmap)


@op_boundary("left_anti_join")
def left_anti_join(left: Table, right: Table, on: Sequence[str]) -> Table:
    """Left rows with no right match (Spark NOT EXISTS plan)."""
    lmap = semi_anti_gather_map(left.select(on), right.select(on), "anti")
    return gather(left, lmap)

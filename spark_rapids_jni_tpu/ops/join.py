"""Equi-join tier (cudf hash join, SURVEY §2.8) — inner / left joins.

TPU-first: XLA has no device hash table, so the join is the canonical
sort-probe formulation:

1. factorize both sides' key rows into dense ids by sorting the
   concatenated key table once (shared total-order key machinery),
2. sort the right side's ids; probe each left id with two searchsorted
   calls giving its match range [lo, hi),
3. expand match ranges into (left_idx, right_idx) gather-map pairs with
   a cumsum + searchsorted enumeration (the output-size host sync every
   join implementation pays at allocation time).

SQL semantics: null keys never match (inner rows dropped; left rows
survive with null right side).

Returns cudf-style gather maps; ``inner_join``/``left_join`` build the
joined Table via ops.copying.gather with NULLIFY bounds.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..columnar.dtype import TypeId
from ..utils.dispatch import op_boundary
from .aggregate import _segment_ids
from .copying import concatenate, gather, gather_column
from .sort import sorted_order

__all__ = ["join_gather_maps", "inner_join", "left_join"]


def _factorize(left_keys: Table, right_keys: Table) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense group ids for each row of both sides (equal keys <-> equal id)."""
    nl, nr = left_keys.num_rows, right_keys.num_rows
    both = concatenate([left_keys, right_keys])
    order = sorted_order(both)
    seg, _num = _segment_ids(both, order)
    ids = jnp.zeros((nl + nr,), jnp.int32).at[order].set(seg)
    return ids[:nl], ids[nl:]


def _any_null(keys: Table) -> Optional[jnp.ndarray]:
    m = None
    for c in keys.columns:
        if c.validity is not None:
            bad = ~c.validity
            m = bad if m is None else (m | bad)
    return m


def join_gather_maps(
    left_keys: Table, right_keys: Table, how: str = "inner"
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(left_idx, right_idx) gather maps; right_idx == -1 marks the
    null-extended rows of a left join."""
    if how not in ("inner", "left"):
        raise ValueError(f"unsupported join type {how!r}")
    nl, nr = left_keys.num_rows, right_keys.num_rows
    lid, rid = _factorize(left_keys, right_keys)

    lnull = _any_null(left_keys)
    rnull = _any_null(right_keys)
    if rnull is not None:
        # null right keys can never match: pull them out of the probe set
        rid = jnp.where(rnull, jnp.int32(-1), rid)

    r_order = jnp.argsort(rid).astype(jnp.int32)
    rid_sorted = rid[r_order]

    probe_id = lid if lnull is None else jnp.where(lnull, jnp.int32(-2), lid)
    lo = jnp.searchsorted(rid_sorted, probe_id, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(rid_sorted, probe_id, side="right").astype(jnp.int32)
    counts = hi - lo

    if how == "left":
        counts = jnp.maximum(counts, 1)

    cum = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)])
    total = int(cum[-1])  # host sync: output size
    if total == 0:
        z = jnp.zeros((0,), jnp.int32)
        return z, z
    pair = jnp.arange(total, dtype=jnp.int32)
    lrow = jnp.searchsorted(cum, pair, side="right").astype(jnp.int32) - 1
    within = pair - cum[lrow]
    matched = (hi - lo)[lrow] > 0
    rpos = jnp.where(matched, lo[lrow] + within, jnp.int32(-1))
    rrow = jnp.where(rpos >= 0, r_order[jnp.clip(rpos, 0, max(nr - 1, 0))], jnp.int32(-1))
    return lrow, rrow


def _joined_table(
    left: Table, right: Table, lmap, rmap, on: Sequence[str], keep_right_on: bool
) -> Table:
    cols: List[Column] = []
    names: List[str] = []
    for name, col in zip(left.names, left.columns):
        cols.append(gather_column(col, lmap))
        names.append(name)
    for name, col in zip(right.names, right.columns):
        if not keep_right_on and name in on:
            continue
        cols.append(gather_column(col, rmap, check_bounds=True))
        names.append(name)
    return Table(cols, names)


@op_boundary("inner_join")
def inner_join(left: Table, right: Table, on: Sequence[str]) -> Table:
    lmap, rmap = join_gather_maps(left.select(on), right.select(on), "inner")
    return _joined_table(left, right, lmap, rmap, list(on), keep_right_on=False)


@op_boundary("left_join")
def left_join(left: Table, right: Table, on: Sequence[str]) -> Table:
    lmap, rmap = join_gather_maps(left.select(on), right.select(on), "left")
    return _joined_table(left, right, lmap, rmap, list(on), keep_right_on=False)

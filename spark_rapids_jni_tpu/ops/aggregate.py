"""Hash-aggregate tier: groupby + reductions (cudf groupby, SURVEY §2.8).

TPU-first: sort-based grouping instead of a device hash table — XLA has
a first-class sort but no general hash table; sort + segment-reduce is
the canonical accelerator formulation. Pipeline:

1. stable sort rows by key columns (ops/sort total-order keys),
2. group boundaries from neighbor inequality (nulls compare equal,
   SQL GROUP BY semantics),
3. ``jax.ops.segment_*`` reductions with num_segments synced to host
   once (the output-allocation sync every engine pays),
4. group keys gathered from each segment's first row.

Supported aggs: sum, count (valid), count_all, min, max, mean,
nunique, and the variance family — var/std (sample, Spark
var_samp/stddev_samp) and var_pop/stddev_pop (population).
FLOAT64 SUM/MEAN are EXACT on every backend — including TPU, which has
no f64 datapath — via the windowed integer accumulator in ops/f64acc
(correctly rounded f64 of the exact real sum; bit-identical CPU vs TPU).
min/max on floats use the exact total-order transform, so they are exact
everywhere too. FLOAT32 sums accumulate in f32 (documented; Spark
promotes float sums to double before they reach this tier).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..columnar.dtype import TypeId
from ..utils.dispatch import op_boundary
from . import bitutils
from .copying import gather
from .sort import sorted_order

__all__ = ["groupby_aggregate", "groupby_sum_bounded"]


def groupby_sum_bounded(
    keys: jnp.ndarray, vals: jnp.ndarray, num_keys: int, f64_bits: bool = False
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """GROUP BY SUM for a BOUNDED integer key domain [0, num_keys):
    one scatter-add pass, no sort — the hash-aggregate hot path for
    dictionary-coded group columns (cudf hash agg does the same when the
    build side fits; the sort-based groupby_aggregate is the general
    fallback). Returns (sums[num_keys], counts[num_keys]); keys outside
    the domain are dropped into a trash segment.

    O(N) and HBM-bandwidth-bound on TPU, where the general path pays an
    O(N log^2 N) sort.

    ``vals`` contract: float32 sums in f32 (MXU kernel on TPU);
    integers sum in two's-complement int64 (uint64 keeps its low 64
    sum bits — wrap past 2^63 is the caller's to reinterpret, as in
    cudf's u64 accumulator). Pass ``f64_bits=True`` when ``vals`` is
    FLOAT64 IEEE-bit storage (the columnar FLOAT64 format,
    ops/bitutils): returns EXACT f64 sums as uint64 bits via the
    ops/f64acc windowed accumulator. An explicit flag, not dtype
    punning — a real UINT64 integer column must keep integer semantics.
    """
    if f64_bits:  # FLOAT64 bits: exact integer-limb path
        if vals.dtype != jnp.uint64:
            raise ValueError("f64_bits vals must be uint64 IEEE-bit storage")
        from .f64acc import segment_sum_f64bits

        seg = jnp.where((keys >= 0) & (keys < num_keys), keys, num_keys).astype(jnp.int32)
        sums = segment_sum_f64bits(vals, seg, num_keys + 1)[:num_keys]
        counts = jax.ops.segment_sum(
            jnp.ones_like(seg, jnp.int64), seg, num_segments=num_keys + 1
        )[:num_keys]
        return sums, counts
    if (
        vals.dtype == jnp.float32
        and num_keys <= 65536
        and keys.shape[0] < (1 << 24)  # counts ride an f32 accumulator:
        # exact only while every per-key count stays below 2^24
        and jax.default_backend() == "tpu"
    ):
        # float path on hardware: the outer-product MXU kernel beats the
        # XLA scatter ~17x at the 1M x 4096 axis and ~2.4x at 65536 keys
        # (see pallas_kernels). Integer sums stay on the exact int64
        # scatter path.
        from .pallas_kernels import pallas_available, pallas_groupby_sum_outer

        if pallas_available():
            return pallas_groupby_sum_outer(keys, vals, num_keys)

    seg = jnp.where((keys >= 0) & (keys < num_keys), keys, num_keys).astype(jnp.int32)
    if jnp.issubdtype(vals.dtype, jnp.integer):
        vals = vals.astype(jnp.int64)
    sums = jax.ops.segment_sum(vals, seg, num_segments=num_keys + 1)[:num_keys]
    counts = jax.ops.segment_sum(jnp.ones_like(seg, jnp.int64), seg, num_segments=num_keys + 1)[
        :num_keys
    ]
    return sums, counts


def _keys_equal_neighbor(col: Column, order: jnp.ndarray) -> jnp.ndarray:
    """[N-1] bool: sorted row i equals row i-1 for this key (nulls equal)."""
    v = col.valid_mask()[order]
    same_valid = v[1:] == v[:-1]
    if col.dtype.id == TypeId.STRING:
        offs = col.offsets
        lens = (offs[1:] - offs[:-1])[order]
        same_len = lens[1:] == lens[:-1]
        # compare up to 16-byte prefix lanes (sort key resolution)
        from .sort import _string_prefix_keys

        k1, k2 = _string_prefix_keys(Column(col.dtype, offsets=col.offsets, chars=col.chars))
        same_data = (k1[order][1:] == k1[order][:-1]) & (k2[order][1:] == k2[order][:-1])
        same = same_len & same_data
    elif col.dtype.id == TypeId.DECIMAL128:
        d = col.data[order]
        same = jnp.all(d[1:] == d[:-1], axis=1)
    else:
        d = col.data[order]
        same = d[1:] == d[:-1]
    both_null = (~v[1:]) & (~v[:-1])
    return same_valid & (same | both_null)


def _segment_ids(keys: Table, order: jnp.ndarray) -> Tuple[jnp.ndarray, int]:
    n = keys.num_rows
    if n == 0:
        return jnp.zeros((0,), jnp.int32), 0
    eq = jnp.ones((n - 1,), bool)
    for col in keys.columns:
        eq = eq & _keys_equal_neighbor(col, order)
    starts = jnp.concatenate([jnp.ones((1,), bool), ~eq])
    seg = jnp.cumsum(starts).astype(jnp.int32) - 1
    num = int(seg[-1]) + 1  # host sync: group count
    return seg, num


def _agg_column(col: Column, order, seg, num, how: str) -> Column:
    d = col.dtype
    sorted_valid = col.valid_mask()[order]

    if how == "count_all":
        data = jax.ops.segment_sum(jnp.ones_like(seg, jnp.int64), seg, num)
        return Column(dt.INT64, data=data)
    if how == "count":
        data = jax.ops.segment_sum(sorted_valid.astype(jnp.int64), seg, num)
        return Column(dt.INT64, data=data)

    if how in _VAR_STD_HOWS:
        # numeric inputs only (Spark var_samp/stddev_samp — and the
        # var_pop/stddev_pop population variants — analysis rule):
        # BOOL8/TIMESTAMP/DURATION would silently compute variance over
        # raw codes / epoch ticks (ADVICE r5 low #5)
        if not (d.is_integral or d.is_floating):
            raise ValueError(
                f"{how} requires a numeric (integral or floating) column, got {d!r}"
            )
        return _var_std_column(col, order, seg, num, how, sorted_valid)

    any_valid = jax.ops.segment_max(sorted_valid.astype(jnp.int32), seg, num) > 0

    if how in ("min", "max") and d.is_fixed_width and d.id != TypeId.DECIMAL128:
        # exact via total-order keys even for floats on TPU
        key = bitutils.total_order_key(col.data, d)[order]
        udt = key.dtype
        fill = jnp.asarray(~jnp.zeros((), udt)) if how == "min" else jnp.zeros((), udt)
        key = jnp.where(sorted_valid, key, fill)
        red = jax.ops.segment_min if how == "min" else jax.ops.segment_max
        best = red(key, seg, num)
        data = _from_total_order(best, d)
        return Column(d, data=data, validity=any_valid)

    if how in ("sum", "mean"):
        if d.id == TypeId.FLOAT64:
            # exact on all backends: windowed integer accumulation over
            # the stored IEEE bits (ops/f64acc) — correctly rounded f64,
            # bit-identical CPU vs TPU; matches the reference's real-f64
            # device reduction semantics (cudf segment reduce, SURVEY §2.8)
            from . import f64acc

            bits = col.data[order]
            if how == "sum":
                out_bits = f64acc.segment_sum_f64bits(bits, seg, num, valid=sorted_valid)
            else:
                out_bits, _ = f64acc.segment_mean_f64bits(bits, seg, num, valid=sorted_valid)
            return Column(dt.FLOAT64, data=out_bits, validity=any_valid)
        if d.is_floating:  # FLOAT32
            vals = col.data[order]
            vals = jnp.where(sorted_valid, vals, 0)
            s = jax.ops.segment_sum(vals, seg, num)
            if how == "mean":
                cnt = jax.ops.segment_sum(sorted_valid.astype(vals.dtype), seg, num)
                s = s / jnp.maximum(cnt, 1)
                return Column(
                    dt.FLOAT64,
                    data=bitutils.float_store(s, dt.FLOAT64),
                    validity=any_valid,
                )
            return Column(dt.FLOAT32, data=s.astype(jnp.float32), validity=any_valid)
        if d.id == TypeId.DECIMAL128:
            # limb-wise int64 partial sums + carry renormalize: summing
            # two's-complement limbs mod 2^128 is exact signed addition
            # (wraps on >128-bit overflow, like int128 accumulation would)
            limbs = col.data[order]
            limbs = jnp.where(sorted_valid[:, None], limbs, 0)
            parts = [
                jax.ops.segment_sum(limbs[:, k].astype(jnp.int64), seg, num) for k in range(4)
            ]
            out = jnp.zeros((num, 4), jnp.uint32)
            carry = jnp.zeros((num,), jnp.int64)
            for k in range(4):
                t = parts[k] + carry
                out = out.at[:, k].set((t & 0xFFFFFFFF).astype(jnp.uint32))
                carry = t >> 32
            return Column(d, data=out, validity=any_valid)
        if how == "mean":
            vals = col.data[order].astype(jnp.float64)
            vals = jnp.where(sorted_valid, vals, 0)
            s = jax.ops.segment_sum(vals, seg, num)
            cnt = jax.ops.segment_sum(sorted_valid.astype(jnp.float64), seg, num)
            m = s / jnp.maximum(cnt, 1)
            return Column(dt.FLOAT64, data=bitutils.float_store(m, dt.FLOAT64), validity=any_valid)
        # integral sum -> int64 (Spark sum semantics)
        vals = col.data[order].astype(jnp.int64)
        vals = jnp.where(sorted_valid, vals, 0)
        s = jax.ops.segment_sum(vals, seg, num)
        return Column(dt.INT64, data=s, validity=any_valid)

    raise ValueError(f"unsupported aggregation {how!r} on {d!r}")


_VAR_STD_HOWS = ("var", "std", "var_pop", "stddev_pop")


def _var_std_column(col: Column, order, seg, num, how: str, sorted_valid) -> Column:
    """Sample variance / stddev (Spark var_samp / stddev_samp: DOUBLE
    out, NULL below two valid rows; q17/q39's missing primitive), plus
    the POPULATION variants ``var_pop`` / ``stddev_pop`` (Spark
    var_pop / stddev_pop: the same M2 divided by n instead of n-1,
    NULL only when NO valid rows — one valid row yields 0.0). Both
    families share the stable two-pass M2; only the divisor and the
    NULL threshold differ (VERDICT item 6, first slice).

    STABLE two-pass formulation — deviations from the group mean, not
    the raw-moment sumsq - sum^2/n (which cancels catastrophically for
    large-mean data: values ~1e9 with stddev ~1 would return noise).
    Pass 1 computes correctly rounded group means (segment_mean
    machinery); pass 2 sums (x - mean)^2. On the f64-less tier the
    deviation and square evaluate in the dd (double-f32, ~2^-48/op)
    domain, materialize to f64 bits through the elementwise two-addend
    adder, and segment-sum EXACTLY through the windowed accumulator —
    precision is set by the per-element deviation arithmetic, relative
    to the DEVIATIONS rather than the raw moments. The [G]-scale
    divide by (n-1) runs in real f64 on the host (this op is an eager
    boundary; the groupby already pays a host sync for the group
    count).

    Precision limit on the f64-less (dd) tier: non-FLOAT64 inputs pass
    through the dd split (~48-bit effective mantissa), so integer
    values with magnitude above 2^48 lose low bits BEFORE the
    deviation is formed — var/std of int64 data beyond +-2^48 is
    approximate there, while the real-f64 backend branch keeps the
    full 53-bit f64 mantissa (ADVICE r5 low #5)."""
    from . import f64acc

    d = col.dtype
    if bitutils.backend_has_f64():
        if d.id == TypeId.FLOAT64:
            x = bitutils.float_view(col.data, d)
        else:
            x = col.data.astype(jnp.float64)
        xs = jnp.where(sorted_valid, x[order], 0.0)
        cnt_dev = jax.ops.segment_sum(sorted_valid.astype(jnp.int64), seg, num)
        mean = jax.ops.segment_sum(xs, seg, num) / jnp.maximum(cnt_dev, 1)
        dx = jnp.where(sorted_valid, xs - mean[seg], 0.0)
        m2_np = np.asarray(jax.ops.segment_sum(dx * dx, seg, num), np.float64)
        cnt = np.asarray(cnt_dev).astype(np.float64)
    else:
        if d.id == TypeId.FLOAT64:
            pair = f64acc.dd_from_f64bits(col.data)
            xbits = col.data[order]  # exact stored bits — no dd round trip
        else:
            pair = f64acc.dd_from_any(col.data)
            xbits = f64acc.dd_to_f64bits(pair)[order]
        mean_bits, cnt_dev = f64acc.segment_mean_f64bits(
            xbits, seg, num, valid=sorted_valid
        )
        mean_pair = f64acc.dd_from_f64bits(mean_bits)
        sp = f64acc.DD(pair.hi[order], pair.lo[order])
        dx = sp - f64acc.DD(mean_pair.hi[seg], mean_pair.lo[seg])
        d2 = dx * dx
        d2bits = f64acc.dd_to_f64bits(d2)
        m2bits = f64acc.segment_sum_f64bits(d2bits, seg, num, valid=sorted_valid)
        m2_np = np.asarray(m2bits).view(np.float64)
        cnt = np.asarray(cnt_dev).astype(np.float64)
    pop = how in ("var_pop", "stddev_pop")
    ok = cnt >= (1 if pop else 2)
    var = m2_np / np.maximum(cnt - (0 if pop else 1), 1.0)
    var = np.maximum(var, 0.0)
    out = np.sqrt(var) if how in ("std", "stddev_pop") else var
    return Column(
        dt.FLOAT64,
        data=jnp.asarray(np.where(ok, out, 0.0).view(np.uint64)),
        validity=jnp.asarray(ok),
    )


def _from_total_order(key: jnp.ndarray, d) -> jnp.ndarray:
    """Inverse of bitutils.total_order_key."""
    from jax import lax

    if d.id == TypeId.FLOAT64:
        neg = (key >> jnp.uint64(63)) == 0
        bits = jnp.where(neg, key ^ jnp.uint64(0xFFFFFFFFFFFFFFFF), key & ~jnp.uint64(1 << 63))
        return bits
    if d.id == TypeId.FLOAT32:
        neg = (key >> jnp.uint32(31)) == 0
        bits = jnp.where(neg, key ^ jnp.uint32(0xFFFFFFFF), key & ~jnp.uint32(1 << 31))
        return lax.bitcast_convert_type(bits, jnp.float32)
    if d.is_signed or d.np_dtype.kind == "i":
        sign_bit = jnp.asarray(1 << (8 * d.size_bytes - 1), dtype=key.dtype)
        return lax.bitcast_convert_type(key ^ sign_bit, d.jnp_dtype)
    return key.astype(d.jnp_dtype)


@op_boundary("groupby_aggregate")
def groupby_aggregate(
    keys: Table, values: Table, aggs: Sequence[Tuple[str, str]]
) -> Table:
    """GROUP BY keys, computing aggs = [(value_col_name, how), ...].

    Returns a Table of unique keys followed by one column per agg named
    ``{col}_{how}``. Row order is key-sorted (callers needing original
    first-appearance order can re-sort; SQL imposes none).
    """
    n = keys.num_rows
    order = sorted_order(keys)
    seg, num = _segment_ids(keys, order)

    first_of_group = jnp.searchsorted(seg, jnp.arange(num, dtype=jnp.int32), side="left")
    out_keys = gather(keys, order[first_of_group] if n else jnp.zeros((0,), jnp.int32))

    out_cols: List[Column] = list(out_keys.columns)
    out_names: List[str] = list(out_keys.names)
    for col_name, how in aggs:
        col = values.column(col_name)
        if how == "nunique":
            out_cols.append(_nunique_column(keys, col, num))
        else:
            out_cols.append(_agg_column(col, order, seg, num, how))
        out_names.append(f"{col_name}_{how}")
    return Table(out_cols, out_names)


def _nunique_column(keys: Table, col: Column, num: int) -> Column:
    """COUNT(DISTINCT col) per group, nulls excluded (SQL semantics).

    Re-sorts by (keys..., col) so equal values are adjacent within each
    group; a value is a NEW distinct when it is valid and differs from
    its predecessor (or the predecessor is another group / null — nulls
    sort first within the group under nulls_first)."""
    both = Table(list(keys.columns) + [col], list(keys.names) + ["__v"])
    order2 = sorted_order(both)
    seg2, num2 = _segment_ids(keys, order2)
    if num2 != num:
        raise AssertionError("group count mismatch between sort orders")
    n = keys.num_rows
    if n == 0:
        return Column(dt.INT64, data=jnp.zeros((0,), jnp.int64))

    valid = col.valid_mask()[order2]
    same_val = _keys_equal_neighbor(col, order2)  # [n-1], value equal to prev
    same_group = seg2[1:] == seg2[:-1]
    prev_valid = valid[:-1]
    is_new_tail = valid[1:] & ~(same_group & same_val & prev_valid)
    is_new = jnp.concatenate([valid[:1], is_new_tail])
    data = jax.ops.segment_sum(is_new.astype(jnp.int64), seg2, num)
    return Column(dt.INT64, data=data)

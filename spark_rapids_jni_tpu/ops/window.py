"""Window functions: ranks, row numbers, lag/lead, and partitioned
aggregates over ordered frames.

The one operator family that kept 15 of the TPC-DS q1-q99 blocked
(QUERIES.md): rank/row_number (q44, q49, q67, q70, ...), aggregates
over a partition (q12, q20, q36, q53, q63, q86, q89, q98), cumulative
frames (q51), and neighbor access (q47, q57). Reference analog: Spark
lowers these onto cudf's rolling/grouped window kernels (SURVEY §2.8
engine tier).

TPU-first formulation — sort + segmented scans, no data-dependent
shapes, no serial loops:

1. one stable sort by (partition keys, order keys) (ops/sort),
2. segment ids from partition-key neighbor equality (ops/aggregate),
3. ranks / cumulative frames as SEGMENTED SCANS: segmented cumsum is
   ``cumsum(x) - running_total_at_segment_entry`` (two O(N) passes, no
   scatter); rank ties resolve with one global cummax over tie-run
   start positions (valid segment-wise because positions increase
   monotonically and every segment start opens a run),
4. full-partition aggregates reuse the EXACT groupby kernels
   (ops/aggregate._agg_column — FLOAT64 sums/means ride the f64acc
   windowed accumulator, min/max the total-order transform), gathered
   back per row,
5. results return in the caller's ORIGINAL row order through the
   inverse sort permutation (windows never reorder output — Spark
   contract).

Exactness: ranks / counts / row numbers integer-exact; full-partition
FLOAT64 SUM/MEAN correctly rounded (bit-identical to the groupby
tier). CUMULATIVE FLOAT64 sums on the f64-less tier scan the dd hi/lo
components through plain f32 cumsums, so the hi rounding is never
compensated into lo: the realized error is ~2^-24 RELATIVE TO THE
GLOBAL PREFIX magnitude (the segment-entry subtraction anchors error
to whole-buffer scale, not the partition's), and a running sum stalls
once the prefix exceeds ~2^24x the element magnitude — a documented
trade (an exact 224-bit prefix scan would serialize the window;
ADVICE r5 high). tests/test_window.py pins the realized bound.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..columnar.dtype import TypeId
from ..utils.dispatch import op_boundary
from .aggregate import _agg_column, _keys_equal_neighbor, _segment_ids
from .sort import sorted_order

__all__ = ["window_aggregate"]

_RANKS = ("row_number", "rank", "dense_rank")
_SHIFTS = ("lag", "lead")
_FULL_AGGS = ("sum", "mean", "min", "max", "count", "var", "std",
              "var_pop", "stddev_pop")
_SUPPORTED = _RANKS + _SHIFTS + _FULL_AGGS + ("cumsum",)
# order-defined results (ADVICE r5 low #3): silently rank/shift/scan an
# arbitrary sort order is a wrong answer, not a default
_ORDER_REQUIRED = ("rank", "dense_rank", "lag", "lead", "cumsum")


def _inverse_permutation(order: jnp.ndarray) -> jnp.ndarray:
    n = order.shape[0]
    return jnp.zeros((n,), jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))


def _segment_starts(seg: jnp.ndarray, num: int) -> jnp.ndarray:
    """[num] first sorted-row index of each segment."""
    return jnp.searchsorted(seg, jnp.arange(num, dtype=jnp.int32), side="left").astype(
        jnp.int32
    )


def _segmented_cumsum(x: jnp.ndarray, seg: jnp.ndarray, starts: jnp.ndarray) -> jnp.ndarray:
    """Inclusive segmented cumsum: the global cumsum minus the running
    total at each segment's entry point."""
    c = jnp.cumsum(x, axis=0)
    prev = jnp.concatenate([jnp.zeros_like(c[:1]), c[:-1]])
    return c - prev[starts][seg]


@op_boundary("window_aggregate")
def window_aggregate(
    table: Table,
    partition_by: Sequence[str],
    order_by: Sequence[Tuple[str, bool]],
    aggs: Sequence[Tuple[str, str, str]],
) -> Table:
    """Evaluate window functions over ``table``.

    ``partition_by``: partition key column names (empty = one global
    partition). ``order_by``: [(column, ascending)] within-partition
    order — REQUIRED (ValueError otherwise) for rank/dense_rank/lag/
    lead/cumsum, whose results are order-defined; row_number with an
    empty order_by numbers rows in an unspecified (implementation)
    order; full-partition aggregates ignore it. ``aggs``:
    [(source_col, how, out_name)] with how in {row_number, rank,
    dense_rank, lag, lead, sum, mean, min, max, count, var, std,
    var_pop, stddev_pop, cumsum}; lag/lead read offset 1 (Spark's default) with NULL at
    partition edges; source_col is ignored for the rank family (pass
    any column name).

    Returns the input table with the window columns appended, in the
    ORIGINAL row order.
    """
    for _, how, _ in aggs:
        if how not in _SUPPORTED:
            raise ValueError(f"unknown window function {how!r}")
        if how in _ORDER_REQUIRED and not order_by:
            raise ValueError(
                f"window function {how!r} requires a non-empty order_by "
                f"(its result is defined by within-partition order)"
            )
    n = table.num_rows
    out_cols: List[Column] = list(table.columns)
    names: List[str] = list(table.names)
    if n == 0:
        for src, how, out in aggs:
            d = _out_dtype(table.column(src).dtype, how)
            out_cols.append(Column(d, data=jnp.zeros((0,), d.jnp_dtype)))
            names.append(out)
        return Table(out_cols, names)

    part_tbl = (
        table.select(list(partition_by))
        if partition_by
        else Table([Column(dt.INT32, data=jnp.zeros((n,), jnp.int32))], ["__g"])
    )
    sort_cols: List[Column] = list(part_tbl.columns)
    sort_names = list(part_tbl.names)
    ascending = [True] * len(sort_cols)
    for name, asc in order_by:
        sort_cols.append(table.column(name))
        sort_names.append(f"__o_{name}")
        ascending.append(bool(asc))
    order = sorted_order(Table(sort_cols, sort_names), ascending=ascending)
    seg, num = _segment_ids(part_tbl, order)
    starts = _segment_starts(seg, num)
    pos = jnp.arange(n, dtype=jnp.int32) - starts[seg]
    inv = _inverse_permutation(order)

    # tie runs for rank/dense_rank: a sorted row opens a new run when
    # any ORDER key differs from its predecessor or the partition
    # changes
    if order_by:
        eq = jnp.ones((n - 1,), bool)
        for name, _asc in order_by:
            eq = eq & _keys_equal_neighbor(table.column(name), order)
        same_order = jnp.concatenate([jnp.zeros((1,), bool), eq])
    else:
        same_order = jnp.zeros((n,), bool)
    new_run = (~same_order) | jnp.concatenate(
        [jnp.ones((1,), bool), seg[1:] != seg[:-1]]
    )

    for src, how, out in aggs:
        out_cols.append(
            _one_window(table, src, how, order, seg, num, starts, pos, new_run, inv)
        )
        names.append(out)
    return Table(out_cols, names)


def _out_dtype(src_dtype, how: str):
    if how in ("row_number", "rank", "dense_rank"):
        return dt.INT32
    if how == "count":
        return dt.INT64
    if how in ("mean", "var", "std", "var_pop", "stddev_pop"):
        return dt.FLOAT64
    return src_dtype


def _one_window(table, src, how, order, seg, num, starts, pos, new_run, inv) -> Column:
    n = seg.shape[0]
    if how == "row_number":
        return Column(dt.INT32, data=(pos + 1)[inv])
    if how == "dense_rank":
        dr = _segmented_cumsum(new_run.astype(jnp.int32), seg, starts)
        return Column(dt.INT32, data=dr[inv])
    if how == "rank":
        # competition rank = tie-run start position within segment + 1.
        # cummax of globally increasing run-start positions never leaks
        # across segments (every segment start opens a run)
        r = jax.lax.cummax(jnp.where(new_run, jnp.arange(n, dtype=jnp.int32), -1))
        return Column(dt.INT32, data=(r - starts[seg] + 1)[inv])

    col = table.column(src)
    if how in _SHIFTS:
        if col.dtype.id in (TypeId.STRING, TypeId.LIST):
            raise NotImplementedError("lag/lead over variable-width columns not lowered")
        shift = 1 if how == "lag" else -1
        idx = jnp.arange(n, dtype=jnp.int32) - shift
        cidx = jnp.clip(idx, 0, n - 1)
        ok = (idx >= 0) & (idx <= n - 1) & (seg[cidx] == seg)
        valid_sorted = col.valid_mask()[order]
        shifted = col.data[order][cidx]
        v = valid_sorted[cidx] & ok
        return Column(col.dtype, data=shifted[inv], validity=v[inv])

    if how == "cumsum":
        valid_sorted = col.valid_mask()[order]
        has_prior = _segmented_cumsum(valid_sorted.astype(jnp.int32), seg, starts) > 0
        if col.dtype.id == TypeId.FLOAT64:
            from . import bitutils
            from .f64acc import DD, dd_from_f64bits, dd_to_f64bits

            if bitutils.backend_has_f64():
                x = bitutils.float_view(col.data, col.dtype)[order]
                x = jnp.where(valid_sorted, x, 0.0)
                bits = jax.lax.bitcast_convert_type(
                    _segmented_cumsum(x, seg, starts), jnp.uint64
                )
            else:
                pair = dd_from_f64bits(col.data)
                hi = jnp.where(valid_sorted, pair.hi[order], jnp.float32(0))
                lo = jnp.where(valid_sorted, pair.lo[order], jnp.float32(0))
                bits = dd_to_f64bits(
                    DD(_segmented_cumsum(hi, seg, starts), _segmented_cumsum(lo, seg, starts))
                )
            return Column(dt.FLOAT64, data=bits[inv], validity=has_prior[inv])
        x = jnp.where(valid_sorted, col.data[order], 0)
        if jnp.issubdtype(x.dtype, jnp.integer):
            x = x.astype(jnp.int64)
            d = dt.INT64
        else:
            d = col.dtype
        return Column(d, data=_segmented_cumsum(x, seg, starts)[inv], validity=has_prior[inv])

    # full-partition aggregates: the EXACT groupby kernels, per-group
    # results gathered back to rows
    g = _agg_column(col, order, seg, num, how)
    data = g.data[seg][inv]
    validity = None if g.validity is None else g.validity[seg][inv]
    return Column(g.dtype, data=data, validity=validity)

"""Expression evaluation over Tables (cudf AST / Spark expression tier).

A small composable AST — column refs, literals, arithmetic, comparisons,
boolean logic, null predicates — evaluated column-at-a-time with Spark
SQL null semantics (null propagates through operators; AND/OR are
three-valued-logic). The TPU shape: every node is a pure jnp map over
[N] arrays, so an entire predicate/projection tree fuses into one XLA
kernel at jit time.

Example::

    e = (col("qty") * col("price")).alias("revenue")
    pred = (col("qty") > lit(5)) & ~col("returned").is_null()
    revenue = e.evaluate(table)
"""

from __future__ import annotations

import operator
from typing import Optional

import jax.numpy as jnp

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..columnar.dtype import DType, TypeId
from . import bitutils

__all__ = ["col", "lit", "when", "Expression"]


def _is_dd(x) -> bool:
    from .f64acc import DD

    return isinstance(x, DD)


class _Value:
    """Evaluated expression: floating data is carried as arithmetic values
    (float_view) and re-bit-packed only at column materialization."""

    __slots__ = ("data", "valid", "dtype")

    def __init__(self, data, valid, dtype: Optional[DType]):
        self.data = data
        self.valid = valid  # None == all valid
        self.dtype = dtype


def _to_value(col_: Column) -> _Value:
    d = col_.dtype
    if d.id == TypeId.FLOAT64:
        if bitutils.backend_has_f64():
            return _Value(bitutils.float_view(col_.data, d), col_.validity, d)
        # no f64 datapath (TPU): carry a double-f32 pair — ~2^-48
        # relative per op vs the 2^-24 of the plain-f32 view it replaces
        # (exactness contract in ops/f64acc; VERDICT r3 item 5)
        from .f64acc import dd_from_f64bits

        return _Value(dd_from_f64bits(col_.data), col_.validity, d)
    if d.id == TypeId.BOOL8:
        return _Value(col_.data.astype(bool), col_.validity, d)
    return _Value(col_.data, col_.validity, d)


def _both_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


class Expression:
    def evaluate(self, table: Table) -> Column:
        v = self._eval(table)
        data = v.data
        if isinstance(data, (int, float)):  # bare literal
            data = jnp.asarray(data)
        if _is_dd(data):
            from .f64acc import dd_to_f64bits

            return Column(dt.FLOAT64, data=dd_to_f64bits(data), validity=v.valid)
        if isinstance(data, jnp.ndarray) and data.dtype == bool:
            return Column(dt.BOOL8, data=data.astype(jnp.uint8), validity=v.valid)
        if data.dtype in (jnp.float64, jnp.float32) and (
            v.dtype is None or v.dtype.id == TypeId.FLOAT64
        ):
            return Column(dt.FLOAT64, data=bitutils.float_store(data.astype(jnp.float64) if bitutils.backend_has_f64() else data, dt.FLOAT64), validity=v.valid)
        out_d = v.dtype if v.dtype is not None else _infer(data.dtype)
        return Column(out_d, data=data, validity=v.valid)

    def _eval(self, table: Table) -> _Value:
        raise NotImplementedError

    # -- operator sugar -----------------------------------------------------
    def _bin(self, other, fn, bool_out=False):
        return _BinOp(self, _wrap(other), fn, bool_out)

    def __add__(self, o):
        return self._bin(o, operator.add)

    def __sub__(self, o):
        return self._bin(o, operator.sub)

    def __mul__(self, o):
        return self._bin(o, operator.mul)

    def __truediv__(self, o):
        return _Div(self, _wrap(o))

    def __mod__(self, o):
        return self._bin(o, operator.mod)

    def __eq__(self, o):  # noqa: A003
        return self._bin(o, operator.eq, bool_out=True)

    def __ne__(self, o):
        return self._bin(o, operator.ne, bool_out=True)

    def __lt__(self, o):
        return self._bin(o, operator.lt, bool_out=True)

    def __le__(self, o):
        return self._bin(o, operator.le, bool_out=True)

    def __gt__(self, o):
        return self._bin(o, operator.gt, bool_out=True)

    def __ge__(self, o):
        return self._bin(o, operator.ge, bool_out=True)

    def __and__(self, o):
        return _And(self, _wrap(o))

    def __or__(self, o):
        return _Or(self, _wrap(o))

    def __invert__(self):
        return _Not(self)

    def is_null(self):
        return _IsNull(self, True)

    def is_not_null(self):
        return _IsNull(self, False)

    def cast(self, d: DType):
        return _Cast(self, d)

    __hash__ = None


class _ColumnRef(Expression):
    def __init__(self, name: str):
        self.name = name

    def _eval(self, table: Table) -> _Value:
        return _to_value(table.column(self.name))


class _Literal(Expression):
    def __init__(self, value):
        self.value = value

    def _eval(self, table: Table) -> _Value:
        if self.value is None:
            n = table.num_rows
            return _Value(jnp.zeros((n,), jnp.int32), jnp.zeros((n,), bool), None)
        if isinstance(self.value, (int, float)) and not isinstance(self.value, bool):
            # keep the HOST scalar: if the peer operand is a dd pair the
            # promotion splits the full f64 literal exactly (an early
            # jnp.asarray would round it to one f32 on the TPU tier)
            return _Value(self.value, None, None)
        return _Value(jnp.asarray(self.value), None, None)


class _BinOp(Expression):
    def __init__(self, a, b, fn, bool_out):
        self.a, self.b, self.fn, self.bool_out = a, b, fn, bool_out

    def _eval(self, table):
        va, vb = self.a._eval(table), self.b._eval(table)
        da, db = va.data, vb.data
        if _is_dd(da) or _is_dd(db):
            # promote BOTH sides before the operator: a jnp array's own
            # dunder would coerce the DD NamedTuple to a [2, N] array
            from .f64acc import dd_from_any

            da, db = dd_from_any(da), dd_from_any(db)
        data = self.fn(da, db)
        d = None if self.bool_out else (va.dtype if va.dtype is not None else vb.dtype)
        if d is not None and not d.is_fixed_width:
            d = None
        # arithmetic output dtype follows jnp promotion unless it matches input
        if d is not None and not self.bool_out and not _is_dd(data):
            if data.dtype != d.jnp_dtype and not d.is_floating:
                d = None
        return _Value(data, _both_valid(va.valid, vb.valid), d)


class _Div(Expression):
    """SQL divide: always floating point, null on divide-by-zero."""

    def __init__(self, a, b):
        self.a, self.b = a, b

    def _eval(self, table):
        va, vb = self.a._eval(table), self.b._eval(table)
        if bitutils.backend_has_f64():
            denom = jnp.asarray(vb.data).astype(jnp.float64)
            zero = jnp.asarray(vb.data) == 0
            data = va.data / jnp.where(zero, 1, denom)
        else:
            # dd division on the f64-emulating tier (~2^-48 relative)
            from .f64acc import DD, dd_from_any

            num = dd_from_any(va.data)
            den = dd_from_any(vb.data)
            zero = (den.hi == 0) & (den.lo == 0)
            safe = DD(jnp.where(zero, jnp.float32(1), den.hi), jnp.where(zero, jnp.float32(0), den.lo))
            data = num / safe
        valid = _both_valid(va.valid, vb.valid)
        valid = _both_valid(valid, ~zero)
        return _Value(data, valid, dt.FLOAT64)


class _And(Expression):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def _eval(self, table):
        va, vb = self.a._eval(table), self.b._eval(table)
        a = jnp.asarray(va.data).astype(bool)
        b = jnp.asarray(vb.data).astype(bool)
        av = jnp.ones_like(a) if va.valid is None else va.valid
        bv = jnp.ones_like(b) if vb.valid is None else vb.valid
        data = a & b
        # 3VL: false dominates null
        valid = (av & bv) | (av & ~a) | (bv & ~b)
        return _Value(data, valid, None)


class _Or(Expression):
    def __init__(self, a, b):
        self.a, self.b = a, b

    def _eval(self, table):
        va, vb = self.a._eval(table), self.b._eval(table)
        a = jnp.asarray(va.data).astype(bool)
        b = jnp.asarray(vb.data).astype(bool)
        av = jnp.ones_like(a) if va.valid is None else va.valid
        bv = jnp.ones_like(b) if vb.valid is None else vb.valid
        data = a | b
        valid = (av & bv) | (av & a) | (bv & b)  # true dominates null
        return _Value(data, valid, None)


class _Not(Expression):
    def __init__(self, a):
        self.a = a

    def _eval(self, table):
        v = self.a._eval(table)
        return _Value(~jnp.asarray(v.data).astype(bool), v.valid, None)


class _IsNull(Expression):
    def __init__(self, a, want_null):
        self.a, self.want_null = a, want_null

    def _eval(self, table):
        v = self.a._eval(table)
        if v.valid is None:
            shape = jnp.shape(jnp.asarray(v.data))[:1] if not _is_dd(v.data) else v.data.shape[:1]
            res = jnp.zeros(shape, bool) if self.want_null else jnp.ones(shape, bool)
        else:
            res = ~v.valid if self.want_null else v.valid
        return _Value(res, None, None)


class _When(Expression):
    """SQL CASE WHEN cond THEN a ELSE b END. 3VL: a NULL condition
    selects the ELSE branch (SQL's CASE treats unknown as not-matched);
    result validity follows the CHOSEN branch per row.

    EAGER EVALUATION (ADVICE r5 low #4): both THEN and ELSE evaluate
    for every row before the select — the columnar/XLA formulation has
    no per-row lazy branch. Consequence: an error-capable op in the
    UNTAKEN branch still raises (an ANSI cast raising CastError on a
    row the condition would have guarded fails the whole expression),
    deviating from SQL CASE's guarded-evaluation guarantee. Callers
    relying on CASE-as-guard must mask/neutralize the branch input
    BEFORE the error-capable op (e.g. substitute a safe value where
    the condition selects the other branch), as Spark's own
    conditional-expression rewrite does."""

    def __init__(self, cond, then, other):
        self.cond, self.then, self.other = cond, then, other

    def _eval(self, table):
        vc = self.cond._eval(table)
        c = jnp.asarray(vc.data).astype(bool)
        if vc.valid is not None:
            c = c & vc.valid
        vt, vo = self.then._eval(table), self.other._eval(table)
        dtd, dod = vt.data, vo.data
        if _is_dd(dtd) or _is_dd(dod):
            from .f64acc import DD, dd_from_any

            t_, o_ = dd_from_any(dtd), dd_from_any(dod)
            data = DD(jnp.where(c, t_.hi, o_.hi), jnp.where(c, t_.lo, o_.lo))
        else:
            data = jnp.where(c, dtd, dod)
        if vt.valid is None and vo.valid is None:
            valid = None
        else:
            tvb = jnp.ones_like(c) if vt.valid is None else vt.valid
            ovb = jnp.ones_like(c) if vo.valid is None else vo.valid
            valid = jnp.where(c, tvb, ovb)
        d = vt.dtype if vt.dtype is not None else vo.dtype
        return _Value(data, valid, d)


class _Cast(Expression):
    def __init__(self, a, d: DType):
        self.a, self.d = a, d

    def _eval(self, table):
        v = self.a._eval(table)
        data = v.data
        if isinstance(data, (int, float)):
            data = jnp.asarray(data)
        if self.d.id == TypeId.FLOAT64 and not bitutils.backend_has_f64():
            from .f64acc import dd_from_any

            return _Value(dd_from_any(data), v.valid, self.d)
        if self.d.is_floating:
            target = jnp.float64 if bitutils.backend_has_f64() else jnp.float32
            return _Value(data.astype(target), v.valid, self.d)
        return _Value(data.astype(self.d.jnp_dtype), v.valid, self.d)


def _infer(np_dtype) -> DType:
    m = {
        "int8": dt.INT8, "int16": dt.INT16, "int32": dt.INT32, "int64": dt.INT64,
        "uint8": dt.UINT8, "uint16": dt.UINT16, "uint32": dt.UINT32, "uint64": dt.UINT64,
        "float32": dt.FLOAT32, "float64": dt.FLOAT64, "bool": dt.BOOL8,
    }
    return m[str(np_dtype)]


def _wrap(v) -> Expression:
    return v if isinstance(v, Expression) else _Literal(v)


def col(name: str) -> Expression:
    return _ColumnRef(name)


def lit(value) -> Expression:
    return _Literal(value)


def when(cond, then, otherwise) -> Expression:
    """SQL ``CASE WHEN cond THEN then ELSE otherwise END``.

    The workhorse conditional ~40 of the TPC-DS q1-q99 use (pivots,
    guarded ratios, bucketed counts — see QUERIES.md); Spark lowers it
    to cudf copy_if_else in the reference engine tier (SURVEY §2.8).
    ``then``/``otherwise`` may be expressions or literals; chained CASE
    arms nest: ``when(c1, a, when(c2, b, c))``."""
    return _When(_wrap(cond), _wrap(then), _wrap(otherwise))

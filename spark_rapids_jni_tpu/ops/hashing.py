"""Spark-compatible Murmur3 hashing (cudf hashing tier, SURVEY §2.8).

Spark's Murmur3Hash (and cudf's MurmurHash3_32) hash each column value
with the running hash as seed, default seed 42; ints are hashed as their
4-byte block, longs/doubles as two blocks, strings per 4-byte chunk with
tail handling. Used by hash_partition (the shuffle partitioner) and the
join/groupby tier.

Vectorized: block loops are unrolled per column width; string chunk
count is the padded max length (static per batch).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from jax import lax

from ..columnar import Column, Table
from ..columnar.dtype import TypeId

__all__ = ["murmur3_table", "murmur3_raw", "hash_partition_map"]

_C1 = jnp.uint32(0xCC9E2D51)
_C2 = jnp.uint32(0x1B873593)


def _rotl(x, r):
    return (x << jnp.uint32(r)) | (x >> jnp.uint32(32 - r))


def _mix_k(k):
    k = k * _C1
    k = _rotl(k, 15)
    return k * _C2


def _mix_h(h, k):
    h = h ^ _mix_k(k)
    h = _rotl(h, 13)
    return h * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h):
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> jnp.uint32(16))


def _hash_fixed(col: Column, seed: jnp.ndarray) -> jnp.ndarray:
    d = col.dtype
    data = col.data
    if d.id == TypeId.DECIMAL128:
        words = [col.data[:, k] for k in range(4)]
    elif d.size_bytes == 8 or d.id == TypeId.FLOAT64:
        u = lax.bitcast_convert_type(data, jnp.uint32)  # [N, 2]
        words = [u[:, 0], u[:, 1]]
    elif d.size_bytes <= 4:
        # promote small ints to a single 4-byte block (Spark hashes
        # byte/short/int identically after widening to int)
        if d.id == TypeId.BOOL8:
            w = data.astype(jnp.uint32)
        else:
            udt = {1: jnp.int8, 2: jnp.int16, 4: jnp.int32}.get(d.size_bytes)
            signed = data.astype(jnp.int32) if d.is_signed or d.id == TypeId.BOOL8 else data
            w = lax.bitcast_convert_type(signed.astype(jnp.int32), jnp.uint32)
        words = [w]
    else:
        raise ValueError(f"cannot hash dtype {d!r}")

    h = seed
    for w in words:
        h = _mix_h(h, w.astype(jnp.uint32))
    h = h ^ jnp.uint32(4 * len(words))
    return _fmix(h)


def _hash_string(col: Column, seed: jnp.ndarray) -> jnp.ndarray:
    offs = col.offsets
    lens = offs[1:] - offs[:-1]
    n = len(col)
    max_len = max(int(jnp.max(lens)) if n else 0, 1)
    pad4 = (max_len + 3) // 4 * 4
    idx = offs[:-1, None] + jnp.arange(pad4, dtype=jnp.int32)[None, :]
    inb = jnp.arange(pad4, dtype=jnp.int32)[None, :] < lens[:, None]
    nchars = max(int(col.chars.shape[0]), 1)
    chars = jnp.where(inb, col.chars[jnp.clip(idx, 0, nchars - 1)], 0).astype(jnp.uint32)

    h = seed
    nblocks = lens // 4
    for b in range(pad4 // 4):
        k = (
            chars[:, 4 * b]
            | (chars[:, 4 * b + 1] << jnp.uint32(8))
            | (chars[:, 4 * b + 2] << jnp.uint32(16))
            | (chars[:, 4 * b + 3] << jnp.uint32(24))
        )
        h = jnp.where(b < nblocks, _mix_h(h, k), h)

    # tail: remaining 1-3 bytes, mixed k1-style without the h-mix
    tail_start = (nblocks * 4).astype(jnp.int32)
    tail_len = lens - tail_start
    k1 = jnp.zeros((n,), jnp.uint32)
    for t in (2, 1, 0):
        byte = jnp.take_along_axis(
            chars, jnp.clip(tail_start + t, 0, pad4 - 1)[:, None], axis=1
        )[:, 0]
        k1 = jnp.where(tail_len > t, (k1 << jnp.uint32(8)) | byte, k1)
    h = jnp.where(tail_len > 0, h ^ _mix_k(k1), h)

    h = h ^ lens.astype(jnp.uint32)
    return _fmix(h)


def murmur3_table(table_or_cols, seed: int = 42) -> jnp.ndarray:
    """[N] uint32 row hashes; columns chain with h as the next seed
    (Spark Murmur3Hash semantics)."""
    cols: Sequence[Column] = (
        table_or_cols.columns if isinstance(table_or_cols, Table) else list(table_or_cols)
    )
    n = len(cols[0])
    h = jnp.full((n,), seed, jnp.uint32)
    for col in cols:
        if col.dtype.id == TypeId.STRING:
            nh = _hash_string(col, h)
        else:
            nh = _hash_fixed(col, h)
        # null values leave the running hash unchanged (Spark semantics)
        if col.validity is not None:
            nh = jnp.where(col.validity, nh, h)
        h = nh
    return h


def murmur3_raw(data: jnp.ndarray, seed=42) -> jnp.ndarray:
    """[N] uint32 murmur3 over a raw integer array — identical result to
    ``murmur3_table`` on a Column of the same width (4-byte values hash
    as one block, 8-byte as two), for use inside shard_map where values
    travel as bare arrays. ``seed`` may be an int or a [N] uint32 array
    (the running hash, for Spark-style multi-column chaining)."""
    n = data.shape[0]
    h = jnp.broadcast_to(jnp.asarray(seed, jnp.uint32), (n,))
    if data.dtype.itemsize == 8:
        u = lax.bitcast_convert_type(data, jnp.uint32)  # [N, 2]
        words = [u[:, 0], u[:, 1]]
    elif data.dtype.itemsize <= 4:
        signed = data.astype(jnp.int32) if jnp.issubdtype(data.dtype, jnp.signedinteger) else data
        words = [lax.bitcast_convert_type(signed.astype(jnp.int32), jnp.uint32)]
    else:
        raise ValueError(f"cannot hash raw dtype {data.dtype}")
    for w in words:
        h = _mix_h(h, w.astype(jnp.uint32))
    h = h ^ jnp.uint32(4 * len(words))
    return _fmix(h)


def hash_partition_map(table_or_cols, num_partitions: int, seed: int = 42) -> jnp.ndarray:
    """[N] int32 partition of each row: pmod(murmur3, num_partitions)."""
    h = murmur3_table(table_or_cols, seed)
    signed = lax.bitcast_convert_type(h, jnp.int32)
    m = signed % jnp.int32(num_partitions)
    return jnp.where(m < 0, m + num_partitions, m)

"""Byte/bit reinterpretation helpers, TPU-safe.

TPU v5e has no 64-bit float datapath: XLA's x64-rewrite emulates s64/u64
exactly as u32 pairs but demotes f64 to f32 (lossy, even for plain
transfers). The framework therefore stores FLOAT64 columns as IEEE-754
bit patterns in uint64 lanes (columnar/dtype.py) and this module is the
single place that moves between bits and arithmetic values:

- ``to_le_bytes`` / ``from_le_bytes``: little-endian byte views for the
  JCUDF transcode and hashing tiers (pure integer bitcasts — supported
  on TPU for every integer width).
- ``float_view``: bits -> floating values for compute ops. Exact f64 on
  backends with a native f64 datapath (CPU tier); documented f32
  approximation on TPU.
- ``float_store``: floating compute results -> FLOAT64 bit storage.
- ``total_order_key``: IEEE-754 total-order transform so sorts and
  comparisons on FLOAT64 stay *exact* on TPU (no precision loss — the
  classic radix-sort bit flip).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..columnar.dtype import DType, TypeId

__all__ = [
    "to_le_bytes",
    "from_le_bytes",
    "float_view",
    "float_store",
    "total_order_key",
    "backend_has_f64",
    "ragged_positions",
]


def ragged_positions(lens):
    """Shared ragged-compaction index math: [N] int32 lengths ->
    (offsets [N+1] i32, row_of [total] i32, pos_in_row [total] i32,
    total). One host sync for `total` (the output-allocation sync every
    engine pays). Used by the string compactions in ops/strings and the
    JCUDF string decode."""
    offs = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
    total = int(offs[-1])  # host sync: chars allocation size
    if total == 0:
        z = jnp.zeros((0,), jnp.int32)
        return offs, z, z, 0
    j = jnp.arange(total, dtype=jnp.int32)
    row_of = jnp.searchsorted(offs, j, side="right").astype(jnp.int32) - 1
    pos = j - offs[row_of]
    return offs, row_of, pos, total


def backend_has_f64() -> bool:
    """True when the default backend computes real float64 (CPU tier)."""
    return jax.default_backend() == "cpu"


def to_le_bytes(data: jnp.ndarray, d: DType) -> jnp.ndarray:
    """[N] typed storage array -> [N, size] uint8 little-endian bytes."""
    if d.size_bytes == 1:
        return lax.bitcast_convert_type(data, jnp.uint8).reshape(-1, 1)
    return lax.bitcast_convert_type(data, jnp.uint8)


def from_le_bytes(bytes_: jnp.ndarray, d: DType) -> jnp.ndarray:
    """[N, size] uint8 -> [N] typed storage array (inverse of to_le_bytes)."""
    if d.size_bytes == 1:
        return lax.bitcast_convert_type(bytes_[:, 0], d.jnp_dtype)
    return lax.bitcast_convert_type(bytes_, d.jnp_dtype)


# ---------------------------------------------------------------------------
# FLOAT64 bits <-> arithmetic values
# ---------------------------------------------------------------------------


def _f64_bits_to_f32(bits: jnp.ndarray) -> jnp.ndarray:
    """uint64 IEEE-754 double bits -> float32 values, round-to-nearest-even.

    Pure integer construction of the f32 bit pattern (u32 bitcast is
    TPU-supported); handles overflow->inf, underflow->0, nan, inf.
    Subnormal f32 results flush to zero (they are below 1e-38; Spark
    doubles in that range are astronomically rare and TPU VPUs flush
    subnormals anyway).
    """
    sign32 = (bits >> jnp.uint64(32)).astype(jnp.uint32) & jnp.uint32(0x80000000)
    exp = ((bits >> jnp.uint64(52)) & jnp.uint64(0x7FF)).astype(jnp.int32)
    frac = bits & jnp.uint64((1 << 52) - 1)

    # round the 52-bit fraction to 23 bits (nearest even on the 29 dropped bits)
    keep = (frac >> jnp.uint64(29)).astype(jnp.uint32)
    dropped = frac & jnp.uint64((1 << 29) - 1)
    half = jnp.uint64(1 << 28)
    round_up = (dropped > half) | ((dropped == half) & ((keep & jnp.uint32(1)) == 1))
    keep = keep + round_up.astype(jnp.uint32)
    exp = exp + (keep >> jnp.uint32(23)).astype(jnp.int32)  # mantissa carry
    keep = keep & jnp.uint32((1 << 23) - 1)

    new_exp = exp - 1023 + 127
    is_nan = (exp == 0x7FF) & (frac != 0)
    is_inf = (exp == 0x7FF) & (frac == 0)
    overflow = new_exp >= 0xFF
    underflow = new_exp <= 0
    is_zero = (exp == 0)  # f64 zeros/subnormals all flush below f32 range

    out = sign32 | (jnp.clip(new_exp, 1, 0xFE).astype(jnp.uint32) << jnp.uint32(23)) | keep
    out = jnp.where(underflow | is_zero, sign32, out)
    out = jnp.where(overflow | is_inf, sign32 | jnp.uint32(0x7F800000), out)
    out = jnp.where(is_nan, sign32 | jnp.uint32(0x7FC00000), out)
    return lax.bitcast_convert_type(out, jnp.float32)


def _f32_to_f64_bits(x: jnp.ndarray) -> jnp.ndarray:
    """float32 values -> uint64 IEEE-754 double bits (exact widening)."""
    b = lax.bitcast_convert_type(x, jnp.uint32).astype(jnp.uint64)
    sign = (b & jnp.uint64(0x80000000)) << jnp.uint64(32)
    exp = ((b >> jnp.uint64(23)) & jnp.uint64(0xFF)).astype(jnp.int64)
    frac = b & jnp.uint64((1 << 23) - 1)

    # normals: rebias 127 -> 1023; widen fraction 23 -> 52 bits
    norm = ((exp - 127 + 1023).astype(jnp.uint64) << jnp.uint64(52)) | (frac << jnp.uint64(29))
    # f32 subnormals: frac * 2^-149; normalize into f64 (which has headroom)
    nz = frac != 0
    # position of the highest set bit of frac (frac < 2^23)
    hi = jnp.int64(22) - _clz23(frac)
    sub_exp = (hi - 23 + 1 - 126 + 1023).astype(jnp.uint64)
    sub_frac = (frac << (jnp.uint64(52 - 23) + (jnp.int64(22) - hi).astype(jnp.uint64))) & jnp.uint64(
        (1 << 52) - 1
    )
    subnormal = jnp.where(nz, (sub_exp << jnp.uint64(52)) | sub_frac, jnp.uint64(0))

    out = jnp.where(exp == 0, subnormal, norm)
    out = jnp.where(exp == 0xFF, (jnp.uint64(0x7FF) << jnp.uint64(52)) | (frac << jnp.uint64(29)), out)
    return sign | out


def _clz23(frac: jnp.ndarray) -> jnp.ndarray:
    """count leading zeros within the low 23 bits (input uint64, frac != 0)."""
    f = frac.astype(jnp.uint32)
    n = jnp.zeros(f.shape, jnp.int64)
    for shift in (16, 8, 4, 2, 1):
        mask = f < (jnp.uint32(1) << jnp.uint32(23 - shift))
        n = jnp.where(mask, n + shift, n)
        f = jnp.where(mask, f << jnp.uint32(shift), f)
    return n


def float_view(data: jnp.ndarray, d: DType) -> jnp.ndarray:
    """Column storage -> floating array for arithmetic.

    FLOAT64: exact f64 on CPU backends; f32 approximation on TPU.
    """
    if d.id == TypeId.FLOAT64:
        if backend_has_f64():
            return lax.bitcast_convert_type(data, jnp.float64)
        return _f64_bits_to_f32(data)
    if d.id == TypeId.FLOAT32:
        return data
    raise ValueError(f"float_view on non-floating dtype {d!r}")


def float_store(values: jnp.ndarray, d: DType) -> jnp.ndarray:
    """Floating compute result -> column storage array."""
    if d.id == TypeId.FLOAT64:
        if values.dtype == jnp.float64 and backend_has_f64():
            return lax.bitcast_convert_type(values, jnp.uint64)
        return _f32_to_f64_bits(values.astype(jnp.float32))
    if d.id == TypeId.FLOAT32:
        return values.astype(jnp.float32)
    raise ValueError(f"float_store on non-floating dtype {d!r}")


def total_order_key(data: jnp.ndarray, d: DType) -> jnp.ndarray:
    """Monotone integer sort key for any fixed-width column (exact).

    Floats use the IEEE-754 total-order transform on raw bits, so FLOAT64
    ordering is exact even on TPU where f64 arithmetic is approximated.
    Signed ints flip the sign bit into unsigned order.
    """
    if d.id == TypeId.FLOAT64:
        bits = data  # already uint64 bit storage
        sign_all = jnp.uint64(0xFFFFFFFFFFFFFFFF)
        neg = (bits >> jnp.uint64(63)) == 1
        return jnp.where(neg, bits ^ sign_all, bits | jnp.uint64(1 << 63))
    if d.id == TypeId.FLOAT32:
        bits = lax.bitcast_convert_type(data, jnp.uint32)
        neg = (bits >> jnp.uint32(31)) == 1
        return jnp.where(neg, bits ^ jnp.uint32(0xFFFFFFFF), bits | jnp.uint32(1 << 31))
    if d.is_signed or d.id in (
        TypeId.TIMESTAMP_DAYS,
        TypeId.TIMESTAMP_SECONDS,
        TypeId.TIMESTAMP_MILLISECONDS,
        TypeId.TIMESTAMP_MICROSECONDS,
        TypeId.TIMESTAMP_NANOSECONDS,
        TypeId.DURATION_DAYS,
        TypeId.DURATION_SECONDS,
        TypeId.DURATION_MILLISECONDS,
        TypeId.DURATION_MICROSECONDS,
        TypeId.DURATION_NANOSECONDS,
        TypeId.DECIMAL32,
        TypeId.DECIMAL64,
    ):
        udt = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}[d.size_bytes]
        bits = lax.bitcast_convert_type(data, udt)
        return bits ^ (udt(1) << udt(8 * d.size_bytes - 1))
    return data  # unsigned ints / bool are already in order

"""TPU-native regex tier (cudf strings/regex replacement, SURVEY §2.8).

The reference offloads Spark's RLIKE / regexp_extract / split to cudf's
warp-per-string backtracking regex VM. A backtracking VM is the wrong
shape for a TPU — data-dependent control flow per string kills XLA.
This engine is compiled + table-driven instead:

  host (per pattern, cached):
    parse a regex SUBSET -> Thompson NFA -> subset-construction DFA over
    codepoint *equivalence classes* (all class boundaries in the pattern
    split [0, 0x110000) into a handful of intervals; a 0x110000-entry
    int32 lookup maps codepoint -> class id).
  device (per batch):
    strings decode to a padded [N, L] int32 codepoint matrix
    (ops/utf8.py), the DFA runs as ONE `lax.scan` over the L columns —
    a [n_states * n_classes] table gather per step, no per-string
    control flow.

Three runtimes ride the same machinery:
  - `matches_re` / `contains_re`: a single DFA run, O(N*L). Unanchored
    search compiles the ".*pattern" DFA (the subset construction absorbs
    the restart loop), so `contains` costs exactly one scan too.
  - span finding (extract/split): an ALL-STARTS run — state column p
    tracks the run anchored at codepoint p, so one scan yields every
    (start, end) match pair. O(N*L^2) work but fully vectorized.
  - leftmost-greedy capture groups: the pattern's top-level
    concatenation is split into segments; a BACKWARD pass computes
    suffix-matchability sets and a forward pass picks each segment's
    greedy (or lazy) end consistent with the suffix — exact Java
    semantics for top-level groups, with no backtracking.

Subset: literals, '.', escapes, char classes (ranges, negation,
\\d \\D \\w \\W \\s \\S), concatenation, alternation, groups
(capturing / (?:...)), quantifiers * + ? {m} {m,} {m,n} with lazy '?'
variants, anchors ^ $ at the pattern edges. Unsupported (raise
ValueError): backreferences, lookaround, word boundaries, inline flags;
nested or quantified capture groups cannot be *extracted* (matching
still works). Alternation is matched longest-wins (DFA semantics), not
PCRE ordered — documented divergence.

Reference parity targets: cudf strings contains_re/matches_re/extract/
split (SURVEY §2.8); Spark exprs RLike, RegExpExtract, StringSplit.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..columnar import Column
from ..columnar import dtype as dt
from ..columnar.dtype import TypeId
from ..utils.dispatch import op_boundary
from .utf8 import MAX_CODEPOINT, decode_padded

__all__ = [
    "compile_pattern",
    "contains_re",
    "matches_re",
    "extract_re",
    "split_re",
    "replace_re",
]

_NCP = MAX_CODEPOINT + 1
_MAX_DFA_STATES = 1024
_MAX_REP = 64

# ---------------------------------------------------------------------------
# Parser: pattern -> AST
# AST nodes (plain tuples):
#   ("class", ((lo, hi), ...))       inclusive codepoint intervals
#   ("cat", (child, ...))
#   ("alt", (child, ...))
#   ("rep", child, m, n, greedy)     n=None means unbounded
#   ("group", index, child)          capturing group, 1-based index
# ---------------------------------------------------------------------------

_D = ((ord("0"), ord("9")),)
_W = ((ord("0"), ord("9")), (ord("A"), ord("Z")), (ord("_"), ord("_")), (ord("a"), ord("z")))
_S = tuple(sorted((ord(c), ord(c)) for c in " \t\n\r\f\v"))


def _negate(intervals) -> Tuple[Tuple[int, int], ...]:
    out, prev = [], 0
    for lo, hi in sorted(intervals):
        if lo > prev:
            out.append((prev, lo - 1))
        prev = max(prev, hi + 1)
    if prev <= MAX_CODEPOINT:
        out.append((prev, MAX_CODEPOINT))
    return tuple(out)


_DOT = _negate(((ord("\n"), ord("\n")),))  # '.' = any char except \n (no DOTALL)
_ANY = ((0, MAX_CODEPOINT),)

_ESCAPE_CLASSES = {
    "d": _D,
    "D": _negate(_D),
    "w": _W,
    "W": _negate(_W),
    "s": _S,
    "S": _negate(_S),
}
_ESCAPE_LITERALS = {
    "n": "\n", "t": "\t", "r": "\r", "f": "\f", "v": "\v",
    "0": "\0", "a": "\a", "b": "\b", "e": "\x1b",
}


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        self.ngroups = 0
        self.anchor_start = False
        self.anchor_end = False

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def take(self) -> str:
        if self.i >= len(self.p):
            raise ValueError(f"unexpected end of pattern /{self.p}/")
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self):
        if self.peek() == "^":
            self.take()
            self.anchor_start = True
        ast = self.alt()
        if self.i < len(self.p):
            raise ValueError(f"unexpected {self.p[self.i]!r} at {self.i} in /{self.p}/")
        if (self.anchor_start or self.anchor_end) and ast[0] == "alt":
            # flags anchor the WHOLE pattern; with a top-level alternation
            # Java scopes them to one branch — refuse rather than silently
            # anchoring every branch (group the alternation to anchor all)
            raise ValueError(
                "anchors with top-level alternation unsupported — "
                "group the alternation: ^(?:a|b)$"
            )
        return ast

    def alt(self):
        branches = [self.cat()]
        while self.peek() == "|":
            self.take()
            branches.append(self.cat())
        return branches[0] if len(branches) == 1 else ("alt", tuple(branches))

    def cat(self):
        items: list = []
        while True:
            c = self.peek()
            if c is None or c in "|)":
                break
            if c == "$":
                if self.i == len(self.p) - 1:
                    self.take()
                    self.anchor_end = True
                    break
                raise ValueError("'$' supported only at pattern end")
            if c == "^":
                raise ValueError("'^' supported only at pattern start")
            items.append(self.quantified())
        return ("cat", tuple(items))

    def quantified(self):
        atom = self.atom()
        c = self.peek()
        if c in ("*", "+", "?"):
            self.take()
            m, n = {"*": (0, None), "+": (1, None), "?": (0, 1)}[c]
        elif c == "{":
            m, n = self.brace()
        else:
            return atom
        greedy = True
        if self.peek() == "?":
            self.take()
            greedy = False
        if _contains_group(atom) and (m, n) != (1, 1):
            # a quantified capture group's spans can't be recovered by
            # the segment decomposition; matching still works with the
            # group markers dropped (extract of that index will raise)
            atom = _strip_groups(atom)
        return ("rep", atom, m, n, greedy)

    def brace(self):
        self.take()  # '{'
        start = self.i
        while self.peek() is not None and self.peek() != "}":
            self.take()
        if self.peek() != "}":
            raise ValueError("unterminated {…} quantifier")
        body = self.p[start : self.i]
        self.take()
        parts = body.split(",")
        try:
            if len(parts) == 1:
                m = n = int(parts[0])
            elif len(parts) == 2:
                m = int(parts[0])
                n = int(parts[1]) if parts[1] else None
            else:
                raise ValueError
        except ValueError:
            raise ValueError(f"bad quantifier {{{body}}}") from None
        if m < 0 or m > _MAX_REP or (n is not None and (n > _MAX_REP or n < m)):
            raise ValueError(f"repetition bounds out of [0, {_MAX_REP}] (or n<m) in {{{body}}}")
        return m, n

    def atom(self):
        c = self.take()
        if c == "(":
            capturing = True
            if self.peek() == "?":
                self.take()
                nxt = self.take()
                if nxt == ":":
                    capturing = False
                else:
                    raise ValueError(f"unsupported group (?{nxt}…) — only (?:…)")
            if capturing:
                self.ngroups += 1
                idx = self.ngroups
            inner = self.alt()
            if self.peek() != ")":
                raise ValueError("unbalanced '('")
            self.take()
            return ("group", idx, inner) if capturing else inner
        if c == "[":
            return self.char_class()
        if c == ".":
            return ("class", _DOT)
        if c == "\\":
            return self.escape(in_class=False)
        if c in "*+?{":
            raise ValueError(f"dangling quantifier {c!r}")
        return ("class", ((ord(c), ord(c)),))

    def escape(self, in_class: bool):
        if self.peek() is None:
            raise ValueError("trailing backslash")
        e = self.take()
        if e in _ESCAPE_CLASSES:
            ivs = _ESCAPE_CLASSES[e]
            return ivs if in_class else ("class", tuple(ivs))
        # \b is backspace inside a class, word boundary (unsupported) outside
        if e in _ESCAPE_LITERALS and (in_class or e != "b"):
            ch = _ESCAPE_LITERALS[e]
            iv = ((ord(ch), ord(ch)),)
            return iv if in_class else ("class", iv)
        if e == "x":
            h = self.take() + self.take()
            iv = ((int(h, 16), int(h, 16)),)
            return iv if in_class else ("class", iv)
        if e == "u":
            h = "".join(self.take() for _ in range(4))
            iv = ((int(h, 16), int(h, 16)),)
            return iv if in_class else ("class", iv)
        if e.isalnum():
            raise ValueError(f"unsupported escape \\{e}")
        iv = ((ord(e), ord(e)),)
        return iv if in_class else ("class", iv)

    def char_class(self):
        negated = False
        if self.peek() == "^":
            self.take()
            negated = True
        intervals: list = []
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise ValueError("unterminated character class")
            if c == "]" and not first:
                self.take()
                break
            first = False
            self.take()
            if c == "\\":
                ivs = self.escape(in_class=True)
                if len(ivs) > 1 or ivs[0][0] != ivs[0][1]:
                    intervals.extend(ivs)
                    continue
                lo = ivs[0][0]
            else:
                lo = ord(c)
            if self.peek() == "-" and self.i + 1 < len(self.p) and self.p[self.i + 1] != "]":
                self.take()
                hc = self.take()
                if hc == "\\":
                    ivs = self.escape(in_class=True)
                    if len(ivs) != 1 or ivs[0][0] != ivs[0][1]:
                        raise ValueError("bad range end in character class")
                    hi = ivs[0][0]
                else:
                    hi = ord(hc)
                if hi < lo:
                    raise ValueError("reversed range in character class")
                intervals.append((lo, hi))
            else:
                intervals.append((lo, lo))
        ivs = tuple(sorted(intervals))
        return ("class", _negate(ivs) if negated else ivs)


def _contains_group(ast) -> bool:
    if ast[0] == "group":
        return True
    if ast[0] in ("cat", "alt"):
        return any(_contains_group(c) for c in ast[1])
    if ast[0] == "rep":
        return _contains_group(ast[1])
    return False


def _strip_groups(ast):
    if ast[0] == "group":
        return _strip_groups(ast[2])
    if ast[0] in ("cat", "alt"):
        return (ast[0], tuple(_strip_groups(c) for c in ast[1]))
    if ast[0] == "rep":
        return ("rep", _strip_groups(ast[1]), *ast[2:])
    return ast


# ---------------------------------------------------------------------------
# NFA (Thompson) -> DFA (subset construction over equivalence classes)
# ---------------------------------------------------------------------------


class _NFA:
    def __init__(self):
        self.eps: List[List[int]] = []
        self.trans: List[List[Tuple[Tuple[Tuple[int, int], ...], int]]] = []

    def new_state(self) -> int:
        self.eps.append([])
        self.trans.append([])
        return len(self.eps) - 1

    def add(self, ast) -> Tuple[int, int]:
        kind = ast[0]
        if kind == "class":
            s, t = self.new_state(), self.new_state()
            self.trans[s].append((ast[1], t))
            return s, t
        if kind == "group":
            return self.add(ast[2])
        if kind == "cat":
            s = t = self.new_state()
            for child in ast[1]:
                cs, ct = self.add(child)
                self.eps[t].append(cs)
                t = ct
            return s, t
        if kind == "alt":
            s, t = self.new_state(), self.new_state()
            for child in ast[1]:
                cs, ct = self.add(child)
                self.eps[s].append(cs)
                self.eps[ct].append(t)
            return s, t
        if kind == "rep":
            _, child, m, n, _greedy = ast
            s = t = self.new_state()
            for _ in range(m):
                cs, ct = self.add(child)
                self.eps[t].append(cs)
                t = ct
            if n is None:
                cs, ct = self.add(child)
                end = self.new_state()
                self.eps[t].append(cs)
                self.eps[ct].append(cs)
                self.eps[t].append(end)
                self.eps[ct].append(end)
                return s, end
            tails = [t]
            for _ in range(n - m):
                cs, ct = self.add(child)
                self.eps[t].append(cs)
                t = ct
                tails.append(t)
            end = self.new_state()
            for x in tails:
                self.eps[x].append(end)
            return s, end
        raise AssertionError(f"unknown AST node {kind}")

    def closure(self, states) -> frozenset:
        seen = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in self.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)


class CompiledPattern:
    """Host-side compiled DFA + lazily-uploaded device tables."""

    def __init__(self, pattern, trans, accept, class_of, anchor_start,
                 anchor_end, ast, ngroups):
        self.pattern = pattern
        self.trans = trans          # np [S, C] int32
        self.accept = accept        # np [S] bool
        self.class_of = class_of    # np [_NCP] int32
        self.anchor_start = anchor_start
        self.anchor_end = anchor_end
        self.ast = ast
        self.ngroups = ngroups
        self._device = None

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]

    @property
    def n_classes(self) -> int:
        return self.trans.shape[1]

    def device_tables(self):
        if self._device is None:
            self._device = (
                jnp.asarray(self.trans.reshape(-1)),
                jnp.asarray(self.accept),
                jnp.asarray(self.class_of),
            )
        return self._device


def _compile_ast(ast, anchor_start=False, anchor_end=False, pattern="", ngroups=0) -> CompiledPattern:
    # 1) codepoint equivalence classes
    bounds = {0, _NCP}

    def walk(a):
        if a[0] == "class":
            for lo, hi in a[1]:
                bounds.add(lo)
                bounds.add(hi + 1)
        elif a[0] in ("cat", "alt"):
            for c in a[1]:
                walk(c)
        elif a[0] == "rep":
            walk(a[1])
        elif a[0] == "group":
            walk(a[2])

    walk(ast)
    cuts = sorted(b for b in bounds if 0 <= b <= _NCP)
    n_classes = len(cuts) - 1
    class_of = np.zeros(_NCP, np.int32)
    for ci in range(n_classes):
        class_of[cuts[ci] : cuts[ci + 1]] = ci
    reps = np.asarray(cuts[:-1], np.int64)  # representative cp per class

    # 2) NFA
    nfa = _NFA()
    start, accept_nfa = nfa.add(ast)

    def class_mask(intervals) -> np.ndarray:
        m = np.zeros(n_classes, bool)
        for lo, hi in intervals:
            m |= (reps >= lo) & (reps <= hi)
        return m

    trans_masks = [
        [(class_mask(ivs), t) for ivs, t in nfa.trans[s]] for s in range(len(nfa.trans))
    ]

    # 3) subset construction
    start_set = nfa.closure([start])
    ids = {start_set: 0}
    order = [start_set]
    rows: List[np.ndarray] = []
    i = 0
    while i < len(order):
        cur = order[i]
        row = np.zeros(n_classes, np.int32)
        for ci in range(n_classes):
            targets = set()
            for s in cur:
                for mask, t in trans_masks[s]:
                    if mask[ci]:
                        targets.add(t)
            nxt = nfa.closure(targets) if targets else frozenset()
            if nxt not in ids:
                if len(ids) >= _MAX_DFA_STATES:
                    raise ValueError(
                        f"pattern /{pattern}/ exceeds {_MAX_DFA_STATES} DFA states"
                    )
                ids[nxt] = len(ids)
                order.append(nxt)
            row[ci] = ids[nxt]
        rows.append(row)
        i += 1
    trans = np.stack(rows)
    accept = np.array([accept_nfa in st for st in order], bool)
    return CompiledPattern(pattern, trans, accept, class_of, anchor_start,
                           anchor_end, ast, ngroups)


@functools.lru_cache(maxsize=256)
def compile_pattern(pattern: str) -> CompiledPattern:
    """Parse + compile the ANCHORED pattern DFA (cached per process,
    like the plugin's cudf regex prog cache)."""
    p = _Parser(pattern)
    ast = p.parse()
    return _compile_ast(ast, p.anchor_start, p.anchor_end, pattern, p.ngroups)


@functools.lru_cache(maxsize=256)
def _search_pattern(pattern: str) -> CompiledPattern:
    """The ".*pattern" DFA for unanchored search: the subset
    construction absorbs the restart loop, so `contains` is a single
    forward run instead of an all-starts matrix."""
    p = _Parser(pattern)
    ast = _strip_groups(p.parse())
    if not p.anchor_start:
        ast = ("cat", (("rep", ("class", _ANY), 0, None, True), ast))
    return _compile_ast(ast, p.anchor_start, p.anchor_end, pattern, 0)


# ---------------------------------------------------------------------------
# Device runtimes
# ---------------------------------------------------------------------------


def _check_string(col: Column) -> None:
    if col.dtype.id != TypeId.STRING:
        raise ValueError("regex op on non-string column")


def _codepoints(col: Column):
    from .strings import to_padded

    padded, lens = to_padded(col)
    cp, cp_lens, byte_off = decode_padded(padded, lens)
    return cp, cp_lens, byte_off


def _forward_run(prog: CompiledPattern, cp, cp_lens, sticky: bool):
    """One DFA pass. sticky=False: return accept[state after the full
    string] (full/suffix match). sticky=True: latch accept at any prefix
    position (substring search with a ".*"-prefixed DFA)."""
    trans_flat, accept, class_of = prog.device_tables()
    C = prog.n_classes
    n, L = cp.shape
    cls = class_of[jnp.clip(cp, 0, _NCP - 1)]

    def body(carry, c):
        state, hit = carry
        j, cls_j = c
        nxt = trans_flat[(state * C + cls_j).astype(jnp.int32)]
        state2 = jnp.where(j < cp_lens, nxt, state)
        hit2 = hit | (accept[state2] & (j < cp_lens))
        return (state2, hit2), None

    init = (jnp.zeros((n,), jnp.int32), jnp.broadcast_to(accept[0], (n,)))
    (state, hit), _ = lax.scan(
        body, init, (jnp.arange(L, dtype=jnp.int32), cls.T)
    )
    return hit if sticky else accept[state]


def _all_starts(prog: CompiledPattern, cp, cp_lens, endmask):
    """All-starts DFA run. Returns (matched [N, L+1], first_end,
    last_end) over start positions p in [0, L]; ends are codepoint
    indices, -1 where no (mask-consistent) accept was seen.

    endmask: optional [N, L+1] bool of permitted END positions; a '$'
    anchor additionally restricts ends to len.
    """
    trans_flat, accept, class_of = prog.device_tables()
    n, L = cp.shape
    P = L + 1
    C = prog.n_classes
    cls = class_of[jnp.clip(cp, 0, _NCP - 1)]

    em = endmask
    if prog.anchor_end:
        e = jnp.arange(P, dtype=jnp.int32)[None, :]
        anchor = e == cp_lens[:, None]
        em = anchor if em is None else (em & anchor)
    if em is None:
        em = jnp.ones((n, P), bool)

    parr = jnp.arange(P, dtype=jnp.int32)[None, :]
    start_ok = parr <= cp_lens[:, None]
    S0 = jnp.zeros((n, P), jnp.int32)
    acc0 = jnp.broadcast_to(jnp.asarray(bool(prog.accept[0])), (n, P)) & start_ok & em
    first0 = jnp.where(acc0, parr, -1)
    last0 = jnp.where(acc0, parr, -1)

    def body(carry, c):
        S, matched, first, last = carry
        j, cls_j, em_j1 = c  # em_j1 = endmask at end position j+1, [N]
        active = (parr <= j) & (j < cp_lens[:, None])
        nxt = trans_flat[(S * C + cls_j[:, None]).astype(jnp.int32)]
        S2 = jnp.where(active, nxt, S)
        acc = accept[S2] & active & em_j1[:, None]
        first2 = jnp.where(acc & (first < 0), j + 1, first)
        last2 = jnp.where(acc, j + 1, last)
        return (S2, matched | acc, first2, last2), None

    (S, matched, first, last), _ = lax.scan(
        body,
        (S0, acc0, first0, last0),
        (jnp.arange(L, dtype=jnp.int32), cls.T, em[:, 1:].T),
    )
    return matched, first, last


@op_boundary("strings.contains_re")
def contains_re(col: Column, pattern: str) -> Column:
    """Spark RLIKE: true iff the pattern matches anywhere in the string."""
    _check_string(col)
    prog = _search_pattern(pattern)
    cp, cp_lens, _ = _codepoints(col)
    # with a '$' anchor the sticky latch is wrong (the match must END at
    # len) — use the final state of the ".*pattern" run instead
    hit = _forward_run(prog, cp, cp_lens, sticky=not prog.anchor_end)
    return Column(dt.BOOL8, data=hit.astype(jnp.uint8), validity=col.validity)


@op_boundary("strings.matches_re")
def matches_re(col: Column, pattern: str) -> Column:
    """Full-string match (cudf matches_re; Spark LIKE-via-regex path)."""
    _check_string(col)
    prog = compile_pattern(pattern)
    cp, cp_lens, _ = _codepoints(col)
    ok = _forward_run(prog, cp, cp_lens, sticky=False)
    return Column(dt.BOOL8, data=ok.astype(jnp.uint8), validity=col.validity)


def _top_segments(prog: CompiledPattern):
    """Split the top-level concatenation into (ast, group_index_or_None)
    segments for span recovery."""
    ast = prog.ast
    items = ast[1] if ast[0] == "cat" else (ast,)
    segs = []
    for it in items:
        if it[0] == "group":
            if _contains_group(it[2]):
                raise ValueError("nested capture groups unsupported in extract")
            segs.append((it[2], it[1]))
        else:
            if _contains_group(it):
                raise ValueError(
                    "capture groups must be top-level concatenation members for extract"
                )
            segs.append((_strip_groups(it), None))
    return segs


def _substr_by_cp_span(col: Column, byte_off, begin_cp, end_cp, valid) -> Column:
    """Slice each row to the byte span of codepoints [begin, end);
    invalid rows become '' (validity handled by the caller)."""
    from .strings import from_padded, to_padded

    padded, _lens = to_padded(col)
    n, L = padded.shape
    P = byte_off.shape[1]
    b0 = jnp.take_along_axis(byte_off, jnp.clip(begin_cp, 0, P - 1)[:, None], axis=1)[:, 0]
    b1 = jnp.take_along_axis(byte_off, jnp.clip(end_cp, 0, P - 1)[:, None], axis=1)[:, 0]
    out_lens = jnp.where(valid, jnp.maximum(b1 - b0, 0), 0).astype(jnp.int32)
    j = jnp.arange(L, dtype=jnp.int32)[None, :]
    src = jnp.clip(b0[:, None] + j, 0, L - 1)
    out = jnp.where(j < out_lens[:, None], jnp.take_along_axis(padded, src, axis=1), 0)
    return from_padded(out, out_lens, col.validity)


@op_boundary("strings.extract_re")
def extract_re(col: Column, pattern: str, group: int = 1) -> Column:
    """Spark regexp_extract(col, pattern, group): the capture group's
    text for the LEFTMOST match; '' when the pattern does not match
    (Spark semantics — null only for null input). group=0 = whole match.

    Exact leftmost-greedy (or lazy) spans via the forward-backward
    segment resolution; alternation inside a segment is longest-wins.
    """
    _check_string(col)
    prog = compile_pattern(pattern)
    if group < 0 or group > prog.ngroups:
        raise IndexError(f"group {group} out of range (pattern has {prog.ngroups})")
    cp, cp_lens, byte_off = _codepoints(col)
    n, L = cp.shape
    P = L + 1

    segs = _top_segments(prog)
    if group > 0 and not any(g == group for _, g in segs):
        raise ValueError(f"group {group} is quantified/nested — spans unrecoverable")
    seg_progs = [
        _compile_ast(ast, anchor_end=(prog.anchor_end and i == len(segs) - 1))
        for i, (ast, _) in enumerate(segs)
    ]

    # backward: suffix_ok[i][:, p] = segments i..k-1 can match from p;
    # cache each segment's (first, last) consistent ends for the
    # forward pass (same endmask, so the scans are shared).
    e = jnp.arange(P, dtype=jnp.int32)[None, :]
    in_range = e <= cp_lens[:, None]
    suffix_ok: List = [None] * (len(segs) + 1)
    suffix_ok[len(segs)] = (
        (e == cp_lens[:, None]) if prog.anchor_end else in_range
    )
    ends_by_seg: List = [None] * len(segs)
    for i in range(len(segs) - 1, -1, -1):
        m_i, f_i, l_i = _all_starts(seg_progs[i], cp, cp_lens, endmask=suffix_ok[i + 1])
        suffix_ok[i] = m_i & in_range
        ends_by_seg[i] = (f_i, l_i)

    # leftmost match start = first p where the whole chain can match
    ok = suffix_ok[0]
    if prog.anchor_start:
        ok = ok & (e == 0)
    has = jnp.any(ok, axis=1)
    m_start = jnp.argmax(ok, axis=1).astype(jnp.int32)

    # forward: chain greedy/lazy consistent ends
    pos = m_start
    spans = {}
    for i, (ast, gi) in enumerate(segs):
        while ast[0] == "cat" and len(ast[1]) == 1:  # unwrap 1-item groups
            ast = ast[1][0]
        greedy = not (ast[0] == "rep" and ast[4] is False)
        f_i, l_i = ends_by_seg[i]
        pick = l_i if greedy else f_i
        nxt = jnp.take_along_axis(pick, jnp.clip(pos, 0, P - 1)[:, None], axis=1)[:, 0]
        nxt = jnp.maximum(nxt, pos)  # -1 guard (rows with no match)
        if gi is not None:
            spans[gi] = (pos, nxt)
        pos = nxt

    begin, end_ = (m_start, pos) if group == 0 else spans[group]
    return _substr_by_cp_span(col, byte_off, begin, end_, has)


@op_boundary("strings.split_re")
def split_re(col: Column, pattern: str, limit: int = -1) -> List[Column]:
    """Spark split(str, regex, limit) — Java String.split semantics:
    limit > 0: at most `limit` tokens, last token = unsplit remainder;
    limit = -1 (Spark default): all tokens, trailing empties kept;
    limit = 0: all tokens, trailing empties removed.
    A zero-width separator match at position 0 is skipped (Java 8+).

    Returns a cudf-split-style list of K string columns; row r's token t
    is null for t >= that row's token count.
    """
    _check_string(col)
    prog = compile_pattern(pattern)
    cp, cp_lens, byte_off = _codepoints(col)
    n, L = cp.shape
    P = L + 1
    parr = jnp.arange(P, dtype=jnp.int32)[None, :]

    matched, _, last_end = _all_starts(prog, cp, cp_lens, endmask=None)
    hit = matched & (parr <= cp_lens[:, None])
    if prog.anchor_start:  # '^' matches only the string start
        hit = hit & (parr == 0)
    sep_end = jnp.maximum(last_end, parr)  # greedy end per start

    # next separator-match start at/after q: suffix-min over hit starts
    INF = jnp.int32(P + 1)
    starts = jnp.where(hit, parr, INF)
    nm = lax.associative_scan(jnp.minimum, starts, reverse=True, axis=1)
    nm = jnp.concatenate([nm, jnp.full((n, 1), INF)], axis=1)  # index q may be P

    K = max(min(limit if limit > 0 else L + 1, L + 1), 1)

    def next_match(search):
        ms = jnp.take_along_axis(nm, jnp.clip(search, 0, P)[:, None], axis=1)[:, 0]
        me = jnp.take_along_axis(sep_end, jnp.clip(ms, 0, P - 1)[:, None], axis=1)[:, 0]
        return ms, me

    def body(carry, t):
        pos, search, done = carry
        ms, me = next_match(search)
        # Java 8: a zero-width match at the very beginning is skipped
        skip0 = (ms == 0) & (me <= ms) & (pos == 0)
        ms2, me2 = next_match(jnp.where(skip0, jnp.ones_like(search), search))
        zero_w = me2 <= ms2
        found = (ms2 <= cp_lens) & ~done
        is_last = jnp.asarray(t == K - 1) if limit > 0 else jnp.asarray(False)
        take_rest = (~found) | is_last
        tok_b = pos
        tok_e = jnp.where(take_rest, cp_lens, ms2)
        tok_valid = ~done
        new_pos = jnp.where(take_rest, cp_lens, jnp.where(zero_w, ms2, me2))
        new_search = jnp.where(take_rest, INF, jnp.where(zero_w, ms2 + 1, me2))
        return (new_pos, new_search, done | take_rest), (tok_b, tok_e, tok_valid)

    init = (jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32), jnp.zeros((n,), bool))
    _, (tb, te, tv) = lax.scan(body, init, jnp.arange(K))
    tb, te, tv = tb.T, te.T, tv.T  # [N, K]

    counts = jnp.sum(tv, axis=1).astype(jnp.int32)
    if limit == 0:
        # drop trailing empty tokens; an empty INPUT still yields one
        # empty token (Java "".split(x) == [""])
        nonempty = tv & (te > tb)
        any_ne = jnp.any(nonempty, axis=1)
        last_ne = (K - 1 - jnp.argmax(nonempty[:, ::-1], axis=1)).astype(jnp.int32)
        counts = jnp.where(any_ne, last_ne + 1, jnp.where(cp_lens == 0, 1, 0))
    k_out = max(int(jnp.max(counts)) if n else 1, 1)

    cols: List[Column] = []
    for t in range(k_out):
        valid_t = counts > t
        out = _substr_by_cp_span(col, byte_off, tb[:, t], te[:, t], valid_t)
        v = valid_t if col.validity is None else (valid_t & col.validity)
        cols.append(Column(dt.STRING, validity=v, offsets=out.offsets, chars=out.chars))
    return cols


@op_boundary("strings.replace_re")
def replace_re(col: Column, pattern: str, replacement: bytes) -> Column:
    """Spark regexp_replace(col, pattern, replacement) for patterns that
    cannot match the empty string (zero-width matches change Java's
    splice semantics in ways the split decomposition can't express —
    they raise). Literal replacement only (no backrefs).

    Rides the split machinery: text between separator matches, rejoined
    with the replacement as the glue (concat_ws semantics keep absent
    token slots silent and propagate null inputs correctly).
    """
    prog = compile_pattern(pattern)
    if bool(prog.accept[0]):
        raise ValueError("replace_re: pattern matches the empty string")
    if isinstance(replacement, str):
        replacement = replacement.encode()
    from .strings import concat

    toks = split_re(col, pattern, -1)
    out = concat(toks, separator=replacement, null_policy="skip")
    # concat_ws never yields null; restore the input's nulls
    return Column(dt.STRING, validity=col.validity, offsets=out.offsets, chars=out.chars)

"""Row movement primitives: gather, boolean-mask filter, slice, concat.

The cuDF-tier copying surface (SURVEY §2.8 — `cudf::gather`,
`apply_boolean_mask`, `concatenate`) rebuilt TPU-first: a gather over a
Table is one fused XLA gather per buffer; string columns re-derive
offsets from gathered lengths and gather chars with the searchsorted
row-binning pattern shared with row_conversion.

Static-shape discipline: ops whose output size is data-dependent
(filter) sync the size to host once (the reference's kernels do the same
via a device count + allocation).
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..columnar.dtype import TypeId

__all__ = ["gather", "gather_column", "apply_boolean_mask", "concatenate", "slice_table"]


def _all_null_column(d, n_out: int) -> Column:
    from ..columnar.dtype import TypeId as _T

    valid = jnp.zeros((n_out,), bool)
    if d.id == _T.STRING:
        return Column(
            d,
            validity=valid,
            offsets=jnp.zeros((n_out + 1,), jnp.int32),
            chars=jnp.zeros((0,), jnp.uint8),
        )
    if d.id == _T.LIST:
        return Column(
            d,
            validity=valid,
            offsets=jnp.zeros((n_out + 1,), jnp.int32),
            child=Column(dt.INT8, data=jnp.zeros((0,), jnp.int8)),
        )
    if d.id == _T.DECIMAL128:
        return Column(d, data=jnp.zeros((n_out, 4), jnp.uint32), validity=valid)
    return Column(d, data=jnp.zeros((n_out,), d.jnp_dtype), validity=valid)


def gather_column(col: Column, idx: jnp.ndarray, check_bounds: bool = False) -> Column:
    """New column with rows col[idx[i]]. Out-of-range -> null when
    check_bounds, matching cudf's bounds-policy NULLIFY."""
    n_out = idx.shape[0]
    n_in = len(col)
    idx = idx.astype(jnp.int32)
    if n_in == 0:
        # gathering from an empty source (e.g. the null-extended side of
        # an outer join against an empty table): every row is OOB-null
        if not check_bounds and n_out > 0:
            raise IndexError("gather from empty column without check_bounds")
        return _all_null_column(col.dtype, n_out)
    oob = (idx < 0) | (idx >= n_in)
    safe = jnp.clip(idx, 0, max(n_in - 1, 0))

    valid = None
    if col.validity is not None:
        valid = col.validity[safe]
    if check_bounds:
        v = jnp.ones((n_out,), bool) if valid is None else valid
        valid = v & ~oob

    if col.dtype.id == TypeId.STRING:
        offs = col.offsets
        lens = (offs[1:] - offs[:-1])[safe]
        new_offs = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)]
        )
        total = int(new_offs[-1])  # host sync: chars allocation
        if total == 0:
            chars = jnp.zeros((0,), jnp.uint8)
        else:
            j = jnp.arange(total, dtype=jnp.int32)
            row_of = jnp.searchsorted(new_offs, j, side="right").astype(jnp.int32) - 1
            src = offs[safe[row_of]] + (j - new_offs[row_of])
            chars = col.chars[src]
        return Column(col.dtype, validity=valid, offsets=new_offs, chars=chars)
    if col.dtype.id == TypeId.LIST:
        offs = col.offsets
        lens = (offs[1:] - offs[:-1])[safe]
        new_offs = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)]
        )
        total = int(new_offs[-1])
        j = jnp.arange(total, dtype=jnp.int32)
        row_of = jnp.searchsorted(new_offs, j, side="right").astype(jnp.int32) - 1
        src = offs[safe[row_of]] + (j - new_offs[row_of])
        child = gather_column(col.child, src)
        return Column(col.dtype, validity=valid, offsets=new_offs, child=child)
    return Column(col.dtype, data=col.data[safe], validity=valid)


def gather(table: Table, idx: jnp.ndarray, check_bounds: bool = False) -> Table:
    return Table([gather_column(c, idx, check_bounds) for c in table.columns], table.names)


def apply_boolean_mask(table: Table, mask) -> Table:
    """Keep rows where mask is True (and non-null): cudf apply_boolean_mask."""
    if isinstance(mask, Column):
        m = mask.data.astype(bool)
        if mask.validity is not None:
            m = m & mask.validity
    else:
        m = jnp.asarray(mask, bool)
    idx = jnp.nonzero(m)[0].astype(jnp.int32)  # host sync on size
    return gather(table, idx)


def slice_table(table: Table, start: int, end: int) -> Table:
    n = table.num_rows
    idx = jnp.arange(max(0, min(start, n)), max(0, min(end, n)), dtype=jnp.int32)
    return gather(table, idx)


def concatenate(tables: Sequence[Table]) -> Table:
    """Row-wise concat of same-schema tables (cudf::concatenate)."""
    tables = [t for t in tables if t.num_rows > 0] or list(tables[:1])
    first = tables[0]
    out: List[Column] = []
    for ci in range(first.num_columns):
        cols = [t.columns[ci] for t in tables]
        d = cols[0].dtype
        has_valid = any(c.validity is not None for c in cols)
        valid = (
            jnp.concatenate([c.valid_mask() for c in cols]) if has_valid else None
        )
        if d.id == TypeId.STRING:
            lens = jnp.concatenate([c.offsets[1:] - c.offsets[:-1] for c in cols])
            offs = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)]
            )
            chars = jnp.concatenate([c.chars for c in cols])
            out.append(Column(d, validity=valid, offsets=offs, chars=chars))
        else:
            out.append(Column(d, data=jnp.concatenate([c.data for c in cols]), validity=valid))
    return Table(out, first.names)

"""DeltaLake-compatible Z-order bit interleaving.

Behavioral parity with reference src/main/cpp/src/zorder.cu
interleave_bits (:32-115): all columns must share one fixed-width type;
the output is a LIST<UINT8> column whose rows are num_cols *
type_size bytes; the most significant output bit takes the most
significant bit of column 0, then column 1, ... cycling; null values
read as zero (:97); total output must stay under the 2GiB size_type
limit (:52-55).

TPU-first design: the (output byte, output bit) -> (column, value bit)
mapping is a pure function of (num_columns, type_size) — so it is
precomputed host-side as two small integer tables and the whole kernel
becomes one gather + shift + masked dot with the bit weights, fully
vectorized over rows (replacing the thread-per-output-byte loop,
zorder.cu:66-101).
"""

from __future__ import annotations

from functools import lru_cache
from typing import List, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..columnar.dtype import TypeId
from ..utils.dispatch import op_boundary

__all__ = ["interleave_bits"]

_MAX_OUTPUT = (1 << 31) - 1


@lru_cache(maxsize=None)
def _bit_maps(num_columns: int, size: int) -> Tuple[np.ndarray, np.ndarray]:
    """(col_of, bit_of): for output byte i (within a row) and bit offset o,
    which column and which value-bit (0 = LSB) feed it. Direct transcription
    of the index arithmetic in zorder.cu:74-99."""
    row_bytes = num_columns * size
    col_of = np.zeros((row_bytes, 8), dtype=np.int32)
    bit_of = np.zeros((row_bytes, 8), dtype=np.int32)
    for ret_idx in range(row_bytes):
        group = (ret_idx // num_columns) * num_columns
        flipped = group + (num_columns - 1 - (ret_idx - group))
        for o in range(8):
            obit = flipped * 8 + o
            col = num_columns - 1 - (obit % num_columns)
            b = obit // num_columns  # bit index within the flipped column bytes
            byte_sig = size - 1 - (b // 8)  # big-endian flip
            col_of[ret_idx, o] = col
            bit_of[ret_idx, o] = byte_sig * 8 + (b % 8)
    return col_of, bit_of


def _column_as_bit_limbs(col: Column) -> jnp.ndarray:
    """[N, L] uint32 little-endian limbs of the value bits; nulls zeroed."""
    d = col.dtype
    if d.id == TypeId.DECIMAL128:
        limbs = col.data
    elif d.size_bytes <= 4:
        u = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}[d.size_bytes]
        limbs = lax.bitcast_convert_type(col.data, u).astype(jnp.uint32)[:, None]
    else:  # 8 bytes
        u64 = lax.bitcast_convert_type(col.data, jnp.uint64)
        limbs = jnp.stack(
            [(u64 & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32),
             (u64 >> jnp.uint64(32)).astype(jnp.uint32)],
            axis=1,
        )
    if col.validity is not None:
        limbs = jnp.where(col.validity[:, None], limbs, 0)
    return limbs


@op_boundary("interleave_bits")
def interleave_bits(num_rows: int, *columns: Column) -> Column:
    """Parity: ZOrder.interleaveBits (ZOrder.java:41) ->
    spark_rapids_jni::interleave_bits (zorder.cu:32).

    The zero-column case returns ``num_rows`` empty lists, matching the
    Java-side corner handling (ZOrder.java:42-47).
    """
    if not columns:
        offsets = jnp.zeros((num_rows + 1,), jnp.int32)
        return Column(dt.LIST, offsets=offsets,
                      child=Column(dt.UINT8, data=jnp.zeros((0,), jnp.uint8)))

    d0 = columns[0].dtype
    if not d0.is_fixed_width:
        raise ValueError("Only fixed width columns can be used")
    if any(c.dtype.id != d0.id for c in columns):
        raise ValueError("All columns of the input table must be the same type.")
    n = len(columns[0])
    size = d0.size_bytes
    num_columns = len(columns)
    total = n * size * num_columns
    if total > _MAX_OUTPUT:
        raise ValueError("Input is too large to process")

    col_of, bit_of = _bit_maps(num_columns, size)
    limbs = jnp.stack([_column_as_bit_limbs(c) for c in columns], axis=1)  # [N, C, L]

    limb_idx = jnp.asarray(bit_of // 32)  # [row_bytes, 8]
    shift = jnp.asarray((bit_of % 32).astype(np.uint32))
    col_idx = jnp.asarray(col_of)

    # gather [N, row_bytes, 8] source limbs, extract bits, dot with weights
    src = limbs[:, col_idx, limb_idx]
    bits = (src >> shift[None, :, :]) & jnp.uint32(1)
    weights = (jnp.uint32(1) << jnp.arange(8, dtype=jnp.uint32))[None, None, :]
    out_bytes = jnp.sum(bits * weights, axis=2, dtype=jnp.uint32).astype(jnp.uint8)

    offsets = (jnp.arange(n + 1, dtype=jnp.int32)) * (size * num_columns)
    return Column(
        dt.LIST,
        offsets=offsets,
        child=Column(dt.UINT8, data=out_bytes.reshape(-1)),
    )


def interleave_bits_table(table: Table) -> Column:
    return interleave_bits(table.num_rows, *table.columns)

"""Multi-key stable sort (cudf::sorted_order / sort_by_key tier).

TPU-first: every fixed-width key is mapped through
``bitutils.total_order_key`` to an unsigned integer whose order matches
the value order EXACTLY (floats via the IEEE total-order transform — so
FLOAT64 sorts are exact on TPU even though f64 arithmetic is
approximated). Null ordering is folded in by splitting the null flag
into a leading key. The composite sort is ``jnp.lexsort``, which XLA
lowers to its sort HLO on TPU.

String keys are supported via a padded-prefix key (first 16 bytes packed
into two u64 lanes) plus a tie-break pass — exact for strings whose
order is decided in the first 16 bytes; longer ties fall back to a host
comparison (documented limitation, rare in Spark sort keys).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..columnar.dtype import TypeId
from ..utils.dispatch import op_boundary
from . import bitutils
from .copying import gather

__all__ = ["sorted_order", "sort_by_key"]


def _string_prefix_keys(col: Column) -> List[jnp.ndarray]:
    """Two big-endian u64 lanes of the first 16 chars (shorter pads \\0)."""
    offs = col.offsets
    lens = offs[1:] - offs[:-1]
    n = len(col)
    idx = offs[:-1, None] + jnp.arange(16, dtype=jnp.int32)[None, :]
    inb = jnp.arange(16, dtype=jnp.int32)[None, :] < lens[:, None]
    nchars = max(int(col.chars.shape[0]), 1)
    chars = jnp.where(inb, col.chars[jnp.clip(idx, 0, nchars - 1)], 0)  # [N, 16]
    keys = []
    for half in range(2):
        block = chars[:, half * 8 : half * 8 + 8].astype(jnp.uint64)
        k = jnp.zeros((n,), jnp.uint64)
        for b in range(8):
            k = (k << jnp.uint64(8)) | block[:, b]
        keys.append(k)
    return keys


def _column_keys(col: Column, ascending: bool, nulls_first: bool) -> List[jnp.ndarray]:
    """Minor-to-major NOT applied here; returns [null_key, k2?, k1] style
    major-first list of u-int key lanes for one column."""
    if col.dtype.id == TypeId.STRING:
        lanes = _string_prefix_keys(col)
    elif col.dtype.id == TypeId.DECIMAL128:
        # flip sign bit of the top limb; compare limbs high->low
        top = col.data[:, 3] ^ jnp.uint32(1 << 31)
        lanes = [
            (top.astype(jnp.uint64) << jnp.uint64(32)) | col.data[:, 2].astype(jnp.uint64),
            (col.data[:, 1].astype(jnp.uint64) << jnp.uint64(32))
            | col.data[:, 0].astype(jnp.uint64),
        ]
    else:
        lanes = [bitutils.total_order_key(col.data, col.dtype)]
    if not ascending:
        lanes = [~k if k.dtype in (jnp.uint64, jnp.uint32) else jnp.invert(k) for k in lanes]
    null_rank = (
        col.valid_mask().astype(jnp.uint8)
        if nulls_first
        else (~col.valid_mask()).astype(jnp.uint8)
    )
    return [null_rank] + lanes


def sorted_order(
    table: Table,
    ascending: Optional[Sequence[bool]] = None,
    nulls_first: Optional[Sequence[bool]] = None,
) -> jnp.ndarray:
    """Stable gather indices ordering the table by its columns (leftmost
    key is most significant), parity with cudf::sorted_order semantics."""
    ncols = table.num_columns
    asc = list(ascending) if ascending is not None else [True] * ncols
    nf = list(nulls_first) if nulls_first is not None else [True] * ncols
    lanes: List[jnp.ndarray] = []
    for col, a, f in zip(table.columns, asc, nf):
        lanes.extend(_column_keys(col, a, f))
    # lexsort: LAST key is primary -> reverse to make column 0 dominate
    return jnp.lexsort(tuple(reversed(lanes))).astype(jnp.int32)


@op_boundary("sort_by_key")
def sort_by_key(values: Table, keys: Table, ascending=None, nulls_first=None) -> Table:
    order = sorted_order(keys, ascending, nulls_first)
    return gather(values, order)

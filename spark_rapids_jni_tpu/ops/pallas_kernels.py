"""Pallas TPU kernels for hot ops.

First resident: the shuffle partitioner — murmur3(key) pmod P fused in
one VMEM pass. XLA already fuses the jnp formulation well; the Pallas
version exists to (a) pin the fused single-pass HBM->VMEM->HBM shape so
no pipeline rematerializes the hash, and (b) carry the kernel
infrastructure (tiling, padding, interpret-mode testing) that later
byte-movement kernels build on.

Bit-exact with ops/hashing.murmur3_raw / hash_partition_map for int32
and int64 keys (tests cross-check in interpret mode on CPU).

Layout: [N] keys are split host-side into u32 lane planes and padded to
(8, 128)-aligned 2-D tiles (the VPU shape); the kernel is gridded over
row blocks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu import fails on builds without the TPU plugin; interpret mode still works
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover  # srjt-lint: allow-broad-except(optional TPU-plugin import guard; interpret mode works without pltpu)
    pltpu = None
    _VMEM = None

__all__ = [
    "pallas_partition_map",
    "pallas_groupby_sum_bounded",
    "pallas_groupby_sum_outer",
    "pallas_available",
]

_LANES = 128
_BLOCK_ROWS = 512  # 512x128 u32 block = 256KB/input plane in VMEM


def pallas_available() -> bool:
    return _VMEM is not None


def _mix_k(k):
    k = k * jnp.uint32(0xCC9E2D51)
    k = (k << jnp.uint32(15)) | (k >> jnp.uint32(17))
    return k * jnp.uint32(0x1B873593)


def _mix_h(h, k):
    h = h ^ _mix_k(k)
    h = (h << jnp.uint32(13)) | (h >> jnp.uint32(19))
    return h * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h):
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> jnp.uint32(16))


def _partition_kernel_2word(lo_ref, hi_ref, out_ref, *, num_partitions: int):
    h = jnp.full(lo_ref.shape, 42, jnp.uint32)
    h = _mix_h(h, lo_ref[:])
    h = _mix_h(h, hi_ref[:])
    h = _fmix(h ^ jnp.uint32(8))
    signed = h.astype(jnp.int32)
    m = signed % jnp.int32(num_partitions)
    out_ref[:] = jnp.where(m < 0, m + num_partitions, m)


def _partition_kernel_1word(w_ref, out_ref, *, num_partitions: int):
    h = jnp.full(w_ref.shape, 42, jnp.uint32)
    h = _mix_h(h, w_ref[:])
    h = _fmix(h ^ jnp.uint32(4))
    signed = h.astype(jnp.int32)
    m = signed % jnp.int32(num_partitions)
    out_ref[:] = jnp.where(m < 0, m + num_partitions, m)


def _pad_to_tiles(plane: jnp.ndarray) -> jnp.ndarray:
    """[N] u32 -> [rows, 128] u32 with rows a multiple of _BLOCK_ROWS."""
    n = plane.shape[0]
    rows = max((n + _LANES - 1) // _LANES, 1)
    rows = (rows + _BLOCK_ROWS - 1) // _BLOCK_ROWS * _BLOCK_ROWS
    padded = jnp.zeros((rows * _LANES,), jnp.uint32).at[:n].set(plane)
    return padded.reshape(rows, _LANES)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _run(planes, num_partitions: int, interpret: bool):
    two = len(planes) == 2
    rows = planes[0].shape[0]
    grid = (rows // _BLOCK_ROWS,)
    # index map returns must be uniformly i32: with jax_enable_x64 the
    # bare literal 0 traces as i64 and Mosaic fails to legalize the
    # mixed-width return
    spec = pl.BlockSpec(
        (_BLOCK_ROWS, _LANES),
        lambda i: (i, jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )
    kernel = (
        functools.partial(_partition_kernel_2word, num_partitions=num_partitions)
        if two
        else functools.partial(_partition_kernel_1word, num_partitions=num_partitions)
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
        grid=grid,
        in_specs=[spec] * len(planes),
        out_specs=spec,
        interpret=interpret,
    )(*planes)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _partition_map_impl(keys, num_partitions: int, interpret: bool):
    from jax import lax

    n = keys.shape[0]
    if keys.dtype.itemsize == 8:
        u = lax.bitcast_convert_type(keys, jnp.uint32)  # [N, 2]
        planes = (_pad_to_tiles(u[:, 0]), _pad_to_tiles(u[:, 1]))
    else:
        signed = keys.astype(jnp.int32)
        planes = (_pad_to_tiles(lax.bitcast_convert_type(signed, jnp.uint32)),)
    out = _run(planes, num_partitions, interpret)
    return out.reshape(-1)[:n]


def pallas_partition_map(
    keys: jnp.ndarray, num_partitions: int, interpret: bool = False
) -> jnp.ndarray:
    """[N] int32/int64 keys -> [N] int32 partition ids, bit-exact with
    hash_partition_map on a single int column.

    interpret=True runs the kernel in the Pallas interpreter (hermetic
    CPU testing); on TPU leave it False for the compiled kernel. The
    whole path (lane split, tile pad, kernel, unpad) is one compiled
    program — eager prep dispatches would dominate on remote backends.
    """
    if keys.dtype.itemsize not in (4, 8):
        raise ValueError(f"pallas_partition_map supports 4/8-byte keys, got {keys.dtype}")
    return _partition_map_impl(keys, int(num_partitions), bool(interpret))


# ---------------------------------------------------------------------------
# bounded-domain GROUP BY SUM on the MXU
# ---------------------------------------------------------------------------
#
# TPUs have no fast scatter: jax.ops.segment_sum over 1M rows costs ~7ms
# (XLA serializes the scatter-add), and an XLA one-hot matmul pays K*N*4
# bytes of HBM traffic just materializing the one-hot. This kernel builds
# each one-hot tile IN VMEM and contracts it on the MXU immediately —
# the one-hot never touches HBM.
#
# Measured (v5e, 1M rows x 4096 keys): ~matches the scatter path
# (~150 Mrows/s) rather than beating it — the [1, 256] x [256, K]
# contraction is a matvec (M=1), which uses 1/128 of the MXU, and
# Precision.HIGHEST (needed for f32-exact sums) triples the passes.
# Next step when this op matters: batch 128 row-chunks into one
# [128, 256] x [256, K] block-diagonal contraction per grid step, or
# specialize K <= 128 where a full-width matmul applies.

_GB_CHUNK = 256  # columns of each (8, 256) row block; one-hot tile [256, K]
_GB_SUBLANES = 8  # TPU block sublane quantum


def _groupby_kernel(k_ref, v_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    kpad = out_ref.shape[1]
    cols = jax.lax.broadcasted_iota(jnp.int32, (_GB_CHUNK, kpad), 1)
    # static unroll over the 8 sublanes: each [256, Kpad] one-hot tile
    # lives only in VMEM/registers; rows with out-of-domain keys (incl.
    # the padding sentinel) match no column and vanish
    for s in range(_GB_SUBLANES):
        oh = (k_ref[s, :].reshape(-1, 1) == cols).astype(jnp.float32)
        # HIGHEST: the MXU's default single-pass bf16 loses ~3 decimal
        # digits; the 3-pass f32 emulation keeps sums exact to f32 ulp
        dot = jax.lax.dot_general(
            v_ref[s, :].reshape(1, -1),
            oh,
            (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        # accumulate straight into the revisited output block: no
        # scratch buffer, so interpret mode needs no TPU plugin
        out_ref[s : s + 1, :] += dot


@functools.partial(jax.jit, static_argnums=(2, 3))
def _groupby_impl(keys, vals, num_keys: int, interpret: bool):
    n = keys.shape[0]
    kpad = max((num_keys + _LANES - 1) // _LANES * _LANES, _LANES)
    step_rows = _GB_SUBLANES * _GB_CHUNK
    m = max((n + step_rows - 1) // step_rows, 1)  # grid=(0,) never runs
    total = m * step_rows
    # domain check BEFORE any narrowing cast: int64 keys >= 2^32 must
    # drop, not wrap into the valid domain
    in_domain = (keys >= 0) & (keys < num_keys)
    keys32 = jnp.where(in_domain, keys, -1).astype(jnp.int32)
    # pad with an out-of-domain sentinel so padding rows sum nowhere
    kp = jnp.full((total,), -1, jnp.int32).at[:n].set(keys32)
    vp = jnp.zeros((total,), jnp.float32).at[:n].set(vals.astype(jnp.float32))
    kp = kp.reshape(m * _GB_SUBLANES, _GB_CHUNK)
    vp = vp.reshape(m * _GB_SUBLANES, _GB_CHUNK)

    row_spec = pl.BlockSpec(
        (_GB_SUBLANES, _GB_CHUNK),
        lambda i: (i, jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )
    out_spec = pl.BlockSpec(
        (_GB_SUBLANES, kpad),
        lambda i: (jnp.int32(0), jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )
    out = pl.pallas_call(
        _groupby_kernel,
        out_shape=jax.ShapeDtypeStruct((_GB_SUBLANES, kpad), jnp.float32),
        grid=(m,),
        in_specs=[row_spec, row_spec],
        out_specs=out_spec,
        interpret=interpret,
    )(kp, vp)
    # 8 sublane partial accumulators -> final sums
    return jnp.sum(out, axis=0)[:num_keys]


# ---------------------------------------------------------------------------
# outer-product GROUP BY SUM: full-width MXU formulation
# ---------------------------------------------------------------------------
#
# The kernel above is a matvec (M=1) and wastes 127/128 of the MXU.
# This one restores the M dimension with the histogram outer-product
# decomposition: write key = hi*128 + lo, then
#
#   sums[hi, lo]   = sum_i vals[i] * OH_hi[i, hi] * OH_lo[i, lo]
#   counts[hi, lo] = sum_i           OH_hi[i, hi] * OH_lo[i, lo]
#
# i.e. ONE [4H, NT] x [NT, 128] matmul per row block:
#   lhs = [A1 | A2 | A3 | C] with A_k = v_k-weighted hi-one-hot and C
#   the unweighted hi-one-hot, rhs = lo-one-hot. v is split into three
#   bf16 limbs (v = v1+v2+v3 captures all 24 f32 mantissa bits), and
#   the rhs one-hot is exactly representable in bf16, so each MXU
#   product is exact and the f32 accumulator gives segment_sum-class
#   accuracy — at single-pass bf16 speed, with H=32 (num_keys=4096)
#   filling the MXU's M dimension (4H=128).
#
# Both one-hots live only in VMEM; HBM traffic is just keys+vals.

_OUTER_NT_MAX = 8192  # rows contracted per grid step (the dot's K dim).
# The transposed build keeps one [4H, NT] lhs, one [128, NT] rhs and one
# [H, NT] cmp tile live — (5H + 128) * 2 bytes per row — so NT scales
# down as the key domain grows. v5e-measured (1M rows, chained): K=4096
# NT 2048/4096/8192 -> 4.1/5.2/6.7 Grows/s; K=16384 NT=8192 -> 1.75
# Grows/s; K=65536 NT=2048 -> 0.38 Grows/s (scatter: 0.15).
_OUTER_VMEM_BUDGET = 13_000_000  # bytes of live kernel tiles that fit


def _outer_nt(H: int) -> int:
    per_row = (5 * H + _LANES) * 2
    nt = _OUTER_VMEM_BUDGET // per_row
    p = 512
    while p * 2 <= min(nt, _OUTER_NT_MAX):
        p *= 2
    return p


def _outer_kernel(k_ref, v_ref, out_ref, *, H: int):
    """One full-width MXU contraction per grid step, everything built in
    the keys' NATIVE row orientation.

    The round-2 kernel spent its time on layout, not math: each sublane
    step paid a [NT] -> [NT, 1] lane->sublane relayout to build one-hots
    and a lane-axis concatenate to assemble the lhs, then issued a small
    dot. Here keys arrive as a [1, NT] row; both one-hots broadcast that
    row across SUBLANES (free) against a dim-0 iota, the limb concat
    stacks along sublanes (tile-aligned), and the dot contracts both
    operands on their last dim — lhsT [4H, NT] x rhsT [128, NT] ->
    [4H, 128] — which the MXU consumes directly.
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    k = k_ref[0]  # [1, NT] i32 (pre-mapped to [0, H*128) + trash H*128)
    v = v_ref[0]  # [1, NT] f32
    nt = k.shape[1]

    hi = k >> 7
    lo = k & 127
    iota_h = jax.lax.broadcasted_iota(jnp.int32, (H, nt), 0)
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (_LANES, nt), 0)
    # single bool->bf16 consumer, then multiplies: Mosaic rejects the
    # multi-consumer broadcast i1 relayout a where-chain needs, and
    # one-hot products are exact either way (factors are 0/1)
    cmp = (jnp.broadcast_to(hi, (H, nt)) == iota_h).astype(jnp.bfloat16)  # [H, NT]
    rhsT = (jnp.broadcast_to(lo, (_LANES, nt)) == iota_l).astype(jnp.bfloat16)  # [128, NT]

    # v = v1 + v2 + v3 in bf16 limbs captures all 24 f32 mantissa bits;
    # each limb and each one-hot entry is exactly representable in bf16,
    # so every MXU product is exact and the f32 accumulator gives
    # segment_sum-class accuracy at single-pass bf16 speed.
    v1 = v.astype(jnp.bfloat16)
    r1 = v - v1.astype(jnp.float32)
    v2 = r1.astype(jnp.bfloat16)
    v3 = (r1 - v2.astype(jnp.float32)).astype(jnp.bfloat16)
    lhsT = jnp.concatenate(
        [cmp * v1, cmp * v2, cmp * v3, cmp],
        axis=0,
    )  # [4H, NT] — sublane-axis concat: tile stacking, no relayout
    out_ref[...] += jax.lax.dot_general(
        lhsT, rhsT, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [4H, 128]


@functools.partial(jax.jit, static_argnums=(2, 3))
def _outer_impl(keys, vals, num_keys: int, interpret: bool):
    n = keys.shape[0]
    H = max((num_keys + _LANES - 1) // _LANES, 1)  # ceil(num_keys/128)
    # out-of-domain/padding rows map to hi == H: outside the hi-one-hot,
    # so they match no column and vanish (no in-matrix trash slot, which
    # would force a 128-misaligned H)
    trash = H * _LANES
    in_domain = (keys >= 0) & (keys < num_keys)
    seg = jnp.where(in_domain, keys, trash).astype(jnp.int32)

    nt = _outer_nt(H)
    g = max((n + nt - 1) // nt, 1)
    total = g * nt
    # [g, 1, NT]: blocks index the leading dim; the trailing (1, NT)
    # equals the array's own trailing dims (the tiling rule Mosaic
    # requires for non-(8,128)-divisible blocks)
    kp = jnp.full((total,), trash, jnp.int32).at[:n].set(seg).reshape(g, 1, nt)
    vp = (
        jnp.zeros((total,), jnp.float32)
        .at[:n]
        .set(vals.astype(jnp.float32))
        .reshape(g, 1, nt)
    )

    row_spec = pl.BlockSpec(
        (1, 1, nt),
        lambda i: (i, jnp.int32(0), jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )
    out_spec = pl.BlockSpec(
        (4 * H, _LANES),
        lambda i: (jnp.int32(0), jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )
    out = pl.pallas_call(
        functools.partial(_outer_kernel, H=H),
        out_shape=jax.ShapeDtypeStruct((4 * H, _LANES), jnp.float32),
        grid=(g,),
        in_specs=[row_spec, row_spec],
        out_specs=out_spec,
        interpret=interpret,
    )(kp, vp)
    sums = (out[:H] + out[H : 2 * H] + out[2 * H : 3 * H]).reshape(H * _LANES)[:num_keys]
    counts = out[3 * H :].reshape(H * _LANES)[:num_keys].astype(jnp.int64)
    return sums, counts


def pallas_groupby_sum_outer(
    keys: jnp.ndarray, vals: jnp.ndarray, num_keys: int, interpret: bool = False
):
    """GROUP BY SUM + COUNT over a bounded key domain [0, num_keys) as a
    full-width MXU outer-product contraction. float32 sums, exact
    int64-safe counts (f32 accumulator: exact below 2^24 rows/key).

    Returns (sums[num_keys] f32, counts[num_keys] i64); out-of-domain
    keys are dropped. num_keys <= 65536: the contraction length NT
    scales down as H grows (see _outer_nt) and past H=512 the one-hot
    work amplification (2*4H*128 FLOPs/row) loses to the scatter path.
    """
    if num_keys > 65536:
        raise ValueError("pallas_groupby_sum_outer supports num_keys <= 65536")
    return _outer_impl(keys, vals, int(num_keys), bool(interpret))


def pallas_groupby_sum_bounded(
    keys: jnp.ndarray, vals: jnp.ndarray, num_keys: int, interpret: bool = False
) -> jnp.ndarray:
    """GROUP BY SUM over a bounded key domain [0, num_keys), one-hot
    matmul on the MXU with VMEM-resident tiles. float32 sums.

    Matches ops.aggregate.groupby_sum_bounded's sums (float path) for
    in-domain keys; out-of-domain keys are dropped.
    """
    if num_keys > 4096:
        raise ValueError("pallas_groupby_sum_bounded supports num_keys <= 4096 (VMEM tile)")
    return _groupby_impl(keys, vals, int(num_keys), bool(interpret))

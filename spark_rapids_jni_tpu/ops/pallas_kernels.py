"""Pallas TPU kernel tier for hot ops (ISSUE 13).

Residents:

- the shuffle partitioner — murmur3(key) pmod P fused in one VMEM pass,
- the bounded-domain GROUP BY SUM MXU kernels (one-hot / outer-product),
- the PAGED HASH JOIN build/probe pair (``build_paged_table`` /
  ``pallas_probe_paged``): the Ragged-Paged-Attention page discipline
  (arxiv 2604.15464) applied to equi-joins — build partitions keys into
  fixed 128-slot pages with contiguous overflow chaining, probe streams
  the probe side through the VMEM-resident page table in one fused pass
  emitting per-row match ranges,
- the FUSED RAGGED DECODE kernel (``pallas_ragged_compact``): the
  Mosaic escalation NOTES_ROUND5 named for the 1M x 155 decode axis —
  offset walk (owner resolution), windowed byte gather, boundary
  masking, and head merge in ONE pass over a scalar-prefetched pool
  window, replacing the XLA formulation's three N-row scatter passes
  and per-column HBM intermediates.

Every kernel keeps an interpret-mode path (``interpret=True``) so the
hermetic CPU test tier exercises the same kernel bodies, and every
caller dispatches through ``kernel_tier_mode`` with the XLA formulation
as automatic fallback — a kernel-tier failure must degrade, never
error (see utils/dispatch.note_tier for the tier observability).

Bit-exactness: the partitioner matches ops/hashing.murmur3_raw, the
join pair matches ops/join.join_gather_maps, the decode kernel matches
ops/ragged_bytes.ragged_compact (tests cross-check all three in
interpret mode on CPU).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from ..utils import knobs

try:  # pltpu import fails on builds without the TPU plugin; interpret mode still works
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover  # srjt-lint: allow-broad-except(optional TPU-plugin import guard; interpret mode works without pltpu)
    pltpu = None
    _VMEM = None

__all__ = [
    "pallas_partition_map",
    "pallas_groupby_sum_bounded",
    "pallas_groupby_sum_outer",
    "pallas_available",
    "on_tpu",
    "kernel_tier_mode",
    "PagedHashTable",
    "build_paged_table",
    "pallas_probe_paged",
    "pallas_decode_probe",
    "pallas_ragged_compact",
]

_LANES = 128
_BLOCK_ROWS = 512  # 512x128 u32 block = 256KB/input plane in VMEM


def _pow2_ceil(v: int) -> int:
    p = 1
    while p < v:
        p *= 2
    return p


# Memoized availability/backend probes (the memory.device_memory_budget
# pattern): both sit on the per-dispatch hot path of every tiered op,
# and ``jax.default_backend()`` re-walks the backend registry on every
# call. The backend cannot change within a process, so one probe each
# is sound; ``_reset_probe_cache`` is the test hook.
_AVAILABLE: "bool | None" = None
_ON_TPU: "bool | None" = None


def pallas_available() -> bool:
    """True when the Pallas TPU plugin surface imported (memoized)."""
    global _AVAILABLE
    if _AVAILABLE is None:
        _AVAILABLE = _VMEM is not None
    return _AVAILABLE


def on_tpu() -> bool:
    """True when the default jax backend is a real TPU (memoized)."""
    global _ON_TPU
    if _ON_TPU is None:
        _ON_TPU = jax.default_backend() == "tpu"
    return _ON_TPU


def _reset_probe_cache() -> None:
    global _AVAILABLE, _ON_TPU
    _AVAILABLE = None
    _ON_TPU = None


def kernel_tier_mode(knob_name: str) -> str:
    """Per-op kernel-tier dispatch decision, shared by every tiered op.

    Returns ``"tpu"`` (compiled kernels), ``"interpret"`` (forced
    through the Pallas interpreter off-TPU — the hermetic CI posture,
    ``SRJT_PALLAS_INTERPRET=1``), or ``""`` (XLA formulation). The
    per-op knob (``SRJT_PALLAS_JOIN`` / ``SRJT_PALLAS_DECODE``) is read
    LIVE (the knob-registry test/operator contract); the backend probes
    are memoized."""
    if not knobs.get_bool(knob_name):
        return ""
    if not pallas_available():
        return ""
    if on_tpu():
        return "tpu"
    if knobs.get_bool("SRJT_PALLAS_INTERPRET"):
        return "interpret"
    return ""


def _mix_k(k):
    k = k * jnp.uint32(0xCC9E2D51)
    k = (k << jnp.uint32(15)) | (k >> jnp.uint32(17))
    return k * jnp.uint32(0x1B873593)


def _mix_h(h, k):
    h = h ^ _mix_k(k)
    h = (h << jnp.uint32(13)) | (h >> jnp.uint32(19))
    return h * jnp.uint32(5) + jnp.uint32(0xE6546B64)


def _fmix(h):
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> jnp.uint32(16))


def _partition_kernel_2word(lo_ref, hi_ref, out_ref, *, num_partitions: int):
    h = jnp.full(lo_ref.shape, 42, jnp.uint32)
    h = _mix_h(h, lo_ref[:])
    h = _mix_h(h, hi_ref[:])
    h = _fmix(h ^ jnp.uint32(8))
    signed = h.astype(jnp.int32)
    m = signed % jnp.int32(num_partitions)
    out_ref[:] = jnp.where(m < 0, m + num_partitions, m)


def _partition_kernel_1word(w_ref, out_ref, *, num_partitions: int):
    h = jnp.full(w_ref.shape, 42, jnp.uint32)
    h = _mix_h(h, w_ref[:])
    h = _fmix(h ^ jnp.uint32(4))
    signed = h.astype(jnp.int32)
    m = signed % jnp.int32(num_partitions)
    out_ref[:] = jnp.where(m < 0, m + num_partitions, m)


def _pad_to_tiles(plane: jnp.ndarray) -> jnp.ndarray:
    """[N] u32 -> [rows, 128] u32 with rows a multiple of _BLOCK_ROWS."""
    n = plane.shape[0]
    rows = max((n + _LANES - 1) // _LANES, 1)
    rows = (rows + _BLOCK_ROWS - 1) // _BLOCK_ROWS * _BLOCK_ROWS
    padded = jnp.zeros((rows * _LANES,), jnp.uint32).at[:n].set(plane)
    return padded.reshape(rows, _LANES)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _run(planes, num_partitions: int, interpret: bool):
    two = len(planes) == 2
    rows = planes[0].shape[0]
    grid = (rows // _BLOCK_ROWS,)
    # index map returns must be uniformly i32: with jax_enable_x64 the
    # bare literal 0 traces as i64 and Mosaic fails to legalize the
    # mixed-width return
    spec = pl.BlockSpec(
        (_BLOCK_ROWS, _LANES),
        lambda i: (i, jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )
    kernel = (
        functools.partial(_partition_kernel_2word, num_partitions=num_partitions)
        if two
        else functools.partial(_partition_kernel_1word, num_partitions=num_partitions)
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
        grid=grid,
        in_specs=[spec] * len(planes),
        out_specs=spec,
        interpret=interpret,
    )(*planes)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _partition_map_impl(keys, num_partitions: int, interpret: bool):
    from jax import lax

    n = keys.shape[0]
    if keys.dtype.itemsize == 8:
        u = lax.bitcast_convert_type(keys, jnp.uint32)  # [N, 2]
        planes = (_pad_to_tiles(u[:, 0]), _pad_to_tiles(u[:, 1]))
    else:
        signed = keys.astype(jnp.int32)
        planes = (_pad_to_tiles(lax.bitcast_convert_type(signed, jnp.uint32)),)
    out = _run(planes, num_partitions, interpret)
    return out.reshape(-1)[:n]


def pallas_partition_map(
    keys: jnp.ndarray, num_partitions: int, interpret: bool = False
) -> jnp.ndarray:
    """[N] int32/int64 keys -> [N] int32 partition ids, bit-exact with
    hash_partition_map on a single int column.

    interpret=True runs the kernel in the Pallas interpreter (hermetic
    CPU testing); on TPU leave it False for the compiled kernel. The
    whole path (lane split, tile pad, kernel, unpad) is one compiled
    program — eager prep dispatches would dominate on remote backends.
    """
    if keys.dtype.itemsize not in (4, 8):
        raise ValueError(f"pallas_partition_map supports 4/8-byte keys, got {keys.dtype}")
    return _partition_map_impl(keys, int(num_partitions), bool(interpret))


# ---------------------------------------------------------------------------
# bounded-domain GROUP BY SUM on the MXU
# ---------------------------------------------------------------------------
#
# TPUs have no fast scatter: jax.ops.segment_sum over 1M rows costs ~7ms
# (XLA serializes the scatter-add), and an XLA one-hot matmul pays K*N*4
# bytes of HBM traffic just materializing the one-hot. This kernel builds
# each one-hot tile IN VMEM and contracts it on the MXU immediately —
# the one-hot never touches HBM.
#
# Measured (v5e, 1M rows x 4096 keys): ~matches the scatter path
# (~150 Mrows/s) rather than beating it — the [1, 256] x [256, K]
# contraction is a matvec (M=1), which uses 1/128 of the MXU, and
# Precision.HIGHEST (needed for f32-exact sums) triples the passes.
# Next step when this op matters: batch 128 row-chunks into one
# [128, 256] x [256, K] block-diagonal contraction per grid step, or
# specialize K <= 128 where a full-width matmul applies.

_GB_CHUNK = 256  # columns of each (8, 256) row block; one-hot tile [256, K]
_GB_SUBLANES = 8  # TPU block sublane quantum


def _groupby_kernel(k_ref, v_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    kpad = out_ref.shape[1]
    cols = jax.lax.broadcasted_iota(jnp.int32, (_GB_CHUNK, kpad), 1)
    # static unroll over the 8 sublanes: each [256, Kpad] one-hot tile
    # lives only in VMEM/registers; rows with out-of-domain keys (incl.
    # the padding sentinel) match no column and vanish
    for s in range(_GB_SUBLANES):
        oh = (k_ref[s, :].reshape(-1, 1) == cols).astype(jnp.float32)
        # HIGHEST: the MXU's default single-pass bf16 loses ~3 decimal
        # digits; the 3-pass f32 emulation keeps sums exact to f32 ulp
        dot = jax.lax.dot_general(
            v_ref[s, :].reshape(1, -1),
            oh,
            (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        # accumulate straight into the revisited output block: no
        # scratch buffer, so interpret mode needs no TPU plugin
        out_ref[s : s + 1, :] += dot


@functools.partial(jax.jit, static_argnums=(2, 3))
def _groupby_impl(keys, vals, num_keys: int, interpret: bool):
    n = keys.shape[0]
    kpad = max((num_keys + _LANES - 1) // _LANES * _LANES, _LANES)
    step_rows = _GB_SUBLANES * _GB_CHUNK
    m = max((n + step_rows - 1) // step_rows, 1)  # grid=(0,) never runs
    total = m * step_rows
    # domain check BEFORE any narrowing cast: int64 keys >= 2^32 must
    # drop, not wrap into the valid domain
    in_domain = (keys >= 0) & (keys < num_keys)
    keys32 = jnp.where(in_domain, keys, -1).astype(jnp.int32)
    # pad with an out-of-domain sentinel so padding rows sum nowhere
    kp = jnp.full((total,), -1, jnp.int32).at[:n].set(keys32)
    vp = jnp.zeros((total,), jnp.float32).at[:n].set(vals.astype(jnp.float32))
    kp = kp.reshape(m * _GB_SUBLANES, _GB_CHUNK)
    vp = vp.reshape(m * _GB_SUBLANES, _GB_CHUNK)

    row_spec = pl.BlockSpec(
        (_GB_SUBLANES, _GB_CHUNK),
        lambda i: (i, jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )
    out_spec = pl.BlockSpec(
        (_GB_SUBLANES, kpad),
        lambda i: (jnp.int32(0), jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )
    out = pl.pallas_call(
        _groupby_kernel,
        out_shape=jax.ShapeDtypeStruct((_GB_SUBLANES, kpad), jnp.float32),
        grid=(m,),
        in_specs=[row_spec, row_spec],
        out_specs=out_spec,
        interpret=interpret,
    )(kp, vp)
    # 8 sublane partial accumulators -> final sums
    return jnp.sum(out, axis=0)[:num_keys]


# ---------------------------------------------------------------------------
# outer-product GROUP BY SUM: full-width MXU formulation
# ---------------------------------------------------------------------------
#
# The kernel above is a matvec (M=1) and wastes 127/128 of the MXU.
# This one restores the M dimension with the histogram outer-product
# decomposition: write key = hi*128 + lo, then
#
#   sums[hi, lo]   = sum_i vals[i] * OH_hi[i, hi] * OH_lo[i, lo]
#   counts[hi, lo] = sum_i           OH_hi[i, hi] * OH_lo[i, lo]
#
# i.e. ONE [4H, NT] x [NT, 128] matmul per row block:
#   lhs = [A1 | A2 | A3 | C] with A_k = v_k-weighted hi-one-hot and C
#   the unweighted hi-one-hot, rhs = lo-one-hot. v is split into three
#   bf16 limbs (v = v1+v2+v3 captures all 24 f32 mantissa bits), and
#   the rhs one-hot is exactly representable in bf16, so each MXU
#   product is exact and the f32 accumulator gives segment_sum-class
#   accuracy — at single-pass bf16 speed, with H=32 (num_keys=4096)
#   filling the MXU's M dimension (4H=128).
#
# Both one-hots live only in VMEM; HBM traffic is just keys+vals.

_OUTER_NT_MAX = 8192  # rows contracted per grid step (the dot's K dim).
# The transposed build keeps one [4H, NT] lhs, one [128, NT] rhs and one
# [H, NT] cmp tile live — (5H + 128) * 2 bytes per row — so NT scales
# down as the key domain grows. v5e-measured (1M rows, chained): K=4096
# NT 2048/4096/8192 -> 4.1/5.2/6.7 Grows/s; K=16384 NT=8192 -> 1.75
# Grows/s; K=65536 NT=2048 -> 0.38 Grows/s (scatter: 0.15).
_OUTER_VMEM_BUDGET = 13_000_000  # bytes of live kernel tiles that fit


def _outer_nt(H: int) -> int:
    per_row = (5 * H + _LANES) * 2
    nt = _OUTER_VMEM_BUDGET // per_row
    p = 512
    while p * 2 <= min(nt, _OUTER_NT_MAX):
        p *= 2
    return p


def _outer_kernel(k_ref, v_ref, out_ref, *, H: int):
    """One full-width MXU contraction per grid step, everything built in
    the keys' NATIVE row orientation.

    The round-2 kernel spent its time on layout, not math: each sublane
    step paid a [NT] -> [NT, 1] lane->sublane relayout to build one-hots
    and a lane-axis concatenate to assemble the lhs, then issued a small
    dot. Here keys arrive as a [1, NT] row; both one-hots broadcast that
    row across SUBLANES (free) against a dim-0 iota, the limb concat
    stacks along sublanes (tile-aligned), and the dot contracts both
    operands on their last dim — lhsT [4H, NT] x rhsT [128, NT] ->
    [4H, 128] — which the MXU consumes directly.
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    k = k_ref[0]  # [1, NT] i32 (pre-mapped to [0, H*128) + trash H*128)
    v = v_ref[0]  # [1, NT] f32
    nt = k.shape[1]

    hi = k >> 7
    lo = k & 127
    iota_h = jax.lax.broadcasted_iota(jnp.int32, (H, nt), 0)
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (_LANES, nt), 0)
    # single bool->bf16 consumer, then multiplies: Mosaic rejects the
    # multi-consumer broadcast i1 relayout a where-chain needs, and
    # one-hot products are exact either way (factors are 0/1)
    cmp = (jnp.broadcast_to(hi, (H, nt)) == iota_h).astype(jnp.bfloat16)  # [H, NT]
    rhsT = (jnp.broadcast_to(lo, (_LANES, nt)) == iota_l).astype(jnp.bfloat16)  # [128, NT]

    # v = v1 + v2 + v3 in bf16 limbs captures all 24 f32 mantissa bits;
    # each limb and each one-hot entry is exactly representable in bf16,
    # so every MXU product is exact and the f32 accumulator gives
    # segment_sum-class accuracy at single-pass bf16 speed.
    v1 = v.astype(jnp.bfloat16)
    r1 = v - v1.astype(jnp.float32)
    v2 = r1.astype(jnp.bfloat16)
    v3 = (r1 - v2.astype(jnp.float32)).astype(jnp.bfloat16)
    lhsT = jnp.concatenate(
        [cmp * v1, cmp * v2, cmp * v3, cmp],
        axis=0,
    )  # [4H, NT] — sublane-axis concat: tile stacking, no relayout
    out_ref[...] += jax.lax.dot_general(
        lhsT, rhsT, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [4H, 128]


@functools.partial(jax.jit, static_argnums=(2, 3))
def _outer_impl(keys, vals, num_keys: int, interpret: bool):
    n = keys.shape[0]
    H = max((num_keys + _LANES - 1) // _LANES, 1)  # ceil(num_keys/128)
    # out-of-domain/padding rows map to hi == H: outside the hi-one-hot,
    # so they match no column and vanish (no in-matrix trash slot, which
    # would force a 128-misaligned H)
    trash = H * _LANES
    in_domain = (keys >= 0) & (keys < num_keys)
    seg = jnp.where(in_domain, keys, trash).astype(jnp.int32)

    nt = _outer_nt(H)
    g = max((n + nt - 1) // nt, 1)
    total = g * nt
    # [g, 1, NT]: blocks index the leading dim; the trailing (1, NT)
    # equals the array's own trailing dims (the tiling rule Mosaic
    # requires for non-(8,128)-divisible blocks)
    kp = jnp.full((total,), trash, jnp.int32).at[:n].set(seg).reshape(g, 1, nt)
    vp = (
        jnp.zeros((total,), jnp.float32)
        .at[:n]
        .set(vals.astype(jnp.float32))
        .reshape(g, 1, nt)
    )

    row_spec = pl.BlockSpec(
        (1, 1, nt),
        lambda i: (i, jnp.int32(0), jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )
    out_spec = pl.BlockSpec(
        (4 * H, _LANES),
        lambda i: (jnp.int32(0), jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )
    out = pl.pallas_call(
        functools.partial(_outer_kernel, H=H),
        out_shape=jax.ShapeDtypeStruct((4 * H, _LANES), jnp.float32),
        grid=(g,),
        in_specs=[row_spec, row_spec],
        out_specs=out_spec,
        interpret=interpret,
    )(kp, vp)
    sums = (out[:H] + out[H : 2 * H] + out[2 * H : 3 * H]).reshape(H * _LANES)[:num_keys]
    counts = out[3 * H :].reshape(H * _LANES)[:num_keys].astype(jnp.int64)
    return sums, counts


def pallas_groupby_sum_outer(
    keys: jnp.ndarray, vals: jnp.ndarray, num_keys: int, interpret: bool = False
):
    """GROUP BY SUM + COUNT over a bounded key domain [0, num_keys) as a
    full-width MXU outer-product contraction. float32 sums, exact
    int64-safe counts (f32 accumulator: exact below 2^24 rows/key).

    Returns (sums[num_keys] f32, counts[num_keys] i64); out-of-domain
    keys are dropped. num_keys <= 65536: the contraction length NT
    scales down as H grows (see _outer_nt) and past H=512 the one-hot
    work amplification (2*4H*128 FLOPs/row) loses to the scatter path.
    """
    if num_keys > 65536:
        raise ValueError("pallas_groupby_sum_outer supports num_keys <= 65536")
    return _outer_impl(keys, vals, int(num_keys), bool(interpret))


def pallas_groupby_sum_bounded(
    keys: jnp.ndarray, vals: jnp.ndarray, num_keys: int, interpret: bool = False
) -> jnp.ndarray:
    """GROUP BY SUM over a bounded key domain [0, num_keys), one-hot
    matmul on the MXU with VMEM-resident tiles. float32 sums.

    Matches ops.aggregate.groupby_sum_bounded's sums (float path) for
    in-domain keys; out-of-domain keys are dropped.
    """
    if num_keys > 4096:
        raise ValueError("pallas_groupby_sum_bounded supports num_keys <= 4096 (VMEM tile)")
    return _groupby_impl(keys, vals, int(num_keys), bool(interpret))


# ---------------------------------------------------------------------------
# paged hash-table JOIN build/probe (the RPA page discipline)
# ---------------------------------------------------------------------------
#
# XLA has no device hash table, so ops/join.py's formulation sorts the
# CONCATENATED key tables (nl + nr rows, multi-pass) per join. Ragged
# Paged Attention's answer to ragged lookups on TPU is fixed-size
# on-chip pages with overflow chaining; applied to an equi-join:
#
# BUILD (XLA prep, build-side scale only): bucket = mix(key) & (B-1);
# build rows sort by (bucket, key) — two stable single-key argsorts,
# not the probe-side multi-column sort — and fill fixed 128-slot pages
# allocated CONTIGUOUSLY per bucket, so a bucket's overflow chain is
# page_first[b] .. page_first[b] + chain_len[b) (the chain pointer is
# the implicit +1). Because slots within a bucket are (key, row)-
# sorted, a probe's matches are one CONTIGUOUS slot range.
#
# PROBE (the Pallas kernel): the whole page table lives in VMEM as u8
# LIMB PLANES in bf16 ([nlimb * n_pages, 128]; 0..255 and the empty
# sentinel 320 are bf16-exact, so one-hot MXU products are exact). Per
# (probe block, chain step) the kernel builds the [BLK, n_pages] page
# one-hot, gathers the chain page's limbs with nlimb MXU contractions,
# and accumulates per-row counts of slots with key < probe (lt) and
# key == probe (eq) via a lexicographic limb compare — so each probe
# row leaves the kernel with its match range [start[bucket] + lt,
# start[bucket] + lt + eq) over the page-sorted build order, and the
# shared join expansion emits gather maps BIT-IDENTICAL to the XLA
# formulation (stable sorts tie-break equal keys by original row on
# both paths).
#
# Work shape: one chain step costs nlimb [BLK, n_pages] x [n_pages,
# 128] bf16 matmuls — the one-hot gather's N_probe x n_pages work
# amplification means the tier targets DIMENSION-TABLE builds (the
# TPC-DS star shape): n_pages is capped, and pathological skew (every
# key in one bucket) stays correct but pays chain_len grid steps.

_PJ_PAGE = _LANES  # slots per page: one lane row
_PJ_BLK = 256  # probe rows per grid step
_PJ_MAX_BUILD = 1 << 16  # build rows the page table will hold
_PJ_MAX_PAGES = 2048  # VMEM cap: 8 limb planes x 2048 pages x 128 x 2B = 4MB
_PJ_BUCKET_TARGET = 64  # average build rows per bucket
_PJ_MAX_BUCKETS = 2048
_PJ_EMPTY = 320.0  # empty-slot sentinel limb: > any u8 limb, bf16-exact


class PagedHashTable(NamedTuple):
    """Build-side page table (see the module comment for the layout)."""

    limbs: jnp.ndarray  # [nlimb * n_pages, 128] bf16 u8-limb planes, MS limb first
    meta: jnp.ndarray  # [B] i64 packed (page_first << 44 | chain_len << 24 | slot_start)
    r_order: jnp.ndarray  # [nm] i32: page-sorted rank -> original build row
    num_buckets: int
    n_pages: int
    nlimb: int
    c_max: int  # longest overflow chain, rounded up to a power of two
    nm: int  # matchable (non-null) build rows


def _order_map_u(keys: jnp.ndarray) -> jnp.ndarray:
    """[N] integer keys -> order-preserving unsigned words (u32 for
    widths <= 4, u64 for 8): unsigned compare in limb space must agree
    with the key dtype's native order."""
    dt_ = keys.dtype
    signed = jnp.issubdtype(dt_, jnp.signedinteger)
    if dt_.itemsize < 4:
        keys = keys.astype(jnp.int32 if signed else jnp.uint32)
        dt_ = keys.dtype
    if dt_.itemsize == 4:
        u = lax.bitcast_convert_type(keys, jnp.uint32)
        return u ^ jnp.uint32(0x80000000) if signed else u
    u = lax.bitcast_convert_type(keys, jnp.uint64)
    return u ^ jnp.uint64(1 << 63) if signed else u


def _bucket_of(u: jnp.ndarray, num_buckets: int) -> jnp.ndarray:
    """[N] order words -> [N] i32 bucket ids in [0, B). Identical on
    the build and probe sides by construction (same function)."""
    if u.dtype == jnp.uint64:
        lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
        h = _fmix(lo ^ _fmix(hi))
    else:
        h = _fmix(u)
    return (h & jnp.uint32(num_buckets - 1)).astype(jnp.int32)


def _limb_val(u: jnp.ndarray, l: int, nlimb: int) -> jnp.ndarray:
    """Most-significant-first u8 limb ``l`` of the order words."""
    sh = 8 * (nlimb - 1 - l)
    one = jnp.uint64(sh) if u.dtype == jnp.uint64 else jnp.uint32(sh)
    mask = jnp.uint64(0xFF) if u.dtype == jnp.uint64 else jnp.uint32(0xFF)
    return (u >> one) & mask


def build_paged_table(
    keys: jnp.ndarray, valid: Optional[jnp.ndarray] = None
) -> Optional[PagedHashTable]:
    """Partition build-side keys into fixed 128-slot pages with
    contiguous overflow chaining. Returns None when the build side is
    empty, all-null, or over the page-table caps — the caller's signal
    to keep the XLA formulation (degrade, never error). Eager-context
    only (ONE stacked host sync: matchable rows, page count, longest
    chain)."""
    n = int(keys.shape[0])
    if n == 0 or n > _PJ_MAX_BUILD:
        return None
    u = _order_map_u(keys)
    nlimb = 8 if u.dtype == jnp.uint64 else 4
    # bucket sizing uses n (nm is still on-device here): at most one
    # doubling of oversize when the build side is null-heavy — empty
    # buckets cost a metadata row, never a page
    num_buckets = 16
    while num_buckets * _PJ_BUCKET_TARGET < n and num_buckets < _PJ_MAX_BUCKETS:
        num_buckets *= 2
    bucket = _bucket_of(u, num_buckets)
    if valid is not None:
        # null build keys never match: park them past the last bucket
        bucket = jnp.where(valid, bucket, jnp.int32(num_buckets))
    # (bucket, key, row) total order from two stable argsorts: sort by
    # key first, then stably by bucket — equal (bucket, key) ties keep
    # original row order, the property the bit-identity proof needs
    perm1 = jnp.argsort(u, stable=True).astype(jnp.int32)
    perm = perm1[jnp.argsort(bucket[perm1], stable=True)].astype(jnp.int32)
    bs_full = bucket[perm]  # nulls parked at bucket B sort LAST, so
    # per-bucket counts over the full array already exclude them

    bids = jnp.arange(num_buckets, dtype=jnp.int32)
    starts = jnp.searchsorted(bs_full, bids, side="left").astype(jnp.int32)
    ends = jnp.searchsorted(bs_full, bids, side="right").astype(jnp.int32)
    cnt = ends - starts
    pages_b = (cnt + _PJ_PAGE - 1) // _PJ_PAGE
    page_first = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(pages_b, dtype=jnp.int32)]
    )
    # ONE stacked host sync for every scalar the build needs (matchable
    # rows, table allocation size, longest chain) — three separate
    # pulls cost three tunnel round-trips on remote backends
    nm_dev = (
        jnp.int32(n) if valid is None else jnp.sum(valid, dtype=jnp.int32)
    )
    nm, n_pages, c_max = (
        int(x)
        for x in np.asarray(jnp.stack([nm_dev, page_first[-1], jnp.max(pages_b)]))
    )
    if nm == 0 or n_pages == 0 or n_pages > _PJ_MAX_PAGES:
        return None
    cp = _pow2_ceil(max(c_max, 1))  # pow2 chain grid keeps the probe
    # compile cache stable
    r_order = perm[:nm]
    bs = bs_full[:nm]
    u_sorted = u[perm][:nm]

    rank = jnp.arange(nm, dtype=jnp.int32) - starts[bs]
    slot = (page_first[bs] + rank // _PJ_PAGE) * _PJ_PAGE + rank % _PJ_PAGE
    planes = []
    for l in range(nlimb):
        init = _PJ_EMPTY if l == 0 else 0.0
        plane = (
            jnp.full((n_pages * _PJ_PAGE,), init, jnp.bfloat16)
            .at[slot]
            .set(_limb_val(u_sorted, l, nlimb).astype(jnp.bfloat16))
        )
        planes.append(plane.reshape(n_pages, _PJ_PAGE))
    limbs = jnp.concatenate(planes, axis=0)
    meta = (
        (page_first[:num_buckets].astype(jnp.int64) << 44)
        | (pages_b.astype(jnp.int64) << 24)
        | starts.astype(jnp.int64)
    )
    return PagedHashTable(limbs, meta, r_order, num_buckets, n_pages, nlimb, cp, nm)


def _probe_kernel(fp_ref, cl_ref, *rest, n_pages: int, nlimb: int, blk: int):
    pls = rest[:nlimb]
    tab_ref = rest[nlimb]
    o_ref = rest[nlimb + 1]
    c = pl.program_id(1)

    @pl.when(c == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    fp = fp_ref[0].reshape(-1, 1)  # [1, BLK] -> [BLK, 1] (the _scal relayout)
    cl = cl_ref[0].reshape(-1, 1)
    pid = fp + c
    iota_p = lax.broadcasted_iota(jnp.int32, (blk, n_pages), 1)
    vmask = c < cl  # [BLK, 1]: rows whose chain still has a page at step c
    # single bool->bf16 consumer (the _outer_kernel Mosaic discipline);
    # one-hot entries are 0/1 and limbs <= 320, all bf16-exact, and each
    # one-hot row selects at most one page, so every MXU product and the
    # length-n_pages sum are exact in any precision
    oh = ((pid == iota_p) & vmask).astype(jnp.bfloat16)
    one = jnp.float32(1)
    zero = jnp.float32(0)
    lt = eq = None
    for l in range(nlimb):
        tl = tab_ref[l * n_pages : (l + 1) * n_pages, :]
        gl = lax.dot_general(
            oh, tl, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BLK, 128]: chain page l-limbs per probe row
        pv = pls[l][0].reshape(-1, 1)  # [BLK, 1] f32 probe limb
        ltk = jnp.where(gl < pv, one, zero)
        eqk = jnp.where(gl == pv, one, zero)
        if l == 0:
            lt, eq = ltk, eqk
        else:
            lt = lt + eq * ltk  # lexicographic: strictly-less at limb l
            eq = eq * eqk  # breaks any earlier all-equal prefix
    # invalid chain steps gathered all-zero limbs, which can spuriously
    # equal an all-zero probe key: mask by chain validity before summing
    lt_n = jnp.sum(jnp.where(vmask, lt, zero), axis=1, keepdims=True)
    eq_n = jnp.sum(jnp.where(vmask, eq, zero), axis=1, keepdims=True)
    upd = jnp.concatenate(
        [lt_n.reshape(1, 1, -1), eq_n.reshape(1, 1, -1)], axis=1
    )  # [1, 2, BLK]
    o_ref[...] += upd


@functools.partial(jax.jit, static_argnums=(4, 5, 6, 7, 8))
def _probe_impl(
    u, lvalid, limbs, meta, num_buckets: int, n_pages: int, nlimb: int,
    c_grid: int, interpret: bool,
):
    n = u.shape[0]
    bucket = jnp.clip(_bucket_of(u, num_buckets), 0, num_buckets - 1)
    m = meta[bucket]  # ONE [N]-from-[B] element gather for all three fields
    fp = (m >> 44).astype(jnp.int32)
    cl = ((m >> 24) & 0xFFFFF).astype(jnp.int32)
    st = (m & 0xFFFFFF).astype(jnp.int32)
    cl = jnp.where(lvalid, cl, 0)  # null probe keys visit no pages

    g = max((n + _PJ_BLK - 1) // _PJ_BLK, 1)
    total = g * _PJ_BLK

    def pack_i(a):
        return (
            jnp.zeros((total,), jnp.int32).at[:n].set(a).reshape(g, 1, _PJ_BLK)
        )

    def pack_f(a):
        return (
            jnp.zeros((total,), jnp.float32).at[:n].set(a).reshape(g, 1, _PJ_BLK)
        )

    limb_ops = [
        pack_f(_limb_val(u, l, nlimb).astype(jnp.float32)) for l in range(nlimb)
    ]
    scal_spec = pl.BlockSpec(
        (1, 1, _PJ_BLK),
        lambda i, c: (i, jnp.int32(0), jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )
    tab_spec = pl.BlockSpec(
        (nlimb * n_pages, _PJ_PAGE),
        lambda i, c: (jnp.int32(0), jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )
    out_spec = pl.BlockSpec(
        (1, 2, _PJ_BLK),
        lambda i, c: (i, jnp.int32(0), jnp.int32(0)),
        memory_space=_VMEM if not interpret else None,
    )
    out = pl.pallas_call(
        functools.partial(
            _probe_kernel, n_pages=n_pages, nlimb=nlimb, blk=_PJ_BLK
        ),
        out_shape=jax.ShapeDtypeStruct((g, 2, _PJ_BLK), jnp.float32),
        grid=(g, c_grid),
        in_specs=[scal_spec] * (2 + nlimb) + [tab_spec],
        out_specs=out_spec,
        interpret=interpret,
    )(pack_i(fp), pack_i(cl), *limb_ops, limbs)
    lt = out[:, 0, :].reshape(-1)[:n].astype(jnp.int32)
    eq = out[:, 1, :].reshape(-1)[:n].astype(jnp.int32)
    return st + lt, eq


def pallas_probe_paged(
    keys: jnp.ndarray,
    valid: Optional[jnp.ndarray],
    table: PagedHashTable,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Stream probe keys through the page table: one fused pass per
    chain step. Returns ``(lo, eq)`` — probe row i matches build ranks
    ``r_order[lo[i] : lo[i] + eq[i]]`` (page-sorted order; equal keys
    keep original build-row order, matching the XLA join's stable
    argsort)."""
    u = _order_map_u(keys)
    nlimb = 8 if u.dtype == jnp.uint64 else 4
    if nlimb != table.nlimb:
        raise ValueError("probe key width does not match the build table")
    lvalid = (
        jnp.ones(keys.shape, bool) if valid is None else valid.astype(bool)
    )
    return _probe_impl(
        u, lvalid, table.limbs, table.meta, table.num_buckets, table.n_pages,
        table.nlimb, table.c_max, bool(interpret),
    )


# ---------------------------------------------------------------------------
# fused ragged DECODE (ragged_compact as one Mosaic kernel)
# ---------------------------------------------------------------------------
#
# ops/ragged_bytes.ragged_compact is the pure-XLA floor NOTES_ROUND5
# measured at ~2.7 s on the 1M x 155 mixed decode axis: per string
# column it pays THREE N-row scatter passes (~40 ns/element each: the
# owner shift c_w, the boundary mask nb, the head-chunk add) plus two
# element gathers per output word, materializing every stage in HBM.
# This kernel is the escalation those notes named: per OUTPUT BLOCK of
# _PD_BLKW u32 words it holds the overlapping ROW WINDOW's metadata and
# a scalar-prefetched two-block POOL WINDOW in VMEM and resolves
# everything on-chip —
#
# - OWNER (the offset walk): c_w[w] = max c_row over window rows with
#   wfirst <= w — a dense masked max over [row_chunk, BLKW] tiles
#   (brute-force compare beats an HBM scatter; the owner row of every
#   word in the block provably lies inside the window),
# - BOUNDARY: nb[w] = min in-word boundary position, same dense min,
# - HEAD: sub-word head chunks of rows starting in the block, dense
#   masked sum (disjoint byte lanes by the dense-offsets contract),
# - FETCH: source words via two in-window dynamic gathers + a 4-way
#   funnel select (constant u32 shifts: no in-kernel i32<->u32
#   conversion, the Mosaic recursion hazard ragged_bytes documents).
#
# The pool window rides pltpu.PrefetchScalarGridSpec: block g fetches
# pool blocks [b_g, b_g + 2) of WINW words each, b_g data-dependent via
# the scalar-prefetched block vector — the RPA paged-fetch shape. WINW
# and the row-window size RW are probed per call (G-scale reduces, one
# host sync — or batched by the caller via ``hint``); inputs whose
# windows exceed the VMEM caps return None and the caller keeps the
# XLA formulation. Zero-length rows (null strings' validity) own no
# bytes and are masked out of all three resolutions.

_PD_BLKW = 512  # output u32 words per grid step (2 KB of output bytes)
_PD_ROW_CHUNK = 128  # row-window rows reduced per unrolled step
_PD_MAX_RW = 1024  # row-window cap (VMEM: [128, 512] i32 tiles per step)
_PD_MAX_WIN = 1 << 17  # pool-window cap in words (2 x 512 KB blocks in VMEM)
_PD_BIG = 0x3FFFFFFF  # parked word index: matches no real output word


@functools.partial(jax.jit, static_argnums=(2,))
def pallas_decode_probe(base, offs, total: int):
    """Static-shape probe for ``pallas_ragged_compact``: [2] i32 of
    (max rows overlapping any output block, max pool-window words any
    block needs). G-scale reduces only; callers batch several columns'
    probes into one host sync."""
    n = base.shape[0]
    nw = (total + 3) // 4
    g = max((nw + _PD_BLKW - 1) // _PD_BLKW, 1)
    w0 = jnp.arange(g, dtype=jnp.int64) * (_PD_BLKW * 4)
    rfirst = jnp.clip(
        jnp.searchsorted(offs[1:], w0, side="right"), 0, n - 1
    ).astype(jnp.int32)
    rlast = jnp.clip(
        jnp.searchsorted(offs[:-1], w0 + 4 * _PD_BLKW - 1, side="right") - 1,
        0, n - 1,
    ).astype(jnp.int32)
    rlast = jnp.maximum(rlast, rfirst)
    rw = jnp.max(rlast - rfirst + 1)
    b_rf = base[rfirst]
    wl = jnp.clip(b_rf - 4, 0, None) >> 2
    c_rl = base[rlast] - offs[rlast]
    span = ((c_rl + w0 + 4 * _PD_BLKW + 8) >> 2) - wl + 2
    return jnp.stack([rw.astype(jnp.int32), jnp.max(span).astype(jnp.int32)])


def _pd_kernel(
    bvec_ref, cr_ref, wf_ref, bw_ref, bp_ref, hw_ref, hc_ref, p0_ref, p1_ref,
    o_ref, *, blkw: int, rw: int, winw: int, rc_chunk: int,
):
    g = pl.program_id(0)
    wb = bvec_ref[g] * winw
    w = g * blkw + lax.broadcasted_iota(jnp.int32, (1, blkw), 1)
    crm = cr_ref[:]
    wfm = wf_ref[:]
    bwm = bw_ref[:]
    bpm = bp_ref[:]
    hwm = hw_ref[:]
    hcm = hc_ref[:]
    acc_c = jnp.zeros((1, blkw), jnp.int32)
    acc_nb = jnp.full((1, blkw), 4, jnp.int32)
    acc_h = jnp.zeros((1, blkw), jnp.uint32)
    # chunked row reduction (the _vacc_kernel VMEM discipline: each
    # [rc_chunk, blkw] tile's temps die before the next chunk)
    for k in range(rw // rc_chunk):
        sl = slice(k * rc_chunk, (k + 1) * rc_chunk)
        wfk = wfm[:, sl].reshape(-1, 1)  # [RC, 1] (the _scal relayout)
        crk = crm[:, sl].reshape(-1, 1)
        acc_c = jnp.maximum(
            acc_c,
            jnp.max(jnp.where(wfk <= w, crk, 0), axis=0, keepdims=True),
        )
        bwk = bwm[:, sl].reshape(-1, 1)
        bpk = bpm[:, sl].reshape(-1, 1)
        acc_nb = jnp.minimum(
            acc_nb,
            jnp.min(jnp.where(bwk == w, bpk, 4), axis=0, keepdims=True),
        )
        hwk = hwm[:, sl].reshape(-1, 1)
        hck = hcm[:, sl].reshape(-1, 1)
        acc_h = acc_h + jnp.sum(
            jnp.where(hwk == w, hck, jnp.uint32(0)),
            axis=0, keepdims=True, dtype=jnp.uint32,  # x64 would promote
        )
    s = acc_c + w * 4  # owner source byte address per output word
    lw = jnp.clip((s >> 2) - wb, 0, 2 * winw - 2)
    w2 = jnp.concatenate([p0_ref[:], p1_ref[:]], axis=1)  # [1, 2*WINW]
    g0 = jnp.take_along_axis(w2, lw, axis=1)
    g1 = jnp.take_along_axis(w2, lw + 1, axis=1)
    # 4-way funnel select on constant u32 shifts: no i32<->u32 astype
    # in-kernel (the Mosaic convert-lowering recursion ragged_bytes hit)
    c1 = (g0 >> jnp.uint32(8)) | (g1 << jnp.uint32(24))
    c2 = (g0 >> jnp.uint32(16)) | (g1 << jnp.uint32(16))
    c3 = (g0 >> jnp.uint32(24)) | (g1 << jnp.uint32(8))
    rbsel = s & 3
    word = jnp.where(
        rbsel == 0, g0, jnp.where(rbsel == 1, c1, jnp.where(rbsel == 2, c2, c3))
    )
    keep = jnp.where(
        acc_nb >= 4,
        ~jnp.uint32(0),
        jnp.where(
            acc_nb == 1,
            jnp.uint32(0xFF),
            jnp.where(acc_nb == 2, jnp.uint32(0xFFFF), jnp.uint32(0xFFFFFF)),
        ),
    )
    o_ref[:] = (word & keep) | acc_h


@functools.partial(jax.jit, static_argnums=(3, 4, 5, 6, 7))
def _pd_impl(
    pool32, base, offs, total: int, plen: int, rw: int, winw: int,
    interpret: bool,
):
    from .ragged_bytes import _funnel_u32, u32_rows_to_u8_flat

    n = base.shape[0]
    nw = (total + 3) // 4
    g = max((nw + _PD_BLKW - 1) // _PD_BLKW, 1)
    pw = pool32.shape[0]
    pb = pw // winw + 2
    pool2d = (
        jnp.zeros((pb * winw,), jnp.uint32).at[:pw].set(pool32).reshape(pb, winw)
    )

    w0 = jnp.arange(g, dtype=jnp.int64) * (_PD_BLKW * 4)
    rfirst = jnp.clip(
        jnp.searchsorted(offs[1:], w0, side="right"), 0, n - 1
    ).astype(jnp.int32)
    ridx = rfirst[:, None] + jnp.arange(rw, dtype=jnp.int32)[None, :]
    inb = ridx < n
    rc = jnp.clip(ridx, 0, n - 1)
    o_r = offs[rc].astype(jnp.int32)  # addresses < 2^31 (cudf size_type)
    e_r = offs[rc + 1].astype(jnp.int32)
    b_r = base[rc].astype(jnp.int32)
    valid = inb & (e_r > o_r)
    cr = jnp.where(valid, b_r - o_r, 0)
    wf = jnp.where(valid, (o_r + 3) >> 2, _PD_BIG)
    bpos = e_r & 3
    bw = jnp.where(inb & (bpos > 0), e_r >> 2, _PD_BIG)
    bp = bpos
    xa = (o_r + 3) & ~jnp.int32(3)
    chunk = jnp.clip(jnp.minimum(e_r, xa) - o_r, 0, 3)
    has = valid & (chunk > 0)
    hsrc = _funnel_u32(pool32, jnp.clip(b_r, 0, plen))
    hmask = (jnp.uint32(1) << (chunk.astype(jnp.uint32) * 8)) - jnp.uint32(1)
    hc = jnp.where(
        has,
        (hsrc & hmask) << ((o_r & 3).astype(jnp.uint32) * 8),
        jnp.uint32(0),
    )
    hw = jnp.where(has, o_r >> 2, _PD_BIG)

    b_rf = base[rfirst].astype(jnp.int32)
    wl = jnp.clip(b_rf - 4, 0, None) >> 2
    bvec = jnp.clip(wl // winw, 0, pb - 2).astype(jnp.int32)

    def _meta_spec():
        return pl.BlockSpec(
            (1, rw),
            lambda i, b: (i, jnp.int32(0)),
            memory_space=_VMEM if not interpret else None,
        )

    def _pool_spec(step: int):
        return pl.BlockSpec(
            (1, winw),
            lambda i, b, _s=step: (b[i] + _s, jnp.int32(0)),
            memory_space=_VMEM if not interpret else None,
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(g,),
        in_specs=[_meta_spec() for _ in range(6)]
        + [_pool_spec(0), _pool_spec(1)],
        out_specs=pl.BlockSpec(
            (1, _PD_BLKW),
            lambda i, b: (i, jnp.int32(0)),
            memory_space=_VMEM if not interpret else None,
        ),
    )
    out = pl.pallas_call(
        functools.partial(
            _pd_kernel, blkw=_PD_BLKW, rw=rw, winw=winw,
            rc_chunk=min(rw, _PD_ROW_CHUNK),
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((g, _PD_BLKW), jnp.uint32),
        interpret=interpret,
    )(bvec, cr, wf, bw, bp, hw, hc, pool2d, pool2d)
    return u32_rows_to_u8_flat(out)[:total]


def pallas_ragged_compact(
    pool: jnp.ndarray,
    base: jnp.ndarray,
    offs: jnp.ndarray,
    total: int,
    pool32: jnp.ndarray = None,
    interpret: bool = False,
    hint=None,
):
    """Fused-kernel twin of ``ops.ragged_bytes.ragged_compact`` (same
    contract: dense ``offs``, nondecreasing non-overlapping ``base``,
    addresses < 2^31). Returns the [total] u8 blob BIT-IDENTICAL to the
    XLA formulation, or None when the probed row/pool windows exceed
    the VMEM caps — the caller's keep-XLA signal. ``hint`` short-cuts
    the probe with precomputed (rw_max, span_max) so multi-column
    callers pay ONE host sync for all columns. Eager-context only."""
    total = int(total)
    n = int(base.shape[0])
    if total == 0 or n == 0:
        return jnp.zeros((0,), jnp.uint8)
    if hint is None:
        rw_max, span_max = (
            int(x) for x in np.asarray(pallas_decode_probe(base, offs, total))
        )
    else:
        rw_max, span_max = int(hint[0]), int(hint[1])
    rw = _pow2_ceil(max(rw_max, 8))
    winw = _pow2_ceil(max(span_max, _LANES))
    if rw > _PD_MAX_RW or winw > _PD_MAX_WIN:
        return None
    if pool32 is None:
        from .ragged_bytes import build_pool32

        pool32 = build_pool32(pool)
    return _pd_impl(
        pool32, base, offs, total, int(pool.shape[0]), rw, winw, bool(interpret)
    )

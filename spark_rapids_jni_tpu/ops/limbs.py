"""Vectorized multi-precision integer arithmetic in uint32 limbs.

TPU v5e lanes are 32-bit: there is no native int128 (and int64 itself is
emulated as u32 pairs). The reference's ``chunked256`` (4 x u64,
decimal_utils.cu:31-119) becomes here arrays shaped ``[..., K]`` of
uint32 limbs, little-endian, with u64 intermediates for carries — K=4
for 128-bit magnitudes, K=8 for 256-bit products. All ops are
elementwise-vectorized over the leading axes and unrolled over K (K is
a static Python int), so XLA sees straight-line vector code.

Magnitude+sign representation is used by the decimal ops (matching the
reference's approach of tracking sign separately in its division path);
two's-complement conversion happens only at column-storage boundaries.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

__all__ = [
    "from_ints",
    "to_ints",
    "add",
    "add_small",
    "sub",
    "negate",
    "mul10_add",
    "mul_small",
    "mul",
    "gt",
    "ge",
    "eq",
    "is_zero",
    "count_digits",
    "POW10_LIMBS",
    "NINES_LIMBS",
    "pow10",
    "shift_left_bits",
    "divmod_bits",
    "to_twos_complement",
    "from_twos_complement",
]

_MASK = jnp.uint64(0xFFFFFFFF)


def from_ints(values, K: int) -> np.ndarray:
    """Host: python ints (non-negative) -> [N, K] uint32 limbs."""
    out = np.zeros((len(values), K), dtype=np.uint32)
    for i, v in enumerate(values):
        v = int(v)
        for k in range(K):
            out[i, k] = (v >> (32 * k)) & 0xFFFFFFFF
    return out


def to_ints(limbs: np.ndarray) -> list:
    """Host: [N, K] uint32 limbs -> non-negative python ints."""
    limbs = np.asarray(limbs)
    out = []
    for row in limbs:
        v = 0
        for k, limb in enumerate(row):
            v |= int(limb) << (32 * k)
        out.append(v)
    return out


def _u64(x) -> jnp.ndarray:
    return x.astype(jnp.uint64)


def add(a: jnp.ndarray, b: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """a + b -> (sum limbs, carry-out). Shapes [..., K]."""
    K = a.shape[-1]
    out = []
    carry = jnp.zeros(a.shape[:-1], jnp.uint64)
    for k in range(K):
        t = _u64(a[..., k]) + _u64(b[..., k]) + carry
        out.append((t & _MASK).astype(jnp.uint32))
        carry = t >> jnp.uint64(32)
    return jnp.stack(out, axis=-1), carry.astype(jnp.uint32)


def add_small(a: jnp.ndarray, x) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """a + x (x: scalar or [...] array fitting u32)."""
    K = a.shape[-1]
    out = []
    carry = jnp.asarray(x, jnp.uint64) * jnp.ones(a.shape[:-1], jnp.uint64)
    for k in range(K):
        t = _u64(a[..., k]) + carry
        out.append((t & _MASK).astype(jnp.uint32))
        carry = t >> jnp.uint64(32)
    return jnp.stack(out, axis=-1), carry.astype(jnp.uint32)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """a - b -> (diff limbs, borrow-out: 1 when b > a)."""
    K = a.shape[-1]
    out = []
    borrow = jnp.zeros(a.shape[:-1], jnp.uint64)
    for k in range(K):
        t = _u64(a[..., k]) - _u64(b[..., k]) - borrow
        out.append((t & _MASK).astype(jnp.uint32))
        borrow = (t >> jnp.uint64(63)) & jnp.uint64(1)  # wrapped negative
    return jnp.stack(out, axis=-1), borrow.astype(jnp.uint32)


def negate(a: jnp.ndarray) -> jnp.ndarray:
    """Two's complement negation."""
    K = a.shape[-1]
    inv = (~a).astype(jnp.uint32)
    one = jnp.zeros_like(a).at[..., 0].set(1)
    s, _ = add(inv, one)
    return s


def mul10_add(a: jnp.ndarray, d: jnp.ndarray) -> jnp.ndarray:
    """a * 10 + d  (d: [...] small non-negative)."""
    K = a.shape[-1]
    out = []
    carry = jnp.asarray(d, jnp.uint64)
    for k in range(K):
        t = _u64(a[..., k]) * jnp.uint64(10) + carry
        out.append((t & _MASK).astype(jnp.uint32))
        carry = t >> jnp.uint64(32)
    return jnp.stack(out, axis=-1)


def mul_small(a: jnp.ndarray, m) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """a * m (m fits u32) -> (product limbs, carry-out)."""
    K = a.shape[-1]
    mm = jnp.asarray(m, jnp.uint64)
    out = []
    carry = jnp.zeros(a.shape[:-1], jnp.uint64)
    for k in range(K):
        t = _u64(a[..., k]) * mm + carry
        out.append((t & _MASK).astype(jnp.uint32))
        carry = t >> jnp.uint64(32)
    return jnp.stack(out, axis=-1), carry.astype(jnp.uint32)


def mul(a: jnp.ndarray, b: jnp.ndarray, out_limbs: int) -> jnp.ndarray:
    """Schoolbook a * b -> [..., out_limbs] (like chunked256::multiply,
    decimal_utils.cu:127-146, re-expressed in 32-bit lanes)."""
    Ka, Kb = a.shape[-1], b.shape[-1]
    acc = [jnp.zeros(a.shape[:-1], jnp.uint64) for _ in range(out_limbs + 1)]
    for i in range(Ka):
        for j in range(Kb):
            k = i + j
            if k >= out_limbs:
                continue
            p = _u64(a[..., i]) * _u64(b[..., j])
            acc[k] = acc[k] + (p & _MASK)
            acc[k + 1] = acc[k + 1] + (p >> jnp.uint64(32))
    out = []
    carry = jnp.zeros(a.shape[:-1], jnp.uint64)
    for k in range(out_limbs):
        t = acc[k] + carry
        out.append((t & _MASK).astype(jnp.uint32))
        carry = t >> jnp.uint64(32)
    return jnp.stack(out, axis=-1)


def _cmp_reduce(a: jnp.ndarray, b: jnp.ndarray, op) -> jnp.ndarray:
    K = a.shape[-1]
    res = op(a[..., 0], b[..., 0])
    for k in range(1, K):
        hi_eq = a[..., k] == b[..., k]
        res = jnp.where(hi_eq, res, op(a[..., k], b[..., k]))
    return res


def gt(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _cmp_reduce(a, b, lambda x, y: x > y)


def ge(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _cmp_reduce(a, b, lambda x, y: x >= y)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == 0, axis=-1)


# 10^k and (10^k - 1) tables: 10^38 < 2^127 (K=4); 10^76 < 2^253 (K=8,
# matching the reference's device pow_ten table, decimal_utils.cu:235-498).
_MAX_POW = {4: 38, 8: 76}


def _table(K: int, minus_one: bool) -> np.ndarray:
    vals = [(10**k - (1 if minus_one else 0)) for k in range(_MAX_POW[K] + 1)]
    return from_ints(vals, K)


POW10_LIMBS = {4: _table(4, False), 8: _table(8, False)}
NINES_LIMBS = {4: _table(4, True), 8: _table(8, True)}


def pow10(k: jnp.ndarray, K: int) -> jnp.ndarray:
    """10^k as limbs; k clipped to [0, 38] (K=4) / [0, 76] (K=8)."""
    tbl = jnp.asarray(POW10_LIMBS[K])
    return tbl[jnp.clip(k, 0, _MAX_POW[K])]


def count_digits(a: jnp.ndarray) -> jnp.ndarray:
    """Number of decimal digits (0 for value 0): #{k : a >= 10^k}."""
    K = a.shape[-1]
    tbl = jnp.asarray(POW10_LIMBS[K])
    c = jnp.zeros(a.shape[:-1], jnp.int32)
    for k in range(_MAX_POW[K] + 1):
        c = c + ge(a, tbl[k]).astype(jnp.int32)
    return c


def precision10(a: jnp.ndarray) -> jnp.ndarray:
    """Smallest i with 10^i >= a (the reference's precision10,
    decimal_utils.cu:505-521 — note exact powers of ten give i, one LESS
    than their digit count; this quirk feeds SPARK-40129 compatibility).
    Equals #{i : 10^i < a}."""
    K = a.shape[-1]
    tbl = jnp.asarray(POW10_LIMBS[K])
    c = jnp.zeros(a.shape[:-1], jnp.int32)
    for k in range(_MAX_POW[K] + 1):
        c = c + gt(a, tbl[k]).astype(jnp.int32)
    return c


def is_all_nines(a: jnp.ndarray) -> jnp.ndarray:
    """True when a == 10^k - 1 for some k >= 1 (rounding carried through
    every digit — the digit-count-increase test of cast_string.cu:479-498)."""
    K = a.shape[-1]
    tbl = jnp.asarray(NINES_LIMBS[K])
    r = jnp.zeros(a.shape[:-1], bool)
    for k in range(1, 39):
        r = r | eq(a, tbl[k])
    return r


def shift_left_bits(a: jnp.ndarray, n) -> jnp.ndarray:
    """a << n for per-element n in [0, 32*K)."""
    K = a.shape[-1]
    n = jnp.asarray(n, jnp.int32)
    word = n // 32
    bit = (n % 32).astype(jnp.uint32)
    out = []
    for k in range(K):
        acc = jnp.zeros(a.shape[:-1], jnp.uint64)
        for src in range(K):
            sel = word == (k - src)
            lo = _u64(a[..., src]) << _u64(bit)
            contrib = jnp.where(sel, lo, 0)
            sel_hi = word == (k - src - 1)
            hi = jnp.where(
                bit > 0, _u64(a[..., src]) >> _u64(jnp.uint32(32) - bit), jnp.uint64(0)
            )
            contrib = contrib + jnp.where(sel_hi, hi, 0)
            acc = acc + contrib
        out.append((acc & _MASK).astype(jnp.uint32))
    return jnp.stack(out, axis=-1)


def divmod_bits(num: jnp.ndarray, den: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Unsigned long division num / den -> (quotient, remainder).

    Bit-serial restoring division over 32*K bits (the TPU-vector analog of
    the reference's long division, decimal_utils.cu:148-167): 32*K
    ``lax.scan`` steps of shift/compare/subtract, each fully vectorized
    across rows. den == 0 yields quotient/remainder 0 (caller must flag
    div-by-zero).
    """
    import jax
    from jax import lax

    K = num.shape[-1]
    nbits = 32 * K
    den_zero = is_zero(den)
    limb_iota = jnp.arange(K, dtype=jnp.uint32)

    def step(carry, i):
        q, r = carry
        block = (i // 32).astype(jnp.uint32)
        bit = (i % 32).astype(jnp.uint32)
        limb = jnp.sum(jnp.where(limb_iota == block, num, 0), axis=-1).astype(jnp.uint32)
        b = (limb >> bit) & jnp.uint32(1)
        r = shift_left_one(r)
        r = r.at[..., 0].set(r[..., 0] | b)
        fits = ge(r, den) & ~den_zero
        r_sub, _ = sub(r, den)
        r = jnp.where(fits[..., None], r_sub, r)
        q_bit = jnp.where(limb_iota == block, jnp.uint32(1) << bit, jnp.uint32(0))
        q = jnp.where(fits[..., None], q | q_bit, q)
        return (q, r), None

    (q, r), _ = lax.scan(
        step,
        (jnp.zeros_like(num), jnp.zeros_like(num)),
        jnp.arange(nbits - 1, -1, -1, dtype=jnp.int32),
    )
    return q, r


def shift_left_one(a: jnp.ndarray) -> jnp.ndarray:
    K = a.shape[-1]
    out = [(a[..., 0] << jnp.uint32(1)).astype(jnp.uint32)]
    for k in range(1, K):
        out.append(((a[..., k] << jnp.uint32(1)) | (a[..., k - 1] >> jnp.uint32(31))).astype(jnp.uint32))
    return jnp.stack(out, axis=-1)


def to_twos_complement(mag: jnp.ndarray, negative: jnp.ndarray) -> jnp.ndarray:
    """(magnitude, sign) -> two's complement limbs."""
    return jnp.where(negative[..., None], negate(mag), mag)


def from_twos_complement(a: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """two's complement limbs -> (magnitude, negative)."""
    neg = (a[..., -1] >> jnp.uint32(31)) == 1
    return jnp.where(neg[..., None], negate(a), a), neg

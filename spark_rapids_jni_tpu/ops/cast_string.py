"""Spark-semantics string casts: string -> integer / decimal, with ANSI mode.

Behavioral parity with reference src/main/cpp/src/cast_string.cu:

- whitespace set is {space, \\r, \\t, \\n} (cast_string.cu:46-55),
- leading whitespace then optional +/- sign (signed targets only),
- non-ANSI integer casts truncate at the first '.', but invalid
  characters after it still invalidate the row (:207-210),
- whitespace inside a value starts a trailing-whitespace region; any
  non-whitespace after that invalidates (:199-204),
- digit accumulation is overflow-checked against the target type at
  every step, negative values accumulate toward min (:77-143),
- decimals support scientific notation, precision-bounded rounding
  half-up away from zero, and zero padding to scale (:243-574),
- ANSI mode: rows that fail (and were not already null) raise
  ``CastError`` carrying the FIRST failing row index and its string
  (validate_ansi_column, :594-627).

TPU-first design: instead of a thread-per-row parser, strings are padded
into an [N, L] byte matrix (L = longest string in the batch) and a
``lax.scan`` marches the character axis once, carrying the whole-column
parser state as arrays — a struct-of-arrays state machine. All control
flow is ``jnp.where``; one compile per (schema, N, L) size class.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..columnar import Column
from ..columnar.dtype import DType, TypeId
from ..utils.dispatch import op_boundary

__all__ = ["CastError", "string_to_integer", "string_to_decimal"]


class CastError(RuntimeError):
    """Parity with com.nvidia.spark.rapids.jni.CastException (CastException.java:25-39)."""

    def __init__(self, row_with_error: int, string_with_error: Optional[str]):
        super().__init__(f"Error casting data on row {row_with_error}: {string_with_error!r}")
        self.row_with_error = int(row_with_error)
        self.string_with_error = string_with_error


_WS = (ord(" "), ord("\r"), ord("\t"), ord("\n"))

_INT_LIMITS = {
    TypeId.INT8: (127, 128),
    TypeId.INT16: (2**15 - 1, 2**15),
    TypeId.INT32: (2**31 - 1, 2**31),
    TypeId.INT64: (2**63 - 1, 2**63),
    TypeId.UINT8: (255, 0),
    TypeId.UINT16: (2**16 - 1, 0),
    TypeId.UINT32: (2**32 - 1, 0),
    TypeId.UINT64: (2**64 - 1, 0),
}


def _is_ws(c: jnp.ndarray) -> jnp.ndarray:
    r = c == _WS[0]
    for w in _WS[1:]:
        r = r | (c == w)
    return r


def _padded_chars(col: Column) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """[N, L] uint8 padded char matrix + [N] lengths. Pad byte is 0."""
    offs = col.offsets
    lens = offs[1:] - offs[:-1]
    max_len = max(col.max_char_len, 1)  # memoized batch size class
    idx = offs[:-1, None] + jnp.arange(max_len, dtype=jnp.int32)[None, :]
    inb = jnp.arange(max_len, dtype=jnp.int32)[None, :] < lens[:, None]
    chars = jnp.where(inb, col.chars[jnp.clip(idx, 0, max(col.chars.shape[0] - 1, 0))], 0)
    return chars, lens, max_len


# ---------------------------------------------------------------------------
# string -> integer
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("is_signed", "max_mag", "neg_mag", "ansi_mode", "max_len"))
def _parse_integer(
    chars: jnp.ndarray,  # [N, L] uint8
    lens: jnp.ndarray,  # [N] int32
    in_valid: jnp.ndarray,  # [N] bool
    is_signed: bool,
    max_mag: int,
    neg_mag: int,
    ansi_mode: bool,
    max_len: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns ([N] uint64 magnitude, [N] negative flag, [N] valid flag)."""
    n = chars.shape[0]
    ws = _is_ws(chars)
    digit = (chars >= ord("0")) & (chars <= ord("9"))
    inb = jnp.arange(max_len, dtype=jnp.int32)[None, :] < lens[:, None]

    # first non-whitespace position (== len when all whitespace)
    nonws = (~ws) & inb
    i0 = jnp.where(jnp.any(nonws, axis=1), jnp.argmax(nonws, axis=1).astype(jnp.int32), lens)
    c0 = jnp.take_along_axis(chars, jnp.clip(i0, 0, max_len - 1)[:, None], axis=1)[:, 0]
    has_sign = is_signed & ((c0 == ord("+")) | (c0 == ord("-"))) & (i0 < lens)
    negative = is_signed & (c0 == ord("-")) & has_sign
    istart = i0 + has_sign.astype(jnp.int32)

    valid = in_valid & (lens > 0) & (istart < lens)

    # scan the char axis: state 0=DIGITS 1=TRUNC(after '.') 2=TRAILWS 3=INVALID
    limit = jnp.where(negative, jnp.uint64(neg_mag), jnp.uint64(max_mag))
    lim_div10 = limit // jnp.uint64(10)

    def step(carry, j):
        state, acc, overflow, seen_digit = carry
        c = chars[:, j]
        active = (j >= istart) & (j < lens)
        d = digit[:, j]
        w = ws[:, j]
        dot = (c == ord(".")) & (not ansi_mode)

        # transitions
        nxt = jnp.where(
            state == 0,
            jnp.where(d, 0, jnp.where(dot, 1, jnp.where(w & (j > istart), 2, 3))),
            jnp.where(
                state == 1,
                jnp.where(d, 1, jnp.where(w, 2, 3)),
                jnp.where(state == 2, jnp.where(w, 2, 3), 3),
            ),
        )
        nxt = jnp.where(active, nxt, state)

        # accumulate while in DIGITS state consuming a digit
        consume = active & d & (state == 0) & (nxt == 0)
        dig = (c - ord("0")).astype(jnp.uint64)
        ovf_mul = acc > lim_div10
        acc10 = acc * jnp.uint64(10)
        ovf_add = acc10 > limit - dig
        first = consume & ~seen_digit
        new_acc = jnp.where(first, dig, acc10 + dig)
        new_ovf = overflow | (consume & ~first & (ovf_mul | ovf_add))
        acc = jnp.where(consume & ~new_ovf, new_acc, acc)
        overflow = new_ovf
        seen_digit = seen_digit | consume
        return (nxt, acc, overflow, seen_digit), None

    state0 = jnp.zeros((n,), jnp.int32)
    acc0 = jnp.zeros((n,), jnp.uint64)
    (state, acc, overflow, seen_digit), _ = lax.scan(
        step, (state0, acc0, jnp.zeros((n,), bool), jnp.zeros((n,), bool)),
        jnp.arange(max_len, dtype=jnp.int32)
    )

    valid = valid & (state != 3) & ~overflow
    if ansi_mode:
        # in ANSI mode a bare "." was never consumable: state would be 3
        pass
    return acc, negative, valid


@op_boundary("string_to_integer")
def string_to_integer(col: Column, ansi_mode: bool, out_dtype: DType) -> Column:
    """String column -> integral column. Parity: cast_string.cu string_to_integer :763."""
    if col.dtype.id != TypeId.STRING:
        raise ValueError("string_to_integer expects a STRING column")
    if not out_dtype.is_integral:
        raise ValueError(f"target must be integral, got {out_dtype!r}")
    n = len(col)
    if n == 0:
        return Column(out_dtype, data=jnp.zeros((0,), out_dtype.jnp_dtype))

    chars, lens, max_len = _padded_chars(col)
    in_valid = col.valid_mask()
    max_mag, neg_mag = _INT_LIMITS[out_dtype.id]
    acc, negative, valid = _parse_integer(
        chars, lens, in_valid,
        out_dtype.is_signed, max_mag, neg_mag, bool(ansi_mode), max_len,
    )

    # magnitude -> signed value in target dtype (two's complement safe)
    as_i = acc.astype(jnp.uint64)
    signed_val = jnp.where(negative, jnp.uint64(0) - as_i, as_i)
    data = lax.convert_element_type(
        lax.bitcast_convert_type(signed_val, jnp.int64)
        if out_dtype.is_signed
        else signed_val,
        out_dtype.jnp_dtype,
    )
    data = jnp.where(valid, data, jnp.zeros((), out_dtype.jnp_dtype))

    if ansi_mode:
        _validate_ansi(valid, col)
    return Column(out_dtype, data=data, validity=valid)


def _validate_ansi(valid: jnp.ndarray, source: Column) -> None:
    """Raise CastError for the first newly-invalid row (cast_string.cu:594-627)."""
    newly_bad = (~valid) & source.valid_mask()
    if bool(jnp.any(newly_bad)):  # host sync, error path only
        row = int(jnp.argmax(newly_bad))
        offs = np.asarray(source.offsets[row : row + 2])
        s = np.asarray(source.chars[offs[0] : offs[1]]).tobytes().decode("utf-8", "replace")
        raise CastError(row, s)


# ---------------------------------------------------------------------------
# string -> decimal
# ---------------------------------------------------------------------------
# implemented in cast_decimal.py (limb arithmetic); re-exported here so the
# public surface matches CastStrings.java (toInteger/toDecimal).


@op_boundary("string_to_decimal")
def string_to_decimal(col: Column, ansi_mode: bool, precision: int, scale: int) -> Column:
    from . import cast_decimal

    return cast_decimal.string_to_decimal(col, ansi_mode, precision, scale)

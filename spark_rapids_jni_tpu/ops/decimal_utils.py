"""DECIMAL128 multiply/divide with Spark-compatible rounding + overflow.

Behavioral parity with reference src/main/cpp/src/decimal_utils.cu:

- ``multiply128`` (dec128_multiplier :524-592): 256-bit product, then the
  SPARK-40129 double-rounding bug-compatibility — first round to
  precision 38 using ``precision10`` (which undercounts exact powers of
  ten by one), then rescale to the requested product scale; overflow when
  the 256-bit value cannot fit a signed 128-bit integer.
- ``divide128`` (dec128_divider :595-684): three scaling regimes keyed by
  ``n_shift_exp = quot_scale - (a_scale - b_scale)``: divide-then-divide
  (> 0), multiply-then-divide (in [-38, 0]), and base-10 long division
  via a 10^38 split (< -38); divide-by-zero sets the overflow flag
  (:608-612); rounding is half-up away from zero driven by the remainder
  (round_from_remainder :196-227).
- both return a 2-column Table {BOOL8 overflow, DECIMAL128 result} whose
  null mask is the AND of the inputs (:690-733).

TPU-first shape: signs are split off and all arithmetic runs on uint32
limb magnitudes (ops/limbs.py) — [N,8] 256-bit intermediates, scan-based
bit-serial division — fully vectorized across rows instead of
thread-per-row functors.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..columnar import Column, Table
from ..columnar.dtype import TypeId, decimal128
from ..columnar import dtype as dt
from ..utils.dispatch import op_boundary
from . import limbs as L

__all__ = ["multiply128", "divide128"]


_SIGNED128_POS_MAX = 2**127 - 1
_SIGNED128_NEG_MAX = 2**127


def _check_inputs(a: Column, b: Column) -> None:
    if a.dtype.id != TypeId.DECIMAL128 or b.dtype.id != TypeId.DECIMAL128:
        raise ValueError("inputs must be DECIMAL128 columns")
    if len(a) != len(b):
        raise ValueError("inputs have mismatched row counts")


def _and_validity(a: Column, b: Column):
    if a.validity is None and b.validity is None:
        return None
    return a.valid_mask() & b.valid_mask()


def _fits_128(mag: jnp.ndarray, negative: jnp.ndarray) -> jnp.ndarray:
    """Signed-128 fit test on a [..., 8] magnitude (chunked256
    fits_in_128_bits :108-118): |v| <= 2^127-1, or 2^127 when negative."""
    pos_max = jnp.asarray(L.from_ints([_SIGNED128_POS_MAX], 8))[0]
    neg_max = jnp.asarray(L.from_ints([_SIGNED128_NEG_MAX], 8))[0]
    return jnp.where(negative, ~L.gt(mag, neg_max), ~L.gt(mag, pos_max))


def _round_half_up(
    q_mag: jnp.ndarray, r_mag: jnp.ndarray, d_mag: jnp.ndarray
) -> jnp.ndarray:
    """Add 1 to |q| when 2*|r| >= |d| (round_from_remainder :196-227;
    away-from-zero in magnitude form). Shapes [..., K]."""
    r2 = L.shift_left_one(r_mag)
    lost = (r_mag[..., -1] >> jnp.uint32(31)) == 1  # doubling overflowed
    need_inc = lost | L.ge(r2, d_mag)
    q_inc, _ = L.add_small(q_mag, jnp.where(need_inc, 1, 0))
    return q_inc


def _divide_and_round(n_mag: jnp.ndarray, d_mag: jnp.ndarray) -> jnp.ndarray:
    q, r = L.divmod_bits(n_mag, d_mag)
    return _round_half_up(q, r, d_mag)


@partial(jax.jit, static_argnames=("a_scale", "b_scale", "prod_scale"))
def _multiply_kernel(a2c, b2c, a_scale: int, b_scale: int, prod_scale: int):
    a_mag, a_neg = L.from_twos_complement(a2c)
    b_mag, b_neg = L.from_twos_complement(b2c)
    negative = a_neg ^ b_neg

    product = L.mul(a_mag, b_mag, 8)  # [N, 8] magnitude

    # SPARK-40129 first rounding: to precision 38 by the product's own
    # precision10 (:538-553)
    dec_precision = L.precision10(product)
    first_div_precision = dec_precision - 38
    do_first = first_div_precision > 0
    divisor1 = L.pow10(jnp.maximum(first_div_precision, 0), 8)
    rounded1 = _divide_and_round(product, divisor1)
    product = jnp.where(do_first[..., None], rounded1, product)
    mult_scale = a_scale + b_scale + jnp.where(do_first, first_div_precision, 0)

    exponent = prod_scale - mult_scale

    # exponent < 0: multiply up unless it would exceed precision 38 (:556-567)
    new_precision = L.precision10(product)
    would_overflow = (exponent < 0) & (new_precision - exponent > 38)
    scale_mult = L.pow10(jnp.maximum(-exponent, 0), 8)
    multiplied = L.mul(product[..., :4], scale_mult[..., :4], 8)
    # product may exceed 4 limbs only when it will overflow anyway
    product_up = jnp.where(would_overflow[..., None], product, multiplied)

    # exponent >= 0: divide and round (:568-576)
    divisor2 = L.pow10(jnp.maximum(exponent, 0), 8)
    divided = _divide_and_round(product, divisor2)

    product = jnp.where((exponent < 0)[..., None], product_up, divided)
    overflow = would_overflow | ~_fits_128(product, negative)

    result = L.to_twos_complement(product[..., :4], negative)
    return result, overflow


@op_boundary("multiply128")
def multiply128(a: Column, b: Column, product_scale: int) -> Table:
    """Parity: DecimalUtils.multiply128 (DecimalUtils.java:40) ->
    cudf::jni::multiply_decimal128 (decimal_utils.cu:690-711)."""
    _check_inputs(a, b)
    # check_scale_divisor (:500-503)
    if product_scale - (a.dtype.scale + b.dtype.scale) > 38:
        raise ValueError("divisor too big")
    result, overflow = _multiply_kernel(
        a.data, b.data, a.dtype.scale, b.dtype.scale, product_scale
    )
    validity = _and_validity(a, b)
    return Table(
        [
            Column(dt.BOOL8, data=overflow.astype(jnp.uint8), validity=validity),
            Column(decimal128(product_scale), data=result, validity=validity),
        ],
        names=["overflow", "product"],
    )


@partial(jax.jit, static_argnames=("a_scale", "b_scale", "quot_scale"))
def _divide_kernel(a2c, b2c, a_scale: int, b_scale: int, quot_scale: int):
    n_mag4, n_neg = L.from_twos_complement(a2c)
    d_mag4, d_neg = L.from_twos_complement(b2c)
    negative = n_neg ^ d_neg
    div_by_zero = L.is_zero(d_mag4)

    pad = jnp.zeros_like(n_mag4)
    n_mag = jnp.concatenate([n_mag4, pad], axis=-1)  # [N, 8]
    d_mag = jnp.concatenate([d_mag4, pad], axis=-1)
    # avoid 0-divisor garbage inside the shared kernel; flagged at the end
    d_safe = jnp.where(div_by_zero[..., None], jnp.zeros_like(d_mag).at[..., 0].set(1), d_mag)

    n_shift_exp = quot_scale - (a_scale - b_scale)  # static int

    if n_shift_exp > 0:
        # divide twice (:617-630)
        q1, _ = L.divmod_bits(n_mag, d_safe)
        divisor = L.pow10(jnp.full(q1.shape[:-1], n_shift_exp, jnp.int32), 8)
        result = _divide_and_round(q1, divisor)
    elif n_shift_exp < -38:
        # base-10 long division via 10^38 split (:631-658)
        n38 = L.mul(n_mag4, jnp.asarray(L.from_ints([10**38], 8))[0], 8)
        q1, r1 = L.divmod_bits(n38, d_safe)
        remaining = -n_shift_exp - 38
        scale_mult = jnp.asarray(L.from_ints([10**min(remaining, 76)], 8))[0]
        # mod-2^256 products, same wrap semantics as chunked256::multiply
        result = L.mul(q1, scale_mult, 8)
        scaled_r = L.mul(r1, scale_mult, 8)
        q2, r2 = L.divmod_bits(scaled_r, d_safe)
        result, _ = L.add(result, q2)
        result = _round_half_up(result, r2, d_safe)
    else:
        # multiply then divide (:660-672)
        if n_shift_exp < 0:
            n_mag = L.mul(n_mag4, jnp.asarray(L.from_ints([10 ** (-n_shift_exp)], 8))[0], 8)
        result = _divide_and_round(n_mag, d_safe)

    overflow = div_by_zero | ~_fits_128(result, negative)
    quotient = L.to_twos_complement(result[..., :4], negative)
    quotient = jnp.where(div_by_zero[..., None], 0, quotient)
    return quotient, overflow


@op_boundary("divide128")
def divide128(a: Column, b: Column, quotient_scale: int) -> Table:
    """Parity: DecimalUtils.divide128 (DecimalUtils.java:55) ->
    cudf::jni::divide_decimal128 (decimal_utils.cu:713-733)."""
    _check_inputs(a, b)
    result, overflow = _divide_kernel(a.data, b.data, a.dtype.scale, b.dtype.scale, quotient_scale)
    validity = _and_validity(a, b)
    return Table(
        [
            Column(dt.BOOL8, data=overflow.astype(jnp.uint8), validity=validity),
            Column(decimal128(quotient_scale), data=result, validity=validity),
        ],
        names=["overflow", "quotient"],
    )

"""JCUDF row <-> columnar transcode — the flagship op family.

Behavioral parity with reference src/main/cpp/src/row_conversion.cu
(format doc: reference RowConversion.java:44-117; layout computation:
row_conversion.cu compute_column_information :1340-1378; string writes
:827-874; validity bit order :404-407):

- each row is laid out like a C struct: every fixed-width column aligned
  to its own size; STRING/LIST columns occupy an 8-byte
  ``{offset:u32, len:u32}`` slot aligned to 4 bytes,
- validity bytes follow the last column with no extra padding; bit
  ``col % 8`` of byte ``col / 8`` is set when the value is VALID,
- variable-width (string) character data follows the validity bytes;
  the u32 ``offset`` written in the slot is relative to the row start,
- every row is padded to a multiple of 8 bytes (JCUDF_ROW_ALIGNMENT),
- output is one or more LIST<INT8> columns, each holding at most 2 GiB
  (cudf ``size_type`` discipline, row_conversion.cu:67,100-105).

TPU-first design notes (NOT a kernel translation):

- The CUDA code moves bytes with warp-cooperative shared-memory tiles
  because GPU global memory wants coalesced 128B transactions. On TPU,
  XLA owns layout: we express the transcode as pure array ops
  (bitcast -> concat -> pad for fixed rows; scatter/gather with
  searchsorted row binning for ragged string rows) and let XLA fuse the
  whole thing into a handful of HBM-bandwidth-bound loops.
- All shapes are static per (schema, num_rows, total_bytes): jit caches
  one executable per size class.
- The 2 GiB batch split is host metadata (the reference also computes it
  with host synchronizations, row_conversion.cu:1465-1543).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..columnar.dtype import DType, TypeId
from ..utils.dispatch import op_boundary
from . import bitutils

__all__ = [
    "RowLayout",
    "compute_row_layout",
    "convert_to_rows",
    "convert_from_rows",
    "convert_from_rows_grouped",
    "GroupedRows",
    "convert_to_rows_fixed_width_optimized",
    "convert_from_rows_fixed_width_optimized",
]

JCUDF_ROW_ALIGNMENT = 8
MAX_BATCH_BYTES = (1 << 31) - 1  # cudf size_type limit per LIST<INT8> batch
MAX_ROW_SIZE_OPTIMIZED = 1024  # RowConversion.java:115-116
MAX_COLS_OPTIMIZED = 100  # RowConversion.java:27-34


def _round_up(v: int, align: int) -> int:
    return (v + align - 1) // align * align


@dataclasses.dataclass(frozen=True)
class RowLayout:
    """Static per-schema row layout (hashable: used as a jit static arg)."""

    col_starts: Tuple[int, ...]  # byte offset of each column's slot
    col_sizes: Tuple[int, ...]  # slot width (8 for compound columns)
    validity_offset: int  # first validity byte
    fixed_end: int  # validity_offset + validity bytes
    variable_cols: Tuple[int, ...]  # indices of STRING columns, in order
    row_size_fixed: int  # aligned row size when no variable data

    @property
    def num_columns(self) -> int:
        return len(self.col_starts)


def compute_row_layout(dtypes: Sequence[DType]) -> RowLayout:
    """Mirror of compute_column_information (row_conversion.cu:1340-1378)."""
    starts: List[int] = []
    sizes: List[int] = []
    variable: List[int] = []
    off = 0
    for i, d in enumerate(dtypes):
        if d.is_compound:
            if d.id != TypeId.STRING:
                raise ValueError(f"only STRING compound columns supported in rows, got {d!r}")
            size, align = 8, 4  # {offset:u32, len:u32}
            variable.append(i)
        elif d.is_fixed_width:
            size = d.size_bytes
            align = size
        else:
            raise ValueError(f"unsupported dtype in row conversion: {d!r}")
        off = _round_up(off, align)
        starts.append(off)
        sizes.append(size)
        off += size
    validity_offset = off
    fixed_end = off + (len(list(dtypes)) + 7) // 8
    return RowLayout(
        col_starts=tuple(starts),
        col_sizes=tuple(sizes),
        validity_offset=validity_offset,
        fixed_end=fixed_end,
        variable_cols=tuple(variable),
        row_size_fixed=_round_up(fixed_end, JCUDF_ROW_ALIGNMENT),
    )


# ---------------------------------------------------------------------------
# byte views
# ---------------------------------------------------------------------------


def _unpack_validity(vbytes: jnp.ndarray, num_cols: int) -> jnp.ndarray:
    """[N, nbytes] uint8 -> [N, num_cols] bool."""
    bits = (vbytes[:, :, None] >> jnp.arange(8, dtype=jnp.uint8)[None, None, :]) & 1
    return bits.reshape(vbytes.shape[0], -1)[:, :num_cols].astype(bool)


# ---------------------------------------------------------------------------
# fixed section assembly (shared by the fixed-only and string paths)
# ---------------------------------------------------------------------------


def _entry_plan(layout: RowLayout, dtypes: Sequence[DType]):
    """Static grouping plan: each column becomes scalar 'entries' of one
    storage dtype (DECIMAL128 -> 4 u32 limbs, STRING slot -> 2 u32s,
    others -> 1 entry). Entries group by dtype so the device program
    stacks each group ONCE — op count scales with the number of distinct
    widths, not the number of columns (the 212-column reference bench
    axis compiles flat).

    Returns (group_order, entries) where entries[i] is a list of
    (dtype_key, byte_offset_in_row) per entry of column i, in entry
    order, and group_order is the dict of dtype_key -> next free index
    (i.e. final group sizes) built in first-seen order.
    """
    groups: dict = {}
    entries: List[List[Tuple[str, int, int]]] = []  # (key, slot_index, row_byte)
    for i, d in enumerate(dtypes):
        start = layout.col_starts[i]
        col_entries = []
        if d.id == TypeId.STRING:
            for sub in range(2):  # offset, length
                idx = groups.setdefault("u4", 0)
                groups["u4"] += 1
                col_entries.append(("u4", idx, start + 4 * sub))
        elif d.id == TypeId.DECIMAL128:
            for limb in range(4):
                idx = groups.setdefault("u4", 0)
                groups["u4"] += 1
                col_entries.append(("u4", idx, start + 4 * limb))
        else:
            key = f"w{d.size_bytes}_{jnp.dtype(d.jnp_dtype).name}"
            idx = groups.setdefault(key, 0)
            groups[key] += 1
            col_entries.append((key, idx, start))
        entries.append(col_entries)
    return groups, entries


def _entry_width(key: str) -> int:
    return 4 if key == "u4" else int(key[1 : key.index("_")])


def _col_u32_parts(col: Column, var_slot_vals: dict, i: int):
    """One column's value as a list of (width_bytes, [N] u32) parts in
    row-byte order, each part holding the value's bytes in its LOW
    bits. Pure arithmetic — no narrow-minor-dim arrays anywhere."""
    d = col.dtype
    if d.id == TypeId.STRING:
        off_u32, len_u32 = var_slot_vals[i]
        return [(4, off_u32.astype(jnp.uint32)), (4, len_u32.astype(jnp.uint32))]
    if d.id == TypeId.DECIMAL128:
        limbs = col.data.T  # [4, N]: one small transpose, contiguous rows
        return [(4, limbs[k]) for k in range(4)]
    w = d.size_bytes
    if w == 8:
        u = col.data
        if jnp.issubdtype(u.dtype, jnp.floating):
            u = lax.bitcast_convert_type(u, jnp.uint64)
        u = u.astype(jnp.uint64) if u.dtype != jnp.uint64 else u
        lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (u >> jnp.uint64(32)).astype(jnp.uint32)
        return [(4, lo), (4, hi)]
    if w == 4:
        u = col.data
        if u.dtype != jnp.uint32:
            u = lax.bitcast_convert_type(u, jnp.uint32)
        return [(4, u)]
    if w == 2:
        u = lax.bitcast_convert_type(col.data, jnp.uint16).astype(jnp.uint32)
        return [(2, u)]
    # w == 1 (int8/uint8/bool)
    u = col.data
    if u.dtype == jnp.bool_:
        u = u.astype(jnp.uint32)
    else:
        u = lax.bitcast_convert_type(u, jnp.uint8).astype(jnp.uint32)
    return [(1, u)]


def _fixed_planes32(
    layout: RowLayout,
    cols: Sequence[Column],
    var_slot_vals: dict,
    pad_to: int,
) -> jnp.ndarray:
    """[ceil(pad_to/4), N] uint32 PLANE STACK: lane p holds bytes
    [4p, 4p+4) of every row (column slots + padding + validity), as
    little-endian u32 words.

    TPU-layout-aware build: every interleave formulation that writes
    narrow lane slices ([N, w] pieces into a wide row) runs at ~0.3 GB/s
    on TPU — sub-128-lane writes waste 64x+ of each vector store (three
    designs measured: static-permutation take, ordered 160-piece concat,
    per-group stack). Instead each u32 LANE of the row is composed
    arithmetically as a contiguous [N] plane and the planes stack along
    axis 0 (dense memcpy). Callers either transpose ONCE to row-major
    ([P, N] -> [N, P], measured ~590 GB/s r+w chained; _fixed_section32)
    or feed the stack straight to the sublane-expand kernel
    (ragged_bytes.expand_u32_planes) whose u8 transpose is cheaper."""
    n = len(cols[0]) if cols else 0
    num_lanes = (pad_to + 3) // 4
    plane_parts: List[List[jnp.ndarray]] = [[] for _ in range(num_lanes)]

    def _emit(byte_off: int, val_u32: jnp.ndarray):
        lane, sub = divmod(byte_off, 4)
        if lane >= num_lanes:
            return
        if sub:
            val_u32 = val_u32 << jnp.uint32(8 * sub)
        plane_parts[lane].append(val_u32)

    for i, col in enumerate(cols):
        pos = layout.col_starts[i]
        for width, val in _col_u32_parts(col, var_slot_vals, i):
            _emit(pos, val)
            pos += width

    # validity bytes, composed from transposed per-column masks — byte
    # b's bit c%8 is column 8b+c's valid bit
    if cols:
        valid_t = jnp.stack([c.valid_mask() for c in cols], axis=0)  # [C, N]
        for b in range((len(cols) + 7) // 8):
            byte = jnp.zeros((n,), jnp.uint32)
            for bit in range(8):
                c = 8 * b + bit
                if c < len(cols):
                    byte = byte | (valid_t[c].astype(jnp.uint32) << jnp.uint32(bit))
            _emit(layout.validity_offset + b, byte)

    zero = jnp.zeros((n,), jnp.uint32)
    planes = [_or_compose(parts, zero) for parts in plane_parts]
    return jnp.stack(planes, axis=0) if planes else jnp.zeros((0, n), jnp.uint32)


def _fixed_section32(
    layout: RowLayout,
    cols: Sequence[Column],
    var_slot_vals: dict,
    pad_to: int,
) -> jnp.ndarray:
    """[N, ceil(pad_to/4)] u32 row-major lanes (see _fixed_planes32)."""
    return _fixed_planes32(layout, cols, var_slot_vals, pad_to).T


def _or_compose(parts: List[jnp.ndarray], zero: jnp.ndarray) -> jnp.ndarray:
    """OR-compose a lane's (disjoint) shifted byte parts."""
    if not parts:
        return zero
    out = parts[0]
    for v in parts[1:]:
        out = out | v
    return out


def _fixed_section(
    layout: RowLayout,
    cols: Sequence[Column],
    var_slot_vals: dict,
    pad_to: int,
) -> jnp.ndarray:
    """[N, pad_to] uint8 view of _fixed_section32 (byte-level callers —
    the scatter fallback). The u32->u8 bitcast goes through the chunked
    converter: whole-array 2-D bitcasts materialize a 32x tile-padded
    temp, worst exactly on the huge inputs this fallback serves."""
    from .ragged_bytes import u32_rows_to_u8_flat

    n = len(cols[0]) if cols else 0
    f32 = _fixed_section32(layout, cols, var_slot_vals, pad_to)
    by = u32_rows_to_u8_flat(f32).reshape(n, -1)
    return by[:, :pad_to]


# ---------------------------------------------------------------------------
# convert_to_rows
# ---------------------------------------------------------------------------


def _batch_boundaries(row_sizes: np.ndarray) -> List[Tuple[int, int, int]]:
    """Split rows into <=2GiB batches: list of (row_start, row_end, nbytes).

    Mirror of build_batches (row_conversion.cu:1465-1543): greedy scan of
    cumulative row sizes against the size_type ceiling.
    """
    n = len(row_sizes)
    if n == 0:
        return [(0, 0, 0)]
    cum = np.concatenate([[0], np.cumsum(row_sizes, dtype=np.int64)])
    batches = []
    start = 0
    while start < n:
        end = int(np.searchsorted(cum, cum[start] + MAX_BATCH_BYTES, side="right")) - 1
        if end == start:
            raise ValueError(f"row {start} larger than 2GiB batch limit")
        end = min(end, n)
        batches.append((start, end, int(cum[end] - cum[start])))
        start = end
    return batches


def _to_rows_fixed(layout: RowLayout, cols: Sequence[Column], n: int) -> jnp.ndarray:
    """All-fixed-width table -> [N * row_size] uint8 blob.

    TPU: plane stack [P, N] -> sublane-expand kernel -> u8 transpose ->
    flatten (the u32 transpose is skipped entirely; round-3 profile
    took this axis from 50.8 ms to ~9 ms at 1M x 212). Elsewhere: the
    row-major u32 section + chunked bitcast."""
    from .ragged_bytes import _use_pallas, expand_u32_planes, u32_rows_to_u8_flat

    if _use_pallas() and n >= 8:
        planes = _fixed_planes32(layout, cols, {}, layout.row_size_fixed)
        return expand_u32_planes(planes).T.reshape(-1)
    f32 = _fixed_section32(layout, cols, {}, layout.row_size_fixed)
    return u32_rows_to_u8_flat(f32)


def _var_maxlens(layout: RowLayout, cols: Sequence[Column]) -> Tuple[int, ...]:
    return tuple(cols[i].max_char_len for i in layout.variable_cols)


# Padded-row memory amplification cap for the fast mixed path: the
# padded RP matrix costs N * (fixed_end + maxvar) bytes, so one huge
# outlier string must not blow device memory (fall back to the scatter
# path instead, which is slow but O(actual bytes)).
_PADDED_ROWS_BYTE_BUDGET = 4 << 30


@partial(jax.jit, static_argnums=(0,))
def _jit_fixed_and_slots(layout: RowLayout, cols: Tuple[Column, ...]):
    """Fixed sections (u32 lanes) + per-row string slot values, one
    program."""
    n = len(cols[0])
    var_cols = [cols[i] for i in layout.variable_cols]
    lens = [c.offsets[1:] - c.offsets[:-1] for c in var_cols]
    var_starts = []
    acc = jnp.full((n,), layout.fixed_end, dtype=jnp.int32)
    for ln in lens:
        var_starts.append(acc)
        acc = acc + ln
    slot_vals = {
        ci: (var_starts[k].astype(jnp.uint32), lens[k].astype(jnp.uint32))
        for k, ci in enumerate(layout.variable_cols)
    }
    fixed32 = _fixed_section32(layout, cols, slot_vals, layout.fixed_end)
    return fixed32, tuple(var_starts), tuple(lens)


@partial(jax.jit, static_argnums=(5, 6, 7))
def _jit_var_section(
    chars: Tuple[jnp.ndarray, ...],
    starts: Tuple[jnp.ndarray, ...],
    lens: Tuple[jnp.ndarray, ...],
    shifts: Tuple[jnp.ndarray, ...],
    tail_lane,  # [N] u32 partial fixed lane when fixed_end % 4 != 0
    tail_bytes: int,
    maxlens: Tuple[int, ...],
    maxvar: int,
):
    """All string columns -> the [N, maxvar/4] u32 variable REGION in
    ONE program: per-column padded extraction (windowed tile gather +
    Pallas rotate), then one Pallas accumulation pass whose shift
    ladders live in VMEM — as plain XLA the ladders materialize
    O(log(maxvar) * cols) full-width HLO temps at once (35 GB / OOM at
    the 155-col x 1M axis, observed), and per-column dispatches cost a
    tunnel round trip each.

    The region starts at byte 4*(fixed_end//4): when fixed_end is not
    lane-aligned, the trailing validity bytes (``tail_lane``) ride in
    as a pseudo-column at shift 0 so the u32 pipeline never needs a
    sub-lane boundary between the fixed and variable parts."""
    from .ragged_bytes import _pow2_ceil, padded_extract, var_accumulate

    p_mats, all_shifts = [], []
    if tail_bytes:
        tail = lax.bitcast_convert_type(tail_lane[:, None], jnp.uint8).reshape(-1, 4)
        mask = (jnp.arange(4, dtype=jnp.int32) < tail_bytes)[None, :]
        p_mats.append(jnp.where(mask, tail, 0))
        all_shifts.append(jnp.zeros((tail_lane.shape[0],), jnp.int32))
    # Serialize the per-column extractions ONLY under memory pressure:
    # each padded matrix is N * pow2(maxlen) bytes and the tile windows
    # another ~2x the char payload; when all K coexist a wide axis can
    # tip over HBM (~4 GB observed at 155-col x 1M with large
    # maxlens) — but forcing N sequential kernels costs real wall time,
    # so small extractions stay concurrent.
    n_rows = tail_lane.shape[0]
    est = sum(
        n_rows * max(_pow2_ceil(min(_round_up(maxlens[k], 4), maxvar)), 4)
        + 2 * int(chars[k].shape[0])
        for k in range(len(chars))
    )
    serialize = est > (1 << 30)
    seq = None
    for k in range(len(chars)):
        lc = min(_round_up(maxlens[k], 4), maxvar)
        st = starts[k].astype(jnp.int64)
        if serialize and seq is not None:
            st = st + (seq[0, 0].astype(jnp.int64) & 0)
        p = padded_extract(chars[k], st, maxlens[k])[:, :lc]
        p = jnp.where(jnp.arange(lc, dtype=jnp.int32)[None, :] < lens[k][:, None], p, 0)
        if serialize:
            p = lax.optimization_barrier(p)
            seq = p
        p_mats.append(p)
        all_shifts.append(shifts[k])
    return var_accumulate(tuple(p_mats), tuple(all_shifts), maxvar)


@partial(jax.jit, static_argnums=(3, 4))
def _jit_assemble(fixed32, var32, row_offsets, total_bytes: int, min_row: int):
    from .ragged_bytes import assemble_rows

    sizes = row_offsets[1:] - row_offsets[:-1]
    return assemble_rows((fixed32, var32), sizes, row_offsets, total_bytes, min_row)


_FUSED_ENCODE_BROKEN = False


def _encode_strings_impl(
    layout: RowLayout,
    cols: Tuple[Column, ...],
    row_offsets: jnp.ndarray,
    total_bytes: int,
    maxlens: Tuple[int, ...],
    maxvar: int,
) -> jnp.ndarray:
    """Shared staging body for the mixed encode. Called DIRECTLY, each
    stage function's own jit gives the staged pipeline (one dispatch
    per stage); called under _jit_encode_strings_fused, the nested jits
    inline into ONE program."""
    var_cols = [cols[i] for i in layout.variable_cols]
    fixed32, var_starts, lens = _jit_fixed_and_slots(layout, tuple(cols))
    n = len(cols[0])

    # the u32 variable REGION starts at the last lane boundary <=
    # fixed_end; string shifts are relative to it, and any partial
    # fixed lane's validity bytes ride in as a pseudo column
    fe4 = layout.fixed_end // 4
    rem = layout.fixed_end % 4
    region = _round_up(rem + maxvar, 64)
    tail_lane = fixed32[:, fe4] if rem else jnp.zeros((n,), jnp.uint32)

    chars, starts, lens_in, shifts, mls = [], [], [], [], []
    for k, col in enumerate(var_cols):
        if maxlens[k] == 0:
            continue
        chars.append(col.chars)
        starts.append(col.offsets[:-1])
        lens_in.append(lens[k])
        shifts.append(var_starts[k] - 4 * fe4)
        # maxlens are table-global; a batch slice's local maximum is
        # bounded by its own maxvar, so clamping is lossless — and
        # required: the padded-extract gather width is sized by this
        # value, so an outlier string in ANOTHER batch must not inflate
        # this batch's temporaries
        mls.append(min(maxlens[k], maxvar))

    if not chars and not rem:
        var32 = jnp.zeros((n, region // 4), jnp.uint32)
    else:
        var32 = _jit_var_section(
            tuple(chars), tuple(starts), tuple(lens_in), tuple(shifts),
            tail_lane, rem, tuple(mls), region,
        )
    fixed_part = fixed32[:, :fe4] if rem else fixed32  # avoid a 1 GB slice copy
    return _jit_assemble(
        fixed_part, var32, row_offsets, total_bytes,
        _round_up(layout.fixed_end, JCUDF_ROW_ALIGNMENT),
    )


@partial(jax.jit, static_argnums=(0, 3, 4, 5))
def _jit_encode_strings_fused(
    layout: RowLayout,
    cols: Tuple[Column, ...],
    row_offsets: jnp.ndarray,
    total_bytes: int,
    maxlens: Tuple[int, ...],
    maxvar: int,
) -> jnp.ndarray:
    """The whole mixed encode as ONE program (nested stage jits inline)
    — the staged pipeline minus three dispatch round trips (~90 ms each
    through the dev tunnel)."""
    return _encode_strings_impl(layout, cols, row_offsets, total_bytes, maxlens, maxvar)


def _to_rows_strings_padded(
    layout: RowLayout,
    cols: Tuple[Column, ...],
    row_offsets: jnp.ndarray,  # [N+1] int64 dst offsets (cumsum of sizes)
    total_bytes: int,
    maxlens: Tuple[int, ...],  # static per-string-col max byte length
    maxvar: int,  # static padded width of the variable section
) -> jnp.ndarray:
    """Mixed fixed+string table -> [total_bytes] u8 blob, ALL regular
    ops (ops/ragged_bytes design memo): replaces the element-granular
    scatters that ran this axis at 0.016 GB/s.

    1. fixed sections assemble as before ([N, fixed_end]),
    2. each string column extracts to a padded [N, L_k] matrix with ONE
       overlapping-tile gather + per-row rotate (~100 GB/s measured),
    3. the variable section accumulates by per-row byte shifts (strings
       are disjoint per row, so sum == placement),
    4. padded rows compact to the exact 8-aligned ragged blob with the
       dst-centric two-source tile assembly (monotonic gathers).

    The reference does step 2-4 with a warp-per-row memcpy
    (row_conversion.cu:827-874); on TPU the same movement is expressed
    as gathers of fixed-width tiles + lane arithmetic. The fused
    single-program form is tried first (dispatch count 2 instead of 5);
    a compile/runtime failure — very wide axes have crashed the XLA:TPU
    compiler on fully fused forms (round-3 observation) — demotes the
    process to the staged pipeline, whose stage outputs are genuine
    materialization points.
    """
    n = len(cols[0])
    # ONE fused program for fixed+slots+var+assemble (3 fewer ~90 ms
    # dispatches through a remote tunnel); very wide axes have crashed
    # the XLA:TPU compiler on the fully fused form before (round-3
    # observation), so a compile failure falls back to the staged path
    global _FUSED_ENCODE_BROKEN
    if not _FUSED_ENCODE_BROKEN:
        try:
            out = _jit_encode_strings_fused(
                layout, tuple(cols), row_offsets, total_bytes, maxlens, maxvar
            )
            # force execution INSIDE the try: async dispatch would defer
            # a runtime failure past this handler and the fallback would
            # never engage
            return jax.block_until_ready(out)
        except Exception as e:  # noqa: BLE001  # srjt-lint: allow-broad-except(any fused-program failure engages the staged fallback; see the latch note below)
            # any fused failure must engage the staged fallback
            # (round-3: wide axes crashed the XLA:TPU compiler;
            # trace-time failures can surface as
            # TypeError/NotImplementedError on other backends)
            import logging

            # A transient RESOURCE_EXHAUSTED (memory pressure from a
            # concurrent batch) must not demote every later encode in
            # the process: fall back for THIS call only and retry the
            # fused form next time. Everything else latches once per
            # process.
            transient = "RESOURCE_EXHAUSTED" in str(e)
            logging.getLogger(__name__).warning(
                "fused string-encode program failed (%s: %s); falling "
                "back to the staged pipeline %s",
                type(e).__name__,
                e,
                "for this call" if transient else "for this process",
            )
            if not transient:
                _FUSED_ENCODE_BROKEN = True  # pay the probe once per process

    return _encode_strings_impl(layout, cols, row_offsets, total_bytes, maxlens, maxvar)


def _to_rows_strings(
    layout: RowLayout,
    cols: Sequence[Column],
    row_offsets: jnp.ndarray,  # [N] int64 dest offset of each row in blob
    total_bytes: int,
) -> jnp.ndarray:
    """Mixed fixed+string table -> [total_bytes] uint8 blob.

    Scatter FALLBACK for tables whose padded-row form would exceed the
    device-memory budget (huge outlier strings): element-granular, slow,
    but O(actual bytes). The hot path is _to_rows_strings_padded.
    """
    n = len(cols[0])
    var_cols = [cols[i] for i in layout.variable_cols]
    lens = [c.offsets[1:] - c.offsets[:-1] for c in var_cols]  # [N] int32 each

    # dest offset (relative to row start) where each string col's chars land:
    # fixed_end + sum of lengths of preceding string cols in the same row.
    var_starts = []
    acc = jnp.full((n,), layout.fixed_end, dtype=jnp.int32)
    for ln in lens:
        var_starts.append(acc)
        acc = acc + ln

    slot_vals = {
        ci: (var_starts[k].astype(jnp.uint32), lens[k].astype(jnp.uint32))
        for k, ci in enumerate(layout.variable_cols)
    }
    fixed = _fixed_section(layout, cols, slot_vals, layout.fixed_end)

    blob = jnp.zeros((total_bytes,), dtype=jnp.uint8)
    # scatter the fixed section in row chunks: the [rows, fixed_end]
    # index matrix is O(total fixed bytes) — materialized whole it is a
    # multi-GB HLO temp at the 155-col x 1M mixed axis (compile-time
    # OOM); ~64MB of indices per scatter keeps the temp bounded
    chunk = max(1, (64 << 20) // 8 // max(layout.fixed_end, 1))  # bytes of i64 indices
    span = jnp.arange(layout.fixed_end, dtype=jnp.int64)[None, :]
    for r0 in range(0, n, chunk):
        r1 = min(r0 + chunk, n)
        fixed_idx = row_offsets[r0:r1, None] + span
        blob = blob.at[fixed_idx.reshape(-1)].set(
            fixed[r0:r1].reshape(-1), mode="drop"
        )

    for k, col in enumerate(var_cols):
        nchars = int(col.chars.shape[0])
        if nchars == 0:
            continue
        offs = col.offsets  # [N+1] int32
        j = jnp.arange(nchars, dtype=jnp.int32)
        row_of = jnp.searchsorted(offs, j, side="right").astype(jnp.int32) - 1
        dest = (
            row_offsets[row_of]
            + var_starts[k][row_of].astype(jnp.int64)
            + (j - offs[row_of]).astype(jnp.int64)
        )
        blob = blob.at[dest].set(col.chars, mode="drop")
    return blob


def _wrap_batch_as_list_column(
    blob: jnp.ndarray, rel_offsets: jnp.ndarray, uniform_stride: int = 0
) -> Column:
    child = Column(dt.INT8, data=lax.bitcast_convert_type(blob, jnp.int8))
    col = Column(dt.LIST, offsets=rel_offsets.astype(jnp.int32), child=child)
    if uniform_stride:
        # producer-known constant row stride: lets the decoder skip the
        # uniformity probe entirely (a blocking device sync — ~90 ms of
        # fixed RPC latency through a remote tunnel). Host metadata,
        # deliberately NOT part of the pytree: it is a cache, not data.
        col._uniform_stride = uniform_stride
    return col


@op_boundary("convert_to_rows")
def convert_to_rows(table: Table) -> List[Column]:
    """Table -> one or more LIST<INT8> columns of JCUDF rows.

    Parity: RowConversion.convertToRows (RowConversion.java:35) ->
    spark_rapids_jni::convert_to_rows (row_conversion.cu:1903-1959).
    """
    layout = compute_row_layout(table.dtypes())
    n = table.num_rows
    cols = table.columns

    if n == 0:
        return [_wrap_batch_as_list_column(jnp.zeros((0,), jnp.uint8), jnp.zeros((1,), jnp.int32))]

    if not layout.variable_cols:
        row_size = layout.row_size_fixed
        row_sizes = np.full((n,), row_size, dtype=np.int64)
        batches = _batch_boundaries(row_sizes)
        out = []
        for rs, re, _ in batches:
            if len(batches) <= 4:
                # STATIC batch offsets: XLA folds the slice into the
                # relayout kernel's first read instead of materializing
                # a sliced copy of all 212 columns — the traced-offset
                # form cost the >2GiB axis an extra full pass (r4:
                # 23.3 GB/s at 4M vs 72.9 at 1M; VERDICT r4 item 5).
                # One compile per (length, offset) pair; bounded by the
                # <=4 batch cap (~8 GiB of rows), past which the
                # traced-offset program keeps compile count at O(1).
                blob = _jit_to_rows_fixed_static(layout, tuple(cols), rs, re - rs)
            else:
                blob = _jit_to_rows_fixed_sliced(layout, tuple(cols), rs, re - rs)
            rel = jnp.arange(re - rs + 1, dtype=jnp.int32) * row_size
            out.append(_wrap_batch_as_list_column(blob, rel, uniform_stride=row_size))
        return out

    # string path: per-row sizes -> batch split -> encode per batch.
    # ONE jitted program for the sizes, and the host pull is kept to
    # TWO SCALARS (total, max) in the common single-batch case — the
    # eager per-column accumulation plus the full [N] i64 pull cost
    # ~1.0 s of the 1.6 s mixed-axis call through a remote tunnel
    # (round-3 profile); offsets stay on device.
    var_offs = tuple(cols[i].offsets for i in layout.variable_cols)
    sizes_dev, offsets_dev, stats = _jit_row_size_stats(layout, var_offs)
    total, max_size = (int(v) for v in np.asarray(stats))  # host sync
    maxlens = _var_maxlens(layout, cols)

    if total <= MAX_BATCH_BYTES:  # single batch: no further host pulls
        row_offsets = offsets_dev
        maxvar = max(_round_up(max_size - layout.fixed_end, 64), 8)
        if n * (layout.fixed_end + maxvar) <= _PADDED_ROWS_BYTE_BUDGET:
            blob = _to_rows_strings_padded(
                layout, tuple(cols), row_offsets, total, maxlens, maxvar
            )
        else:  # huge outlier strings: padded form would OOM
            blob = _to_rows_strings(layout, cols, row_offsets[:-1], total)
        return [_wrap_batch_as_list_column(blob, row_offsets)]

    row_sizes = np.asarray(sizes_dev)  # host sync: full batch metadata
    batches = _batch_boundaries(row_sizes)
    out = []
    for rs, re, nbytes in batches:
        batch_cols = [_slice_column(c, rs, re) for c in cols]
        sizes = jnp.asarray(row_sizes[rs:re], dtype=jnp.int64)
        row_offsets = jnp.concatenate([jnp.zeros((1,), jnp.int64), jnp.cumsum(sizes)])
        # static padded width of the var section, bucketed to 64B so
        # batches of similar shape share one compiled program
        max_size = int(row_sizes[rs:re].max())
        maxvar = max(_round_up(max_size - layout.fixed_end, 64), 8)
        if (re - rs) * (layout.fixed_end + maxvar) <= _PADDED_ROWS_BYTE_BUDGET:
            blob = _to_rows_strings_padded(
                layout, tuple(batch_cols), row_offsets, nbytes, maxlens, maxvar
            )
        else:  # huge outlier strings: padded form would OOM
            blob = _to_rows_strings(layout, batch_cols, row_offsets[:-1], nbytes)
        out.append(_wrap_batch_as_list_column(blob, row_offsets))
    return out


@partial(jax.jit, static_argnums=(0,))
def _jit_row_size_stats(layout: RowLayout, var_offsets: Tuple[jnp.ndarray, ...]):
    """([N] int64 8-aligned row sizes ON DEVICE, [2] {sum, max}) for the
    string path, one program — the caller pulls only the two scalars
    unless the table spans multiple 2 GiB batches."""
    n = var_offsets[0].shape[0] - 1
    lens_total = jnp.zeros((n,), dtype=jnp.int64)
    for offs in var_offsets:
        lens_total = lens_total + (offs[1:] - offs[:-1]).astype(jnp.int64)
    sizes = (
        (lens_total + layout.fixed_end + JCUDF_ROW_ALIGNMENT - 1)
        // JCUDF_ROW_ALIGNMENT
        * JCUDF_ROW_ALIGNMENT
    )
    offsets = jnp.concatenate([jnp.zeros((1,), jnp.int64), jnp.cumsum(sizes)])
    return sizes, offsets, jnp.stack([jnp.sum(sizes), jnp.max(sizes)])




def _slice_column(col: Column, rs: int, re: int) -> Column:
    if rs == 0 and re == len(col):
        return col
    v = None if col.validity is None else col.validity[rs:re]
    if col.dtype.id == TypeId.STRING:
        offs = col.offsets[rs : re + 1]
        base, end = offs[0], offs[-1]
        chars = lax.dynamic_slice_in_dim(col.chars, base, int(end - base))
        return Column(col.dtype, validity=v, offsets=offs - base, chars=chars)
    return Column(col.dtype, data=col.data[rs:re], validity=v)


# ---------------------------------------------------------------------------
# convert_from_rows
# ---------------------------------------------------------------------------


@op_boundary("convert_from_rows")
def convert_from_rows(rows: Column, dtypes: Sequence[DType]) -> Table:
    """LIST<INT8> column of JCUDF rows + schema -> Table.

    Parity: RowConversion.convertFromRows (RowConversion.java:137) ->
    convert_from_rows (row_conversion.cu:2031-2252).
    """
    if rows.dtype.id != TypeId.LIST:
        raise ValueError("convert_from_rows expects a LIST<INT8> column")
    dtypes = list(dtypes)
    layout = compute_row_layout(dtypes)
    n = len(rows)
    blob = lax.bitcast_convert_type(rows.child.data, jnp.uint8)
    starts = rows.offsets[:-1].astype(jnp.int64)

    if n == 0:
        return Table([_empty_column(d) for d in dtypes])

    uniform = _offsets_uniform(rows, blob.shape[0], layout.row_size_fixed, n)
    if uniform:
        # constant row stride (always true for all-fixed-width tables we
        # produced): the row gather is a free reshape + static slice,
        # fused with the group decode in one program
        col_datas, valid = _decode_fixed_uniform(layout, tuple(dtypes), blob)
        return _assemble_from_rows(dtypes, col_datas, valid, blob, starts, n)
    fixed = _gather_fixed(layout, blob, starts, n)
    col_datas, valid = _decode_fixed_cols(layout, tuple(dtypes), fixed)
    return _assemble_from_rows(dtypes, col_datas, valid, blob, starts, n)


def _gather_fixed(layout: RowLayout, blob, starts, n: int):
    """Gather each row's fixed section out of a ragged blob: [N, fixed_end] u8.

    The naive [N, fixed_end] index-matrix gather materializes an i64
    index array as big as 8x the fixed bytes (OOM at 1M x 1012 on a
    16 GB chip, observed round 3): on TPU the rows come out of ONE
    overlapping-tile gather + Pallas rotate (padded_extract), elsewhere
    the index matrix is chunked to ~64 MB."""
    fe = layout.fixed_end
    if not layout.variable_cols:
        return _jit_gather_fixed(blob, starts, fe, n)
    from .ragged_bytes import _use_pallas

    if _use_pallas() and n >= 8:
        return _jit_padded_gather(blob, starts, fe)
    chunk = max(1, (64 << 20) // 8 // max(fe, 1))
    span = jnp.arange(fe, dtype=jnp.int64)[None, :]
    parts = []
    for r0 in range(0, n, chunk):
        idx = starts[r0 : min(r0 + chunk, n), None] + span
        parts.append(blob[idx])
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


@partial(jax.jit, static_argnums=(2,))
def _jit_padded_gather(blob, starts, fixed_end: int):
    from .ragged_bytes import padded_extract

    return padded_extract(blob, starts, fixed_end)[:, :fixed_end]


@jax.jit
def _offsets_uniform_probe(offsets, stride):
    return (offsets[0] == 0) & jnp.all(offsets[1:] - offsets[:-1] == stride)


def _offsets_uniform(rows: Column, blob_len: int, stride: int, n: int) -> bool:
    """Constant-row-stride check. Prefer the producer-attached stride
    metadata (zero syncs); otherwise reduce ON DEVICE and pull one
    scalar — pulling the whole offsets array would move 8B/row over the
    runtime, and even the scalar sync costs a full RPC round trip on a
    remote tunnel, which is why the metadata path matters."""
    if blob_len != n * stride:
        return False
    known = getattr(rows, "_uniform_stride", None)
    if known is not None:
        return known == stride
    return bool(_offsets_uniform_probe(rows.offsets, jnp.asarray(stride, rows.offsets.dtype)))


def _finish_column(d: DType, data, vmask, blob, starts) -> Column:
    """Wrap one decoded column's device data as a Column (strings gather
    their character bytes out of the row blob here)."""
    if d.id == TypeId.STRING:
        from .ragged_bytes import ragged_compact

        in_off, ln32 = data
        in_off = in_off.astype(jnp.int64)
        ln = ln32.astype(jnp.int32)
        offs = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(ln, dtype=jnp.int32)])
        total = int(offs[-1])  # host sync: chars allocation size
        chars = ragged_compact(blob, starts + in_off, offs.astype(jnp.int64), total)
        return Column(d, validity=vmask, offsets=offs, chars=chars)
    return Column(d, data=data, validity=vmask)


@jax.jit
def _jit_string_offsets(lns: Tuple[jnp.ndarray, ...]):
    """Per-string-column output offsets + a [K] totals vector, ONE
    program (the per-column `int(offs[-1])` syncs cost a full tunnel
    round trip each — 16 of them dominated the mixed decode)."""
    offs = tuple(
        jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(ln, dtype=jnp.int32)])
        for ln in lns
    )
    return offs, jnp.stack([o[-1] for o in offs])


@partial(jax.jit, static_argnums=(0,))
def _jit_string_chars(
    totals: Tuple[int, ...],
    blob: jnp.ndarray,
    starts: jnp.ndarray,
    in_offs: Tuple[jnp.ndarray, ...],
    offs: Tuple[jnp.ndarray, ...],
):
    """All string columns' character gathers in ONE compiled program
    (compile count and dispatch count stop scaling with the string
    column count).

    Round 4: each column's chars come out via ragged_compact — the
    word-granular compaction (2 monotone u64 gathers + funnel per 8
    output bytes, ~2 ns/byte) that replaces the per-BYTE u8 element
    gather (~8 ns/byte at 0.034 GB/s measured; the axis's 7.5 s floor
    in round 3). Dst offsets are dense cumsums and row bases
    (starts[r] + in_off[r]) are monotone over rows, exactly
    ragged_compact's contract. Reference analog: the warp-per-row
    copy_strings_from_rows (row_conversion.cu:1141)."""
    from .ragged_bytes import build_pool32, ragged_compact

    pool32 = build_pool32(blob) if any(totals) else None  # ONCE per blob
    outs = []
    for k, total in enumerate(totals):
        if total == 0:
            outs.append(jnp.zeros((0,), jnp.uint8))
            continue
        base = starts + in_offs[k]
        outs.append(
            ragged_compact(blob, base, offs[k].astype(jnp.int64), total, pool32=pool32)
        )
    return tuple(outs)


def _pallas_string_chars(totals, blob, starts, in_offs, offs, mode):
    """Kernel-tier string decode (ISSUE 13): every string column's
    chars through the FUSED pallas_ragged_compact kernel — the offset
    walk, windowed byte gather, boundary masking, and head merge run
    in-VMEM instead of materializing the XLA formulation's per-column
    scatter/gather intermediates in HBM. The per-column window probes
    batch into ONE host sync (the _jit_string_offsets discipline: 16
    per-column syncs dominated the mixed decode through a remote
    tunnel). Returns None when any column's probed windows exceed the
    kernel caps — the caller keeps the fused XLA program."""
    from .pallas_kernels import pallas_decode_probe, pallas_ragged_compact
    from .ragged_bytes import build_pool32

    live = [k for k, t in enumerate(totals) if t > 0]
    bases = {}
    offs64 = {}
    probes = []
    for k in live:
        bases[k] = starts + in_offs[k]
        offs64[k] = offs[k].astype(jnp.int64)
        probes.append(pallas_decode_probe(bases[k], offs64[k], totals[k]))
    if not live:
        return tuple(jnp.zeros((0,), jnp.uint8) for _ in totals)
    hints = np.asarray(jnp.stack(probes))  # ONE host sync for all columns
    pool32 = build_pool32(blob)  # ONCE per blob
    outs = [jnp.zeros((0,), jnp.uint8)] * len(totals)
    for j, k in enumerate(live):
        out = pallas_ragged_compact(
            blob, bases[k], offs64[k], totals[k], pool32=pool32,
            interpret=mode == "interpret", hint=hints[j],
        )
        if out is None:
            return None
        outs[k] = out
    return tuple(outs)


def _assemble_from_rows(dtypes, col_datas, valid_cols, blob, starts, n) -> Table:
    from ..utils import metrics
    from ..utils.dispatch import note_tier
    from .pallas_kernels import kernel_tier_mode

    str_idx = [i for i, d in enumerate(dtypes) if d.id == TypeId.STRING]
    prebuilt = {}
    if str_idx and n > 0:
        lns = tuple(col_datas[i][1].astype(jnp.int32) for i in str_idx)
        offs, totals_dev = _jit_string_offsets(lns)
        totals = tuple(int(t) for t in np.asarray(totals_dev))  # ONE host sync
        in_offs = tuple(col_datas[i][0].astype(jnp.int64) for i in str_idx)
        chars = None
        mode = kernel_tier_mode("SRJT_PALLAS_DECODE")
        if mode:
            try:
                chars = _pallas_string_chars(
                    totals, blob, starts, in_offs, offs, mode
                )
            except Exception:  # srjt-lint: allow-broad-except(kernel-tier contract: any kernel failure degrades to the fused XLA decode, never errors the op)
                chars = None
                metrics.event(
                    "dispatch.tier_degrade", op="string_decode", tier=mode
                )
                note_tier("degrade", "string_decode")
        if chars is not None:
            note_tier("pallas", "string_decode")
        else:
            note_tier("xla", "string_decode")
            chars = _jit_string_chars(totals, blob, starts, in_offs, offs)
        for k, i in enumerate(str_idx):
            prebuilt[i] = Column(
                dtypes[i], validity=valid_cols[i], offsets=offs[k], chars=chars[k]
            )
    return Table(
        [
            prebuilt[i]
            if i in prebuilt
            else _finish_column(d, col_datas[i], valid_cols[i], blob, starts)
            for i, d in enumerate(dtypes)
        ]
    )


@dataclasses.dataclass
class GroupedRows:
    """Decoded JCUDF rows in the width-grouped device layout.

    The TPU-first counterpart of ``convert_from_rows``
    (row_conversion.cu:2031-2252 materializes one cudf column per schema
    entry): here the decode runs as ONE program producing O(distinct
    widths) device arrays, and per-column materialization is deferred.
    Fused query pipelines should consume ``groups``/``valid_t``
    directly; ``column(i)`` / ``to_table()`` materialize the
    ColumnVector-shaped contract on demand. The grouped form keeps the
    decode a single dispatch with O(width-groups) outputs — the form a
    downstream fused program can consume without 2*num_columns buffer
    round-trips through the runtime.
    """

    dtypes: Tuple[DType, ...]
    layout: RowLayout
    groups: dict  # width-group key -> [k, N] typed lanes (transposed)
    valid_t: jnp.ndarray  # [C, N] bool
    blob: jnp.ndarray  # [total_bytes] u8 row blob (string chars live here)
    starts: jnp.ndarray  # [N] i64 row start offsets

    def __len__(self) -> int:
        return int(self.valid_t.shape[1])

    def column(self, i: int) -> Column:
        """Materialize a single column (eager; for selective access)."""
        if len(self) == 0:
            return _empty_column(self.dtypes[i])
        _, entries = _entry_plan(self.layout, self.dtypes)
        d = self.dtypes[i]
        data, vmask = _extract_column(self.groups, self.valid_t, entries, i, d)
        return _finish_column(d, data, vmask, self.blob, self.starts)

    def to_table(self) -> Table:
        """Materialize every column through ONE jitted extraction (a
        per-column eager loop would re-pay the O(columns) dispatch
        overhead this representation exists to avoid)."""
        if len(self) == 0:
            return Table([_empty_column(d) for d in self.dtypes])
        col_datas, valids = _extract_all(
            self.layout, self.dtypes, tuple(self.groups), tuple(self.groups.values()),
            self.valid_t,
        )
        return _assemble_from_rows(
            self.dtypes, col_datas, valids, self.blob, self.starts, len(self)
        )


@partial(jax.jit, static_argnums=(0, 1, 2))
def _extract_all(layout, dtypes, group_keys, garrs, valid_t):
    groups = dict(zip(group_keys, garrs))
    _, entries = _entry_plan(layout, dtypes)
    col_datas, valids = [], []
    for i, d in enumerate(dtypes):
        data, v = _extract_column(groups, valid_t, entries, i, d)
        col_datas.append(data)
        valids.append(v)
    return tuple(col_datas), tuple(valids)


@op_boundary("convert_from_rows_grouped")
def convert_from_rows_grouped(rows: Column, dtypes: Sequence[DType]) -> GroupedRows:
    """LIST<INT8> rows + schema -> GroupedRows (one compiled program,
    no per-column buffers). See GroupedRows for when to prefer this
    over ``convert_from_rows``."""
    if rows.dtype.id != TypeId.LIST:
        raise ValueError("convert_from_rows_grouped expects a LIST<INT8> column")
    dtypes = tuple(dtypes)
    layout = compute_row_layout(dtypes)
    n = len(rows)
    blob = lax.bitcast_convert_type(rows.child.data, jnp.uint8)
    starts = rows.offsets[:-1].astype(jnp.int64)
    if n == 0:
        return GroupedRows(
            dtypes, layout, {}, jnp.zeros((len(dtypes), 0), bool), blob, starts
        )

    uniform = _offsets_uniform(rows, blob.shape[0], layout.row_size_fixed, n)
    if uniform:
        garrs, valid_t = _decode_grouped_uniform(layout, dtypes, blob)
    else:
        fixed = _gather_fixed(layout, blob, starts, n)
        garrs, valid_t = _decode_grouped_fixed(layout, dtypes, fixed)
    group_keys, _ = _entry_plan(layout, dtypes)
    groups = dict(zip(group_keys, garrs))
    return GroupedRows(dtypes, layout, groups, valid_t, blob, starts)


@partial(jax.jit, static_argnums=(0, 1))
def _decode_grouped_uniform(layout: RowLayout, dtypes: Tuple[DType, ...], blob: jnp.ndarray):
    n = blob.shape[0] // layout.row_size_fixed
    ga, vt = _decode_groups_core(layout, dtypes, _uniform_fixed(layout, blob, n))
    return tuple(ga.values()), vt


def _uniform_fixed(layout: RowLayout, blob: jnp.ndarray, n: int) -> jnp.ndarray:
    """Row view of a uniform-stride blob. The planes path keeps the full
    (8-aligned) row width — its transpose wants lane-aligned input and
    the pad bytes are never read; the byte-slice path trims to
    fixed_end so its strided slices touch fewer bytes."""
    from .ragged_bytes import _use_pallas

    rows = blob.reshape(n, layout.row_size_fixed)
    if _use_pallas() and n >= 8:
        return rows
    return rows[:, : layout.fixed_end]


@partial(jax.jit, static_argnums=(0, 1))
def _decode_grouped_fixed(layout: RowLayout, dtypes: Tuple[DType, ...], fixed: jnp.ndarray):
    ga, vt = _decode_groups_core(layout, dtypes, fixed)
    return tuple(ga.values()), vt


@partial(jax.jit, static_argnums=(0, 1))
def _decode_fixed_uniform(layout: RowLayout, dtypes: Tuple[DType, ...], blob: jnp.ndarray):
    """Uniform-stride decode: [n*row_size] u8 blob -> grouped columns in
    ONE program (reshape is free; XLA fuses the slice into the group
    gathers, so bytes move HBM->HBM exactly once)."""
    n = blob.shape[0] // layout.row_size_fixed
    return _decode_fixed_groups(layout, dtypes, _uniform_fixed(layout, blob, n))


@partial(jax.jit, static_argnums=(0, 1))
def _decode_fixed_cols(layout: RowLayout, dtypes: Tuple[DType, ...], fixed: jnp.ndarray):
    """[N, fixed_end] u8 -> (per-column data arrays, [N, C] validity).

    Inverse of _fixed_section's grouped assembly: one static permutation
    gather per width group, then a bitcast back to typed lanes — the
    whole decode is a single compiled program whose op count scales with
    distinct widths, not columns. STRING columns yield their (offset,
    length) u32 slot pair; DECIMAL128 yields [N, 4] limbs.
    """
    return _decode_fixed_groups(layout, dtypes, fixed)


def _decode_groups_from_planes(
    layout: RowLayout, dtypes: Tuple[DType, ...], fixed: jnp.ndarray
):
    """TPU decode core: [N, W] u8 rows -> the same (group_arrays,
    valid_t) contract as _decode_groups_core, via the sublane-pack
    kernel instead of strided byte slices.

    fixed.T IS the byte-plane stack (row j = byte j of every row), so
    pack_u8_planes turns it into [W/4, N] u32 words — one streaming
    kernel — and every group extraction is a contiguous ROW take of the
    plane array plus lane-constant shifts (slot alignment guarantees
    4-byte entries sit at lane boundaries). Replaces the 4-strided-
    u8-slice lane build that dominated decode (14.4 of 13.6..14 ms at
    1M x 212, round-3 profile)."""
    from .ragged_bytes import pack_u8_planes

    n, w = fixed.shape
    pad = (-w) % 4
    if pad:
        fixed = jnp.pad(fixed, ((0, 0), (0, pad)))
    planes = pack_u8_planes(fixed.T)  # [W/4, N] u32

    groups, entries = _entry_plan(layout, dtypes)
    group_arrays: dict = {}
    for key, count in groups.items():
        ew = _entry_width(key)
        byte_off = np.zeros((count,), np.int64)
        for col_entries in entries:
            for k2, idx, row_byte in col_entries:
                if k2 == key:
                    byte_off[idx] = row_byte
        b4 = jnp.asarray(byte_off // 4, jnp.int32)
        if ew == 4:
            lanes = jnp.take(planes, b4, axis=0)  # [k, N] u32
        elif ew == 8:
            lo = jnp.take(planes, b4, axis=0).astype(jnp.uint64)
            hi = jnp.take(planes, b4 + 1, axis=0).astype(jnp.uint64)
            lanes = lo | (hi << jnp.uint64(32))
        else:  # ew in (1, 2): sub-word shift is constant per entry
            base = jnp.take(planes, b4, axis=0)
            sh = jnp.asarray((byte_off % 4) * 8, np.uint32)[:, None]
            if ew == 2:
                lanes = lax.convert_element_type(
                    (base >> sh) & jnp.uint32(0xFFFF), jnp.uint16)
            else:
                lanes = lax.convert_element_type(
                    (base >> sh) & jnp.uint32(0xFF), jnp.uint8)
        if key == "u4":
            typed = lanes
        else:
            target = jnp.dtype(key[key.index("_") + 1:])
            typed = lanes if lanes.dtype == target else lax.bitcast_convert_type(lanes, target)
        group_arrays[key] = lax.optimization_barrier(typed)  # [k, N]

    c = len(dtypes)
    vbyte = layout.validity_offset + np.arange(c) // 8
    vbase = jnp.take(planes, jnp.asarray(vbyte // 4, jnp.int32), axis=0)  # [C, N]
    vsh = jnp.asarray((vbyte % 4) * 8 + np.arange(c) % 8, np.uint32)[:, None]
    valid_t = lax.optimization_barrier(((vbase >> vsh) & jnp.uint32(1)).astype(bool))
    return group_arrays, valid_t


def _decode_groups_core(layout: RowLayout, dtypes: Tuple[DType, ...], fixed: jnp.ndarray):
    """[N, fixed_end] u8 -> ({group key: [k, N] typed lanes}, [C, N] validity).

    The width-grouped, TRANSPOSED device representation: O(distinct
    widths) arrays regardless of column count. This is the form fused
    query pipelines consume, and the form `convert_from_rows_grouped`
    returns — through a remote PJRT tunnel, per-buffer creation
    (~0.5 ms/buffer) dominates a per-column decode of wide tables, and
    even locally a 212-column table costs 424 buffer registrations the
    grouped form avoids.
    """
    from .ragged_bytes import _use_pallas

    if _use_pallas() and fixed.shape[0] >= 8:
        return _decode_groups_from_planes(layout, dtypes, fixed)
    return _decode_groups_bytes(layout, dtypes, fixed)


def _decode_groups_bytes(layout: RowLayout, dtypes: Tuple[DType, ...], fixed: jnp.ndarray):
    """Byte-slice decode core (the non-Pallas implementation; see
    _decode_groups_core for the representation contract). Kept callable
    directly so the planes core can be cross-checked against it on any
    backend — on a TPU host the dispatcher above would otherwise route
    both sides of the comparison to the planes path."""
    groups, entries = _entry_plan(layout, dtypes)

    # NOTE on shapes: everything stays 2-D. A tempting "lane view"
    # (reshape [N, P/w, w] + bitcast) OOMs on TPU — XLA tile-pads the
    # tiny minor dim (w -> 128), a 32x memory blow-up for w=4. Instead,
    # wide lanes are built ARITHMETICALLY from strided byte slices
    # (fixed[:, b::4]), which are large-minor 2-D ops, and every group
    # read is a take of lane indices — w× fewer gather elements than
    # byte addressing.
    pad_w = _round_up(fixed.shape[1], 8)
    fixed_p = (
        jnp.pad(fixed, ((0, 0), (0, pad_w - fixed.shape[1])))
        if pad_w != fixed.shape[1]
        else fixed
    )
    widths = {_entry_width(k) for k in groups}
    lane16 = lane32 = None
    if 2 in widths:
        b = [fixed_p[:, i::2].astype(jnp.uint16) for i in range(2)]
        lane16 = b[0] | (b[1] << jnp.uint16(8))  # [N, P/2]
    if 4 in widths or 8 in widths:
        b = [fixed_p[:, i::4].astype(jnp.uint32) for i in range(4)]
        lane32 = b[0] | (b[1] << jnp.uint32(8)) | (b[2] << jnp.uint32(16)) | (
            b[3] << jnp.uint32(24)
        )  # [N, P/4]

    group_arrays: dict = {}
    for key, count in groups.items():
        w = _entry_width(key)
        lane_idx = np.zeros((count,), np.int32)
        for col_entries in entries:
            for k2, idx, row_byte in col_entries:
                if k2 == key:
                    lane_idx[idx] = row_byte // (4 if w == 8 else w)
        idxs = jnp.asarray(lane_idx)
        if w == 1:
            lanes = jnp.take(fixed_p, idxs, axis=1)  # [N, k] u8
        elif w == 2:
            lanes = jnp.take(lane16, idxs, axis=1)
        elif w == 4:
            lanes = jnp.take(lane32, idxs, axis=1)
        else:  # w == 8: two u32 lanes -> one u64
            lo = jnp.take(lane32, idxs, axis=1).astype(jnp.uint64)
            hi = jnp.take(lane32, idxs + 1, axis=1).astype(jnp.uint64)
            lanes = lo | (hi << jnp.uint64(32))
        if key == "u4":
            typed = lanes
        else:
            target = jnp.dtype(key[key.index("_") + 1 :])
            typed = lanes if lanes.dtype == target else lax.bitcast_convert_type(lanes, target)
        # materialize the group ONCE and TRANSPOSED: without the barrier
        # XLA rematerializes the gather inside every per-column consumer
        # fusion (O(bytes * columns)); without the transpose each
        # per-column extraction is a minor-axis lane slice, which on TPU
        # tiles reads a full (8, 128) tile per element — ~128x HBM read
        # amplification across 212 columns was the 6 GB/s decode of
        # round 1. Row slices of the [k, N] layout are contiguous.
        group_arrays[key] = lax.optimization_barrier(typed.T)  # [k, N]

    valid = _unpack_validity(
        fixed[:, layout.validity_offset : layout.fixed_end], len(dtypes)
    )
    # transposed for the same reason as the data groups: per-column
    # validity reads must be contiguous rows, not lane slices
    valid_t = lax.optimization_barrier(valid.T)  # [C, N]
    return group_arrays, valid_t


def _extract_column(group_arrays, valid_t, entries, i: int, d: DType):
    """One column's (data, validity) out of the grouped representation."""
    ents = entries[i]
    if d.id == TypeId.STRING:
        data = (group_arrays["u4"][ents[0][1]], group_arrays["u4"][ents[1][1]])
    elif d.id == TypeId.DECIMAL128:
        data = jnp.stack([group_arrays["u4"][e[1]] for e in ents], axis=1)
    else:
        key, idx, _ = ents[0]
        lane = group_arrays[key][idx]
        if key.startswith("w1_"):
            lane = lax.bitcast_convert_type(lane, jnp.dtype(key[3:]))
        data = lane
    return data, valid_t[i]


def _decode_fixed_groups(layout: RowLayout, dtypes: Tuple[DType, ...], fixed: jnp.ndarray):
    group_arrays, valid_t = _decode_groups_core(layout, dtypes, fixed)
    _, entries = _entry_plan(layout, dtypes)

    # split per column INSIDE the program: the caller assembling Columns
    # must not pay one eager dispatch per column (212-col tables)
    col_datas = []
    valid_cols = []
    for i, d in enumerate(dtypes):
        data, vmask = _extract_column(group_arrays, valid_t, entries, i, d)
        col_datas.append(data)
        valid_cols.append(vmask)
    return tuple(col_datas), tuple(valid_cols)


def _empty_column(d: DType) -> Column:
    if d.id == TypeId.STRING:
        return Column(d, offsets=jnp.zeros((1,), jnp.int32), chars=jnp.zeros((0,), jnp.uint8))
    if d.id == TypeId.DECIMAL128:
        return Column(d, data=jnp.zeros((0, 4), jnp.uint32))
    return Column(d, data=jnp.zeros((0,), d.jnp_dtype))


# ---------------------------------------------------------------------------
# fixed-width-optimized variants (legacy API surface, RowConversion.java:118-173)
# ---------------------------------------------------------------------------


def _check_optimized(dtypes: Sequence[DType]) -> RowLayout:
    dtypes = list(dtypes)
    if len(dtypes) >= MAX_COLS_OPTIMIZED:
        raise ValueError(
            f"fixed-width-optimized path supports < {MAX_COLS_OPTIMIZED} columns, got {len(dtypes)}"
        )
    for d in dtypes:
        if not d.is_fixed_width:
            raise ValueError(f"fixed-width-optimized path requires fixed-width types, got {d!r}")
    layout = compute_row_layout(dtypes)
    if layout.row_size_fixed > MAX_ROW_SIZE_OPTIMIZED:
        raise ValueError(f"row size {layout.row_size_fixed} exceeds 1KB limit")
    return layout


@op_boundary("convert_to_rows_fixed_width_optimized")
def convert_to_rows_fixed_width_optimized(table: Table) -> List[Column]:
    """Legacy <100-column fixed-width entry (RowConversion.java:118).

    Produces the identical JCUDF layout as convert_to_rows — the reference
    keeps two implementations only as a CUDA launch-shape optimization
    (row_conversion.cu:299-416); under XLA one lowering serves both, so this
    validates limits then delegates (the dual-implementation cross-check of
    row_conversion.cpp:43-60 holds by construction).
    """
    _check_optimized(table.dtypes())
    return convert_to_rows(table)


@op_boundary("convert_from_rows_fixed_width_optimized")
def convert_from_rows_fixed_width_optimized(rows: Column, dtypes: Sequence[DType]) -> Table:
    """Legacy fixed-width decode entry (RowConversion.java:158)."""
    _check_optimized(dtypes)
    return convert_from_rows(rows, dtypes)


# ---------------------------------------------------------------------------
# jit wrappers (one executable per (layout, n) size class)
# ---------------------------------------------------------------------------


@jax.jit
def _jit_gather_fixed_impl(blob, starts, iota):
    return blob[starts[:, None] + iota[None, :]]


def _jit_gather_fixed(blob, starts, fixed_end: int, n: int):
    return _jit_gather_fixed_impl(blob, starts, jnp.arange(fixed_end, dtype=jnp.int64))


@partial(jax.jit, static_argnums=(0, 2, 3))
def _jit_to_rows_fixed_static(layout: RowLayout, cols: Tuple[Column, ...],
                              rs: int, n: int):
    """Batch encode with a STATIC slice start: static slices fuse into
    the consuming relayout (no materialized per-column copies). Chosen
    for tables with <=4 batches; see convert_to_rows."""
    sliced = tuple(
        Column(c.dtype, data=lax.slice_in_dim(c.data, rs, rs + n),
               validity=None if c.validity is None
               else lax.slice_in_dim(c.validity, rs, rs + n))
        for c in cols
    )
    return _to_rows_fixed(layout, sliced, n)


@partial(jax.jit, static_argnums=(0, 3))
def _jit_to_rows_fixed_sliced(layout: RowLayout, cols: Tuple[Column, ...],
                              rs, n: int):
    """Batch encode with the row slicing INSIDE the program: per-column
    eager slices cost one dispatch each (212 columns x batches of
    round-trips through a remote backend dominated the >2GiB axis).
    Only the batch LENGTH is static (shapes need it); the start rides
    as a traced scalar so a many-batch table compiles once per distinct
    size, not once per offset."""
    sliced = tuple(
        Column(c.dtype, data=lax.dynamic_slice_in_dim(c.data, rs, n),
               validity=None if c.validity is None
               else lax.dynamic_slice_in_dim(c.validity, rs, n))
        for c in cols
    )
    return _to_rows_fixed(layout, sliced, n)

"""Device ops: the TPU-native kernel tier.

Each module replaces one CUDA kernel family from the reference
(`src/main/cpp/src/*.cu`), re-designed for XLA/TPU: static shapes,
vectorized byte arithmetic instead of warp-level byte addressing, and
host code only for metadata (batching, layout).
"""

from . import row_conversion  # noqa: F401

"""Device ops: the TPU-native kernel tier.

Each module replaces one CUDA kernel family from the reference
(`src/main/cpp/src/*.cu`), re-designed for XLA/TPU: static shapes,
vectorized byte arithmetic instead of warp-level byte addressing, and
host code only for metadata (batching, layout).
"""

from . import (  # noqa: F401
    aggregate,
    bitutils,
    cast_decimal,
    cast_string,
    copying,
    decimal_utils,
    expressions,
    hashing,
    join,
    limbs,
    regex,
    row_conversion,
    sort,
    utf8,
    zorder,
)

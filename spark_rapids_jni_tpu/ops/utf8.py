"""Vectorized UTF-8 codec: padded byte matrices <-> codepoint matrices.

The regex and Unicode-case tiers operate on CODEPOINTS (like cudf's
regex engine, which works on code points over its char-utf8 iterators),
not raw bytes — '.' must match one character, char classes are
codepoint ranges, and case mapping is a codepoint relation. This module
converts the string tier's padded [N, L] uint8 matrices (ops/strings.py
``to_padded``) into padded [N, Lc] int32 codepoint matrices and back,
fully vectorized (no per-string loops — the XLA formulation of the
reference's warp-per-string byte walking).

Malformed UTF-8 is tolerated garbage-in/garbage-out (continuation bytes
without a lead decode as replacement-free salvage values), matching the
"bytes are bytes" stance of the JCUDF transcode tier.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

__all__ = ["decode_padded", "encode_padded", "utf8_nbytes"]

MAX_CODEPOINT = 0x10FFFF


def decode_padded(padded: jnp.ndarray, lens: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """[N, L] uint8 + [N] byte lengths -> (cp [N, L] int32 left-compacted,
    cp_lens [N] int32, byte_off [N, L+1] int32).

    ``cp[i, k]`` is the k-th codepoint of row i (positions >= cp_lens[i]
    are 0). ``byte_off[i, k]`` is the byte offset where codepoint k
    starts; entries at/after cp_lens[i] equal the row's byte length, so
    a codepoint span [a, b) maps to the byte span
    [byte_off[i, a], byte_off[i, b]).
    """
    n, L = padded.shape
    if n == 0 or L == 0:
        z2 = jnp.zeros((n, max(L, 1)), jnp.int32)
        return z2, jnp.zeros((n,), jnp.int32), jnp.zeros((n, max(L, 1) + 1), jnp.int32)

    b = padded.astype(jnp.int32)
    j = jnp.arange(L, dtype=jnp.int32)[None, :]
    inb = j < lens[:, None]
    is_cont = (b & 0xC0) == 0x80
    lead = inb & ~is_cont

    def nxt(k):
        src = jnp.clip(j + k, 0, L - 1)
        return jnp.take_along_axis(b, jnp.broadcast_to(src, b.shape), axis=1) & 0x3F

    b1, b2, b3 = nxt(1), nxt(2), nxt(3)
    cp1 = b
    cp2 = ((b & 0x1F) << 6) | b1
    cp3 = ((b & 0x0F) << 12) | (b1 << 6) | b2
    cp4 = ((b & 0x07) << 18) | (b1 << 12) | (b2 << 6) | b3
    cp = jnp.where(
        b < 0x80,
        cp1,
        jnp.where(b < 0xE0, cp2, jnp.where(b < 0xF0, cp3, cp4)),
    )
    cp = jnp.clip(cp, 0, MAX_CODEPOINT)

    # Left-compact lead positions: k-th lead of row i lands in column k.
    k_idx = jnp.cumsum(lead.astype(jnp.int32), axis=1) - 1
    cp_lens = jnp.sum(lead, axis=1).astype(jnp.int32)
    rows = jnp.arange(n, dtype=jnp.int32)[:, None]
    dest = jnp.clip(k_idx, 0, L - 1)
    cp_out = jnp.zeros((n, L), jnp.int32).at[
        jnp.broadcast_to(rows, (n, L)), dest
    ].add(jnp.where(lead, cp, 0))
    byte_pos = jnp.zeros((n, L), jnp.int32).at[
        jnp.broadcast_to(rows, (n, L)), dest
    ].add(jnp.where(lead, j, 0))

    # byte_off: [N, L+1]; columns >= cp_len take the row's byte length.
    col = jnp.arange(L + 1, dtype=jnp.int32)[None, :]
    byte_off = jnp.concatenate([byte_pos, jnp.zeros((n, 1), jnp.int32)], axis=1)
    byte_off = jnp.where(col >= cp_lens[:, None], lens[:, None].astype(jnp.int32), byte_off)
    return cp_out, cp_lens, byte_off


def utf8_nbytes(cp: jnp.ndarray) -> jnp.ndarray:
    """Encoded length (1..4) of each codepoint."""
    return (
        1
        + (cp >= 0x80).astype(jnp.int32)
        + (cp >= 0x800).astype(jnp.int32)
        + (cp >= 0x10000).astype(jnp.int32)
    )


def encode_padded(cp: jnp.ndarray, cp_lens: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[N, Lc] int32 codepoints + [N] counts -> ([N, Lb] uint8, [N] byte
    lengths). Lb is sized to the batch max (one host sync, the standard
    output-allocation sync)."""
    n, Lc = cp.shape
    k = jnp.arange(Lc, dtype=jnp.int32)[None, :]
    inb = k < cp_lens[:, None]
    nb = jnp.where(inb, utf8_nbytes(cp), 0)
    lens = jnp.sum(nb, axis=1).astype(jnp.int32)
    if n == 0:
        return jnp.zeros((0, 1), jnp.uint8), lens
    Lb = max(int(jnp.max(lens)), 1)
    start = jnp.cumsum(nb, axis=1) - nb  # exclusive prefix

    b0 = jnp.where(
        nb == 1,
        cp,
        jnp.where(
            nb == 2,
            0xC0 | (cp >> 6),
            jnp.where(nb == 3, 0xE0 | (cp >> 12), 0xF0 | (cp >> 18)),
        ),
    )
    b1 = jnp.where(
        nb == 2,
        0x80 | (cp & 0x3F),
        jnp.where(nb == 3, 0x80 | ((cp >> 6) & 0x3F), 0x80 | ((cp >> 12) & 0x3F)),
    )
    b2 = jnp.where(nb == 3, 0x80 | (cp & 0x3F), 0x80 | ((cp >> 6) & 0x3F))
    b3 = 0x80 | (cp & 0x3F)

    rows = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], (n, Lc))
    out = jnp.zeros((n, Lb), jnp.int32)
    for t, bt in enumerate((b0, b1, b2, b3)):
        keep = inb & (nb > t)
        dest = jnp.clip(start + t, 0, Lb - 1)
        out = out.at[rows, dest].add(jnp.where(keep, bt, 0))
    return out.astype(jnp.uint8), lens


def _build_case_table(upper: bool) -> np.ndarray:
    """BMP 1:1 case-map table (codepoint -> codepoint). Multi-char
    special casings (ß->SS, ...) map to identity — the cudf to_upper
    core has the same 1:1 restriction. Supplementary-plane case pairs
    (Deseret etc.) are identity-mapped; documented limitation."""
    tab = np.arange(0x10000, dtype=np.int32)
    for c in range(0x10000):
        if 0xD800 <= c <= 0xDFFF:
            continue
        m = chr(c).upper() if upper else chr(c).lower()
        if len(m) == 1 and ord(m) < 0x10000:
            tab[c] = ord(m)
    return tab


_CASE_TABLES: dict = {}


def case_table(upper: bool) -> jnp.ndarray:
    key = bool(upper)
    if key not in _CASE_TABLES:
        _CASE_TABLES[key] = jnp.asarray(_build_case_table(upper))
    return _CASE_TABLES[key]

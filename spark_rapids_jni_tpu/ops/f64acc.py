"""Exact float64 accumulation + double-f32 arithmetic on integer-only
datapaths (TPU v5e has no f64 ALU; XLA's x64 rewrite demotes f64
arithmetic to f32 and this platform's compile helper rejects f64
bitcasts outright — NOTES_ROUND3).

The reference sums doubles in real f64 on device (cudf segment reduce;
SURVEY §2.8), so Spark ``sum(double)`` semantics require f64-accurate
accumulation. This module delivers that WITHOUT an f64 datapath:

**Exact windowed integer accumulation** (``segment_sum_f64bits``):
each FLOAT64 value (stored as IEEE-754 bits in uint64 lanes — see
bitutils) is decomposed into sign/exponent/53-bit mantissa with pure
integer ops (exact on TPU), aligned to the per-group maximum exponent
inside a 224-bit fixed-point window (7 x u32 limbs), and segment-summed
limb-wise in int64 (exact: every per-limb partial stays < 2^63 for up to
2^31 rows). A carry-propagate + round-to-nearest-even pass rebuilds the
IEEE bits. Values more than ~108 bits below the group maximum fall off
the window — an error < 2^-107 relative to the largest element, i.e.
strictly tighter than one f64 ulp of any achievable result, so the
returned sum is the correctly rounded f64 of the exact real sum in all
practical regimes (and far more accurate than sequential f64 addition,
whose error grows with N). The same bits come back on every backend —
CPU and TPU agree bit-for-bit.

**Exact mean**: the 224-bit limb sum is divided by the count with a
restoring bit-at-a-time long division (compare/subtract only — the
emulated 64-bit integer divide never enters the program), the remainder
folds into the sticky bit, and the quotient rounds through the same
nearest-even path.

**Double-f32 ("dd") arithmetic** for the expression tier: values carried
as an unevaluated (hi, lo) f32 pair with |lo| <= ulp(hi)/2, giving
~2^-48 relative error for +,-,*,/ — vs 2^-24 for the plain-f32
approximation it replaces. Error-free transforms (2Sum, Dekker split
2Prod) use only IEEE f32 add/mul, both exact on the TPU VPU. dd covers
the f32 exponent range (|x| in ~[1e-38, 3e38]); magnitudes outside it
saturate exactly as the old f32 path did. dd -> f64-bits conversion is
exact: each half widens losslessly to f64 bits and the pair goes through
the windowed accumulator (n=2), rounding once.

IEEE edges: +/-inf and NaN propagate via per-group flags (inf + -inf =
NaN); subnormal inputs accumulate exactly (they are just e_eff=1
mantissas); subnormal RESULTS round correctly into the f64 subnormal
encoding. The single knowingly dropped edge: a group whose every addend
is -0.0 returns +0.0 (IEEE says -0.0); no aggregation consumer observes
the sign of zero.

Reference parity: cudf groupby SUM/MEAN on FLOAT64
(/root/reference 's engine tier via the linked cudf, SURVEY §2.8);
exactness target pinned by VERDICT r3 item 5.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "segment_sum_f64bits",
    "segment_mean_f64bits",
    "i64_to_f64bits",
    "mean_i64_div",
    "div_f64bits_by_int",
    "DD",
    "dd_from_f64bits",
    "dd_to_f64bits",
    "dd_from_any",
]

_U64 = jnp.uint64
_U32 = jnp.uint32
_I64 = jnp.int64
_I32 = jnp.int32

LIMBS = 7  # 224-bit window
# Window anchoring: the mantissa MSB (bit 52 of the 53-bit mantissa) of a
# max-exponent element sits at window bit 160, i.e. the mantissa LSB at
# bit 108; window bit 0 weighs 2^(E - 1183) where E is the group's max
# biased effective exponent. 64 headroom bits (160..223) keep the signed
# sum of up to 2^31 elements inside the window.
_ANCHOR_LSB = 108


def _u64(x) -> jnp.ndarray:
    return jnp.asarray(x, _U64)


def _decompose(bits: jnp.ndarray):
    """IEEE-754 double bits -> (negative, e_eff, mantissa, is_nan, is_pinf, is_ninf).

    e_eff is the *effective* biased exponent: subnormals (e=0) read as
    e_eff=1 with no implicit bit — which makes value = m * 2^(e_eff-1075)
    uniformly true for every finite double, subnormals included.
    """
    neg = (bits >> _u64(63)) != 0
    e = ((bits >> _u64(52)) & _u64(0x7FF)).astype(_I32)
    frac = bits & _u64((1 << 52) - 1)
    is_nan = (e == 0x7FF) & (frac != 0)
    is_inf = (e == 0x7FF) & (frac == 0)
    mant = jnp.where(e == 0, frac, frac | _u64(1 << 52))
    e_eff = jnp.where(e == 0, 1, e)
    finite = e != 0x7FF
    mant = jnp.where(finite, mant, _u64(0))
    e_eff = jnp.where(finite, e_eff, 1)
    return neg, e_eff, mant, is_nan, is_inf & ~neg, is_inf & neg


def _element_limbs(mant: jnp.ndarray, shift: jnp.ndarray) -> list:
    """Per-element limb values: bits [32k, 32k+32) of mant << (108 - shift).

    shift = E[group] - e_elem >= 0. Returns LIMBS arrays of uint32.
    All shift amounts are clamped into [0, 63] with where-guards (XLA
    shifts >= bit width are undefined).
    """
    out = []
    m32 = (mant & _u64(0xFFFFFFFF)).astype(_U64)
    for k in range(LIMBS):
        # t = bit offset into mant of this limb's LSB
        t = _I32(32 * k - _ANCHOR_LSB) + shift.astype(_I32)
        pos = jnp.clip(t, 0, 63).astype(_U64)
        neg_sh = jnp.clip(-t, 0, 31).astype(_U64)
        right = (mant >> pos) & _u64(0xFFFFFFFF)
        left = (m32 << neg_sh) & _u64(0xFFFFFFFF)
        limb = jnp.where(t >= 0, right, left)
        # mantissas are <= 64 bits (53 for doubles; up to 63 for the
        # integer-mean dividend) — t >= 64 reads past any of them
        limb = jnp.where((t >= 64) | (t <= -32), _u64(0), limb)
        out.append(limb.astype(_U32))
    return out


class _GroupSum(NamedTuple):
    """Exact per-group sum in windowed fixed point, pre-rounding."""

    limbs: jnp.ndarray  # [G, LIMBS] int64 signed limb partial sums
    emax: jnp.ndarray  # [G] int32 group max effective biased exponent
    has_nan: jnp.ndarray  # [G] bool
    has_pinf: jnp.ndarray
    has_ninf: jnp.ndarray


# one-hot bytes per group x row the MXU path may materialize (256 MB)
_MXU_ONEHOT_BUDGET = 1 << 28
# rows per matmul chunk: |signed nibble partial| <= 15 * chunk must stay
# inside s32 (2^31); 2^26 rows leaves 32x headroom
_MXU_CHUNK = 1 << 26


def _accumulate_mxu(
    neg, e_eff, mant, is_nan, is_pinf, is_ninf, live, emax, seg, num_segments
) -> _GroupSum:
    """Per-group limb reduction as a signed one-hot int8 MXU contraction.

    The round-4 payload formulation ([N, LIMBS+3] int64 stacked per
    element, segment-summed) was per-element ALU/relayout-bound: ~0.34 s
    per fused-q1 iteration at 1M rows (NOTES_ROUND4 item 5). Here the
    reduction rides the systolic array instead: each 32-bit limb splits
    into 8 nibble planes (values 0..15, int8), planes stack row-major as
    B [8*LIMBS+3, N], and a signed one-hot A [G, N] (+1/-1 by element
    sign, 0 for dead rows) contracts over N in one s8 x s8 -> s32
    dot_general. Nibble partial sums recombine into the exact signed
    224-bit window limbs in int64 at [G] scale — bit-identical to the
    payload path, at matmul bandwidth.

    Exactness bound: every per-group nibble partial is <= 15 * chunk
    rows in magnitude; chunking at 2^26 rows keeps it under 2^30, well
    inside the s32 accumulator. Non-finite rows carry zero limbs and a
    forced +1 sign so the nan/pinf/ninf indicator planes cannot cancel
    between +NaN and -NaN payload signs.
    """
    n = mant.shape[0]
    shift = emax[seg] - e_eff  # >= 0 for live rows
    limbs = _element_limbs(mant, shift)
    nonfinite = is_nan | is_pinf | is_ninf
    sgn8 = jnp.where(
        live, jnp.where(nonfinite | ~neg, jnp.int8(1), jnp.int8(-1)), jnp.int8(0)
    )
    planes = []
    for limb in limbs:
        for j in range(8):
            planes.append(((limb >> _U32(4 * j)) & _U32(0xF)).astype(jnp.int8))
    planes.append(is_nan.astype(jnp.int8))
    planes.append(is_pinf.astype(jnp.int8))
    planes.append(is_ninf.astype(jnp.int8))
    b = jnp.stack(planes, axis=0)  # [8*LIMBS+3, N] — rows contiguous
    onehot = (seg[None, :] == jnp.arange(num_segments, dtype=seg.dtype)[:, None])
    a = jnp.where(onehot, sgn8[None, :], jnp.int8(0))  # [G, N]
    acc = None
    for start in range(0, max(n, 1), _MXU_CHUNK):
        stop = min(start + _MXU_CHUNK, n)
        s = lax.dot_general(
            a[:, start:stop],
            b[:, start:stop],
            (((1,), (1,)), ((), ())),
            preferred_element_type=_I32,
        ).astype(_I64)
        acc = s if acc is None else acc + s
    # recombine nibble sums into signed 32-bit-limb partials (int64 at
    # [G, LIMBS] scale — tiny)
    limb_sums = []
    for k in range(LIMBS):
        t = jnp.zeros((num_segments,), _I64)
        for j in range(8):
            t = t + (acc[:, 8 * k + j] << _I64(4 * j))
        limb_sums.append(t)
    return _GroupSum(
        jnp.stack(limb_sums, axis=-1),
        emax,
        acc[:, 8 * LIMBS] > 0,
        acc[:, 8 * LIMBS + 1] > 0,
        acc[:, 8 * LIMBS + 2] > 0,
    )


def _accumulate(bits, valid, seg, num_segments) -> _GroupSum:
    if num_segments == 0 or bits.shape[0] == 0:
        # zero groups (fully filtered batch) or zero rows with live
        # groups: every group sums to +0.0. The small-G masked path
        # below would jnp.max over a zero-size array, which errors.
        z64 = jnp.zeros((num_segments, LIMBS), _I64)
        zb = jnp.zeros((num_segments,), bool)
        return _GroupSum(z64, jnp.ones((num_segments,), _I32), zb, zb, zb)
    neg, e_eff, mant, is_nan, is_pinf, is_ninf = _decompose(bits)
    if valid is not None:
        live = valid
    else:
        live = jnp.ones(bits.shape, bool)
    is_nan = is_nan & live
    is_pinf = is_pinf & live
    is_ninf = is_ninf & live

    e_live = jnp.where(live, e_eff, 0)
    # TPU scatters cost ~40 ns per ELEMENT (payload lanes included): at
    # 1M rows the 10-lane scatter alone is ~0.4 s. For small group
    # counts — the fused-pipeline regime (q1 has 6 groups, a global sum
    # 1) — G masked bandwidth-bound reductions are orders of magnitude
    # cheaper than one scatter pass.
    small = num_segments <= 16
    if small:
        emax = jnp.stack(
            [jnp.max(jnp.where(seg == g, e_live, 0)) for g in range(num_segments)]
        )
    else:
        emax = jax.ops.segment_max(e_live, seg, num_segments=num_segments)
    emax = jnp.maximum(emax, 1)  # empty / all-invalid groups: any base works

    if num_segments * bits.shape[0] <= _MXU_ONEHOT_BUDGET:
        # hot path (round 5): signed one-hot int8 MXU contraction —
        # bit-identical to the payload reduction below, at matmul
        # bandwidth instead of per-element i64 ALU (NOTES_ROUND4 item 5)
        return _accumulate_mxu(
            neg, e_eff, mant, is_nan, is_pinf, is_ninf, live, emax, seg, num_segments
        )

    shift = emax[seg] - e_eff  # >= 0 for live rows
    limbs = _element_limbs(mant, shift)
    sgn = jnp.where(neg, _I64(-1), _I64(1))
    sgn = jnp.where(live, sgn, _I64(0))
    # ONE vectorized [N, LIMBS+3] payload (fallback when the one-hot
    # would blow the budget). Measured on chip at the q6 axis (1M rows):
    # payload scatter 0.42 s/iter, payload + small-G masked reduction
    # 0.34 s/iter, flat per-lane masked reductions 2.4 s/iter (XLA
    # re-materializes the shared decompose per lane).
    payload = jnp.stack(
        [l.astype(_I64) * sgn for l in limbs]
        + [is_nan.astype(_I64), is_pinf.astype(_I64), is_ninf.astype(_I64)],
        axis=-1,
    )
    if small:
        acc = jnp.stack(
            [
                jnp.sum(jnp.where((seg == g)[:, None], payload, _I64(0)), axis=0)
                for g in range(num_segments)
            ]
        )
    else:
        acc = jax.ops.segment_sum(payload, seg, num_segments=num_segments)
    return _GroupSum(
        acc[..., :LIMBS],
        emax,
        acc[..., LIMBS] > 0,
        acc[..., LIMBS + 1] > 0,
        acc[..., LIMBS + 2] > 0,
    )


def _carry_normalize(acc: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[G, LIMBS] signed int64 partials -> (negative [G], mag [G, LIMBS] u32).

    Carry-propagates into a two's-complement limb string, then converts
    to sign-magnitude (the window headroom guarantees the value fits)."""
    out = []
    carry = jnp.zeros(acc.shape[:-1], _I64)
    for k in range(LIMBS):
        t = acc[..., k] + carry
        out.append((t & _I64(0xFFFFFFFF)).astype(_U32))
        carry = t >> _I64(32)  # arithmetic shift: sign-correct
    negative = carry < 0
    # two's complement -> magnitude: invert + 1 with a ripple carry
    mag = []
    add = jnp.where(negative, _U64(1), _U64(0))
    for k in range(LIMBS):
        limb = jnp.where(negative, ~out[k], out[k]).astype(_U64)
        t = limb + add
        mag.append((t & _u64(0xFFFFFFFF)).astype(_U32))
        add = t >> _u64(32)
    return negative, jnp.stack(mag, axis=-1)


def _clz32(x: jnp.ndarray) -> jnp.ndarray:
    """count leading zeros of a u32 (x != 0 -> 0..31; x == 0 -> 32)."""
    n = jnp.full(x.shape, 32, _I32)
    f = x
    # classic binary clz: n tracks 32 - bits consumed
    for shift in (16, 8, 4, 2, 1):
        big = f >= (_U32(1) << _U32(shift))
        n = jnp.where(big, n - shift, n)
        f = jnp.where(big, f >> _U32(shift), f)
    return jnp.where(x == 0, 32, n - 1)  # x>=1 consumed one sentinel bit


def _msb_pos(mag: jnp.ndarray) -> jnp.ndarray:
    """[G, LIMBS] u32 magnitude -> [G] int32 highest set bit (-1 if zero)."""
    best = jnp.full(mag.shape[:-1], -1, _I32)
    for k in range(LIMBS):
        limb = mag[..., k]
        pos = 32 * k + 31 - _clz32(limb)
        best = jnp.where(limb != 0, pos, best)
    return best


def _extract_bits(mag: jnp.ndarray, start: jnp.ndarray, width: int) -> jnp.ndarray:
    """bits [start, start+width) of the limb string as u64 (width <= 62).

    start may be any int32 >= 0 (bits above the window read as 0).
    Funnel-shifts out of the three aligned u64 words."""
    words = []
    for w in range((LIMBS + 1) // 2):
        lo = mag[..., 2 * w].astype(_U64)
        hi = (
            mag[..., 2 * w + 1].astype(_U64)
            if 2 * w + 1 < LIMBS
            else jnp.zeros_like(lo)
        )
        words.append(lo | (hi << _u64(32)))
    nwords = len(words)
    idx = (start >> 6).astype(_I32)
    r = (start & 63).astype(_U64)
    res = jnp.zeros(mag.shape[:-1], _U64)
    for w in range(nwords):
        cur = words[w]
        nxt = words[w + 1] if w + 1 < nwords else jnp.zeros_like(cur)
        # (cur >> r) | (nxt << (64 - r)), r == 0 handled without UB
        lo_part = cur >> r
        hi_part = jnp.where(r == 0, _u64(0), nxt << (_u64(64) - jnp.maximum(r, _u64(1))))
        res = jnp.where(idx == w, lo_part | hi_part, res)
    return res & _u64((1 << width) - 1)


def _sticky_below(mag: jnp.ndarray, pos: jnp.ndarray) -> jnp.ndarray:
    """any bit of the limb string strictly below bit `pos` set? [G] bool."""
    sticky = jnp.zeros(mag.shape[:-1], bool)
    for k in range(LIMBS):
        limb = mag[..., k]
        # bits of limb k occupy [32k, 32k+32)
        full = pos >= 32 * (k + 1)
        partial = (pos > 32 * k) & ~full
        nbits = jnp.clip(pos - 32 * k, 0, 32)
        mask = jnp.where(
            nbits >= 32, _U32(0xFFFFFFFF), (_U32(1) << nbits.astype(_U32)) - _U32(1)
        )
        sticky = sticky | (full & (limb != 0)) | (partial & ((limb & mask) != 0))
    return sticky


def _round_to_bits(
    negative, mag, emax, has_nan, has_pinf, has_ninf, extra_sticky=None
) -> jnp.ndarray:
    """Windowed sign-magnitude -> IEEE-754 double bits, nearest-even."""
    B = _msb_pos(mag)
    # shift q: result value = keep53 * 2^(q + emax - 1183); the subnormal
    # boundary forces q >= 109 - emax (so the quotient aligns with the
    # f64 subnormal LSB 2^-1074 exactly when the exponent bottoms out)
    q = jnp.maximum(B - 52, 109 - emax)
    q_pos = jnp.maximum(q, 0)

    # rounding path (q > 0): keep = bits [q, q+53), guard = bit q-1,
    # sticky = bits below q-1 (plus the division remainder, if any)
    keep_r = _extract_bits(mag, q_pos.astype(_I32), 53)
    guard_start = jnp.maximum(q_pos - 1, 0).astype(_I32)
    guard = jnp.where(
        q_pos > 0, _extract_bits(mag, guard_start, 1), _u64(0)
    )
    sticky = _sticky_below(mag, jnp.maximum(q_pos - 1, 0)) & (q_pos > 0)
    if extra_sticky is not None:
        sticky = sticky | extra_sticky
    round_up = (guard == 1) & (sticky | ((keep_r & _u64(1)) == 1))
    keep_r = keep_r + round_up.astype(_U64)

    # exact path (q <= 0): the whole magnitude fits below bit 53 —
    # left-shift it into place (B <= 52 implies it lives in word 0).
    # A division remainder here (sub-window-bit resolution while the
    # result wants finer ulps) only arises after >108-bit cancellation,
    # i.e. already below the window's documented noise floor — the
    # sticky is ignorable by construction on this branch.
    w0 = mag[..., 0].astype(_U64) | (mag[..., 1].astype(_U64) << _u64(32))
    keep_e = w0 << jnp.clip(-q, 0, 63).astype(_U64)

    keep = jnp.where(q > 0, keep_r, keep_e)
    # mantissa overflow from rounding: 2^53 -> 2^52, exponent +1
    ovf = keep >> _u64(53) != 0
    keep = jnp.where(ovf, keep >> _u64(1), keep)
    q = q + ovf.astype(_I32)

    subnormal = (B + emax) < 161  # biased exponent would be <= 0
    biased = jnp.clip(q + emax - 108, 0, 0x7FF).astype(_U64)
    frac = keep & _u64((1 << 52) - 1)
    # subnormal encoding: exp field 0, keep53 <= 2^52; a rounding carry
    # into bit 52 lands exactly on biased-exponent 1 — IEEE's layout
    # makes the transition seamless
    bits = jnp.where(
        subnormal, keep, (biased << _u64(52)) | frac
    )
    overflow = (~subnormal) & (q + emax - 108 >= 0x7FF)
    bits = jnp.where(overflow, _u64(0x7FF) << _u64(52), bits)
    zero = _msb_pos(mag) < 0
    bits = jnp.where(zero, _u64(0), bits)
    sign = jnp.where(negative & ~zero, _u64(1) << _u64(63), _u64(0))
    bits = bits | sign

    inf_bits = _u64(0x7FF) << _u64(52)
    bits = jnp.where(has_pinf & ~has_ninf, inf_bits, bits)
    bits = jnp.where(has_ninf & ~has_pinf, inf_bits | (_u64(1) << _u64(63)), bits)
    is_nan = has_nan | (has_pinf & has_ninf)
    bits = jnp.where(is_nan, inf_bits | _u64(1 << 51), bits)
    return bits


def segment_sum_f64bits(
    bits: jnp.ndarray,
    seg: jnp.ndarray,
    num_segments: int,
    valid: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Exact per-segment SUM of FLOAT64 bit-stored values.

    Returns [num_segments] uint64 IEEE bits: the f64 nearest-even
    rounding of the exact real sum (window error < 2^-107 of the largest
    addend — below any representable ulp). Integer-only: identical bits
    on CPU and TPU. Invalid rows (valid=False) contribute nothing.
    """
    gs = _accumulate(bits, valid, seg, num_segments)
    negative, mag = _carry_normalize(gs.limbs)
    return _round_to_bits(
        negative, mag, gs.emax, gs.has_nan, gs.has_pinf, gs.has_ninf
    )


def _limb_divide(mag: jnp.ndarray, cnt: jnp.ndarray):
    """Restoring long division of the 224-bit magnitude by cnt (< 2^31).

    Returns (quotient [G, LIMBS] u32, remainder-nonzero [G] bool). No
    64-bit hardware divide anywhere: the magnitude is exploded into an
    MSB-first bit matrix, a 224-step lax.scan shifts each bit into a
    per-group int64 remainder with one compare/subtract, and the scanned
    quotient bits pack back into limbs. G is a group count — small — so
    the serial scan is cheap."""
    G = mag.shape[0]
    total_bits = 32 * LIMBS
    cnt64 = jnp.maximum(cnt.astype(_I64), 1)
    shifts = jnp.arange(32, dtype=_U32)
    # [G, LIMBS*32] bits, LSB-first within the whole window
    bits_lsb = ((mag[..., None] >> shifts[None, None, :]) & _U32(1)).reshape(G, total_bits)
    xs = bits_lsb[:, ::-1].T.astype(_I64)  # [224, G], MSB first

    def step(r, b):
        r = (r << 1) | b
        ge = r >= cnt64
        return jnp.where(ge, r - cnt64, r), ge

    # carry seeds from a VARYING operand (cnt) so the scan type-checks
    # under shard_map's varying-manual-axes tracking; plain zeros would
    # start unvarying and mismatch the carry output
    rem, qbits = lax.scan(step, cnt64 * 0, xs)
    qb = qbits.T[:, ::-1].reshape(G, LIMBS, 32)  # LSB-first again
    weights = _u64(1) << jnp.arange(32, dtype=_U64)
    q = (qb.astype(_U64) * weights[None, None, :]).sum(axis=-1).astype(_U32)
    return q, rem != 0


def segment_mean_f64bits(
    bits: jnp.ndarray,
    seg: jnp.ndarray,
    num_segments: int,
    valid: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact per-segment MEAN of FLOAT64 bit-stored values.

    The 224-bit exact sum divides by the valid count via binary long
    division; the remainder folds into the sticky bit, so the result is
    the f64 nearest-even rounding of (exact sum / count). Returns
    (mean_bits [G] u64, count [G] i64)."""
    gs = _accumulate(bits, valid, seg, num_segments)
    live = valid if valid is not None else jnp.ones(bits.shape, bool)
    if num_segments == 0:
        cnt = jnp.zeros((0,), _I64)
    elif num_segments <= 16:  # masked reductions beat the scatter class
        cnt = jnp.stack(
            [
                jnp.sum(jnp.where(seg == g, live, False).astype(_I64))
                for g in range(num_segments)
            ]
        )
    else:
        cnt = jax.ops.segment_sum(live.astype(_I64), seg, num_segments=num_segments)
    negative, mag = _carry_normalize(gs.limbs)
    q, rem = _limb_divide(mag, cnt)
    out = _round_to_bits(
        negative, q, gs.emax, gs.has_nan, gs.has_pinf, gs.has_ninf, extra_sticky=rem
    )
    return out, cnt


def u64_to_f64bits(x: jnp.ndarray) -> jnp.ndarray:
    """uint64 -> IEEE-754 double bits, nearest-even (exact < 2^53)."""
    return _abs64_to_f64bits(x.astype(_U64), jnp.zeros(x.shape, bool))


def i64_to_f64bits(x: jnp.ndarray) -> jnp.ndarray:
    """int64 -> IEEE-754 double bits, nearest-even (exact for |x| < 2^53).

    Integer-only, for materializing exact integer aggregates into
    FLOAT64 columns on the f64-less tier."""
    neg = x < 0
    return _abs64_to_f64bits(jnp.where(neg, -x, x).astype(_U64), neg)


def _abs64_to_f64bits(a: jnp.ndarray, neg: jnp.ndarray) -> jnp.ndarray:
    msb = jnp.zeros(a.shape, _I32)
    v = a
    for shift in (32, 16, 8, 4, 2, 1):
        big = v >= (_u64(1) << _u64(shift))
        msb = jnp.where(big, msb + shift, msb)
        v = jnp.where(big, v >> _u64(shift), v)
    sh = jnp.maximum(msb - 52, 0)
    shc = jnp.clip(sh, 0, 63).astype(_U64)
    keep = a >> shc
    dropped = a & ((_u64(1) << shc) - _u64(1))
    half = jnp.where(sh > 0, _u64(1) << jnp.clip(sh - 1, 0, 63).astype(_U64), _u64(0))
    round_up = (sh > 0) & ((dropped > half) | ((dropped == half) & ((keep & _u64(1)) == 1)))
    keep = keep + round_up.astype(_U64)
    carry = keep >> _u64(53) != 0
    keep = jnp.where(carry, keep >> _u64(1), keep)
    up = jnp.clip(52 - msb, 0, 63)
    mant = jnp.where(sh > 0, keep, keep << up.astype(_U64))
    # normalized mantissa MSB sits at bit 52; value exponent = msb (+1
    # when rounding carried out of the mantissa)
    biased = (msb + carry.astype(_I32) + 1023).astype(_U64)
    bits = (biased << _u64(52)) | (mant & _u64((1 << 52) - 1))
    bits = jnp.where(a == 0, _u64(0), bits)
    bits = bits | jnp.where(neg, _u64(1) << _u64(63), _u64(0))
    return bits


def mean_i64_div(sums: jnp.ndarray, cnt: jnp.ndarray, unsigned: bool = False) -> jnp.ndarray:
    """Exact f64 mean of integer aggregates: |sums| rides the window
    shifted up to the mantissa anchor (bit 108, via _element_limbs with
    shift 0), so the long division yields 108 FRACTIONAL quotient bits
    below the integer point before the shared nearest-even rounding.
    E = 1075 makes window bit 108 weigh 2^0. [G] i64 / [G] i64 -> u64.
    ``unsigned=True`` reads ``sums`` as uint64 magnitudes (UINT64
    aggregates whose two's-complement sum bits exceed 2^63)."""
    if unsigned:
        neg = jnp.zeros(sums.shape, bool)
        a = sums.astype(_U64)
    else:
        neg = sums < 0
        a = jnp.where(neg, -sums, sums).astype(_U64)
    e = jnp.full(sums.shape, 1075, _I32)
    mag = jnp.stack(_element_limbs(a, jnp.zeros_like(e)), axis=-1)
    q, rem = _limb_divide(mag, cnt)
    false = jnp.zeros(sums.shape, bool)
    return _round_to_bits(neg, q, e, false, false, false, extra_sticky=rem)


def div_f64bits_by_int(bits: jnp.ndarray, cnt: jnp.ndarray) -> jnp.ndarray:
    """Correctly rounded f64 division of bit-stored doubles by positive
    ints (< 2^31): mean recombination (partial sum / merged count).

    The mantissa rides the window at its own exponent (shift 0), the
    limb divider produces 161 quotient bits + remainder-sticky, and the
    shared rounding path emits the bits. Integer-only."""
    neg, e_eff, mant, is_nan, is_pinf, is_ninf = _decompose(bits)
    limbs = _element_limbs(mant, jnp.zeros_like(e_eff))
    mag = jnp.stack(limbs, axis=-1)
    q, rem = _limb_divide(mag, cnt)
    return _round_to_bits(neg, q, e_eff, is_nan, is_pinf, is_ninf, extra_sticky=rem)


# ---------------------------------------------------------------------------
# double-f32 ("dd") arithmetic for the expression tier
# ---------------------------------------------------------------------------


def _two_sum(a, b):
    """Knuth 2Sum: s + e == a + b exactly (IEEE f32 add only)."""
    s = a + b
    bb = s - a
    e = (a - (s - bb)) + (b - bb)
    return s, e


def _split(a):
    """Dekker split: a == hi + lo with 12-bit halves (f32: 2^12+1)."""
    c = jnp.float32(4097.0) * a
    hi = c - (c - a)
    return hi, a - hi


def _two_prod(a, b):
    """p + e == a * b exactly, via Dekker splitting (no FMA dependence)."""
    p = a * b
    ah, al = _split(a)
    bh, bl = _split(b)
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


class DD(NamedTuple):
    """Unevaluated f32 pair: value = hi + lo, |lo| <= ulp(hi)/2.

    Carried by the expression tier for FLOAT64 columns on backends
    without an f64 datapath; ~2^-48 relative error per operation.
    Comparison operators compare (hi, lo) — exact on the dd values.
    """

    hi: jnp.ndarray
    lo: jnp.ndarray

    # -- arithmetic ---------------------------------------------------------
    def __add__(self, o):
        o = dd_from_any(o)
        s, e = _two_sum(self.hi, o.hi)
        e = e + self.lo + o.lo
        hi, lo = _two_sum(s, e)
        return DD(hi, lo)

    __radd__ = __add__

    def __neg__(self):
        return DD(-self.hi, -self.lo)

    def __sub__(self, o):
        return self + (-dd_from_any(o))

    def __rsub__(self, o):
        return dd_from_any(o) + (-self)

    def __mul__(self, o):
        o = dd_from_any(o)
        p, e = _two_prod(self.hi, o.hi)
        e = e + self.hi * o.lo + self.lo * o.hi
        hi, lo = _two_sum(p, e)
        return DD(hi, lo)

    __rmul__ = __mul__

    def __truediv__(self, o):
        o = dd_from_any(o)
        q1 = self.hi / o.hi
        # r = self - q1 * o, evaluated in dd
        p, e = _two_prod(q1, o.hi)
        r = self + DD(-p, -e - q1 * o.lo)
        q2 = (r.hi + r.lo) / o.hi
        hi, lo = _two_sum(q1, q2)
        return DD(hi, lo)

    def __rtruediv__(self, o):
        return dd_from_any(o) / self

    def __mod__(self, o):
        # C fmod semantics (Spark %): r = a - trunc(a/b) * b, result
        # carries a's sign with |r| < |b|. Error bound ~ |a| * 2^-48
        # (the dd quotient's rounding scaled back by b) — large
        # quotients lose low bits, like any non-iterative fmod.
        # trunc of a dd value: truncate hi; only when hi is already
        # integral can lo still carry a fractional part that moves the
        # integer part (hi int, lo < 0).
        o = dd_from_any(o)
        q = self / o
        t = q.trunc()
        r = self - t * o
        # one correction step absorbs the dd division's ulp-level error
        babs = DD(jnp.abs(o.hi), jnp.where(o.hi < 0, -o.lo, o.lo))
        r_neg_wrong = (r.hi < 0) & (self.hi >= 0)
        r_pos_wrong = (r.hi > 0) & (self.hi < 0)
        r = DD(
            jnp.where(r_neg_wrong, (r + babs).hi, jnp.where(r_pos_wrong, (r - babs).hi, r.hi)),
            jnp.where(r_neg_wrong, (r + babs).lo, jnp.where(r_pos_wrong, (r - babs).lo, r.lo)),
        )
        too_big = jnp.abs(r.hi) >= jnp.abs(o.hi)
        sgn = jnp.where(r.hi < 0, jnp.float32(-1), jnp.float32(1))
        shrunk = r - DD(sgn * babs.hi, sgn * babs.lo)
        return DD(jnp.where(too_big, shrunk.hi, r.hi), jnp.where(too_big, shrunk.lo, r.lo))

    def __rmod__(self, o):
        return dd_from_any(o) % self

    # -- comparisons (lexicographic on the normalized pair) -----------------
    def __lt__(self, o):
        o = dd_from_any(o)
        return (self.hi < o.hi) | ((self.hi == o.hi) & (self.lo < o.lo))

    def __le__(self, o):
        o = dd_from_any(o)
        return (self.hi < o.hi) | ((self.hi == o.hi) & (self.lo <= o.lo))

    def __gt__(self, o):
        o = dd_from_any(o)
        return (o.hi < self.hi) | ((self.hi == o.hi) & (o.lo < self.lo))

    def __ge__(self, o):
        o = dd_from_any(o)
        return (o.hi < self.hi) | ((self.hi == o.hi) & (o.lo <= self.lo))

    def __eq__(self, o):  # noqa: A003 — SQL equality, not identity
        o = dd_from_any(o)
        return (self.hi == o.hi) & (self.lo == o.lo)

    def __ne__(self, o):
        return ~(self == o)

    __hash__ = None

    @property
    def shape(self):
        return self.hi.shape

    def trunc(self) -> "DD":
        """Truncate the PAIR VALUE toward zero (not the halves
        separately): when hi is already integral, a fractional lo of
        the opposite sign pulls the value past the integer, so the
        truncation steps hi by one."""
        t_hi = jnp.trunc(self.hi)
        t_lo = jnp.where(t_hi == self.hi, jnp.trunc(self.lo), jnp.float32(0))
        frac_lo = (t_hi == self.hi) & (self.lo != t_lo)
        adj = jnp.where(
            frac_lo & (self.hi > 0) & (self.lo < 0), jnp.float32(-1), jnp.float32(0)
        )
        adj = adj + jnp.where(
            frac_lo & (self.hi < 0) & (self.lo > 0), jnp.float32(1), jnp.float32(0)
        )
        return DD(t_hi, t_lo + adj)

    def astype(self, dtype):
        """Narrowing view for casts out of FLOAT64."""
        if jnp.issubdtype(dtype, jnp.integer):
            # truncate the pair value first (per-half truncation casts
            # 2.9999999999 to 3, not 2), then split across both halves
            # to keep ~48-bit integers exact
            t = self.trunc()
            return t.hi.astype(dtype) + t.lo.astype(dtype)
        return self.hi.astype(dtype)


def dd_from_any(x) -> DD:
    """Promote a scalar / f32 array / DD to DD.

    Python floats split exactly on the host (real f64 there); f32 arrays
    carry lo = 0 (exact)."""
    if isinstance(x, DD):
        return x
    if isinstance(x, (int, float)):
        import numpy as np

        hi = np.float32(x)
        lo = np.float32(float(x) - float(hi))
        return DD(jnp.float32(hi), jnp.float32(lo))
    arr = jnp.asarray(x)
    if jnp.issubdtype(arr.dtype, jnp.integer):
        # exact 2-term split of wide ints: hi holds the top 24 bits, the
        # integer residual (computed exactly in int64) rounds into lo —
        # ~48-bit coverage, vs 24 for a bare f32 cast
        wide = arr.astype(_I64)
        hi = wide.astype(jnp.float32)
        lo = (wide - hi.astype(_I64)).astype(jnp.float32)
        return DD(hi, lo)
    if arr.dtype != jnp.float32:
        arr = arr.astype(jnp.float32)
    return DD(arr, jnp.zeros_like(arr))


def dd_from_f64bits(bits: jnp.ndarray) -> DD:
    """FLOAT64 bit storage -> dd: hi = round-f32(x) (bitutils' integer
    construction), lo = round-f32(x - hi).

    The residual x - hi is computed EXACTLY in the integer domain (both
    mantissas aligned at x's scale) and then rounded to 24 bits, nearest
    even — the pair captures ~48 of f64's 53 mantissa bits (relative
    representation error <= 2^-49; a 2x(f32) pair cannot do better).
    |x| beyond f32 range saturates hi to +/-inf (same loss as the plain
    f32 path this replaces); residuals under the f32 normal floor flush
    to 0."""
    from .bitutils import _f64_bits_to_f32

    hi = _f64_bits_to_f32(bits)
    neg, e_eff, mant, is_nan, is_pinf, is_ninf = _decompose(bits)
    hb = lax.bitcast_convert_type(hi, _U32)
    he = ((hb >> _U32(23)) & _U32(0xFF)).astype(_I32)
    hfrac = (hb & _U32((1 << 23) - 1)).astype(_U64)
    hmant = jnp.where(he == 0, hfrac, hfrac | _u64(1 << 23))
    he_eff = jnp.where(he == 0, 1, he).astype(_I32)
    # |hi| = hmant * 2^(he_eff - 150); express at x's scale 2^(e_eff - 1075):
    # sigma ~ 29 (30 after a rounding carry); hmant << sigma fits u64
    sigma = (he_eff - 150) - (e_eff - 1075)
    hmant_scaled = hmant << jnp.clip(sigma, 0, 40).astype(_U64)
    r = mant.astype(_I64) - hmant_scaled.astype(_I64)  # exact, |r| <= 2^29
    # residual of the SIGNED value x - hi = sign(x) * r * 2^(e_eff-1075)
    r_neg = r < 0
    lo_neg = neg != r_neg
    ra = jnp.where(r_neg, -r, r).astype(_U64)

    # highest set bit of ra (ra < 2^40)
    msb = jnp.zeros(ra.shape, _I32)
    v = ra
    for shift in (32, 16, 8, 4, 2, 1):
        big = v >= (_u64(1) << _u64(shift))
        msb = jnp.where(big, msb + shift, msb)
        v = jnp.where(big, v >> _u64(shift), v)

    # round ra to 24 bits, nearest even (residuals carry up to 29
    # significant bits — the unavoidable f64 -> 2xf32 truncation)
    sh = jnp.maximum(msb - 23, 0)
    shc = jnp.clip(sh, 0, 63).astype(_U64)
    keep = ra >> shc
    rem_mask = (_u64(1) << shc) - _u64(1)
    dropped = ra & rem_mask
    half = jnp.where(sh > 0, _u64(1) << jnp.clip(sh - 1, 0, 63).astype(_U64), _u64(0))
    round_up = (sh > 0) & (
        (dropped > half) | ((dropped == half) & ((keep & _u64(1)) == 1))
    )
    keep = keep + round_up.astype(_U64)
    carry = keep >> _u64(24) != 0
    keep = jnp.where(carry, keep >> _u64(1), keep)
    sh = sh + carry.astype(_I32)
    # msb after rounding, at ra's scale: rounded residuals are 24-bit
    # normalized (msb 23 + sh); short ones (sh == 0) keep their true msb
    msb_r = jnp.where(sh > 0, 23 + sh, msb)

    lo_exp = msb_r + (e_eff - 1075) + 127  # biased f32 exponent of the residual
    # left-align short residuals to the 24-bit mantissa position
    up = jnp.clip(23 - msb, 0, 63)
    m24 = jnp.where(sh > 0, keep, keep << up.astype(_U64))
    lo_bits = (
        jnp.clip(lo_exp, 1, 254).astype(_U32) << _U32(23)
    ) | (m24.astype(_U32) & _U32((1 << 23) - 1))
    lo_sign = jnp.where(lo_neg, _U32(0x80000000), _U32(0))
    lo = lax.bitcast_convert_type(lo_bits | lo_sign, jnp.float32)
    lo = jnp.where((ra == 0) | (lo_exp < 1) | (lo_exp > 254), jnp.float32(0), lo)
    lo = jnp.where(is_nan | is_pinf | is_ninf | (he == 0xFF), jnp.float32(0), lo)
    return DD(hi, lo)


def add2_f64bits(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Correctly rounded f64 sum of two bit-stored doubles, ELEMENTWISE.

    The windowed accumulator with one segment per element is a scatter
    over [2N] rows — measured ~0.34 s/iter at 1M rows inside the fused
    pipelines (the round-4 flagship regression, NOTES_ROUND4 item 5).
    A two-addend sum needs no window at all: align the smaller mantissa
    into an 8-bit guard extension of the larger (61 bits total, flat
    u64 lanes), fold bits beyond the guard into a sticky (for effective
    subtraction the floor correction R-1 keeps the value bracketed:
    gap >= guard implies at most one bit of cancellation, so the
    rounding position stays above the guard LSB and the sticky is
    exact), then round to nearest-even with the shared subnormal /
    overflow handling. Pure elementwise integer ops — bit-identical on
    every backend, verified against real-f64 hardware addition on the
    CPU tier (tests).
    """
    GUARD = 8
    neg_a, e_a, m_a, nan_a, pinf_a, ninf_a = _decompose(a)
    neg_b, e_b, m_b, nan_b, pinf_b, ninf_b = _decompose(b)

    a_big = (e_a > e_b) | ((e_a == e_b) & (m_a >= m_b))
    e_big = jnp.where(a_big, e_a, e_b)
    m_big = jnp.where(a_big, m_a, m_b)
    neg_big = jnp.where(a_big, neg_a, neg_b)
    e_sm = jnp.where(a_big, e_b, e_a)
    m_sm = jnp.where(a_big, m_b, m_a)
    neg_sm = jnp.where(a_big, neg_b, neg_a)

    gap = e_big - e_sm  # >= 0
    big = m_big << _u64(GUARD)  # <= 61 bits
    sh_r = jnp.clip(gap - GUARD, 0, 63).astype(_U64)
    sh_l = jnp.clip(GUARD - gap, 0, GUARD).astype(_U64)
    aligned = jnp.where(gap >= GUARD, m_sm >> sh_r, m_sm << sh_l)
    dropped = jnp.where(gap >= GUARD, m_sm & ((_u64(1) << sh_r) - _u64(1)), _u64(0))
    sticky = dropped != 0

    same_sign = neg_big == neg_sm
    r = jnp.where(same_sign, big + aligned, big - aligned)
    # effective subtraction with dropped bits: true value is r - frac,
    # frac in (0,1) guard-LSB units -> floor is r-1 with sticky kept
    r = jnp.where(~same_sign & sticky, r - _u64(1), r)

    # highest set bit of r (<= 61)
    p = jnp.zeros(r.shape, _I32)
    v = r
    for shift in (32, 16, 8, 4, 2, 1):
        bigger = v >= (_u64(1) << _u64(shift))
        p = jnp.where(bigger, p + shift, p)
        v = jnp.where(bigger, v >> _u64(shift), v)

    # drop q bits to land a 53-bit mantissa; the subnormal floor pins
    # E_res = e_big - GUARD + q >= 1
    q = jnp.maximum(p - 52, 1 + GUARD - e_big)
    q_pos = jnp.clip(q, 0, 63).astype(_U64)
    keep_r = r >> q_pos
    gmask = (_u64(1) << q_pos) - _u64(1)
    low = r & gmask
    half = jnp.where(q > 0, _u64(1) << jnp.clip(q - 1, 0, 63).astype(_U64), _u64(0))
    round_up = (q > 0) & (
        (low > half) | ((low == half) & (sticky | ((keep_r & _u64(1)) == 1)))
    )
    keep_r = keep_r + round_up.astype(_U64)
    keep_l = r << jnp.clip(-q, 0, 63).astype(_U64)
    keep = jnp.where(q > 0, keep_r, keep_l)
    ovf = keep >> _u64(53) != 0
    keep = jnp.where(ovf, keep >> _u64(1), keep)
    q = q + ovf.astype(_I32)

    e_res = e_big - GUARD + q
    subnormal = keep < _u64(1 << 52)
    biased = jnp.clip(e_res, 0, 0x7FF).astype(_U64)
    bits = jnp.where(
        subnormal, keep, (biased << _u64(52)) | (keep & _u64((1 << 52) - 1))
    )
    inf_bits = _u64(0x7FF) << _u64(52)
    bits = jnp.where((~subnormal) & (e_res >= 0x7FF), inf_bits, bits)
    zero = r == 0
    bits = jnp.where(zero, _u64(0), bits)
    bits = bits | jnp.where(neg_big & ~zero, _u64(1) << _u64(63), _u64(0))

    # IEEE specials: NaN dominates; inf +/- finite = inf; inf - inf = NaN
    has_pinf = pinf_a | pinf_b
    has_ninf = ninf_a | ninf_b
    bits = jnp.where(has_pinf & ~has_ninf, inf_bits, bits)
    bits = jnp.where(has_ninf & ~has_pinf, inf_bits | (_u64(1) << _u64(63)), bits)
    bits = jnp.where(nan_a | nan_b | (has_pinf & has_ninf), inf_bits | _u64(1 << 51), bits)
    return bits


def dd_to_f64bits(x: DD) -> jnp.ndarray:
    """dd -> FLOAT64 bits, exactly: widen each half losslessly to f64
    bits and round their exact pair-sum once through the elementwise
    two-addend adder."""
    from .bitutils import _f32_to_f64_bits

    return add2_f64bits(_f32_to_f64_bits(x.hi), _f32_to_f64_bits(x.lo))

"""Spark-semantics string -> decimal cast (DECIMAL32/64/128).

Behavioral parity with reference cast_string.cu:243-574:

- pass 1 (validate_and_exponent :243-369): state machine over the chars
  accepting [ws] [+-] digits ['.' digits] [eE [+-] digits] [ws], one
  decimal point max, whitespace after exponent digits is INVALID (quirk
  kept), empty/sign-only strings invalid; returns sign, first digit
  index and the decimal location adjusted by the (overflow-checked)
  exponent.
- pass 2 (string_to_decimal_kernel :385-574): accumulate digits up to
  precision / scale cutoff, round half-up away from zero at the cutoff
  digit (detecting a digit-count increase from carry ripple), count
  significant digits before the decimal, zero-pad up to the decimal
  location and down to scale, with target-type overflow checks at every
  multiply — rows that fail become null (non-ANSI) or raise CastError.

Scale follows the cudf convention (negative = fractional digits).
Output type by precision: <=9 DECIMAL32, <=18 DECIMAL64, else DECIMAL128
(string_to_decimal :792-801).

TPU-first shape: both passes are ``lax.scan`` state machines over the
padded [N, L] char matrix carried as struct-of-arrays; the digit
accumulator is a [N, 4] uint32 limb magnitude (ops/limbs.py) so one code
path serves all three decimal widths; every counter the reference keeps
per-thread becomes a prefix-sum/cummax over the char axis.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..columnar import Column
from ..columnar.dtype import DType, TypeId, decimal32, decimal64, decimal128
from . import limbs as L
from .cast_string import CastError, _is_ws, _padded_chars, _validate_ansi

__all__ = ["string_to_decimal"]

_LIMITS = {  # (positive magnitude limit, negative magnitude limit)
    TypeId.DECIMAL32: (2**31 - 1, 2**31),
    TypeId.DECIMAL64: (2**63 - 1, 2**63),
    TypeId.DECIMAL128: (2**127 - 1, 2**127),
}

# pass-1 states
_D = 0  # reading value digits (includes just-after-dot)
_EOS = 1  # just read e/E: exponent-or-sign
_ES = 2  # just read exponent sign
_E = 3  # reading exponent digits
_W = 4  # trailing whitespace
_X = 5  # invalid


@partial(jax.jit, static_argnames=("max_len", "precision", "scale", "pos_limit", "neg_limit"))
def _parse_decimal(
    chars: jnp.ndarray,  # [N, L] uint8
    lens: jnp.ndarray,  # [N] int32
    in_valid: jnp.ndarray,  # [N] bool
    max_len: int,
    precision: int,
    scale: int,
    pos_limit: int,
    neg_limit: int,
):
    n = chars.shape[0]
    ws = _is_ws(chars)
    digit = (chars >= ord("0")) & (chars <= ord("9"))
    isdot = chars == ord(".")
    is_e = (chars == ord("e")) | (chars == ord("E"))

    # --- leading whitespace / sign ---------------------------------------
    inb = jnp.arange(max_len, dtype=jnp.int32)[None, :] < lens[:, None]
    nonws = (~ws) & inb
    i0 = jnp.where(jnp.any(nonws, axis=1), jnp.argmax(nonws, axis=1).astype(jnp.int32), lens)
    c0 = jnp.take_along_axis(chars, jnp.clip(i0, 0, max_len - 1)[:, None], axis=1)[:, 0]
    has_sign = ((c0 == ord("+")) | (c0 == ord("-"))) & (i0 < lens)
    positive = ~((c0 == ord("-")) & has_sign)
    istart = i0 + has_sign.astype(jnp.int32)
    valid = in_valid & (lens > 0) & (istart < lens)

    # --- pass 1: validation state machine + exponent ----------------------
    def step1(carry, j):
        state, dot_seen, dot_rel, last_digit_abs, exp_mag, exp_pos, exp_seen, prev_digit = carry
        c = chars[:, j]
        active = (j >= istart) & (j < lens)
        rel = j - istart
        d, w, dot, e = digit[:, j], ws[:, j], isdot[:, j], is_e[:, j]

        from_d = jnp.where(
            d, _D,
            jnp.where(
                dot & ~dot_seen, _D,
                jnp.where(e, _EOS, jnp.where(w & (rel != 0), _W, _X)),
            ),
        )
        from_eos = jnp.where(
            c == ord("+"), _ES,
            jnp.where(
                c == ord("-"), _ES,
                jnp.where(w & (rel != 0), _W, jnp.where(d, _E, _X)),
            ),
        )
        from_es_e = jnp.where(d, _E, _X)
        from_w = jnp.where(w, _W, _X)
        nxt = jnp.where(
            state == _D, from_d,
            jnp.where(
                state == _EOS, from_eos,
                jnp.where((state == _ES) | (state == _E), from_es_e, from_w),
            ),
        )
        nxt = jnp.where(active, nxt, state)

        # record first dot position (relative)
        new_dot = active & (state == _D) & dot & ~dot_seen
        dot_rel = jnp.where(new_dot, rel, dot_rel)
        dot_seen = dot_seen | new_dot

        # last_digit: leaving the digit run for e/ws, only when the previous
        # char was an actual digit (cast_string.cu:344-347 last_state check)
        leave = active & (state == _D) & prev_digit & ((nxt == _EOS) | (nxt == _W))
        last_digit_abs = jnp.where(leave & (last_digit_abs == lens), j, last_digit_abs)

        # exponent sign / digits
        exp_pos = jnp.where(active & (state == _EOS) & (c == ord("-")), False, exp_pos)
        consume_exp = active & ((state == _EOS) | (state == _ES) | (state == _E)) & d & (nxt == _E)
        dig = (c - ord("0")).astype(jnp.uint64)
        first = consume_exp & (exp_mag == 0)
        lim = jnp.uint64(2**63 - 1)
        ovf = (exp_mag > lim // jnp.uint64(10)) | (exp_mag * jnp.uint64(10) > lim - dig)
        exp_new = jnp.where(first, dig, exp_mag * jnp.uint64(10) + dig)
        bad_exp = consume_exp & ~first & ovf
        nxt = jnp.where(bad_exp, _X, nxt)
        exp_mag = jnp.where(consume_exp & ~bad_exp, exp_new, exp_mag)
        exp_seen = exp_seen | consume_exp

        prev_digit = jnp.where(active, d, prev_digit)
        return (nxt, dot_seen, dot_rel, last_digit_abs, exp_mag, exp_pos, exp_seen, prev_digit), None

    init1 = (
        jnp.zeros((n,), jnp.int32),
        jnp.zeros((n,), bool),
        jnp.zeros((n,), jnp.int32),
        lens,  # last_digit defaults to len (abs)
        jnp.zeros((n,), jnp.uint64),
        jnp.ones((n,), bool),
        jnp.zeros((n,), bool),
        jnp.zeros((n,), bool),
    )
    (state, dot_seen, dot_rel, last_digit_abs, exp_mag, exp_pos, _exp_seen, _pd), _ = lax.scan(
        step1, init1, jnp.arange(max_len, dtype=jnp.int32)
    )
    valid = valid & (state != _X)

    exp_val = jnp.where(exp_pos, exp_mag.astype(jnp.int64), -exp_mag.astype(jnp.int64))
    dl0 = jnp.where(dot_seen, dot_rel, last_digit_abs - istart).astype(jnp.int64)
    decimal_location = dl0 + exp_val  # pre-rounding (cast_string.cu:363-366)

    # --- pass 2 precomputation (prefix counters over the char axis) -------
    j_idx = jnp.arange(max_len, dtype=jnp.int32)[None, :]
    after_start = (j_idx >= istart[:, None]) & inb
    # break at first char after istart that is neither digit nor dot
    breaker = after_start & ~digit & ~isdot
    has_break = jnp.any(breaker, axis=1)
    break_pos = jnp.where(has_break, jnp.argmax(breaker, axis=1).astype(jnp.int32), lens)

    last_digit = decimal_location - scale  # :444
    in_run = after_start & (j_idx < break_pos[:, None])
    dmask = in_run & digit & (last_digit >= 0)[:, None]  # :453 loop guard

    td = jnp.cumsum(dmask, axis=1).astype(jnp.int64)  # total_digits incl. current
    nonzero = chars != ord("0")
    sig_seed = dmask & (nonzero | (td > decimal_location[:, None]))
    found_prior = jnp.cumsum(sig_seed, axis=1) - sig_seed.astype(jnp.int64) > 0
    sig = dmask & (found_prior | nonzero | (td > decimal_location[:, None]))
    np_ = jnp.cumsum(sig, axis=1).astype(jnp.int64)  # num_precise_digits incl. current

    np_excl = np_ - sig.astype(jnp.int64)
    td_excl = td - dmask.astype(jnp.int64)
    cutoff_cond = dmask & ((np_excl + 1 > precision) | (td_excl + 1 > last_digit[:, None]))
    has_cut = jnp.any(cutoff_cond, axis=1)
    cut_pos = jnp.where(has_cut, jnp.argmax(cutoff_cond, axis=1).astype(jnp.int32), max_len)
    acc_mask = dmask & (j_idx < cut_pos[:, None])

    # counters at the end of accumulation (exclusive of the cutoff digit)
    total_digits = jnp.sum(acc_mask, axis=1).astype(jnp.int64)
    num_precise = jnp.sum(sig & acc_mask, axis=1).astype(jnp.int64)

    # --- accumulate magnitude over the char axis --------------------------
    def step2(acc, j):
        m = acc_mask[:, j]
        dig = (chars[:, j] - ord("0")).astype(jnp.uint32)
        nxt = L.mul10_add(acc, jnp.where(m, dig, 0))
        return jnp.where(m[:, None], nxt, acc), None

    acc0 = jnp.zeros((n, 4), jnp.uint32)
    acc, _ = lax.scan(step2, acc0, jnp.arange(max_len, dtype=jnp.int32))

    limit = jnp.where(
        positive[:, None],
        jnp.asarray(L.from_ints([pos_limit], 4))[0][None, :],
        jnp.asarray(L.from_ints([neg_limit], 4))[0][None, :],
    )

    # --- rounding at the cutoff digit (:466-506) --------------------------
    cut_digit = jnp.take_along_axis(chars, jnp.clip(cut_pos, 0, max_len - 1)[:, None], axis=1)[
        :, 0
    ]
    round_up = has_cut & ((cut_digit - ord("0")) >= 5) & (cut_digit >= ord("0")) & (
        cut_digit <= ord("9")
    )
    acc_inc, carry = L.add_small(acc, jnp.where(round_up, 1, 0))
    inc_overflow = round_up & (L.gt(acc_inc, limit) | (carry != 0))
    valid = valid & ~inc_overflow
    digit_added = round_up & ~L.is_zero(acc) & L.is_all_nines(acc)
    acc = jnp.where(round_up[:, None], acc_inc, acc)
    rounding_digits = jnp.where(digit_added, 1, 0).astype(jnp.int64)
    total_digits = total_digits + rounding_digits
    num_precise = num_precise + rounding_digits
    decimal_location_r = decimal_location + rounding_digits

    # --- significant digits before the decimal in the string (:411-433) ---
    count_region = after_start & ~isdot & (
        j_idx < jnp.where(jnp.any(after_start & is_e, axis=1),
                          jnp.argmax(after_start & is_e, axis=1).astype(jnp.int32), lens)[:, None]
    )
    df = jnp.cumsum(count_region, axis=1)  # digits_found incl. current
    counted = count_region & (df <= decimal_location[:, None])
    started = jnp.cumsum(counted & nonzero, axis=1) > 0
    sig_in_string = jnp.sum(counted & started, axis=1).astype(jnp.int64)

    # --- zero padding to the decimal location (:527-539) ------------------
    zeros_to_decimal = jnp.maximum(
        0,
        jnp.where(
            scale > 0,
            decimal_location_r - total_digits - scale,
            decimal_location_r - total_digits,
        ),
    )
    sig_before_decimal = sig_in_string + zeros_to_decimal + rounding_digits
    valid = valid & ~(precision + scale < sig_before_decimal)  # :522

    acc, ovf1 = _mul_pow10_checked(acc, zeros_to_decimal, limit)
    valid = valid & ~ovf1
    num_precise = num_precise + zeros_to_decimal

    # --- zero padding down to scale (:541-556) ----------------------------
    sig_preceding_zeros = jnp.where(decimal_location_r < 0, -decimal_location_r, 0)
    digits_after_decimal = num_precise - sig_before_decimal + sig_preceding_zeros
    digits_needed = jnp.minimum(precision - sig_before_decimal, -scale)
    pad = jnp.maximum(0, digits_needed - digits_after_decimal)
    acc, ovf2 = _mul_pow10_checked(acc, pad, limit)
    valid = valid & ~ovf2

    return acc, positive, valid


def _mul_pow10_checked(
    acc: jnp.ndarray, k: jnp.ndarray, limit: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """acc * 10^k with the reference's per-step overflow semantics
    (will_overflow before each *10, cast_string.cu:528-538): equivalent to
    checking the final product against the limit; k > 38 with acc != 0
    always overflows (10^39 > 2^127)."""
    p = L.pow10(k, 4)
    prod = L.mul(acc, p, 8)
    lo, hi = prod[..., :4], prod[..., 4:]
    nz = ~L.is_zero(acc)
    overflow = nz & ((k > 38) | ~L.is_zero(hi) | L.gt(lo, limit))
    out = jnp.where((k > 0)[..., None] & ~overflow[..., None], lo, acc)
    return out, overflow


def string_to_decimal(col: Column, ansi_mode: bool, precision: int, scale: int) -> Column:
    """String column -> decimal column. Parity: cast_string.cu :785-801.

    ``scale`` is the cudf scale (negative = fractional digits).
    """
    if col.dtype.id != TypeId.STRING:
        raise ValueError("string_to_decimal expects a STRING column")
    if not (1 <= precision <= 38):
        raise ValueError(f"precision must be in [1, 38], got {precision}")

    if precision <= 9:
        out_dtype = decimal32(scale)
    elif precision <= 18:
        out_dtype = decimal64(scale)
    else:
        out_dtype = decimal128(scale)

    n = len(col)
    if n == 0:
        if out_dtype.id == TypeId.DECIMAL128:
            return Column(out_dtype, data=jnp.zeros((0, 4), jnp.uint32))
        return Column(out_dtype, data=jnp.zeros((0,), out_dtype.jnp_dtype))

    chars, lens, max_len = _padded_chars(col)
    pos_limit, neg_limit = _LIMITS[out_dtype.id]
    acc, positive, valid = _parse_decimal(
        chars, lens, col.valid_mask(), max_len, precision, scale, pos_limit, neg_limit
    )

    signed = L.to_twos_complement(acc, ~positive)
    signed = jnp.where(valid[:, None], signed, 0)
    if out_dtype.id == TypeId.DECIMAL128:
        data = signed
    elif out_dtype.id == TypeId.DECIMAL64:
        data = (
            signed[:, 0].astype(jnp.uint64) | (signed[:, 1].astype(jnp.uint64) << jnp.uint64(32))
        )
        data = lax.bitcast_convert_type(data, jnp.int64)
    else:
        data = lax.bitcast_convert_type(signed[:, 0], jnp.int32)

    if ansi_mode:
        _validate_ansi(valid, col)
    return Column(out_dtype, data=data, validity=valid)

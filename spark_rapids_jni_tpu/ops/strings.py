"""String operator tier (cudf strings replacement, SURVEY §2.8).

The RAPIDS plugin offloads Spark string expressions to cudf's strings
kernels; this module rebuilds the surface TPU-first. Ragged Arrow
(offsets + chars) data is densified to a padded [N, L] byte matrix
(L = max length in the batch — one static shape per size class, the
XLA-friendly formulation of cudf's warp-per-string loops), operated on
vectorized, and re-compacted to ragged storage.

Ops: length, upper/lower (ASCII), substring (start/len, negative start
from the end like Spark SUBSTR), concat (columns + scalar separator),
contains / startswith / endswith (literal pattern), strip.
Null propagation follows Spark: null in -> null out.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..columnar.dtype import TypeId
from ..utils.dispatch import op_boundary

__all__ = [
    "length",
    "upper",
    "lower",
    "substring",
    "concat",
    "concat_ws",
    "contains",
    "instr",
    "startswith",
    "endswith",
    "strip",
]


def _check_string(col: Column) -> None:
    if col.dtype.id != TypeId.STRING:
        raise ValueError("string op on non-string column")


def to_padded(col: Column) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Ragged -> ([N, L] uint8 right-padded with 0, [N] int32 lengths).
    Width comes from the memoized ``Column.max_char_len`` (the per-call
    device sync here used to dominate whole kernels through the
    tunnel)."""
    _check_string(col)
    offs = col.offsets
    lens = offs[1:] - offs[:-1]
    n = len(col)
    if n == 0:
        return jnp.zeros((0, 1), jnp.uint8), jnp.zeros((0,), jnp.int32)
    max_len = max(col.max_char_len, 1)
    nchars = int(col.chars.shape[0])
    if nchars == 0:  # every row empty (or null): nothing to gather
        return jnp.zeros((n, max_len), jnp.uint8), lens.astype(jnp.int32)
    idx = offs[:-1, None] + jnp.arange(max_len, dtype=jnp.int32)[None, :]
    inb = jnp.arange(max_len, dtype=jnp.int32)[None, :] < lens[:, None]
    padded = jnp.where(inb, col.chars[jnp.clip(idx, 0, nchars - 1)], 0)
    return padded, lens.astype(jnp.int32)


def from_padded(padded: jnp.ndarray, lens: jnp.ndarray, validity=None) -> Column:
    """[N, L] bytes + [N] lengths -> ragged STRING column (compaction).

    Rides ragged_compact (word-granular funnel gathers, ~2 ns/B): the
    padded matrix flattens to a pool whose per-row base r*L is monotone
    — exactly the compaction contract. The old padded[row_of, pos] form
    was one element gather per CHARACTER (~8 ns/B, the slow class)."""
    from .ragged_bytes import ragged_compact

    lens = lens.astype(jnp.int32)
    offs = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
    total = int(offs[-1])  # host sync: chars allocation size
    if total == 0:
        chars = jnp.zeros((0,), jnp.uint8)
    else:
        n, width = padded.shape
        base = jnp.arange(n, dtype=jnp.int64) * width
        chars = ragged_compact(padded.reshape(-1), base, offs.astype(jnp.int64), total)
    return Column(dt.STRING, validity=validity, offsets=offs, chars=chars)


@op_boundary("strings.length")
def length(col: Column) -> Column:
    """Byte length per row (Spark length() on binary semantics)."""
    _check_string(col)
    lens = (col.offsets[1:] - col.offsets[:-1]).astype(jnp.int32)
    return Column(dt.INT32, data=lens, validity=col.validity)


def _case_map_ascii(col: Column, offset: int, lo: int, hi: int) -> Column:
    padded, lens = to_padded(col)
    in_range = (padded >= lo) & (padded <= hi)
    out = jnp.where(in_range, padded + jnp.uint8(offset), padded)
    return from_padded(out, lens, col.validity)


def _case_map_unicode(col: Column, to_upper: bool) -> Column:
    """UTF-8-aware 1:1 case map over codepoints (BMP table; multi-char
    special casings identity-mapped — same core restriction as cudf's
    to_upper/to_lower). Re-encodes because cased pairs can change UTF-8
    length (e.g. U+023A <-> U+2C65 is 2 vs 3 bytes)."""
    from .utf8 import case_table, decode_padded, encode_padded

    padded, lens = to_padded(col)
    cp, cp_lens, _ = decode_padded(padded, lens)
    tab = case_table(to_upper)
    mapped = jnp.where(cp < 0x10000, tab[jnp.clip(cp, 0, 0xFFFF)], cp)
    out, out_lens = encode_padded(mapped, cp_lens)
    return from_padded(out, out_lens, col.validity)


def _is_ascii(col: Column) -> bool:
    if col.chars.shape[0] == 0:
        return True
    return bool(jnp.all(col.chars < 0x80))


@op_boundary("strings.upper")
def upper(col: Column) -> Column:
    """Spark upper(): Unicode 1:1 case map; pure-ASCII batches take the
    branchless byte path (one data-dependent host check, same class of
    sync as the padded-width allocation)."""
    _check_string(col)
    if _is_ascii(col):
        return _case_map_ascii(col, -32 & 0xFF, ord("a"), ord("z"))
    return _case_map_unicode(col, to_upper=True)


@op_boundary("strings.lower")
def lower(col: Column) -> Column:
    _check_string(col)
    if _is_ascii(col):
        return _case_map_ascii(col, 32, ord("A"), ord("Z"))
    return _case_map_unicode(col, to_upper=False)


@op_boundary("strings.substring")
def substring(col: Column, start: int, slen: Optional[int] = None) -> Column:
    """Spark SUBSTRING semantics: 1-based start; 0 treated as 1; negative
    start counts from the end; slen None -> to end of string."""
    _check_string(col)
    padded, lens = to_padded(col)
    n, L = padded.shape
    # Spark UTF8String.substringSQL: the window [begin, begin+len) is
    # computed BEFORE clamping, so a negative start consumes its length
    # budget off-string (substring('hello', -6, 3) == 'he', -10 -> '')
    if start > 0:
        begin_raw = jnp.full((n,), start - 1, jnp.int32)
    elif start == 0:
        begin_raw = jnp.zeros((n,), jnp.int32)
    else:
        begin_raw = lens + start
    end_raw = lens if slen is None else begin_raw + max(slen, 0)
    begin = jnp.clip(begin_raw, 0, lens)
    end = jnp.clip(end_raw, 0, lens)
    out_lens = jnp.maximum(end - begin, 0)
    j = jnp.arange(L, dtype=jnp.int32)[None, :]
    src = begin[:, None] + j
    out = jnp.where(j < out_lens[:, None], jnp.take_along_axis(padded, jnp.clip(src, 0, L - 1), axis=1), 0)
    return from_padded(out, out_lens, col.validity)


@op_boundary("strings.concat")
def concat(
    cols: Sequence[Column], separator: bytes = b"", null_policy: str = "propagate"
) -> Column:
    """Row-wise concatenation with a scalar separator.

    ``null_policy`` selects between Spark's two distinct operators
    (they differ ONLY in null handling, so both ride one kernel):

    - ``"propagate"`` — Spark ``concat`` semantics: a null row in any
      input nulls the whole output row.
    - ``"skip"`` — Spark ``concat_ws`` semantics: null inputs are
      skipped entirely (contributing neither text nor a separator
      slot); the result is never null for a non-null separator.
    """
    if null_policy not in ("propagate", "skip"):
        raise ValueError(f"unknown null_policy {null_policy!r}")
    cols = list(cols)
    if not cols:
        raise ValueError("concat needs at least one column")
    for c in cols:
        _check_string(c)
    sep = np.frombuffer(separator, np.uint8)
    n = len(cols[0])

    parts = [to_padded(c) for c in cols]
    if null_policy == "skip":
        kept = [
            jnp.ones((n,), bool) if c.validity is None else c.validity for c in cols
        ]
    else:
        # every input contributes text; nullness is applied to the
        # output validity mask instead
        kept = [jnp.ones((n,), bool)] * len(cols)

    # per-row output length: kept parts + a separator before each kept
    # part that follows at least one earlier kept part
    out_lens = jnp.zeros((n,), jnp.int32)
    emitted = jnp.zeros((n,), bool)
    sep_present: list = []
    for k, (_, lens) in enumerate(parts):
        present = (emitted & kept[k]) if (k > 0 and len(sep)) else jnp.zeros((n,), bool)
        sep_present.append(present)
        out_lens = out_lens + present * len(sep) + jnp.where(kept[k], lens, 0)
        emitted = emitted | kept[k]
    L = max(int(jnp.max(out_lens)) if n else 1, 1)

    out = jnp.zeros((n, L), jnp.uint8)
    cursor = jnp.zeros((n,), jnp.int32)
    for k, (padded, lens) in enumerate(parts):
        if k > 0 and len(sep):
            sep_lens = jnp.where(sep_present[k], len(sep), 0).astype(jnp.int32)
            sep_j = jnp.arange(len(sep), dtype=jnp.int32)[None, :]
            dest = cursor[:, None] + sep_j
            out = _scatter_rows(out, dest, jnp.broadcast_to(jnp.asarray(sep)[None, :], (n, len(sep))), sep_lens, sep_j)
            cursor = cursor + sep_lens
        eff_lens = jnp.where(kept[k], lens, 0).astype(jnp.int32)
        src_j = jnp.arange(padded.shape[1], dtype=jnp.int32)[None, :]
        dest = cursor[:, None] + src_j
        out = _scatter_rows(out, dest, padded, eff_lens, src_j)
        cursor = cursor + eff_lens

    validity = None
    if null_policy == "propagate":
        masks = [c.validity for c in cols if c.validity is not None]
        if masks:
            v = masks[0]
            for m in masks[1:]:
                v = v & m
            validity = v
    return from_padded(out, out_lens, validity)


@op_boundary("strings.concat_ws")
def concat_ws(cols: Sequence[Column], separator: bytes) -> Column:
    """Spark ``concat_ws``: null inputs skipped, never-null output."""
    return concat(cols, separator, null_policy="skip")


def _scatter_rows(out, dest, vals, lens, src_j):
    """Scatter vals[:, :lens] into out rows at dest positions (bounded)."""
    L = out.shape[1]
    keep = src_j < lens[:, None]
    dest_c = jnp.clip(dest, 0, L - 1)
    contrib = jnp.zeros_like(out).at[
        jnp.arange(out.shape[0], dtype=jnp.int32)[:, None], dest_c
    ].add(jnp.where(keep, vals, 0))
    return out | contrib  # disjoint regions: OR == placement


def _match_at(padded, lens, pattern: bytes, pos):
    """[N, P?] bool: pattern matches at byte position(s) pos."""
    pat = np.frombuffer(pattern, np.uint8)
    m = len(pat)
    n, L = padded.shape
    if m == 0:
        return jnp.ones_like(pos, bool)
    ok = jnp.ones(pos.shape, bool)
    for t in range(m):
        src = jnp.clip(pos + t, 0, L - 1)
        ok = ok & (jnp.take_along_axis(padded, src, axis=1) == pat[t])
    ok = ok & (pos + m <= lens[:, None])
    return ok


def _bool_col(data, validity) -> Column:
    return Column(dt.BOOL8, data=data.astype(jnp.uint8), validity=validity)


@op_boundary("strings.contains")
def contains(col: Column, pattern: bytes) -> Column:
    """Literal substring search (Spark Contains)."""
    _check_string(col)
    padded, lens = to_padded(col)
    n, L = padded.shape
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    pos = jnp.broadcast_to(pos, (n, L))
    hit = jnp.any(_match_at(padded, lens, pattern, pos), axis=1)
    return _bool_col(hit, col.validity)


@op_boundary("strings.startswith")
def startswith(col: Column, pattern: bytes) -> Column:
    _check_string(col)
    padded, lens = to_padded(col)
    pos = jnp.zeros((padded.shape[0], 1), jnp.int32)
    return _bool_col(_match_at(padded, lens, pattern, pos)[:, 0], col.validity)


@op_boundary("strings.endswith")
def endswith(col: Column, pattern: bytes) -> Column:
    _check_string(col)
    padded, lens = to_padded(col)
    pos = jnp.maximum(lens - len(pattern), 0)[:, None]
    ok = _match_at(padded, lens, pattern, pos)[:, 0] & (lens >= len(pattern))
    return _bool_col(ok, col.validity)


@op_boundary("strings.strip")
def strip(col: Column) -> Column:
    """Trim ASCII spaces both sides (Spark trim)."""
    _check_string(col)
    padded, lens = to_padded(col)
    n, L = padded.shape
    j = jnp.arange(L, dtype=jnp.int32)[None, :]
    is_space = (padded == ord(" ")) & (j < lens[:, None])
    non_space = (padded != ord(" ")) & (j < lens[:, None])
    any_ns = jnp.any(non_space, axis=1)
    first_ns = jnp.argmax(non_space, axis=1).astype(jnp.int32)
    last_ns = (L - 1 - jnp.argmax(non_space[:, ::-1], axis=1)).astype(jnp.int32)
    begin = jnp.where(any_ns, first_ns, 0)
    out_lens = jnp.where(any_ns, last_ns - first_ns + 1, 0)
    src = jnp.clip(begin[:, None] + j, 0, L - 1)
    out = jnp.where(j < out_lens[:, None], jnp.take_along_axis(padded, src, axis=1), 0)
    return from_padded(out, out_lens, col.validity)


@op_boundary("strings.instr")
def instr(col: Column, pattern: bytes) -> Column:
    """Spark instr/locate: 1-based CHARACTER position of the first
    literal occurrence, 0 when absent (empty pattern -> 1). A valid
    UTF-8 needle can only match at character boundaries, so the byte
    hit converts to a character index by counting lead bytes before it."""
    _check_string(col)
    padded, lens = to_padded(col)
    n, L = padded.shape
    pos = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[None, :], (n, L))
    hits = _match_at(padded, lens, pattern, pos)
    any_hit = jnp.any(hits, axis=1)
    first = jnp.argmax(hits, axis=1).astype(jnp.int32)
    # byte position -> character position: lead (non-continuation)
    # bytes strictly before the hit
    lead = ((padded & 0xC0) != 0x80) & (pos < lens[:, None])
    cum = jnp.cumsum(lead.astype(jnp.int32), axis=1)
    chars_before = jnp.where(
        first > 0,
        jnp.take_along_axis(cum, jnp.clip(first - 1, 0, L - 1)[:, None], axis=1)[:, 0],
        0,
    )
    out = jnp.where(any_hit, chars_before + 1, 0)
    if len(pattern) == 0:
        out = jnp.ones((n,), jnp.int32)
    return Column(dt.INT32, data=out, validity=col.validity)

"""Subresult cache (srjt-cache, ISSUE 17): stage outputs as governed
memgov catalog entries.

Scan- and aggregate-stage results are registered with the memory
governor's BufferCatalog (``kind="cache"``) so eviction, spill tiering,
and byte accounting ride the EXISTING governor: a cached subresult can
be demoted host-ward under pressure and re-materializes (CRC-checked)
on the next hit — a corrupt or spilled-away entry is a miss that
recomputes, never stale bytes.

Keys are ``("sub", param_fp, literal_values, table_stamps, catalog_sig)``
tuples: the parameterized structural fingerprint of the subtree, the
literal bindings that specialize it, the generation stamps of every
table the subtree scans (tablegen.py — a changed table makes the old
key unreachable), and the schema signature of the bound catalog. The
compute side is single-flighted per key, so two concurrent queries
sharing a subplan compute it once (multi-query optimization at the
stage level).

Capacity: ``SRJT_CACHE_SUBRESULT_BYTES`` bounds what the cache itself
retains (LRU unregistration) ON TOP of the governor's own pressure
machinery — the cache can only ever shrink the governed footprint, the
governor stays the authority on where the bytes live.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Callable, Dict, Optional

from ..utils import faultinj, metrics, tracing
from ..utils.faultinj import CacheEvictInjected
from .flight import SingleFlight
from .plancache import _lru_touch, _pop_oldest

__all__ = ["SubresultCache"]


def _durable(name: str):
    return metrics.registry().counter(name)


class _SubEntry:
    __slots__ = ("regkey", "handle", "nbytes")

    def __init__(self, regkey: str, handle, nbytes: int):
        self.regkey = regkey
        self.handle = handle
        self.nbytes = nbytes


def _regkey(key) -> str:
    return "cache.sub." + hashlib.sha1(repr(key).encode()).hexdigest()[:16]


class SubresultCache:
    """key -> governed SpillableHandle map with LRU byte-capping."""

    def __init__(self, max_bytes: int):
        self._lock = threading.RLock()
        from ..analysis.lockdep import track as _race_track

        self._entries: Dict = _race_track({}, "cache.sub.entries")
        self._bytes = 0
        self._max_bytes = int(max_bytes)
        self._flight = SingleFlight("sub")

    # -- the hook _Exec.run calls --------------------------------------------

    def lookup_or_compute(self, key, thunk: Callable):
        """The compiled-stage hook: return the cached subtree result,
        or compute it (single-flighted) and insert. Every failure mode
        of the cached side — injected eviction, spill-tier corruption,
        a concurrently-closed handle — degrades to recompute."""
        try:
            # chaos choke point: a `cache_evict` rule keyed cache.* (or
            # this specific subtree's op) forces the entry out mid-query
            faultinj.maybe_inject(f"cache.sub.{key[1]}")
        except CacheEvictInjected:
            self.evict(key)
            _durable("cache.evict_injected").inc()
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                _lru_touch(self._entries, key)
        if e is not None:
            try:
                out = e.handle.get()
                _durable("cache.sub_hits").inc()
                tracing.event_span("cache.sub.hit", fp=key[1])
                return out
            except Exception:  # srjt-lint: allow-broad-except(any rematerialization failure degrades to a recompute miss)
                # DataCorruption from the spill tier, or the governor
                # closed it under us: drop and recompute — the CRC
                # layer's whole point is that rot is a MISS, not an
                # answer
                self.evict(key)
                _durable("cache.sub_corrupt").inc()

        def _compute():
            out = thunk()
            _durable("cache.sub_misses").inc()
            tracing.event_span("cache.sub.miss", fp=key[1])
            self._insert(key, out)
            return out

        return self._flight.run(key, _compute)

    # -- bookkeeping ---------------------------------------------------------

    def _insert(self, key, table) -> None:
        from .. import memgov

        cat = memgov.catalog()
        h = cat.register(_regkey(key), table, kind="cache")
        evicted = []
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                # same key re-registered: catalog already closed the
                # old handle (register replaces); only fix accounting
                self._bytes -= old.nbytes
            self._entries[key] = _SubEntry(_regkey(key), h, h.nbytes)
            self._bytes += h.nbytes
            while self._bytes > self._max_bytes and len(self._entries) > 1:
                _, victim = _pop_oldest(self._entries)
                self._bytes -= victim.nbytes
                evicted.append(victim)
        for victim in evicted:
            cat.unregister(victim.regkey)
            _durable("cache.sub_evictions").inc()

    def evict(self, key) -> bool:
        with self._lock:
            e = self._entries.pop(key, None)
            if e is not None:
                self._bytes -= e.nbytes
        if e is None:
            return False
        from .. import memgov

        memgov.catalog().unregister(e.regkey)
        _durable("cache.sub_evictions").inc()
        return True

    def invalidate_serial(self, serial: int) -> int:
        """Drop every entry whose key references table ``serial`` (the
        proactive half of invalidation — the key-shape half is that a
        bumped generation makes future lookups miss anyway)."""
        with self._lock:
            doomed = [
                k for k in self._entries
                if any(s[1][0] == serial for s in k[3])
            ]
        n = 0
        for k in doomed:
            if self.evict(k):
                n += 1
        if n:
            _durable("cache.invalidations").inc(n)
        return n

    def clear(self) -> None:
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
            self._bytes = 0
        from .. import memgov

        cat = memgov.catalog()
        for e in entries:
            cat.unregister(e.regkey)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self._max_bytes,
                "inflight": self._flight.inflight_count(),
            }

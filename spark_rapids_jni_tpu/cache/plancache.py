"""Compiled-plan cache (srjt-cache, ISSUE 17).

Entries key on ``(parameterized fingerprint, catalog signature)``: the
plan's structure with literal values slotted out
(``plan.rewrites.parameterized_fingerprint``) plus the dtype schema of
the bound tables — the "same dashboard query, different date" pattern
maps to ONE entry. A hit skips rewrite→verify→compile entirely:

- exact-variant hit: the same literal values over the same table
  objects returns the retained ``CompiledPlan`` outright;
- rebind hit: fresh literal values are substituted into the cached
  OPTIMIZED plan (``rebind_literals``) and only re-lowered
  (``plan.compiler.lower_ir``) — the rewrite fixpoint and the verifier
  never re-run.

The once-per-structure verification contract: at INSERT the compiled
artifact must be verifier-green (``verify_for_cache`` — obligations
discharge + estimate consistency) or it is not cached; the entry
records that fact and every hit carries the original obligation ledger
forward, so a production artifact from the cache is as auditable as a
fresh compile.

Rebind soundness: slot tags pin the literal type class (and explicit
dtype), so substitution can never change an inferred schema; rewrite
rules copy/reorder literals but never fold them, so mapping old values
to new BY VALUE reproduces exactly the plan a fresh rewrite would have
produced — and when the mapping would be ambiguous (one old value, two
different new values) or a value does not round-trip equality (NaN),
the cache refuses to guess and falls back to a full compile, counted
under ``cache.rebind_fallbacks``.

Cached entries also carry an observed-cost EWMA (``observe_cost``) —
the admission-cost forecast the serve scheduler sheds on
(``Overloaded(cause="forecast")``).
"""

from __future__ import annotations

import hashlib
import math
import threading
from typing import Dict, Optional, Tuple

from ..plan.compiler import CompiledPlan, compile_ir, lower_ir
from ..plan.nodes import Aggregate, Node, Scan
from ..plan.rewrites import parameterized_fingerprint, rebind_literals
from ..plan.verifier import verify_for_cache
from ..utils import faultinj, metrics, tracing
from ..utils.faultinj import CacheEvictInjected
from . import tablegen

__all__ = ["PlanCache", "arm_subresults", "catalog_signature",
           "table_stamps"]

# cost EWMA weight for the newest observation
_COST_ALPHA = 0.3


def _durable(name: str):
    return metrics.registry().counter(name)


def catalog_signature(tables: Dict) -> str:
    """Schema signature of the bound tables: a cached optimized plan is
    only valid against the column dtypes it was rewritten for (rules
    consult the catalog), so the signature is part of the entry key."""
    items = tuple(sorted(
        (name, tuple((n, int(c.dtype.id), c.dtype.scale)
                     for n, c in zip(t.names, t.columns)))
        for name, t in tables.items()
    ))
    return hashlib.sha1(repr(items).encode()).hexdigest()[:12]


def table_stamps(tables: Dict) -> Tuple:
    """Sorted (name, (serial, generation)) stamps of the bound tables —
    the identity/invalidation component of variant and subresult keys."""
    return tuple(sorted((name, tablegen.stamp(t))
                        for name, t in tables.items()))


def _values_ok(values) -> bool:
    """False when any literal value does not round-trip equality (NaN):
    such a value can neither key a variant nor anchor a rebind map."""
    for v in values:
        try:
            if v != v:
                return False
        except Exception:  # srjt-lint: allow-broad-except(exotic literal __eq__ = not keyable, never an error)
            return False
    return True


def _subtree_tables(node: Node):
    """Names of the tables the subtree scans, sorted."""
    names = set()
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, Scan):
            names.add(n.table)
        stack.extend(n.inputs())
    return tuple(sorted(names))


def arm_subresults(cp: CompiledPlan, tables: Dict, sig: str,
                   subcache) -> None:
    """Point the compiled plan's stage executors at the subresult
    cache: Scan and Aggregate stages (and the plan root) get a
    ``("sub", param_fp, literal_values, table_stamps, catalog_sig)``
    cache key, and ``_Exec.run`` routes through
    ``subcache.lookup_or_compute`` instead of computing. Must run
    BEFORE the plan is published to other threads (keys are written
    once here, read-only afterwards)."""
    if subcache is None:
        return
    cp.subcache = subcache
    seen = set()
    stack = [cp.optimized]
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.extend(node.inputs())
        if not (node is cp.optimized or isinstance(node, (Scan, Aggregate))):
            continue
        ex = cp.exec_for(node)
        if ex is None:
            continue  # fused away or not lowered standalone
        pf = parameterized_fingerprint(node)
        if not _values_ok(pf.values):
            continue  # NaN literal: the key would never hit
        refs = _subtree_tables(node)
        if any(t not in tables for t in refs):
            continue
        stamps = tuple((t, tablegen.stamp(tables[t])) for t in refs)
        ex.cache_key = ("sub", pf.key, pf.values, stamps, sig)


class _PlanEntry:
    """One parameterized structure: the cached optimized plan + its
    provenance, bound variants, and the observed-cost EWMA."""

    __slots__ = ("opt_plan", "obligations", "rewrites", "raw_nodes",
                 "bindings", "rebindable", "variants", "cost_ewma_s")

    def __init__(self, opt_plan: Node, obligations, rewrites, raw_nodes,
                 bindings, rebindable: bool):
        self.opt_plan = opt_plan
        self.obligations = obligations
        self.rewrites = rewrites
        self.raw_nodes = raw_nodes
        self.bindings = bindings  # raw-plan (tag, value, dtype_key) triples
        self.rebindable = rebindable
        self.variants: Dict = {}  # vkey -> CompiledPlan, LRU order
        self.cost_ewma_s: Optional[float] = None


def _lru_touch(d, key) -> None:
    """move_to_end without OrderedDict: pop + reinsert. The LRU maps
    must stay PLAIN-dict-compatible because the srjt-race proxy
    (``lockdep.track``) replaces them with a ``dict`` subclass when
    armed — insertion order is a language guarantee either way."""
    d[key] = d.pop(key)


def _pop_oldest(d):
    """Evict the least-recently-touched entry (the insertion-order
    head; every hit reinserts at the tail via ``_lru_touch``)."""
    k = next(iter(d))
    return k, d.pop(k)


def _rebindable(raw_bindings, opt_plan: Node) -> bool:
    """A structure is literal-rebindable when the optimized plan's
    literals and the raw plan's literals cover each other by value-key
    (null fills excepted — rewrite-synthesized and binding-independent).
    Any folding/elimination a future rule might introduce breaks the
    containment and demotes the entry to exact-variant hits only."""
    if not _values_ok(tuple(b[1] for b in raw_bindings)):
        return False
    raw_keys = set(raw_bindings)
    opt_keys = set(parameterized_fingerprint(opt_plan).bindings)
    if not raw_keys <= opt_keys:
        return False
    return all(k in raw_keys for k in opt_keys if k[0] != "null")


class PlanCache:
    """(param_fp, catalog_sig) -> _PlanEntry under one lock; compiles
    run OUTSIDE the lock (two concurrent misses may both compile — the
    single-flight latch shares executions, not compilations)."""

    def __init__(self, max_entries: int, max_variants: int):
        self._lock = threading.RLock()
        from ..analysis.lockdep import track as _race_track

        self._entries: Dict = _race_track({}, "cache.plan.entries")
        self._max_entries = int(max_entries)
        self._max_variants = int(max_variants)

    # -- the serve integration point -----------------------------------------

    def get_or_compile(self, plan: Node, tables: Dict, name: str = "plan",
                       subcache=None) -> Tuple[CompiledPlan, tuple, tuple]:
        """The cache-armed replacement for ``compile_ir``: returns
        ``(compiled, entry_key, variant_key)`` — the keys identify the
        structure (for cost observation) and the exact submission (for
        single-flight sharing)."""
        pf = parameterized_fingerprint(plan)
        sig = catalog_signature(tables)
        ck = (pf.key, sig)
        try:
            # chaos choke point (`cache_evict` keyed cache.*): the
            # whole structure entry is dropped mid-submission and the
            # lookup proceeds as a miss
            faultinj.maybe_inject(f"cache.plan.{pf.key}")
        except CacheEvictInjected:
            with self._lock:
                self._entries.pop(ck, None)
            _durable("cache.evict_injected").inc()
        stamps = table_stamps(tables)
        vkey = (pf.values, stamps) if _values_ok(pf.values) else None
        entry: Optional[_PlanEntry] = None
        cp: Optional[CompiledPlan] = None
        with self._lock:
            entry = self._entries.get(ck)
            if entry is not None:
                _lru_touch(self._entries, ck)
                if vkey is not None:
                    cp = entry.variants.get(vkey)
                    if cp is not None:
                        _lru_touch(entry.variants, vkey)
        if cp is not None:
            _durable("cache.hits").inc()
            tracing.event_span("cache.hit", fp=pf.key, kind="exact")
            return cp, ck, vkey
        if entry is not None:
            cp = self._rebind(entry, pf, tables, name)
            if cp is not None:
                arm_subresults(cp, tables, sig, subcache)
                self._put_variant(ck, vkey, cp)
                _durable("cache.hits").inc()
                _durable("cache.rebinds").inc()
                tracing.event_span("cache.hit", fp=pf.key, kind="rebind")
                return cp, ck, vkey
            _durable("cache.rebind_fallbacks").inc()
        # -- miss: full compile, verify, insert -------------------------------
        cp = compile_ir(plan, tables, name=name)
        _durable("cache.misses").inc()
        tracing.event_span("cache.miss", fp=pf.key)
        arm_subresults(cp, tables, sig, subcache)
        violations = verify_for_cache(cp, tables, where=f"cache.{name}")
        if violations:
            # not verifier-green: run it, never cache it
            _durable("cache.insert_rejected").inc()
            return cp, ck, vkey
        _durable("cache.insert_verified").inc()
        fresh = _PlanEntry(cp.optimized, cp.obligations, cp.rewrites_fired,
                           cp._raw_nodes, pf.bindings,
                           _rebindable(pf.bindings, cp.optimized))
        if vkey is not None:
            fresh.variants[vkey] = cp
        evicted = 0
        with self._lock:
            prev = self._entries.get(ck)
            if prev is not None:
                # concurrent miss raced us: keep the incumbent (its
                # variants/EWMA are warmer), just add our variant
                if vkey is not None and vkey not in prev.variants:
                    prev.variants[vkey] = cp
                    while len(prev.variants) > self._max_variants:
                        _pop_oldest(prev.variants)
            else:
                self._entries[ck] = fresh
                while len(self._entries) > self._max_entries:
                    _pop_oldest(self._entries)
                    evicted += 1
        if evicted:
            _durable("cache.evictions").inc(evicted)
        return cp, ck, vkey

    def _rebind(self, entry: _PlanEntry, pf, tables: Dict,
                name: str) -> Optional[CompiledPlan]:
        """Bind fresh literal values into the cached optimized plan and
        re-lower. None when the entry cannot be rebound soundly (the
        caller falls back to a full compile)."""
        if not entry.rebindable:
            return None
        if len(entry.bindings) != len(pf.bindings):
            return None  # same key implies same arity; refuse if not
        if not _values_ok(pf.values):
            return None
        mapping: Dict = {}
        for old, new in zip(entry.bindings, pf.bindings):
            if old[0] != new[0] or old[2] != new[2]:
                return None  # tag/dtype drift — refuse to guess
            if old in mapping and not _same(mapping[old], new[1]):
                return None  # ambiguous: one old value, two new values
            mapping[old] = new[1]
        rebound = rebind_literals(entry.opt_plan, mapping)
        return lower_ir(rebound, tables, name=name,
                        raw_nodes=entry.raw_nodes,
                        rewrites_fired=entry.rewrites,
                        obligations=entry.obligations)

    def _put_variant(self, ck, vkey, cp: CompiledPlan) -> None:
        if vkey is None:
            return
        with self._lock:
            entry = self._entries.get(ck)
            if entry is None:
                return
            entry.variants.pop(vkey, None)
            entry.variants[vkey] = cp
            while len(entry.variants) > self._max_variants:
                _pop_oldest(entry.variants)

    # -- cost forecasting ----------------------------------------------------

    def observe_cost(self, ck, seconds: float) -> None:
        if not (isinstance(seconds, float) and math.isfinite(seconds)):
            return
        with self._lock:
            entry = self._entries.get(ck)
            if entry is None:
                return
            if entry.cost_ewma_s is None:
                entry.cost_ewma_s = seconds
            else:
                entry.cost_ewma_s = (_COST_ALPHA * seconds
                                     + (1.0 - _COST_ALPHA) * entry.cost_ewma_s)

    def predicted_cost(self, ck) -> Optional[float]:
        with self._lock:
            entry = self._entries.get(ck)
            return None if entry is None else entry.cost_ewma_s

    # -- maintenance ---------------------------------------------------------

    def evict(self, ck) -> bool:
        with self._lock:
            if self._entries.pop(ck, None) is None:
                return False
        _durable("cache.evictions").inc()
        return True

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "max_entries": self._max_entries,
                "variants": sum(len(e.variants)
                                for e in self._entries.values()),
                "rebindable": sum(1 for e in self._entries.values()
                                  if e.rebindable),
            }


def _same(a, b) -> bool:
    try:
        return bool(a == b)
    except Exception:  # srjt-lint: allow-broad-except(exotic literal __eq__ = ambiguous mapping, full-compile fallback)
        return False

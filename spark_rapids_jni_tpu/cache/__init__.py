"""srjt-cache: the serving tier's caching subsystem (ISSUE 17).

Three cooperating layers, each independently knob-gated and OFF by
default (the stub posture: with every knob down this package is inert
and ``compile_cached`` is exactly ``plan.compile_ir``):

1. **Compiled-plan cache** (``SRJT_PLAN_CACHE``, plancache.py) —
   entries keyed on the plan's *parameterized* fingerprint (structure
   with literal values slotted out) plus the catalog schema signature.
   A hit skips rewrite→verify→compile; fresh literal values are bound
   into the cached optimized plan and only re-lowered. Artifacts are
   verifier-green at insert (``verify_for_cache``) and carry their
   obligation ledger forward.

2. **Subresult cache** (``SRJT_SUBRESULT_CACHE``, subresult.py) —
   scan/aggregate stage outputs registered as memgov catalog entries
   (``kind="cache"``): eviction, spill tiering, and byte accounting
   ride the existing governor. Keys carry per-table generation stamps
   (tablegen.py); ``invalidate_table`` bumps a stamp and proactively
   drops dependents.

3. **In-flight sharing** (``SRJT_CACHE_SHARING``, flight.py) —
   concurrent submissions of the same (plan, literals, tables) attach
   to ONE in-flight execution via a single-flight latch; admission
   happens once, waiter cancellation never cancels the shared leg,
   and a leader failure is never fanned out.

Cached plans also carry an observed-cost EWMA; the serve scheduler
sheds on the predicted cost of the queue + incoming query
(``Overloaded(cause="forecast")``, ``SRJT_SERVE_FORECAST_BUDGET_SEC``).

All counters are registry-direct under ``cache.*`` and surface in
``runtime.stats_report()["cache"]`` / ``metrics.stage_report``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..plan.compiler import compile_ir
from ..utils import knobs, metrics
from . import tablegen
from .flight import SingleFlight
from .plancache import PlanCache, catalog_signature, table_stamps
from .subresult import SubresultCache

__all__ = [
    "CachedQuery",
    "compile_cached",
    "invalidate_table",
    "is_enabled",
    "plan_cache",
    "reset",
    "stats_section",
    "subresult_cache",
    "table_generation",
]

_lock = threading.Lock()
_plan_cache: Optional[PlanCache] = None
_subresult_cache: Optional[SubresultCache] = None
# plan-level single-flight: shares whole-query executions across
# concurrent identical submissions (subresult.py has its own latch for
# stage-level sharing)
_plan_flight = SingleFlight("plan")

# the counters stats_section reports (all registry-durable, survive
# stage_report resets)
_COUNTER_NAMES = (
    "hits", "misses", "rebinds", "rebind_fallbacks",
    "insert_verified", "insert_rejected", "evictions", "evict_injected",
    "share", "share_fallback",
    "sub_hits", "sub_misses", "sub_evictions", "sub_corrupt",
    "invalidations",
)


def is_enabled() -> bool:
    return knobs.get_bool("SRJT_PLAN_CACHE")


def plan_cache() -> PlanCache:
    """The process singleton, sized from knobs at first use."""
    global _plan_cache
    with _lock:
        if _plan_cache is None:
            _plan_cache = PlanCache(
                max_entries=knobs.get_int("SRJT_CACHE_PLAN_ENTRIES"),
                max_variants=knobs.get_int("SRJT_CACHE_PLAN_VARIANTS"),
            )
        return _plan_cache


def subresult_cache() -> SubresultCache:
    global _subresult_cache
    with _lock:
        if _subresult_cache is None:
            _subresult_cache = SubresultCache(
                max_bytes=knobs.get_int("SRJT_CACHE_SUBRESULT_BYTES"),
            )
        return _subresult_cache


class CachedQuery:
    """What the serve scheduler runs when the plan cache is armed: a
    callable over a cached ``CompiledPlan`` that (a) single-flights
    identical concurrent submissions, (b) feeds observed wall time back
    into the structure's cost EWMA, and (c) passes the compiled plan's
    memory estimate through for memgov pre-admission."""

    __slots__ = ("_cp", "_ck", "_vkey", "_pc")

    def __init__(self, cp, ck, vkey, pc: PlanCache):
        self._cp = cp
        self._ck = ck
        self._vkey = vkey
        self._pc = pc

    @property
    def estimated_memory_bytes(self):
        return getattr(self._cp, "estimated_memory_bytes", None)

    @property
    def partition_memory_bytes(self):
        # srjt-ooc (ISSUE 18): when the cached binding degraded to
        # out-of-core, serve admission wants the per-partition peak
        return getattr(self._cp, "partition_memory_bytes", None)

    @property
    def name(self):
        return getattr(self._cp, "name", "plan")

    @property
    def compiled(self):
        return self._cp

    @property
    def predicted_cost_s(self) -> Optional[float]:
        """The structure's observed-cost EWMA — the scheduler's
        admission forecast input. None until the first completed run."""
        return self._pc.predicted_cost(self._ck)

    def __call__(self):
        if self._vkey is not None and knobs.get_bool("SRJT_CACHE_SHARING"):
            # key on the exact submission: structure + literal values +
            # table stamps — anything less would fan one answer out to
            # queries that asked different questions
            return _plan_flight.run((self._ck, self._vkey), self._run_once)
        return self._run_once()

    def _run_once(self):
        t0 = time.perf_counter()
        out = self._cp()
        self._pc.observe_cost(self._ck, time.perf_counter() - t0)
        return out


def compile_cached(plan, tables: Dict, name: str = "plan"):
    """The serve tier's compile entry point. Off-knob this IS
    ``compile_ir``; armed, it returns a ``CachedQuery`` over the
    cached/rebound/freshly-compiled plan."""
    if not knobs.get_bool("SRJT_PLAN_CACHE"):
        return compile_ir(plan, tables, name=name)
    sub = (subresult_cache()
           if knobs.get_bool("SRJT_SUBRESULT_CACHE") else None)
    cp, ck, vkey = plan_cache().get_or_compile(
        plan, tables, name=name, subcache=sub
    )
    return CachedQuery(cp, ck, vkey, plan_cache())


def table_generation(table):
    """The (serial, generation) stamp cache keys carry for ``table``."""
    return tablegen.stamp(table)


def invalidate_table(table):
    """The explicit invalidation hook: callers that mutate/reload a
    table's content in place call this — the generation bump makes
    every derived cache key unreachable, and cached subresults that
    reference the table are proactively dropped. Returns the new
    stamp."""
    serial, _ = tablegen.stamp(table)
    new_stamp = tablegen.bump(table)
    with _lock:
        sc = _subresult_cache
    if sc is not None:
        sc.invalidate_serial(serial)
    return new_stamp


def stats_section() -> dict:
    """The ``cache`` section of runtime.stats_report(): knob posture,
    durable counters, and per-layer snapshots."""
    reg = metrics.registry()
    out = {
        "enabled": {
            "plan": knobs.get_bool("SRJT_PLAN_CACHE"),
            "subresult": knobs.get_bool("SRJT_SUBRESULT_CACHE"),
            "sharing": knobs.get_bool("SRJT_CACHE_SHARING"),
        },
        "counters": {n: reg.value(f"cache.{n}") for n in _COUNTER_NAMES},
    }
    with _lock:
        pc, sc = _plan_cache, _subresult_cache
    if pc is not None:
        out["plan"] = pc.snapshot()
    if sc is not None:
        out["subresult"] = sc.snapshot()
    try:
        from .. import memgov

        entries, nbytes = memgov.catalog().kind_stats("cache")
        out["governed"] = {"entries": entries, "bytes": nbytes}
    except Exception:  # srjt-lint: allow-broad-except(stats reporting must never fail the report)
        pass
    return out


def reset() -> None:
    """Test hook: drop both caches (unregistering governed subresult
    entries) and all table-generation records."""
    global _plan_cache, _subresult_cache
    with _lock:
        pc, sc = _plan_cache, _subresult_cache
        _plan_cache = None
        _subresult_cache = None
    if pc is not None:
        pc.clear()
    if sc is not None:
        sc.clear()
    tablegen.reset()

"""Single-flight latch (srjt-cache, ISSUE 17): N concurrent callers
with one key run ONE computation and fan the result out.

The loser-attaches-to-winner race is settled under one lock: the first
caller in becomes the leader and computes; every later caller with the
same key attaches as a waiter on the flight's event. Waiters poll the
event in short slices so the ambient deadline scope stays live —
cancelling or expiring an ATTACHED waiter raises out of ITS wait only
(``deadline.check``), never touching the shared leg: the leader owns
the computation and the other waiters keep it reachable.

Failure isolation: a leader failure is NOT fanned out. Chaos faults
(and real ones) are per-leg — an attached query that inherited a
leader's injected crash would turn one fault into N failures — so a
waiter whose leader failed falls back to computing independently,
counted under ``cache.share_fallback``.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict

from ..utils import deadline as deadline_mod
from ..utils import metrics, tracing

__all__ = ["SingleFlight"]

# waiter poll slice: short enough that cancellation/expiry of a waiter
# is observed promptly, long enough to stay off the scheduler's back
_WAIT_SLICE_S = 0.02


def _durable(name: str):
    return metrics.registry().counter(name)


class _Flight:
    """One in-flight computation: leader's outcome + the fan-out latch."""

    __slots__ = ("event", "result", "ok", "waiters")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.ok = False
        self.waiters = 0


class SingleFlight:
    """key -> in-flight computation map under one lock."""

    def __init__(self, name: str):
        self._lock = threading.Lock()
        # srjt-race layer 2: the flight map is crossed by every serve
        # slot racing on one key (tracked when SRJT_RACE=1)
        from ..analysis.lockdep import track as _race_track

        self._flights: Dict = _race_track({}, f"cache.flight.{name}")
        self._name = name

    def run(self, key, thunk: Callable):
        """Run ``thunk`` as the key's leader, or attach to the leader
        already running it. Exactly one thunk executes per key per
        flight; waiters receive the leader's result object (results are
        immutable Tables — sharing is safe)."""
        with self._lock:
            fl = self._flights.get(key)
            if fl is None:
                fl = _Flight()
                self._flights[key] = fl
                leader = True
            else:
                fl.waiters += 1
                leader = False
        if leader:
            try:
                out = thunk()
                fl.result = out
                fl.ok = True
                return out
            finally:
                # pop BEFORE set: once waiters wake, a new caller must
                # start a fresh flight, not attach to a finished one
                with self._lock:
                    self._flights.pop(key, None)
                fl.event.set()
        # -- attached waiter --------------------------------------------------
        _durable("cache.share").inc()
        tracing.event_span("cache.attach", flight=self._name)
        while not fl.event.wait(_WAIT_SLICE_S):
            # raises DeadlineExceeded when THIS waiter's budget expires
            # or its CancelToken trips — the leader and the other
            # waiters are untouched (waiter cancellation never cancels
            # the shared leg)
            deadline_mod.check("cache.attach")
        if fl.ok:
            return fl.result
        # leader failed: faults are per-leg — compute independently
        _durable("cache.share_fallback").inc()
        return thunk()

    def inflight_count(self) -> int:
        with self._lock:
            return len(self._flights)

"""Table generation stamps (srjt-cache, ISSUE 17).

Cached results are only reusable while the data they were computed
from is unchanged. The subresult cache keys on a *generation stamp*
per bound table: a ``(serial, generation)`` pair where ``serial`` is a
process-unique number assigned the first time a Table object is seen
(identity — a DIFFERENT table object gets a different serial, so a
fresh load never aliases a cached result computed over the old one)
and ``generation`` is a bump counter for in-place mutation (the repo's
tables are immutable pytrees, but ``bump()`` is the explicit
invalidation hook callers use when they rebind a name to updated
content they consider "the same table").

Serials live in a WeakKeyDictionary — a dropped table releases its
record, and because the serial (not ``id()``) goes into cache keys,
CPython's id reuse after GC can never resurrect a stale entry.
"""

from __future__ import annotations

import threading
import weakref
from typing import Tuple

__all__ = ["stamp", "bump", "reset"]

_lock = threading.Lock()
_records: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_next_serial = 0


def stamp(table) -> Tuple[int, int]:
    """The (serial, generation) stamp of one table object, assigning a
    fresh serial on first sight."""
    global _next_serial
    with _lock:
        rec = _records.get(table)
        if rec is None:
            _next_serial += 1
            rec = [_next_serial, 0]
            _records[table] = rec
        return (rec[0], rec[1])


def bump(table) -> Tuple[int, int]:
    """Advance the table's generation — every cache key derived from
    the old stamp becomes unreachable. Returns the new stamp."""
    global _next_serial
    with _lock:
        rec = _records.get(table)
        if rec is None:
            _next_serial += 1
            rec = [_next_serial, 0]
            _records[table] = rec
        rec[1] += 1
        return (rec[0], rec[1])


def reset() -> None:
    """Test hook: drop every record (fresh serials from here on)."""
    with _lock:
        _records.clear()

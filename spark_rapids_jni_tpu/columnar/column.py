"""Device-resident columns: the TPU-native analog of ``cudf::column``.

The reference's entire JNI surface trades in ``ai.rapids.cudf.ColumnVector``
handles (reference RowConversion.java:19, SURVEY §2.8). Here a column is a
small pytree of jax arrays, so every op composes under ``jax.jit`` /
``shard_map`` and XLA owns layout & fusion:

- fixed width:  ``data``    [N]        (DECIMAL128: [N, 4] uint32 limbs, LE)
- validity:     ``validity``[N] bool   (True == valid; None == all valid;
                                        matches cudf's set-bit-means-valid)
- STRING:       ``offsets`` [N+1] int32, ``chars`` [nbytes] uint8
- LIST:         ``offsets`` [N+1] int32, ``child``  Column
- STRUCT:       ``children`` tuple of Columns (+ ``child_names``), all
                length N (cudf struct_column layout)

Host<->device conversion goes through numpy only at the API edges (the
role the reference's HostMemoryBuffer + JNI marshalling play).
"""

from __future__ import annotations

import decimal
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .dtype import DType, TypeId

__all__ = ["Column"]


def _pack_decimal128_host(values: Sequence[int]) -> np.ndarray:
    """Unscaled python ints -> [N, 4] uint32 little-endian limbs (two's complement)."""
    out = np.empty((len(values), 4), dtype=np.uint32)
    mask = (1 << 128) - 1
    for i, v in enumerate(values):
        u = v & mask
        for j in range(4):
            out[i, j] = (u >> (32 * j)) & 0xFFFFFFFF
    return out


def _unpack_decimal128_host(limbs: np.ndarray) -> list:
    """[N, 4] uint32 limbs -> unscaled python ints (signed)."""
    vals = []
    for row in limbs:
        u = 0
        for j in range(4):
            u |= int(row[j]) << (32 * j)
        if u >= 1 << 127:
            u -= 1 << 128
        vals.append(u)
    return vals


@jax.tree_util.register_pytree_node_class
class Column:
    """An immutable device column. Registered as a pytree so Tables of
    Columns flow through jit/shard_map boundaries directly."""

    def __init__(
        self,
        dtype: DType,
        data: Optional[jnp.ndarray] = None,
        validity: Optional[jnp.ndarray] = None,
        offsets: Optional[jnp.ndarray] = None,
        chars: Optional[jnp.ndarray] = None,
        child: Optional["Column"] = None,
        children: Optional[tuple] = None,
        child_names: Optional[tuple] = None,
    ):
        self.dtype = dtype
        self.data = data
        self.validity = validity
        self.offsets = offsets
        self.chars = chars
        self.child = child
        self.children = tuple(children) if children is not None else None
        self.child_names = tuple(child_names) if child_names is not None else None

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        children = (self.data, self.validity, self.offsets, self.chars, self.child, self.children)
        return children, (self.dtype, self.child_names)

    @classmethod
    def tree_unflatten(cls, aux, children):
        dtype, child_names = aux if isinstance(aux, tuple) else (aux, None)
        data, validity, offsets, chars, child, struct_children = children
        return cls(dtype, data=data, validity=validity, offsets=offsets, chars=chars,
                   child=child, children=struct_children, child_names=child_names)

    # -- shape --------------------------------------------------------------
    def __len__(self) -> int:
        if self.dtype.id in (TypeId.STRING, TypeId.LIST):
            return int(self.offsets.shape[0]) - 1
        if self.dtype.id == TypeId.STRUCT:
            if self.validity is not None:
                return int(self.validity.shape[0])
            return len(self.children[0]) if self.children else 0
        return int(self.data.shape[0])

    @property
    def num_rows(self) -> int:
        return len(self)

    @property
    def max_char_len(self) -> int:
        """Max byte length across rows (STRING columns): the padded-
        matrix width every string kernel needs. Memoized — at most one
        device sync per column, and host-side constructors prepopulate
        it for free (through a remote backend the sync is a full RTT)."""
        ml = self.__dict__.get("_max_char_len")
        if ml is None:
            if len(self) == 0:
                ml = 0
            else:
                offs = self.offsets
                ml = int(jnp.max(offs[1:] - offs[:-1]))
            self._max_char_len = ml
        return ml

    @property
    def null_count(self) -> int:
        if self.validity is None:
            return 0
        return int(jnp.sum(~self.validity))

    def has_nulls(self) -> bool:
        return self.validity is not None and self.null_count > 0

    def valid_mask(self) -> jnp.ndarray:
        """Materialized [N] bool validity (all-True when validity is None)."""
        if self.validity is not None:
            return self.validity
        return jnp.ones((len(self),), dtype=bool)

    # -- construction -------------------------------------------------------
    @classmethod
    def from_pylist(cls, values: Sequence[Any], dtype: DType) -> "Column":
        """Build a device column from host python values; None == null.

        Decimal columns accept unscaled ints or ``decimal.Decimal`` (scaled by
        ``dtype.scale``); BOOL8 accepts bools; STRING accepts str/bytes.
        """
        n = len(values)
        has_null = any(v is None for v in values)
        validity = None
        if has_null:
            validity = jnp.asarray(np.array([v is not None for v in values], dtype=bool))

        tid = dtype.id
        if tid == TypeId.STRING:
            encoded = [b"" if v is None else (v.encode() if isinstance(v, str) else bytes(v)) for v in values]
            lens = np.array([len(e) for e in encoded], dtype=np.int32)
            offsets = np.zeros(n + 1, dtype=np.int32)
            np.cumsum(lens, out=offsets[1:])
            chars = np.frombuffer(b"".join(encoded), dtype=np.uint8).copy()
            col = cls(
                dtype,
                validity=validity,
                offsets=jnp.asarray(offsets),
                chars=jnp.asarray(chars),
            )
            # free while host-side: saves ops/strings.to_padded a device
            # sync (a full RTT on remote backends) per op
            col._max_char_len = int(lens.max()) if n else 0
            return col
        if tid == TypeId.DECIMAL128:
            unscaled = [0 if v is None else _to_unscaled(v, dtype.scale) for v in values]
            return cls(dtype, data=jnp.asarray(_pack_decimal128_host(unscaled)), validity=validity)
        if tid in (TypeId.DECIMAL32, TypeId.DECIMAL64):
            unscaled = [0 if v is None else _to_unscaled(v, dtype.scale) for v in values]
            return cls(dtype, data=jnp.asarray(np.array(unscaled, dtype=dtype.np_dtype)), validity=validity)
        if tid == TypeId.BOOL8:
            host = np.array([0 if v is None else int(bool(v)) for v in values], dtype=np.uint8)
            return cls(dtype, data=jnp.asarray(host), validity=validity)
        if tid == TypeId.FLOAT64:
            host = np.array([0.0 if v is None else v for v in values], dtype=np.float64)
            return cls(dtype, data=jnp.asarray(host.view(np.uint64)), validity=validity)
        host = np.array([0 if v is None else v for v in values], dtype=dtype.np_dtype)
        return cls(dtype, data=jnp.asarray(host), validity=validity)

    @classmethod
    def from_numpy(cls, arr: np.ndarray, dtype: Optional[DType] = None,
                   validity: Optional[np.ndarray] = None) -> "Column":
        if dtype is None:
            dtype = _infer_dtype(arr.dtype)
        v = None if validity is None else jnp.asarray(validity.astype(bool))
        if dtype.id == TypeId.FLOAT64:
            host = arr.astype(np.float64, copy=False).view(np.uint64)
        else:
            host = arr.astype(dtype.np_dtype, copy=False)
        return cls(dtype, data=jnp.asarray(host), validity=v)

    @classmethod
    def strings_from_parts(cls, offsets, chars, validity=None) -> "Column":
        from . import dtype as dt

        return cls(dt.STRING, validity=validity, offsets=jnp.asarray(offsets), chars=jnp.asarray(chars))

    @classmethod
    def list_from_parts(cls, offsets, child: "Column", validity=None) -> "Column":
        from . import dtype as dt

        return cls(dt.LIST, validity=validity, offsets=jnp.asarray(offsets), child=child)

    @classmethod
    def struct_from_parts(cls, children: Sequence["Column"], names: Sequence[str],
                          validity=None) -> "Column":
        from . import dtype as dt

        return cls(dt.STRUCT, validity=validity, children=tuple(children),
                   child_names=tuple(names))

    # -- host round trip (test/debug surface, like cudf::test wrappers) -----
    def to_pylist(self) -> list:
        tid = self.dtype.id
        valid = np.asarray(self.valid_mask())
        if tid == TypeId.STRING:
            offs = np.asarray(self.offsets)
            chars = np.asarray(self.chars).tobytes()
            out = []
            for i in range(len(self)):
                if not valid[i]:
                    out.append(None)
                else:
                    out.append(chars[offs[i]:offs[i + 1]].decode("utf-8", errors="replace"))
            return out
        if tid == TypeId.LIST:
            offs = np.asarray(self.offsets)
            child_vals = self.child.to_pylist()
            return [
                None if not valid[i] else child_vals[offs[i]:offs[i + 1]]
                for i in range(len(self))
            ]
        if tid == TypeId.STRUCT:
            names = self.child_names or tuple(f"f{j}" for j in range(len(self.children)))
            per_child = [c.to_pylist() for c in self.children]
            return [
                None if not valid[i] else {nm: per_child[j][i] for j, nm in enumerate(names)}
                for i in range(len(self))
            ]
        if tid == TypeId.DECIMAL128:
            unscaled = _unpack_decimal128_host(np.asarray(self.data))
            return [None if not valid[i] else unscaled[i] for i in range(len(self))]
        host = np.asarray(self.data)
        if tid == TypeId.BOOL8:
            return [None if not valid[i] else bool(host[i]) for i in range(len(self))]
        if tid == TypeId.FLOAT64:
            host = host.view(np.float64)
        return [None if not valid[i] else host[i].item() for i in range(len(self))]

    def to_decimal_pylist(self) -> list:
        """Decimal columns as ``decimal.Decimal`` values (scaled)."""
        assert self.dtype.is_decimal
        scale = self.dtype.scale
        return [
            None if v is None else decimal.Decimal(v).scaleb(scale)
            for v in self.to_pylist()
        ]

    def __repr__(self):
        return f"Column({self.dtype!r}, rows={len(self)}, nulls={self.null_count})"


def _to_unscaled(v, scale: int) -> int:
    if isinstance(v, decimal.Decimal):
        q = v.scaleb(-scale)
        return int(q.to_integral_value(rounding=decimal.ROUND_HALF_UP))
    return int(v)


def _infer_dtype(np_dt: np.dtype) -> DType:
    from . import dtype as dt

    table = {
        np.dtype(np.int8): dt.INT8,
        np.dtype(np.int16): dt.INT16,
        np.dtype(np.int32): dt.INT32,
        np.dtype(np.int64): dt.INT64,
        np.dtype(np.uint8): dt.UINT8,
        np.dtype(np.uint16): dt.UINT16,
        np.dtype(np.uint32): dt.UINT32,
        np.dtype(np.uint64): dt.UINT64,
        np.dtype(np.float32): dt.FLOAT32,
        np.dtype(np.float64): dt.FLOAT64,
        np.dtype(np.bool_): dt.BOOL8,
    }
    if np_dt not in table:
        raise ValueError(f"cannot infer DType from {np_dt}")
    return table[np_dt]

"""Spark/cudf-shaped logical types over TPU-native physical storage.

Mirrors the type surface the reference's Java API exchanges across JNI
(``ai.rapids.cudf.DType`` — see reference RowConversionJni.cpp:85 where
``(types[], scale[])`` pairs are rebuilt into ``data_type``), but the physical
mapping is chosen for TPU/XLA:

- fixed-width types map 1:1 onto jax dtypes,
- BOOL8 is stored as uint8 (one byte, Spark semantics: non-zero == true),
- DECIMAL32/64 store unscaled values in int32/int64 lanes,
- DECIMAL128 stores unscaled values as 4 x uint32 little-endian limbs
  (shape ``[N, 4]``) because the TPU MXU/VPU has no 128-bit lanes; all
  arithmetic is limb-based (see ops/decimal_utils.py),
- STRING is Arrow-style: int32 offsets + uint8 character bytes,
- LIST is offsets + child column (used for JCUDF row blobs and Z-order keys).

cudf convention kept throughout: ``scale`` here is the *cudf* scale (negative
of the Spark/SQL scale); helpers convert at the API boundary.
"""

from __future__ import annotations

import dataclasses
import enum

import jax.numpy as jnp
import numpy as np


class TypeId(enum.IntEnum):
    """Logical type ids, aligned with the surface used by the reference JNI."""

    EMPTY = 0
    INT8 = 1
    INT16 = 2
    INT32 = 3
    INT64 = 4
    UINT8 = 5
    UINT16 = 6
    UINT32 = 7
    UINT64 = 8
    FLOAT32 = 9
    FLOAT64 = 10
    BOOL8 = 11
    TIMESTAMP_DAYS = 12
    TIMESTAMP_SECONDS = 13
    TIMESTAMP_MILLISECONDS = 14
    TIMESTAMP_MICROSECONDS = 15
    TIMESTAMP_NANOSECONDS = 16
    DURATION_DAYS = 17
    DURATION_SECONDS = 18
    DURATION_MILLISECONDS = 19
    DURATION_MICROSECONDS = 20
    DURATION_NANOSECONDS = 21
    STRING = 23
    LIST = 24
    DECIMAL32 = 26
    DECIMAL64 = 27
    DECIMAL128 = 28
    STRUCT = 29


# Physical element width in bytes inside a JCUDF row / Arrow buffer.
_SIZES = {
    TypeId.INT8: 1,
    TypeId.INT16: 2,
    TypeId.INT32: 4,
    TypeId.INT64: 8,
    TypeId.UINT8: 1,
    TypeId.UINT16: 2,
    TypeId.UINT32: 4,
    TypeId.UINT64: 8,
    TypeId.FLOAT32: 4,
    TypeId.FLOAT64: 8,
    TypeId.BOOL8: 1,
    TypeId.TIMESTAMP_DAYS: 4,
    TypeId.TIMESTAMP_SECONDS: 8,
    TypeId.TIMESTAMP_MILLISECONDS: 8,
    TypeId.TIMESTAMP_MICROSECONDS: 8,
    TypeId.TIMESTAMP_NANOSECONDS: 8,
    TypeId.DURATION_DAYS: 4,
    TypeId.DURATION_SECONDS: 8,
    TypeId.DURATION_MILLISECONDS: 8,
    TypeId.DURATION_MICROSECONDS: 8,
    TypeId.DURATION_NANOSECONDS: 8,
    TypeId.DECIMAL32: 4,
    TypeId.DECIMAL64: 8,
    TypeId.DECIMAL128: 16,
}

# jax storage dtype for each fixed-width logical type.
_JNP = {
    TypeId.INT8: jnp.int8,
    TypeId.INT16: jnp.int16,
    TypeId.INT32: jnp.int32,
    TypeId.INT64: jnp.int64,
    TypeId.UINT8: jnp.uint8,
    TypeId.UINT16: jnp.uint16,
    TypeId.UINT32: jnp.uint32,
    TypeId.UINT64: jnp.uint64,
    TypeId.FLOAT32: jnp.float32,
    # FLOAT64 stores IEEE-754 *bits* in uint64 lanes: TPU v5e has no f64
    # datapath (XLA's x64 rewrite demotes f64 buffers and compute to f32,
    # losing bits even on transfer), while u64 is emulated exactly as u32
    # pairs. Byte movement (JCUDF rows, shuffle) therefore stays bit-exact;
    # arithmetic decodes via ops/bitutils.float_view (exact f64 on CPU,
    # documented f32 approximation on TPU).
    TypeId.FLOAT64: jnp.uint64,
    TypeId.BOOL8: jnp.uint8,
    TypeId.TIMESTAMP_DAYS: jnp.int32,
    TypeId.TIMESTAMP_SECONDS: jnp.int64,
    TypeId.TIMESTAMP_MILLISECONDS: jnp.int64,
    TypeId.TIMESTAMP_MICROSECONDS: jnp.int64,
    TypeId.TIMESTAMP_NANOSECONDS: jnp.int64,
    TypeId.DURATION_DAYS: jnp.int32,
    TypeId.DURATION_SECONDS: jnp.int64,
    TypeId.DURATION_MILLISECONDS: jnp.int64,
    TypeId.DURATION_MICROSECONDS: jnp.int64,
    TypeId.DURATION_NANOSECONDS: jnp.int64,
    TypeId.DECIMAL32: jnp.int32,
    TypeId.DECIMAL64: jnp.int64,
    # DECIMAL128 handled specially: [N, 4] uint32 limbs.
    TypeId.DECIMAL128: jnp.uint32,
}

_INTEGRAL = frozenset(
    {
        TypeId.INT8,
        TypeId.INT16,
        TypeId.INT32,
        TypeId.INT64,
        TypeId.UINT8,
        TypeId.UINT16,
        TypeId.UINT32,
        TypeId.UINT64,
    }
)

_DECIMAL = frozenset({TypeId.DECIMAL32, TypeId.DECIMAL64, TypeId.DECIMAL128})


@dataclasses.dataclass(frozen=True)
class DType:
    """A logical type: id + cudf-convention scale (decimals only).

    cudf scale is the negation of SQL scale: value = unscaled * 10**scale,
    so a SQL DECIMAL(p, 2) has cudf scale -2.
    """

    id: TypeId
    scale: int = 0

    def __post_init__(self):
        if self.scale != 0 and self.id not in _DECIMAL:
            raise ValueError(f"scale only valid for decimal types, got {self.id!r}")

    @property
    def size_bytes(self) -> int:
        if self.id not in _SIZES:
            raise ValueError(f"{self.id!r} has no fixed width")
        return _SIZES[self.id]

    @property
    def is_fixed_width(self) -> bool:
        return self.id in _SIZES

    @property
    def is_compound(self) -> bool:
        return self.id in (TypeId.STRING, TypeId.LIST, TypeId.STRUCT)

    @property
    def is_integral(self) -> bool:
        return self.id in _INTEGRAL

    @property
    def is_decimal(self) -> bool:
        return self.id in _DECIMAL

    @property
    def is_floating(self) -> bool:
        return self.id in (TypeId.FLOAT32, TypeId.FLOAT64)

    @property
    def is_signed(self) -> bool:
        return self.id in _INTEGRAL and not TypeId(self.id).name.startswith("U")

    @property
    def jnp_dtype(self):
        if self.id not in _JNP:
            raise ValueError(f"{self.id!r} has no single jax storage dtype")
        return _JNP[self.id]

    @property
    def np_dtype(self):
        return np.dtype(self.jnp_dtype)

    @property
    def precision_cap(self) -> int:
        """Max decimal digits representable (cudf convention)."""
        return {TypeId.DECIMAL32: 9, TypeId.DECIMAL64: 18, TypeId.DECIMAL128: 38}[self.id]

    def __repr__(self):
        if self.id in _DECIMAL:
            return f"DType({self.id.name}, scale={self.scale})"
        return f"DType({self.id.name})"


# Convenience singletons, mirroring ai.rapids.cudf.DType statics.
INT8 = DType(TypeId.INT8)
INT16 = DType(TypeId.INT16)
INT32 = DType(TypeId.INT32)
INT64 = DType(TypeId.INT64)
UINT8 = DType(TypeId.UINT8)
UINT16 = DType(TypeId.UINT16)
UINT32 = DType(TypeId.UINT32)
UINT64 = DType(TypeId.UINT64)
FLOAT32 = DType(TypeId.FLOAT32)
FLOAT64 = DType(TypeId.FLOAT64)
BOOL8 = DType(TypeId.BOOL8)
STRING = DType(TypeId.STRING)
LIST = DType(TypeId.LIST)
STRUCT = DType(TypeId.STRUCT)
TIMESTAMP_DAYS = DType(TypeId.TIMESTAMP_DAYS)
TIMESTAMP_SECONDS = DType(TypeId.TIMESTAMP_SECONDS)
TIMESTAMP_MILLISECONDS = DType(TypeId.TIMESTAMP_MILLISECONDS)
TIMESTAMP_MICROSECONDS = DType(TypeId.TIMESTAMP_MICROSECONDS)
TIMESTAMP_NANOSECONDS = DType(TypeId.TIMESTAMP_NANOSECONDS)
DURATION_DAYS = DType(TypeId.DURATION_DAYS)
DURATION_SECONDS = DType(TypeId.DURATION_SECONDS)
DURATION_MILLISECONDS = DType(TypeId.DURATION_MILLISECONDS)
DURATION_MICROSECONDS = DType(TypeId.DURATION_MICROSECONDS)
DURATION_NANOSECONDS = DType(TypeId.DURATION_NANOSECONDS)


def decimal32(scale: int) -> DType:
    return DType(TypeId.DECIMAL32, scale)


def decimal64(scale: int) -> DType:
    return DType(TypeId.DECIMAL64, scale)


def decimal128(scale: int) -> DType:
    return DType(TypeId.DECIMAL128, scale)

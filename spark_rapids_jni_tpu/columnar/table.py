"""Table: an ordered collection of equal-length Columns.

Analog of ``cudf::table_view`` / ``ai.rapids.cudf.Table`` (the handle type
every reference JNI entry point receives, e.g. RowConversionJni.cpp:42).
Registered as a pytree so whole tables pass through jit/pjit boundaries.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax

from .column import Column

__all__ = ["Table"]


@jax.tree_util.register_pytree_node_class
class Table:
    def __init__(self, columns: Sequence[Column], names: Optional[Sequence[str]] = None):
        columns = list(columns)
        if columns:
            n = len(columns[0])
            for c in columns[1:]:
                if len(c) != n:
                    raise ValueError("all columns in a Table must have equal length")
        self.columns: List[Column] = columns
        self.names = list(names) if names is not None else [f"c{i}" for i in range(len(columns))]

    def tree_flatten(self):
        return tuple(self.columns), tuple(self.names)

    @classmethod
    def tree_unflatten(cls, names, columns):
        return cls(list(columns), list(names))

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    def column(self, i) -> Column:
        if isinstance(i, str):
            return self.columns[self.names.index(i)]
        return self.columns[i]

    def __getitem__(self, i) -> Column:
        return self.column(i)

    def dtypes(self):
        return [c.dtype for c in self.columns]

    def select(self, idxs) -> "Table":
        idxs = [self.names.index(i) if isinstance(i, str) else i for i in idxs]
        return Table([self.columns[i] for i in idxs], [self.names[i] for i in idxs])

    def with_column(self, name: str, col: Column) -> "Table":
        return Table(self.columns + [col], self.names + [name])

    def to_pydict(self) -> dict:
        return {n: c.to_pylist() for n, c in zip(self.names, self.columns)}

    def __repr__(self):
        cols = ", ".join(f"{n}: {c.dtype!r}" for n, c in zip(self.names, self.columns))
        return f"Table(rows={self.num_rows}, [{cols}])"

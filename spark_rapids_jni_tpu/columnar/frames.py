"""Versioned columnar frame codec: ONE wire/spill/exchange layout.

Thallus (PAPERS.md, arXiv 2412.02192) gets its transport wins from a
self-describing columnar frame reused across every boundary — the
schema travels with the bytes, and each column carries its own
checksum so corruption is localized to a column, not "somewhere in a
blob". Until this module the stack had THREE ad-hoc layouts: the
sidecar's positional table walker (sidecar._read_table), memgov's
npz-in-a-CRC-envelope spill container, and the shuffle exchange's
order-independent payload sum. This codec replaces all three payload
layouts (the envelopes that carried them keep reading their legacy
forms):

- sidecar table payloads: ``_read_table`` sniffs the magic and decodes
  frames; the worker answers in the format the request used, so the
  native C++ client (which always emits the legacy walker layout)
  keeps its framing byte for byte,
- memgov disk spills (memgov/catalog.py): new spills are one frame of
  raw ndarray parts; pre-existing ``SRJTSPL1`` containers and plain
  npz files still load,
- TCP shuffle exchanges (parallel/shuffle.py): every partition crosses
  the socket as one frame, so a tampered exchange surfaces as
  retryable ``DataCorruption`` at decode, never as wrong rows.

Frame layout (little-endian)::

    [8]  magic   b"SRJTFRM1"
    [2]  u16 version (=1)
    [2]  u16 flags   (bit 0: per-part CRC words + header CRC valid)
    [4]  u32 npart
    per part (descriptor, variable length):
        [4]  i32 type_id     (columnar TypeId, or -1 for a raw ndarray)
        [4]  i32 scale       (decimal scale; 0 otherwise)
        [1]  u8  role        (0 data, 1 validity, 2 offsets, 3 chars)
        [4]  u32 col         (owning logical column index)
        [8]  u64 null_count
        [1]  u8  dlen, then dlen bytes of numpy dtype.str (ascii)
        [1]  u8  ndim, then ndim x u64 shape
        [8]  u64 nbytes      (payload length)
        [4]  u32 crc         (utils/integrity checksum; 0 when unchecked)
    [4]  u32 header_crc      (over magic..descriptors; 0 when unchecked)
    part payloads, concatenated in descriptor order

With ``SRJT_INTEGRITY_CHECKS=0`` frames are emitted with flags bit 0
clear (no hashing anywhere) and decode skips verification — the seed
posture. A checked decode counts
``sidecar.integrity.frame_decodes_checked``; any mismatch raises
``DataCorruption`` through ``integrity.raise_corruption`` so it lands
under the same ``sidecar.integrity.crc_mismatch.<where>`` accounting
as every other surface.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils import integrity

__all__ = [
    "MAGIC",
    "VERSION",
    "FramePart",
    "is_frame",
    "is_checked",
    "encode_parts",
    "decode_parts",
    "encode_table",
    "decode_table",
    "encode_leaves",
    "decode_leaves",
]

MAGIC = b"SRJTFRM1"
VERSION = 1
_FLAG_CRC = 0x0001

ROLE_DATA = 0
ROLE_VALIDITY = 1
ROLE_OFFSETS = 2
ROLE_CHARS = 3

_PREAMBLE = struct.Struct("<8sHHI")  # magic, version, flags, npart
_RAW_TYPE_ID = -1


class FramePart:
    """One encoded buffer: a contiguous ndarray plus the schema bits a
    decoder needs to hang it back onto a logical column."""

    __slots__ = ("array", "type_id", "scale", "role", "col", "null_count")

    def __init__(
        self,
        array: np.ndarray,
        type_id: int = _RAW_TYPE_ID,
        scale: int = 0,
        role: int = ROLE_DATA,
        col: int = 0,
        null_count: int = 0,
    ):
        self.array = np.ascontiguousarray(array)
        self.type_id = int(type_id)
        self.scale = int(scale)
        self.role = int(role)
        self.col = int(col)
        self.null_count = int(null_count)


def is_frame(buf, offset: int = 0) -> bool:
    """Cheap sniff: do ``buf[offset:]`` start a columnar frame?"""
    return bytes(buf[offset : offset + len(MAGIC)]) == MAGIC


def is_checked(buf, offset: int = 0) -> bool:
    """Was the frame at ``buf[offset:]`` emitted WITH CRC words (flags
    bit 0)? A frame written under ``SRJT_INTEGRITY_CHECKS=0`` carries
    no hashes — decoding it verifies nothing, and callers keeping
    verified-coverage counters must not count it as checked."""
    if not is_frame(buf, offset):
        return False
    try:
        _magic, _version, flags, _npart = _PREAMBLE.unpack_from(
            memoryview(buf), offset
        )
    except struct.error:
        return False
    return bool(flags & _FLAG_CRC)


# ---------------------------------------------------------------------------
# part-level codec (the one encoder/decoder every surface shares)
# ---------------------------------------------------------------------------


def encode_parts(parts: Sequence[FramePart]) -> bytes:
    """Encode ``parts`` into one frame. Per-part CRCs (and the header
    CRC) are emitted only while integrity checks are armed — disarmed
    frames carry flags bit 0 clear and zero CRC words, no hashing."""
    checked = integrity.is_enabled()
    flags = _FLAG_CRC if checked else 0
    head = [_PREAMBLE.pack(MAGIC, VERSION, flags, len(parts))]
    payloads: List[bytes] = []
    for p in parts:
        blob = p.array.tobytes()
        dstr = p.array.dtype.str.encode("ascii")
        shape = p.array.shape
        crc = integrity.checksum(blob) if checked else 0
        head.append(
            struct.pack("<iiBIQ", p.type_id, p.scale, p.role, p.col, p.null_count)
            + struct.pack("<B", len(dstr)) + dstr
            + struct.pack("<B", len(shape))
            + struct.pack(f"<{len(shape)}Q", *shape)
            + struct.pack("<QI", len(blob), crc)
        )
        payloads.append(blob)
    header = b"".join(head)
    hcrc = integrity.checksum(header) if checked else 0
    return header + struct.pack("<I", hcrc) + b"".join(payloads)


def decode_parts(
    buf, where: str = "columnar.frame", offset: int = 0
) -> Tuple[List[FramePart], int]:
    """Decode one frame from ``buf[offset:]``; returns (parts, end
    offset). A non-frame prefix raises ValueError (callers sniff with
    ``is_frame`` first); a frame whose bytes rotted — bad header CRC,
    truncated payload, part CRC mismatch — raises retryable
    ``DataCorruption`` counted under ``where``."""
    view = memoryview(buf)
    if not is_frame(view, offset):
        raise ValueError(f"{where}: not a columnar frame (bad magic)")
    try:
        magic, version, flags, npart = _PREAMBLE.unpack_from(view, offset)
    except struct.error:
        raise integrity.raise_corruption(where, "truncated frame preamble")
    if version != VERSION:
        raise ValueError(f"{where}: unsupported frame version {version}")
    checked = bool(flags & _FLAG_CRC) and integrity.is_enabled()
    pos = offset + _PREAMBLE.size
    descs = []
    try:
        for _ in range(npart):
            type_id, scale, role, col, null_count = struct.unpack_from(
                "<iiBIQ", view, pos
            )
            pos += 21
            (dlen,) = struct.unpack_from("<B", view, pos)
            pos += 1
            dstr = bytes(view[pos : pos + dlen]).decode("ascii")
            pos += dlen
            (ndim,) = struct.unpack_from("<B", view, pos)
            pos += 1
            shape = struct.unpack_from(f"<{ndim}Q", view, pos)
            pos += 8 * ndim
            nbytes, crc = struct.unpack_from("<QI", view, pos)
            pos += 12
            descs.append((type_id, scale, role, col, null_count, dstr, shape, nbytes, crc))
        (hcrc,) = struct.unpack_from("<I", view, pos)
    except (struct.error, UnicodeDecodeError):
        raise integrity.raise_corruption(where, "truncated/garbled frame header")
    if checked:
        integrity.verify(bytes(view[offset:pos]), hcrc, f"{where}.header")
    pos += 4
    parts: List[FramePart] = []
    for type_id, scale, role, col, null_count, dstr, shape, nbytes, crc in descs:
        blob = bytes(view[pos : pos + nbytes])
        if len(blob) != nbytes:
            raise integrity.raise_corruption(
                where, f"truncated part payload ({len(blob)} != {nbytes})"
            )
        pos += nbytes
        if checked:
            integrity.verify(blob, crc, where)
        try:
            arr = np.frombuffer(blob, dtype=np.dtype(dstr)).reshape(shape)
        except (TypeError, ValueError) as e:
            raise integrity.raise_corruption(where, f"undecodable part ({e})")
        parts.append(FramePart(arr, type_id, scale, role, col, null_count))
    if checked:
        from ..utils import metrics

        metrics.registry().counter(
            "sidecar.integrity.frame_decodes_checked"
        ).inc()
    return parts, pos


# ---------------------------------------------------------------------------
# Table layer (sidecar wire payloads, TCP exchange partitions)
# ---------------------------------------------------------------------------


def encode_table(table) -> bytes:
    """Encode a columnar Table as one frame. Covers the sidecar wire
    surface: fixed-width columns (DECIMAL128 [N, 4] limbs included),
    STRING (offsets + chars), LIST with a byte child, each with an
    optional validity part."""
    from .dtype import TypeId

    parts: List[FramePart] = []
    for i, col in enumerate(table.columns):
        d = col.dtype
        tid = int(d.id.value)
        null_count = 0
        if col.validity is not None:
            v = np.asarray(col.validity, np.uint8)
            null_count = int(v.size - int(np.count_nonzero(v)))
        if d.id in (TypeId.STRING, TypeId.LIST):
            parts.append(FramePart(
                np.asarray(col.offsets, np.int32), tid, d.scale,
                ROLE_OFFSETS, i, null_count,
            ))
            chars = (
                np.asarray(col.chars, np.uint8)
                if d.id == TypeId.STRING
                else np.asarray(col.child.data).view(np.uint8)
            )
            parts.append(FramePart(chars, tid, d.scale, ROLE_CHARS, i, null_count))
        elif d.id == TypeId.STRUCT:
            raise ValueError("frames: STRUCT columns do not cross the wire")
        else:
            parts.append(FramePart(
                np.asarray(col.data), tid, d.scale, ROLE_DATA, i, null_count
            ))
        if col.validity is not None:
            parts.append(FramePart(
                np.asarray(col.validity, np.uint8), tid, d.scale,
                ROLE_VALIDITY, i, null_count,
            ))
    return encode_parts(parts)


def decode_table(buf, where: str = "columnar.frame", offset: int = 0):
    """Decode a frame back into a Table (default column names, like the
    legacy wire walker)."""
    import jax.numpy as jnp

    from .column import Column
    from .dtype import DType, TypeId
    from .table import Table

    parts, _end = decode_parts(buf, where=where, offset=offset)
    by_col: dict = {}
    order: List[int] = []
    for p in parts:
        if p.col not in by_col:
            by_col[p.col] = {}
            order.append(p.col)
        by_col[p.col][p.role] = p
    cols = []
    for ci in order:
        roles = by_col[ci]
        anchor = roles.get(ROLE_DATA) or roles.get(ROLE_OFFSETS)
        if anchor is None:
            raise integrity.raise_corruption(
                where, f"column {ci} has neither data nor offsets part"
            )
        tid = TypeId(anchor.type_id)
        d = DType(tid, anchor.scale if tid.name.startswith("DECIMAL") else 0)
        vp = roles.get(ROLE_VALIDITY)
        validity = (
            jnp.asarray(vp.array.astype(bool)) if vp is not None else None
        )
        if tid in (TypeId.STRING, TypeId.LIST):
            offs = jnp.asarray(roles[ROLE_OFFSETS].array)
            chars = roles.get(ROLE_CHARS)
            cbytes = chars.array if chars is not None else np.zeros(0, np.uint8)
            if tid == TypeId.LIST:
                cols.append(Column(
                    d, validity=validity, offsets=offs,
                    child=Column(
                        DType(TypeId.INT8),
                        data=jnp.asarray(cbytes).view(jnp.int8),
                    ),
                ))
            else:
                cols.append(Column(
                    d, validity=validity, offsets=offs, chars=jnp.asarray(cbytes)
                ))
        else:
            cols.append(Column(d, data=jnp.asarray(anchor.array), validity=validity))
    return Table(cols)


# ---------------------------------------------------------------------------
# raw-leaves layer (memgov disk spills: any pytree's ndarray leaves)
# ---------------------------------------------------------------------------


def encode_leaves(leaves: Sequence[np.ndarray]) -> bytes:
    """Encode a flat list of ndarrays (a spilled pytree's leaves) as one
    frame of raw parts — dtype and shape round-trip exactly, so a
    spill->load cycle is bit-identical."""
    return encode_parts([
        FramePart(np.asarray(a), _RAW_TYPE_ID, 0, ROLE_DATA, i)
        for i, a in enumerate(leaves)
    ])


def decode_leaves(buf, where: str = "memgov.spill") -> List[np.ndarray]:
    parts, _end = decode_parts(buf, where=where)
    out: List[Optional[np.ndarray]] = [None] * len(parts)
    for p in parts:
        if not (0 <= p.col < len(parts)) or out[p.col] is not None:
            raise integrity.raise_corruption(where, "garbled leaf indexing")
        out[p.col] = p.array
    return out  # type: ignore[return-value]

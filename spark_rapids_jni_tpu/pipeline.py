"""Compiled query pipelines: (plan, schema) -> ONE XLA program.

The execution model the Spark plugin needs per offloaded stage
(SURVEY §2.8's cudf hash-agg path, reference plugin behavior): rewrite a
physical plan's scan->filter->project->aggregate stage into a single
compiled program per (plan, schema) pair, so a remote/TPU backend pays
one dispatch per ColumnarBatch instead of one per operator. Round 1
hand-fused exactly two queries (models/compiled.py); this is the
general mechanism — the hand-fused forms are now thin plans.

Design notes (TPU-first):
- ``Table`` is a jax pytree, so the whole plan body traces under one
  ``jax.jit``; the plan spec (expressions, group keys, agg list) is
  Python-static and closed over per CompiledPipeline instance.
- Grouped aggregation uses BOUNDED key domains (dictionary-coded group
  columns, the plugin's common case): group ids are computed as a mixed
  radix over the per-key domains and reduced with dense segment
  reductions — no sort, no data-dependent shapes, empty groups carried
  densely and compacted host-side at the end.
- Filters never materialize a filtered table: rows outside the
  predicate fall into a trash segment (grouped) or a masked identity
  (global), exactly like the hand-fused kernels did.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .columnar import Column, Table
from .columnar import dtype as dt
from .ops import bitutils
from .ops.expressions import Expression
from .utils import deadline, metrics
from .utils.dispatch import op_boundary

__all__ = ["Agg", "GroupKey", "JoinSpec", "PlanSpec", "CompiledPipeline", "compile_plan"]

_AGG_HOWS = ("sum", "count", "count_all", "min", "max", "mean")


@dataclasses.dataclass(frozen=True)
class Agg:
    """One aggregate over an input or projected column."""

    source: str
    how: str
    name: Optional[str] = None  # output column name; default source_how

    @property
    def out_name(self) -> str:
        return self.name or f"{self.source}_{self.how}"


@dataclasses.dataclass(frozen=True)
class GroupKey:
    """Bounded-domain group key: values must lie in [0, num_keys)."""

    column: str
    num_keys: int


@dataclasses.dataclass(frozen=True)
class JoinSpec:
    """Join against a BUILD table (the broadcast dim-join Spark offloads
    per stage; q3's star joins, q95's EXISTS / NOT EXISTS). Two
    TPU-first lowerings, both static-shape inside the one compiled
    program:

    - ``num_keys`` set — bounded-domain: the build side scatters into a
      DENSE [num_keys] presence/payload map (dim keys are bounded) and
      the probe is a row gather.
    - ``num_keys=None`` — SORT-MERGE fallback for arbitrary int64 keys
      (cudf's general hash join has no domain restriction, SURVEY
      §2.8): the build side sorts once (excluded rows park at a +inf
      sentinel), the probe binary-searches (log2 |build| gathers), and
      every candidate verifies raw key equality, so sentinel collisions
      are impossible. Probe misses flow into the same trash-segment
      mask either way.

    ``how``: "inner" gathers ``payload`` columns into the working
    schema and drops probe misses; "semi"/"anti" keep/drop rows by
    presence only (payload must be empty). Build keys must be UNIQUE
    among rows passing ``build_filter`` for inner joins —
    duplicates are surfaced as a loud error, like out-of-domain group
    keys."""

    build: str  # name of the build table passed to __call__
    probe_key: str  # column in the working (fact-side) schema
    build_key: str  # column in the build table
    num_keys: Optional[int] = None  # bounded domain; None = sort-merge
    payload: Tuple[str, ...] = ()
    how: str = "inner"
    build_filter: Optional[Expression] = None

    def __post_init__(self):
        if self.how not in ("inner", "semi", "anti"):
            raise ValueError(f"unknown join {self.how!r}")
        if self.how != "inner" and self.payload:
            raise ValueError("payload columns require an inner join")


@dataclasses.dataclass(frozen=True)
class PlanSpec:
    """Declarative single-stage plan: join* -> filter -> project ->
    aggregate, compiled to ONE program.

    ``joins`` apply in order and splice their payload columns into the
    working schema; ``filter`` and ``project`` see the post-join
    schema; aggregates may reference input, payload, or projected
    names. With no ``group_by`` the stage is a global aggregation
    producing one row.
    """

    filter: Optional[Expression] = None
    project: Tuple[Tuple[str, Expression], ...] = ()
    group_by: Tuple[GroupKey, ...] = ()
    aggregates: Tuple[Agg, ...] = ()
    joins: Tuple[JoinSpec, ...] = ()

    def __post_init__(self):
        if not self.aggregates:
            raise ValueError("plan needs at least one aggregate")
        for a in self.aggregates:
            if a.how not in _AGG_HOWS:
                raise ValueError(f"unknown aggregate {a.how!r}")




class CompiledPipeline:
    """A plan compiled against a schema: call with a Table of that
    schema; every call with the same shapes reuses one XLA executable."""

    def __init__(self, plan: PlanSpec):
        self.plan = plan
        self._fn = jax.jit(self._trace)
        self._build_handles: Dict[str, object] = {}
        self._build_finalizer = None
        metrics.counter("pipeline.compiles").inc()

    # -- spillable build tables (memgov/, ISSUE 4) --------------------------
    def register_build(self, name: str, table: Table) -> None:
        """Attach a BUILD table to this pipeline through the memory
        governor's spillable catalog: ``__call__`` materializes it
        automatically (no ``builds`` entry needed), and BETWEEN calls
        the table may demote device->host(->disk) under memory pressure
        and re-materialize transparently — bit-identical — on the next
        batch. During a call the handle is pinned so the pressure loop
        cannot demote it mid-dispatch. Registration is bookkeeping
        (always-on); demotion only ever happens under an armed
        governor's pressure loop. A dropped pipeline cleans up after
        itself (weakref finalizer), so catalog entries and their spill
        files never outlive the pipeline that registered them."""
        import weakref

        from . import memgov

        cat = memgov.catalog()
        key = f"pipeline.build.{id(self)}.{name}"
        self._build_handles[name] = cat.register(key, table, kind="build")
        if self._build_finalizer is None:
            # the callback must not capture self: it holds the handle
            # DICT (shared, mutated by register/unregister) instead
            self._build_finalizer = weakref.finalize(
                self, _drop_build_handles, self._build_handles
            )

    def unregister_builds(self) -> None:
        """Drop this pipeline's registered build tables from the
        catalog (and any spill files backing them)."""
        _drop_build_handles(self._build_handles)

    # -- traced body (ONE program) -----------------------------------------
    def _trace(self, table: Table, builds: Dict[str, Table]):
        plan = self.plan
        cols = dict(zip(table.names, table.columns))
        mask = None
        n_dup = jnp.zeros((), jnp.int64)

        n_bad_build = jnp.zeros((), jnp.int64)
        for js in plan.joins:
            if js.num_keys is None:
                hit, joined, dups, bad_build = _sorted_join(js, cols, builds[js.build])
            else:
                hit, joined, dups, bad_build = _dense_join(js, cols, builds[js.build])
            n_dup = n_dup + dups
            n_bad_build = n_bad_build + bad_build
            keep = ~hit if js.how == "anti" else hit
            mask = keep if mask is None else mask & keep
            cols.update(joined)

        if plan.filter is not None:
            work = Table(list(cols.values()), list(cols.keys()))
            pred = plan.filter.evaluate(work)
            fm = pred.data.astype(bool)
            if pred.validity is not None:
                fm = fm & pred.validity
            mask = fm if mask is None else mask & fm

        # projected columns become part of the working schema
        work = Table(list(cols.values()), list(cols.keys()))
        for name, expr in plan.project:
            cols[name] = expr.evaluate(work)

        def masked_valid(col: Column):
            v = None if col.validity is None else col.validity
            if mask is not None:
                v = mask if v is None else (v & mask)
            return v

        if not plan.group_by:
            out = {}
            for agg in plan.aggregates:
                col = cols[agg.source]
                if agg.how == "count_all":
                    # COUNT(*): filter applies, null VALUES still count
                    v = mask
                else:
                    v = masked_valid(col)
                out[agg.out_name] = _global_agg(col, v, agg.how)
            return out, None, None, None, n_dup, n_bad_build

        # mixed-radix group id over the bounded domains; rows filtered
        # out (or null-keyed) land in the trash segment
        num = 1
        for gk in plan.group_by:
            num *= gk.num_keys
        gid = jnp.zeros((table.num_rows,), jnp.int32)
        bad = jnp.zeros((table.num_rows,), bool)  # null key or filtered
        out_of_domain = jnp.zeros((table.num_rows,), bool)
        for gk in plan.group_by:
            kcol = cols[gk.column]
            k = kcol.data.astype(jnp.int32)
            oob = (k < 0) | (k >= gk.num_keys)
            if kcol.validity is not None:
                oob = oob & kcol.validity  # null keys are not "out of domain"
                bad = bad | ~kcol.validity
            out_of_domain = out_of_domain | oob
            bad = bad | oob
            gid = gid * gk.num_keys + jnp.clip(k, 0, gk.num_keys - 1)
        if mask is not None:
            bad = bad | ~mask
            out_of_domain = out_of_domain & mask
        gid = jnp.where(bad, num, gid)
        # rows whose key escaped the declared bounded domain: a plan
        # mis-declaration, surfaced loudly (host wrapper raises)
        n_out_of_domain = jnp.sum(out_of_domain.astype(jnp.int64))

        counts_all = jax.ops.segment_sum(
            jnp.ones_like(gid, jnp.int64), gid, num_segments=num + 1
        )[:num]
        aggs = {}
        for agg in plan.aggregates:
            col = cols[agg.source]
            v = None if col.validity is None else col.validity
            aggs[agg.out_name] = _grouped_agg(col, v, gid, num, agg.how, counts_all)
        return aggs, counts_all, num, n_out_of_domain, n_dup, n_bad_build

    # -- host wrapper -------------------------------------------------------
    @op_boundary("compiled_pipeline")
    def __call__(self, table: Table, builds: Optional[Dict[str, Table]] = None) -> Table:
        """One batch through the compiled program. The op_boundary
        wrapper makes this a deadline-scoped dispatch: pass
        ``deadline_s=`` for a per-call budget (or set SRJT_DEADLINE_SEC
        for the ambient per-query budget), and the whole call —
        including armed retries and their backoffs — is bounded, with
        a cooperative cancel point between the device dispatch and the
        host-side result materialization."""
        plan = self.plan
        # end-to-end pipeline stats: batch/row throughput counters (the
        # op_boundary wrapper already records wall time per dispatch)
        metrics.counter("pipeline.batches").inc()
        metrics.counter("pipeline.rows").inc(table.num_rows)
        # catalog-registered build tables fill in (re-materializing if
        # demoted); an explicit `builds` entry of the same name wins
        pinned = []
        if self._build_handles:
            builds = dict(builds or {})
            for name, h in self._build_handles.items():
                if name not in builds:
                    pinned.append(h.pin())
                    builds[name] = h.get()
        try:
            want = {js.build for js in plan.joins}
            have = set(builds or {})
            if want != have:
                raise ValueError(f"plan needs build tables {sorted(want)}, got {sorted(have)}")
            aggs, counts_all, num, n_oob, n_dup, n_bad_build = self._fn(table, builds or {})
        finally:
            for h in pinned:
                h.unpin()
        # cancel point: a query whose budget died during the compiled
        # dispatch stops HERE, before paying the host syncs/compaction
        deadline.check("compiled_pipeline")
        if plan.joins:
            # one host sync covers both join mis-declaration classes
            dups, bad_build = int(n_dup), int(n_bad_build)
            if dups:
                raise ValueError(
                    f"{dups} duplicate build keys in an inner-join payload map; "
                    "bounded-domain joins require unique build keys"
                )
            if bad_build:
                raise ValueError(
                    f"{bad_build} build rows have join keys outside the declared "
                    "bounded domain; widen the JoinSpec num_keys"
                )
        if n_oob is not None:
            oob = int(n_oob)  # piggybacks on the result-size host sync
            if oob:
                raise ValueError(
                    f"{oob} rows have group keys outside the declared bounded "
                    "domain; widen the GroupKey num_keys or pre-filter"
                )
        if not plan.group_by:
            out_cols, names = [], []
            for agg in plan.aggregates:
                data, valid = aggs[agg.out_name]
                out_cols.append(
                    _wrap_result(data[None], None if valid is None else valid[None], agg.how)
                )
                names.append(agg.out_name)
            return Table(out_cols, names)

        # compact non-empty groups (one host sync for the result size —
        # the same sync every grouped aggregation pays at gather time)
        counts_np = np.asarray(counts_all)
        present = np.nonzero(counts_np > 0)[0]
        idx = jnp.asarray(present, jnp.int32)
        out_cols, names = [], []
        radix = present.copy()
        for gk in reversed(plan.group_by):
            out_cols.insert(0, Column(dt.INT32, data=jnp.asarray(radix % gk.num_keys, jnp.int32)))
            radix //= gk.num_keys
        names = [gk.column for gk in plan.group_by]
        for agg in plan.aggregates:
            data, valid = aggs[agg.out_name]
            out_cols.append(_wrap_result(data[idx], None if valid is None else valid[idx], agg.how))
            names.append(agg.out_name)
        return Table(out_cols, names)


def _global_agg(col: Column, v, how: str):
    """Global (one-group) aggregate: delegates to the grouped kernels
    with a single segment so every exactness path is shared."""
    n = len(col)
    gid = jnp.zeros((n,), jnp.int32)
    m = jnp.ones((n,), bool) if v is None else v
    counts = jnp.sum(m.astype(jnp.int64))[None]
    data, valid = _grouped_agg(col, v, gid, 1, how, counts)
    return data[0], None if valid is None else valid[0]


def _grouped_agg(col: Column, v, gid, num: int, how: str, counts_all):
    """Dense [num] aggregate + optional [num] validity, rows with
    gid==num dropped.

    Exactness contract (VERDICT r3 item 5): FLOAT64 SUM/MEAN ride the
    windowed integer accumulator (ops/f64acc — correctly rounded f64,
    bit-identical CPU vs TPU); integer SUM accumulates in exact int64
    (MEAN divides the exact sum via the limb divider); FLOAT64 and
    integer MIN/MAX compare in the exact total-order / integer domain,
    never through a lossy f32 view. Exact FLOAT64 results return as
    uint64 IEEE bits (detected downstream by _wrap_result). FLOAT32
    keeps the f32 MXU kernel."""
    n = len(col)
    m = jnp.ones((n,), bool) if v is None else v
    gid_v = jnp.where(m, gid, num)  # null values drop from value aggs
    if how == "count_all":
        return counts_all, None
    if how == "count":
        # exact int64 count via key routing
        c = jax.ops.segment_sum(m.astype(jnp.int64), gid_v, num_segments=num + 1)[:num]
        return c, None
    d = col.dtype
    if how in ("sum", "mean"):
        if d.id == dt.TypeId.FLOAT64:
            from .ops.f64acc import segment_mean_f64bits, segment_sum_f64bits

            if how == "sum":
                s = segment_sum_f64bits(col.data, gid_v, num + 1)[:num]
                c = jax.ops.segment_sum(
                    m.astype(jnp.int64), gid_v, num_segments=num + 1
                )[:num]
                return s, c > 0
            mb, c = segment_mean_f64bits(col.data, gid_v, num + 1)
            return mb[:num], c[:num] > 0
        if not d.is_floating:
            # integers: exact int64 accumulation (Spark sum(int)->long);
            # results materialize into FLOAT64 bits without an f32 hop.
            # UINT64 sums share the same two's-complement bits (mod
            # 2^64) — only the final interpretation reads them unsigned
            from jax import lax as _lax

            from .ops.f64acc import (
                i64_to_f64bits,
                mean_i64_div,
                u64_to_f64bits,
            )

            is_u64 = col.data.dtype == jnp.uint64
            vals = _lax.bitcast_convert_type(col.data, jnp.int64) if is_u64 else col.data.astype(jnp.int64)
            s = jax.ops.segment_sum(
                jnp.where(m, vals, 0), gid_v, num_segments=num + 1
            )[:num]
            c = jax.ops.segment_sum(m.astype(jnp.int64), gid_v, num_segments=num + 1)[:num]
            if how == "sum":
                if is_u64:
                    return u64_to_f64bits(_lax.bitcast_convert_type(s, jnp.uint64)), c > 0
                return i64_to_f64bits(s), c > 0
            if is_u64:
                return mean_i64_div(_lax.bitcast_convert_type(s, jnp.uint64), c, unsigned=True), c > 0
            return mean_i64_div(s, c), c > 0
        # FLOAT32: one fused kernel for (sums, per-group valid counts) —
        # segment_sum lowers to the slow XLA scatter class on TPU; the
        # MXU outer-product kernel in groupby_sum_bounded is ~17x faster
        # at the 1M x 4096 axis and falls back to segment_sum off-TPU
        from .ops.aggregate import groupby_sum_bounded

        s, c = groupby_sum_bounded(gid_v, col.data, num)
        if how == "sum":
            return s, c > 0
        cf = c.astype(s.dtype)
        return s / jnp.maximum(cf, 1.0), c > 0
    # min/max validity comes from the per-group valid-row COUNT, never
    # from isfinite(result): a genuine +/-inf value must survive
    has_vals = jax.ops.segment_sum(m.astype(jnp.int32), gid_v, num_segments=num + 1)[:num] > 0
    lo_i, hi_i = jnp.iinfo(jnp.int64).min, jnp.iinfo(jnp.int64).max
    if d.id == dt.TypeId.FLOAT64:
        # exact total-order comparison on the stored bits; the u64 key
        # views as order-preserving int64 so segment_min/max stay on the
        # well-trodden s64 path
        from jax import lax

        from .ops import bitutils as _bt
        from .ops.aggregate import _from_total_order

        key = _bt.total_order_key(col.data, dt.FLOAT64)
        k = lax.bitcast_convert_type(key ^ jnp.uint64(1 << 63), jnp.int64)
        fill = hi_i if how == "min" else lo_i
        red = jax.ops.segment_min if how == "min" else jax.ops.segment_max
        r = red(jnp.where(m, k, fill), gid_v, num_segments=num + 1)[:num]
        key_back = lax.bitcast_convert_type(r, jnp.uint64) ^ jnp.uint64(1 << 63)
        return _from_total_order(key_back, dt.FLOAT64), has_vals
    if not d.is_floating:
        from jax import lax as _lax

        from .ops.f64acc import i64_to_f64bits, u64_to_f64bits

        is_u64 = col.data.dtype == jnp.uint64
        if is_u64:
            # order-preserving signed view (flip the top bit) so the
            # comparison stays correct past 2^63
            vals = _lax.bitcast_convert_type(
                col.data ^ jnp.uint64(1 << 63), jnp.int64
            )
        else:
            vals = col.data.astype(jnp.int64)
        fill = hi_i if how == "min" else lo_i
        red = jax.ops.segment_min if how == "min" else jax.ops.segment_max
        r = red(jnp.where(m, vals, fill), gid_v, num_segments=num + 1)[:num]
        r = jnp.where(has_vals, r, 0)
        if is_u64:
            back = _lax.bitcast_convert_type(r, jnp.uint64) ^ jnp.uint64(1 << 63)
            return u64_to_f64bits(jnp.where(has_vals, back, jnp.uint64(0))), has_vals
        return i64_to_f64bits(r), has_vals
    x = col.data
    if how == "min":
        s = jax.ops.segment_min(jnp.where(m, x, jnp.inf), gid_v, num_segments=num + 1)[:num]
        return s, has_vals
    s = jax.ops.segment_max(jnp.where(m, x, -jnp.inf), gid_v, num_segments=num + 1)[:num]
    return s, has_vals


def _build_enter_mask(js: JoinSpec, bt: Table) -> jnp.ndarray:
    """Build-side liveness: valid key AND build_filter (with its own
    null semantics) — shared by both join lowerings so filter handling
    can never diverge between them."""
    bk = bt.column(js.build_key)
    enter = bk.valid_mask()
    if js.build_filter is not None:
        bf = js.build_filter.evaluate(bt)
        bfm = bf.data.astype(bool)
        if bf.validity is not None:
            bfm = bfm & bf.validity
        enter = enter & bfm
    return enter


def _sorted_join(js: JoinSpec, cols: Dict[str, Column], bt: Table):
    """Sort-merge lowering for unbounded build keys (JoinSpec
    num_keys=None): lexsort the build side by (parked-last, key) so
    entered rows form a sorted prefix at every key — including a
    genuine INT64_MAX key, which therefore cannot collide with the
    parked sentinel — then binary-search every probe and verify raw
    equality AND build-row liveness. Same (hit, joined, dups,
    bad_build) contract as _dense_join (payload columns are always
    emitted, null-filled when the build is empty); bad_build is always
    0 (there is no declared domain to escape)."""
    bk = bt.column(js.build_key)
    n_b = len(bk)
    enter = _build_enter_mask(js, bt)
    keys = bk.data.astype(jnp.int64)
    big = jnp.int64((1 << 63) - 1)

    pcol = cols[js.probe_key]
    pk = pcol.data.astype(jnp.int64)
    n_p = pk.shape[0]

    def null_payloads():
        out: Dict[str, Column] = {}
        for pname in js.payload:
            src_c = bt.column(pname)
            d = src_c.dtype
            if not d.is_fixed_width or d.id == dt.TypeId.DECIMAL128:
                raise ValueError(f"join payload {pname!r}: only plain fixed-width columns")
            shape = (n_p,) + src_c.data.shape[1:]
            out[pname] = Column(
                d,
                data=jnp.zeros(shape, src_c.data.dtype),
                validity=jnp.zeros((n_p,), bool),
            )
        return out

    dups = jnp.zeros((), jnp.int64)
    if n_b == 0:
        hit = jnp.zeros((n_p,), bool)
        return hit, null_payloads(), dups, jnp.zeros((), jnp.int64)

    # parked rows sort AFTER every entered row, entered rows by key:
    # searchsorted(side='left') therefore always lands on an entered
    # row when one exists for the probe key
    order = jnp.lexsort((keys, ~enter)).astype(jnp.int32)
    ks = keys[order]
    es = enter[order]
    sk = jnp.where(es, ks, big)

    if js.how == "inner" and n_b > 1:
        dups = jnp.sum(((ks[1:] == ks[:-1]) & es[1:] & es[:-1]).astype(jnp.int64))

    idx = jnp.clip(
        jnp.searchsorted(sk, pk, side="left"), 0, n_b - 1
    ).astype(jnp.int32)
    src = order[idx]
    hit = (ks[idx] == pk) & es[idx] & pcol.valid_mask()

    joined: Dict[str, Column] = {}
    for pname in js.payload:
        pc = bt.column(pname)
        d = pc.dtype
        if not d.is_fixed_width or d.id == dt.TypeId.DECIMAL128:
            raise ValueError(f"join payload {pname!r}: only plain fixed-width columns")
        data = jnp.where(
            hit.reshape(hit.shape + (1,) * (pc.data.ndim - 1)),
            pc.data[src],
            jnp.zeros((), pc.data.dtype),
        )
        pv = pc.valid_mask()[src] & hit
        joined[pname] = Column(d, data=data, validity=pv)
    return hit, joined, dups, jnp.zeros((), jnp.int64)


def _dense_join(js: JoinSpec, cols: Dict[str, Column], bt: Table):
    """One bounded-domain join: scatter the (filtered) build side into
    dense presence/payload maps, probe by row gather. Returns
    (hit [N] bool, {name: joined Column}, duplicate-key count,
    out-of-domain build-row count — both loud mis-declaration errors)."""
    num = js.num_keys
    bk = bt.column(js.build_key)
    enter = _build_enter_mask(js, bt)
    # domain guard BEFORE the i32 narrowing: an int64 key >= 2^31 must
    # miss, not wrap into the valid domain. A build row INSIDE the
    # filter but OUTSIDE the declared domain is a mis-declaration
    # (silently dropping it would quietly un-match fact rows) — counted
    # and raised host-side like out-of-domain group keys.
    in_dom_b = (bk.data >= 0) & (bk.data < num)
    bad_build = jnp.sum((enter & ~in_dom_b).astype(jnp.int64))
    enter = enter & in_dom_b
    bkeys = bk.data.astype(jnp.int32)
    slot = jnp.where(enter, bkeys, num)  # trash slot for dropped rows

    present = (
        jnp.zeros((num + 1,), bool).at[slot].set(True, mode="drop")[:num]
    )
    dups = jnp.zeros((), jnp.int64)
    if js.how == "inner":
        # duplicate build keys would silently collapse inner-join row
        # multiplicity to semi semantics — always surfaced, with or
        # without payload columns
        cnt = jax.ops.segment_sum(enter.astype(jnp.int32), slot, num_segments=num + 1)[:num]
        dups = jnp.sum((cnt > 1).astype(jnp.int64))

    pcol = cols[js.probe_key]
    indom = (pcol.data >= 0) & (pcol.data < num)
    pkc = jnp.clip(pcol.data, 0, num - 1).astype(jnp.int32)
    hit = present[pkc] & indom & pcol.valid_mask()

    joined: Dict[str, Column] = {}
    for pname in js.payload:
        src = bt.column(pname)
        d = src.dtype
        if not d.is_fixed_width or d.id == dt.TypeId.DECIMAL128:
            raise ValueError(f"join payload {pname!r}: only plain fixed-width columns")
        dense = jnp.zeros((num + 1,), src.data.dtype).at[slot].set(
            jnp.where(enter, src.data, jnp.zeros((), src.data.dtype)), mode="drop"
        )[:num]
        dvalid = (
            jnp.zeros((num + 1,), bool).at[slot].set(src.valid_mask() & enter, mode="drop")[:num]
        )
        joined[pname] = Column(d, data=dense[pkc], validity=dvalid[pkc] & hit)
    return hit, joined, dups, bad_build


def _wrap_result(data, valid, how: str) -> Column:
    if how in ("count", "count_all"):
        return Column(dt.INT64, data=data.astype(jnp.int64), validity=valid)
    if data.dtype == jnp.uint64:
        # exact paths return ready-made FLOAT64 IEEE bits
        return Column(dt.FLOAT64, data=data, validity=valid)
    # f32-lane aggregates store into the FLOAT64 bit format
    return Column(dt.FLOAT64, data=bitutils.float_store(data.astype(jnp.float64), dt.FLOAT64), validity=valid)


def _drop_build_handles(handles: Dict[str, object]) -> None:
    """Close a pipeline's registered build handles (module-level so the
    weakref finalizer keeps no reference to the pipeline itself)."""
    for h in handles.values():
        h.close()
    handles.clear()


def compile_plan(plan: PlanSpec) -> CompiledPipeline:
    """Compile a plan once; reuse across batches of the same schema."""
    return CompiledPipeline(plan)

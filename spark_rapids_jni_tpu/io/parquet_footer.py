"""Parquet footer service: parse, prune, row-group filter, re-serialize.

Pure-CPU metadata path, behavioral parity with reference
NativeParquetJni.cpp and ParquetFooter.java:

- schema DSL (StructElement/ListElement/MapElement/ValueElement with
  builder + depth-first flattening, ParquetFooter.java:35-185),
- ``column_pruner`` rebuilt from the flattened (names, num_children,
  tags) triple exactly as the JNI does (:394-439), producing
  {schema_map, schema_num_children, chunk_map} gather maps (:84-94),
- per-Tag filter_schema variants — STRUCT (:185-219), VALUE (:224-240),
  LIST incl. legacy 2-level and ``_tuple`` formats (:245-305),
  MAP/MAP_KEY_VALUE (:310-361),
- row-group selection by split midpoint with the PARQUET-2078 bad-offset
  workaround (:445-525),
- unicode-aware case folding (:45-77 uses towlower; python str.lower),
- re-serialization framed as PAR1 + thrift + little-endian length + PAR1
  (:672-706) so downstream readers accept it as a data-less file.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple

from . import thrift_compact as tc
from .thrift_compact import ThriftList, ThriftStruct

__all__ = [
    "Tag",
    "ValueElement",
    "ListElement",
    "MapElement",
    "StructElement",
    "ParquetFooter",
    "read_and_filter",
]


# FileMetaData field ids (parquet.thrift)
_FMD_VERSION = 1
_FMD_SCHEMA = 2
_FMD_NUM_ROWS = 3
_FMD_ROW_GROUPS = 4
_FMD_COLUMN_ORDERS = 7
# SchemaElement
_SE_TYPE = 1
_SE_REPETITION = 3
_SE_NAME = 4
_SE_NUM_CHILDREN = 5
_SE_CONVERTED_TYPE = 6
# RowGroup
_RG_COLUMNS = 1
_RG_NUM_ROWS = 3
_RG_FILE_OFFSET = 5
_RG_TOTAL_COMPRESSED_SIZE = 6
# ColumnChunk
_CC_META_DATA = 3
# ColumnMetaData
_CMD_TOTAL_COMPRESSED_SIZE = 7
_CMD_DATA_PAGE_OFFSET = 9
_CMD_DICT_PAGE_OFFSET = 11

_REPEATED = 2
_CONVERTED_LIST = 3
_CONVERTED_MAP = 1
_CONVERTED_MAP_KEY_VALUE = 2


class Tag:
    VALUE = 0
    STRUCT = 1
    LIST = 2
    MAP = 3


# ---------------------------------------------------------------------------
# schema DSL (ParquetFooter.java:35-93)
# ---------------------------------------------------------------------------


class _SchemaElement:
    def flatten(self, names: List[str], num_children: List[int], tags: List[int]) -> None:
        raise NotImplementedError


class ValueElement(_SchemaElement):
    def flatten(self, names, num_children, tags):
        pass  # leaf: contributes nothing below itself

    children: Sequence[Tuple[str, "_SchemaElement"]] = ()
    tag = Tag.VALUE


class ListElement(_SchemaElement):
    tag = Tag.LIST

    def __init__(self, item: _SchemaElement):
        self.item = item

    @property
    def children(self):
        return (("element", self.item),)


class MapElement(_SchemaElement):
    tag = Tag.MAP

    def __init__(self, key: _SchemaElement, value: _SchemaElement):
        self.key = key
        self.value = value

    @property
    def children(self):
        return (("key", self.key), ("value", self.value))


class StructElement(_SchemaElement):
    """Builder mirror of ParquetFooter.StructElement (:58-93)."""

    tag = Tag.STRUCT

    def __init__(self, fields: Optional[Sequence[Tuple[str, _SchemaElement]]] = None):
        self._fields: List[Tuple[str, _SchemaElement]] = list(fields) if fields else []

    def add_child(self, name: str, child: _SchemaElement) -> "StructElement":
        self._fields.append((name, child))
        return self

    @property
    def children(self):
        return tuple(self._fields)


def flatten_schema(root: StructElement) -> Tuple[List[str], List[int], List[int], int]:
    """Depth-first flatten (ParquetFooter.java:136-185): the root is not
    included; returns (names, num_children, tags, parent_num_children)."""
    names: List[str] = []
    num_children: List[int] = []
    tags: List[int] = []

    def walk(elem: _SchemaElement):
        for name, child in elem.children:
            names.append(name)
            num_children.append(len(child.children))
            tags.append(child.tag)
            walk(child)

    walk(root)
    return names, num_children, tags, len(root.children)


# ---------------------------------------------------------------------------
# column_pruner (NativeParquetJni.cpp:119-439)
# ---------------------------------------------------------------------------


class _Pruner:
    def __init__(self, tag: int):
        self.tag = tag
        self.children = {}  # name -> _Pruner


def build_pruner(
    names: Sequence[str], num_children: Sequence[int], tags: Sequence[int],
    parent_num_children: int,
) -> _Pruner:
    """Rebuild the pruning tree from the flattened triple (add_depth_first
    :394-439)."""
    root = _Pruner(Tag.STRUCT)
    pos = 0

    def add(parent: _Pruner, count: int):
        nonlocal pos
        for _ in range(count):
            if pos >= len(names):
                raise ValueError("flattened schema truncated")
            node = _Pruner(tags[pos])
            parent.children[names[pos]] = node
            cnt = num_children[pos]
            pos += 1
            add(node, cnt)

    add(root, parent_num_children)
    return root


class _SchemaWalk:
    """Shared walker state: (schema index, chunk index) cursors + output maps."""

    def __init__(self, schema: List[ThriftStruct], ignore_case: bool):
        self.schema = schema
        self.ignore_case = ignore_case
        self.i = 0  # current_input_schema_index
        self.chunk = 0  # next_input_chunk_index
        self.schema_map: List[int] = []
        self.schema_num_children: List[int] = []
        self.chunk_map: List[int] = []

    def elem(self) -> ThriftStruct:
        return self.schema[self.i]

    def name(self, elem: ThriftStruct) -> str:
        n = elem.get(_SE_NAME, b"").decode("utf-8", "replace")
        return n.lower() if self.ignore_case else n

    @staticmethod
    def is_leaf(elem: ThriftStruct) -> bool:
        return elem.has(_SE_TYPE)

    @staticmethod
    def n_children(elem: ThriftStruct) -> int:
        return elem.get(_SE_NUM_CHILDREN, 0) or 0

    def skip(self) -> None:
        """Skip the current element and its subtree, advancing chunk counts
        for every leaf passed (:163-181)."""
        to_skip = 1
        while to_skip > 0 and self.i < len(self.schema):
            e = self.schema[self.i]
            if self.is_leaf(e):
                self.chunk += 1
            to_skip += self.n_children(e)
            to_skip -= 1
            self.i += 1


def _filter_schema(p: _Pruner, w: _SchemaWalk) -> None:
    if p.tag == Tag.STRUCT:
        _filter_struct(p, w)
    elif p.tag == Tag.VALUE:
        _filter_value(w)
    elif p.tag == Tag.LIST:
        _filter_list(p, w)
    elif p.tag == Tag.MAP:
        _filter_map(p, w)
    else:
        raise ValueError(f"unexpected tag {p.tag}")


def _filter_struct(p: _Pruner, w: _SchemaWalk) -> None:
    e = w.elem()
    if w.is_leaf(e):
        raise ValueError("struct request hit a leaf file element")
    n = w.n_children(e)
    w.schema_map.append(w.i)
    my_count_idx = len(w.schema_num_children)
    w.schema_num_children.append(0)
    w.i += 1
    for _ in range(n):
        if w.i >= len(w.schema):
            break
        child = w.elem()
        found = p.children.get(w.name(child))
        if found is not None:
            w.schema_num_children[my_count_idx] += 1
            _filter_schema(found, w)
        else:
            w.skip()


def _filter_value(w: _SchemaWalk) -> None:
    e = w.elem()
    if not w.is_leaf(e):
        raise ValueError("leaf request hit a group element")
    if w.n_children(e) != 0:
        raise ValueError("leaf request but file element has children")
    w.schema_map.append(w.i)
    w.schema_num_children.append(0)
    w.i += 1
    w.chunk_map.append(w.chunk)
    w.chunk += 1


def _filter_list(p: _Pruner, w: _SchemaWalk) -> None:
    found = p.children["element"]
    e = w.elem()
    list_name = e.get(_SE_NAME, b"").decode("utf-8", "replace")
    if w.is_leaf(e):
        if e.get(_SE_REPETITION) != _REPEATED:
            raise ValueError("list element child is not marked repeated")
        return _filter_value(w)
    if e.get(_SE_CONVERTED_TYPE) != _CONVERTED_LIST:
        raise ValueError("requested LIST does not match the file element type")
    if w.n_children(e) != 1:
        raise ValueError("outer list group has an unsupported layout")
    w.schema_map.append(w.i)
    w.schema_num_children.append(1)
    w.i += 1

    rep = w.elem()
    if rep.get(_SE_REPETITION) != _REPEATED:
        raise ValueError("list child layout unsupported: child is not repeated")
    rep_is_group = not w.is_leaf(rep)
    rep_n = w.n_children(rep)
    rep_name = rep.get(_SE_NAME, b"").decode("utf-8", "replace")
    if rep_is_group and rep_n == 1 and rep_name != "array" and rep_name != list_name + "_tuple":
        # standard 3-level list
        w.schema_map.append(w.i)
        w.schema_num_children.append(1)
        w.i += 1
        _filter_schema(found, w)
    else:
        # legacy 2-level list
        _filter_schema(found, w)


def _filter_map(p: _Pruner, w: _SchemaWalk) -> None:
    key_found = p.children["key"]
    value_found = p.children["value"]
    e = w.elem()
    if w.is_leaf(e):
        raise ValueError("requested MAP hit a single-value element")
    if e.get(_SE_CONVERTED_TYPE) not in (_CONVERTED_MAP, _CONVERTED_MAP_KEY_VALUE):
        raise ValueError("requested MAP does not match the file element type")
    if w.n_children(e) != 1:
        raise ValueError("outer map group has an unsupported layout")
    w.schema_map.append(w.i)
    w.schema_num_children.append(1)
    w.i += 1

    rep = w.elem()
    if rep.get(_SE_REPETITION) != _REPEATED:
        raise ValueError("map key_value child is not marked repeated")
    rep_n = w.n_children(rep)
    if rep_n not in (1, 2):
        raise ValueError("map key_value group must have 1 or 2 children")
    w.schema_map.append(w.i)
    w.schema_num_children.append(rep_n)
    w.i += 1

    _filter_schema(key_found, w)
    if rep_n == 2:
        _filter_schema(value_found, w)


# ---------------------------------------------------------------------------
# row-group selection (filter_groups :473-525)
# ---------------------------------------------------------------------------


def _chunk_offset(cc: ThriftStruct) -> int:
    md = cc.get(_CC_META_DATA)
    off = md.get(_CMD_DATA_PAGE_OFFSET, 0)
    dict_off = md.get(_CMD_DICT_PAGE_OFFSET)
    if dict_off is not None and off > dict_off:
        off = dict_off
    return off


def _invalid_file_offset(start: int, pre_start: int, pre_size: int) -> bool:
    if pre_start == 0 and start != 4:
        return True
    return start < pre_start + pre_size


def _filter_groups(meta: ThriftStruct, part_offset: int, part_length: int) -> None:
    rgs = meta.get(_FMD_ROW_GROUPS)
    if rgs is None:
        return
    groups: List[ThriftStruct] = rgs.values
    pre_start = 0
    pre_size = 0
    first_has_md = bool(groups) and groups[0].get(_RG_COLUMNS).values[0].has(_CC_META_DATA)

    kept = []
    for rg in groups:
        cols = rg.get(_RG_COLUMNS).values
        if first_has_md:
            start = _chunk_offset(cols[0])
        else:
            start = rg.get(_RG_FILE_OFFSET, 0)
            if _invalid_file_offset(start, pre_start, pre_size):
                start = 4 if pre_start == 0 else pre_start + pre_size
            pre_start = start
            pre_size = rg.get(_RG_TOTAL_COMPRESSED_SIZE, 0)
        if rg.has(_RG_TOTAL_COMPRESSED_SIZE):
            total = rg.get(_RG_TOTAL_COMPRESSED_SIZE)
        else:
            total = sum(c.get(_CC_META_DATA).get(_CMD_TOTAL_COMPRESSED_SIZE, 0) for c in cols)
        mid = start + total // 2
        if part_offset <= mid < part_offset + part_length:
            kept.append(rg)
    rgs.values = kept


# ---------------------------------------------------------------------------
# public surface (ParquetFooter.java API shape)
# ---------------------------------------------------------------------------


class ParquetFooter:
    """A parsed + filtered footer handle (close() is a no-op here; the
    C ABI exposes explicit ownership like the reference's jlong handle)."""

    def __init__(self, meta: ThriftStruct):
        self._meta = meta

    def get_num_rows(self) -> int:
        rgs = self._meta.get(_FMD_ROW_GROUPS)
        if rgs is None:
            return 0
        return sum(rg.get(_RG_NUM_ROWS, 0) for rg in rgs.values)

    def get_num_columns(self) -> int:
        schema = self._meta.get(_FMD_SCHEMA)
        if schema is None or not schema.values:
            return 0
        return schema.values[0].get(_SE_NUM_CHILDREN, 0) or 0

    def serialize_thrift_file(self) -> bytes:
        """PAR1 + thrift + LE length + PAR1 (:672-706)."""
        body = tc.write_struct(self._meta)
        return b"PAR1" + body + struct.pack("<I", len(body)) + b"PAR1"

    def close(self) -> None:
        self._meta = None


def _extract_footer_bytes(buf: bytes) -> bytes:
    """Accept either raw footer thrift bytes or a full/tail parquet file
    slice ending in <len><PAR1>."""
    if len(buf) >= 8 and buf[-4:] == b"PAR1":
        (flen,) = struct.unpack("<I", buf[-8:-4])
        if flen + 8 <= len(buf):
            return buf[-8 - flen : -8]
    return buf


def read_and_filter(
    buf: bytes,
    part_offset: int,
    part_length: int,
    schema: StructElement,
    ignore_case: bool = False,
) -> ParquetFooter:
    """Parity: ParquetFooter.readAndFilter (ParquetFooter.java:200) ->
    Java_..._readAndFilter (NativeParquetJni.cpp:574-633)."""
    meta = tc.read_struct(_extract_footer_bytes(buf))

    names, num_children, tags, parent_n = flatten_schema(schema)
    if ignore_case:
        # requested names fold at the API layer (ParquetFooter.java:207);
        # footer-side names fold in _SchemaWalk.name
        names = [n.lower() for n in names]
    pruner = build_pruner(names, num_children, tags, parent_n)

    schema_list = meta.get(_FMD_SCHEMA)
    walk = _SchemaWalk(schema_list.values, ignore_case)
    _filter_schema(pruner, walk)

    # gather new schema, patching num_children (:601-611)
    new_schema = []
    for idx, n_kids in zip(walk.schema_map, walk.schema_num_children):
        e = ThriftStruct(dict(schema_list.values[idx].fields))
        # Groups keep num_children even when pruned to 0 (the reference
        # serializes num_children=0 rather than an untyped pseudo-leaf);
        # true leaves never had the field and stay without it.
        if e.has(_SE_NUM_CHILDREN) or n_kids > 0:
            e.set(_SE_NUM_CHILDREN, tc.CT_I32, n_kids)
        new_schema.append(e)
    schema_list.values = new_schema

    # column_orders gathered by chunk_map (:612-619)
    orders = meta.get(_FMD_COLUMN_ORDERS)
    if orders is not None:
        orders.values = [orders.values[i] for i in walk.chunk_map]

    # row-group split selection (:621-624)
    if part_length >= 0:
        _filter_groups(meta, part_offset, part_length)

    # prune each row group's chunks (:558-567)
    rgs = meta.get(_FMD_ROW_GROUPS)
    if rgs is not None:
        for rg in rgs.values:
            cols = rg.get(_RG_COLUMNS)
            cols.values = [cols.values[i] for i in walk.chunk_map]

    return ParquetFooter(meta)

"""Host-side IO tier: parquet footer service (pure CPU, like the
reference's NativeParquetJni.cpp) and parquet/ORC data decode feeding
device columns."""

from . import orc_reader, parquet_footer  # noqa: F401

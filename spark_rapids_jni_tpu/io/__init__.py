"""Host-side IO tier: parquet footer service (pure CPU, like the
reference's NativeParquetJni.cpp) and parquet data decode feeding device
columns."""

from . import parquet_footer  # noqa: F401

"""Parquet data decode: column chunks -> device Columns.

Replaces the capability the reference inherits from cudf's GPU parquet
decode (SURVEY §2.8). Round-1 scope: flat schemas, PLAIN +
PLAIN_DICTIONARY/RLE_DICTIONARY encodings, RLE/bit-packed definition
levels, data page v1/v2, UNCOMPRESSED/SNAPPY/ZSTD/GZIP codecs
(decompression via pyarrow's bundled codecs — the analog of the
reference statically linking libsnappy et al).

Decode runs host-side in numpy and lands device-resident ``Column``s —
the same host->device split as the reference's CPU thrift + GPU decode,
with the device-side decode kernel left as a later optimization.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..utils.dispatch import op_boundary
from . import thrift_compact as tc

__all__ = ["read_table", "ParquetReadError"]


class ParquetReadError(RuntimeError):
    pass


# physical types (parquet.thrift Type)
_T_BOOLEAN = 0
_T_INT32 = 1
_T_INT64 = 2
_T_INT96 = 3
_T_FLOAT = 4
_T_DOUBLE = 5
_T_BYTE_ARRAY = 6
_T_FIXED_LEN_BYTE_ARRAY = 7

# encodings
_E_PLAIN = 0
_E_PLAIN_DICTIONARY = 2
_E_RLE = 3
_E_RLE_DICTIONARY = 8

# page types
_P_DATA = 0
_P_DICTIONARY = 2
_P_DATA_V2 = 3

# compression codecs (parquet.thrift CompressionCodec)
_CODECS = {0: None, 1: "snappy", 2: "gzip", 4: "brotli", 5: "lz4", 6: "zstd", 7: "lz4_raw"}

# converted types
_C_UTF8 = 0

# PageHeader field ids
_PH_TYPE = 1
_PH_UNCOMP = 2
_PH_COMP = 3
_PH_DATA = 5
_PH_DICT = 7
_PH_DATA_V2 = 8
# DataPageHeader
_DPH_NUM_VALUES = 1
_DPH_ENCODING = 2
# DataPageHeaderV2
_DPH2_NUM_VALUES = 1
_DPH2_NUM_NULLS = 2
_DPH2_NUM_ROWS = 3
_DPH2_ENCODING = 4
_DPH2_DEF_BYTES = 5
_DPH2_REP_BYTES = 6
_DPH2_COMPRESSED = 7
# SchemaElement / metadata ids reused from parquet_footer
from .parquet_footer import (  # noqa: E402
    _CC_META_DATA,
    _CMD_DATA_PAGE_OFFSET,
    _CMD_DICT_PAGE_OFFSET,
    _CMD_TOTAL_COMPRESSED_SIZE,
    _FMD_ROW_GROUPS,
    _FMD_SCHEMA,
    _RG_COLUMNS,
    _RG_NUM_ROWS,
    _SE_CONVERTED_TYPE,
    _SE_NAME,
    _SE_NUM_CHILDREN,
    _SE_REPETITION,
    _SE_TYPE,
)

_CMD_TYPE = 1
_CMD_ENCODINGS = 2
_CMD_PATH = 3
_CMD_CODEC = 4
_CMD_NUM_VALUES = 5
_CMD_TOTAL_UNCOMPRESSED = 6


def _decompress(data: bytes, codec: Optional[str], uncompressed_size: int) -> bytes:
    if codec is None:
        return data
    if codec == "snappy":
        # native codec tier first (nvcomp analog, native/src/snappy.cc)
        from .. import runtime

        if runtime.native_available():
            return runtime.snappy_uncompress(data, uncompressed_size)
    import pyarrow as pa

    return pa.Codec(codec).decompress(data, decompressed_size=uncompressed_size).to_pybytes()


# ---------------------------------------------------------------------------
# RLE / bit-packed hybrid (parquet format spec)
# ---------------------------------------------------------------------------


def _read_rle_bitpacked(data: bytes, bit_width: int, num_values: int) -> np.ndarray:
    """Decode the RLE/bit-packed hybrid encoding into int32 values."""
    out = np.empty(num_values, dtype=np.int32)
    pos = 0
    filled = 0
    if bit_width == 0:
        out[:] = 0
        return out
    byte_width = (bit_width + 7) // 8
    while filled < num_values:
        header = 0
        shift = 0
        while True:
            if pos >= len(data):
                raise ParquetReadError("rle: truncated varint")
            b = data[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        if header & 1:
            # bit-packed run: (header >> 1) groups of 8 values
            groups = header >> 1
            count = groups * 8
            nbytes = groups * bit_width
            chunk = np.frombuffer(data[pos : pos + nbytes], dtype=np.uint8)
            pos += nbytes
            bits = np.unpackbits(chunk, bitorder="little")
            vals = bits.reshape(-1, bit_width)
            weights = (1 << np.arange(bit_width, dtype=np.int64))
            decoded = (vals.astype(np.int64) * weights).sum(axis=1).astype(np.int32)
            take = min(count, num_values - filled)
            out[filled : filled + take] = decoded[:take]
            filled += take
        else:
            # rle run
            count = header >> 1
            raw = data[pos : pos + byte_width]
            pos += byte_width
            val = int.from_bytes(raw, "little")
            take = min(count, num_values - filled)
            out[filled : filled + take] = val
            filled += take
    return out


def _read_plain(data: bytes, ptype: int, num: int, type_length: int = 0):
    if ptype == _T_INT32:
        return np.frombuffer(data, dtype=np.int32, count=num), 4 * num
    if ptype == _T_INT64:
        return np.frombuffer(data, dtype=np.int64, count=num), 8 * num
    if ptype == _T_FLOAT:
        return np.frombuffer(data, dtype=np.float32, count=num), 4 * num
    if ptype == _T_DOUBLE:
        return np.frombuffer(data, dtype=np.float64, count=num), 8 * num
    if ptype == _T_BOOLEAN:
        bits = np.unpackbits(
            np.frombuffer(data, dtype=np.uint8, count=(num + 7) // 8), bitorder="little"
        )[:num]
        return bits.astype(np.uint8), (num + 7) // 8
    if ptype == _T_BYTE_ARRAY:
        vals = []
        pos = 0
        for _ in range(num):
            (ln,) = struct.unpack_from("<I", data, pos)
            pos += 4
            vals.append(data[pos : pos + ln])
            pos += ln
        return vals, pos
    raise ParquetReadError(f"unsupported physical type {ptype}")


class _ChunkDecoder:
    def __init__(self, file_bytes: bytes, chunk: tc.ThriftStruct, max_def: int):
        md = chunk.get(_CC_META_DATA)
        self.ptype = md.get(_CMD_TYPE)
        self.codec = _CODECS.get(md.get(_CMD_CODEC, 0))
        self.num_values = md.get(_CMD_NUM_VALUES, 0)
        self.max_def = max_def
        start = md.get(_CMD_DATA_PAGE_OFFSET, 0)
        dict_off = md.get(_CMD_DICT_PAGE_OFFSET)
        if dict_off is not None and dict_off < start:
            start = dict_off
        self.data = file_bytes
        self.pos = start
        self.dictionary = None

    def _read_page_header(self) -> tc.ThriftStruct:
        r = tc._Reader(self.data, self.pos)
        hdr = tc._read_struct_body(r)
        self.pos = r.pos
        return hdr

    def decode(self) -> Tuple[object, np.ndarray]:
        """Returns (values, def_levels) concatenated across pages."""
        vals_parts: List = []
        defs_parts: List[np.ndarray] = []
        remaining = self.num_values
        while remaining > 0:
            hdr = self._read_page_header()
            ptype_page = hdr.get(_PH_TYPE)
            comp_size = hdr.get(_PH_COMP)
            uncomp_size = hdr.get(_PH_UNCOMP)
            raw = self.data[self.pos : self.pos + comp_size]
            self.pos += comp_size

            if ptype_page == _P_DICTIONARY:
                page = _decompress(raw, self.codec, uncomp_size)
                n = hdr.get(_PH_DICT).get(_DPH_NUM_VALUES)
                self.dictionary, _ = _read_plain(page, self.ptype, n)
                continue

            if ptype_page == _P_DATA:
                dph = hdr.get(_PH_DATA)
                n = dph.get(_DPH_NUM_VALUES)
                enc = dph.get(_DPH_ENCODING)
                page = _decompress(raw, self.codec, uncomp_size)
                off = 0
                if self.max_def > 0:
                    (ln,) = struct.unpack_from("<I", page, off)
                    off += 4
                    bw = max(self.max_def.bit_length(), 1)
                    defs = _read_rle_bitpacked(page[off : off + ln], bw, n)
                    off += ln
                else:
                    defs = np.ones(n, dtype=np.int32)
            elif ptype_page == _P_DATA_V2:
                dph = hdr.get(_PH_DATA_V2)
                n = dph.get(_DPH2_NUM_VALUES)
                enc = dph.get(_DPH2_ENCODING)
                def_bytes = dph.get(_DPH2_DEF_BYTES, 0)
                rep_bytes = dph.get(_DPH2_REP_BYTES, 0)
                if rep_bytes:
                    raise ParquetReadError("nested columns not supported yet")
                levels = raw[: def_bytes + rep_bytes]  # v2 levels are never compressed
                if self.max_def > 0 and def_bytes:
                    bw = max(self.max_def.bit_length(), 1)
                    defs = _read_rle_bitpacked(levels[rep_bytes:], bw, n)
                else:
                    defs = np.ones(n, dtype=np.int32)
                body = raw[def_bytes + rep_bytes :]
                compressed_flag = dph.get(_DPH2_COMPRESSED, True)
                page = (
                    _decompress(body, self.codec, uncomp_size - def_bytes - rep_bytes)
                    if compressed_flag
                    else body
                )
                off = 0
            else:
                raise ParquetReadError(f"unsupported page type {ptype_page}")

            n_present = int(np.count_nonzero(defs == self.max_def)) if self.max_def else n
            if enc == _E_RLE and self.ptype == _T_BOOLEAN:
                # v2 boolean values: u32 length + RLE/bit-packed, bit width 1
                (ln,) = struct.unpack_from("<I", page, off)
                vals = _read_rle_bitpacked(page[off + 4 : off + 4 + ln], 1, n_present).astype(
                    np.uint8
                )
            elif enc == _E_PLAIN:
                vals, _ = _read_plain(page[off:], self.ptype, n_present)
            elif enc in (_E_PLAIN_DICTIONARY, _E_RLE_DICTIONARY):
                if self.dictionary is None:
                    raise ParquetReadError("dictionary page missing")
                bw = page[off]
                idx = _read_rle_bitpacked(page[off + 1 :], bw, n_present)
                if self.ptype == _T_BYTE_ARRAY:
                    vals = [self.dictionary[i] for i in idx]
                else:
                    vals = np.asarray(self.dictionary)[idx]
            else:
                raise ParquetReadError(f"unsupported encoding {enc}")

            vals_parts.append(vals)
            defs_parts.append(defs)
            remaining -= n

        defs = np.concatenate(defs_parts) if defs_parts else np.zeros(0, np.int32)
        if self.ptype == _T_BYTE_ARRAY:
            values: List[bytes] = []
            for v in vals_parts:
                values.extend(v)
            return values, defs
        values = np.concatenate(vals_parts) if vals_parts else np.zeros(0, np.int32)
        return values, defs


def _leaf_schema_elements(meta: tc.ThriftStruct):
    """Flat-schema leaves with their max definition level (root's children)."""
    schema = meta.get(_FMD_SCHEMA).values
    root_n = schema[0].get(_SE_NUM_CHILDREN, 0)
    if len(schema) != root_n + 1:
        raise ParquetReadError("nested schemas not supported yet")
    leaves = []
    for e in schema[1:]:
        name = e.get(_SE_NAME, b"").decode()
        optional = e.get(_SE_REPETITION, 0) == 1
        leaves.append((name, e, 1 if optional else 0))
    return leaves


def _to_column(name: str, elem: tc.ThriftStruct, values, defs, max_def: int) -> Column:
    present = defs == max_def if max_def else np.ones(len(defs), bool)
    n = len(defs)
    validity = None if present.all() else present
    ptype = elem.get(_SE_TYPE)
    conv = elem.get(_SE_CONVERTED_TYPE)

    if ptype == _T_BYTE_ARRAY:
        # scatter present byte strings into full row set
        full: List[bytes] = [b""] * n
        j = 0
        for i in range(n):
            if present[i]:
                full[i] = values[j]
                j += 1
        lens = np.fromiter((len(b) for b in full), dtype=np.int32, count=n)
        offsets = np.zeros(n + 1, dtype=np.int32)
        np.cumsum(lens, out=offsets[1:])
        chars = np.frombuffer(b"".join(full), dtype=np.uint8).copy()
        import jax.numpy as jnp

        return Column(
            dt.STRING,
            validity=None if validity is None else jnp.asarray(validity),
            offsets=jnp.asarray(offsets),
            chars=jnp.asarray(chars),
        )

    np_map = {
        _T_INT32: (np.int32, dt.INT32),
        _T_INT64: (np.int64, dt.INT64),
        _T_FLOAT: (np.float32, dt.FLOAT32),
        _T_DOUBLE: (np.float64, dt.FLOAT64),
        _T_BOOLEAN: (np.uint8, dt.BOOL8),
    }
    if ptype not in np_map:
        raise ParquetReadError(f"unsupported type {ptype}")
    np_dt, col_dt = np_map[ptype]
    full_arr = np.zeros(n, dtype=np_dt)
    full_arr[present] = values
    return Column.from_numpy(full_arr, col_dt, validity=None if validity is None else validity)


@op_boundary("read_table")
def read_table(file_bytes: bytes, columns: Optional[List[str]] = None) -> Table:
    """Read a flat-schema parquet file into a device Table."""
    if file_bytes[:4] != b"PAR1" or file_bytes[-4:] != b"PAR1":
        raise ParquetReadError("not a parquet file")
    (flen,) = struct.unpack("<I", file_bytes[-8:-4])
    meta = tc.read_struct(file_bytes[-8 - flen : -8])

    leaves = _leaf_schema_elements(meta)
    if columns is not None:
        name_set = set(columns)
        sel = [(i, leaf) for i, leaf in enumerate(leaves) if leaf[0] in name_set]
    else:
        sel = list(enumerate(leaves))

    rgs = meta.get(_FMD_ROW_GROUPS).values
    out_cols: Dict[str, Tuple[List, List, tc.ThriftStruct, int]] = {}
    order: List[str] = []
    for i, (name, elem, max_def) in sel:
        vparts: List = []
        dparts: List[np.ndarray] = []
        for rg in rgs:
            chunk = rg.get(_RG_COLUMNS).values[i]
            dec = _ChunkDecoder(file_bytes, chunk, max_def)
            vals, defs = dec.decode()
            vparts.append(vals)
            dparts.append(defs)
        if elem.get(_SE_TYPE) == _T_BYTE_ARRAY:
            values: List[bytes] = []
            for v in vparts:
                values.extend(v)
        else:
            values = np.concatenate(vparts) if vparts else np.zeros(0, np.int32)
        defs = np.concatenate(dparts) if dparts else np.zeros(0, np.int32)
        out_cols[name] = (values, defs, elem, max_def)
        order.append(name)

    cols = [
        _to_column(name, out_cols[name][2], out_cols[name][0], out_cols[name][1], out_cols[name][3])
        for name in order
    ]
    return Table(cols, names=order)

"""Parquet data decode: column chunks -> device Columns.

Replaces the capability the reference inherits from cudf's GPU parquet
decode (SURVEY §2.8). Scope: nested schemas (lists / structs / maps,
arbitrary depth), PLAIN + PLAIN_DICTIONARY/RLE_DICTIONARY encodings,
RLE/bit-packed levels, data page v1/v2, UNCOMPRESSED/SNAPPY/ZSTD/GZIP
codecs (snappy through the native tier when built, else pyarrow's
bundled codecs — the analog of the reference statically linking
libsnappy et al).

TPU-first decode split (the cudf GPU-decode analog, reshaped for XLA):
- **Bulk value bytes run on device.** PLAIN fixed-width pages upload
  zero-copy and bitcast; dictionary *indices* expand on device from a
  host-parsed run directory (the sequential varint headers are O(#runs),
  the O(#values) bit extraction is one vectorized gather+shift); the
  dictionary gather, null scatter, and all string character movement
  are device gathers.
- **Level streams (1-3 bits/value) decode host-side** via vectorized
  numpy unpackbits: they are metadata, the nested-assembly offset math
  consumes them on the host anyway, and at <=3 bits/value they are two
  orders of magnitude smaller than the data they describe.
- **Nested assembly is vectorized numpy** (Dremel record shredding
  inverse): per-level slot selection + cumsum/searchsorted offset
  construction — no per-row Python.

Reference parity anchors: schema shapes handled here mirror the pruning
matrix in NativeParquetJni.cpp:245-361 (lists, structs, maps,
single-child tails); cudf reads the same shapes on GPU.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..utils.dispatch import op_boundary
from . import thrift_compact as tc

__all__ = ["read_table", "ParquetReadError"]


class ParquetReadError(RuntimeError):
    pass


# physical types (parquet.thrift Type)
_T_BOOLEAN = 0
_T_INT32 = 1
_T_INT64 = 2
_T_INT96 = 3
_T_FLOAT = 4
_T_DOUBLE = 5
_T_BYTE_ARRAY = 6
_T_FIXED_LEN_BYTE_ARRAY = 7

# encodings
_E_PLAIN = 0
_E_PLAIN_DICTIONARY = 2
_E_RLE = 3
_E_RLE_DICTIONARY = 8

# page types
_P_DATA = 0
_P_DICTIONARY = 2
_P_DATA_V2 = 3

# compression codecs (parquet.thrift CompressionCodec)
_CODECS = {0: None, 1: "snappy", 2: "gzip", 3: "lzo", 4: "brotli", 5: "lz4",
           6: "zstd", 7: "lz4_raw"}

# converted types
_C_UTF8 = 0
_C_MAP = 1
_C_MAP_KEY_VALUE = 2
_C_LIST = 3

# repetition
_R_REQUIRED = 0
_R_OPTIONAL = 1
_R_REPEATED = 2

# PageHeader field ids
_PH_TYPE = 1
_PH_UNCOMP = 2
_PH_COMP = 3
_PH_DATA = 5
_PH_DICT = 7
_PH_DATA_V2 = 8
# DataPageHeader
_DPH_NUM_VALUES = 1
_DPH_ENCODING = 2
# DataPageHeaderV2
_DPH2_NUM_VALUES = 1
_DPH2_NUM_NULLS = 2
_DPH2_NUM_ROWS = 3
_DPH2_ENCODING = 4
_DPH2_DEF_BYTES = 5
_DPH2_REP_BYTES = 6
_DPH2_COMPRESSED = 7
# SchemaElement / metadata ids reused from parquet_footer
from .parquet_footer import (  # noqa: E402
    _CC_META_DATA,
    _CMD_DATA_PAGE_OFFSET,
    _CMD_DICT_PAGE_OFFSET,
    _CMD_TOTAL_COMPRESSED_SIZE,
    _FMD_ROW_GROUPS,
    _FMD_SCHEMA,
    _RG_COLUMNS,
    _RG_NUM_ROWS,
    _SE_CONVERTED_TYPE,
    _SE_NAME,
    _SE_NUM_CHILDREN,
    _SE_REPETITION,
    _SE_TYPE,
)

_CMD_TYPE = 1
_CMD_ENCODINGS = 2
_CMD_PATH = 3
_CMD_CODEC = 4
_CMD_NUM_VALUES = 5
_CMD_TOTAL_UNCOMPRESSED = 6


def _lz4_hadoop(data: bytes, uncompressed_size: int) -> Optional[bytes]:
    """Legacy parquet codec 5 (LZ4) as written by Hadoop/parquet-mr:
    repeated [u32 BE uncompressed size][u32 BE compressed size][raw LZ4
    block]. Returns None when the framing does not validate (some
    writers used the LZ4 frame format instead — caller falls back)."""
    pos, n = 0, len(data)
    parts: List[bytes] = []
    total = 0
    while pos < n:
        if pos + 8 > n:
            return None
        (usize,) = struct.unpack_from(">I", data, pos)
        (csize,) = struct.unpack_from(">I", data, pos + 4)
        pos += 8
        if csize == 0 or pos + csize > n or total + usize > uncompressed_size:
            return None
        block = data[pos : pos + csize]
        pos += csize
        try:
            out = _lz4_raw_block(block, usize)
        except Exception:  # srjt-lint: allow-broad-except(codec sniffing: None = framing did not validate, the caller tries the next framing)
            return None
        if len(out) != usize:
            return None
        parts.append(out)
        total += usize
    if total != uncompressed_size:
        return None
    return b"".join(parts)


def _lzo_hadoop(data: bytes, uncompressed_size: int) -> Optional[bytes]:
    """Parquet codec 3 (LZO): Hadoop block framing — repeated
    [u32 BE uncompressed size][u32 BE compressed size][raw LZO1X
    stream]. Returns None when the framing does not validate."""
    from .. import runtime

    pos, n = 0, len(data)
    parts: List[bytes] = []
    total = 0
    while pos < n:
        if pos + 8 > n:
            return None
        (usize,) = struct.unpack_from(">I", data, pos)
        (csize,) = struct.unpack_from(">I", data, pos + 4)
        pos += 8
        if csize == 0 or pos + csize > n or total + usize > uncompressed_size:
            return None
        block = data[pos : pos + csize]
        pos += csize
        try:
            out = runtime.lzo1x_decompress(block, usize)
        except Exception:  # srjt-lint: allow-broad-except(codec sniffing: None = framing did not validate, the caller tries the next framing)
            return None
        if len(out) != usize:
            return None
        parts.append(out)
        total += usize
    if total != uncompressed_size:
        return None
    return b"".join(parts)


def _lz4_raw_block(block: bytes, uncompressed_size: int) -> bytes:
    """One raw LZ4 block via the native decoder, pyarrow as fallback."""
    from .. import runtime

    if runtime.native_available():
        return runtime.lz4_decompress_block(block, uncompressed_size)
    import pyarrow as pa

    return pa.Codec("lz4_raw").decompress(block, decompressed_size=uncompressed_size).to_pybytes()


def _decompress(data: bytes, codec: Optional[str], uncompressed_size: int) -> bytes:
    if codec is None:
        return data
    # native codec tier first (nvcomp analog, native/src/{snappy,lz4}.cc)
    if codec == "snappy":
        from .. import runtime

        if runtime.native_available():
            return runtime.snappy_uncompress(data, uncompressed_size)
    if codec == "lz4":
        # legacy codec 5: Hadoop block framing in the wild (parquet-mr);
        # LZ4 *frame* format from other writers — try Hadoop first
        out = _lz4_hadoop(data, uncompressed_size)
        if out is not None:
            return out
    if codec == "lzo":
        # codec 3: Hadoop block framing around raw LZO1X blocks
        # (native/src/lzo.cc); pyarrow ships no LZO codec, so this is
        # native-or-error — mapping it to None would silently treat the
        # page as uncompressed
        from .. import runtime

        if not runtime.native_available():
            raise ParquetReadError("LZO parquet needs the native runtime (cmake native/)")
        out = _lzo_hadoop(data, uncompressed_size)
        if out is None:
            raise ParquetReadError("malformed Hadoop LZO page framing")
        return out
    if codec == "zstd":
        from .. import runtime

        if runtime.native_available():
            out = runtime.zstd_decompress(data, uncompressed_size)
            if len(out) != uncompressed_size:  # corrupt page: fail loudly
                raise ParquetReadError(
                    f"zstd page decoded to {len(out)} bytes, header says {uncompressed_size}"
                )
            return out
    if codec == "lz4_raw":
        out = _lz4_raw_block(data, uncompressed_size)
        if len(out) != uncompressed_size:  # corrupt page: fail loudly
            raise ParquetReadError(
                f"lz4 page decoded to {len(out)} bytes, header says {uncompressed_size}"
            )
        return out
    import pyarrow as pa

    return pa.Codec(codec).decompress(data, decompressed_size=uncompressed_size).to_pybytes()


# ---------------------------------------------------------------------------
# RLE / bit-packed hybrid (parquet format spec)
# ---------------------------------------------------------------------------


def _read_rle_bitpacked(data: bytes, bit_width: int, num_values: int) -> np.ndarray:
    """Host decode of the RLE/bit-packed hybrid into int32 values
    (vectorized per run via unpackbits). Used for level streams."""
    out = np.empty(num_values, dtype=np.int32)
    pos = 0
    filled = 0
    if bit_width == 0:
        out[:] = 0
        return out
    byte_width = (bit_width + 7) // 8
    while filled < num_values:
        header = 0
        shift = 0
        while True:
            if pos >= len(data):
                raise ParquetReadError("rle: truncated varint")
            b = data[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        if header & 1:
            groups = header >> 1
            count = groups * 8
            nbytes = groups * bit_width
            chunk = np.frombuffer(data[pos : pos + nbytes], dtype=np.uint8)
            pos += nbytes
            bits = np.unpackbits(chunk, bitorder="little")
            vals = bits.reshape(-1, bit_width)
            weights = (1 << np.arange(bit_width, dtype=np.int64))
            decoded = (vals.astype(np.int64) * weights).sum(axis=1).astype(np.int32)
            take = min(count, num_values - filled)
            out[filled : filled + take] = decoded[:take]
            filled += take
        else:
            count = header >> 1
            raw = data[pos : pos + byte_width]
            pos += byte_width
            val = int.from_bytes(raw, "little")
            take = min(count, num_values - filled)
            out[filled : filled + take] = val
            filled += take
    return out


def _parse_rle_runs(data: bytes, bit_width: int, num_values: int):
    """Host parse of ONLY the run directory (O(#runs), not O(#values)).
    Returns (first, is_packed, payload): for an RLE run `payload` is the
    literal value; for a bit-packed run it is the absolute BIT offset of
    the run's first value inside `data`."""
    first: List[int] = []
    packed: List[bool] = []
    payload: List[int] = []
    pos = 0
    filled = 0
    if bit_width == 0:
        return (np.asarray([0], np.int64), np.asarray([False]), np.asarray([0], np.int64))
    byte_width = (bit_width + 7) // 8
    while filled < num_values:
        header = 0
        shift = 0
        while True:
            if pos >= len(data):
                raise ParquetReadError("rle: truncated varint")
            b = data[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        if header & 1:
            groups = header >> 1
            count = groups * 8
            first.append(filled)
            packed.append(True)
            payload.append(pos * 8)
            pos += groups * bit_width
        else:
            count = header >> 1
            first.append(filled)
            packed.append(False)
            payload.append(int.from_bytes(data[pos : pos + byte_width], "little"))
            pos += byte_width
        filled += count
    return (
        np.asarray(first, np.int64),
        np.asarray(packed, bool),
        np.asarray(payload, np.int64),
    )


def _rle_expand_device(data: bytes, bit_width: int, num_values: int) -> jnp.ndarray:
    """Device expansion of an RLE/bit-packed stream: one searchsorted to
    map value index -> run, one 5-byte window gather + shift for packed
    runs. All O(num_values) work is vectorized device code."""
    first, packed, payload = _parse_rle_runs(data, bit_width, num_values)
    buf = np.frombuffer(data, np.uint8)
    buf = np.concatenate([buf, np.zeros(8, np.uint8)])  # window slack
    b = jnp.asarray(buf).astype(jnp.int64)
    first_d = jnp.asarray(first)
    packed_d = jnp.asarray(packed)
    payload_d = jnp.asarray(payload)

    i = jnp.arange(num_values, dtype=jnp.int64)
    run_of = jnp.searchsorted(first_d, i, side="right") - 1
    k = i - first_d[run_of]
    bitpos = payload_d[run_of] + k * bit_width
    byte0 = bitpos >> 3
    w = (
        b[byte0]
        | (b[byte0 + 1] << 8)
        | (b[byte0 + 2] << 16)
        | (b[byte0 + 3] << 24)
        | (b[byte0 + 4] << 32)
    )
    val_packed = (w >> (bitpos & 7)) & ((1 << bit_width) - 1)
    return jnp.where(packed_d[run_of], val_packed, payload_d[run_of]).astype(jnp.int32)


# ---------------------------------------------------------------------------
# byte-array (string) helpers
# ---------------------------------------------------------------------------


def _byte_array_lens(page: bytes) -> np.ndarray:
    """Walk a PLAIN BYTE_ARRAY page: [u32 len][bytes]... -> lengths.
    Sequential by nature; the native tier does the walk in C when built."""
    from .. import runtime

    if runtime.native_available() and hasattr(runtime, "byte_array_lens"):
        try:
            return runtime.byte_array_lens(page)
        except RuntimeError as e:  # keep the module's error contract
            raise ParquetReadError(str(e)) from e
    lens: List[int] = []
    pos = 0
    n = len(page)
    while pos + 4 <= n:
        (ln,) = struct.unpack_from("<I", page, pos)
        if pos + 4 + ln > n:
            raise ParquetReadError("byte-array page: truncated trailing value")
        lens.append(ln)
        pos += 4 + ln
    if pos != n:
        raise ParquetReadError("byte-array page: trailing garbage")
    return np.asarray(lens, np.int32)


def _byte_array_chars_device(page: bytes, lens: np.ndarray) -> jnp.ndarray:
    """Strip the u32 length prefixes on device: ragged gather from the
    uploaded page buffer."""
    from ..ops.bitutils import ragged_positions

    starts = np.zeros(len(lens), np.int64)
    if len(lens):
        np.cumsum(lens[:-1] + 4, out=starts[1:])
        starts += 4  # skip each value's own length prefix
    buf = jnp.asarray(np.frombuffer(page, np.uint8))
    lens_d = jnp.asarray(lens)
    _, row_of, pos, total = ragged_positions(lens_d)
    if total == 0:
        return jnp.zeros((0,), jnp.uint8)
    starts_d = jnp.asarray(starts)
    return buf[starts_d[row_of] + pos]


# ---------------------------------------------------------------------------
# decoded value segments
# ---------------------------------------------------------------------------


@dataclass
class _Values:
    """Decoded present values of one chunk: device-resident."""

    kind: str  # "fixed" | "bytes"
    data: Optional[jnp.ndarray] = None      # fixed: [n_present] storage dtype
    lens: Optional[jnp.ndarray] = None      # bytes: [n_present] int32
    chars: Optional[jnp.ndarray] = None     # bytes: [total] uint8

    @staticmethod
    def concat(parts: List["_Values"]) -> "_Values":
        if not parts:
            return _Values("fixed", data=jnp.zeros((0,), jnp.int32))
        if parts[0].kind == "fixed":
            return _Values("fixed", data=jnp.concatenate([p.data for p in parts]))
        return _Values(
            "bytes",
            lens=jnp.concatenate([p.lens for p in parts]),
            chars=jnp.concatenate([p.chars for p in parts]),
        )


_NP_STORE = {
    _T_INT32: np.int32,
    _T_INT64: np.int64,
    _T_FLOAT: np.float32,
    _T_DOUBLE: np.float64,
    _T_BOOLEAN: np.uint8,
}


def _plain_fixed_device(page: bytes, ptype: int, n_present: int) -> _Values:
    np_dt = _NP_STORE[ptype]
    if ptype == _T_BOOLEAN:
        bits = np.unpackbits(
            np.frombuffer(page, np.uint8, count=(n_present + 7) // 8), bitorder="little"
        )[:n_present].astype(np.uint8)
        return _Values("fixed", data=jnp.asarray(bits))
    arr = np.frombuffer(page, dtype=np_dt, count=n_present)
    if ptype == _T_DOUBLE:
        arr = arr.view(np.uint64)  # FLOAT64 storage convention (bit lanes)
    return _Values("fixed", data=jnp.asarray(arr))


class _Dictionary:
    """Device-resident dictionary page."""

    def __init__(self, page: bytes, ptype: int, n: int):
        self.ptype = ptype
        if ptype == _T_BYTE_ARRAY:
            lens = _byte_array_lens(page)[:n]
            if len(lens) < n:
                raise ParquetReadError("dictionary page truncated")
            self.lens = jnp.asarray(lens)
            offs = np.zeros(n + 1, np.int64)
            np.cumsum(lens, out=offs[1:])
            self.offs = jnp.asarray(offs)
            self.chars = _byte_array_chars_device(page, lens)
        elif ptype in _NP_STORE:
            arr = np.frombuffer(page, dtype=_NP_STORE[ptype], count=n)
            if ptype == _T_DOUBLE:
                arr = arr.view(np.uint64)
            self.data = jnp.asarray(arr)
        else:
            raise ParquetReadError(f"unsupported dictionary type {ptype}")

    def take(self, idx: jnp.ndarray) -> _Values:
        from ..ops.bitutils import ragged_positions

        if self.ptype != _T_BYTE_ARRAY:
            return _Values("fixed", data=self.data[idx])
        lens = self.lens[idx]
        _, row_of, pos, total = ragged_positions(lens)
        if total == 0:
            return _Values("bytes", lens=lens, chars=jnp.zeros((0,), jnp.uint8))
        chars = self.chars[self.offs[idx[row_of]] + pos]
        return _Values("bytes", lens=lens, chars=chars)


# ---------------------------------------------------------------------------
# chunk decode: pages -> (defs, reps, values)
# ---------------------------------------------------------------------------


class _ChunkDecoder:
    def __init__(self, file_bytes: bytes, chunk: tc.ThriftStruct, max_def: int, max_rep: int):
        md = chunk.get(_CC_META_DATA)
        self.ptype = md.get(_CMD_TYPE)
        self.codec = _CODECS.get(md.get(_CMD_CODEC, 0))
        self.num_values = md.get(_CMD_NUM_VALUES, 0)
        self.max_def = max_def
        self.max_rep = max_rep
        start = md.get(_CMD_DATA_PAGE_OFFSET, 0)
        dict_off = md.get(_CMD_DICT_PAGE_OFFSET)
        if dict_off is not None and dict_off < start:
            start = dict_off
        self.data = file_bytes
        self.pos = start
        self.dictionary: Optional[_Dictionary] = None

    def _read_page_header(self) -> tc.ThriftStruct:
        r = tc._Reader(self.data, self.pos)
        hdr = tc._read_struct_body(r)
        self.pos = r.pos
        return hdr

    def decode(self) -> Tuple[np.ndarray, Optional[np.ndarray], _Values]:
        """Returns (def_levels, rep_levels_or_None, values) concatenated
        across the chunk's pages. Levels host (assembly metadata),
        values device."""
        vals_parts: List[_Values] = []
        defs_parts: List[np.ndarray] = []
        reps_parts: List[np.ndarray] = []
        remaining = self.num_values
        while remaining > 0:
            hdr = self._read_page_header()
            ptype_page = hdr.get(_PH_TYPE)
            comp_size = hdr.get(_PH_COMP)
            uncomp_size = hdr.get(_PH_UNCOMP)
            raw = self.data[self.pos : self.pos + comp_size]
            self.pos += comp_size

            if ptype_page == _P_DICTIONARY:
                page = _decompress(raw, self.codec, uncomp_size)
                n = hdr.get(_PH_DICT).get(_DPH_NUM_VALUES)
                self.dictionary = _Dictionary(page, self.ptype, n)
                continue

            if ptype_page == _P_DATA:
                dph = hdr.get(_PH_DATA)
                n = dph.get(_DPH_NUM_VALUES)
                enc = dph.get(_DPH_ENCODING)
                page = _decompress(raw, self.codec, uncomp_size)
                off = 0
                reps = None
                if self.max_rep > 0:
                    (ln,) = struct.unpack_from("<I", page, off)
                    off += 4
                    bw = max(self.max_rep.bit_length(), 1)
                    reps = _read_rle_bitpacked(page[off : off + ln], bw, n)
                    off += ln
                if self.max_def > 0:
                    (ln,) = struct.unpack_from("<I", page, off)
                    off += 4
                    bw = max(self.max_def.bit_length(), 1)
                    defs = _read_rle_bitpacked(page[off : off + ln], bw, n)
                    off += ln
                else:
                    defs = np.full(n, self.max_def, dtype=np.int32)
            elif ptype_page == _P_DATA_V2:
                dph = hdr.get(_PH_DATA_V2)
                n = dph.get(_DPH2_NUM_VALUES)
                enc = dph.get(_DPH2_ENCODING)
                def_bytes = dph.get(_DPH2_DEF_BYTES, 0)
                rep_bytes = dph.get(_DPH2_REP_BYTES, 0)
                levels = raw[: def_bytes + rep_bytes]  # v2 levels never compressed
                reps = None
                if self.max_rep > 0 and rep_bytes:
                    bw = max(self.max_rep.bit_length(), 1)
                    reps = _read_rle_bitpacked(levels[:rep_bytes], bw, n)
                elif self.max_rep > 0:
                    reps = np.zeros(n, dtype=np.int32)
                if self.max_def > 0 and def_bytes:
                    bw = max(self.max_def.bit_length(), 1)
                    defs = _read_rle_bitpacked(levels[rep_bytes : rep_bytes + def_bytes], bw, n)
                else:
                    defs = np.full(n, self.max_def, dtype=np.int32)
                body = raw[def_bytes + rep_bytes :]
                compressed_flag = dph.get(_DPH2_COMPRESSED, True)
                page = (
                    _decompress(body, self.codec, uncomp_size - def_bytes - rep_bytes)
                    if compressed_flag
                    else body
                )
                off = 0
            else:
                raise ParquetReadError(f"unsupported page type {ptype_page}")

            n_present = int(np.count_nonzero(defs == self.max_def)) if self.max_def else n
            if enc == _E_RLE and self.ptype == _T_BOOLEAN:
                # v2 boolean values: u32 length + RLE/bit-packed, width 1
                (ln,) = struct.unpack_from("<I", page, off)
                bits = _read_rle_bitpacked(page[off + 4 : off + 4 + ln], 1, n_present)
                vals = _Values("fixed", data=jnp.asarray(bits.astype(np.uint8)))
            elif enc == _E_PLAIN:
                body = page[off:]
                if self.ptype == _T_BYTE_ARRAY:
                    lens = _byte_array_lens(body)[:n_present]
                    if len(lens) < n_present:
                        raise ParquetReadError("byte-array page truncated")
                    vals = _Values(
                        "bytes",
                        lens=jnp.asarray(lens),
                        chars=_byte_array_chars_device(body, lens),
                    )
                else:
                    vals = _plain_fixed_device(body, self.ptype, n_present)
            elif enc in (_E_PLAIN_DICTIONARY, _E_RLE_DICTIONARY):
                if self.dictionary is None:
                    raise ParquetReadError("dictionary page missing")
                bw = page[off]
                idx = _rle_expand_device(page[off + 1 :], bw, n_present)
                vals = self.dictionary.take(idx)
            else:
                raise ParquetReadError(f"unsupported encoding {enc}")

            vals_parts.append(vals)
            defs_parts.append(defs)
            if reps is not None:
                reps_parts.append(reps)
            remaining -= n

        defs = np.concatenate(defs_parts) if defs_parts else np.zeros(0, np.int32)
        reps = np.concatenate(reps_parts) if reps_parts else None
        return defs, reps, _Values.concat(vals_parts)


# ---------------------------------------------------------------------------
# schema tree -> logical tree
# ---------------------------------------------------------------------------


@dataclass
class _SchemaElem:
    name: str
    repetition: int
    ptype: Optional[int]
    converted: Optional[int]
    num_children: int
    children: List["_SchemaElem"] = field(default_factory=list)
    raw: Optional[tc.ThriftStruct] = None


def _parse_schema(meta: tc.ThriftStruct) -> _SchemaElem:
    flat = meta.get(_FMD_SCHEMA).values
    pos = 0

    def walk() -> _SchemaElem:
        nonlocal pos
        e = flat[pos]
        pos += 1
        node = _SchemaElem(
            name=e.get(_SE_NAME, b"").decode(),
            repetition=e.get(_SE_REPETITION, 0),
            ptype=e.get(_SE_TYPE),
            converted=e.get(_SE_CONVERTED_TYPE),
            num_children=e.get(_SE_NUM_CHILDREN, 0) or 0,
            raw=e,
        )
        for _ in range(node.num_children):
            node.children.append(walk())
        return node

    root = walk()
    if pos != len(flat):
        raise ParquetReadError("malformed schema tree")
    return root


@dataclass
class _LLeaf:
    name: str
    elem: _SchemaElem
    max_def: int
    max_rep: int
    leaf_index: int = -1


@dataclass
class _LStruct:
    name: str
    max_def: int
    nullable: bool
    children: List[object]


@dataclass
class _LList:
    name: str
    nullable: bool      # null iff def < elem_def - 1 (when nullable)
    elem_def: int       # def level at which an element slot exists
    rep: int            # rep level of the repeated node
    element: object


def _build_logical(elem: _SchemaElem, d: int, r: int, counter: List[int]):
    """Schema element -> logical node, threading (max_def, max_rep)."""
    if elem.repetition == _R_REPEATED:
        # implicit (2-level / legacy) list: `repeated X x` == non-null
        # list of required X
        d_e, r_e = d + 1, r + 1
        inner = _SchemaElem(elem.name, _R_REQUIRED, elem.ptype, elem.converted,
                            elem.num_children, elem.children, elem.raw)
        element = _build_logical(inner, d_e, r_e, counter)
        return _LList(elem.name, nullable=False, elem_def=d_e, rep=r_e, element=element)

    nullable = elem.repetition == _R_OPTIONAL
    d2 = d + 1 if nullable else d

    if elem.num_children == 0:
        leaf = _LLeaf(elem.name, elem, max_def=d2, max_rep=r)
        leaf.leaf_index = counter[0]
        counter[0] += 1
        return leaf

    conv = elem.converted
    ch = elem.children
    if conv == _C_LIST and len(ch) == 1 and ch[0].repetition == _R_REPEATED:
        rg = ch[0]
        d_e, r_e = d2 + 1, r + 1
        if rg.num_children == 0:
            # legacy 2-level list: repeated primitive directly
            inner = _SchemaElem(rg.name, _R_REQUIRED, rg.ptype, rg.converted, 0, [], rg.raw)
            element = _build_logical(inner, d_e, r_e, counter)
        elif rg.num_children == 1:
            # standard 3-level: repeated group wraps the element
            element = _build_logical(rg.children[0], d_e, r_e, counter)
        else:
            # legacy: repeated group with several fields == list<struct>
            element = _LStruct(
                rg.name, max_def=d_e, nullable=False,
                children=[_build_logical(c, d_e, r_e, counter) for c in rg.children],
            )
        return _LList(elem.name, nullable=nullable, elem_def=d_e, rep=r_e, element=element)

    if conv in (_C_MAP, _C_MAP_KEY_VALUE) and len(ch) == 1 and ch[0].repetition == _R_REPEATED:
        kv = ch[0]
        d_e, r_e = d2 + 1, r + 1
        element = _LStruct(
            kv.name, max_def=d_e, nullable=False,
            children=[_build_logical(c, d_e, r_e, counter) for c in kv.children],
        )
        return _LList(elem.name, nullable=nullable, elem_def=d_e, rep=r_e, element=element)

    return _LStruct(
        elem.name, max_def=d2, nullable=nullable,
        children=[_build_logical(c, d2, r, counter) for c in ch],
    )


def _leaves_of(lnode) -> List[_LLeaf]:
    if isinstance(lnode, _LLeaf):
        return [lnode]
    if isinstance(lnode, _LList):
        return _leaves_of(lnode.element)
    return [lf for c in lnode.children for lf in _leaves_of(c)]


# ---------------------------------------------------------------------------
# nested assembly (Dremel inverse), vectorized numpy for the level math
# ---------------------------------------------------------------------------


def _range_counts(mask: np.ndarray, slot_idx: np.ndarray) -> np.ndarray:
    """Per slot j (range [slot_idx[j], slot_idx[j+1]) over the stream),
    the number of True entries of `mask` inside the range."""
    P = np.zeros(len(mask) + 1, np.int64)
    np.cumsum(mask, out=P[1:])
    bounds = np.append(slot_idx, len(mask))
    return (P[bounds[1:]] - P[bounds[:-1]]).astype(np.int32)


def _leaf_column(leaf: _LLeaf, defs: np.ndarray, idx: np.ndarray, values: _Values) -> Column:
    """Scatter the chunk's present values into the leaf's slot set."""
    n = len(idx)
    present = defs[idx] == leaf.max_def
    all_valid = bool(present.all())
    validity = None if all_valid else jnp.asarray(present)

    ptype = leaf.elem.ptype
    if ptype == _T_BYTE_ARRAY:
        present_d = jnp.asarray(present)
        pos = jnp.cumsum(present_d.astype(jnp.int32)) - 1
        if values.lens.shape[0] == 0:
            lens_slot = jnp.zeros((n,), jnp.int32)
        else:
            lens_slot = jnp.where(present_d, values.lens[jnp.clip(pos, 0, None)], 0)
        offsets = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(lens_slot, dtype=jnp.int32)]
        )
        # present slots appear in value order, so chars need no reorder
        return Column(dt.STRING, validity=validity, offsets=offsets, chars=values.chars)

    np_map = {
        _T_INT32: dt.INT32,
        _T_INT64: dt.INT64,
        _T_FLOAT: dt.FLOAT32,
        _T_DOUBLE: dt.FLOAT64,
        _T_BOOLEAN: dt.BOOL8,
    }
    if ptype not in np_map:
        raise ParquetReadError(f"unsupported type {ptype}")
    col_dt = np_map[ptype]
    data = values.data
    if all_valid and len(data) == n:
        return Column(col_dt, data=data, validity=None)
    present_d = jnp.asarray(present)
    pos = jnp.clip(jnp.cumsum(present_d.astype(jnp.int32)) - 1, 0, None)
    if data.shape[0] == 0:
        full = jnp.zeros((n,), data.dtype if data.size else jnp.int32)
    else:
        full = jnp.where(present_d, data[pos], jnp.zeros((), data.dtype))
    return Column(col_dt, data=full, validity=validity)


def _assemble(lnode, streams: Dict[int, Tuple[np.ndarray, Optional[np.ndarray], np.ndarray, _Values]]) -> Column:
    """streams: leaf_index -> (defs, reps, slot_idx, values)."""
    if isinstance(lnode, _LLeaf):
        defs, _reps, idx, values = streams[lnode.leaf_index]
        return _leaf_column(lnode, defs, idx, values)

    if isinstance(lnode, _LStruct):
        # struct validity from any descendant stream (consistent at
        # shared ancestor levels)
        first_leaf = _leaves_of(lnode)[0]
        defs, _r, idx, _v = streams[first_leaf.leaf_index]
        validity = None
        if lnode.nullable:
            present = defs[idx] >= lnode.max_def
            if not present.all():
                validity = jnp.asarray(present)
        children = [_assemble(c, {
            lf.leaf_index: streams[lf.leaf_index] for lf in _leaves_of(c)
        }) for c in lnode.children]
        names = [c.name for c in lnode.children]
        return Column.struct_from_parts(children, names, validity=validity)

    assert isinstance(lnode, _LList)
    first_leaf = _leaves_of(lnode)[0]
    defs0, reps0, idx0, _v0 = streams[first_leaf.leaf_index]
    if reps0 is None:
        raise ParquetReadError("list column without repetition levels")
    elem_mask0 = (reps0 <= lnode.rep) & (defs0 >= lnode.elem_def)
    counts = _range_counts(elem_mask0, idx0)
    offsets = np.zeros(len(idx0) + 1, np.int32)
    np.cumsum(counts, out=offsets[1:])
    validity = None
    if lnode.nullable:
        present = defs0[idx0] >= lnode.elem_def - 1
        if not present.all():
            validity = jnp.asarray(present)

    # element slot positions per descendant stream
    child_streams = {}
    for lf in _leaves_of(lnode.element):
        defs, reps, _idx, vals = streams[lf.leaf_index]
        em = (reps <= lnode.rep) & (defs >= lnode.elem_def)
        child_streams[lf.leaf_index] = (defs, reps, np.flatnonzero(em), vals)
    child = _assemble(lnode.element, child_streams)
    return Column.list_from_parts(jnp.asarray(offsets), child, validity=validity)


# ---------------------------------------------------------------------------
# read_table
# ---------------------------------------------------------------------------


@op_boundary("read_table")
def read_table(file_bytes: bytes, columns: Optional[List[str]] = None) -> Table:
    """Read a parquet file into a device Table. `columns` selects
    TOP-LEVEL fields by name; nested fields come whole (lists, structs,
    maps as LIST<STRUCT<key,value>> — the cudf representation)."""
    if file_bytes[:4] != b"PAR1" or file_bytes[-4:] != b"PAR1":
        raise ParquetReadError("not a parquet file")
    (flen,) = struct.unpack("<I", file_bytes[-8:-4])
    meta = tc.read_struct(file_bytes[-8 - flen : -8])

    root = _parse_schema(meta)
    counter = [0]
    fields = [(c.name, _build_logical(c, 0, 0, counter)) for c in root.children]
    n_leaves = counter[0]

    if columns is not None:
        keep = set(columns)
        sel_fields = [(nm, f) for nm, f in fields if nm in keep]
        missing = keep - {nm for nm, _ in sel_fields}
        if missing:
            raise ParquetReadError(f"columns not in schema: {sorted(missing)}")
    else:
        sel_fields = fields

    needed_leaves: Dict[int, _LLeaf] = {}
    for _nm, f in sel_fields:
        for lf in _leaves_of(f):
            needed_leaves[lf.leaf_index] = lf

    rgs_field = meta.get(_FMD_ROW_GROUPS)
    rgs = rgs_field.values if rgs_field is not None else []
    # decode each needed leaf chunk across row groups, then concatenate
    streams: Dict[int, Tuple[np.ndarray, Optional[np.ndarray], np.ndarray, _Values]] = {}
    for li, leaf in needed_leaves.items():
        d_parts: List[np.ndarray] = []
        r_parts: List[np.ndarray] = []
        v_parts: List[_Values] = []
        has_reps = leaf.max_rep > 0
        for rg in rgs:
            chunks = rg.get(_RG_COLUMNS).values
            if li >= len(chunks):
                raise ParquetReadError("row group missing column chunk")
            dec = _ChunkDecoder(file_bytes, chunks[li], leaf.max_def, leaf.max_rep)
            defs, reps, vals = dec.decode()
            d_parts.append(defs)
            if has_reps:
                r_parts.append(
                    reps if reps is not None else np.zeros(len(defs), np.int32)
                )
            v_parts.append(vals)
        defs = np.concatenate(d_parts) if d_parts else np.zeros(0, np.int32)
        reps = np.concatenate(r_parts) if r_parts else None
        if reps is None and has_reps:
            # zero-row-group files: nested leaves still assemble (empty)
            reps = np.zeros(len(defs), np.int32)
        vals = _Values.concat(v_parts)
        # top-level slots: record starts (rep == 0); flat: every entry
        if reps is not None:
            idx = np.flatnonzero(reps == 0)
        else:
            idx = np.arange(len(defs), dtype=np.int64)
        streams[li] = (defs, reps, idx, vals)

    out_cols: List[Column] = []
    names: List[str] = []
    for nm, f in sel_fields:
        sub = {lf.leaf_index: streams[lf.leaf_index] for lf in _leaves_of(f)}
        out_cols.append(_assemble(f, sub))
        names.append(nm)
    return Table(out_cols, names=names)

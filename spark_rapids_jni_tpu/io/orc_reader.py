"""ORC data decode: stripes -> device Columns.

The reference inherits GPU ORC decode from cudf (SURVEY §2.8 capability
table names "GPU parquet/ORC decode"); this module rebuilds the ORC
side the same way io/parquet_reader.py rebuilds parquet: from-scratch
format parsing (no ORC library — a minimal protobuf wire reader plays
the role thrift_compact plays for parquet), host-side decode of the
sequential/metadata tiers, device-resident Columns out.

Scope: flat AND nested struct-root schemas (STRUCT/LIST/MAP at any
depth; maps assemble as LIST<STRUCT<key,value>>, the cudf shape);
BOOLEAN/BYTE/SHORT/INT/LONG/FLOAT/DOUBLE/STRING/BINARY/DATE/TIMESTAMP/
DECIMAL leaves; DIRECT + DICTIONARY (v2) string encodings; integer
RLEv1 and RLEv2 (short-repeat, direct, delta, patched-base); byte-RLE
and boolean bit streams; NONE/ZLIB/SNAPPY/LZO/LZ4/ZSTD compression
framing. PRESENT streams drive validity with the same present-scatter
shape as the parquet reader; nested presence composes down the type
tree (children store values only where every ancestor is non-null).
UNIONs decode as STRUCT<tag INT8, f0, f1, ...> (sparse mapping of the
dense union; cudf has no union type).

Oracle for tests: pyarrow.orc.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..columnar import Column, Table
from ..columnar import dtype as dt
from ..utils.dispatch import op_boundary

__all__ = ["read_table", "OrcReadError"]


class OrcReadError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# minimal protobuf wire format reader (the thrift_compact analog)
# ---------------------------------------------------------------------------


class _PB:
    def __init__(self, data: bytes, pos: int = 0, end: Optional[int] = None):
        self.d = data
        self.pos = pos
        self.end = len(data) if end is None else end

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            if self.pos >= self.end:
                raise OrcReadError("pb: truncated varint")
            b = self.d[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not (b & 0x80):
                return out
            shift += 7

    def fields(self):
        """Yields (field_no, wire_type, value). value: int for varint,
        bytes for length-delimited, raw int for fixed32/64."""
        while self.pos < self.end:
            key = self.varint()
            fno, wt = key >> 3, key & 7
            if wt == 0:
                yield fno, wt, self.varint()
            elif wt == 2:
                ln = self.varint()
                s = self.pos
                self.pos += ln
                yield fno, wt, self.d[s : self.pos]
            elif wt == 5:
                v = struct.unpack_from("<I", self.d, self.pos)[0]
                self.pos += 4
                yield fno, wt, v
            elif wt == 1:
                v = struct.unpack_from("<Q", self.d, self.pos)[0]
                self.pos += 8
                yield fno, wt, v
            else:
                raise OrcReadError(f"pb: unsupported wire type {wt}")


def _pb_dict(data: bytes) -> Dict[int, list]:
    out: Dict[int, list] = {}
    for fno, _wt, v in _PB(data).fields():
        out.setdefault(fno, []).append(v)
    return out


def _packed_varints(vals: list) -> List[int]:
    """A repeated uint32/uint64 field arrives either as individual
    varints or as PACKED length-delimited blobs of varints."""
    out: List[int] = []
    for v in vals:
        if isinstance(v, int):
            out.append(v)
        else:
            r = _PB(v)
            while r.pos < r.end:
                out.append(r.varint())
    return out


# ---------------------------------------------------------------------------
# compression framing
# ---------------------------------------------------------------------------

_K_NONE, _K_ZLIB, _K_SNAPPY, _K_LZO, _K_LZ4, _K_ZSTD = 0, 1, 2, 3, 4, 5


def _decompress_block(kind: int, blob: bytes, block_size: int) -> bytes:
    if kind == _K_ZLIB:
        return zlib.decompress(blob, -15)  # raw deflate
    if kind == _K_SNAPPY:
        from .. import runtime

        if runtime.native_available():
            return runtime.snappy_uncompress(blob)
        import pyarrow as pa

        # raw snappy carries its uncompressed length as a leading
        # varint; pyarrow's Codec requires it passed explicitly
        n, shift, pos = 0, 0, 0
        while True:
            b = blob[pos]
            n |= (b & 0x7F) << shift
            pos += 1
            shift += 7
            if not (b & 0x80):
                break
        return pa.Codec("snappy").decompress(blob, n).to_pybytes()
    if kind == _K_LZ4:
        # LZ4 block; decompressed chunk is bounded by compressionBlockSize
        from .. import runtime

        if runtime.native_available():
            return runtime.lz4_decompress_block(blob, max(block_size, 1 << 18))
        raise OrcReadError("LZ4 ORC needs the native runtime (cmake native/)")
    if kind == _K_ZSTD:
        from .. import runtime

        if runtime.native_available():
            # frame content size when declared, else the ORC chunk
            # bound; the header is untrusted bytes, so the allocation
            # is CLAMPED to the block size a valid chunk can reach
            bound = max(block_size, 1 << 18)
            size = runtime.zstd_frame_content_size(blob)
            if size > bound:
                raise OrcReadError(f"zstd chunk declares {size} bytes > block size {bound}")
            return runtime.zstd_decompress(blob, size if size >= 0 else bound)
        import pyarrow as pa

        # zstd frames carry no decompressed size in ORC chunks — stream
        return pa.input_stream(pa.BufferReader(blob), compression="zstd").read()
    if kind == _K_LZO:
        # LZO1X chunk; decompressed size bounded by compressionBlockSize
        from .. import runtime

        if runtime.native_available():
            return runtime.lzo1x_decompress(blob, max(block_size, 1 << 18))
        raise OrcReadError("LZO ORC needs the native runtime (cmake native/)")
    raise OrcReadError(f"unsupported compression kind {kind}")


def _deframe(data: bytes, kind: int, block_size: int = 1 << 18) -> bytes:
    """ORC compressed streams are chunked: 3-byte LE header =
    (length << 1) | isOriginal."""
    if kind == _K_NONE:
        return data
    out = []
    pos = 0
    n = len(data)
    while pos + 3 <= n:
        hdr = data[pos] | (data[pos + 1] << 8) | (data[pos + 2] << 16)
        pos += 3
        ln = hdr >> 1
        chunk = data[pos : pos + ln]
        pos += ln
        out.append(chunk if (hdr & 1) else _decompress_block(kind, chunk, block_size))
    return b"".join(out)


# ---------------------------------------------------------------------------
# low-level decoders
# ---------------------------------------------------------------------------


def _byte_rle(data: bytes, count: int) -> np.ndarray:
    out = np.empty(count, np.uint8)
    pos = 0
    filled = 0
    while filled < count and pos < len(data):
        ctrl = data[pos]
        pos += 1
        if ctrl < 128:  # run
            run = ctrl + 3
            take = min(run, count - filled)
            out[filled : filled + take] = data[pos]
            pos += 1
            filled += take
        else:  # literals
            lit = 256 - ctrl
            take = min(lit, count - filled)
            out[filled : filled + take] = np.frombuffer(data, np.uint8, take, pos)
            pos += lit
            filled += take
    if filled < count:
        raise OrcReadError("byte rle: truncated")
    return out


def _bool_bits(data: bytes, count: int) -> np.ndarray:
    """Boolean stream: byte-RLE over bytes of 8 MSB-first bits."""
    nbytes = (count + 7) // 8
    raw = _byte_rle(data, nbytes)
    return np.unpackbits(raw, bitorder="big")[:count].astype(bool)


def _zigzag(u: np.ndarray) -> np.ndarray:
    """Zigzag decode in the UNSIGNED 64-bit domain: `u >> 1` must be a
    logical shift of the raw encoding (an arithmetic shift on a negative
    int64 reinterpretation corrupts every value with |v| >= 2^62)."""
    uu = np.asarray(u, dtype=np.int64).view(np.uint64)
    dec = (uu >> np.uint64(1)) ^ (np.uint64(0) - (uu & np.uint64(1)))
    return dec.view(np.int64)


def _zigzag_py(v: int) -> int:
    """Zigzag decode of a raw unsigned Python int (any magnitude up to
    2^64-1 — np.int64() would raise OverflowError above 2^63-1)."""
    return (v >> 1) ^ -(v & 1)


def _varints(data: bytes, pos: int, count: int) -> Tuple[np.ndarray, int]:
    out = np.empty(count, np.int64)
    for i in range(count):
        v = 0
        shift = 0
        while True:
            b = data[pos]
            pos += 1
            v |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        v &= 0xFFFFFFFFFFFFFFFF  # 64-bit two's complement lane
        out[i] = v - (1 << 64) if v >= (1 << 63) else v
    return out, pos


def _rle_v1(data: bytes, count: int, signed: bool) -> np.ndarray:
    out = np.empty(count, np.int64)
    pos = 0
    filled = 0
    while filled < count:
        ctrl = data[pos]
        pos += 1
        if ctrl < 128:
            run = ctrl + 3
            delta = struct.unpack_from("b", data, pos)[0]
            pos += 1
            base_arr, pos = _varints(data, pos, 1)
            base = int(base_arr[0])
            if signed:
                base = _zigzag_py(base & 0xFFFFFFFFFFFFFFFF)
            take = min(run, count - filled)
            out[filled : filled + take] = base + delta * np.arange(take, dtype=np.int64)
            filled += take
        else:
            lit = 256 - ctrl
            vals, pos = _varints(data, pos, lit)
            if signed:
                vals = _zigzag(vals)
            take = min(lit, count - filled)
            out[filled : filled + take] = vals[:take]
            filled += take
    return out


_V2_WIDTHS = [
    1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16,
    17, 18, 19, 20, 21, 22, 23, 24, 26, 28, 30, 32, 40, 48, 56, 64,
]


def _unpack_be(data: bytes, pos: int, width: int, count: int) -> Tuple[np.ndarray, int]:
    """Big-endian bit-packed unsigned ints (ORC packs MSB-first).
    Accumulates in uint64 (bit 63 is data, not sign) and reinterprets
    as int64 two's complement lanes."""
    if width == 0:
        return np.zeros(count, np.int64), pos
    nbits = width * count
    nbytes = (nbits + 7) // 8
    raw = np.frombuffer(data, np.uint8, nbytes, pos)
    bits = np.unpackbits(raw, bitorder="big")[:nbits].reshape(count, width)
    weights = (np.uint64(1) << np.arange(width - 1, -1, -1, dtype=np.uint64))
    vals = (bits.astype(np.uint64) * weights).sum(axis=1, dtype=np.uint64)
    return vals.view(np.int64), pos + nbytes


def _rle_v2(data: bytes, count: int, signed: bool) -> np.ndarray:
    out = np.empty(count, np.int64)
    pos = 0
    filled = 0
    while filled < count:
        first = data[pos]
        enc = first >> 6
        if enc == 0:  # short repeat
            width = ((first >> 3) & 0x7) + 1
            run = (first & 0x7) + 3
            pos += 1
            v = int.from_bytes(data[pos : pos + width], "big")
            pos += width
            val = _zigzag_py(v) if signed else v
            take = min(run, count - filled)
            out[filled : filled + take] = val
            filled += take
        elif enc == 1:  # direct
            width = _V2_WIDTHS[(first >> 1) & 0x1F]
            run = ((first & 1) << 8 | data[pos + 1]) + 1
            pos += 2
            vals, pos = _unpack_be(data, pos, width, run)
            if signed:
                vals = _zigzag(vals)
            take = min(run, count - filled)
            out[filled : filled + take] = vals[:take]
            filled += take
        elif enc == 3:  # delta
            wcode = (first >> 1) & 0x1F
            width = 0 if wcode == 0 else _V2_WIDTHS[wcode]
            run = ((first & 1) << 8 | data[pos + 1]) + 1
            pos += 2
            r = _PB(data, pos)
            base_u = r.varint() & 0xFFFFFFFFFFFFFFFF
            base = _zigzag_py(base_u) if signed else base_u
            delta_base_u = r.varint() & 0xFFFFFFFFFFFFFFFF
            delta_base = _zigzag_py(delta_base_u)
            pos = r.pos
            vals = np.empty(run, np.int64)
            vals[0] = base
            if run > 1:
                vals[1] = base + delta_base
                if run > 2:
                    if width:
                        deltas, pos = _unpack_be(data, pos, width, run - 2)
                    else:
                        deltas = np.full(run - 2, abs(delta_base), np.int64)
                    sign = 1 if delta_base >= 0 else -1
                    vals[2:] = vals[1] + sign * np.cumsum(deltas)
            take = min(run, count - filled)
            out[filled : filled + take] = vals[:take]
            filled += take
        else:  # enc == 2: patched base
            width = _V2_WIDTHS[(first >> 1) & 0x1F]
            run = ((first & 1) << 8 | data[pos + 1]) + 1
            third, fourth = data[pos + 2], data[pos + 3]
            bw = ((third >> 5) & 0x7) + 1
            pw = _V2_WIDTHS[third & 0x1F]
            pgw = ((fourth >> 5) & 0x7) + 1
            pll = fourth & 0x1F
            pos += 4
            base = int.from_bytes(data[pos : pos + bw], "big")
            sign_mask = 1 << (bw * 8 - 1)
            if base & sign_mask:
                base = -(base & (sign_mask - 1))
            pos += bw
            vals, pos = _unpack_be(data, pos, width, run)
            if pll:
                # patch entries use the closest ALIGNED fixed width
                patch_entry_w = next(
                    w for w in (1, 2, 4, 8, 16, 24, 32, 40, 48, 56, 64) if w >= pgw + pw
                )
                patches, pos = _unpack_be(data, pos, patch_entry_w, pll)
                idx = 0
                for p in patches:
                    pu = int(p) % (1 << 64)  # unsigned view of the entry
                    gap = pu >> pw
                    patch_bits = pu & ((1 << pw) - 1)
                    idx += gap
                    v = (int(vals[idx]) % (1 << 64)) | (patch_bits << width)
                    vals[idx] = v - (1 << 64) if v >= (1 << 63) else v
            vals = vals + base
            take = min(run, count - filled)
            out[filled : filled + take] = vals[:take]
            filled += take
    return out


# ---------------------------------------------------------------------------
# metadata messages
# ---------------------------------------------------------------------------

# orc_proto.Type.Kind
_T_BOOLEAN, _T_BYTE, _T_SHORT, _T_INT, _T_LONG = 0, 1, 2, 3, 4
_T_FLOAT, _T_DOUBLE, _T_STRING, _T_BINARY, _T_TIMESTAMP = 5, 6, 7, 8, 9
_T_LIST, _T_MAP, _T_STRUCT, _T_UNION = 10, 11, 12, 13
_T_DECIMAL, _T_DATE, _T_VARCHAR, _T_CHAR = 14, 15, 16, 17

_S_PRESENT, _S_DATA, _S_LENGTH, _S_DICT_DATA, _S_SECONDARY = 0, 1, 2, 3, 5
_E_DIRECT, _E_DICTIONARY, _E_DIRECT_V2, _E_DICTIONARY_V2 = 0, 1, 2, 3


@dataclass
class _TypeNode:
    kind: int
    subtypes: List[int] = field(default_factory=list)
    field_names: List[str] = field(default_factory=list)
    precision: int = 0
    scale: int = 0


@dataclass
class _Stripe:
    offset: int
    index_len: int
    data_len: int
    footer_len: int
    num_rows: int


def _parse_tail(data: bytes):
    ps_len = data[-1]
    ps = _pb_dict(data[-1 - ps_len : -1])
    footer_len = ps.get(1, [0])[0]
    kind = ps.get(2, [_K_NONE])[0]
    block_size = ps.get(3, [1 << 18])[0]
    footer_raw = data[-1 - ps_len - footer_len : -1 - ps_len]
    footer = _pb_dict(_deframe(footer_raw, kind, block_size))

    types: List[_TypeNode] = []
    for traw in footer.get(4, []):
        td = _pb_dict(traw)
        types.append(
            _TypeNode(
                kind=td.get(1, [_T_STRUCT])[0],
                subtypes=_packed_varints(td.get(2, [])),
                field_names=[x.decode() for x in td.get(3, [])],
                precision=td.get(5, [0])[0],
                scale=td.get(6, [0])[0],
            )
        )
    stripes = []
    for sraw in footer.get(3, []):
        sd = _pb_dict(sraw)
        stripes.append(
            _Stripe(
                offset=sd.get(1, [0])[0],
                index_len=sd.get(2, [0])[0],
                data_len=sd.get(3, [0])[0],
                footer_len=sd.get(4, [0])[0],
                num_rows=sd.get(5, [0])[0],
            )
        )
    num_rows = footer.get(6, [0])[0]
    return types, stripes, kind, num_rows, block_size


# ---------------------------------------------------------------------------
# column assembly
# ---------------------------------------------------------------------------

_INT_KINDS = {_T_BYTE: dt.INT8, _T_SHORT: dt.INT16, _T_INT: dt.INT32, _T_LONG: dt.INT64,
              _T_DATE: dt.INT32}
_ORC_TS_EPOCH = 1420070400  # 2015-01-01 00:00:00 UTC, the ORC timestamp base


def _scatter_present(values: np.ndarray, present: Optional[np.ndarray], fill=0) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    if present is None:
        return values, None
    n = len(present)
    out = np.full(n, fill, dtype=values.dtype)
    out[present] = values[: int(present.sum())]
    return out, present


class _StripeReader:
    def __init__(self, data: bytes, stripe: _Stripe, kind: int, block_size: int = 1 << 18):
        self.kind = kind
        self.block_size = block_size
        foot = _pb_dict(
            _deframe(
                data[stripe.offset + stripe.index_len + stripe.data_len :
                     stripe.offset + stripe.index_len + stripe.data_len + stripe.footer_len],
                kind,
                block_size,
            )
        )
        self.encodings = []
        for eraw in foot.get(2, []):
            ed = _pb_dict(eraw)
            self.encodings.append((ed.get(1, [_E_DIRECT])[0], ed.get(2, [0])[0]))
        # stream directory: (column, kind) -> raw bytes
        self.streams: Dict[Tuple[int, int], bytes] = {}
        pos = stripe.offset  # index streams come first; walk everything
        for sraw in foot.get(1, []):
            sd = _pb_dict(sraw)
            skind = sd.get(1, [0])[0]
            col = sd.get(2, [0])[0]
            ln = sd.get(3, [0])[0]
            self.streams[(col, skind)] = data[pos : pos + ln]
            pos += ln
        self.num_rows = stripe.num_rows

    def stream(self, col: int, skind: int) -> Optional[bytes]:
        raw = self.streams.get((col, skind))
        return None if raw is None else _deframe(raw, self.kind, self.block_size)

    def present(self, col: int, count: Optional[int] = None) -> Optional[np.ndarray]:
        raw = self.stream(col, _S_PRESENT)
        if raw is None:
            return None
        return _bool_bits(raw, self.num_rows if count is None else count)

    def ints(self, col: int, signed: bool, count: int) -> np.ndarray:
        return self.ints_stream(col, _S_DATA, signed, count)

    def ints_stream(self, col: int, skind: int, signed: bool, count: int) -> np.ndarray:
        raw = self.stream(col, skind)
        enc = self.encodings[col][0]
        if enc in (_E_DIRECT_V2, _E_DICTIONARY_V2):
            return _rle_v2(raw, count, signed)
        return _rle_v1(raw, count, signed)

    def lengths(self, col: int, count: int) -> np.ndarray:
        raw = self.stream(col, _S_LENGTH)
        enc = self.encodings[col][0]
        if enc in (_E_DIRECT_V2, _E_DICTIONARY_V2):
            return _rle_v2(raw, count, False)
        return _rle_v1(raw, count, False)


def _read_column(rd: _StripeReader, col: int, types: List[_TypeNode],
                 count: Optional[int] = None):
    """Returns (values np/tuple, present np|None) for one stripe.

    ``count`` is the column's value count at its nesting level (stripe
    rows at the root; the parent's non-null count under a STRUCT; the
    summed lengths under a LIST/MAP) — ORC presence and data streams
    are all relative to the parent's surviving entries.
    """
    tnode = types[col]
    if count is None:
        count = rd.num_rows
    present = rd.present(col, count)
    n_present = int(present.sum()) if present is not None else count

    k = tnode.kind
    if k == _T_STRUCT:
        children = [_read_column(rd, sub, types, n_present) for sub in tnode.subtypes]
        return ("struct", children), present
    if k in (_T_LIST, _T_MAP):
        lens = rd.lengths(col, n_present).astype(np.int64)
        child_count = int(lens.sum())
        if k == _T_LIST:
            child = _read_column(rd, tnode.subtypes[0], types, child_count)
            return ("list", lens, child), present
        key = _read_column(rd, tnode.subtypes[0], types, child_count)
        val = _read_column(rd, tnode.subtypes[1], types, child_count)
        return ("map", lens, key, val), present
    if k == _T_BYTE:  # tinyint DATA is byte-RLE, not integer RLE
        raw = rd.stream(col, _S_DATA)
        return _byte_rle(raw, n_present).view(np.int8), present
    if k in _INT_KINDS:
        vals = rd.ints(col, True, n_present)
        return vals, present
    if k == _T_BOOLEAN:
        raw = rd.stream(col, _S_DATA)
        return _bool_bits(raw, n_present), present
    if k in (_T_FLOAT, _T_DOUBLE):
        raw = rd.stream(col, _S_DATA)
        npdt = np.float32 if k == _T_FLOAT else np.float64
        return np.frombuffer(raw, npdt, n_present), present
    if k in (_T_STRING, _T_VARCHAR, _T_CHAR, _T_BINARY):
        enc = rd.encodings[col][0]
        if enc in (_E_DICTIONARY, _E_DICTIONARY_V2):
            dict_size = rd.encodings[col][1]
            dlens = rd.lengths(col, dict_size)
            dchars = rd.stream(col, _S_DICT_DATA) or b""
            idx = rd.ints(col, False, n_present)
            doffs = np.zeros(dict_size + 1, np.int64)
            np.cumsum(dlens, out=doffs[1:])
            lens = dlens[idx] if dict_size else np.zeros(n_present, np.int64)
            starts = doffs[idx] if dict_size else np.zeros(n_present, np.int64)
            return ("bytes", lens.astype(np.int32), np.frombuffer(dchars, np.uint8), starts), present
        lens = rd.lengths(col, n_present)
        chars = rd.stream(col, _S_DATA) or b""
        starts = np.zeros(n_present, np.int64)
        if n_present:
            np.cumsum(lens[:-1], out=starts[1:])
        return ("bytes", lens.astype(np.int32), np.frombuffer(chars, np.uint8), starts), present
    if k == _T_TIMESTAMP:
        # DATA: seconds relative to 2015-01-01 UTC (signed RLE);
        # SECONDARY: nanos with the trailing-zero packing (low 3 bits =
        # zero-count code c; c != 0 restores c+1 trailing zeros)
        secs = rd.ints(col, True, n_present).astype(np.int64)
        raw = rd.ints_stream(col, _S_SECONDARY, False, n_present).view(np.uint64)
        z = (raw & np.uint64(7)).astype(np.int64)
        nanos = (raw >> np.uint64(3)).astype(np.int64)
        scale_f = np.power(10, np.where(z != 0, z + 1, 0)).astype(np.int64)
        nanos = nanos * scale_f
        # no pre-epoch second adjustment: the ORC C++ writer (pyarrow's)
        # stores floor(seconds) directly, so seconds + nanos compose for
        # negative values too (validated against the oracle incl.
        # pre-2015 and pre-1970 fractional timestamps)
        total = (secs + np.int64(_ORC_TS_EPOCH)) * np.int64(1_000_000_000) + nanos
        return total, present
    if k == _T_DECIMAL:
        # DATA: unbounded zigzag base-128 varints (can exceed 64 bits);
        # SECONDARY: per-value scale (signed RLE). Host decode: decimal
        # columns are metadata-scale next to the fact lanes.
        raw = rd.stream(col, _S_DATA) or b""
        vals: List[int] = []
        pos = 0
        for _ in range(n_present):
            v = 0
            shift = 0
            while True:
                b = raw[pos]
                pos += 1
                v |= (b & 0x7F) << shift
                if not (b & 0x80):
                    break
                shift += 7
            vals.append((v >> 1) ^ -(v & 1))
        scales = rd.ints_stream(col, _S_SECONDARY, True, n_present)
        declared = tnode.scale
        out: List[int] = []
        for v, s_ in zip(vals, scales.tolist()):
            if s_ > declared:  # cannot happen in valid files; guard
                raise OrcReadError("decimal stored scale exceeds declared scale")
            out.append(v * (10 ** int(declared - s_)))
        return ("decimal", out), present
    if k == _T_UNION:
        # DATA: byte-RLE variant tags; each child carries only the
        # values whose tag selects it (ORC dense-union layout)
        raw = rd.stream(col, _S_DATA)
        tags = _byte_rle(raw, n_present)
        children = []
        for ci, sub in enumerate(tnode.subtypes):
            ccount = int((tags == ci).sum())
            children.append(_read_column(rd, sub, types, ccount))
        return ("union", tags, children), present
    raise OrcReadError(f"unsupported ORC type kind {k}")


def _assemble_nested(
    tnode: _TypeNode,
    types: List[_TypeNode],
    pieces: List,
    presents: List[np.ndarray],
) -> Column:
    """Merge per-stripe pieces of one (possibly nested) column into a
    device Column. ``presents`` are FULL-length masks at this nesting
    level per stripe (parent presence already composed in: a child
    stores values only where every ancestor is non-null, so masks
    compose by scattering the child's packed mask into the parent's
    surviving positions). MAPs assemble as LIST<STRUCT<key,value>> —
    the cudf representation the parquet reader uses too."""
    present_all = np.concatenate(presents) if presents else np.zeros(0, bool)
    has_nulls = not bool(present_all.all())
    k = tnode.kind

    if k == _T_STRUCT:
        child_cols = []
        for ci, sub in enumerate(tnode.subtypes):
            sub_pieces, sub_presents = [], []
            for sp, ppres in zip(pieces, presents):
                cpiece, cpres = sp[1][ci]
                n_par = int(ppres.sum())
                packed = cpres if cpres is not None else np.ones(n_par, bool)
                full = np.zeros(len(ppres), bool)
                full[np.flatnonzero(ppres)] = packed
                sub_pieces.append(cpiece)
                sub_presents.append(full)
            child_cols.append(_assemble_nested(types[sub], types, sub_pieces, sub_presents))
        return Column.struct_from_parts(
            child_cols, tnode.field_names,
            validity=jnp.asarray(present_all) if has_nulls else None,
        )

    if k in (_T_LIST, _T_MAP):
        full_lens_parts = []
        child_sets: List[List] = [[], []] if k == _T_MAP else [[]]
        child_pres: List[List[np.ndarray]] = [[], []] if k == _T_MAP else [[]]
        for sp, ppres in zip(pieces, presents):
            lens = sp[1]
            fl = np.zeros(len(ppres), np.int64)
            fl[ppres] = lens
            full_lens_parts.append(fl)
            cc = int(lens.sum())
            kids = (sp[2],) if k == _T_LIST else (sp[2], sp[3])
            for ci, (cpiece, cpres) in enumerate(kids):
                child_sets[ci].append(cpiece)
                child_pres[ci].append(cpres if cpres is not None else np.ones(cc, bool))
        full_lens = (
            np.concatenate(full_lens_parts) if full_lens_parts else np.zeros(0, np.int64)
        )
        offsets = np.zeros(len(full_lens) + 1, np.int32)
        np.cumsum(full_lens, out=offsets[1:])
        if k == _T_LIST:
            child = _assemble_nested(
                types[tnode.subtypes[0]], types, child_sets[0], child_pres[0]
            )
        else:
            key = _assemble_nested(types[tnode.subtypes[0]], types, child_sets[0], child_pres[0])
            val = _assemble_nested(types[tnode.subtypes[1]], types, child_sets[1], child_pres[1])
            child = Column.struct_from_parts([key, val], ["key", "value"])
        return Column.list_from_parts(
            offsets, child, validity=jnp.asarray(present_all) if has_nulls else None
        )

    if k == _T_UNION:
        # Dense union -> STRUCT<tag INT8, f0, f1, ...>: cudf (and the
        # Table tier here) has no union type, so each variant
        # materializes full-length with validity tag==ci — the sparse
        # mapping of an arrow dense union. The tag field preserves
        # lossless round-tripping.
        tag_parts = []
        child_sets: List[List] = [[] for _ in tnode.subtypes]
        child_pres: List[List[np.ndarray]] = [[] for _ in tnode.subtypes]
        for sp, ppres in zip(pieces, presents):
            tags = sp[1]  # packed to this level's surviving entries
            full_tags = np.zeros(len(ppres), np.int8)
            full_tags[np.flatnonzero(ppres)] = tags.astype(np.int8)
            tag_parts.append(full_tags)
            surv = np.flatnonzero(ppres)
            for ci, (cpiece, cpres) in enumerate(sp[2]):
                n_ci = int((tags == ci).sum())
                packed = cpres if cpres is not None else np.ones(n_ci, bool)
                full = np.zeros(len(ppres), bool)
                full[surv[tags == ci]] = packed
                child_sets[ci].append(cpiece)
                child_pres[ci].append(full)
        tags_all = (
            np.concatenate(tag_parts) if tag_parts else np.zeros(0, np.int8)
        )
        fields = [Column(dt.INT8, data=jnp.asarray(tags_all))]
        names = ["tag"]
        for ci, sub in enumerate(tnode.subtypes):
            fields.append(_assemble_nested(types[sub], types, child_sets[ci], child_pres[ci]))
            names.append(f"f{ci}")
        return Column.struct_from_parts(
            fields, names, validity=jnp.asarray(present_all) if has_nulls else None
        )

    return _to_column_normalized(pieces, present_all, tnode)


@op_boundary("orc_read_table")
def read_table(file_bytes: bytes, columns: Optional[List[str]] = None) -> Table:
    """Read an ORC file (flat or nested schema) into a device Table."""
    if not file_bytes.startswith(b"ORC"):
        raise OrcReadError("not an ORC file")
    types, stripes, kind, _num_rows, block_size = _parse_tail(file_bytes)
    if not types or types[0].kind != _T_STRUCT:
        raise OrcReadError("ORC root must be a struct")
    root = types[0]

    names = root.field_names
    sel = list(range(len(names)))
    if columns is not None:
        keep = set(columns)
        missing = keep - set(names)
        if missing:
            raise OrcReadError(f"columns not in schema: {sorted(missing)}")
        sel = [i for i, nm in enumerate(names) if nm in keep]

    readers = [_StripeReader(file_bytes, s, kind, block_size) for s in stripes]
    out_cols, out_names = [], []
    for i in sel:
        col_id = root.subtypes[i]
        tnode = types[col_id]
        parts, presents = [], []
        for rd in readers:
            vals, present = _read_column(rd, col_id, types)
            parts.append(vals)
            presents.append(present if present is not None else np.ones(rd.num_rows, bool))
        col = _assemble_nested(tnode, types, parts, presents)
        out_cols.append(col)
        out_names.append(names[i])
    return Table(out_cols, names=out_names)


def _to_column_normalized(parts, present_all: np.ndarray, tnode: _TypeNode) -> Column:
    """Like _to_column but with a prebuilt global present mask."""
    has_nulls = not present_all.all()
    present = present_all if has_nulls else None
    k = tnode.kind
    if k in (_T_STRING, _T_VARCHAR, _T_CHAR, _T_BINARY):
        lens_parts, chars_parts = [], []
        for part in parts:
            _tag, lens, chars, starts = part
            lens_parts.append(lens)
            total = int(lens.sum())
            if total:
                reps = np.repeat(starts, lens)
                within = np.arange(total) - np.repeat(np.cumsum(lens) - lens, lens)
                chars_parts.append(chars[(reps + within).astype(np.int64)])
            else:
                chars_parts.append(np.zeros(0, np.uint8))
        lens_all = np.concatenate(lens_parts) if lens_parts else np.zeros(0, np.int32)
        chars_all = np.concatenate(chars_parts) if chars_parts else np.zeros(0, np.uint8)
        n = len(present_all)
        if has_nulls:
            full_lens = np.zeros(n, np.int32)
            full_lens[present] = lens_all
        else:
            full_lens = lens_all
        offsets = np.zeros(n + 1, np.int32)
        np.cumsum(full_lens, out=offsets[1:])
        return Column(dt.STRING, validity=None if not has_nulls else jnp.asarray(present),
                      offsets=jnp.asarray(offsets), chars=jnp.asarray(chars_all))

    if k == _T_DECIMAL:
        merged: List[int] = []
        for p in parts:
            merged.extend(p[1])
        out_vals: List[Optional[int]] = []
        j = 0
        for ok in present_all.tolist():
            if ok:
                out_vals.append(merged[j])
                j += 1
            else:
                out_vals.append(None)
        d = dt.decimal64(-tnode.scale) if tnode.precision <= 18 else dt.decimal128(-tnode.scale)
        return Column.from_pylist(out_vals, d)
    if k == _T_TIMESTAMP:
        vals = np.concatenate([np.asarray(p) for p in parts]) if parts else np.zeros(0, np.int64)
        full, _ = _scatter_present(vals, present)
        return Column.from_numpy(full, dt.TIMESTAMP_NANOSECONDS,
                                 validity=present if has_nulls else None)
    vals = np.concatenate([np.asarray(p) for p in parts]) if parts else np.zeros(0, np.int64)
    if k == _T_BOOLEAN:
        full, _ = _scatter_present(vals.astype(np.uint8), present)
        return Column(dt.BOOL8, data=jnp.asarray(full),
                      validity=None if not has_nulls else jnp.asarray(present))
    if k in (_T_FLOAT, _T_DOUBLE):
        full, _ = _scatter_present(vals, present)
        cd = dt.FLOAT32 if k == _T_FLOAT else dt.FLOAT64
        return Column.from_numpy(full, cd, validity=present if has_nulls else None)
    cd = _INT_KINDS[k]
    full, _ = _scatter_present(vals.astype(np.dtype(cd.np_dtype)), present)
    return Column.from_numpy(full, cd, validity=present if has_nulls else None)

"""Thrift TCompactProtocol codec over a generic value tree.

The reference links apache thrift and parses into generated
``parquet::format`` classes (NativeParquetJni.cpp:527-556). Here the
protocol is implemented from scratch into a *generic* field-id-keyed
tree, which round-trips unknown fields byte-faithfully — the property
the footer service needs (filter a few known fields, re-serialize
everything else untouched).

Size-bomb guards mirror the reference: strings capped at 100MB,
containers at 1M elements (:544-548).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

__all__ = ["ThriftStruct", "ThriftList", "ThriftMap", "read_struct", "write_struct"]

MAX_STRING = 100 * 1000 * 1000
MAX_CONTAINER = 1000 * 1000

# compact wire types
CT_STOP = 0x0
CT_TRUE = 0x1
CT_FALSE = 0x2
CT_BYTE = 0x3
CT_I16 = 0x4
CT_I32 = 0x5
CT_I64 = 0x6
CT_DOUBLE = 0x7
CT_BINARY = 0x8
CT_LIST = 0x9
CT_SET = 0xA
CT_MAP = 0xB
CT_STRUCT = 0xC


class ThriftStruct:
    """Ordered field-id -> (wire_type, value) mapping."""

    __slots__ = ("fields",)

    def __init__(self, fields: Dict[int, Tuple[int, Any]] = None):
        self.fields = dict(fields) if fields else {}

    def get(self, fid: int, default=None):
        f = self.fields.get(fid)
        return f[1] if f is not None else default

    def has(self, fid: int) -> bool:
        return fid in self.fields

    def set(self, fid: int, wire_type: int, value) -> None:
        self.fields[fid] = (wire_type, value)

    def delete(self, fid: int) -> None:
        self.fields.pop(fid, None)

    def __repr__(self):
        return f"ThriftStruct({self.fields!r})"


class ThriftList:
    __slots__ = ("elem_type", "values", "is_set")

    def __init__(self, elem_type: int, values: List[Any], is_set: bool = False):
        self.elem_type = elem_type
        self.values = values
        self.is_set = is_set

    def __repr__(self):
        return f"ThriftList(t={self.elem_type}, n={len(self.values)})"


class ThriftMap:
    __slots__ = ("key_type", "val_type", "items")

    def __init__(self, key_type: int, val_type: int, items: List[Tuple[Any, Any]]):
        self.key_type = key_type
        self.val_type = val_type
        self.items = items


class _Reader:
    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf: bytes, pos: int = 0, end: int = None):
        self.buf = buf
        self.pos = pos
        self.end = len(buf) if end is None else end

    def byte(self) -> int:
        if self.pos >= self.end:
            raise ValueError("thrift: truncated input")
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        result = 0
        shift = 0
        while True:
            b = self.byte()
            result |= (b & 0x7F) << shift
            if not (b & 0x80):
                return result
            shift += 7
            if shift > 70:
                raise ValueError("thrift: varint too long")

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def read_bytes(self, n: int) -> bytes:
        if n < 0 or self.pos + n > self.end:
            raise ValueError("thrift: truncated binary")
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out


def _read_value(r: _Reader, wire_type: int):
    if wire_type == CT_TRUE:
        return True
    if wire_type == CT_FALSE:
        return False
    if wire_type == CT_BYTE:
        b = r.byte()
        return b - 256 if b >= 128 else b
    if wire_type in (CT_I16, CT_I32, CT_I64):
        return r.zigzag()
    if wire_type == CT_DOUBLE:
        return struct.unpack("<d", r.read_bytes(8))[0]
    if wire_type == CT_BINARY:
        n = r.varint()
        if n > MAX_STRING:
            raise ValueError("thrift: string size limit exceeded")
        return r.read_bytes(n)
    if wire_type in (CT_LIST, CT_SET):
        head = r.byte()
        size = head >> 4
        elem_type = head & 0x0F
        if size == 15:
            size = r.varint()
        if size > MAX_CONTAINER:
            raise ValueError("thrift: container size limit exceeded")
        vals = [_read_container_elem(r, elem_type) for _ in range(size)]
        return ThriftList(elem_type, vals, is_set=(wire_type == CT_SET))
    if wire_type == CT_MAP:
        size = r.varint()
        if size > MAX_CONTAINER:
            raise ValueError("thrift: container size limit exceeded")
        if size == 0:
            return ThriftMap(0, 0, [])
        kv = r.byte()
        kt, vt = kv >> 4, kv & 0x0F
        items = [(_read_container_elem(r, kt), _read_container_elem(r, vt)) for _ in range(size)]
        return ThriftMap(kt, vt, items)
    if wire_type == CT_STRUCT:
        return _read_struct_body(r)
    raise ValueError(f"thrift: unknown wire type {wire_type}")


def _read_container_elem(r: _Reader, elem_type: int):
    if elem_type in (CT_TRUE, CT_FALSE):  # container bools are 1/2 bytes
        return r.byte() == CT_TRUE
    return _read_value(r, elem_type)


def _read_struct_body(r: _Reader) -> ThriftStruct:
    s = ThriftStruct()
    last_fid = 0
    while True:
        head = r.byte()
        if head == CT_STOP:
            return s
        delta = head >> 4
        wire_type = head & 0x0F
        fid = last_fid + delta if delta else r.zigzag()
        last_fid = fid
        s.set(fid, wire_type, _read_value(r, wire_type))


def read_struct(buf: bytes, pos: int = 0, end: int = None) -> ThriftStruct:
    return _read_struct_body(_Reader(buf, pos, end))


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------


class _Writer:
    __slots__ = ("out",)

    def __init__(self):
        self.out = bytearray()

    def byte(self, b: int) -> None:
        self.out.append(b & 0xFF)

    def varint(self, v: int) -> None:
        while True:
            if v < 0x80:
                self.out.append(v)
                return
            self.out.append((v & 0x7F) | 0x80)
            v >>= 7


def _zigzag_encode(v: int) -> int:
    return v << 1 if v >= 0 else ((-v) << 1) - 1


def _write_value(w: _Writer, wire_type: int, v) -> None:
    if wire_type in (CT_TRUE, CT_FALSE):
        return  # encoded in the field header
    if wire_type == CT_BYTE:
        w.byte(v & 0xFF)
        return
    if wire_type in (CT_I16, CT_I32, CT_I64):
        w.varint(_zigzag_encode(int(v)))
        return
    if wire_type == CT_DOUBLE:
        w.out += struct.pack("<d", v)
        return
    if wire_type == CT_BINARY:
        b = v if isinstance(v, (bytes, bytearray)) else str(v).encode()
        w.varint(len(b))
        w.out += b
        return
    if wire_type in (CT_LIST, CT_SET):
        n = len(v.values)
        if n < 15:
            w.byte((n << 4) | v.elem_type)
        else:
            w.byte(0xF0 | v.elem_type)
            w.varint(n)
        for e in v.values:
            _write_container_elem(w, v.elem_type, e)
        return
    if wire_type == CT_MAP:
        n = len(v.items)
        w.varint(n)
        if n:
            w.byte((v.key_type << 4) | v.val_type)
            for k, val in v.items:
                _write_container_elem(w, v.key_type, k)
                _write_container_elem(w, v.val_type, val)
        return
    if wire_type == CT_STRUCT:
        _write_struct_body(w, v)
        return
    raise ValueError(f"thrift: cannot write wire type {wire_type}")


def _write_container_elem(w: _Writer, elem_type: int, v) -> None:
    if elem_type in (CT_TRUE, CT_FALSE):
        w.byte(CT_TRUE if v else CT_FALSE)
        return
    _write_value(w, elem_type, v)


def _write_struct_body(w: _Writer, s: ThriftStruct) -> None:
    last_fid = 0
    for fid in sorted(s.fields):
        wire_type, v = s.fields[fid]
        if wire_type in (CT_TRUE, CT_FALSE):
            wire_type = CT_TRUE if v else CT_FALSE
        delta = fid - last_fid
        if 0 < delta <= 15:
            w.byte((delta << 4) | wire_type)
        else:
            w.byte(wire_type)
            w.varint(_zigzag_encode(fid))
        _write_value(w, wire_type, v)
        last_fid = fid
    w.byte(CT_STOP)


def write_struct(s: ThriftStruct) -> bytes:
    w = _Writer()
    _write_struct_body(w, s)
    return bytes(w.out)
